"""Background prefetch: overlap host batch prep + H2D with the step.

A daemon producer thread drives the underlying batch iterator through a
bounded queue; the training thread pops ready batches.  With a transfer
function (``jax.device_put``) applied *in the producer*, the device
transfer for batch *i+1* is dispatched while batch *i*'s step executes
— JAX transfers are async, so a queue depth of 2 gives the classic
double-buffering (bench.py's host-feed path hand-rolls the same idiom).

Correctness properties the tests pin down:

* **Exception propagation** — a producer crash re-raises in the
  consumer (wrapped batches carry the original exception), never a
  silent hang.
* **Stall detection** — the consumer logs a warning after the stall
  warning window and, when a hard timeout is configured, raises
  :class:`~horovod_tpu.core.exceptions.DataStallError` instead of
  blocking forever (the data-plane analog of ``stall_inspector.h``;
  see ``tests/test_stall.py`` for the coordinator-side idiom).
* **Clean shutdown** — ``close()`` wakes a blocked producer, joins the
  thread, and is idempotent; no orphan threads survive under pytest
  (``tests/conftest.py`` enforces this for the whole suite).
* **Consumer-accurate state** — each queued batch carries the sampler
  snapshot taken right after it was drawn, so ``consumer_state()``
  reflects what the *training thread* has consumed, not how far ahead
  the producer ran.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterator, Optional

from ..core.exceptions import DataStallError
from ..debug import flight as _flight
from ..utils import logging as log
from ..utils import profiler

_BATCH = "batch"
_END = "end"
_ERROR = "error"


def _chaos_input_delay_s() -> float:
    """Deterministic input-pipeline slowdown injection
    (``HVD_TPU_CHAOS_INPUT_DELAY_MS``, read at iterator construction
    like the recovery chaos knobs): every batch pays this extra host
    latency in the producer (prefetch) / inside the wait span (inline).
    The perf-observatory drill (ci/run_test_tiers.sh,
    tests/test_perf_observatory.py) uses it to prove the drift detector
    attributes an input-pipeline regression to the data component."""
    from ..core.config import get_float
    return max(0.0, get_float("CHAOS_INPUT_DELAY_MS", 0.0)) / 1e3


class InlineIterator:
    """The prefetch-off twin: same interface, no thread.

    Pulls batches synchronously, applies the same transfer function and
    records the same consumer-position state snapshots, so ``DataLoader``
    (and its checkpoint/restore path) is agnostic to whether prefetch is
    on.  The blocking gather is wrapped in a ``data_wait`` span — here
    the span covers the *whole* host cost, which is exactly what an
    unpipelined step pays.
    """

    def __init__(self, it: Iterator[Any],
                 transfer: Optional[Callable[[Any], Any]] = None,
                 state_fn: Optional[Callable[[], Any]] = None):
        self._it = it
        self._transfer = transfer
        self._state_fn = state_fn
        self._last_state: Any = None
        self._finished = False
        self._closed = False
        self._chaos_delay_s = _chaos_input_delay_s()
        self.consumed = 0
        if self._chaos_delay_s:
            _flight.record("data.chaos_delay", "inline",
                           delay_ms=self._chaos_delay_s * 1e3)

    def __iter__(self):
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration
        if self._closed:
            # A stale iterator must not keep consuming the shared
            # sampler after the loader closed/rewound it — that would
            # silently drop the batches it steals (the prefetch twin
            # refuses identically).
            raise RuntimeError("inline data iterator is closed")
        with profiler.data_wait():
            if self._chaos_delay_s:
                time.sleep(self._chaos_delay_s)
            try:
                item = next(self._it)
            except StopIteration:
                # Natural exhaustion advanced the epoch inside the
                # generator — capture the post-advance state (the
                # prefetch path's _END message), or the loader's
                # close() rewind would undo the epoch change.
                if self._state_fn is not None:
                    self._last_state = self._state_fn()
                self._finished = True
                raise
            state = self._state_fn() if self._state_fn is not None else None
            if self._transfer is not None:
                item = self._transfer(item)
        self._last_state = state
        self.consumed += 1
        return item

    def consumer_state(self) -> Any:
        return self._last_state

    def close(self) -> None:
        self._closed = True


class PrefetchIterator:
    """Bounded-queue background prefetch over a batch iterator."""

    def __init__(self, it: Iterator[Any], *, depth: int = 2,
                 transfer: Optional[Callable[[Any], Any]] = None,
                 state_fn: Optional[Callable[[], Any]] = None,
                 stall_warning_s: float = 60.0,
                 stall_timeout_s: float = 0.0,
                 name: str = "prefetch"):
        if depth < 1:
            raise ValueError(f"queue depth must be >= 1, got {depth}")
        self._it = it
        self._transfer = transfer
        self._state_fn = state_fn
        self._stall_warning_s = float(stall_warning_s)
        self._stall_timeout_s = float(stall_timeout_s)
        self._name = name
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._closed = False
        self._finished = False
        self._last_state: Any = None
        self._chaos_delay_s = _chaos_input_delay_s()
        self.consumed = 0
        self.max_queued = 0  # high-water mark, for overlap diagnostics
        if self._chaos_delay_s:
            _flight.record("data.chaos_delay", name,
                           delay_ms=self._chaos_delay_s * 1e3)
        self._thread = threading.Thread(
            target=self._produce, name=f"hvd-tpu-{name}", daemon=True)
        self._thread.start()

    # -- producer (background thread) --------------------------------------
    def _put(self, item) -> bool:
        """Enqueue, waking up for close(); False when asked to stop."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                self.max_queued = max(self.max_queued, self._q.qsize())
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        try:
            for item in self._it:
                if self._chaos_delay_s:
                    time.sleep(self._chaos_delay_s)
                state = self._state_fn() \
                    if self._state_fn is not None else None
                if self._transfer is not None:
                    item = self._transfer(item)
                if not self._put((_BATCH, item, state)):
                    return
            state = self._state_fn() if self._state_fn is not None else None
            self._put((_END, None, state))
        except BaseException as exc:  # noqa: BLE001 — relayed to consumer
            self._put((_ERROR, exc, None))

    # -- consumer (training thread) ----------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration
        if self._closed:
            raise RuntimeError(f"{self._name}: iterator is closed")
        waited = 0.0
        warned = False
        with profiler.data_wait():
            while True:
                try:
                    kind, payload, state = self._q.get(timeout=0.5)
                    break
                except queue.Empty:
                    waited += 0.5
                    if not self._thread.is_alive() and self._q.empty():
                        # Producer died without posting an END/ERROR —
                        # only possible if it was killed abruptly.
                        _flight.record("data.producer_dead", self._name,
                                       waited_s=waited)
                        self.close()
                        raise DataStallError(
                            f"{self._name}: producer thread died without "
                            "reporting a result")
                    if not warned and self._stall_warning_s > 0 \
                            and waited >= self._stall_warning_s:
                        warned = True
                        log.warning(
                            "%s: input pipeline stalled — no batch for "
                            "%.0fs (source blocked or filesystem slow?)",
                            self._name, waited)
                        _flight.record("data.stall_warning", self._name,
                                       waited_s=waited)
                        from ..metrics.registry import registry
                        registry().counter(
                            "hvd_data_stall_warnings_total",
                            "Input-pipeline stall warnings").inc()
                    if 0 < self._stall_timeout_s <= waited:
                        _flight.record("data.stall_timeout", self._name,
                                       waited_s=waited)
                        self.close()
                        raise DataStallError(
                            f"{self._name}: no batch within the "
                            f"{self._stall_timeout_s:.0f}s stall window")
        if waited:
            # Slow-path only (the queue was empty for >= one 0.5 s poll):
            # a run of data.wait events in the flight buffer is what the
            # hang report's input-bound attribution keys on.
            _flight.record("data.wait", self._name, waited_s=waited)
        if kind == _ERROR:
            self.close()
            raise payload
        if kind == _END:
            self._last_state = state
            self._finished = True
            self.close()
            raise StopIteration
        self._last_state = state
        self.consumed += 1
        return payload

    def consumer_state(self) -> Any:
        """Sampler snapshot for the last batch the CONSUMER received —
        the checkpoint-correct position even while the producer has run
        several batches ahead."""
        return self._last_state

    # -- lifecycle ----------------------------------------------------------
    def close(self, join_timeout_s: float = 5.0) -> None:
        """Stop the producer and join its thread.  Idempotent; after it
        returns no live producer thread remains (asserted suite-wide by
        tests/conftest.py)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        # Drain so a producer blocked on put() observes the stop event.
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        if self._thread.is_alive():
            self._thread.join(timeout=join_timeout_s)
            if self._thread.is_alive():
                log.warning("%s: producer thread did not exit within "
                            "%.0fs of close()", self._name, join_timeout_s)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close(join_timeout_s=0.5)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
