"""Deterministic, elastic-resumable index sharding.

The reference makes lossless restarts possible by making the *sampler*
the unit of resumable state (``horovod/torch/elastic/sampler.py``): what
a rank feeds its model is a pure function of (dataset size, seed, epoch,
world size, position).  ``ShardedIndexSampler`` carries that idea with
one structural change that fits the TPU stack: its state is **global**,
not per-rank.

* The epoch order is a pure function ``epoch_order(epoch)`` of
  ``(seed, epoch, num_samples)`` — every rank derives the identical
  permutation without communicating.
* One ``cursor`` counts globally consumed samples.  A *global batch* is
  ``batch_size x world_size`` consecutive entries of the order; rank
  *r* owns the contiguous slice ``[r*b, (r+1)*b)`` of it.  Because all
  ranks advance in lockstep (one global batch per training step), the
  (epoch, cursor, seed) triple is rank-invariant — it can ride a rank-0
  broadcast, live in a checkpoint manifest, and restore into ANY world
  size.
* Resharding N→M is therefore a pure function of the remaining indices:
  nothing is recorded per rank, so nothing is lost or duplicated when
  the world resizes mid-epoch — the survivors simply re-slice
  ``order[cursor:]`` by the new world.

End-of-epoch policies when the remainder does not fill a global batch:

* ``"drop"`` — drop the tail (the classic ``drop_last``);
* ``"pad"``  — wrap indices from the epoch head so every rank still
  draws a full batch (the reference sampler's pad-to-even behavior).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

DROP = "drop"
PAD = "pad"
_POLICIES = (DROP, PAD)


class ShardedIndexSampler:
    """Partition ``range(num_samples)`` across ``world_size`` ranks with a
    seed-keyed per-epoch shuffle and a resumable global cursor."""

    def __init__(self, num_samples: int, batch_size: int, *,
                 world_size: int = 1, rank: int = 0,
                 shuffle: bool = True, seed: int = 0,
                 policy: str = PAD, epoch: int = 0):
        if num_samples <= 0:
            raise ValueError(f"num_samples must be positive, got "
                             f"{num_samples}")
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got "
                             f"{batch_size}")
        if policy not in _POLICIES:
            raise ValueError(f"policy must be one of {_POLICIES}, got "
                             f"{policy!r}")
        self.num_samples = int(num_samples)
        self.batch_size = int(batch_size)
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        self.policy = policy
        self.epoch = int(epoch)
        self.cursor = 0          # globally consumed samples this epoch
        self._order: Optional[np.ndarray] = None
        self.reshard(world_size, rank)

    # -- pure functions ----------------------------------------------------
    def epoch_order(self, epoch: int) -> np.ndarray:
        """The epoch's global index order — pure in (seed, epoch, n), so
        every rank (and every restore, at any world size) derives the
        identical permutation without a collective."""
        if not self.shuffle:
            return np.arange(self.num_samples, dtype=np.int64)
        rng = np.random.default_rng((self.seed, epoch))
        return rng.permutation(self.num_samples).astype(np.int64)

    @property
    def global_batch_size(self) -> int:
        return self.batch_size * self.world_size

    # -- topology ----------------------------------------------------------
    def reshard(self, world_size: int, rank: int = 0) -> None:
        """Re-seat this sampler in a (possibly different) world.  Pure
        over the global state: epoch/cursor/seed are untouched, so the
        *remaining* indices ``order[cursor:]`` are simply re-sliced by
        the new world — no sample is dropped or replayed."""
        world_size = int(world_size)
        rank = int(rank)
        if world_size <= 0:
            raise ValueError(f"world_size must be positive, got "
                             f"{world_size}")
        if not 0 <= rank < world_size:
            raise ValueError(f"rank {rank} out of range for world "
                             f"{world_size}")
        self.world_size = world_size
        self.rank = rank

    # -- iteration ---------------------------------------------------------
    def _epoch_order_cached(self) -> np.ndarray:
        if self._order is None or len(self._order) != self.num_samples:
            self._order = self.epoch_order(self.epoch)
        return self._order

    def next_global_batch(self) -> Optional[np.ndarray]:
        """The next global batch (all ranks' indices, rank-major), or
        None when the epoch is exhausted under the configured policy."""
        order = self._epoch_order_cached()
        gbs = self.global_batch_size
        remaining = self.num_samples - self.cursor
        if remaining <= 0:
            return None
        if remaining >= gbs:
            g = order[self.cursor:self.cursor + gbs]
        elif self.policy == DROP:
            self.cursor = self.num_samples
            return None
        else:  # PAD: wrap from the epoch head so every rank gets a
            # batch; np.resize tiles cyclically, so even a global batch
            # larger than the whole dataset (tiny set, big elastic
            # world) comes back full-size.
            g = np.concatenate([order[self.cursor:],
                                np.resize(order, gbs - remaining)])
        self.cursor += min(remaining, gbs)
        return g

    def shard(self, global_batch: np.ndarray,
              ranks: Optional[Sequence[int]] = None) -> np.ndarray:
        """The contiguous slice of a global batch owned by ``ranks``
        (default: this sampler's rank).  ``ranks`` must be contiguous —
        a single-controller process feeding several chips takes them in
        rank order so the device sharding lines up."""
        if ranks is None:
            ranks = (self.rank,)
        ranks = sorted(int(r) for r in ranks)
        if ranks != list(range(ranks[0], ranks[0] + len(ranks))):
            raise ValueError(f"ranks must be contiguous, got {ranks}")
        b = self.batch_size
        return global_batch[ranks[0] * b:(ranks[-1] + 1) * b]

    def next_batch(self, ranks: Optional[Sequence[int]] = None
                   ) -> Optional[np.ndarray]:
        g = self.next_global_batch()
        return None if g is None else self.shard(g, ranks)

    def advance_epoch(self) -> None:
        self.set_epoch(self.epoch + 1)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)
        self.cursor = 0
        self._order = None

    def batches_remaining(self) -> int:
        """Global batches left in the current epoch from the cursor."""
        remaining = self.num_samples - self.cursor
        if remaining <= 0:
            return 0
        gbs = self.global_batch_size
        whole, tail = divmod(remaining, gbs)
        return whole + (1 if tail and self.policy == PAD else 0)

    def __len__(self) -> int:
        return self.batches_remaining()

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            batch = self.next_batch()
            if batch is None:
                return
            yield batch

    # -- resumable state ---------------------------------------------------
    def state_dict(self) -> Dict[str, object]:
        """JSON-serializable snapshot: (epoch, cursor, seed, world size)
        plus the static shape of the epoch, enough to resume with no
        duplicated and no dropped samples at any world size."""
        return {
            "epoch": int(self.epoch),
            "cursor": int(self.cursor),
            "seed": int(self.seed),
            "world_size": int(self.world_size),
            "num_samples": int(self.num_samples),
            "batch_size": int(self.batch_size),
            "shuffle": bool(self.shuffle),
            "policy": self.policy,
        }

    def load_state_dict(self, state: Dict[str, object]) -> None:
        """Adopt a snapshot.  The *current* world/rank seating is kept —
        the recorded ``world_size`` documents where the state was
        written; the remaining indices reshard to wherever this sampler
        is seated now (the elastic N→M path)."""
        if int(state["num_samples"]) != self.num_samples:
            raise ValueError(
                f"sampler state is for a dataset of "
                f"{state['num_samples']} samples; this sampler covers "
                f"{self.num_samples}")
        self.seed = int(state["seed"])
        self.shuffle = bool(state["shuffle"])
        self.policy = str(state["policy"])
        self.batch_size = int(state["batch_size"])
        self.epoch = int(state["epoch"])
        self.cursor = int(state["cursor"])
        self._order = None
