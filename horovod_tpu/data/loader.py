"""`DataLoader` — sharded, prefetched, checkpointable batch feed.

One object ties the pipeline together:

* a :class:`~horovod_tpu.data.sampler.ShardedIndexSampler` decides which
  sample indices this process feeds (deterministic per-rank sharding,
  seed-keyed per-epoch shuffle, drop/pad tail policy);
* a :class:`~horovod_tpu.data.sources.DataSource` gathers those indices
  into host batches;
* a :class:`~horovod_tpu.data.prefetch.PrefetchIterator` (or its inline
  twin when prefetch is off) overlaps the gather + ``jax.device_put``
  with the training step.

Topology: in a single-controller process that feeds the whole mesh
(`hvd.size()` chips, one process), the loader emits the **global** batch
— the contiguous concatenation of every local rank's shard — which is
exactly what a ``shard_map`` with ``P("data")`` in-specs expects.  In a
one-process-per-slot launch each process gets only its own rank's
shard.  Both fall out of the same rank arithmetic
(``size // process_count`` local ranks starting at ``hvd.rank()``).

Checkpointing: ``state_dict()`` / ``load_state_dict()`` capture the
(epoch, cursor, seed, world size) tuple at the **consumer** position —
batches the prefetch producer ran ahead on are not counted — so a
mid-epoch restore resumes with no duplicated and no dropped samples,
at the same or a different world size.  Register the loader on
``hvd.elastic.TpuState(...)`` and this state rides commit/restore/sync
and the sharded checkpoint engine's manifest automatically.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

import numpy as np

from .prefetch import InlineIterator, PrefetchIterator
from .sampler import PAD, ShardedIndexSampler
from .sources import ArraySource, DataSource


def _runtime_config():
    from ..core.state import global_state
    if global_state.initialized and global_state.config is not None:
        return global_state.config
    from ..core.config import Config
    return Config.from_env()


def _resolve_topology() -> tuple:
    """(world_size, first local rank, local rank count) from the runtime;
    (1, 0, 1) when uninitialized (plain library use)."""
    from ..core.state import global_state
    if not global_state.initialized:
        return 1, 0, 1
    world = max(int(global_state.size), 1)
    procs = max(int(global_state.process_count), 1)
    n_local = max(world // procs, 1)
    return world, int(global_state.rank), n_local


class DataLoader:
    """Iterate per-epoch over sharded batches of ``source``.

    Args:
      source: a :class:`DataSource` (bare arrays/tuples are wrapped in
        :class:`ArraySource` for convenience).
      batch_size: per-rank batch size.  A single-controller process
        feeding N chips yields ``batch_size x N`` rows per step.
      shuffle / seed / policy / epoch: sampler knobs (see sampler.py).
      world_size / rank / local_ranks: explicit topology override.  By
        default the runtime topology is used (and re-resolved after an
        elastic reset via ``load_state_dict``); pass e.g.
        ``world_size=dp, local_ranks=range(dp)`` to feed a dp-way data
        axis of a larger dp×pp×mp mesh from one process.
      prefetch: background prefetch on/off; default from
        ``HVD_TPU_DATA_PREFETCH`` (on).
      queue_depth: prefetch queue depth; default
        ``HVD_TPU_DATA_QUEUE_DEPTH`` (2 = double buffering).
      transfer: applied to each host batch in the producer —
        typically ``lambda b: jax.device_put(b, sharding)``.  With
        ``sharding=`` given, that exact transfer is built for you.
      stall_timeout_s: hard ceiling on waiting for one batch; default
        ``HVD_TPU_DATA_STALL_TIMEOUT_SECONDS`` (0 = warn only).
    """

    def __init__(self, source, batch_size: int, *,
                 shuffle: bool = True, seed: int = 0, policy: str = PAD,
                 epoch: int = 0,
                 world_size: Optional[int] = None,
                 rank: Optional[int] = None,
                 local_ranks: Optional[Sequence[int]] = None,
                 prefetch: Optional[bool] = None,
                 queue_depth: Optional[int] = None,
                 transfer: Optional[Callable[[Any], Any]] = None,
                 sharding=None,
                 stall_timeout_s: Optional[float] = None,
                 name: str = "data"):
        if not isinstance(source, DataSource):
            if isinstance(source, (tuple, list)):
                source = ArraySource(*source)
            else:
                source = ArraySource(source)
        self.source = source
        self._name = name
        cfg = _runtime_config()
        self._prefetch = cfg.data_prefetch if prefetch is None \
            else bool(prefetch)
        self._depth = cfg.data_queue_depth if queue_depth is None \
            else int(queue_depth)
        self._stall_timeout_s = cfg.data_stall_timeout_seconds \
            if stall_timeout_s is None else float(stall_timeout_s)
        self._stall_warning_s = cfg.stall_warning_time_seconds
        if sharding is not None and transfer is not None:
            raise ValueError("pass either transfer= or sharding=, not both")
        if sharding is not None:
            transfer = _sharding_transfer(sharding)
        self._transfer = transfer

        self._explicit_topology = world_size is not None
        if self._explicit_topology:
            world = int(world_size)
            if local_ranks is not None:
                ranks = sorted(int(r) for r in local_ranks)
                if rank is not None and rank != ranks[0]:
                    raise ValueError("rank and local_ranks disagree")
            else:
                ranks = [int(rank) if rank is not None else 0]
            if ranks[0] < 0 or ranks[-1] >= world:
                # Out-of-range ranks would slice past the global batch
                # and numpy would silently clamp to undersized batches.
                raise ValueError(
                    f"local_ranks {ranks} out of range for world "
                    f"size {world}")
        else:
            if rank is not None or local_ranks is not None:
                raise ValueError(
                    "rank/local_ranks need an explicit world_size")
            world, first, n_local = _resolve_topology()
            ranks = list(range(first, first + n_local))
        self._ranks = ranks
        self.sampler = ShardedIndexSampler(
            len(source), batch_size, world_size=world, rank=ranks[0],
            shuffle=shuffle, seed=seed, policy=policy, epoch=epoch)

        self._active = None        # live epoch iterator, if any
        self._iter_start_state: Dict[str, Any] = self.sampler.state_dict()

    # -- iteration ---------------------------------------------------------
    def _epoch_gen(self):
        while True:
            idx = self.sampler.next_batch(self._ranks)
            if idx is None:
                break
            yield self.source.gather(idx)
        # Natural exhaustion (not close()): the next epoch begins here,
        # so the post-epoch state snapshot already points at it.
        self.sampler.advance_epoch()

    def __iter__(self):
        """One epoch (resuming mid-epoch when state says so).  Building
        a new iterator closes the previous one — a single producer
        owns the sampler at any time."""
        self.close()
        self._iter_start_state = self.sampler.state_dict()
        gen = self._epoch_gen()
        if self._prefetch:
            self._active = PrefetchIterator(
                gen, depth=self._depth, transfer=self._transfer,
                state_fn=self.sampler.state_dict,
                stall_warning_s=self._stall_warning_s,
                stall_timeout_s=self._stall_timeout_s,
                name=self._name)
        else:
            self._active = InlineIterator(
                gen, transfer=self._transfer,
                state_fn=self.sampler.state_dict)
        return self._active

    def __len__(self) -> int:
        """Batches left in the current epoch (consumer view when no
        iterator is live; the producer may have run ahead otherwise)."""
        return self.sampler.batches_remaining()

    @property
    def batch_size(self) -> int:
        return self.sampler.batch_size

    @property
    def feed_rows(self) -> int:
        """Rows per yielded batch from this process (all local ranks)."""
        return self.sampler.batch_size * len(self._ranks)

    def close(self) -> None:
        """Shut down any live prefetch producer (idempotent).  The
        sampler rewinds to the consumer position: batches the producer
        drew but never delivered are NOT skipped — they come back on
        the next iteration."""
        if self._active is not None:
            state = self._active.consumer_state()
            self._active.close()
            self._active = None
            if state is None:
                state = self._iter_start_state
            self.sampler.load_state_dict(state)

    # -- resumable state ---------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        """Consumer-position snapshot, safe to call mid-iteration: while
        a prefetch producer is running ahead, the state of the last
        batch the training thread actually received is returned."""
        if self._active is not None:
            state = self._active.consumer_state()
            if state is not None:
                return dict(state)
            return dict(self._iter_start_state)
        return self.sampler.state_dict()

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        """Adopt a snapshot and re-seat in the CURRENT topology: after
        an elastic resize the remaining indices of the epoch reshard
        across the new world (pure index arithmetic, no replays)."""
        self.close()
        self.sampler.load_state_dict(state)
        if not self._explicit_topology:
            world, first, n_local = _resolve_topology()
            self._ranks = list(range(first, first + n_local))
            self.sampler.reshard(world, self._ranks[0])
        self._iter_start_state = self.sampler.state_dict()

    def __repr__(self) -> str:
        s = self.sampler
        return (f"DataLoader(n={s.num_samples}, batch={s.batch_size}, "
                f"world={s.world_size}, ranks={self._ranks}, "
                f"epoch={s.epoch}, cursor={s.cursor}, "
                f"prefetch={'on' if self._prefetch else 'off'})")


def _sharding_transfer(sharding) -> Callable[[Any], Any]:
    """Leaf-wise ``device_put`` with one sharding — built lazily so the
    loader itself never forces a JAX backend init."""
    def _transfer(batch):
        import jax
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, sharding), batch)
    return _transfer
