"""Storage abstraction for estimator data/checkpoints.

Capability parity with the reference horovod/spark/common/store.py:32-520:
a ``Store`` owns three sub-trees (intermediate train/val data, checkpoints,
logs) under a prefix path, knows how to materialize a DataFrame to Parquet
and read it back, and is subclassed per filesystem.  The reference ships
LocalStore/HDFSStore/DBFSLocalStore; the TPU-native analogs are
``LocalStore`` (local disk, NFS, GCS-FUSE mounts) and ``FsspecStore`` /
``GCSStore`` (remote object stores addressed by URL — ``gs://`` on TPU VMs,
any fsspec protocol in general).  ``Store.create`` picks by prefix like the
reference's ``Store.create`` (store.py:46-58).

The worker feed (``iter_array_batches``) streams parquet row groups without
materializing the dataset (the reference's Petastorm reader role,
spark/keras/remote.py:102) and shards *reads* per rank: with enough row
groups each rank reads only its own ~1/size of the files.  Chunks are
re-batched to a fixed size and truncated to the common per-rank row count
so every rank executes an identical optimizer-step schedule — the blocking
per-gradient allreduces stay in lockstep (the reference equalizes with
steps_per_epoch = rows / batch / np the same way).
"""

from __future__ import annotations

import os
import shutil
from typing import Optional

import numpy as np

def prefer_system_arrow_pool() -> None:
    """pyarrow's bundled mimalloc segfaults in mi_thread_init when arrow
    is first exercised from a freshly-created Python thread in processes
    with certain loader states (observed: estimator worker processes;
    kernel log points the fault into libarrow's mi_thread_init).  The
    async prefetch reader is exactly such a thread.  The pool is baked at
    pyarrow import, so estimator WORKERS call this before their first
    arrow touch to default to the system allocator — scoped there rather
    than at library import, which would silently change the allocator of
    any host application that merely imports horovod_tpu.spark.  No-op
    once pyarrow is loaded (the runtime guard in iter_array_batches then
    degrades prefetch instead).  An explicitly set
    ARROW_DEFAULT_MEMORY_POOL always wins."""
    import sys
    if "pyarrow" not in sys.modules:
        os.environ.setdefault("ARROW_DEFAULT_MEMORY_POOL", "system")


def _prefetch_iter(gen, depth: int):
    """Run ``gen`` on a background thread through a bounded queue of
    ``depth`` items: the next chunk's (possibly remote) store reads
    overlap the consumer's compute.  Exceptions re-raise at the consuming
    site.  Abandoning the iterator (consumer raised mid-epoch /
    generator closed) stops the reader promptly via a cancellation flag
    — a reader permanently parked on the bounded queue would leak the
    thread plus ``depth`` buffered chunks per retried fit."""
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    _END = object()
    stop = threading.Event()

    def put_checked(item) -> bool:
        """Bounded put that gives up when the consumer abandoned the
        iterator — EVERY reader put must go through this, including the
        end sentinel and the exception relay, or the thread parks on the
        full queue forever."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def reader():
        try:
            for item in gen:
                if not put_checked(item):
                    return
            put_checked(_END)
        except BaseException as e:  # noqa: BLE001 — re-raised by consumer
            put_checked(e)

    t = threading.Thread(target=reader, daemon=True,
                         name="hvd-store-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        # Closing `gen` from this thread would race the reader executing
        # it (generators are single-threaded); the flag makes the reader
        # exit at its next put and the generator unwinds with its thread.
        stop.set()


_WARNED_NO_PREFETCH = False


def _arrow_background_thread_safe() -> bool:
    """True when arrow's default pool is not mimalloc (the module-import
    env default took effect, or the user picked another pool): exercising
    arrow from a fresh Python thread is then safe."""
    try:
        import pyarrow as pa
    except Exception:  # noqa: BLE001 — no arrow in the process
        return True  # prefetch cannot touch arrow; nothing to trip
    try:
        return pa.default_memory_pool().backend_name != "mimalloc"
    except Exception:  # noqa: BLE001 — older pyarrow, no backend_name
        # pyarrow IS present but the pool cannot be identified: the
        # mimalloc hazard the guard exists for cannot be ruled out —
        # degrade to synchronous reads.
        return False


class Store:
    """Base class: path layout + parquet materialization."""

    def __init__(self, prefix_path: str):
        self.prefix_path = prefix_path

    @staticmethod
    def create(prefix_path: str) -> "Store":
        if "://" in prefix_path:
            if prefix_path.startswith("gs://"):
                return GCSStore(prefix_path)
            return FsspecStore(prefix_path)  # file://, s3://, memory://, …
        # GCS FUSE and local paths are both filesystem paths on TPU VMs.
        return LocalStore(prefix_path)

    # -- path layout (reference store.py:60-101) --
    def get_train_data_path(self, idx: Optional[str] = None) -> str:
        return self._join(self.prefix_path, "intermediate_train_data",
                          idx or "")

    def get_val_data_path(self, idx: Optional[str] = None) -> str:
        return self._join(self.prefix_path, "intermediate_val_data",
                          idx or "")

    def get_checkpoint_path(self, run_id: str) -> str:
        return self._join(self.prefix_path, "runs", run_id, "checkpoint")

    def get_logs_path(self, run_id: str) -> str:
        return self._join(self.prefix_path, "runs", run_id, "logs")

    @staticmethod
    def _join(*parts: str) -> str:
        return "/".join(p.rstrip("/") for p in parts if p)

    # -- filesystem primitives (overridden per backend) --
    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    # Local-filesystem defaults, NOT abstract: pre-existing user Store
    # subclasses implemented only exists/makedirs/delete (the reference's
    # abstract surface) and must keep working when the base data paths
    # call these.
    def _open(self, path: str, mode: str):
        return open(path, mode)

    def _listdir(self, path: str):
        return [os.path.join(path, f) for f in os.listdir(path)]

    # -- data materialization --
    def write_dataframe(self, df, path: str) -> int:
        """Materialize a DataFrame as Parquet under ``path``; returns the
        row count.  Spark DataFrames are written executor-side
        (``df.write.parquet``) — the dataset never funnels through driver
        memory, unlike a ``toPandas()`` materialization (the reference
        streams through Petastorm for the same reason,
        spark/keras/remote.py:102)."""
        if hasattr(df, "write") and hasattr(df, "toPandas"):
            # Spark DataFrame: distributed write straight to the store.
            # Row count comes from the written parquet footers — a
            # pre-write df.count() would execute the input lineage twice.
            df.write.mode("overwrite").parquet(path)
            try:
                import pyarrow.parquet as pq
                total = 0
                for p in self._parquet_parts(path):
                    with self._open(p, "rb") as f:
                        total += pq.ParquetFile(f).metadata.num_rows
                return total
            except Exception:
                return -1  # listing unsupported; count unknown
        self.makedirs(path)
        target = self._join(path, "part-00000.parquet")
        with self._open(target, "wb") as f:
            df.to_parquet(f)
        return len(df)

    def _parquet_parts(self, path: str):
        return sorted(p for p in self._listdir(path)
                      if p.endswith(".parquet"))

    def read_dataframe(self, path: str):
        import pandas as pd
        frames = []
        for p in self._parquet_parts(path):
            with self._open(p, "rb") as f:
                frames.append(pd.read_parquet(f))
        return pd.concat(frames, ignore_index=True)

    def iter_array_batches(self, path: str, feature_cols, label_cols,
                           chunk_rows: int = 65536, rank: int = 0,
                           size: int = 1, epoch: int = 0,
                           shuffle_seed: Optional[int] = None,
                           prefetch: int = 0):
        """Stream (X, y) float32 chunks from the parquet files under
        ``path`` without loading the dataset into memory.

        With ``size > 1`` the stream is *rank-local*: row groups are
        sharded ``rank::size`` when there are at least ``size`` of them
        (each rank reads only its own files — the remote-store fast path),
        falling back to a strided row split over shared reads otherwise.
        Either way every rank yields chunks of identical sizes (fixed
        ``chunk_rows``, truncated to the common per-rank row count), so
        per-batch blocking collectives across ranks stay in lockstep.

        ``shuffle_seed`` enables a per-``epoch`` seeded permutation of the
        row-group unit schedule (the Petastorm shuffle role,
        reference spark/keras/remote.py:102): the permutation is a pure
        function of (seed, epoch) over the deterministic unit table, so
        it is identical on every rank with no communication — epochs
        traverse the dataset in different orders while rank shards stay
        disjoint and globally complete.  Row-group granularity (the
        strided-row fallback for tiny datasets streams unshuffled;
        estimators additionally shuffle rows within each chunk).

        ``prefetch > 0`` reads ahead through a bounded background-thread
        queue of that depth, overlapping the next chunk's store reads
        with the caller's train step (the Petastorm pooled-reader role).
        """
        # use_threads=False on the arrow calls below: the feed streams
        # sequentially (arrow's pool buys nothing here) and the prefetch
        # reader must not fan out further foreign threads on top of the
        # mimalloc thread-init hazard handled at module import.
        gen = self._iter_array_batches_impl(
            path, feature_cols, label_cols, chunk_rows, rank, size,
            epoch, shuffle_seed)
        if prefetch > 0 and not _arrow_background_thread_safe():
            # The allocator default at module import came too late (the
            # caller touched pyarrow first and it picked mimalloc):
            # running arrow from a fresh thread risks the mi_thread_init
            # segfault documented above — degrade to synchronous reads.
            global _WARNED_NO_PREFETCH
            if not _WARNED_NO_PREFETCH:
                import sys
                print("[horovod_tpu] warning: pyarrow initialized with "
                      "the mimalloc pool before horovod_tpu.spark was "
                      "imported; disabling feed prefetch (set "
                      "ARROW_DEFAULT_MEMORY_POOL=system before importing "
                      "pyarrow to re-enable).", file=sys.stderr)
                _WARNED_NO_PREFETCH = True
            prefetch = 0
        if prefetch > 0:
            gen = _prefetch_iter(gen, prefetch)
        return gen

    def _iter_array_batches_impl(self, path, feature_cols, label_cols,
                                 chunk_rows, rank, size, epoch,
                                 shuffle_seed):
        import pyarrow.parquet as pq
        parts = self._parquet_parts(path)
        if size <= 1 and shuffle_seed is None:
            for part in parts:
                with self._open(part, "rb") as f:
                    pf = pq.ParquetFile(f)
                    for rb in pf.iter_batches(batch_size=chunk_rows,
                                              use_threads=False):
                        yield dataframe_to_arrays(rb.to_pandas(),
                                                  feature_cols, label_cols)
            return

        # Deterministic unit table (identical on every rank: same listing,
        # same metadata) — the shard plan needs no communication.  Cached
        # per path: estimator epochs re-iterate the same materialized
        # dataset, and footer reads are round trips on remote stores.
        units = self._row_group_units(path, parts)

        if shuffle_seed is not None and len(units) > 1:
            # Identical permutation on every rank: pure function of
            # (seed, epoch) over the deterministic unit table.  Sharding
            # the PERMUTED table keeps rank shards disjoint and globally
            # complete while both the per-rank read order and the
            # rank->unit assignment change each epoch.
            perm = np.random.default_rng(
                [int(shuffle_seed) & 0x7FFFFFFF,
                 int(epoch)]).permutation(len(units))
            units = [units[i] for i in perm]

        if len(units) >= size:
            mine = units[rank::size]
            common = min(sum(u[2] for u in units[r::size])
                         for r in range(size))

            def frames():
                # Per-part handle cache: the shuffled schedule interleaves
                # parts, so open each file once on first use and reuse its
                # handle for later row groups (on remote stores every
                # open+footer parse is a round trip).  Row groups stream
                # in chunk_rows batches — a single row group can be the
                # whole file, and materializing it would break the
                # bounded-memory contract the unsharded path keeps.
                # LRU-capped handle cache: reuse per-part handles under
                # the shuffled (interleaved) schedule without holding one
                # fd/remote connection per part of an arbitrarily large
                # dataset open at once.
                from collections import OrderedDict
                _CAP = 64
                open_files: "OrderedDict" = OrderedDict()  # part -> (f, pf)

                def _close(part):
                    f, _pf = open_files.pop(part)
                    try:
                        f.close()
                    except Exception:  # noqa: BLE001
                        pass

                try:
                    for part, rg, _rows in mine:
                        ent = open_files.get(part)
                        if ent is None:
                            if len(open_files) >= _CAP:
                                _close(next(iter(open_files)))
                            f = self._open(part, "rb")
                            ent = (f, pq.ParquetFile(f))
                            open_files[part] = ent
                        else:
                            open_files.move_to_end(part)
                        for rb in ent[1].iter_batches(
                                batch_size=chunk_rows,
                                row_groups=[rg],
                                use_threads=False):
                            yield rb.to_pandas()
                finally:
                    for part in list(open_files):
                        _close(part)
        else:
            total = sum(u[2] for u in units)
            common = min(len(range(r, total, size)) for r in range(size))

            def frames():
                offset = 0
                for part in parts:
                    with self._open(part, "rb") as f:
                        pf = pq.ParquetFile(f)
                        for rb in pf.iter_batches(batch_size=chunk_rows,
                                                  use_threads=False):
                            df = rb.to_pandas()
                            sel = [i for i in range(len(df))
                                   if (offset + i) % size == rank]
                            offset += len(df)
                            yield df.iloc[sel]

        # Re-batch to fixed-size chunks truncated at the common row count:
        # identical chunk schedule on every rank.
        pend_x = pend_y = None
        emitted = 0
        for df in frames():
            if not len(df):
                continue
            x, y = dataframe_to_arrays(df, feature_cols, label_cols)
            pend_x = x if pend_x is None else np.concatenate([pend_x, x])
            pend_y = y if pend_y is None else np.concatenate([pend_y, y])
            while len(pend_x) >= chunk_rows and \
                    emitted + chunk_rows <= common:
                yield pend_x[:chunk_rows], pend_y[:chunk_rows]
                pend_x = pend_x[chunk_rows:]
                pend_y = pend_y[chunk_rows:]
                emitted += chunk_rows
            # Stop reading once enough rows are buffered for the tail:
            # a skewed shard must not keep downloading surplus row groups
            # that would only be discarded.
            if emitted + len(pend_x) >= common:
                break
        tail = common - emitted
        if tail > 0 and pend_x is not None and len(pend_x) >= tail:
            yield pend_x[:tail], pend_y[:tail]

    def _row_group_units(self, path: str, parts):
        """(part, row_group, rows) table for ``path``, cached on the
        instance (datasets under a run id are written once)."""
        import pyarrow.parquet as pq
        cache = getattr(self, "_unit_cache", None)
        if cache is None:
            cache = self._unit_cache = {}
        key = (path, tuple(parts))
        if key not in cache:
            units = []
            for part in parts:
                with self._open(part, "rb") as f:
                    md = pq.ParquetFile(f).metadata
                    for rg in range(md.num_row_groups):
                        units.append((part, rg,
                                      md.row_group(rg).num_rows))
            cache[key] = units
        return cache[key]

    def save_checkpoint(self, run_id: str, payload: bytes) -> str:
        path = self.get_checkpoint_path(run_id)
        self.makedirs(self._dirname(path))
        with self._open(path, "wb") as f:
            f.write(payload)
        return path

    def load_checkpoint(self, run_id: str) -> bytes:
        with self._open(self.get_checkpoint_path(run_id), "rb") as f:
            return f.read()

    @staticmethod
    def _dirname(path: str) -> str:
        return path.rsplit("/", 1)[0] if "/" in path else path


class LocalStore(Store):
    """Filesystem store (reference LocalStore, store.py:105-132); covers
    local disk, NFS and GCS-FUSE mounts on TPU VMs.  _open/_listdir come
    from the base's local-filesystem defaults."""

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)


class FsspecStore(Store):
    """URL-addressed remote store over any fsspec filesystem (the
    reference's HDFSStore role, store.py:337-471, generalized): ``gs://``,
    ``s3://``, ``memory://`` (tests), ...  Workers re-resolve the
    filesystem lazily so Store objects stay picklable across process
    boundaries."""

    def __init__(self, prefix_path: str):
        super().__init__(prefix_path.rstrip("/"))
        self._protocol = prefix_path.split("://", 1)[0]
        self.__fs = None

    @property
    def _fs(self):
        if self.__fs is None:
            import fsspec
            self.__fs = fsspec.filesystem(self._protocol)
        return self.__fs

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_FsspecStore__fs"] = None  # filesystems may hold sockets
        return state

    def _with_protocol(self, path: str) -> str:
        return path if "://" in path else f"{self._protocol}://{path}"

    def exists(self, path: str) -> bool:
        return self._fs.exists(path)

    def makedirs(self, path: str) -> None:
        self._fs.makedirs(path, exist_ok=True)

    def delete(self, path: str) -> None:
        if self._fs.exists(path):
            self._fs.rm(path, recursive=True)

    def _open(self, path: str, mode: str):
        return self._fs.open(path, mode)

    def _listdir(self, path: str):
        # fs.ls returns protocol-less paths; keep them addressable.
        return [self._with_protocol(p)
                for p in self._fs.ls(path, detail=False)]


class GCSStore(FsspecStore):
    """Google Cloud Storage store for TPU-VM estimator jobs (the
    TPU-native analog of the reference's HDFSStore, store.py:337): a
    ``gs://bucket/prefix`` path served by gcsfs.  Credentials come from
    the VM's application-default service account (the standard TPU-VM
    setup); pass nothing here."""

    def __init__(self, prefix_path: str):
        if not prefix_path.startswith("gs://"):
            raise ValueError("GCSStore requires a gs:// prefix path")
        super().__init__(prefix_path)


def dataframe_to_arrays(df, feature_cols, label_cols):
    """Split a pandas DataFrame into (X, y) float32 arrays; list-valued
    cells (vector columns) are stacked."""
    def col_to_array(c):
        v = df[c].to_numpy()
        if len(v) and isinstance(v[0], (list, tuple, np.ndarray)):
            return np.stack([np.asarray(x, dtype=np.float32) for x in v])
        return v.astype(np.float32)[:, None]

    x = np.concatenate([col_to_array(c) for c in feature_cols], axis=1)
    y = np.concatenate([col_to_array(c) for c in label_cols], axis=1)
    return x, y
