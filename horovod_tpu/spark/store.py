"""Storage abstraction for estimator data/checkpoints.

Capability parity with the reference horovod/spark/common/store.py:32-154:
a ``Store`` owns three sub-trees (intermediate train/val data, checkpoints,
logs) under a prefix path, knows how to materialize a DataFrame to Parquet
and read it back, and is subclassed per filesystem.  The reference ships
LocalStore/HDFSStore/DBFSLocalStore; TPU-VM jobs live on local SSD or GCS
FUSE mounts, both of which are plain filesystem paths — so ``LocalStore``
(any mounted path, including ``/gcs/...``) is the primary implementation
and ``Store.create`` picks by prefix.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional

import numpy as np


class Store:
    """Base class: path layout + parquet materialization."""

    def __init__(self, prefix_path: str):
        self.prefix_path = prefix_path

    @staticmethod
    def create(prefix_path: str) -> "Store":
        # GCS FUSE and local paths are both filesystem paths on TPU VMs.
        return LocalStore(prefix_path)

    # -- path layout (reference store.py:60-101) --
    def get_train_data_path(self, idx: Optional[str] = None) -> str:
        return os.path.join(self.prefix_path, "intermediate_train_data",
                            idx or "")

    def get_val_data_path(self, idx: Optional[str] = None) -> str:
        return os.path.join(self.prefix_path, "intermediate_val_data",
                            idx or "")

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self.prefix_path, "runs", run_id, "checkpoint")

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self.prefix_path, "runs", run_id, "logs")

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    # -- data materialization --
    def write_dataframe(self, df, path: str) -> int:
        """Materialize a DataFrame as Parquet under ``path``; returns the
        row count.  Spark DataFrames are written executor-side
        (``df.write.parquet``) — the dataset never funnels through driver
        memory, unlike a ``toPandas()`` materialization (the reference
        streams through Petastorm for the same reason,
        spark/keras/remote.py:102)."""
        if hasattr(df, "write") and hasattr(df, "toPandas"):
            # Spark DataFrame: distributed write straight to the store.
            # Row count comes from the written parquet footers — a
            # pre-write df.count() would execute the input lineage twice.
            df.write.mode("overwrite").parquet(path)
            try:
                import pyarrow.parquet as pq
                return sum(pq.ParquetFile(p).metadata.num_rows
                           for p in self._parquet_parts(path))
            except Exception:
                return -1  # non-local store path; count unknown
        self.makedirs(path)
        target = os.path.join(path, "part-00000.parquet")
        df.to_parquet(target)
        return len(df)

    def _parquet_parts(self, path: str):
        return sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.endswith(".parquet"))

    def read_dataframe(self, path: str):
        import pandas as pd
        return pd.concat([pd.read_parquet(p)
                          for p in self._parquet_parts(path)],
                         ignore_index=True)

    def iter_array_batches(self, path: str, feature_cols, label_cols,
                           chunk_rows: int = 65536):
        """Stream (X, y) float32 chunks from the parquet files under
        ``path`` without loading the dataset into memory — the worker-side
        analog of the reference's Petastorm batch feed
        (spark/keras/remote.py:102)."""
        import pyarrow.parquet as pq
        for part in self._parquet_parts(path):
            pf = pq.ParquetFile(part)
            for rb in pf.iter_batches(batch_size=chunk_rows):
                yield dataframe_to_arrays(rb.to_pandas(), feature_cols,
                                          label_cols)

    def save_checkpoint(self, run_id: str, payload: bytes) -> str:
        path = self.get_checkpoint_path(run_id)
        self.makedirs(os.path.dirname(path))
        with open(path, "wb") as f:
            f.write(payload)
        return path

    def load_checkpoint(self, run_id: str) -> bytes:
        with open(self.get_checkpoint_path(run_id), "rb") as f:
            return f.read()


class LocalStore(Store):
    """Filesystem store (reference LocalStore, store.py:105-132); covers
    local disk, NFS and GCS-FUSE mounts on TPU VMs."""

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)


def dataframe_to_arrays(df, feature_cols, label_cols):
    """Split a pandas DataFrame into (X, y) float32 arrays; list-valued
    cells (vector columns) are stacked."""
    def col_to_array(c):
        v = df[c].to_numpy()
        if len(v) and isinstance(v[0], (list, tuple, np.ndarray)):
            return np.stack([np.asarray(x, dtype=np.float32) for x in v])
        return v.astype(np.float32)[:, None]

    x = np.concatenate([col_to_array(c) for c in feature_cols], axis=1)
    y = np.concatenate([col_to_array(c) for c in label_cols], axis=1)
    return x, y
