"""Elastic Spark worker main (spawned by spark.run_elastic through the
elastic driver): runs the cloudpickled user function as this rank and
drops the (rank, result) pickle into the shared results directory."""

from __future__ import annotations

import sys

from ..runner.fnpickle import load_payload, write_result


def main(payload_path: str, results_dir: str) -> int:
    payload = load_payload(payload_path)
    result = payload["fn"](*payload["args"], **payload["kwargs"])

    # global_state keeps the last assignment's topology across the user
    # fn's own shutdown() (reset() clears only mesh/controller/initialized)
    # — hvd.rank() itself refuses to answer post-shutdown.
    from horovod_tpu.core.state import global_state
    write_result(results_dir, global_state.rank, result)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1], sys.argv[2]))
