"""Elastic Spark worker main (spawned by spark.run_elastic through the
elastic driver): loads the pickled user function, runs it as this rank, and
drops the (rank, result) pickle into the shared results directory."""

from __future__ import annotations

import os
import pickle
import sys


def main(payload_path: str, results_dir: str) -> int:
    import cloudpickle

    with open(payload_path, "rb") as f:
        payload = cloudpickle.load(f)

    result = payload["fn"](*payload["args"], **payload["kwargs"])

    # global_state keeps the last assignment's topology across the user
    # fn's own shutdown() (reset() clears only mesh/controller/initialized)
    # — hvd.rank() itself refuses to answer post-shutdown.
    from horovod_tpu.core.state import global_state
    rank = global_state.rank
    tmp = os.path.join(results_dir, f".rank_{rank}.tmp")
    with open(tmp, "wb") as f:
        pickle.dump((rank, result), f)
    os.replace(tmp, os.path.join(results_dir, f"rank_{rank}.pkl"))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1], sys.argv[2]))
