"""Estimator API: fit DataFrames, get back transformers.

Capability parity with the reference horovod/spark Estimators
(spark/common/estimator.py + spark/keras/ + spark/torch/): an Estimator
holds a model + training params + a ``Store``; ``fit(df)`` materializes the
DataFrame to Parquet in the store, trains it data-parallel (on Spark
executors when pyspark is present, else in-process over the local runtime),
checkpoints into the store, and returns a Model transformer whose
``transform(df)`` appends predictions.

TPU-first deltas from the reference: Petastorm is replaced by a plain
Parquet→numpy feed (pandas/pyarrow are universal on TPU VMs), and the
in-process path trains through the same ``horovod_tpu`` front-ends users
run under ``hvdrun``.
"""

from __future__ import annotations

import io
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .store import Store, dataframe_to_arrays


class _EstimatorParams:
    def __init__(self, store: Optional[Store] = None,
                 num_proc: Optional[int] = None,
                 batch_size: int = 32, epochs: int = 1,
                 feature_cols: Sequence[str] = ("features",),
                 label_cols: Sequence[str] = ("label",),
                 validation: Optional[float] = None,
                 run_id: Optional[str] = None,
                 verbose: int = 1,
                 shuffle: bool = True,
                 shuffle_seed: int = 0,
                 prefetch: int = 2):
        if store is None:
            raise ValueError("an Estimator requires a store= (Store.create "
                             "or LocalStore) for intermediate data and "
                             "checkpoints")
        self.store = store
        self.num_proc = num_proc
        self.batch_size = int(batch_size)
        self.epochs = int(epochs)
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.validation = validation
        self.run_id = run_id or "run_" + uuid.uuid4().hex[:8]
        self.verbose = verbose
        # Feed behavior (the Petastorm roles, reference
        # spark/keras/remote.py:102): per-epoch seeded row-group shuffle
        # identical across ranks, and async read-ahead depth.
        self.shuffle = bool(shuffle)
        self.shuffle_seed = int(shuffle_seed)
        self.prefetch = int(prefetch)

    def _materialize(self, df):
        """DataFrame → (train_path, val_path|None) parquet in the store
        (reference util.prepare_data).  Spark DataFrames split and write
        executor-side (randomSplit + distributed parquet write) — nothing
        funnels through driver memory."""
        store = self.store
        train_path = store.get_train_data_path(self.run_id)
        val_path = None
        if hasattr(df, "toPandas"):  # Spark DataFrame
            if self.validation:
                v = float(self.validation)
                val_df, train_df = df.randomSplit([v, 1.0 - v], seed=17)
            else:
                val_df, train_df = None, df
            store.write_dataframe(train_df, train_path)
            if val_df is not None:
                val_path = store.get_val_data_path(self.run_id)
                store.write_dataframe(val_df, val_path)
            return train_path, val_path
        n = len(df)
        if self.validation:
            # Shuffle before splitting: ordered input (time- or
            # label-sorted warehouse extracts) must not yield a biased
            # validation set (the reference splits randomized too).
            df = df.sample(frac=1.0, random_state=17).reset_index(drop=True)
            n_val = int(n * float(self.validation))
            val_df, train_df = df.iloc[:n_val], df.iloc[n_val:]
        else:
            val_df, train_df = None, df
        store.write_dataframe(train_df, train_path)
        if val_df is not None and len(val_df):
            val_path = store.get_val_data_path(self.run_id)
            store.write_dataframe(val_df, val_path)
        return train_path, val_path

    def _load_arrays(self, path):
        df = self.store.read_dataframe(path)
        return dataframe_to_arrays(df, self.feature_cols, self.label_cols)


def _rank_local_batches(store, path, feature_cols, label_cols, rank, size,
                        chunk_rows=65536, epoch=0, shuffle_seed=None,
                        prefetch=0):
    """Rank-local (X, y) chunks from the store feed.  Stores implementing
    the sharded reader (rank=/size= kwargs) yield rank-local data with a
    lockstep chunk schedule — plus per-epoch seeded shuffle and async
    prefetch when supported; legacy user Store subclasses overriding the
    old iter_array_batches signature fall back to shared reads + strided
    row slicing (the pre-sharding behavior)."""
    import inspect
    try:
        params = inspect.signature(store.iter_array_batches).parameters
    except (TypeError, ValueError):  # builtins / exotic callables
        params = {}
    if "rank" in params and "size" in params:
        extra = {}
        if "epoch" in params:
            extra = {"epoch": epoch, "shuffle_seed": shuffle_seed,
                     "prefetch": prefetch}
        yield from store.iter_array_batches(
            path, feature_cols, label_cols, chunk_rows=chunk_rows,
            rank=rank, size=size, **extra)
        return
    # Legacy override: pass only the kwargs its signature accepts.
    legacy_kw = {"chunk_rows": chunk_rows} if "chunk_rows" in params else {}
    for x, y in store.iter_array_batches(path, feature_cols, label_cols,
                                         **legacy_kw):
        n_local = len(x) // size if size > 1 else len(x)
        if size > 1:
            x, y = x[rank::size][:n_local], y[rank::size][:n_local]
        if n_local:
            yield x, y


class KerasEstimator(_EstimatorParams):
    """Fit a tf.keras model on a DataFrame (reference
    spark/keras/estimator.py KerasEstimator)."""

    def __init__(self, model=None, optimizer: Any = "sgd",
                 loss: Any = "mse", metrics: Sequence[str] = (),
                 custom_objects: Optional[Dict[str, Any]] = None, **kw):
        super().__init__(**kw)
        if model is None:
            raise ValueError("KerasEstimator requires model=")
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.metrics = list(metrics)
        self.custom_objects = custom_objects or {}

    def fit(self, df) -> "KerasModel":
        if self.num_proc and self.num_proc > 1:
            raise ValueError(
                "KerasEstimator in-process fit is single-rank; for "
                "distributed keras training launch the script under "
                "hvdrun or use horovod_tpu.spark.run on a pyspark "
                "cluster (keras models don't survive spawn pickling)")
        train_path, val_path = self._materialize(df)
        x, y = self._load_arrays(train_path)
        val = self._load_arrays(val_path) if val_path else None

        import horovod_tpu.keras as hvd_keras
        hvd_keras.init()
        model = self.model
        opt = hvd_keras.DistributedOptimizer(
            self._build_optimizer(model))
        model.compile(optimizer=opt, loss=self.loss,
                      metrics=self.metrics or None)
        callbacks = [hvd_keras.callbacks.
                     BroadcastGlobalVariablesCallback(0)]
        # shuffle= honors the estimator-level feed knob (the in-memory
        # keras path shuffles rows via model.fit itself; prefetch is
        # moot here — the arrays are already resident).
        model.fit(x, y, batch_size=self.batch_size, epochs=self.epochs,
                  validation_data=val, verbose=self.verbose,
                  shuffle=self.shuffle, callbacks=callbacks)

        import tempfile, os, pathlib
        with tempfile.TemporaryDirectory() as td:
            p = os.path.join(td, "model.keras")
            model.save(p)
            payload = pathlib.Path(p).read_bytes()
        self.store.save_checkpoint(self.run_id, payload)
        return KerasModel(model=model, feature_cols=self.feature_cols,
                          label_cols=self.label_cols, store=self.store,
                          run_id=self.run_id)

    def _build_optimizer(self, model):
        import tensorflow as tf
        if isinstance(self.optimizer, str):
            return tf.keras.optimizers.get(self.optimizer)
        return self.optimizer


class _Model:
    """Shared transformer shape for fitted models: ``transform(df)``
    appends one output column per label column."""

    def __init__(self, model, feature_cols, label_cols, store=None,
                 run_id=None, output_cols: Optional[List[str]] = None):
        self.model = model
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.store = store
        self.run_id = run_id
        self.output_cols = output_cols or [
            c + "__output" for c in self.label_cols]

    def _predict(self, x: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, df):
        if hasattr(df, "toPandas"):
            df = df.toPandas()
        x, _ = dataframe_to_arrays(
            df.assign(**{c: 0.0 for c in self.label_cols
                         if c not in df.columns}),
            self.feature_cols, self.label_cols)
        preds = np.asarray(self._predict(x))
        out = df.copy()
        for i, c in enumerate(self.output_cols):
            col = preds[:, i] if preds.ndim > 1 and preds.shape[1] > i \
                else preds.reshape(len(out), -1)[:, 0]
            out[c] = col
        return out


class KerasModel(_Model):
    """Transformer returned by KerasEstimator.fit (reference
    spark/keras/estimator.py KerasModel)."""

    def _predict(self, x):
        return self.model.predict(x, verbose=0)


class TorchEstimator(_EstimatorParams):
    """Fit a torch model on a DataFrame (reference
    spark/torch/estimator.py TorchEstimator)."""

    def __init__(self, model=None, optimizer: Optional[Callable] = None,
                 loss: Optional[Callable] = None, lr: float = 0.01, **kw):
        super().__init__(**kw)
        if model is None:
            raise ValueError("TorchEstimator requires model=")
        self.model = model
        self.optimizer_fn = optimizer
        self.loss_fn = loss
        self.lr = lr

    def fit(self, df) -> "TorchModel":
        import torch
        train_path, val_path = self._materialize(df)

        spec = {
            "model": self.model, "optimizer_fn": self.optimizer_fn,
            "loss_fn": self.loss_fn, "lr": self.lr, "epochs": self.epochs,
            "batch_size": self.batch_size, "store": self.store,
            "train_path": train_path,
            "feature_cols": self.feature_cols,
            "label_cols": self.label_cols,
            "shuffle_seed": self.shuffle_seed if self.shuffle else None,
            "prefetch": self.prefetch,
        }
        if self.num_proc and self.num_proc > 1:
            # Data-parallel fit: one local rank per process, batches
            # sharded by rank, gradients averaged by DistributedOptimizer
            # (the reference distributes over Spark executors; pyspark jobs
            # should use horovod_tpu.spark.run with a module-level fn).
            from ..runner import run as _run
            states = _run(_torch_fit_worker, args=(spec,),
                          np=int(self.num_proc))
            state = next(s for s in states if s is not None)
            self.model.load_state_dict(
                torch.load(io.BytesIO(state), weights_only=True))
        else:
            _torch_train_loop(spec)

        val_loss = None
        if val_path:
            xv, yv = self._load_arrays(val_path)
            loss_fn = self.loss_fn or torch.nn.MSELoss()
            with torch.no_grad():
                val_loss = float(loss_fn(self.model(torch.from_numpy(xv)),
                                         torch.from_numpy(yv)))
            if self.verbose:
                print(f"[TorchEstimator {self.run_id}] "
                      f"validation loss: {val_loss:.6f}")

        buf = io.BytesIO()
        torch.save(self.model.state_dict(), buf)
        self.store.save_checkpoint(self.run_id, buf.getvalue())
        out = TorchModel(model=self.model, feature_cols=self.feature_cols,
                         label_cols=self.label_cols, store=self.store,
                         run_id=self.run_id)
        out.validation_loss = val_loss
        return out


def _torch_train_loop(spec) -> None:
    """One rank's training loop: parquet chunks streamed from the store
    (never the whole dataset in memory — the reference's Petastorm role),
    rows sharded by rank within each chunk, grads allreduced through
    DistributedOptimizer, initial params synced from rank 0."""
    import torch
    import horovod_tpu.torch as hvd_torch
    hvd_torch.init()
    model = spec["model"]
    store = spec["store"]  # user Store subclass travels to workers intact
    # Shard by the eager communicator (participating processes), not
    # hvd.size() — chip-level size can exceed the process count on a
    # multi-device host, which would silently drop data.  The store's
    # sharded reader guarantees an identical fixed-size chunk schedule on
    # every rank (truncated to the common per-rank row count), so the
    # blocking per-gradient allreduces stay in lockstep.
    from ..ops.collective import communicator_size
    size = communicator_size()
    rank = hvd_torch.rank() % size if size > 1 else 0

    base_opt = (spec["optimizer_fn"](model.parameters())
                if spec["optimizer_fn"]
                else torch.optim.SGD(model.parameters(), lr=spec["lr"]))
    opt = hvd_torch.DistributedOptimizer(
        base_opt, named_parameters=model.named_parameters())
    hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)
    loss_fn = spec["loss_fn"] or torch.nn.MSELoss()

    g = torch.Generator().manual_seed(13)
    chunk_rows = int(spec.get("chunk_rows") or 65536)
    for epoch in range(spec["epochs"]):
        # The feed yields rank-local chunks (per-rank sharded reads with
        # an identical chunk schedule on every rank; legacy Store
        # overrides fall back to shared reads + strided rows), traversed
        # in a fresh seeded order each epoch with async read-ahead.
        for x, y in _rank_local_batches(
                store, spec["train_path"], spec["feature_cols"],
                spec["label_cols"], rank, size, chunk_rows=chunk_rows,
                epoch=epoch, shuffle_seed=spec.get("shuffle_seed"),
                prefetch=spec.get("prefetch", 0)):
            n_local = len(x)
            if n_local == 0:
                continue
            xt, yt = torch.from_numpy(x), torch.from_numpy(y)
            perm = torch.randperm(n_local, generator=g)
            for s in range(0, n_local, spec["batch_size"]):
                idx = perm[s:s + spec["batch_size"]]
                opt.zero_grad()
                loss = loss_fn(model(xt[idx]), yt[idx])
                loss.backward()
                opt.step()


def _torch_fit_worker(spec):
    """Module-level worker for runner.run (spawn requires picklability):
    trains a rank; rank 0 returns the state_dict bytes."""
    from .store import prefer_system_arrow_pool
    prefer_system_arrow_pool()  # before the worker's first arrow touch
    import io as _io
    import torch
    import horovod_tpu.torch as hvd_torch
    _torch_train_loop(spec)
    if hvd_torch.rank() == 0:
        buf = _io.BytesIO()
        torch.save(spec["model"].state_dict(), buf)
        return buf.getvalue()
    return None


class TorchModel(_Model):
    """Transformer returned by TorchEstimator.fit."""

    def _predict(self, x):
        import torch
        with torch.no_grad():
            return self.model(torch.from_numpy(x)).numpy()


class LightningEstimator(_EstimatorParams):
    """Fit a LightningModule-style model on a DataFrame (reference
    spark/lightning/estimator.py LightningEstimator).

    Duck-typed against the LightningModule protocol —
    ``configure_optimizers()`` and ``training_step(batch, batch_idx)`` on a
    torch ``nn.Module`` — so it works with real ``pytorch_lightning``
    modules *and* without the lightning package installed (TPU VMs rarely
    ship it).  The optimizer the module configures is wrapped with
    hvd.DistributedOptimizer; batches stream from the store in chunks."""

    def __init__(self, model=None, **kw):
        super().__init__(**kw)
        if model is None:
            raise ValueError("LightningEstimator requires model= (a "
                             "LightningModule or any nn.Module with "
                             "configure_optimizers + training_step)")
        for required in ("configure_optimizers", "training_step"):
            if not callable(getattr(model, required, None)):
                raise TypeError(f"model lacks {required}(); pass a "
                                "LightningModule-style module")
        self.model = model

    def fit(self, df) -> "LightningModel":
        import torch
        train_path, _val_path = self._materialize(df)
        spec = {
            "model": self.model, "epochs": self.epochs,
            "batch_size": self.batch_size, "store": self.store,
            "train_path": train_path,
            "feature_cols": self.feature_cols,
            "label_cols": self.label_cols,
            "shuffle_seed": self.shuffle_seed if self.shuffle else None,
            "prefetch": self.prefetch,
        }
        if self.num_proc and self.num_proc > 1:
            from ..runner import run as _run
            states = _run(_lightning_fit_worker, args=(spec,),
                          np=int(self.num_proc))
            state = next(s for s in states if s is not None)
            self.model.load_state_dict(
                torch.load(io.BytesIO(state), weights_only=True))
        else:
            _lightning_train_loop(spec)
        buf = io.BytesIO()
        torch.save(self.model.state_dict(), buf)
        self.store.save_checkpoint(self.run_id, buf.getvalue())
        return LightningModel(
            model=self.model, feature_cols=self.feature_cols,
            label_cols=self.label_cols, store=self.store,
            run_id=self.run_id)


def _first_optimizer(configured):
    """configure_optimizers may return an optimizer, a list, or the
    lightning ([optimizers], [schedulers]) pair."""
    if isinstance(configured, (list, tuple)):
        first = configured[0]
        if isinstance(first, (list, tuple)):
            first = first[0]
        return first
    return configured


def _lightning_train_loop(spec) -> None:
    import horovod_tpu.torch as hvd_torch
    hvd_torch.init()
    model = spec["model"]
    store = spec["store"]
    from ..ops.collective import communicator_size
    size = communicator_size()
    rank = hvd_torch.rank() % size if size > 1 else 0

    import torch
    base_opt = _first_optimizer(model.configure_optimizers())
    opt = hvd_torch.DistributedOptimizer(
        base_opt, named_parameters=model.named_parameters())
    hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)

    g = torch.Generator().manual_seed(13)
    batch_idx = 0
    for epoch in range(spec["epochs"]):
        for x, y in _rank_local_batches(
                store, spec["train_path"], spec["feature_cols"],
                spec["label_cols"], rank, size,
                epoch=epoch, shuffle_seed=spec.get("shuffle_seed"),
                prefetch=spec.get("prefetch", 0)):
            n_local = len(x)
            if n_local == 0:
                continue
            xt, yt = torch.from_numpy(x), torch.from_numpy(y)
            perm = torch.randperm(n_local, generator=g)
            for s in range(0, n_local, spec["batch_size"]):
                idx = perm[s:s + spec["batch_size"]]
                opt.zero_grad()
                loss = model.training_step((xt[idx], yt[idx]), batch_idx)
                if isinstance(loss, dict):  # lightning allows {"loss": t}
                    loss = loss["loss"]
                loss.backward()
                opt.step()
                batch_idx += 1


def _lightning_fit_worker(spec):
    """Module-level lightning worker for runner.run: trains a rank;
    rank 0 returns the state_dict bytes."""
    from .store import prefer_system_arrow_pool
    prefer_system_arrow_pool()  # before the worker's first arrow touch
    import io as _io
    import torch
    import horovod_tpu.torch as hvd_torch
    _lightning_train_loop(spec)
    if hvd_torch.rank() == 0:
        buf = _io.BytesIO()
        torch.save(spec["model"].state_dict(), buf)
        return buf.getvalue()
    return None


class LightningModel(_Model):
    """Transformer returned by LightningEstimator.fit."""

    def _predict(self, x):
        import torch
        with torch.no_grad():
            return self.model(torch.from_numpy(x)).numpy()
