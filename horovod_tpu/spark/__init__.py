"""Spark integration — run a training function on Spark executors as ranks.

Capability parity with the reference horovod.spark.run
(spark/runner.py:47-156): one barrier-mode task per executor registers its
hostname with the driver, ranks are assigned host-major, the launcher env is
injected, and the user function runs inside each task.  The Estimator API
(store.py ``Store``/``LocalStore``, estimator.py ``KerasEstimator``/
``TorchEstimator``) fits DataFrames via Parquet materialization into the
store, mirroring the reference's spark/common/store.py + spark/keras +
spark/torch estimators.

``pyspark`` is an optional dependency; a clear error is raised without it.
"""

from __future__ import annotations

import socket
from typing import Any, Callable, List, Optional

from .store import Store, LocalStore                      # noqa: F401
from .estimator import (KerasEstimator, KerasModel,       # noqa: F401
                        TorchEstimator, TorchModel)


def run(fn: Callable, args=(), kwargs=None, num_proc: Optional[int] = None,
        controller_port: int = 29100) -> List[Any]:
    try:
        from pyspark import BarrierTaskContext
        from pyspark.sql import SparkSession
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark.run requires pyspark; install pyspark or "
            "use the hvdrun launcher instead") from e

    kwargs = kwargs or {}
    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    num_proc = num_proc or int(sc.defaultParallelism)

    from ..runner.hosts import HostInfo, get_host_assignments, slot_env

    def _task(_):
        ctx = BarrierTaskContext.get()
        hostname = socket.gethostname()
        # Barrier all-gather of hostnames establishes the host->slots map
        # (reference: driver/task registration, spark/runner.py:47-156).
        infos = ctx.allGather(hostname)
        counts = {}
        for h in infos:
            counts[h] = counts.get(h, 0) + 1
        hosts = [HostInfo(h, c) for h, c in sorted(counts.items())]
        slots = get_host_assignments(hosts, len(infos))
        # This task's rank: position among same-host partitions.
        pid = ctx.partitionId()
        my_slot = slots[pid]
        controller_addr = f"{slots[0].hostname}:{controller_port}"
        import os
        os.environ.update(slot_env(my_slot, controller_addr))
        return [fn(*args, **kwargs)]

    rdd = sc.parallelize(range(num_proc), num_proc).barrier()
    return rdd.mapPartitions(_task).collect()
