"""Spark integration — run a training function on Spark executors as ranks.

Capability parity with the reference horovod.spark (spark/runner.py):

* ``run(fn, ...)`` (reference runner.py:47-156) — one barrier-mode task per
  executor registers its hostname, ranks are assigned host-major, the
  launcher env is injected, and ``fn`` runs inside each task.
* ``run_elastic(fn, ...)`` (reference runner.py:306) — elastic variant:
  executor hosts feed the elastic driver, workers are (re)spawned across
  rendezvous rounds, and per-rank results are collected from the round
  that completes.
* Estimator API (store.py ``Store``/``LocalStore``, estimator.py
  ``KerasEstimator``/``TorchEstimator``/``LightningEstimator``).

``pyspark`` is an optional dependency; a clear error is raised without it.
"""

from __future__ import annotations

import os
import socket
import sys
import tempfile
from typing import Any, Callable, List, Optional, Tuple

from .store import (Store, LocalStore, FsspecStore,       # noqa: F401
                    GCSStore)
from .estimator import (KerasEstimator, KerasModel,       # noqa: F401
                        TorchEstimator, TorchModel,
                        LightningEstimator, LightningModel)
from ..runner.hosts import (HostInfo, SlotInfo, get_host_assignments,
                            slot_env)


def _require_pyspark():
    try:
        from pyspark import BarrierTaskContext
        from pyspark.sql import SparkSession
        return BarrierTaskContext, SparkSession
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark; install pyspark or use "
            "the hvdrun launcher instead") from e


def _resolve_slot(infos: List[str], pid: int) -> Tuple[SlotInfo, str]:
    """Map this barrier task to its slot from the gathered hostname list.

    ``infos[i]`` is partition i's hostname (BarrierTaskContext.allGather
    preserves partition order).  Slots are host-major over sorted
    hostnames, but partition→host placement is arbitrary — so the task's
    slot is found by its OWN hostname and its index among same-host
    partitions, never by raw partition id (which mis-assigns whenever
    partition order differs from sorted-host order; the controller then
    binds on the wrong machine and the job cannot form).

    Returns (slot, controller_host) where controller_host is rank 0's
    actual hostname.
    """
    hostname = infos[pid]
    counts: dict = {}
    for h in infos:
        counts[h] = counts.get(h, 0) + 1
    hosts = [HostInfo(h, c) for h, c in sorted(counts.items())]
    slots = get_host_assignments(hosts, len(infos))
    local_idx = sum(1 for h in infos[:pid] if h == hostname)
    my_slot = next(s for s in slots
                   if s.hostname == hostname and s.local_rank == local_idx)
    return my_slot, slots[0].hostname


def run(fn: Callable, args=(), kwargs=None, num_proc: Optional[int] = None,
        controller_port: int = 29100) -> List[Any]:
    BarrierTaskContext, SparkSession = _require_pyspark()
    kwargs = kwargs or {}
    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    num_proc = num_proc or int(sc.defaultParallelism)

    def _task(_):
        ctx = BarrierTaskContext.get()
        infos = list(ctx.allGather(socket.gethostname()))
        my_slot, controller_host = _resolve_slot(infos, ctx.partitionId())
        controller_addr = f"{controller_host}:{controller_port}"
        os.environ.update(slot_env(my_slot, controller_addr))
        return [(my_slot.rank, fn(*args, **kwargs))]

    rdd = sc.parallelize(range(num_proc), num_proc).barrier()
    results = rdd.mapPartitions(_task).collect()
    return [value for _rank, value in sorted(results)]


def _discover_executor_hosts(num_proc: int) -> List[HostInfo]:
    """Barrier-mode job gathering executor hostnames → HostInfo list (the
    reference's driver/task registration, spark/runner.py:47+).  Barrier
    mode forces one concurrent task per slot, so the host→slot counts
    reflect real executor capacity — a plain job could run every task on
    one fast executor and oversubscribe it."""
    BarrierTaskContext, SparkSession = _require_pyspark()
    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext

    def _task(_):
        BarrierTaskContext.get()
        return [socket.gethostname()]

    names = sc.parallelize(range(num_proc), num_proc).barrier() \
        .mapPartitions(_task).collect()
    counts: dict = {}
    for h in names:
        counts[h] = counts.get(h, 0) + 1
    return [HostInfo(h, c) for h, c in sorted(counts.items())]


def run_elastic(fn: Callable, args=(), kwargs=None,
                num_proc: Optional[int] = None,
                min_np: Optional[int] = None,
                max_np: Optional[int] = None,
                controller_base_port: int = 29400,
                work_dir: Optional[str] = None,
                hosts: Optional[List[HostInfo]] = None,
                gateway: Optional[str] = None,
                priority: int = 0,
                tenant: str = "default",
                verbose: bool = False) -> List[Any]:
    """Elastic Spark run (reference spark/runner.py:306 run_elastic).

    ``fn`` must be importable/picklable (module-level) and should drive its
    training with ``hvd.elastic.run(state)`` so worker failures restore
    committed state.  Completed ranks' return values are collected (rank
    order).  Worker payload and results travel through ``work_dir`` — a
    path visible to every executor host (defaults to a local temp dir,
    which is correct for local-mode Spark; pass a shared-filesystem path,
    e.g. a Store prefix, on real clusters).

    ``hosts`` overrides executor discovery (test seam / static clusters).

    With ``gateway=`` the job is SUBMITTED to a fleet gateway instead of
    this process owning the device fleet: the gateway schedules it onto
    its inventory (priority/quota/preemption apply; docs/fleet.md), and
    ``work_dir`` must be visible to the gateway's hosts.
    """
    from ..runner.fnpickle import collect_results, dump_payload

    kwargs = kwargs or {}
    num_proc = num_proc or (sum(h.slots for h in hosts) if hosts else 1)
    if hosts is None and gateway is None:
        hosts = _discover_executor_hosts(num_proc)
    min_np = min_np or num_proc

    own_tmp = work_dir is None
    work_dir = work_dir or tempfile.mkdtemp(prefix="hvd_spark_elastic_")
    payload_path, results_dir = dump_payload(work_dir, fn, args, kwargs)

    command = [sys.executable, "-m", "horovod_tpu.spark.elastic_exec",
               payload_path, results_dir]
    if gateway is not None:
        from ..fleet import JobSpec, client
        rec = client.submit_job(
            JobSpec(command=command, min_np=min_np, max_np=max_np,
                    priority=priority, tenant=tenant), addr=gateway)
        if rec.state == "queued":
            rec = client.wait_job(rec.id, addr=gateway)
        if rec.state != "done":
            raise RuntimeError(
                f"fleet job {rec.id} ended {rec.state}"
                + (f": {rec.reason}" if rec.reason else ""))
        rc = 0
    else:
        from ..runner.elastic_driver import ElasticDriver, FixedHosts
        driver = ElasticDriver(
            FixedHosts(hosts), command, min_np=min_np, max_np=max_np,
            controller_base_port=controller_base_port, verbose=verbose)
        rc = driver.run()
        if rc != 0:
            raise RuntimeError(f"elastic spark job failed (exit {rc})")

    out = collect_results(results_dir)
    if own_tmp:
        import shutil
        shutil.rmtree(work_dir, ignore_errors=True)
    return out
