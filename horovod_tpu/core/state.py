"""Global runtime state.

Analog of the reference's ``HorovodGlobalState`` (global_state.h:43-132), but
TPU-native: instead of a background-thread handle plus NCCL stream tables, the
state owns the global ``jax.sharding.Mesh``, the process-level topology
(rank/size/local/cross, reference common.h:119-123), the parsed ``Config`` and
— once the native runtime is attached — the controller handle for the eager
path.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional

from .config import Config

# Default mesh axis name for data parallelism. Compiled collectives default to
# reducing over this axis when no axis_name is given.
DATA_AXIS = "data"


@dataclasses.dataclass
class GlobalState:
    initialized: bool = False
    config: Config = dataclasses.field(default_factory=Config)

    # Chip-level topology (Horovod rank semantics: one rank per accelerator).
    rank: int = 0
    size: int = 1
    local_rank: int = 0
    local_size: int = 1
    cross_rank: int = 0
    cross_size: int = 1

    # Process-level topology (JAX multi-controller).
    process_rank: int = 0
    process_count: int = 1

    # The global device mesh. 1-D over DATA_AXIS unless the user passed one.
    # Built lazily by basics.mesh() so eager-only workers never touch the
    # JAX backend; mesh_axes_hint carries init(axes=...) until then.
    mesh: Optional[Any] = None
    mesh_axes_hint: Optional[Any] = None

    # Native eager-path runtime (attached lazily; None in pure-compiled mode).
    controller: Optional[Any] = None

    # Elastic bookkeeping. elastic_round survives reset() so a re-init can
    # demand a *newer* rendezvous round than the one that just failed.
    elastic_enabled: bool = False
    elastic_round: int = -1

    def reset(self) -> None:
        self.initialized = False
        self.mesh = None
        self.mesh_axes_hint = None
        self.controller = None


global_state = GlobalState()


def _env_int(name: str) -> Optional[int]:
    """Read a launcher-provided env int; both HOROVOD_ and HVD_TPU_ accepted.

    The launcher→worker contract is pure environment variables, mirroring the
    reference (gloo_run.py:64-75 exports HOROVOD_RANK/SIZE/LOCAL_RANK/...).
    """
    for prefix in ("HVD_TPU_", "HOROVOD_"):
        val = os.environ.get(prefix + name)
        if val is not None:
            try:
                return int(val)
            except ValueError:
                return None
    return None
