"""Environment-variable configuration knobs.

The reference concentrates all runtime tunables in ``HOROVOD_*`` env vars
(common.h:66-96, parsed in operations.cc:395-540 and utils/env_parser.cc).
We accept both the original ``HOROVOD_*`` names (drop-in compatibility) and
``HVD_TPU_*`` overrides; the TPU-specific name wins when both are set.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

# Knob names (reference: common.h:66-96).
FUSION_THRESHOLD = "FUSION_THRESHOLD"          # bytes
CYCLE_TIME = "CYCLE_TIME"                      # ms, background loop cadence
CACHE_CAPACITY = "CACHE_CAPACITY"              # response-cache entries
TIMELINE = "TIMELINE"                          # filename
TIMELINE_MARK_CYCLES = "TIMELINE_MARK_CYCLES"
AUTOTUNE = "AUTOTUNE"
AUTOTUNE_LOG = "AUTOTUNE_LOG"
AUTOTUNE_WARMUP_SAMPLES = "AUTOTUNE_WARMUP_SAMPLES"
AUTOTUNE_STEPS_PER_SAMPLE = "AUTOTUNE_STEPS_PER_SAMPLE"
AUTOTUNE_BAYES_OPT_MAX_SAMPLES = "AUTOTUNE_BAYES_OPT_MAX_SAMPLES"
AUTOTUNE_GAUSSIAN_PROCESS_NOISE = "AUTOTUNE_GAUSSIAN_PROCESS_NOISE"
# Closed-loop autotuning (the observatory feedback plane): persistent
# tuning memory keyed by (model fingerprint, world, topology) — the
# autotune analog of the response cache — plus drift-triggered bounded
# re-tune episodes with regression-gated rollback.  See
# docs/timeline_autotune.md ("Closing the loop").
AUTOTUNE_MEMORY = "AUTOTUNE_MEMORY"            # warm start + write-back
AUTOTUNE_MEMORY_DIR = "AUTOTUNE_MEMORY_DIR"    # local store (no gateway)
AUTOTUNE_RETUNE = "AUTOTUNE_RETUNE"            # drift-triggered re-tune
AUTOTUNE_RETUNE_WINDOWS = "AUTOTUNE_RETUNE_WINDOWS"  # episode budget
AUTOTUNE_ROLLBACK_PCT = "AUTOTUNE_ROLLBACK_PCT"  # regression gate (%)
LOG_LEVEL = "LOG_LEVEL"
LOG_HIDE_TIME = "LOG_HIDE_TIME"
STALL_CHECK_DISABLE = "STALL_CHECK_DISABLE"
STALL_CHECK_TIME_SECONDS = "STALL_CHECK_TIME_SECONDS"
STALL_SHUTDOWN_TIME_SECONDS = "STALL_SHUTDOWN_TIME_SECONDS"
HIERARCHICAL_ALLREDUCE = "HIERARCHICAL_ALLREDUCE"
HIERARCHICAL_ALLGATHER = "HIERARCHICAL_ALLGATHER"
# Topology-probed per-payload schedule dispatch (ops/dispatch.py): a
# short seeded probe at init() measures flat vs hierarchical per payload
# size and installs a per-(op kind, payload bucket) dispatch table the
# coordinator stamps into every response.  An EXPLICIT
# HVD_TPU_HIERARCHICAL_ALLREDUCE/_ALLGATHER pins that op kind to the
# given schedule for the whole payload range and bypasses its probe
# (the blind-global semantics those knobs had before the dispatch plane
# — kept as pins, deprecated as defaults).
SCHEDULE_PROBE = "SCHEDULE_PROBE"              # probe + dispatch on/off
SCHEDULE_PROBE_SEED = "SCHEDULE_PROBE_SEED"    # payload-content seed
SCHEDULE_PROBE_REPS = "SCHEDULE_PROBE_REPS"    # timed reps per arm
BATCH_D2D_MEMCOPIES = "BATCH_D2D_MEMCOPIES"
ELASTIC = "ELASTIC"
MESH_AXES = "MESH_AXES"                        # TPU-only: mesh axis spec
COMPILE_CACHE_DIR = "COMPILE_CACHE_DIR"        # TPU-only: persistent XLA cache
# Input pipeline (horovod_tpu/data/).
DATA_PREFETCH = "DATA_PREFETCH"                # background prefetch on/off
DATA_QUEUE_DEPTH = "DATA_QUEUE_DEPTH"          # prefetch queue depth
DATA_STALL_TIMEOUT_SECONDS = "DATA_STALL_TIMEOUT_SECONDS"  # 0 = warn only
# Quantized collective engine (horovod_tpu/ops/quantization.py).
COMPRESSION = "COMPRESSION"                    # none|fp16|bf16|int8|int4
QUANT_BLOCK = "QUANT_BLOCK"                    # elements per absmax scale
# Backward-overlap bucketed gradient scheduler (horovod_tpu/ops/overlap.py).
OVERLAP = "OVERLAP"                            # session default on/off
OVERLAP_BUCKET_BYTES = "OVERLAP_BUCKET_BYTES"  # bucket size; pins autotune
# GSPMD-native weight-update sharding (horovod_tpu/optimizers.py
# ZeroShardedOptimizer + ops/gspmd.py): 1 = optimizer state sharded
# (ZeRO-1), 2 = + gradient shards are the persistent objects (ZeRO-2),
# 3 = + parameters sharded with forward-prefetched per-bucket gathers
# (ZeRO-3).  ZERO_PREFETCH gates the per-bucket forward gather schedule
# (off = one monolithic gather before forward).
ZERO_STAGE = "ZERO_STAGE"                      # 1 | 2 | 3
ZERO_PREFETCH = "ZERO_PREFETCH"                # bucketed forward gathers
ZERO_QUANT_GATHER = "ZERO_QUANT_GATHER"        # quantized stage-3 gathers
# Metrics subsystem (horovod_tpu/metrics/).
METRICS_SYNC_STEPS = "METRICS_SYNC_STEPS"      # cross-rank cadence; 0 = off
METRICS_PORT = "METRICS_PORT"                  # Prometheus port; 0 = off
METRICS_STRAGGLER_FACTOR = "METRICS_STRAGGLER_FACTOR"
METRICS_STRAGGLER_MIN_SECONDS = "METRICS_STRAGGLER_MIN_SECONDS"
METRICS_STRAGGLER_PATIENCE = "METRICS_STRAGGLER_PATIENCE"
# Host-sharded (hierarchical) telemetry plane (metrics/digest.py +
# metrics/observer.py): intra-host digest merge at the per-host
# observer, one O(hosts) exchange per sync round, flat allgather kept
# as the small-world default.  TOPK bounds the per-host raw outlier
# evidence riding each digest.
METRICS_TREE = "METRICS_TREE"                  # hierarchical sync on/off
METRICS_TOPK = "METRICS_TOPK"                  # outlier evidence per host
METRICS_TREE_TIMEOUT_S = "METRICS_TREE_TIMEOUT_S"  # exchange deadline
METRICS_TREE_GRACE_S = "METRICS_TREE_GRACE_S"  # laggard-snapshot grace
METRICS_RETAIN_FILES = "METRICS_RETAIN_FILES"  # JSONL rotation retention
# Performance observatory (horovod_tpu/metrics/attribution.py +
# baseline.py): per-step time attribution, live MFU, drift detection.
ATTRIBUTION = "ATTRIBUTION"                    # per-step attribution on/off
ATTRIBUTION_JSONL = "ATTRIBUTION_JSONL"        # per-step JSONL sink path
PEAK_TFLOPS = "PEAK_TFLOPS"                    # calibrated chip peak; 0 = spec
PERF_DRIFT = "PERF_DRIFT"                      # drift detector on/off
PERF_DRIFT_WARMUP = "PERF_DRIFT_WARMUP"        # baseline steps before arming
PERF_DRIFT_THRESHOLD = "PERF_DRIFT_THRESHOLD"  # CUSUM trip level (sigmas)
PERF_DRIFT_MIN_PCT = "PERF_DRIFT_MIN_PCT"      # min % slowdown to fire
PERF_DRIFT_COOLDOWN = "PERF_DRIFT_COOLDOWN"    # steps muted after a fire
PERF_DRIFT_LOOKBACK_S = "PERF_DRIFT_LOOKBACK_S"  # event-correlation window
# Flight recorder / hang diagnosis (horovod_tpu/debug/).
FLIGHT_DISABLE = "FLIGHT_DISABLE"              # recorder off entirely
FLIGHT_CAPACITY = "FLIGHT_CAPACITY"            # ring-buffer events
FLIGHT_DIR = "FLIGHT_DIR"                      # dumps + hang reports
FLIGHT_PORT = "FLIGHT_PORT"                    # debug endpoint; 0 = ephemeral
FLIGHT_LAST_EVENTS = "FLIGHT_LAST_EVENTS"      # events quoted per rank
FLIGHT_ESCALATE = "FLIGHT_ESCALATE"            # stall -> hang report
# Peer-to-peer hot recovery (horovod_tpu/recovery/).
RECOVERY = "RECOVERY"                          # buddy replication + peer restore
RECOVERY_STRIDE = "RECOVERY_STRIDE"            # buddy ring shift; 0 = local size
ASYNC_COMMIT = "ASYNC_COMMIT"                  # background disk committer
CKPT_STREAMING = "CKPT_STREAMING"              # per-leaf streaming restore
# Deterministic fault injection (horovod_tpu/recovery/chaos.py).  The
# chaos layer is inert unless at least one CHAOS_* knob is set.
CHAOS_SEED = "CHAOS_SEED"                      # schedule seed
CHAOS_KILL_STEPS = "CHAOS_KILL_STEPS"          # "rank@step,..." kill schedule
CHAOS_COMMIT_CRASH = "CHAOS_COMMIT_CRASH"      # "<point>[@step]" crash point
CHAOS_SLOW_PEER_MS = "CHAOS_SLOW_PEER_MS"      # peer-serving latency injection
CHAOS_TORN_RANKS = "CHAOS_TORN_RANKS"          # corrupt these ranks' replicas
CHAOS_INPUT_DELAY_MS = "CHAOS_INPUT_DELAY_MS"  # input-pipeline slowdown drill
CHAOS_COMM_DELAY_MS = "CHAOS_COMM_DELAY_MS"    # comm-side slowdown drill
# Self-healing wire fabric (horovod_tpu/net/ + native/src/net.cc).  The
# native knobs are parsed in C (net.cc NetResilience/NetChaos); they are
# listed here so the knob table has one home and launch.py exports them.
NET_RESILIENCE = "NET_RESILIENCE"              # escalation ladder on/off
NET_PROBE_MS = "NET_PROBE_MS"                  # no-progress reconnect probe
NET_RECONNECT_S = "NET_RECONNECT_S"            # budget per reconnect
NET_OP_DEADLINE_S = "NET_OP_DEADLINE_S"        # per-transfer total budget
NET_MAX_RENEG = "NET_MAX_RENEG"                # ring re-formations cap
NET_RENEGOTIATE = "NET_RENEGOTIATE"            # rung 3 on/off
NET_HTTP_RETRIES = "NET_HTTP_RETRIES"          # attempts per HTTP request
NET_HTTP_BACKOFF_MS = "NET_HTTP_BACKOFF_MS"    # base of the jittered backoff
# Fleet service mode (horovod_tpu/fleet/): always-on multi-tenant job
# gateway multiplexing submitted jobs onto one device fleet.
FLEET_PORT = "FLEET_PORT"                      # gateway HTTP port
FLEET_ADDR = "FLEET_ADDR"                      # client default gateway addr
FLEET_SECRET = "FLEET_SECRET"                  # submission HMAC secret
FLEET_DIR = "FLEET_DIR"                        # durable job-queue directory
FLEET_TICK_S = "FLEET_TICK_S"                  # scheduler cadence
FLEET_QUOTA_SLOTS = "FLEET_QUOTA_SLOTS"        # per-tenant slots; 0 = unlimited
FLEET_PREEMPTION = "FLEET_PREEMPTION"          # priority preemption on/off
FLEET_PREEMPT_GRACE_S = "FLEET_PREEMPT_GRACE_S"  # commit wait before forcing
# Fleet timeline (fleet/observe.py): host observers push digests to the
# gateway's bounded ring store on a cadence; operators query per-job
# series over GET /fleet/observe/<job> without touching worker disks.
FLEET_OBSERVE_PUSH_S = "FLEET_OBSERVE_PUSH_S"  # push cadence; 0 = off
FLEET_OBSERVE_RETAIN = "FLEET_OBSERVE_RETAIN"  # ring samples per job
# Serving plane (horovod_tpu/serving/): continuous-batching inference
# services on the fleet fabric — decode-slot geometry, the bounded
# admission queue, checkpoint hot-swap polling, and queue/SLO-driven
# replica autoscaling.  See docs/serving.md.
SERVING_PORT = "SERVING_PORT"                  # request-plane HTTP port
SERVING_ADDR = "SERVING_ADDR"                  # client default replica addr
SERVING_SECRET = "SERVING_SECRET"              # request HMAC secret
SERVING_SLOTS = "SERVING_SLOTS"                # decode slots per replica
SERVING_PAGE_TOKENS = "SERVING_PAGE_TOKENS"    # tokens per KV page
SERVING_MAX_LEN = "SERVING_MAX_LEN"            # context cap; 0 = model seq_len
SERVING_MAX_NEW_TOKENS = "SERVING_MAX_NEW_TOKENS"  # default output cap
SERVING_QUEUE_CAP = "SERVING_QUEUE_CAP"        # admission queue bound
SERVING_SWAP_POLL_S = "SERVING_SWAP_POLL_S"    # checkpoint watch cadence
SERVING_AUTOSCALE = "SERVING_AUTOSCALE"        # replica autoscaler on/off
SERVING_TARGET_QUEUE = "SERVING_TARGET_QUEUE"  # queued reqs/replica target
SERVING_SLO_TTFT_S = "SERVING_SLO_TTFT_S"      # TTFT target; 0 = none
SERVING_SCALE_COOLDOWN_S = "SERVING_SCALE_COOLDOWN_S"  # resize hysteresis
# Production-scale serving (ISSUE 18): radix prefix cache, chunked
# prefill, speculative decoding, disaggregated prefill/decode.
SERVING_PREFIX_CACHE = "SERVING_PREFIX_CACHE"  # radix KV prefix cache on/off
SERVING_PREFILL_CHUNK = "SERVING_PREFILL_CHUNK"  # prefill tokens/iter; 0 = all
SERVING_AGING_S = "SERVING_AGING_S"            # page-reservation aging; 0 = off
SERVING_MIGRATE_BITS = "SERVING_MIGRATE_BITS"  # KV wire quant: 0 = fp32; 8 | 4
SPEC_K = "SPEC_K"                              # draft tokens/round; 0 = off
# Request-scoped tracing + per-tenant SLO error budgets (ISSUE 19):
# serving/tracing.py and serving/slo.py.  See docs/observability.md.
TRACE_SAMPLE = "TRACE_SAMPLE"                  # sampled request fraction [0,1]
TRACE_SEED = "TRACE_SEED"                      # trace-id derivation seed
SLO_TARGET = "SLO_TARGET"                      # attainment target [0.5,0.9999]
SLO_WINDOW_S = "SLO_WINDOW_S"                  # rolling budget window (s)
SLO_BURN_THRESHOLD = "SLO_BURN_THRESHOLD"      # burn rate that trips action
# Third mesh dimensions (parallel/moe.py, parallel/pipeline.py): MoE
# routing geometry and the pipeline schedule.  Single-sourced here —
# models read these through Config/the getters, never os.environ
# directly.  See docs/parallel.md for the knob table.
MOE_TOP_K = "MOE_TOP_K"                        # experts routed per token
MOE_CAPACITY_FACTOR = "MOE_CAPACITY_FACTOR"    # dispatch slots / even share
MOE_DISPATCH_BITS = "MOE_DISPATCH_BITS"        # 0 = fp32 wire; 8 | 4
MOE_DISPATCH_BLOCK = "MOE_DISPATCH_BLOCK"      # quant scale-block length
PP_SCHEDULE = "PP_SCHEDULE"                    # "gpipe" | "1f1b"
PP_MICROBATCHES = "PP_MICROBATCHES"            # microbatches per step
# Seeded wire chaos (both the native socket layer and the Python HTTP
# planes read these; inert unless set).
CHAOS_NET_SEED = "CHAOS_NET_SEED"              # wire-chaos schedule seed
CHAOS_NET_DROP_PCT = "CHAOS_NET_DROP_PCT"      # swallow a frame/request (%)
CHAOS_NET_RESET_PCT = "CHAOS_NET_RESET_PCT"    # connection reset (%)
CHAOS_NET_DELAY_MS = "CHAOS_NET_DELAY_MS"      # injected latency per frame
CHAOS_NET_TRUNCATE = "CHAOS_NET_TRUNCATE"      # truncate a frame/response (%)
CHAOS_NET_BLACKHOLE = "CHAOS_NET_BLACKHOLE"    # "a-b,..." dead rank pairs

_PREFIXES = ("HVD_TPU_", "HOROVOD_")


def get_env(name: str, default: Optional[str] = None) -> Optional[str]:
    """Read a knob, preferring HVD_TPU_* over HOROVOD_*."""
    for prefix in _PREFIXES:
        val = os.environ.get(prefix + name)
        if val is not None:
            return val
    return default


def get_bool(name: str, default: bool = False) -> bool:
    val = get_env(name)
    if val is None:
        return default
    return val.strip().lower() in ("1", "true", "yes", "on")


def get_int(name: str, default: int) -> int:
    val = get_env(name)
    if val is None:
        return default
    try:
        return int(val)
    except ValueError:
        return default


def get_float(name: str, default: float) -> float:
    val = get_env(name)
    if val is None:
        return default
    try:
        return float(val)
    except ValueError:
        return default


@dataclasses.dataclass
class Config:
    """Parsed runtime configuration.

    Defaults mirror the reference: 64 MB fusion buffer unless autotuning
    (operations.cc:448 sets 128 MB when tuning), 1 ms cycle time, response
    cache capacity 1024, stall warning at 60 s.
    """

    fusion_threshold_bytes: int = 64 * 1024 * 1024
    cycle_time_ms: float = 1.0
    cache_capacity: int = 1024
    timeline_filename: str = ""
    timeline_mark_cycles: bool = False
    autotune: bool = False
    autotune_log: str = ""
    # Reference autotune defaults (parameter_manager.h / launch.py flags).
    autotune_warmup_samples: int = 3
    autotune_steps_per_sample: int = 0   # 0 = time-windowed sampling
    autotune_bayes_opt_max_samples: int = 20
    autotune_gaussian_process_noise: float = 0.8
    # Closed-loop autotuning: the tuning memory is on by default but
    # only engages once a model fingerprint is announced (TpuState or
    # autotune.announce_model); gateway jobs ride the fleet store, the
    # local dir is the gateway-less fallback.  A drift whose suspect is
    # a tunable subsystem triggers a bounded re-tune of
    # autotune_retune_windows sample windows; the re-tuned config rolls
    # back to the last-known-good entry when its score lands more than
    # autotune_rollback_pct percent below the pre-drift baseline.
    autotune_memory: bool = True
    autotune_memory_dir: str = "./autotune_memory"
    autotune_retune: bool = True
    autotune_retune_windows: int = 6
    autotune_rollback_pct: float = 5.0
    stall_check_disable: bool = False
    stall_warning_time_seconds: float = 60.0
    stall_shutdown_time_seconds: float = 0.0
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    # Tri-state pins for the dispatch plane: None = the knob was not set
    # (the probe decides per payload), True/False = the operator
    # explicitly pinned the schedule — the probe is bypassed for that op
    # kind and the whole payload range uses the pinned choice.
    hierarchical_allreduce_pin: Optional[bool] = None
    hierarchical_allgather_pin: Optional[bool] = None
    # Topology probe: a few seeded payload sizes x {flat, hierarchical}
    # over the native collective path at init() (<1s at world <= 8; runs
    # only when the topology has a real hierarchy to choose, i.e.
    # 1 < local_size < world dividing evenly).
    schedule_probe: bool = True
    schedule_probe_seed: int = 0
    schedule_probe_reps: int = 2
    elastic: bool = False
    mesh_axes: str = ""
    compile_cache_dir: str = ""
    # Input pipeline: prefetch on, double buffering, no hard stall
    # ceiling (the warning still fires at stall_warning_time_seconds).
    data_prefetch: bool = True
    data_queue_depth: int = 2
    data_stall_timeout_seconds: float = 0.0
    # Wire compression: the default format for the eager plane (every
    # allreduce/reducescatter without an explicit ``compression=``) and
    # the negotiated device plane's response-stream stamp.  Quantized
    # formats scale per ``quant_block`` elements (ops/quantization.py).
    compression: str = "none"
    quant_block: int = 256
    # Backward-overlap bucketed gradient scheduler: the session default
    # for optimizers called without an explicit ``overlap=`` argument
    # (bit-parity with the barrier schedule, so an env default is safe),
    # and the bucket size used when overlap is on.  Setting the bytes
    # knob explicitly PINS the autotuner's bucket-size dimension.
    overlap: bool = False
    overlap_bucket_bytes: int = 8 * 1024 * 1024
    # ZeRO weight-update sharding stage (ZeroShardedOptimizer default)
    # and the stage-3 forward-prefetch schedule (docs/zero.md).
    zero_stage: int = 1
    zero_prefetch: bool = True
    # Opt-in: put the stage-3 parameter gather itself on the quantized
    # wire (ops/overlap.gather_in_forward, ops/gspmd).  Off by default —
    # a gather has no error-feedback channel, so its loss (one bounded
    # qdq round trip per step; the sharded master stays fp32) lands on
    # the forward.  docs/compression.md prices the trade.
    zero_quant_gather: bool = False
    # Metrics: registry always records locally; cross-rank aggregation
    # and the scrape endpoint are opt-in (both default off).
    metrics_sync_steps: int = 0
    metrics_port: int = 0
    # Host-sharded telemetry plane: tree sync off by default (small
    # worlds lose nothing to the flat allgather; the launcher exports
    # the knob fleet-wide so every rank agrees).  topk bounds per-host
    # raw outlier evidence; retain_files prunes rotated JSONL sinks on
    # long-lived fleet workers.
    metrics_tree: bool = False
    metrics_topk: int = 4
    metrics_tree_timeout_s: float = 10.0
    metrics_tree_grace_s: float = 2.0
    metrics_retain_files: int = 3
    # Performance observatory: step_end() closes a per-step attribution
    # record (compute / exposed comm / hidden comm / input / checkpoint /
    # host gap) and feeds the EWMA/CUSUM drift detector; both default on
    # (the per-step cost is a handful of cached metric reads — bench.py
    # --bench attribution pins it under the 1% bar).  peak_tflops grades
    # hvd_mfu_ratio: 0 = the chip's spec-sheet peak by device kind; set
    # it to a CALIBRATED ceiling instead (round-5 silicon measured 171
    # TFLOP/s steady matmul on the 197-peak v5e — docs/mfu_readiness.md).
    attribution: bool = True
    attribution_jsonl: str = ""
    peak_tflops: float = 0.0
    perf_drift: bool = True
    perf_drift_warmup: int = 30
    perf_drift_threshold: float = 8.0
    perf_drift_min_pct: float = 10.0
    perf_drift_cooldown: int = 50
    perf_drift_lookback_s: float = 120.0
    # Flight recorder: always-on ring buffer (cost is unmeasurable —
    # bench.py --bench flight_overhead pins it under 1%); the stall →
    # hang-report escalation runs wherever the native controller does.
    flight_disable: bool = False
    flight_capacity: int = 4096
    flight_dir: str = "."
    flight_port: int = 0
    flight_last_events: int = 20
    flight_escalate: bool = True
    # Peer-to-peer hot recovery: buddy replication of committed ZeRO
    # shards + peer-first elastic restore (disk stays the correlated-
    # failure fallback).  Async commit overlaps the disk write with the
    # next training steps (single-controller only — the commit barrier
    # of a multi-controller save is a collective that cannot run on a
    # background thread).  Streaming restore reads one leaf at a time
    # so restore's transient memory is O(largest leaf), not O(state).
    recovery: bool = True
    recovery_stride: int = 0   # 0 = auto: the local world size
    async_commit: bool = False
    ckpt_streaming: bool = False
    # Self-healing wire fabric: graded failure escalation on every
    # cross-host channel (native TCP ring: framing + acks + reconnect-
    # and-resume + ring renegotiation; HTTP planes: per-attempt deadlines
    # with bounded jittered retries).  The native defaults live in
    # net.cc NetResilience() and MUST match these.
    # Fleet service mode: the job gateway's port, durable-queue home,
    # scheduler cadence, per-tenant slot quota (0 = unlimited), and the
    # checkpoint-mediated preemption knobs (preemption on/off + how long
    # the scheduler waits for the victim's next commit before shrinking
    # anyway).  See docs/fleet.md.
    fleet_port: int = 28642
    fleet_dir: str = "./fleet_state"
    fleet_tick_s: float = 0.5
    fleet_quota_slots: int = 0
    fleet_preemption: bool = True
    fleet_preempt_grace_s: float = 30.0
    fleet_observe_push_s: float = 0.0
    fleet_observe_retain: int = 512
    # Serving plane: decode-slot geometry (slots × pages × page tokens
    # is the replica's whole KV budget), the request plane's bounded
    # admission queue, the checkpoint-watch cadence of the hot-swap
    # path, and the queue-depth/SLO autoscaler (off by default — a
    # replica only resizes itself when asked to).  See docs/serving.md.
    serving_port: int = 28643
    serving_slots: int = 8
    serving_page_tokens: int = 16
    serving_max_len: int = 0          # 0 = the model's seq_len
    serving_max_new_tokens: int = 64
    serving_queue_cap: int = 64
    serving_swap_poll_s: float = 2.0
    serving_autoscale: bool = False
    serving_target_queue: float = 4.0
    serving_slo_ttft_s: float = 0.0
    serving_scale_cooldown_s: float = 10.0
    # Production-scale serving: the radix prefix cache rides every
    # admission by default (it only ever SAVES prefill work); chunked
    # prefill, reservation aging, and speculation are opt-in; the
    # KV-migration wire int8-quantizes by default (~3.9x smaller,
    # block-scaled — set 0 for the bit-exact fp32 wire).
    serving_prefix_cache: bool = True
    serving_prefill_chunk: int = 0    # prompt tokens/iteration; 0 = all
    serving_aging_s: float = 0.0      # page-reservation aging; 0 = off
    serving_migrate_bits: int = 8     # 0 = fp32 wire; 8 | 4
    spec_k: int = 0                   # draft tokens/round; 0 = off
    # Request-scoped tracing + SLO budgets: a 1% default sample rate
    # keeps the span stream within the flight recorder's <1% overhead
    # bar; the budget window and burn threshold follow SRE convention
    # (burn rate 1.0 = exactly spending the error budget).
    trace_sample: float = 0.01        # sampled request fraction [0, 1]
    trace_seed: int = 0               # trace-id derivation seed
    slo_target: float = 0.99          # per-tenant attainment target
    slo_window_s: float = 300.0       # rolling error-budget window (s)
    slo_burn_threshold: float = 1.0   # burn rate that trips scale/shed
    # MoE / pipeline geometry: experts routed per token, dispatch-
    # buffer headroom over the even share, the optional block-scaled
    # quantized dispatch wire (0 = fp32; 8/4 ride ops/quantization.py),
    # and the pipeline schedule + microbatch count.
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_dispatch_bits: int = 0
    moe_dispatch_block: int = 256
    pp_schedule: str = "gpipe"
    pp_microbatches: int = 1
    net_resilience: bool = True
    net_probe_ms: float = 10000.0
    net_reconnect_s: float = 10.0
    net_op_deadline_s: float = 60.0
    net_http_retries: int = 3        # attempts per HTTP request
    net_http_backoff_ms: float = 50.0

    @classmethod
    def from_env(cls) -> "Config":
        cfg = cls()
        cfg.fusion_threshold_bytes = get_int(
            FUSION_THRESHOLD, cfg.fusion_threshold_bytes)
        cfg.cycle_time_ms = get_float(CYCLE_TIME, cfg.cycle_time_ms)
        cfg.cache_capacity = get_int(CACHE_CAPACITY, cfg.cache_capacity)
        cfg.timeline_filename = get_env(TIMELINE, "") or ""
        cfg.timeline_mark_cycles = get_bool(TIMELINE_MARK_CYCLES)
        cfg.autotune = get_bool(AUTOTUNE)
        cfg.autotune_log = get_env(AUTOTUNE_LOG, "") or ""
        cfg.autotune_warmup_samples = get_int(
            AUTOTUNE_WARMUP_SAMPLES, cfg.autotune_warmup_samples)
        cfg.autotune_steps_per_sample = get_int(
            AUTOTUNE_STEPS_PER_SAMPLE, cfg.autotune_steps_per_sample)
        cfg.autotune_bayes_opt_max_samples = get_int(
            AUTOTUNE_BAYES_OPT_MAX_SAMPLES,
            cfg.autotune_bayes_opt_max_samples)
        cfg.autotune_gaussian_process_noise = get_float(
            AUTOTUNE_GAUSSIAN_PROCESS_NOISE,
            cfg.autotune_gaussian_process_noise)
        cfg.autotune_memory = get_bool(AUTOTUNE_MEMORY, cfg.autotune_memory)
        cfg.autotune_memory_dir = get_env(
            AUTOTUNE_MEMORY_DIR, cfg.autotune_memory_dir) \
            or cfg.autotune_memory_dir
        cfg.autotune_retune = get_bool(AUTOTUNE_RETUNE, cfg.autotune_retune)
        cfg.autotune_retune_windows = max(1, get_int(
            AUTOTUNE_RETUNE_WINDOWS, cfg.autotune_retune_windows))
        cfg.autotune_rollback_pct = max(0.0, get_float(
            AUTOTUNE_ROLLBACK_PCT, cfg.autotune_rollback_pct))
        cfg.stall_check_disable = get_bool(STALL_CHECK_DISABLE)
        cfg.stall_warning_time_seconds = get_float(
            STALL_CHECK_TIME_SECONDS, cfg.stall_warning_time_seconds)
        cfg.stall_shutdown_time_seconds = get_float(
            STALL_SHUTDOWN_TIME_SECONDS, cfg.stall_shutdown_time_seconds)
        cfg.hierarchical_allreduce = get_bool(HIERARCHICAL_ALLREDUCE)
        cfg.hierarchical_allgather = get_bool(HIERARCHICAL_ALLGATHER)
        # Presence (not value) of the legacy knobs is what pins: an
        # unset knob means "let the probe decide per payload".
        cfg.hierarchical_allreduce_pin = (
            None if get_env(HIERARCHICAL_ALLREDUCE) is None
            else cfg.hierarchical_allreduce)
        cfg.hierarchical_allgather_pin = (
            None if get_env(HIERARCHICAL_ALLGATHER) is None
            else cfg.hierarchical_allgather)
        cfg.schedule_probe = get_bool(SCHEDULE_PROBE, cfg.schedule_probe)
        cfg.schedule_probe_seed = get_int(SCHEDULE_PROBE_SEED,
                                          cfg.schedule_probe_seed)
        cfg.schedule_probe_reps = max(
            1, get_int(SCHEDULE_PROBE_REPS, cfg.schedule_probe_reps))
        cfg.elastic = get_bool(ELASTIC)
        cfg.mesh_axes = get_env(MESH_AXES, "") or ""
        cfg.compile_cache_dir = get_env(COMPILE_CACHE_DIR, "") or ""
        cfg.data_prefetch = get_bool(DATA_PREFETCH, cfg.data_prefetch)
        cfg.data_queue_depth = max(
            1, get_int(DATA_QUEUE_DEPTH, cfg.data_queue_depth))
        cfg.data_stall_timeout_seconds = get_float(
            DATA_STALL_TIMEOUT_SECONDS, cfg.data_stall_timeout_seconds)
        comp = (get_env(COMPRESSION, cfg.compression) or "none")
        comp = comp.strip().lower()
        # A typo'd knob must not kill (or silently de-compress) a fleet:
        # normalize unknown names to none — by_name() does the same for
        # call-site strings — and keep the block even (int4 packs pairs).
        # (Name set mirrors ops/compression._BY_NAME; kept literal here
        # so config parsing never imports the jax-backed ops layer.)
        if comp not in ("none", "fp16", "bf16", "int8", "int4"):
            comp = "none"
        cfg.compression = comp
        cfg.quant_block = max(2, get_int(QUANT_BLOCK, cfg.quant_block))
        cfg.quant_block -= cfg.quant_block % 2
        cfg.overlap = get_bool(OVERLAP, cfg.overlap)
        # Floor of 1 KB: a zero/garbage bucket size would put every leaf
        # alone in a bucket — legal but never what anyone meant.
        cfg.overlap_bucket_bytes = max(
            1024, get_int(OVERLAP_BUCKET_BYTES, cfg.overlap_bucket_bytes))
        # Clamp to the defined stages: a typo'd knob must not silently
        # run unsharded (0) or invent a stage 4.
        cfg.zero_stage = min(3, max(1, get_int(ZERO_STAGE, cfg.zero_stage)))
        cfg.zero_prefetch = get_bool(ZERO_PREFETCH, cfg.zero_prefetch)
        cfg.zero_quant_gather = get_bool(ZERO_QUANT_GATHER,
                                         cfg.zero_quant_gather)
        cfg.metrics_sync_steps = max(
            0, get_int(METRICS_SYNC_STEPS, cfg.metrics_sync_steps))
        cfg.metrics_port = get_int(METRICS_PORT, cfg.metrics_port)
        cfg.metrics_tree = get_bool(METRICS_TREE, cfg.metrics_tree)
        # The other tree/retention knobs (METRICS_TOPK, the tree
        # timeouts, METRICS_RETAIN_FILES) are read at their use sites
        # with the dataclass defaults below — like the straggler knobs,
        # they are consumed by long-lived helpers, not by init(), so
        # parsing them into this snapshot would just be a second copy
        # of the clamp logic that nothing reads.
        cfg.attribution = get_bool(ATTRIBUTION, cfg.attribution)
        cfg.attribution_jsonl = get_env(
            ATTRIBUTION_JSONL, cfg.attribution_jsonl) or ""
        cfg.peak_tflops = max(0.0, get_float(PEAK_TFLOPS, cfg.peak_tflops))
        cfg.perf_drift = get_bool(PERF_DRIFT, cfg.perf_drift)
        cfg.perf_drift_warmup = max(
            1, get_int(PERF_DRIFT_WARMUP, cfg.perf_drift_warmup))
        cfg.perf_drift_threshold = max(0.5, get_float(
            PERF_DRIFT_THRESHOLD, cfg.perf_drift_threshold))
        cfg.perf_drift_min_pct = max(0.0, get_float(
            PERF_DRIFT_MIN_PCT, cfg.perf_drift_min_pct))
        cfg.perf_drift_cooldown = max(
            0, get_int(PERF_DRIFT_COOLDOWN, cfg.perf_drift_cooldown))
        cfg.perf_drift_lookback_s = max(1.0, get_float(
            PERF_DRIFT_LOOKBACK_S, cfg.perf_drift_lookback_s))
        cfg.flight_disable = get_bool(FLIGHT_DISABLE, cfg.flight_disable)
        cfg.flight_capacity = max(
            1, get_int(FLIGHT_CAPACITY, cfg.flight_capacity))
        cfg.flight_dir = get_env(FLIGHT_DIR, cfg.flight_dir) or "."
        cfg.flight_port = get_int(FLIGHT_PORT, cfg.flight_port)
        cfg.flight_last_events = max(
            1, get_int(FLIGHT_LAST_EVENTS, cfg.flight_last_events))
        cfg.flight_escalate = get_bool(FLIGHT_ESCALATE, cfg.flight_escalate)
        cfg.recovery = get_bool(RECOVERY, cfg.recovery)
        cfg.recovery_stride = max(
            0, get_int(RECOVERY_STRIDE, cfg.recovery_stride))
        cfg.async_commit = get_bool(ASYNC_COMMIT, cfg.async_commit)
        cfg.ckpt_streaming = get_bool(CKPT_STREAMING, cfg.ckpt_streaming)
        cfg.fleet_port = get_int(FLEET_PORT, cfg.fleet_port)
        cfg.fleet_dir = get_env(FLEET_DIR, cfg.fleet_dir) or cfg.fleet_dir
        cfg.fleet_tick_s = max(
            0.05, get_float(FLEET_TICK_S, cfg.fleet_tick_s))
        cfg.fleet_quota_slots = max(
            0, get_int(FLEET_QUOTA_SLOTS, cfg.fleet_quota_slots))
        cfg.fleet_preemption = get_bool(FLEET_PREEMPTION,
                                        cfg.fleet_preemption)
        cfg.fleet_preempt_grace_s = get_float(FLEET_PREEMPT_GRACE_S,
                                              cfg.fleet_preempt_grace_s)
        cfg.fleet_observe_push_s = max(0.0, get_float(
            FLEET_OBSERVE_PUSH_S, cfg.fleet_observe_push_s))
        cfg.fleet_observe_retain = max(1, get_int(
            FLEET_OBSERVE_RETAIN, cfg.fleet_observe_retain))
        cfg.serving_port = get_int(SERVING_PORT, cfg.serving_port)
        cfg.serving_slots = max(1, get_int(SERVING_SLOTS,
                                           cfg.serving_slots))
        cfg.serving_page_tokens = max(1, get_int(SERVING_PAGE_TOKENS,
                                                 cfg.serving_page_tokens))
        cfg.serving_max_len = max(0, get_int(SERVING_MAX_LEN,
                                             cfg.serving_max_len))
        cfg.serving_max_new_tokens = max(1, get_int(
            SERVING_MAX_NEW_TOKENS, cfg.serving_max_new_tokens))
        cfg.serving_queue_cap = max(1, get_int(SERVING_QUEUE_CAP,
                                               cfg.serving_queue_cap))
        cfg.serving_swap_poll_s = max(0.05, get_float(
            SERVING_SWAP_POLL_S, cfg.serving_swap_poll_s))
        cfg.serving_autoscale = get_bool(SERVING_AUTOSCALE,
                                         cfg.serving_autoscale)
        cfg.serving_target_queue = max(0.5, get_float(
            SERVING_TARGET_QUEUE, cfg.serving_target_queue))
        cfg.serving_slo_ttft_s = max(0.0, get_float(
            SERVING_SLO_TTFT_S, cfg.serving_slo_ttft_s))
        cfg.serving_scale_cooldown_s = max(0.0, get_float(
            SERVING_SCALE_COOLDOWN_S, cfg.serving_scale_cooldown_s))
        cfg.serving_prefix_cache = get_bool(SERVING_PREFIX_CACHE,
                                            cfg.serving_prefix_cache)
        cfg.serving_prefill_chunk = max(0, get_int(
            SERVING_PREFILL_CHUNK, cfg.serving_prefill_chunk))
        cfg.serving_aging_s = max(0.0, get_float(
            SERVING_AGING_S, cfg.serving_aging_s))
        mbits = get_int(SERVING_MIGRATE_BITS, cfg.serving_migrate_bits)
        cfg.serving_migrate_bits = mbits if mbits in (0, 4, 8) else 8
        cfg.spec_k = min(32, max(0, get_int(SPEC_K, cfg.spec_k)))
        cfg.trace_sample = min(1.0, max(0.0, get_float(
            TRACE_SAMPLE, cfg.trace_sample)))
        cfg.trace_seed = get_int(TRACE_SEED, cfg.trace_seed)
        cfg.slo_target = min(0.9999, max(0.5, get_float(
            SLO_TARGET, cfg.slo_target)))
        cfg.slo_window_s = max(1.0, get_float(
            SLO_WINDOW_S, cfg.slo_window_s))
        cfg.slo_burn_threshold = max(0.01, get_float(
            SLO_BURN_THRESHOLD, cfg.slo_burn_threshold))
        cfg.moe_top_k = max(1, get_int(MOE_TOP_K, cfg.moe_top_k))
        cfg.moe_capacity_factor = max(0.0, get_float(
            MOE_CAPACITY_FACTOR, cfg.moe_capacity_factor))
        bits = get_int(MOE_DISPATCH_BITS, cfg.moe_dispatch_bits)
        cfg.moe_dispatch_bits = bits if bits in (0, 4, 8) else 0
        cfg.moe_dispatch_block = max(1, get_int(
            MOE_DISPATCH_BLOCK, cfg.moe_dispatch_block))
        sched = (get_env(PP_SCHEDULE, cfg.pp_schedule) or
                 cfg.pp_schedule).strip().lower()
        cfg.pp_schedule = sched if sched in ("gpipe", "1f1b") \
            else cfg.pp_schedule
        cfg.pp_microbatches = max(1, get_int(
            PP_MICROBATCHES, cfg.pp_microbatches))
        cfg.net_resilience = get_bool(NET_RESILIENCE, cfg.net_resilience)
        cfg.net_probe_ms = get_float(NET_PROBE_MS, cfg.net_probe_ms)
        cfg.net_reconnect_s = get_float(NET_RECONNECT_S,
                                        cfg.net_reconnect_s)
        cfg.net_op_deadline_s = get_float(NET_OP_DEADLINE_S,
                                          cfg.net_op_deadline_s)
        cfg.net_http_retries = max(
            1, get_int(NET_HTTP_RETRIES, cfg.net_http_retries))
        cfg.net_http_backoff_ms = get_float(NET_HTTP_BACKOFF_MS,
                                            cfg.net_http_backoff_ms)
        if cfg.autotune and get_env(FUSION_THRESHOLD) is None:
            cfg.fusion_threshold_bytes = 128 * 1024 * 1024
        return cfg
