"""init / shutdown / topology queries.

Analog of the reference's ``HorovodBasics`` ctypes layer plus the C API it
wraps (horovod/common/basics.py:22-75 → operations.cc:703-915).  TPU-native
differences:

* There is no singleton background thread to spawn for the compiled path —
  XLA compiles collectives into the program. ``init()`` instead (a) resolves
  the chip/process topology, (b) builds the global device mesh, and (c)
  optionally attaches the native eager-path controller.
* Topology resolution honors the launcher env contract first
  (HOROVOD_RANK/SIZE/LOCAL_RANK/... — reference gloo_run.py:64-75) and falls
  back to JAX's own multi-controller topology.
"""

from __future__ import annotations

from typing import Optional, Sequence

from . import state as _state
from .config import Config, get_env as _cfg_get
from .exceptions import NotInitializedError
from .state import global_state, _env_int
from ..utils import logging as log


def init(mesh=None,
         axes: Optional[Sequence[str]] = None,
         comm=None,
         use_controller: Optional[bool] = None) -> None:
    """Initialize the runtime.

    Args:
      mesh: optional pre-built ``jax.sharding.Mesh``. When None a 1-D mesh
        named ``("data",)`` over all global devices is created (ICI-ordered via
        ``mesh_utils.create_device_mesh``).
      axes: when ``mesh`` is None, optional axis names for a multi-dim mesh
        parsed from HVD_TPU_MESH_AXES (e.g. "data:8,model:4").
      comm: ignored; accepted for API compatibility with ``hvd.init(comm)``.
      use_controller: force-enable/disable the native eager-path controller.
        Default: enabled iff the launcher exported a rendezvous address.
    """
    del comm
    if global_state.initialized:
        return

    global_state.config = Config.from_env()

    # --- persistent compilation cache -------------------------------------
    # HVD_TPU_COMPILE_CACHE_DIR points XLA's persistent cache at a durable
    # directory so re-runs (and elastic respawns) skip recompilation —
    # silicon spends its live minutes executing instead of compiling.
    # Setting the config does NOT initialize the accelerator backend, so
    # it is safe before the launcher-worker topology resolution below.
    if global_state.config.compile_cache_dir:
        import jax
        jax.config.update("jax_compilation_cache_dir",
                          global_state.config.compile_cache_dir)

    # --- topology ---------------------------------------------------------
    # Launcher-spawned workers MUST NOT touch the JAX backend here: N
    # workers initializing the accelerator platform on one host contend for
    # the same chip(s) and block forever (the reference's init never touches
    # a device either — gloo_run workers get topology purely from env,
    # gloo_run.py:64-75).  JAX is consulted only in the single-process /
    # jax.distributed fallback, and the mesh is built lazily on first use.

    # Elastic workers fetch their (re-)assignment from the rendezvous KV
    # each init — the world may have changed since the last round.
    elastic_assignment = None
    import os as _os
    if _os.environ.get("HVD_TPU_ELASTIC_SLOT"):
        from ..runner.worker import fetch_assignment
        elastic_assignment = fetch_assignment(
            min_round=global_state.elastic_round + 1)
        global_state.elastic_round = elastic_assignment["round"]
        global_state.rank = elastic_assignment["rank"]
        global_state.size = elastic_assignment["size"]
        global_state.local_rank = elastic_assignment["local_rank"]
        global_state.local_size = elastic_assignment["local_size"]
        global_state.cross_rank = elastic_assignment["cross_rank"]
        global_state.cross_size = elastic_assignment["cross_size"]
        # Elastic device plane: the driver publishes a fresh jax
        # coordinator per round; every worker (survivor or respawn)
        # rebuilds its jax.distributed world to the round's topology so
        # HBM-resident eager tensors keep riding the negotiated device
        # plane across failures (SURVEY §7.3 "Elastic on TPU").
        jax_addr = elastic_assignment.get("jax_coord_addr")
        if jax_addr:
            from ..runner.bootstrap import rebuild_jax_world
            rebuild_jax_world(jax_addr, global_state.size,
                              global_state.rank)
        else:
            # The round declares no jax world (e.g. the host set stopped
            # being all-local): a survivor must not keep a stale one —
            # its process count is wrong and its error poller dies with
            # old peers.  No-op when no world exists.
            from ..runner.bootstrap import teardown_jax_world
            teardown_jax_world()

    env_rank = _env_int("RANK")
    env_size = _env_int("SIZE")
    if elastic_assignment is not None:
        # One process per slot: process topology == slot topology.
        global_state.process_rank = global_state.rank
        global_state.process_count = global_state.size
    elif env_rank is not None and env_size is not None:
        # Launcher-provided chip topology (one launched process per slot).
        global_state.rank = env_rank
        global_state.size = env_size
        global_state.local_rank = _env_int("LOCAL_RANK") or 0
        global_state.local_size = _env_int("LOCAL_SIZE") or 1
        global_state.cross_rank = _env_int("CROSS_RANK") or 0
        global_state.cross_size = _env_int("CROSS_SIZE") or 1
        global_state.process_rank = env_rank
        global_state.process_count = env_size
        # If a spanning jax.distributed world already exists, its process
        # ids must match the env-provided ranks: eager device-plane
        # collectives place shards in JAX process-index order and read
        # them back in rank order (broadcast root, gather concatenation),
        # so a permuted world silently misroutes data.  Fail fast here —
        # every rank passes through init(), making this the one
        # synchronous point where the misconfiguration is visible before
        # any collective can hang aligned peers.  The distributed state is
        # read directly (NOT jax.process_index(), which initializes the
        # XLA backend — forbidden here per the note above).
        try:
            from jax._src import distributed as _jd
            _ds = _jd.global_state
            jax_pid = _ds.process_id if _ds.client is not None else None
            jax_np = _ds.num_processes
        except Exception:
            jax_pid = jax_np = None
        if jax_pid is not None and jax_np == env_size \
                and jax_pid != env_rank:
            raise RuntimeError(
                f"horovod_tpu.init(): jax.distributed process_id "
                f"{jax_pid} != rank {env_rank} from the environment. "
                "Initialize jax.distributed with process_id == rank "
                "(the launcher does this), or unset the rank env vars "
                "to derive ranks from JAX.")
    else:
        # Derive from JAX: rank = chip-rank of this process's first device.
        import jax
        global_state.process_rank = jax.process_index()
        global_state.process_count = jax.process_count()
        local_devices = jax.local_device_count()
        total_devices = jax.device_count()
        global_state.rank = global_state.process_rank * local_devices
        global_state.size = total_devices
        global_state.local_rank = 0
        global_state.local_size = local_devices
        global_state.cross_rank = global_state.process_rank
        global_state.cross_size = global_state.process_count

    # --- mesh (lazy: built on first mesh() access) ------------------------
    if mesh is not None:
        global_state.mesh = mesh
    else:
        global_state.mesh = None
        global_state.mesh_axes_hint = tuple(axes) if axes else None

    # --- eager-path controller -------------------------------------------
    if use_controller is None:
        use_controller = bool(_cfg_get("CONTROLLER_ADDR")) or \
            elastic_assignment is not None
    if use_controller:
        from ..native import runtime as native_runtime
        if elastic_assignment is not None:
            global_state.controller = native_runtime.attach(
                rank=elastic_assignment["rank"],
                size=elastic_assignment["size"],
                coord_addr=elastic_assignment["controller_addr"])
        else:
            global_state.controller = native_runtime.attach()

    # --- per-payload collective schedule dispatch -------------------------
    # Topology probe + dispatch-table install (ops/dispatch.py): a short
    # seeded probe (only on topologies where hierarchical schedules can
    # actually run — 1 < local_size < world dividing evenly) builds the
    # per-(op kind, payload bucket) table every subsequent collective is
    # stamped from.  Probe collectives ride the controller like any
    # other op, so a transport failure surfaces exactly like one
    # (elastic jobs: HorovodInternalError -> reset); the decision inputs
    # are env-derived and rank-consistent, so every rank enqueues the
    # identical probe sequence.
    if global_state.controller is not None:
        from ..ops import dispatch as _dispatch
        _dispatch.bootstrap(global_state.controller, global_state.config,
                            global_state.local_size)

    # --- metrics ----------------------------------------------------------
    # Topology gauges + (opt-in) the Prometheus scrape endpoint.  serve()
    # is idempotent, so elastic re-inits keep the one server alive across
    # rounds instead of rebinding the port; the daemon thread dies with
    # the process (shutdown() deliberately leaves it serving — a reset
    # mid-round must not blind the scraper).
    from ..metrics.registry import registry as _metrics_registry
    _mreg = _metrics_registry()
    _mreg.counter("hvd_init_total", "Runtime initializations").inc()
    _mreg.gauge("hvd_rank", "Chip-level rank of this process").set(
        global_state.rank)
    _mreg.gauge("hvd_size", "Total chips in the communicator").set(
        global_state.size)
    _mreg.gauge("hvd_elastic_round", "Current elastic rendezvous round "
                "(-1 outside elastic jobs)").set(
        global_state.elastic_round)
    if global_state.config.metrics_port:
        # Rank-gate the env-configured port: with several worker
        # processes per host (LOCAL_SIZE > 1) only local rank 0 can own
        # it.  Telemetry must never kill training — a bind failure
        # (port held by a dying predecessor after an elastic respawn,
        # another job, a stale server) degrades to a warning.
        if global_state.local_rank == 0:
            try:
                from ..metrics import serve as _metrics_serve
                _metrics_serve(port=global_state.config.metrics_port)
            except OSError as e:
                log.warning(
                    "metrics: cannot serve on port %d (%s); continuing "
                    "without a scrape endpoint",
                    global_state.config.metrics_port, e)

    # --- flight recorder / hang diagnosis ---------------------------------
    # The recorder itself is always armed (ring-buffer appends are
    # unmeasurable — bench.py --bench flight_overhead); what init() adds
    # is the dump/triage plumbing: identity for dumps, the SIGUSR1
    # trigger, the coordinator clock-offset estimate (piggybacked on the
    # rendezvous channel every worker already polls), the per-rank debug
    # endpoint + its KV-published address, and — on the coordinator rank
    # of launcher-run jobs — the stall→hang-report escalation watchdog.
    if not global_state.config.flight_disable:
        from .. import debug as _debug
        _debug.flight.set_identity(rank=global_state.rank,
                                   world=global_state.size)
        _debug.flight.record("init", None, rank=global_state.rank,
                             size=global_state.size,
                             round=global_state.elastic_round,
                             wire=global_state.config.compression)
        _debug.install_signal_handler()
        _rdv = _os.environ.get("HVD_TPU_RENDEZVOUS_ADDR")
        if _rdv:
            try:
                _debug.estimate_clock_offset(_rdv, samples=3)
            except Exception as e:  # noqa: BLE001 — telemetry never kills
                log.debug("flight: clock-offset estimate failed: %r", e)
        if global_state.controller is not None:
            if _rdv:
                try:
                    _debug.serve_and_publish(
                        rank=global_state.controller.rank(), rdv_addr=_rdv,
                        port=global_state.config.flight_port)
                except OSError as e:
                    log.warning("flight: cannot serve debug endpoint "
                                "(%s); continuing without one", e)
            if global_state.config.flight_escalate and \
                    global_state.controller.rank() == 0:
                _debug.start_stall_watchdog(
                    global_state.controller,
                    report_dir=global_state.config.flight_dir,
                    rdv_addr=_rdv)

    # --- peer-to-peer hot recovery ----------------------------------------
    # Multi-process jobs with a rendezvous KV publish the replica
    # endpoint so buddies can push committed shards across processes
    # (horovod_tpu/recovery/transport.py).  Single-controller jobs need
    # none of this — every rank's store is this process's store.  Like
    # the debug endpoint, serving is idempotent across elastic rounds
    # and a bind failure degrades (the peer tier falls back to disk).
    if global_state.config.recovery and global_state.controller is not None:
        _rdv = _os.environ.get("HVD_TPU_RENDEZVOUS_ADDR")
        if _rdv:
            try:
                from .. import recovery as _recovery
                _recovery.transport.serve_and_publish(
                    rank=global_state.controller.rank(), rdv_addr=_rdv)
            except OSError as e:
                log.warning("recovery: cannot serve replica endpoint "
                            "(%s); peer tier degraded to disk", e)

    global_state.elastic_enabled = global_state.config.elastic
    global_state.initialized = True

    # --- host-sharded telemetry plane -------------------------------------
    # Tree mode: local rank 0 hosts the per-host observer (the host's
    # one serving slot, same gate as the metrics port above) that merges
    # its ranks' snapshots and runs the O(hosts) digest exchange.  Like
    # every telemetry server, a failure to start degrades to a warning —
    # the sync path then falls back to local-only digests, named.
    if global_state.config.metrics_tree and global_state.local_rank == 0:
        try:
            from ..metrics.observer import start_host_observer
            start_host_observer()
        except Exception as e:  # noqa: BLE001 — telemetry never kills
            log.warning("metrics tree: cannot start host observer (%r); "
                        "sync degrades to local-only digests", e)

    log.debug(
        "initialized: rank=%d size=%d local=%d/%d cross=%d/%d mesh=%s",
        global_state.rank, global_state.size, global_state.local_rank,
        global_state.local_size, global_state.cross_rank,
        global_state.cross_size, global_state.mesh or "<lazy>")


def _build_default_mesh(axes: Optional[Sequence[str]] = None):
    import jax
    import numpy as np
    from jax.experimental import mesh_utils

    spec = global_state.config.mesh_axes
    if axes is None and spec:
        # "data:8,model:4" → axes=("data","model"), shape=(8,4)
        names, dims = [], []
        for part in spec.split(","):
            name, _, dim = part.partition(":")
            names.append(name.strip())
            dims.append(int(dim))
        devices = mesh_utils.create_device_mesh(tuple(dims))
        return jax.sharding.Mesh(devices, tuple(names))
    n = jax.device_count()
    try:
        devices = mesh_utils.create_device_mesh((n,))
    except Exception:
        devices = np.array(jax.devices())
    return jax.sharding.Mesh(devices, (_state.DATA_AXIS,))


def shutdown() -> None:
    """Tear down the runtime (reference: horovod_shutdown, operations.cc)."""
    # Stop the hang watchdog BEFORE the controller it polls goes away
    # (its thread is named hvd-tpu-*, so a leak fails the test suite's
    # stray-thread check).  The debug HTTP endpoint, like the metrics
    # server, deliberately stays up across elastic resets.
    try:
        from .. import debug as _debug
        _debug.stop_stall_watchdog()
        _debug.flight.record("shutdown", None)
    except Exception:  # noqa: BLE001 - best-effort teardown
        pass
    # The host observer's exchange thread is also hvd-tpu-* named, and
    # unlike the metrics server its identity (cross_rank, local ranks)
    # is world-shaped: a re-init after an elastic renumber must build a
    # fresh one, not inherit a stale rank map that names departed ranks
    # "missing" forever.
    try:
        from ..metrics.observer import stop_host_observer
        stop_host_observer()
    except Exception:  # noqa: BLE001 - best-effort teardown
        pass
    try:
        # Drop the dispatch-table mirror: a fresh init() re-probes (the
        # topology may have changed), and annotation must not quote a
        # dead world's table in between.
        from ..ops import dispatch as _dispatch
        _dispatch.reset()
    except Exception:  # noqa: BLE001 - best-effort teardown
        pass
    if global_state.controller is not None:
        try:
            global_state.controller.shutdown()
        except Exception:  # noqa: BLE001 - best-effort teardown
            pass
    global_state.reset()


def is_initialized() -> bool:
    return global_state.initialized


def _check_init():
    if not global_state.initialized:
        raise NotInitializedError()


def rank() -> int:
    """Global (chip-level) rank of this process's first device."""
    _check_init()
    return global_state.rank


def size() -> int:
    """Total number of chips across all processes."""
    _check_init()
    return global_state.size


def local_rank() -> int:
    _check_init()
    return global_state.local_rank


def local_size() -> int:
    _check_init()
    return global_state.local_size


def cross_rank() -> int:
    """Rank among hosts (one per node) — reference common.h:119-123."""
    _check_init()
    return global_state.cross_rank


def cross_size() -> int:
    _check_init()
    return global_state.cross_size


def process_rank() -> int:
    _check_init()
    return global_state.process_rank


def process_count() -> int:
    _check_init()
    return global_state.process_count


def mesh():
    """The global device mesh.  Built lazily on first access so eager-only
    workers (launcher-spawned, native TCP data plane) never initialize the
    JAX backend at all."""
    _check_init()
    if global_state.mesh is None:
        global_state.mesh = _build_default_mesh(global_state.mesh_axes_hint)
    return global_state.mesh


def is_homogeneous() -> bool:
    """True when every node has the same number of chips."""
    _check_init()
    return global_state.size % max(global_state.cross_size, 1) == 0


def mpi_threads_supported() -> bool:
    """API-compat shim; there is no MPI in the TPU runtime."""
    return False


# Build-capability queries (reference common/util.py:137-220): scripts
# branch on these to pick a controller/ops stack.  On TPU the answers are
# static: the TCP controller is the gloo-analog control plane; there is no
# MPI/NCCL/CUDA/ROCm/oneCCL/DDL in the loop.

def mpi_built(verbose: bool = False) -> bool:
    return False


def gloo_built(verbose: bool = False) -> bool:
    return True  # the TCP controller + rendezvous fills the Gloo role


def nccl_built(verbose: bool = False) -> bool:
    return False


def ddl_built(verbose: bool = False) -> bool:
    return False


def ccl_built(verbose: bool = False) -> bool:
    return False


def cuda_built(verbose: bool = False) -> bool:
    return False


def rocm_built(verbose: bool = False) -> bool:
    return False


def start_timeline(filename: str, mark_cycles: bool = False) -> None:
    """Start Chrome-trace timeline recording at runtime (reference
    horovod_start_timeline, operations.cc:740-769).  Requires the native
    controller (launcher-run jobs); a warning is logged otherwise."""
    del mark_cycles  # cycle markers controlled by env knob at init
    _check_init()
    if global_state.controller is None:
        log.warning("start_timeline: no native runtime attached; timeline "
                    "is recorded only for launcher-run jobs")
        return
    global_state.controller.start_timeline(filename)


def stop_timeline() -> None:
    _check_init()
    if global_state.controller is not None:
        global_state.controller.stop_timeline()
