"""Async handle management for the eager path.

Analog of the reference's Torch ``HandleManager`` (torch/handle_manager.cc:60,
torch/mpi_ops.py:843-882): ``*_async`` ops return an integer handle;
``poll(handle)`` checks completion; ``synchronize(handle)`` blocks and returns
the result.  On TPU the eager dispatch is already asynchronous (JAX dispatches
to the device and returns futures), so a handle wraps either a dispatched
``jax.Array`` or a native-controller request.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional


class Handle:
    __slots__ = ("_result", "_error", "_done", "_poll_fn", "_wait_fn")

    def __init__(self,
                 result: Any = None,
                 poll_fn: Optional[Callable[[], bool]] = None,
                 wait_fn: Optional[Callable[[], Any]] = None):
        self._result = result
        self._error: Optional[BaseException] = None
        self._done = poll_fn is None
        self._poll_fn = poll_fn
        self._wait_fn = wait_fn

    def poll(self) -> bool:
        if self._done:
            return True
        if self._poll_fn is not None and self._poll_fn():
            self._done = True
        return self._done

    def wait(self) -> Any:
        if not self._done and self._wait_fn is not None:
            self._result = self._wait_fn()
            self._done = True
        if self._error is not None:
            raise self._error
        return self._result


class HandleManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._next = 0
        self._handles: Dict[int, Handle] = {}

    def allocate(self, handle: Handle) -> int:
        with self._lock:
            hid = self._next
            self._next += 1
            self._handles[hid] = handle
            return hid

    def get(self, hid: int) -> Handle:
        with self._lock:
            if hid not in self._handles:
                raise ValueError(f"unknown handle {hid}")
            return self._handles[hid]

    def poll(self, hid: int) -> bool:
        return self.get(hid).poll()

    def synchronize(self, hid: int) -> Any:
        handle = self.get(hid)
        result = handle.wait()
        with self._lock:
            self._handles.pop(hid, None)
        return result


handle_manager = HandleManager()
