"""Async handle management for the eager path.

Analog of the reference's Torch ``HandleManager`` (torch/handle_manager.cc:60,
torch/mpi_ops.py:843-882): ``*_async`` ops return an integer handle;
``poll(handle)`` checks completion; ``synchronize(handle)`` blocks and returns
the result.  On TPU the eager dispatch is already asynchronous (JAX dispatches
to the device and returns futures), so a handle wraps either a dispatched
``jax.Array`` or a native-controller request.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional


class Handle:
    """One in-flight eager op.  ``poll_fn`` answers "has the op completed?"
    without finalizing it; ``wait_fn`` blocks, finalizes (releases native
    resources) and returns the result — it runs exactly once even if poll
    already reported completion."""

    __slots__ = ("_result", "_error", "_finalized", "_poll_fn", "_wait_fn")

    def __init__(self,
                 result: Any = None,
                 poll_fn: Optional[Callable[[], bool]] = None,
                 wait_fn: Optional[Callable[[], Any]] = None):
        self._result = result
        self._error: Optional[BaseException] = None
        self._finalized = wait_fn is None
        self._poll_fn = poll_fn
        self._wait_fn = wait_fn

    def poll(self) -> bool:
        if self._finalized:
            return True
        if self._poll_fn is None:
            return True
        return bool(self._poll_fn())

    def wait(self) -> Any:
        if not self._finalized:
            try:
                self._result = self._wait_fn()
            except Exception as e:  # surfaced on this and later waits
                self._error = e
            # KeyboardInterrupt/SystemExit propagate un-finalized: the op is
            # still pending and a later wait must retry (and release native
            # resources) rather than replay a stale interrupt.
            self._finalized = True
        if self._error is not None:
            raise self._error
        return self._result


class HandleManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._next = 0
        self._handles: Dict[int, Handle] = {}

    def allocate(self, handle: Handle) -> int:
        with self._lock:
            hid = self._next
            self._next += 1
            self._handles[hid] = handle
            return hid

    def get(self, hid: int) -> Handle:
        with self._lock:
            if hid not in self._handles:
                raise ValueError(f"unknown handle {hid}")
            return self._handles[hid]

    def poll(self, hid: int) -> bool:
        return self.get(hid).poll()

    def synchronize(self, hid: int) -> Any:
        handle = self.get(hid)
        result = handle.wait()
        with self._lock:
            self._handles.pop(hid, None)
        return result


handle_manager = HandleManager()
