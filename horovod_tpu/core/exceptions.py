"""Framework exceptions.

Capability parity with the reference's ``horovod/common/exceptions.py:18-32``:
``HorovodInternalError`` aborts the current training iteration and triggers an
elastic restore; ``HostsUpdatedInterrupt`` re-runs rendezvous without restoring
state (the host set changed but no worker failed).
"""


class HorovodTpuError(Exception):
    """Base class for all framework errors."""


class HorovodInternalError(HorovodTpuError):
    """Internal error requiring a reset of the collective runtime.

    Raised when a collective fails mid-flight (peer died, slice became
    unhealthy).  Under ``horovod_tpu.elastic.run`` this triggers
    ``state.restore()`` followed by re-rendezvous.
    """


class HostsUpdatedInterrupt(HorovodTpuError):
    """The set of hosts changed; re-rendezvous without restoring state.

    ``skip_sync`` mirrors the reference: when True the rejoining workers do
    not need a state broadcast because no state was lost.
    """

    def __init__(self, skip_sync: bool = False):
        super().__init__()
        self.skip_sync = skip_sync


class NotInitializedError(HorovodTpuError):
    """An API that requires ``init()`` was called before initialization."""

    def __init__(self, what: str = "operation"):
        super().__init__(
            f"{what} called before horovod_tpu.init(); call init() first")


class DuplicateNameError(HorovodTpuError):
    """Two in-flight eager collectives used the same tensor name.

    Mirrors the reference's DUPLICATE_NAME_ERROR (common.h:169).
    """


class WorkersAvailableException(HorovodTpuError):
    """Elastic driver: new workers are available for rendezvous."""


class DataStallError(HorovodTpuError):
    """The input pipeline produced no batch within the stall window.

    The data-plane analog of the coordinator's stall inspector
    (stall_inspector.h): a warning is logged after the warning window,
    and when ``HVD_TPU_DATA_STALL_TIMEOUT_SECONDS`` > 0 the consumer
    raises this error instead of blocking forever on a wedged producer
    (dead filesystem, livelocked source, crashed loader thread).
    """
