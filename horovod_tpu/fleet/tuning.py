"""Fleet-level tuning memory — the autotune analog of the response
cache (arXiv:1802.05799).

The GP autotuner used to re-derive the same best config from a cold
start on every submission of the same job.  This module persists tuned
configs keyed by::

    (model fingerprint, world size, topology signature)

* **model fingerprint** — the PR 1 checkpoint engine's leaf-spec sha256
  (``checkpoint.manifest.spec_fingerprint``): world-size-invariant,
  changes exactly when the model/optimizer structure does.
* **world size** — the process count the config was tuned at (fusion
  thresholds and hierarchical crossovers are world-dependent).
* **topology signature** — local world size plus the probe-built
  dispatch table's content hash (ops/dispatch.py), so a config tuned on
  one schedule regime never seeds a different one.

Two stores speak the same records:

* :class:`LocalTuningStore` — one JSON file with the fleet queue's
  durability discipline (tmp + fsync + rename + dir-fsync), the
  gateway-less fallback (``HVD_TPU_AUTOTUNE_MEMORY_DIR``).
* :class:`GatewayTuningStore` — ``GET/PUT /fleet/tuning/<key>`` on the
  fleet gateway (HMAC-gated like every fleet endpoint, riding the
  hvd.net retry ladder), so resubmitted fleet jobs start warm from a
  durable store the gateway owns.

Every record carries a schema version AND the GP dimension tuple it was
tuned over (``ParameterManager.gp_dims()``): the knob space has grown
twice already (PR 5 added the compression dim, PR 11 rebased the
hierarchical booleans to crossover shifts) and a mismatched record is
refused with a pointed :class:`TuningSchemaMismatch` instead of
silently mis-seeding the tuner.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import threading
import time
from typing import Optional

SCHEMA_VERSION = 1
_STORE_FILE = "tuned_configs.json"


class TuningSchemaMismatch(RuntimeError):
    """A stored tuned-config record does not match this job's knob
    space (schema version or GP dimension tuple) — warm-starting from
    it would seed coordinates the tuner would misread."""


# ---------------------------------------------------------------------------
# keys
# ---------------------------------------------------------------------------

def model_fingerprint(tree) -> str:
    """Leaf-spec sha256 of a params/optimizer pytree — the checkpoint
    engine's run fingerprint (path, dtype, logical size per leaf;
    world-size-invariant, see checkpoint/manifest.py)."""
    import jax
    from ..checkpoint import manifest as M
    leaves_with_path, _ = jax.tree_util.tree_flatten_with_path(tree)
    specs = []
    for path, leaf in leaves_with_path:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        size = int(math.prod(shape)) if shape else 1
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        specs.append(M.LeafSpec(
            path=jax.tree_util.keystr(path), kind=M.REPLICATED,
            shape=list(shape), dtype=dtype, true_size=size))
    return M.spec_fingerprint(specs)


def topology_signature() -> str:
    """The comm-regime half of the key: local world size plus the
    active dispatch table's content hash.  World size is NOT folded in
    here — it is its own key component."""
    parts = []
    from ..core.config import get_env
    local = get_env("LOCAL_SIZE")  # honors both knob prefixes
    if local:
        parts.append(f"l{local}")
    try:
        from ..ops import dispatch as _dispatch
        table = _dispatch.active_table()
    except Exception:  # noqa: BLE001 — dispatch plane optional
        table = None
    if table is not None:
        h = hashlib.sha256(table.encode().tobytes()).hexdigest()[:12]
        parts.append(f"t{h}")
    return ".".join(parts) or "flat"


def config_key(fingerprint: str, world: int, topo: str) -> str:
    """The store key for one (model, world, topology) triple."""
    h = hashlib.sha256(
        f"{fingerprint}|{int(world)}|{topo}".encode()).hexdigest()
    return h[:32]


# ---------------------------------------------------------------------------
# records
# ---------------------------------------------------------------------------

def make_record(config: dict, score: Optional[float] = None,
                dims=()) -> dict:
    """One tuned-config record: the named config, the score it froze
    at, and the knob space it is only valid over."""
    return {
        "schema": SCHEMA_VERSION,
        "dims": list(dims),
        "config": dict(config),
        "score": None if score is None else float(score),
        "updated_at": time.time(),
    }


def check_record(record, dims=None) -> dict:
    """Validate a record against this job's knob space; raises
    :class:`TuningSchemaMismatch` with a pointed message on any
    mismatch.  Returns the record."""
    if not isinstance(record, dict) or \
            not isinstance(record.get("config"), dict):
        raise TuningSchemaMismatch(
            "stored tuned-config record is not a config record "
            f"(got {type(record).__name__})")
    schema = record.get("schema")
    if schema != SCHEMA_VERSION:
        raise TuningSchemaMismatch(
            f"stored tuned-config record has schema {schema!r}, this "
            f"build speaks schema {SCHEMA_VERSION} — refusing to "
            "warm-start from it; delete the record or re-tune cold")
    if dims is not None:
        stored = list(record.get("dims") or [])
        expected = list(dims)
        if stored != expected:
            raise TuningSchemaMismatch(
                f"stored tuned config was tuned over GP dims {stored}, "
                f"but this job's knob space is {expected} — the tuner's "
                "dimensionality changed between runs (it grew in PR 5 "
                "and PR 11; dispatch-probe mode also rebases the "
                "hierarchical dims to shifts), and seeding mismatched "
                "coordinates would silently mis-tune.  Refusing to "
                "warm-start; delete the record or re-tune cold")
    return record


# ---------------------------------------------------------------------------
# stores
# ---------------------------------------------------------------------------

class LocalTuningStore:
    """Durable JSON store: ``<dir>/tuned_configs.json`` holding
    ``{key: record}``, written with the fleet queue's tmp + fsync +
    rename + dir-fsync discipline so a torn write is never loadable."""

    def __init__(self, directory: str):
        self._dir = directory
        self._path = os.path.join(directory, _STORE_FILE)
        self._lock = threading.Lock()

    def _load(self) -> dict:
        try:
            with open(self._path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return {}
        configs = data.get("configs")
        return configs if isinstance(configs, dict) else {}

    def _flush(self, configs: dict) -> None:
        os.makedirs(self._dir, exist_ok=True)
        payload = json.dumps({"version": 1, "configs": configs},
                             indent=0).encode()
        tmp = f"{self._path}.tmp.{os.getpid()}"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
        try:
            os.write(fd, payload)
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, self._path)
        try:
            dfd = os.open(self._dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass  # platform without directory fsync

    def get(self, key: str, dims=None) -> Optional[dict]:
        """The stored record for ``key`` (None on miss).  With ``dims``
        the record is validated against that knob space — a mismatch
        raises :class:`TuningSchemaMismatch` rather than returning a
        record that would mis-seed the tuner."""
        with self._lock:
            rec = self._load().get(key)
        if rec is None:
            return None
        if dims is not None:
            check_record(rec, dims)
        return rec

    def put(self, key: str, record: dict) -> dict:
        record = check_record(dict(record))
        with self._lock:
            configs = self._load()
            configs[str(key)] = record
            self._flush(configs)
        return record

    def keys(self):
        with self._lock:
            return sorted(self._load().keys())


class GatewayTuningStore:
    """The same surface over the fleet gateway's HMAC-gated
    ``/fleet/tuning/<key>`` endpoints (requests ride the hvd.net
    rung-1 retry ladder via fleet/client.py)."""

    def __init__(self, addr: Optional[str] = None,
                 secret: Optional[str] = None):
        from .client import default_addr
        self.addr = default_addr(addr)
        self._secret = secret

    def get(self, key: str, dims=None) -> Optional[dict]:
        from .client import _request
        rec = _request("GET", self.addr, f"tuning/{key}",
                       secret=self._secret, none_on_404=True)
        if rec is None:
            return None
        if dims is not None:
            check_record(rec, dims)
        return rec

    def put(self, key: str, record: dict) -> dict:
        from .client import _request
        record = check_record(dict(record))
        return _request("PUT", self.addr, f"tuning/{key}",
                        json.dumps(record).encode(), secret=self._secret)


def resolve_store(addr: Optional[str] = None):
    """The store this job should use: the fleet gateway when one is
    addressed (explicitly or via ``HVD_TPU_FLEET_ADDR`` — fleet-
    submitted jobs carry it), else the local-file fallback under
    ``HVD_TPU_AUTOTUNE_MEMORY_DIR``."""
    from ..core.config import Config, get_env
    addr = addr or get_env("FLEET_ADDR")
    if addr:
        return GatewayTuningStore(addr)
    d = get_env("AUTOTUNE_MEMORY_DIR", Config.autotune_memory_dir) \
        or Config.autotune_memory_dir
    return LocalTuningStore(d)
