"""Durable job queue — the gateway's single source of truth.

One JSON file (``<fleet_dir>/jobs.json``) holds every job record plus
the submission sequence counter.  Writes follow the checkpoint engine's
durability discipline in miniature: serialize to a tmp file, fsync,
rename over the live file, fsync the directory — a torn write is never
loadable, and a gateway restart reloads exactly the committed queue.
Jobs that were RUNNING/PREEMPTING when the previous gateway died are
requeued on load (their workers died with the gateway's drivers; the
entrypoints resume from their checkpoints when rescheduled).
"""

from __future__ import annotations

import json
import os
import threading
import uuid
from typing import Callable, Dict, List, Optional

from .job import (JobRecord, JobSpec, PREEMPTED, PREEMPTING, QUEUED,
                  RUNNING)

_QUEUE_FILE = "jobs.json"
_FORMAT_VERSION = 1


class DurableJobQueue:
    def __init__(self, fleet_dir: str):
        self._dir = fleet_dir
        self._path = os.path.join(fleet_dir, _QUEUE_FILE)
        self._lock = threading.RLock()
        self._jobs: Dict[str, JobRecord] = {}
        self._seq = 0
        os.makedirs(fleet_dir, exist_ok=True)
        self._load()

    # -- durability --------------------------------------------------------

    def _load(self):
        if not os.path.exists(self._path):
            return  # fresh gateway
        try:
            with open(self._path) as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            # An unreadable-but-present queue must not be silently
            # overwritten by the next flush: sideline it for forensics
            # and say so loudly, then start empty.
            import time
            from ..utils import logging as log
            quarantine = f"{self._path}.unreadable-{int(time.time())}"
            try:
                os.replace(self._path, quarantine)
            except OSError:
                quarantine = "<could not sideline>"
            log.warning(
                "fleet queue %s is unreadable (%r); sidelined to %s and "
                "starting with an empty queue", self._path, e, quarantine)
            return
        self._seq = int(data.get("seq", 0))
        for d in data.get("jobs", []):
            try:
                rec = JobRecord.from_dict(d)
            except (KeyError, TypeError):
                continue  # one corrupt record must not drop the queue
            if rec.state in (RUNNING, PREEMPTING, PREEMPTED):
                # The previous gateway died with this job's driver; its
                # workers are gone.  Requeue — the entrypoint restores
                # from its committed checkpoint when rescheduled.
                rec.state = QUEUED
                rec.np = 0
                rec.resumes += 1
                rec.reason = "requeued after gateway restart"
            self._jobs[rec.id] = rec

    def _flush_locked(self):
        payload = json.dumps({
            "version": _FORMAT_VERSION,
            "seq": self._seq,
            "jobs": [r.to_dict() for r in self._jobs.values()],
        }, indent=0).encode()
        tmp = self._path + ".tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
        try:
            os.write(fd, payload)
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, self._path)
        try:
            dfd = os.open(self._dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass  # platform without directory fsync

    # -- queue API ---------------------------------------------------------

    def submit(self, spec: JobSpec, state: str = QUEUED,
               reason: str = "") -> JobRecord:
        import time
        with self._lock:
            self._seq += 1
            rec = JobRecord(id=uuid.uuid4().hex[:12], spec=spec,
                            state=state, submit_seq=self._seq,
                            submitted_at=time.time(), reason=reason)
            self._jobs[rec.id] = rec
            self._flush_locked()
            return rec

    def get(self, job_id: str) -> Optional[JobRecord]:
        with self._lock:
            return self._jobs.get(job_id)

    def list(self) -> List[JobRecord]:
        with self._lock:
            return sorted(self._jobs.values(), key=lambda r: r.submit_seq)

    def update(self, job_id: str,
               mutate: Callable[[JobRecord], None]) -> Optional[JobRecord]:
        """Apply ``mutate`` to the record under the lock and persist."""
        with self._lock:
            rec = self._jobs.get(job_id)
            if rec is None:
                return None
            mutate(rec)
            self._flush_locked()
            return rec

    def remove(self, job_id: str) -> bool:
        with self._lock:
            if self._jobs.pop(job_id, None) is None:
                return False
            self._flush_locked()
            return True
