"""``python -m horovod_tpu.fleet.submit`` — the tenant-side CLI.

Submits a job spec to a running fleet gateway (the alternative surface
is ``horovodrun --submit``, runner/launch.py)::

    python -m horovod_tpu.fleet.submit --gateway host:28642 \\
        --min-np 2 --max-np 8 --priority 5 --tenant research \\
        -- python train.py --model bert

Prints the job id and state; ``--wait`` polls to a terminal state and
exits 0 only on DONE.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from . import client
from .job import DONE, JobSpec


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.fleet.submit",
        description="Submit a job to the fleet gateway.")
    p.add_argument("--gateway", default=None,
                   help="gateway address host:port (default: "
                        "HVD_TPU_FLEET_ADDR, then 127.0.0.1:"
                        "<HVD_TPU_FLEET_PORT>)")
    p.add_argument("--secret", default=None,
                   help="fleet HMAC secret (default: HVD_TPU_FLEET_SECRET)")
    p.add_argument("-np", "--num-proc", type=int, default=None,
                   help="exact width (sets min-np and max-np together)")
    p.add_argument("--min-np", type=int, default=None)
    p.add_argument("--max-np", type=int, default=None)
    p.add_argument("--priority", type=int, default=0,
                   help="higher preempts lower")
    p.add_argument("--tenant", default="default")
    p.add_argument("--name", default="")
    p.add_argument("--checkpoint-dir", default="",
                   help="where the job commits state (resume-from on "
                        "preemption)")
    p.add_argument("--max-queue-s", type=float, default=0.0,
                   help="queue-wait SLO target in seconds (dashboard + "
                        "equal-priority ordering hint)")
    p.add_argument("--kind", default="batch",
                   choices=("batch", "service"),
                   help="'service' marks a long-lived job that never "
                        "completes (a serving replica — docs/serving.md)")
    p.add_argument("--env", action="append", default=[],
                   metavar="KEY=VALUE", help="worker env (repeatable)")
    p.add_argument("--wait", action="store_true",
                   help="block until the job reaches a terminal state")
    p.add_argument("--wait-timeout", type=float, default=3600.0)
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="worker command (e.g. python train.py)")
    args = p.parse_args(argv)
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if not args.command:
        p.error("no worker command given")
    return args


def build_spec(args: argparse.Namespace) -> JobSpec:
    env = {}
    for kv in args.env:
        key, sep, value = kv.partition("=")
        if not sep:
            raise SystemExit(f"--env expects KEY=VALUE, got {kv!r}")
        env[key] = value
    min_np = args.min_np if args.min_np is not None else \
        (args.num_proc or 1)
    max_np = args.max_np if args.max_np is not None else args.num_proc
    return JobSpec(command=list(args.command), min_np=min_np,
                   max_np=max_np, priority=args.priority,
                   tenant=args.tenant, name=args.name, env=env,
                   checkpoint_dir=args.checkpoint_dir,
                   max_queue_s=args.max_queue_s, kind=args.kind)


def main(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    spec = build_spec(args)
    rec = client.submit_job(spec, addr=args.gateway, secret=args.secret)
    print(f"job {rec.id}: {rec.state}"
          + (f" ({rec.reason})" if rec.reason else ""))
    if rec.state != "queued":
        return 0 if rec.state == DONE else 1
    if not args.wait:
        return 0
    rec = client.wait_job(rec.id, addr=args.gateway, secret=args.secret,
                          timeout=args.wait_timeout)
    print(f"job {rec.id}: {rec.state}"
          + (f" ({rec.reason})" if rec.reason else ""))
    return 0 if rec.state == DONE else 1


if __name__ == "__main__":
    sys.exit(main())
