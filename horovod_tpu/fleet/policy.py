"""Scheduling policy — pure, deterministic, golden-testable.

One function, :func:`plan`, maps the fleet's current view (job states,
healthy capacity, quotas) to a list of decisions.  No I/O, no clocks, no
threads: the scheduler executes decisions; this module only chooses
them.  Determinism matters — two gateway restarts over the same queue
must schedule identically.

Policy, in order:

* **Admission** — a queued job whose ``min_np`` exceeds the *healthy*
  capacity (total slots minus health-hint exclusions) is denied: the
  gateway never promises capacity the straggler/health plane says is
  sick.
* **Priority** — queued jobs are considered highest priority first.
* **Fair share** — among equal priority, the tenant with the fewest
  running slots goes first; ties break on the SLO hint (tightest
  ``max_queue_s`` first) then submission order.
* **Quota** — a per-tenant concurrent-slot ceiling; a job that would
  exceed it waits (counted, never silently) rather than being denied.
* **Preemption** — when the head job cannot fit, lower-priority running
  jobs are shrunk toward their ``min_np`` (newest first), and suspended
  outright only when shrinking cannot free enough.  Preemption
  decisions are commit-gated by the scheduler (the checkpoint-mediated
  part); the freed slots go to the preemptor on a later tick, and the
  plan stops there so no lower-priority queued job can steal them.
* **Grow** — leftover healthy capacity is handed to running jobs below
  their ``max_np``, highest priority first (how a shrunk victim resumes
  its full width once the preemptor finishes).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

# Decision tuples (kind first; see plan()):
#   ("deny",       job_id, reason)
#   ("quota_wait", job_id, tenant)
#   ("start",      job_id, np)
#   ("grow",       job_id, np)           # raise a running job to np
#   ("shrink",     victim_id, np, for_job_id)
#   ("stop",       victim_id, for_job_id)
Decision = Tuple


@dataclasses.dataclass
class JobView:
    """The policy-relevant projection of a JobRecord."""

    id: str
    tenant: str
    priority: int
    min_np: int
    max_np: Optional[int]
    submit_seq: int
    state: str                 # "queued" | "running" | "preempting"
    np: int = 0                # slots currently held (running/preempting)
    max_queue_s: float = 0.0   # SLO hint; 0 = no target


_INF = float("inf")


def plan(views: List[JobView], healthy_slots: int,
         quota_slots: int = 0, preemption: bool = True) -> List[Decision]:
    decisions: List[Decision] = []
    running = [v for v in views if v.state in ("running", "preempting")]
    tenant_used = {}
    for v in running:
        tenant_used[v.tenant] = tenant_used.get(v.tenant, 0) + v.np
    free = healthy_slots - sum(v.np for v in running)

    def quota_room(tenant: str) -> float:
        if quota_slots <= 0:
            return _INF
        return quota_slots - tenant_used.get(tenant, 0)

    queued = sorted(
        (v for v in views if v.state == "queued"),
        key=lambda v: (-v.priority, tenant_used.get(v.tenant, 0),
                       v.max_queue_s if v.max_queue_s > 0 else _INF,
                       v.submit_seq))
    for v in queued:
        if v.min_np > healthy_slots:
            decisions.append((
                "deny", v.id,
                f"healthy capacity {healthy_slots} < min_np {v.min_np} "
                "(health hints exclude part of the fleet)"
                if healthy_slots > 0 else
                f"healthy capacity 0 < min_np {v.min_np} "
                "(health hints exclude the whole fleet)"))
            continue
        if quota_room(v.tenant) < v.min_np:
            decisions.append(("quota_wait", v.id, v.tenant))
            continue
        if free >= v.min_np:
            np = int(min(v.max_np if v.max_np is not None else free,
                         free, quota_room(v.tenant)))
            decisions.append(("start", v.id, np))
            free -= np
            tenant_used[v.tenant] = tenant_used.get(v.tenant, 0) + np
            continue
        if not preemption:
            continue
        # Preemption: reclaim (min_np - free) slots from strictly lower
        # priority running jobs — shrink newest victims toward their
        # min_np first, suspend outright only if shrinking cannot cover.
        victims = sorted(
            (r for r in running
             if r.state == "running" and r.priority < v.priority),
            key=lambda r: (r.priority, -r.submit_seq))
        need = v.min_np - free
        shrinks = {}   # victim_id -> new np
        stops = []
        for victim in victims:
            if need <= 0:
                break
            reclaim = victim.np - victim.min_np
            if reclaim <= 0:
                continue
            take = min(reclaim, need)
            shrinks[victim.id] = victim.np - take
            need -= take
        if need > 0:
            for victim in victims:
                if need <= 0:
                    break
                freed = (victim.np - shrinks.pop(victim.id)
                         if victim.id in shrinks else 0)
                stops.append(victim.id)
                need -= victim.np - freed
        if need > 0:
            continue  # even full preemption cannot seat it; keep waiting
        for vid, np in shrinks.items():
            decisions.append(("shrink", vid, np, v.id))
        for vid in stops:
            decisions.append(("stop", vid, v.id))
        # The freed slots are promised to v (it starts once they free);
        # planning further queued jobs against them would hand them to a
        # lower-priority job first.
        return decisions
    # Grow: leftover healthy capacity to running jobs below max_np.
    if free > 0:
        for v in sorted((r for r in running if r.state == "running"),
                        key=lambda r: (-r.priority, r.submit_seq)):
            if free <= 0:
                break
            ceiling = min(v.max_np if v.max_np is not None else _INF,
                          v.np + free, v.np + quota_room(v.tenant))
            if ceiling > v.np:
                give = int(ceiling) - v.np
                decisions.append(("grow", v.id, v.np + give))
                free -= give
                tenant_used[v.tenant] = \
                    tenant_used.get(v.tenant, 0) + give
    return decisions
