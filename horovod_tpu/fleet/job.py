"""Job specs and records — the unit of work the fleet gateway schedules.

A *spec* is what a tenant submits (entrypoint, resource envelope,
priority, SLO hints); a *record* is the gateway's durable bookkeeping
around it (state machine, timestamps, preemption counters).  Both are
plain-dict-serializable so the queue file and the HTTP wire share one
format.

State machine::

    QUEUED ──start──▶ RUNNING ──exit 0──▶ DONE
      ▲                │  │
      │                │  └──exit ≠0──▶ FAILED
      └──requeue── PREEMPTED ◀──preempt()──┘   (RUNNING may also pass
                                                through PREEMPTING while
                                                the scheduler waits for
                                                the victim's commit)
    QUEUED ──admission──▶ DENIED     QUEUED/RUNNING ──DELETE──▶ CANCELLED
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

# Job states.
QUEUED = "queued"
RUNNING = "running"
PREEMPTING = "preempting"   # running, commit-gated shrink/stop pending
PREEMPTED = "preempted"     # suspended; requeued for resume
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"
DENIED = "denied"

ACTIVE_STATES = (QUEUED, RUNNING, PREEMPTING, PREEMPTED)
TERMINAL_STATES = (DONE, FAILED, CANCELLED, DENIED)


@dataclasses.dataclass
class JobSpec:
    """What a tenant submits.  ``command`` is the worker argv (each rank
    runs it, exactly like a ``horovodrun`` command); ``min_np`` is the
    floor below which the job cannot run, ``max_np`` the width it can
    use when the fleet has room (None = as much as offered).  Higher
    ``priority`` preempts lower.  ``max_queue_s`` is an SLO hint: the
    queue-wait target the dashboards grade this tenant against (the
    scheduler also uses it to order equal-priority submissions —
    tightest target first)."""

    command: List[str]
    min_np: int = 1
    max_np: Optional[int] = None
    priority: int = 0
    tenant: str = "default"
    name: str = ""
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    checkpoint_dir: str = ""
    max_queue_s: float = 0.0
    # "batch" jobs run to completion; "service" jobs (serving replicas,
    # docs/serving.md) never complete — the scheduler seats them like
    # any running job (shrinkable toward min_np by preemption, grown
    # back when capacity frees), and only a DELETE or a preemption
    # suspend ever ends one.
    kind: str = "batch"

    def __post_init__(self):
        # Coerce the numeric fields at the boundary (JSON clients send
        # "5" as easily as 5): every internal consumer — the policy's
        # sort keys, capacity comparisons — may then assume real
        # numbers.  Uncoercible values raise ValueError/TypeError here,
        # which the HTTP handler maps to a 400 instead of a queued
        # record that wedges the scheduler's sort on every tick.
        self.min_np = int(self.min_np)
        self.max_np = None if self.max_np is None else int(self.max_np)
        self.priority = int(self.priority)
        self.max_queue_s = float(self.max_queue_s)

    def validate(self) -> Optional[str]:
        """None when launchable, else a pointed refusal reason."""
        if not self.command or not all(
                isinstance(c, str) for c in self.command):
            return "command must be a non-empty list of strings"
        if self.min_np < 1:
            return "min_np must be >= 1"
        if self.max_np is not None and self.max_np < self.min_np:
            return "max_np must be >= min_np"
        if not self.tenant:
            return "tenant must be non-empty"
        if not isinstance(self.env, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in self.env.items()):
            return "env must be a {str: str} mapping"
        if self.kind not in ("batch", "service"):
            return f"kind must be 'batch' or 'service', got {self.kind!r}"
        return None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "JobSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclasses.dataclass
class JobRecord:
    """The gateway's durable view of one submission."""

    id: str
    spec: JobSpec
    state: str = QUEUED
    submit_seq: int = 0          # FIFO tie-break, monotonic per gateway
    submitted_at: float = 0.0
    started_at: float = 0.0      # last (re)start
    first_started_at: float = 0.0
    finished_at: float = 0.0
    np: int = 0                  # slots currently assigned
    exit_code: Optional[int] = None
    preemptions: int = 0         # times shrunk or suspended for a peer
    resumes: int = 0             # times rescheduled after a suspension
    reason: str = ""             # denial / failure / preemption detail
    queue_wait_s: float = 0.0    # submit → first start (the SLO metric)
    # Commit generation the last preemption acted on (the victim's
    # restored step) — the checkpoint-mediated guarantee, queryable.
    preempt_generation: Optional[int] = None

    def queue_wait(self, now: Optional[float] = None) -> float:
        if self.first_started_at:
            return self.first_started_at - self.submitted_at
        return (now or time.time()) - self.submitted_at

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["spec"] = self.spec.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "JobRecord":
        d = dict(d)
        spec = JobSpec.from_dict(d.pop("spec"))
        known = {f.name for f in dataclasses.fields(cls)} - {"spec"}
        return cls(spec=spec, **{k: v for k, v in d.items() if k in known})
