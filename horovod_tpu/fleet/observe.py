"""The fleet timeline — bounded-retention time series at the gateway.

Until now every fleet-scale question ("what was job J's MFU over the
last hour?", "did step time drift after the resize?") needed a rank-0
JSONL on some worker's disk.  This module gives the gateway memory:
per-host observers (``metrics/observer.py``) push their host digests on
the ``HVD_TPU_FLEET_OBSERVE_PUSH_S`` cadence, the gateway merges pushes
belonging to the same sync round (the digest algebra is closed — a
partial round is still a valid, named-partial sample) and retains a
bounded ring of derived samples per job:

    step-time p50/p95/mean/max · fleet MFU min/mean · wall-component
    shares · reporting hosts/ranks · outlier ranks · missing evidence

Queryable over the gateway's HTTP plane (``fleet/gateway.py``)::

    POST /fleet/observe/<job>    ingest one host digest  (HMAC-gated)
    GET  /fleet/observe/<job>    the job's retained series (HMAC-gated)
    GET  /fleet/observe          jobs with series (HMAC-gated)
    GET  /fleet/metrics          fleet-wide Prometheus exposition of the
                                 latest sample per job (unsigned, like
                                 every scrape endpoint in this stack)

Retention is ``HVD_TPU_FLEET_OBSERVE_RETAIN`` samples per job (default
512) — a ring, not a database: old samples fall off, the memory bound
is samples x jobs, and a gateway restart starts empty (series are
telemetry, not state; the durable queue stays the only thing the
gateway persists).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from ..metrics import digest as _digest


def _slo_summary(d: dict) -> Optional[dict]:
    """Per-job SLO rollup from the digest's merged gauge map
    (``hvd_slo_*{tenant=...}`` — serving/slo.py): the worst burn rate
    and thinnest remaining budget across every tenant on every
    reporting replica, plus which tenants are burning (>= 1.0).  None
    for jobs that serve no SLO-tracked traffic (training jobs)."""
    gauges = d.get("gauges") or {}
    burn: Dict[str, float] = {}
    budget: Dict[str, float] = {}
    for key, v in gauges.items():
        for prefix, dst in (("hvd_slo_burn_rate{", burn),
                            ("hvd_slo_budget_remaining{", budget)):
            if key.startswith(prefix):
                tenant = key[len(prefix):-1]
                tenant = tenant.partition("tenant=")[2] or tenant
                last = float(v[2])   # gauges merge as [min, max, last]
                # Merge rule across replicas: worst case wins.
                if dst is burn:
                    dst[tenant] = max(dst.get(tenant, 0.0), last)
                else:
                    dst[tenant] = min(dst.get(tenant, 1.0), last)
    if not burn and not budget:
        return None
    return {
        "burn_max": max(burn.values()) if burn else 0.0,
        "budget_min": min(budget.values()) if budget else 1.0,
        "tenants": len(set(burn) | set(budget)),
        "burning": sorted(t for t, b in burn.items() if b >= 1.0),
    }


def _sample_from_digest(d: dict, ts: float) -> dict:
    """One retained timeline sample, derived (not stored raw — digests
    carry full scalar maps; the ring keeps only the series shape)."""
    steps = _digest.digest_step_quantiles(d)
    mfu = _digest.digest_mfu(d)
    window = d.get("window") or {}
    n = int(window.get("step_count", 0))
    sample = {
        "ts": ts,
        "round": int(d.get("round", -1)),
        "step": int(d.get("step", 0)),
        "hosts": len(d.get("hosts") or []),
        "ranks": int(d.get("ranks", 0)),
        "failed_hosts": list(d.get("failed_hosts") or []),
        "missing_ranks": list(d.get("missing") or []),
        "step_time_mean": (float(window.get("step_time_sum", 0.0)) / n)
        if n else None,
        "step_p50": steps["p50"] if steps else None,
        "step_p95": steps["p95"] if steps else None,
        "step_max": steps["max"] if steps else None,
        "mfu_min": mfu["min"] if mfu else None,
        "mfu_mean": mfu["mean"] if mfu else None,
        "shares": _digest.digest_shares(d),
        "outlier_ranks": [int(s.get("rank", -1))
                          for s in d.get("outliers") or []],
        "slo": _slo_summary(d),
    }
    return sample


class FleetSeriesStore:
    """Bounded per-job ring of timeline samples, fed by digest pushes.

    Pushes carrying the same ``round`` merge (the closed digest
    algebra) until a newer round arrives, which SEALS the previous one
    into a sample — hosts push independently, and a sample should
    reflect every host that reported for its round, not just the first
    pusher.  The open round is visible in queries too (marked
    ``open``), so a dashboard never lags a full round behind.
    """

    def __init__(self, retain: Optional[int] = None):
        from ..core.config import Config, get_int
        if retain is None:
            retain = get_int("FLEET_OBSERVE_RETAIN",
                             Config.fleet_observe_retain)
        self.retain = max(int(retain), 1)
        self._lock = threading.Lock()
        self._series: Dict[str, deque] = {}
        self._open: Dict[str, "OrderedDict[int, dict]"] = {}
        self._sealed: Dict[str, int] = {}   # job -> highest sealed round
        self._ingests = 0
        self._late_drops = 0

    # -- write side --------------------------------------------------------

    def ingest(self, job: str, host_digest: dict,
               now: Optional[float] = None) -> None:
        if not isinstance(host_digest, dict) or \
                int(host_digest.get("v", 0)) != _digest.DIGEST_VERSION:
            raise ValueError("not a digest (or an unknown digest "
                             "version)")
        # Shape-check BEFORE storing: a field-poor digest (buggy or
        # future client) accepted into an open round would poison it —
        # every later legitimate same-round push hits the merge's
        # KeyError instead of a 400, and the round's sample is lost.
        if not isinstance(host_digest.get("window"), dict) or \
                not isinstance(host_digest.get("outliers", []), list):
            raise ValueError("digest missing required fields "
                             "(window/outliers)")
        try:
            _sample_from_digest(host_digest, 0.0)
        except (KeyError, TypeError, AttributeError) as e:
            raise ValueError(f"malformed digest field: {e!r}") from None
        ts = time.time() if now is None else float(now)
        r = int(host_digest.get("round", -1))
        with self._lock:
            self._ingests += 1
            open_rounds = self._open.setdefault(job, OrderedDict())
            sealed = self._sealed.get(job)
            if sealed is not None and r <= sealed and r not in open_rounds:
                if sealed - r <= 2:
                    # A straggling host's push for a recently-sealed
                    # round: dropping it (bounded by the push cadence)
                    # beats appending a duplicate, out-of-order,
                    # unmerged sample behind the sealed one.
                    self._late_drops += 1
                    return
                # A round far BELOW the sealed high-water mark is not a
                # straggler — the job's round clock restarted (elastic
                # reset, job resubmission).  Start a fresh epoch.
                for old in sorted(open_rounds):
                    self._seal_locked(job, old, open_rounds.pop(old))
                self._sealed[job] = r - 1
            if r in open_rounds:
                try:
                    open_rounds[r]["digest"] = _digest.merge_digests(
                        open_rounds[r]["digest"], host_digest)
                except (KeyError, TypeError) as e:
                    raise ValueError(
                        f"digest does not merge: {e!r}") from None
                open_rounds[r]["ts"] = ts
            else:
                open_rounds[r] = {"digest": dict(host_digest), "ts": ts}
            # Seal every open round older than the newest: its pushers
            # have moved on (merging a straggler into a sealed *sample*
            # would reorder history — a late push to a sealed round is
            # dropped, bounded by the push cadence).  This also caps
            # open rounds per job at exactly one.
            newest = max(open_rounds)
            for old in [k for k in open_rounds if k < newest]:
                entry = open_rounds.pop(old)
                self._seal_locked(job, old, entry)

    def _seal_locked(self, job: str, round_idx: int, entry: dict) -> None:
        ring = self._series.setdefault(job, deque(maxlen=self.retain))
        ring.append(_sample_from_digest(entry["digest"], entry["ts"]))
        prev = self._sealed.get(job)
        self._sealed[job] = round_idx if prev is None \
            else max(prev, round_idx)

    # -- read side ---------------------------------------------------------

    def jobs(self) -> List[str]:
        with self._lock:
            return sorted(set(self._series) | set(self._open))

    def series(self, job: str, since: float = 0.0) -> List[dict]:
        """The job's samples oldest-first (sealed rounds plus the open
        one, marked)."""
        with self._lock:
            out = [dict(s) for s in self._series.get(job, ())
                   if s["ts"] >= since]
            for r, entry in (self._open.get(job) or {}).items():
                if entry["ts"] >= since:
                    s = _sample_from_digest(entry["digest"], entry["ts"])
                    s["open"] = True
                    out.append(s)
        return out

    def latest(self, job: str) -> Optional[dict]:
        """The newest sample (the open round when one exists, else the
        last sealed) — O(1) per job, NOT a series() copy: the unsigned
        /fleet/metrics exposition calls this per job per scrape."""
        with self._lock:
            open_rounds = self._open.get(job)
            if open_rounds:
                r = max(open_rounds)
                s = _sample_from_digest(open_rounds[r]["digest"],
                                        open_rounds[r]["ts"])
                s["open"] = True
                return s
            ring = self._series.get(job)
            return dict(ring[-1]) if ring else None

    def stats(self) -> dict:
        with self._lock:
            return {"jobs": len(set(self._series) | set(self._open)),
                    "ingests": self._ingests,
                    "late_drops": self._late_drops,
                    "samples": sum(len(v) for v in self._series.values()),
                    "retain": self.retain}

    # -- exposition --------------------------------------------------------

    def render_prometheus(self) -> str:
        """Fleet-wide text exposition: the latest sample per job as
        ``hvd_fleet_job_*{job=...}`` gauges — what a fleet dashboard
        scrapes off the gateway instead of 125 worker hosts."""
        gauges = (
            ("hvd_fleet_job_step_time_mean_seconds", "step_time_mean",
             "Mean step time in the job's last observed window"),
            ("hvd_fleet_job_step_time_p50_seconds", "step_p50",
             "Median per-step time (sketched)"),
            ("hvd_fleet_job_step_time_p95_seconds", "step_p95",
             "95th-percentile per-step time (sketched)"),
            ("hvd_fleet_job_mfu_min", "mfu_min",
             "Lowest per-rank MFU in the job's last window"),
            ("hvd_fleet_job_mfu_mean", "mfu_mean",
             "Mean per-rank MFU in the job's last window"),
            ("hvd_fleet_job_ranks", "ranks",
             "Ranks that reported into the job's last window"),
        )
        # Tenant-supplied job ids go into label VALUES: escape them
        # (exporters.py's exposition rules) or one job id containing a
        # quote would malform the whole scrape for every job.
        from ..metrics.exporters import _escape_label
        lines: List[str] = []
        latest = {job: self.latest(job) for job in self.jobs()}
        for name, field, help_text in gauges:
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} gauge")
            for job in sorted(latest):
                s = latest[job]
                if s is None or s.get(field) is None:
                    continue
                lines.append(f'{name}{{job="{_escape_label(job)}"}} '
                             f'{float(s[field])!r}')
        slo_gauges = (
            ("hvd_fleet_job_slo_burn_max", "burn_max",
             "Worst per-tenant SLO burn rate across the job's serving "
             "replicas (1.0 = spending budget exactly at rate)"),
            ("hvd_fleet_job_slo_budget_min", "budget_min",
             "Thinnest per-tenant SLO error budget remaining across "
             "the job's serving replicas"),
        )
        for name, field, help_text in slo_gauges:
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} gauge")
            for job in sorted(latest):
                s = latest[job]
                slo = s.get("slo") if s else None
                if not slo or slo.get(field) is None:
                    continue
                lines.append(f'{name}{{job="{_escape_label(job)}"}} '
                             f'{float(slo[field])!r}')
        lines.append("# HELP hvd_fleet_job_component_share Wall-time "
                     "share by component in the job's last window")
        lines.append("# TYPE hvd_fleet_job_component_share gauge")
        for job in sorted(latest):
            s = latest[job]
            if s is None or not s.get("shares"):
                continue
            for comp in sorted(s["shares"]):
                lines.append(
                    'hvd_fleet_job_component_share'
                    f'{{job="{_escape_label(job)}",'
                    f'component="{comp}"}} {float(s["shares"][comp])!r}')
        return "\n".join(lines) + ("\n" if lines else "")
