"""The fleet gateway — an always-on, multi-tenant job submission plane.

Promoted from the rendezvous/metrics ``BackgroundHTTPServer`` scaffold
(``runner/rendezvous.py``): one HTTP server owns the fleet, tenants
submit jobs to it, and the :class:`.scheduler.Scheduler` multiplexes
them onto the device inventory.  Endpoints::

    GET    /fleet/healthz      liveness + identity (unsigned — this is
                               what ``horovodrun`` probes to print the
                               "fleet mode is active" error)
    GET    /fleet/status       capacity + job counts
    POST   /fleet/jobs         submit a JobSpec (JSON body)
    GET    /fleet/jobs         list job records
    GET    /fleet/jobs/<id>    one job record
    DELETE /fleet/jobs/<id>    cancel (queued or running)
    GET    /fleet/tuning/<key> stored tuned config (tuning memory)
    PUT    /fleet/tuning/<key> persist a tuned config record
    POST   /fleet/observe/<job> ingest one host digest (fleet timeline)
    GET    /fleet/observe/<job> the job's retained series [?since=ts]
    GET    /fleet/observe      jobs with series + store stats
    GET    /fleet/metrics      fleet-wide Prometheus exposition
                               (unsigned, like every scrape endpoint)

All job endpoints are HMAC-gated with the fleet secret
(``HVD_TPU_FLEET_SECRET``) under the rendezvous KV's signature scheme —
method + path + body, so a captured signature authorizes nothing else.
Admission control runs at submit time: a spec whose ``min_np`` exceeds
the *healthy* capacity (inventory minus health-hint exclusions) is
recorded DENIED with a pointed reason instead of queueing forever.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional

from ..runner.hosts import HostInfo
from ..runner.rendezvous import BackgroundHTTPServer, _signature
from .job import DENIED, PREEMPTED, QUEUED, RUNNING, JobSpec
from .queue import DurableJobQueue
from .scheduler import Scheduler

SERVICE_NAME = "horovod_tpu_fleet"


class _FleetHandler(BaseHTTPRequestHandler):
    server_version = "hvd_tpu_fleet"

    def log_message(self, fmt, *args):  # silence request logging
        pass

    # -- plumbing ----------------------------------------------------------

    def _key(self) -> Optional[str]:
        """The signature key: the path under /fleet/, query stripped
        (None = not ours).  Clients sign the bare key — a ``?since=``
        filter is a read refinement, not a distinct resource."""
        parts = self.path.partition("?")[0].strip("/").split("/")
        if not parts or parts[0] != "fleet":
            return None
        return "/".join(parts[1:])

    def _authorized(self, method: str, key: str, body: bytes = b"") -> bool:
        secret = self.server.gateway.secret  # type: ignore[attr-defined]
        if not secret:
            return True
        import hmac
        provided = self.headers.get("X-HVD-Signature", "")
        return hmac.compare_digest(
            provided, _signature(secret, method, "fleet", key, body))

    def _send(self, code: int, payload) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- verbs -------------------------------------------------------------

    def do_GET(self):
        gw = self.server.gateway  # type: ignore[attr-defined]
        key = self._key()
        if key is None:
            return self._send(404, {"error": "not found"})
        if key == "healthz":
            # Unsigned on purpose: liveness probes and the launcher's
            # gateway detection must work without the tenant secret.
            return self._send(200, {
                "service": SERVICE_NAME, "ok": True,
                "jobs": len(gw.store.list()),
            })
        if key == "metrics":
            # Fleet-wide Prometheus exposition of the timeline's latest
            # sample per job — unsigned like every scrape endpoint in
            # this stack (scrapers cannot sign; only aggregates leave).
            body = gw.observe.render_prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if not self._authorized("GET", key):
            return self._send(403, {"error": "bad or missing signature"})
        if key == "status":
            records = gw.store.list()
            return self._send(200, {
                "service": SERVICE_NAME,
                "healthy_slots": gw.scheduler.healthy_slots(),
                "total_slots": sum(
                    h.slots for h in gw.scheduler.fleet_hosts()),
                "queued": sum(1 for r in records
                              if r.state in (QUEUED, PREEMPTED)),
                "running": gw.scheduler.running_count(),
                # Long-lived inference replicas currently seated
                # (JobSpec kind="service" — docs/serving.md).
                "services": sum(1 for r in records
                                if r.spec.kind == "service"
                                and r.state == RUNNING),
            })
        if key == "jobs":
            return self._send(200, {
                "jobs": [r.to_dict() for r in gw.store.list()]})
        if key.startswith("jobs/"):
            rec = gw.store.get(key[len("jobs/"):])
            if rec is None:
                return self._send(404, {"error": "no such job"})
            return self._send(200, rec.to_dict())
        if key.startswith("tuning/"):
            # Tuning memory (fleet/tuning.py): the stored record is
            # served raw — schema/dims validation belongs to the
            # consumer, whose knob space the server cannot know.
            rec = gw.tuning.get(key[len("tuning/"):])
            if rec is None:
                return self._send(404, {"error": "no tuned config"})
            return self._send(200, rec)
        if key == "observe":
            return self._send(200, {"jobs": gw.observe.jobs(),
                                    "stats": gw.observe.stats()})
        if key.startswith("observe/"):
            # The fleet timeline (fleet/observe.py): per-job series
            # derived from pushed host digests — observability without
            # touching worker disks.
            job = key[len("observe/"):]
            since = 0.0
            q = self.path.partition("?")[2]
            for part in q.split("&"):
                if part.startswith("since="):
                    try:
                        since = float(part[6:])
                    except ValueError:
                        pass
            if job not in gw.observe.jobs():
                return self._send(404, {"error": "no series for job "
                                                 f"{job!r}"})
            # A known job with nothing newer than ?since= is an EMPTY
            # window, not a missing job — 404 here would make every
            # idle poll interval read as "series disappeared".
            rows = gw.observe.series(job, since=since)
            return self._send(200, {"job": job, "series": rows})
        return self._send(404, {"error": "not found"})

    def do_POST(self):
        gw = self.server.gateway  # type: ignore[attr-defined]
        key = self._key()
        if key is not None and key.startswith("observe/"):
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            if not self._authorized("POST", key, body):
                return self._send(403,
                                  {"error": "bad or missing signature"})
            try:
                gw.observe.ingest(key[len("observe/"):],
                                  json.loads(body.decode()))
            except (ValueError, TypeError) as e:
                return self._send(400, {"error": f"malformed digest: {e}"})
            return self._send(200, {"ok": True})
        if key != "jobs":
            return self._send(404, {"error": "not found"})
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._authorized("POST", key, body):
            return self._send(403, {"error": "bad or missing signature"})
        try:
            spec = JobSpec.from_dict(json.loads(body.decode()))
        except (ValueError, TypeError, KeyError) as e:
            return self._send(400, {"error": f"malformed job spec: {e}"})
        rec = gw.submit(spec)
        if isinstance(rec, str):  # validation refusal
            return self._send(400, {"error": rec})
        return self._send(200, rec.to_dict())

    def do_PUT(self):
        gw = self.server.gateway  # type: ignore[attr-defined]
        key = self._key()
        if key is None or not key.startswith("tuning/"):
            return self._send(404, {"error": "not found"})
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._authorized("PUT", key, body):
            return self._send(403, {"error": "bad or missing signature"})
        from .tuning import TuningSchemaMismatch
        try:
            rec = json.loads(body.decode())
            stored = gw.tuning.put(key[len("tuning/"):], rec)
        except (ValueError, TypeError, TuningSchemaMismatch) as e:
            return self._send(400, {"error": f"malformed tuned-config "
                                             f"record: {e}"})
        return self._send(200, stored)

    def do_DELETE(self):
        gw = self.server.gateway  # type: ignore[attr-defined]
        key = self._key()
        if key is None or not key.startswith("jobs/"):
            return self._send(404, {"error": "not found"})
        if not self._authorized("DELETE", key):
            return self._send(403, {"error": "bad or missing signature"})
        rec = gw.scheduler.cancel(key[len("jobs/"):])
        if rec is None:
            return self._send(404, {"error": "no such job"})
        return self._send(200, rec.to_dict())


class _FleetServer(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self, addr, gateway: "FleetGateway"):
        super().__init__(addr, _FleetHandler)
        self.gateway = gateway


class FleetGateway(BackgroundHTTPServer):
    """The composed service: durable queue + scheduler + HTTP plane.

    ``hosts`` is the fleet inventory — a static list or a callable
    (e.g. a discovery script wrapper) re-evaluated each tick.  Pass
    ``port=0`` for an ephemeral port (tests); the production default is
    ``HVD_TPU_FLEET_PORT``."""

    def __init__(self, hosts, port: Optional[int] = None,
                 host: str = "0.0.0.0",
                 fleet_dir: Optional[str] = None,
                 secret: Optional[str] = None,
                 runner_factory=None,
                 health_hook: Optional[Callable[[], List[str]]] = None,
                 quota_slots: Optional[int] = None,
                 preemption: Optional[bool] = None,
                 preempt_grace_s: Optional[float] = None,
                 tick_s: Optional[float] = None,
                 extra_env=None,
                 verbose: bool = False):
        from ..core.config import Config, get_env, get_int
        if port is None:
            port = get_int("FLEET_PORT", Config.fleet_port)
        if fleet_dir is None:
            fleet_dir = get_env("FLEET_DIR", Config.fleet_dir) \
                or Config.fleet_dir
        if secret is None:
            secret = get_env("FLEET_SECRET")
        self.secret = secret
        self.store = DurableJobQueue(fleet_dir)
        # Fleet-level tuning memory: tuned configs persist beside the
        # job queue with the same durability discipline, served at
        # GET/PUT /fleet/tuning/<key> so resubmitted jobs start warm.
        from .tuning import LocalTuningStore
        self.tuning = LocalTuningStore(fleet_dir)
        # The fleet timeline (fleet/observe.py): bounded per-job series
        # fed by host-digest pushes; telemetry, deliberately NOT
        # persisted with the queue's durability.
        from .observe import FleetSeriesStore
        self.observe = FleetSeriesStore()
        hosts_provider = hosts if callable(hosts) else (lambda: list(hosts))
        self.scheduler = Scheduler(
            self.store, hosts_provider, runner_factory=runner_factory,
            health_hook=health_hook, quota_slots=quota_slots,
            preemption=preemption, preempt_grace_s=preempt_grace_s,
            tick_s=tick_s, extra_env=extra_env, verbose=verbose)
        super().__init__(_FleetServer((host, port), self))
        self._submit_lock = threading.Lock()

    # -- service lifecycle -------------------------------------------------

    def serve(self) -> int:
        """Start the HTTP plane and the scheduler; returns the port."""
        port = self.start()
        self.scheduler.start()
        return port

    def close(self, cancel_jobs: bool = False) -> None:
        self.scheduler.stop(cancel_jobs=cancel_jobs)
        self.stop()

    # -- submission plane --------------------------------------------------

    def submit(self, spec: JobSpec):
        """Admission-checked submission.  Returns the JobRecord (state
        QUEUED or DENIED), or an error string for a malformed spec."""
        bad = spec.validate()
        if bad is not None:
            return bad
        with self._submit_lock:
            healthy = self.scheduler.healthy_slots()
            # Deny only against a capacity we have actually observed: a
            # hosts-provider glitch at startup reads as "unknown", and
            # an unknown fleet queues the job instead of refusing it.
            if spec.min_np > healthy and self.scheduler.inventory_seen:
                rec = self.store.submit(
                    spec, state=DENIED,
                    reason=(f"admission refused: healthy capacity "
                            f"{healthy} < min_np {spec.min_np}"))
                from ..metrics.registry import registry
                registry().counter(
                    "hvd_fleet_admission_denials_total",
                    "Jobs denied by the admission controller").inc()
            else:
                rec = self.store.submit(spec)
            from ..debug import flight
            flight.record("fleet.submit", rec.id, tenant=spec.tenant,
                          priority=spec.priority, min_np=spec.min_np,
                          state=rec.state)
            return rec
