"""``hvd.fleet`` — fleet service mode: a multi-tenant job gateway on the
elastic fabric.

Instead of one job owning the device fleet from ``horovodrun`` to exit,
an always-on :class:`FleetGateway` owns it: tenants submit job specs
(HTTP, the :mod:`.submit` CLI, or ``horovodrun --submit``) into a
durable queue, and the scheduler multiplexes them onto the inventory by
driving per-job ``ElasticDriver``s — priority + per-tenant quota + fair
share, **checkpoint-mediated preemption** (the victim commits, shrinks
through the existing ``HostsUpdatedInterrupt`` path, and later resumes
bit-identically from its committed step), and SLO-driven admission
control fed by the health plane.  See docs/fleet.md.
"""

from .client import (cancel_job, default_addr, detect_gateway,
                     get_job, get_observation, list_jobs,
                     list_observed_jobs, push_observation, submit_job,
                     wait_job)
from .gateway import SERVICE_NAME, FleetGateway
from .observe import FleetSeriesStore
from .job import (ACTIVE_STATES, CANCELLED, DENIED, DONE, FAILED,
                  PREEMPTED, PREEMPTING, QUEUED, RUNNING,
                  TERMINAL_STATES, JobRecord, JobSpec)
from .policy import JobView, plan
from .queue import DurableJobQueue
from .scheduler import ElasticJobRunner, Scheduler
from .tuning import (GatewayTuningStore, LocalTuningStore,
                     TuningSchemaMismatch, config_key, make_record,
                     model_fingerprint, resolve_store,
                     topology_signature)

__all__ = [
    "ACTIVE_STATES", "CANCELLED", "DENIED", "DONE", "FAILED",
    "PREEMPTED", "PREEMPTING", "QUEUED", "RUNNING", "TERMINAL_STATES",
    "SERVICE_NAME",
    "DurableJobQueue", "ElasticJobRunner", "FleetGateway",
    "FleetSeriesStore", "JobRecord", "JobSpec", "JobView", "Scheduler",
    "cancel_job", "default_addr", "detect_gateway", "get_job",
    "get_observation", "list_jobs", "list_observed_jobs", "plan",
    "push_observation", "submit_job", "wait_job",
    "GatewayTuningStore", "LocalTuningStore", "TuningSchemaMismatch",
    "config_key", "make_record", "model_fingerprint", "resolve_store",
    "topology_signature",
]
