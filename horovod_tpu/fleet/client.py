"""Fleet gateway client — submit/inspect/cancel jobs over HTTP.

Requests ride the wire fabric's rung-1 ladder (``hvd.net``) and are
HMAC-signed with the fleet secret (``HVD_TPU_FLEET_SECRET`` or the
``secret=`` argument) under the rendezvous signature scheme.  The
default gateway address is ``HVD_TPU_FLEET_ADDR``, falling back to
``127.0.0.1:<HVD_TPU_FLEET_PORT>``.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import List, Optional

from .job import TERMINAL_STATES, JobRecord, JobSpec


def default_addr(addr: Optional[str] = None) -> str:
    from ..core.config import Config, get_env, get_int
    if addr:
        return addr
    env = get_env("FLEET_ADDR")
    if env:
        return env
    return f"127.0.0.1:{get_int('FLEET_PORT', Config.fleet_port)}"


def _secret(secret: Optional[str]) -> Optional[str]:
    from ..core.config import get_env
    return secret if secret is not None else get_env("FLEET_SECRET")


def _request(method: str, addr: str, key: str, body: bytes = b"",
             secret: Optional[str] = None, timeout: float = 5.0,
             none_on_404: bool = False, query: str = ""):
    from .. import net as _net
    from ..runner.rendezvous import _signature
    req = urllib.request.Request(
        f"http://{addr}/fleet/{key}" + (f"?{query}" if query else ""),
        data=body or None, method=method)
    sec = _secret(secret)
    if sec:
        req.add_header("X-HVD-Signature",
                       _signature(sec, method, "fleet", key, body))
    try:
        raw = _net.request_bytes(req, timeout=timeout,
                                 name=f"fleet.{method.lower()}.{key}")
    except urllib.error.HTTPError as e:
        if e.code == 404 and none_on_404:
            # A miss is an answer, not a failure (tuning-memory lookups).
            return None
        if e.code == 403:
            raise PermissionError(
                f"fleet gateway at {addr} rejected the request signature "
                "(missing or wrong HVD_TPU_FLEET_SECRET)") from None
        try:
            detail = json.loads(e.read().decode()).get("error", "")
        except Exception:  # noqa: BLE001
            detail = ""
        raise RuntimeError(
            f"fleet gateway at {addr}: HTTP {e.code} on {method} "
            f"/fleet/{key}" + (f": {detail}" if detail else "")) from None
    return json.loads(raw.decode())


def detect_gateway(addr: str, timeout: float = 2.0) -> Optional[dict]:
    """Probe ``/fleet/healthz`` (unsigned).  Returns the identity
    payload when a live fleet gateway answers there, else None — the
    launcher uses this to turn an opaque bind failure into the pointed
    "fleet mode is active" error."""
    req = urllib.request.Request(f"http://{addr}/fleet/healthz")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            payload = json.loads(resp.read().decode())
    except (OSError, ValueError, urllib.error.URLError):
        return None
    if isinstance(payload, dict) and \
            payload.get("service") == "horovod_tpu_fleet":
        return payload
    return None


def submit_job(spec: JobSpec, addr: Optional[str] = None,
               secret: Optional[str] = None) -> JobRecord:
    payload = json.dumps(spec.to_dict()).encode()
    return JobRecord.from_dict(
        _request("POST", default_addr(addr), "jobs", payload,
                 secret=secret))


def get_job(job_id: str, addr: Optional[str] = None,
            secret: Optional[str] = None) -> JobRecord:
    return JobRecord.from_dict(
        _request("GET", default_addr(addr), f"jobs/{job_id}",
                 secret=secret))


def list_jobs(addr: Optional[str] = None,
              secret: Optional[str] = None) -> List[JobRecord]:
    payload = _request("GET", default_addr(addr), "jobs", secret=secret)
    return [JobRecord.from_dict(d) for d in payload.get("jobs", [])]


def cancel_job(job_id: str, addr: Optional[str] = None,
               secret: Optional[str] = None) -> JobRecord:
    return JobRecord.from_dict(
        _request("DELETE", default_addr(addr), f"jobs/{job_id}",
                 secret=secret))


def push_observation(job_id: str, host_digest: dict,
                     addr: Optional[str] = None,
                     secret: Optional[str] = None) -> None:
    """Ingest one host digest into the gateway's fleet timeline
    (``fleet/observe.py``) — what the per-host observer's push loop
    calls on the ``HVD_TPU_FLEET_OBSERVE_PUSH_S`` cadence."""
    payload = json.dumps(host_digest).encode()
    _request("POST", default_addr(addr), f"observe/{job_id}", payload,
             secret=secret)


def get_observation(job_id: str, addr: Optional[str] = None,
                    secret: Optional[str] = None,
                    since: float = 0.0) -> Optional[dict]:
    """The job's retained timeline series (None when the gateway has
    none) — "what was job J's MFU over the last hour" without touching
    worker disks."""
    return _request("GET", default_addr(addr), f"observe/{job_id}",
                    secret=secret, none_on_404=True,
                    query=f"since={since}" if since else "")


def list_observed_jobs(addr: Optional[str] = None,
                       secret: Optional[str] = None) -> List[str]:
    payload = _request("GET", default_addr(addr), "observe",
                       secret=secret)
    return list(payload.get("jobs", []))


def wait_job(job_id: str, addr: Optional[str] = None,
             secret: Optional[str] = None, timeout: float = 3600.0,
             poll_s: float = 1.0) -> JobRecord:
    """Poll until the job reaches a terminal state (done/failed/
    cancelled/denied)."""
    deadline = time.time() + timeout
    while True:
        rec = get_job(job_id, addr=addr, secret=secret)
        if rec.state in TERMINAL_STATES:
            return rec
        if time.time() >= deadline:
            raise TimeoutError(
                f"job {job_id} still {rec.state} after {timeout}s")
        time.sleep(poll_s)
