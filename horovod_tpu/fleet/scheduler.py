"""The fleet scheduler — multiplexes queued jobs onto one device fleet.

Executes :func:`.policy.plan` decisions against real jobs: each running
job is an :class:`ElasticJobRunner` (an ``ElasticDriver`` on a slice of
the fleet's hosts, driven through the PR-8 ``request_resize``/
``preempt`` carve-outs), and preemption is **checkpoint-mediated** —
a shrink/stop decision first parks in ``_pending_preempt`` until the
victim announces a commit newer than the decision (or the grace window
expires), so the victim always resumes from the step it just committed.

The scheduler is deliberately driveable without threads: ``tick()`` is
the whole control loop, tests call it directly with fake runners, and
``start()`` just runs it on a cadence.  Every decision lands in
``hvd_fleet_*`` metrics and ``fleet.*`` flight events.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..runner.hosts import HostInfo
from .job import (DENIED, DONE, FAILED, CANCELLED, PREEMPTED, PREEMPTING,
                  QUEUED, RUNNING, JobRecord)
from .policy import JobView, plan
from .queue import DurableJobQueue

# Queue-wait SLO buckets (seconds): sub-second dispatch .. multi-hour
# backlog.
_WAIT_BUCKETS = (0.5, 2.0, 10.0, 60.0, 300.0, 1800.0, 7200.0)


def _flight(kind: str, name: Optional[str] = None, **fields):
    from ..debug import flight
    flight.record(kind, name, **fields)


def _registry():
    from ..metrics.registry import registry
    return registry()


class ElasticJobRunner:
    """One job = one ``ElasticDriver`` on a host slice, run on a daemon
    thread (``driver.run()`` blocks until the job ends)."""

    def __init__(self, record: JobRecord, extra_env: Dict[str, str],
                 verbose: bool = False):
        from ..runner.elastic_driver import ElasticDriver, FixedHosts
        self._record_id = record.id
        self._discovery = FixedHosts([])
        env = dict(record.spec.env)
        env.update(extra_env)
        env["HVD_TPU_FLEET_JOB_ID"] = record.id
        env["HVD_TPU_FLEET_TENANT"] = record.spec.tenant
        env["HVD_TPU_FLEET_JOB_KIND"] = record.spec.kind
        self._driver = ElasticDriver(
            self._discovery, list(record.spec.command),
            min_np=record.spec.min_np, max_np=record.spec.max_np,
            extra_env=env, verbose=verbose)
        self._thread: Optional[threading.Thread] = None
        self._rc: Optional[int] = None
        self.cancelled = False

    def start(self, hosts: List[HostInfo]) -> None:
        self._discovery.set(list(hosts))

        def _run():
            self._rc = self._driver.run()

        self._thread = threading.Thread(
            target=_run, name=f"hvd-tpu-fleet-job-{self._record_id}",
            daemon=True)
        self._thread.start()

    def resize(self, hosts: List[HostInfo], np: int, reason: str) -> bool:
        self._discovery.set(list(hosts))
        return self._driver.request_resize(np, reason)

    def announce_resize(self) -> float:
        return self._driver.announce_resize()

    def preempt(self, reason: str) -> bool:
        return self._driver.preempt(reason)

    def cancel(self, reason: str) -> bool:
        # Flag only on success: a job whose run() already returned 0
        # must reap as DONE, not CANCELLED, when the DELETE races its
        # completion.
        if self._driver.preempt(reason):
            self.cancelled = True
            return True
        return False

    def last_commit(self) -> Optional[dict]:
        return self._driver.last_commit()

    @property
    def preempted(self) -> bool:
        return self._driver.preempted

    def result(self) -> Optional[int]:
        if self._thread is not None and self._thread.is_alive():
            return None
        return self._rc

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)


class Scheduler:
    """Drives the queue.  ``hosts_provider()`` returns the fleet's total
    inventory; ``health_hook()`` (optional) returns hostnames the
    health plane excludes — their slots are never promised.
    ``runner_factory(record, extra_env)`` builds a runner (tests inject
    fakes); the default is :class:`ElasticJobRunner`."""

    def __init__(self, store: DurableJobQueue,
                 hosts_provider: Callable[[], List[HostInfo]],
                 runner_factory=None,
                 health_hook: Optional[Callable[[], List[str]]] = None,
                 quota_slots: Optional[int] = None,
                 preemption: Optional[bool] = None,
                 preempt_grace_s: Optional[float] = None,
                 tick_s: Optional[float] = None,
                 extra_env: Optional[Dict[str, str]] = None,
                 verbose: bool = False):
        from ..core.config import Config, get_bool, get_float, get_int
        self._store = store
        self._hosts_provider = hosts_provider
        self._health_hook = health_hook
        self._runner_factory = runner_factory or (
            lambda rec, env: ElasticJobRunner(rec, env, verbose=verbose))
        self._quota = (get_int("FLEET_QUOTA_SLOTS", Config.fleet_quota_slots)
                       if quota_slots is None else int(quota_slots))
        self._preemption = (get_bool("FLEET_PREEMPTION",
                                     Config.fleet_preemption)
                            if preemption is None else bool(preemption))
        self._grace_s = (get_float("FLEET_PREEMPT_GRACE_S",
                                   Config.fleet_preempt_grace_s)
                         if preempt_grace_s is None
                         else float(preempt_grace_s))
        self._tick_s = (get_float("FLEET_TICK_S", Config.fleet_tick_s)
                        if tick_s is None else float(tick_s))
        self._extra_env = dict(extra_env or {})
        self._verbose = verbose
        self._lock = threading.RLock()
        self._runners: Dict[str, object] = {}
        self._alloc: Dict[str, Dict[str, int]] = {}  # job -> host -> slots
        # victim_id -> {"kind", "np", "for_job", "t0", "deadline"}
        self._pending_preempt: Dict[str, dict] = {}
        self._quota_waiting: set = set()
        self._shrunk: set = set()  # shrunk victims owed a resume/regrow
        # Inventory resilience: a transient hosts_provider failure must
        # not read as "capacity 0" (plan() would DENY the whole queue,
        # a terminal state).  Keep the last good view; until one exists,
        # admission denials are suppressed entirely.
        self._last_hosts: List[HostInfo] = []
        self._inventory_seen = False
        # Per-tick healthy-inventory snapshot (None outside a tick).
        self._healthy_now: Optional[List[HostInfo]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- capacity ----------------------------------------------------------

    def fleet_hosts(self) -> List[HostInfo]:
        try:
            hosts = list(self._hosts_provider())
        except Exception as e:  # noqa: BLE001 — glitch: keep last view
            from ..utils import logging as log
            log.warning("fleet inventory read failed (%r); keeping the "
                        "last good view (%d hosts)", e,
                        len(self._last_hosts))
            return list(self._last_hosts)
        self._last_hosts = hosts
        self._inventory_seen = True
        return hosts

    @property
    def inventory_seen(self) -> bool:
        """True once the hosts provider has succeeded at least once —
        before that, capacity 0 means "unknown", not "deny"."""
        return self._inventory_seen

    def healthy_hosts(self) -> List[HostInfo]:
        hosts = self.fleet_hosts()
        if self._health_hook is None:
            return hosts
        try:
            excluded = set(self._health_hook() or ())
        except Exception:  # noqa: BLE001 — a hint, not an oracle
            excluded = set()
        return [h for h in hosts if h.hostname not in excluded]

    def healthy_slots(self) -> int:
        return sum(h.slots for h in self.healthy_hosts())

    def _allocate(self, np: int) -> Optional[List[HostInfo]]:
        """Greedy slice of free healthy slots, inventory order (from
        the tick's snapshot when inside a tick)."""
        healthy = self._healthy_now
        if healthy is None:
            healthy = self.healthy_hosts()
        used: Dict[str, int] = {}
        for alloc in self._alloc.values():
            for host, n in alloc.items():
                used[host] = used.get(host, 0) + n
        out: List[HostInfo] = []
        for h in healthy:
            if np <= 0:
                break
            avail = h.slots - used.get(h.hostname, 0)
            if avail <= 0:
                continue
            take = min(avail, np)
            out.append(HostInfo(h.hostname, take))
            np -= take
        return out if np <= 0 else None

    @staticmethod
    def _trim_alloc(alloc: Dict[str, int], np: int) -> Dict[str, int]:
        """Shrink an allocation to np slots, keeping the earliest hosts
        (survivor slots stay seated; the tail frees)."""
        out: Dict[str, int] = {}
        for host, n in alloc.items():
            if np <= 0:
                break
            take = min(n, np)
            out[host] = take
            np -= take
        return out

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="hvd-tpu-fleet-scheduler", daemon=True)
        self._thread.start()

    def stop(self, cancel_jobs: bool = False) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if cancel_jobs:
            with self._lock:
                runners = list(self._runners.values())
            for r in runners:
                r.cancel("gateway shutdown")
            for r in runners:
                r.join(timeout=15)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                from ..utils import logging as log
                log.warning("fleet scheduler tick failed: %r", e)
            self._stop.wait(self._tick_s)

    # -- control loop ------------------------------------------------------

    def tick(self) -> List[tuple]:
        """One scheduling round; returns the decisions it executed
        (tests assert on them)."""
        with self._lock:
            # One inventory read per tick: the provider may be a
            # subprocess-backed discovery script, and the plan, the
            # allocations, and the gauges must all see the SAME view —
            # re-reading mid-tick is both redundant I/O and a window
            # for plan/allocate disagreement.
            self._healthy_now = self.healthy_hosts()
            try:
                self._reap()
                self._run_pending_preemptions()
                decisions = self._plan_and_execute(self._healthy_now)
                self._update_gauges(self._healthy_now)
                return decisions
            finally:
                self._healthy_now = None

    def _reap(self) -> None:
        now = time.time()
        for job_id in list(self._runners):
            runner = self._runners[job_id]
            rc = runner.result()
            if rc is None:
                continue
            self._runners.pop(job_id)
            self._alloc.pop(job_id, None)
            self._pending_preempt.pop(job_id, None)
            rec = self._store.get(job_id)
            if rec is None:
                continue
            if getattr(runner, "cancelled", False):
                state, reason = CANCELLED, "cancelled"
            elif getattr(runner, "preempted", False):
                # Suspended for a higher-priority job: observable as
                # PREEMPTED (scheduled like a queued job, keeping its
                # submit_seq seniority); the entrypoint resumes from its
                # committed checkpoint when reseated.
                state, reason = PREEMPTED, rec.reason or "preempted"
            elif rc == 0:
                state, reason = DONE, ""
            else:
                state, reason = FAILED, f"exit code {rc}"

            def _mut(r, state=state, reason=reason, rc=rc, now=now):
                r.state = state
                r.np = 0
                r.reason = reason
                if state == QUEUED:
                    r.exit_code = None
                else:
                    r.exit_code = rc
                    r.finished_at = now

            self._store.update(job_id, _mut)
            self._shrunk.discard(job_id)
            _flight("fleet.job_end", job_id, state=state, exit=rc)

    def _run_pending_preemptions(self) -> None:
        now = time.time()
        for victim_id in list(self._pending_preempt):
            p = self._pending_preempt[victim_id]
            runner = self._runners.get(victim_id)
            if runner is None:
                self._pending_preempt.pop(victim_id)
                continue
            lc = runner.last_commit()
            # Generation comparison, not wall clocks: the worker stamps
            # ts with ITS host's clock, so skew against the gateway
            # would either void the gate (worker ahead: a pre-announce
            # commit passes) or always burn the grace window (worker
            # behind).  The commit counter is monotonic and clock-free.
            committed = (lc is not None and
                         int(lc.get("generation", 0)) > p["gen0"])
            if not committed and now < p["deadline"]:
                continue
            self._pending_preempt.pop(victim_id)
            self._execute_preemption(victim_id, p, runner,
                                     committed=committed, commit=lc)

    def _execute_preemption(self, victim_id: str, p: dict, runner,
                            committed: bool, commit=None) -> None:
        generation = (commit or {}).get("generation")
        _registry().counter(
            "hvd_fleet_preemptions_total",
            "Jobs shrunk or suspended for a higher-priority job").inc()
        _flight("fleet.preempt", victim_id, mode=p["kind"],
                np=p.get("np"), for_job=p["for_job"],
                committed=committed, generation=generation)
        if p["kind"] == "shrink":
            new_alloc = self._trim_alloc(
                self._alloc.get(victim_id, {}), p["np"])
            hosts = [HostInfo(h, n) for h, n in new_alloc.items()]
            if runner.resize(hosts, p["np"],
                             f"preempted by {p['for_job']}"):
                self._alloc[victim_id] = new_alloc
                self._shrunk.add(victim_id)

                def _mut(r, np=p["np"]):
                    r.state = RUNNING
                    r.np = np
                    r.preemptions += 1
                    r.preempt_generation = generation
                    r.reason = (f"shrunk for {p['for_job']} at commit "
                                f"generation {generation}")
                self._store.update(victim_id, _mut)
            else:
                # Resize refused (job completing): drop back to RUNNING.
                self._store.update(
                    victim_id, lambda r: setattr(r, "state", RUNNING))
        else:  # stop: suspend the whole job; requeued at reap time
            def _mut(r):
                r.preemptions += 1
                r.preempt_generation = generation
                r.reason = f"preempted by {p['for_job']}"
            self._store.update(victim_id, _mut)
            runner.preempt(f"preempted by {p['for_job']}")

    def _views(self) -> List[JobView]:
        views = []
        for rec in self._store.list():
            if rec.state in (QUEUED, PREEMPTED):
                state = "queued"
            elif rec.state == RUNNING:
                state = ("preempting"
                         if rec.id in self._pending_preempt else "running")
            elif rec.state == PREEMPTING:
                state = "preempting"
            else:
                continue
            views.append(JobView(
                id=rec.id, tenant=rec.spec.tenant,
                priority=rec.spec.priority, min_np=rec.spec.min_np,
                max_np=rec.spec.max_np, submit_seq=rec.submit_seq,
                state=state, np=rec.np,
                max_queue_s=rec.spec.max_queue_s))
        return views

    def _plan_and_execute(self, healthy_hosts: List[HostInfo]) \
            -> List[tuple]:
        healthy = sum(h.slots for h in healthy_hosts)
        decisions = plan(self._views(), healthy,
                         quota_slots=self._quota,
                         preemption=self._preemption)
        now = time.time()
        new_quota_waiting = set()
        for d in decisions:
            kind = d[0]
            if kind == "deny":
                if not self._inventory_seen:
                    continue  # capacity unknown, not absent: keep queued
                _, job_id, reason = d

                def _mut(r, reason=reason, now=now):
                    r.state = DENIED
                    r.reason = reason
                    r.finished_at = now
                self._store.update(job_id, _mut)
                _registry().counter(
                    "hvd_fleet_admission_denials_total",
                    "Jobs denied by the admission controller").inc()
                _flight("fleet.schedule", job_id, decision="deny",
                        reason=reason)
            elif kind == "quota_wait":
                _, job_id, tenant = d
                new_quota_waiting.add(job_id)
                if job_id not in self._quota_waiting:
                    _registry().counter(
                        "hvd_fleet_quota_denials_total",
                        "Scheduling passes a job waited on its tenant "
                        "quota", tenant=tenant).inc()
                    _flight("fleet.schedule", job_id,
                            decision="quota_wait", tenant=tenant)
            elif kind == "start":
                _, job_id, np = d
                self._start_job(job_id, np, now)
            elif kind == "grow":
                _, job_id, np = d
                self._grow_job(job_id, np)
            elif kind in ("shrink", "stop"):
                victim_id = d[1]
                if victim_id in self._pending_preempt:
                    continue
                runner = self._runners.get(victim_id)
                if runner is None:
                    continue
                # Graceful phase one: the host event parks every victim
                # worker at its next commit (HostsUpdatedInterrupt), so
                # the shrink that follows lands between steps — never
                # mid-collective.  The commit gate waits for a commit
                # GENERATION beyond the one current at announce time
                # (clock-free; see _run_pending_preemptions).  gen0 is
                # read before the announce: a commit racing the publish
                # may open the gate un-parked, which just means the
                # shrink takes the ordinary failure-path restore to that
                # same committed step.
                gen0 = int((runner.last_commit() or {})
                           .get("generation", 0))
                announce = getattr(runner, "announce_resize", None)
                t0 = announce() if announce is not None else now
                p = {"kind": kind,
                     "np": d[2] if kind == "shrink" else 0,
                     "for_job": d[-1], "t0": t0, "gen0": gen0,
                     "deadline": t0 + self._grace_s}
                self._pending_preempt[victim_id] = p
                self._store.update(
                    victim_id, lambda r: setattr(r, "state", PREEMPTING))
                _flight("fleet.preempt", victim_id, mode=kind,
                        phase="commit_wait", for_job=p["for_job"])
        self._quota_waiting = new_quota_waiting
        return decisions

    def _start_job(self, job_id: str, np: int, now: float) -> None:
        rec = self._store.get(job_id)
        if rec is None or rec.state not in (QUEUED, PREEMPTED):
            return
        hosts = self._allocate(np)
        if hosts is None:
            return  # raced with a health change; next tick replans
        runner = self._runner_factory(rec, dict(self._extra_env))
        self._runners[job_id] = runner
        self._alloc[job_id] = {h.hostname: h.slots for h in hosts}
        resume = rec.started_at > 0

        def _mut(r):
            r.state = RUNNING
            r.np = np
            r.started_at = now
            if not r.first_started_at:
                r.first_started_at = now
                r.queue_wait_s = now - r.submitted_at
            if resume:
                r.resumes += 1
        self._store.update(job_id, _mut)
        if not resume:
            _registry().histogram(
                "hvd_fleet_queue_wait_seconds",
                "Submission to first start", buckets=_WAIT_BUCKETS
            ).observe(max(0.0, now - rec.submitted_at))
        runner.start(hosts)
        _flight("fleet.resume" if resume else "fleet.schedule",
                job_id, np=np, tenant=rec.spec.tenant)

    def _grow_job(self, job_id: str, np: int) -> None:
        runner = self._runners.get(job_id)
        rec = self._store.get(job_id)
        if runner is None or rec is None:
            return
        cur = self._alloc.get(job_id, {})
        extra = self._allocate(np - sum(cur.values()))
        if extra is None:
            return
        merged = dict(cur)
        for h in extra:
            merged[h.hostname] = merged.get(h.hostname, 0) + h.slots
        hosts = [HostInfo(h, n) for h, n in merged.items()]
        if runner.resize(hosts, np, "fleet capacity available"):
            self._alloc[job_id] = merged
            self._store.update(job_id, lambda r: setattr(r, "np", np))
            if job_id in self._shrunk:
                # A preemption victim regained its width: the shrink
                # half of preempt/resume closes here.
                self._shrunk.discard(job_id)
                _flight("fleet.resume", job_id, np=np, regrow=True)
            else:
                _flight("fleet.schedule", job_id, decision="grow", np=np)

    # -- operations --------------------------------------------------------

    def cancel(self, job_id: str) -> Optional[JobRecord]:
        with self._lock:
            rec = self._store.get(job_id)
            if rec is None:
                return None
            runner = self._runners.get(job_id)
            if runner is not None:
                runner.cancel("cancelled by tenant")
                return self._store.get(job_id)  # reaped on a later tick
            if rec.state in (QUEUED, PREEMPTED):
                def _mut(r):
                    r.state = CANCELLED
                    r.reason = "cancelled"
                    r.finished_at = time.time()
                return self._store.update(job_id, _mut)
            return rec

    def running_count(self) -> int:
        with self._lock:
            return len(self._runners)

    def _update_gauges(self, healthy_hosts: List[HostInfo]) -> None:
        reg = _registry()
        records = self._store.list()
        reg.gauge("hvd_fleet_jobs_queued",
                  "Jobs waiting for capacity").set(
            sum(1 for r in records
                if r.state in (QUEUED, PREEMPTED)))
        reg.gauge("hvd_fleet_jobs_running",
                  "Jobs currently holding fleet slots").set(
            sum(1 for r in records
                if r.state in (RUNNING, PREEMPTING)))
        reg.gauge("hvd_fleet_healthy_slots",
                  "Slots the admission controller may promise").set(
            sum(h.slots for h in healthy_hosts))
