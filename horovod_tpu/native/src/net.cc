#include "net.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <net/if.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/ioctl.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstdio>
#include <algorithm>
#include <chrono>
#include <cstdlib>
#include <cstring>

namespace hvdtpu {

// ---------------------------------------------------------------------------
// Resilience / chaos configuration + counters
// ---------------------------------------------------------------------------

namespace {

const char* EnvOr(const char* hvd, const char* legacy = nullptr) {
  const char* v = getenv(hvd);
  if (!v && legacy) v = getenv(legacy);
  return v;
}

double EnvDouble(const char* name, double dflt) {
  const char* v = EnvOr(name);
  return (v && *v) ? atof(v) : dflt;
}

bool EnvBool(const char* name, bool dflt) {
  const char* v = EnvOr(name);
  if (!v || !*v) return dflt;
  return !(strcmp(v, "0") == 0 || strcasecmp(v, "false") == 0 ||
           strcasecmp(v, "off") == 0 || strcasecmp(v, "no") == 0);
}

}  // namespace

const NetResilienceConfig& NetResilience() {
  static const NetResilienceConfig cfg = [] {
    NetResilienceConfig c;
    c.enabled = EnvBool("HVD_TPU_NET_RESILIENCE", true);
    c.probe_ms = EnvDouble("HVD_TPU_NET_PROBE_MS", c.probe_ms);
    c.reconnect_s = EnvDouble("HVD_TPU_NET_RECONNECT_S", c.reconnect_s);
    c.op_deadline_s =
        EnvDouble("HVD_TPU_NET_OP_DEADLINE_S", c.op_deadline_s);
    c.max_renegotiations = static_cast<int>(
        EnvDouble("HVD_TPU_NET_MAX_RENEG", c.max_renegotiations));
    c.renegotiate = EnvBool("HVD_TPU_NET_RENEGOTIATE", true);
    return c;
  }();
  return cfg;
}

const NetChaosConfig& NetChaos() {
  static const NetChaosConfig cfg = [] {
    NetChaosConfig c;
    c.seed = static_cast<uint64_t>(
        EnvDouble("HVD_TPU_CHAOS_NET_SEED", 0));
    c.drop_pct = EnvDouble("HVD_TPU_CHAOS_NET_DROP_PCT", 0);
    c.reset_pct = EnvDouble("HVD_TPU_CHAOS_NET_RESET_PCT", 0);
    c.delay_ms = EnvDouble("HVD_TPU_CHAOS_NET_DELAY_MS", 0);
    c.truncate_pct = EnvDouble("HVD_TPU_CHAOS_NET_TRUNCATE", 0);
    if (const char* bh = EnvOr("HVD_TPU_CHAOS_NET_BLACKHOLE")) {
      std::string s(bh);
      size_t pos = 0;
      while (pos < s.size()) {
        size_t end = s.find(',', pos);
        if (end == std::string::npos) end = s.size();
        std::string tok = s.substr(pos, end - pos);
        size_t dash = tok.find('-');
        if (dash != std::string::npos) {
          int a = atoi(tok.substr(0, dash).c_str());
          int b = atoi(tok.substr(dash + 1).c_str());
          c.blackhole.insert({std::min(a, b), std::max(a, b)});
        }
        pos = end + 1;
      }
    }
    return c;
  }();
  return cfg;
}

// splitmix64 over (seed, rank, peer, index): platform-independent and
// identical on every incarnation — the same determinism contract as the
// Python recovery chaos layer's sha256 draws.
double NetChaosDraw(uint64_t seed, int rank, int peer, uint64_t index) {
  uint64_t x = seed * 0x9E3779B97F4A7C15ull + 0xBF58476D1CE4E5B9ull;
  x ^= (static_cast<uint64_t>(rank) << 32) ^
       (static_cast<uint64_t>(static_cast<uint32_t>(peer)));
  x += index * 0x94D049BB133111EBull;
  x ^= x >> 30; x *= 0xBF58476D1CE4E5B9ull;
  x ^= x >> 27; x *= 0x94D049BB133111EBull;
  x ^= x >> 31;
  return static_cast<double>(x >> 11) / 9007199254740992.0;  // [0, 1)
}

NetCountersState& NetCounters() {
  static NetCountersState* s = new NetCountersState();
  return *s;
}

// HVD_TPU_NET_TRACE=1: recovery-path stderr traces (debug aid; off in
// production — the hot path never calls this when disabled).
bool NetTrace() {
  static const bool on = [] {
    const char* v = getenv("HVD_TPU_NET_TRACE");
    return v && *v && strcmp(v, "0") != 0;
  }();
  return on;
}

#define NET_TRACE(fmt, ...)                                              \
  do {                                                                   \
    if (NetTrace())                                                      \
      fprintf(stderr, "[hvdnet r%d p%d] " fmt "\n", net_->rank(), peer_, \
              ##__VA_ARGS__);                                            \
  } while (0)

int64_t SteadyNowMs() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------------------
// Raw socket helpers
// ---------------------------------------------------------------------------

Socket::~Socket() {
  if (fd_ >= 0) ::close(fd_);
}

Status Socket::SendAll(const void* data, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  while (n > 0) {
    ssize_t k = ::send(fd_, p, n, MSG_NOSIGNAL);
    if (k < 0) {
      if (errno == EINTR) continue;
      return Status::Error(std::string("send: ") + strerror(errno));
    }
    p += k;
    n -= k;
  }
  return Status::OK();
}

Status Socket::RecvAll(void* data, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(data);
  while (n > 0) {
    ssize_t k = ::recv(fd_, p, n, 0);
    if (k < 0) {
      if (errno == EINTR) continue;
      return Status::Error(std::string("recv: ") + strerror(errno));
    }
    if (k == 0) return Status::Aborted("peer closed connection");
    p += k;
    n -= k;
  }
  return Status::OK();
}

Status Socket::SendFrame(const std::vector<uint8_t>& payload) {
  uint32_t len = static_cast<uint32_t>(payload.size());
  Status s = SendAll(&len, 4);
  if (!s.ok()) return s;
  return SendAll(payload.data(), payload.size());
}

Status Socket::RecvFrame(std::vector<uint8_t>& payload) {
  uint32_t len = 0;
  Status s = RecvAll(&len, 4);
  if (!s.ok()) return s;
  payload.resize(len);
  return RecvAll(payload.data(), len);
}

namespace {

void SetNoDelay(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  // Large kernel buffers keep the full-duplex ring streaming instead of
  // stalling on flow control (both directions carry MBs per step).
  // (No socket-level SO_SNDTIMEO/RCVTIMEO: control-plane waits — e.g. a
  // worker blocking on the address table while slow peers start up — are
  // legitimately longer than any collective timeout; the collective paths
  // bound their own waits with poll().)
  int bufsz = 4 << 20;
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &bufsz, sizeof(bufsz));
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bufsz, sizeof(bufsz));
}

int Listen(uint16_t port, uint16_t* bound_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 64) < 0) {
    ::close(fd);
    return -1;
  }
  socklen_t len = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  *bound_port = ntohs(addr.sin_port);
  return fd;
}

// Non-blocking connect bounded by timeout_s; on success the socket is
// returned in blocking mode.  Bounding connect() itself matters: against a
// black-holed address a blocking connect sits in the kernel SYN retry for
// minutes, which would blow any caller-side deadline.
static int ConnectTimeout(const addrinfo* res, double timeout_s) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  int rc = ::connect(fd, res->ai_addr, res->ai_addrlen);
  if (rc != 0) {
    if (errno != EINPROGRESS) {
      ::close(fd);
      return -1;
    }
    pollfd pfd{fd, POLLOUT, 0};
    int ms = static_cast<int>(timeout_s * 1000);
    if (poll(&pfd, 1, ms > 0 ? ms : 1) != 1) {
      ::close(fd);
      return -1;
    }
    int err = 0;
    socklen_t len = sizeof(err);
    if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len) != 0 || err != 0) {
      ::close(fd);
      return -1;
    }
  }
  fcntl(fd, F_SETFL, flags);  // back to blocking for the frame protocol
  return fd;
}

bool ParseAddr(const std::string& addr, std::string* host, uint16_t* port) {
  auto pos = addr.rfind(':');
  if (pos == std::string::npos) return false;
  *host = addr.substr(0, pos);
  *port = static_cast<uint16_t>(atoi(addr.c_str() + pos + 1));
  return true;
}

int DialOnce(const std::string& host, uint16_t port, double timeout_s) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  snprintf(portstr, sizeof(portstr), "%u", port);
  if (getaddrinfo(host.c_str(), portstr, &hints, &res) != 0 || !res)
    return -1;
  int fd = ConnectTimeout(res, timeout_s);
  freeaddrinfo(res);
  if (fd >= 0) SetNoDelay(fd);
  return fd;
}

int DialRetry(const std::string& host, uint16_t port, int attempts = 600) {
  // --start-timeout: bound how long workers wait for the coordinator (and
  // for peer-mesh dials during startup) — reference horovodrun
  // --start-timeout; default stays ~60 s.  Deadline-based: retries plus
  // DNS/connect time all count against the budget.
  double timeout_s = attempts * 0.1;
  const char* st = getenv("HVD_TPU_START_TIMEOUT");
  if (!st) st = getenv("HOROVOD_START_TIMEOUT");
  if (st && atof(st) > 0) timeout_s = atof(st);
  auto deadline = std::chrono::steady_clock::now() +
      std::chrono::duration<double>(timeout_s);
  while (std::chrono::steady_clock::now() < deadline) {
    double remaining = std::chrono::duration<double>(
        deadline - std::chrono::steady_clock::now()).count();
    int fd = DialOnce(host, port, std::min(remaining, 2.0));
    if (fd >= 0) return fd;
    usleep(100000);  // coordinator may not be up yet; retry until deadline
  }
  return -1;
}

std::string LocalHostname() {
  // HVD_TPU_IFACE / HOROVOD_GLOO_IFACE: advertise this interface's IPv4
  // to peers instead of the hostname (reference --network-interface /
  // HOROVOD_GLOO_IFACE semantics — on multi-NIC hosts gethostname() may
  // resolve to an address peers cannot route to).
  const char* ifn = getenv("HVD_TPU_IFACE");
  if (!ifn || !*ifn) ifn = getenv("HOROVOD_GLOO_IFACE");
  if (ifn && *ifn) {
    int fd = ::socket(AF_INET, SOCK_DGRAM, 0);
    if (fd >= 0) {
      ifreq ifr{};
      strncpy(ifr.ifr_name, ifn, IFNAMSIZ - 1);
      bool ok = ioctl(fd, SIOCGIFADDR, &ifr) == 0;
      ::close(fd);
      if (ok) {
        auto* sin = reinterpret_cast<sockaddr_in*>(&ifr.ifr_addr);
        char abuf[INET_ADDRSTRLEN];
        if (inet_ntop(AF_INET, &sin->sin_addr, abuf, sizeof(abuf))) {
          return abuf;
        }
      }
    }
  }
  char buf[256];
  if (gethostname(buf, sizeof(buf)) == 0) return buf;
  return "127.0.0.1";
}

// --- resilient frame wire format -------------------------------------------

constexpr uint32_t kMagicData = 0x48444154;   // 'HDAT'
constexpr uint32_t kMagicAck = 0x4841434Bu;   // 'HACK'
constexpr uint32_t kMagicAbort = 0x48414254;  // 'HABT'
constexpr uint32_t kMagicHello = 0x48454C4F;  // 'HELO'  (resume)
constexpr uint32_t kMagicHelloReset = 0x48525354;  // 'HRST' (fresh link)
constexpr uint32_t kMagicReport = 0x48524550;      // 'HREP' (agreement)
constexpr uint32_t kMagicVerdict = 0x48564552;     // 'HVER' (agreement)

struct FrameHdr {
  uint32_t magic;
  uint32_t len;
  uint64_t seq;
};

struct HelloWire {
  uint32_t magic;
  int32_t rank;
  uint64_t generation;
};

struct ResumeWire {
  uint64_t recv_bytes;
  uint64_t recv_frames;
  uint64_t recv_ops;
};

constexpr size_t kFrameChunk = 1 << 20;
constexpr int kPumpSliceMs = 100;
// cv fallback when another thread holds the reader lock: bounded SHORT —
// a waiter that lost the try_lock race by a hair must not sleep until
// the next dispatch happens to notify it (measured ~+100us per op).
constexpr int kPumpWaitMs = 2;
// Unacked-send replay cap: a sender may run this far ahead of the
// receiver's acks before it must block and drain them.  Covers the
// default 64 MB fusion buffer's largest ring segment with room to
// spare.
constexpr size_t kReplayCap = 64u << 20;
// Ops up to this size complete optimistically (bytes copied into the
// replay buffer; the ack round-trip leaves the critical path — it is
// what dominates small ring steps).  Larger ops stream zero-copy and
// ack-wait at the end: the RTT is amortized by the transfer itself and
// the replay memcpy would be the new per-byte tax.
constexpr size_t kOptimisticMax = 256u << 10;
// ACK cadence: small-op receivers batch their delivery acks until this
// many bytes accumulate — per-op acks doubled the syscall count of a
// ring step for no correctness gain (resume exchanges recv_bytes_
// directly; acks only prune the sender's replay tail).  Ops at or above
// kOptimisticMax always ack at completion: their sender is waiting.
constexpr uint64_t kAckEveryBytes = 1u << 20;

bool IoAllTimeout(int fd, void* buf, size_t n, int ms, bool write) {
  // I/O-first: syscalls dominate on sandboxed kernels, so attempt the
  // transfer directly and fall back to poll() only on EAGAIN.
  uint8_t* p = static_cast<uint8_t*>(buf);
  auto end = std::chrono::steady_clock::now() +
             std::chrono::milliseconds(ms);
  size_t done = 0;
  while (done < n) {
    ssize_t k = write
        ? ::send(fd, p + done, n - done, MSG_NOSIGNAL | MSG_DONTWAIT)
        : ::recv(fd, p + done, n - done, MSG_DONTWAIT);
    if (k > 0) {
      done += k;
      continue;
    }
    if (k == 0 && !write) return false;
    if (k < 0 && errno != EINTR && errno != EAGAIN &&
        errno != EWOULDBLOCK)
      return false;
    int left = static_cast<int>(
        std::chrono::duration_cast<std::chrono::milliseconds>(
            end - std::chrono::steady_clock::now())
            .count());
    if (left <= 0) return false;
    pollfd pfd{fd, static_cast<short>(write ? POLLOUT : POLLIN), 0};
    int pr = ::poll(&pfd, 1, left);
    if (pr < 0 && errno == EINTR) continue;
    if (pr <= 0) return false;
  }
  return true;
}

}  // namespace

// ---------------------------------------------------------------------------
// Channel
// ---------------------------------------------------------------------------

struct Channel::Deadline {
  std::chrono::steady_clock::time_point end;
  bool infinite = false;
  static Deadline After(double seconds) {
    Deadline d;
    if (seconds <= 0) {
      d.infinite = true;
    } else {
      d.end = std::chrono::steady_clock::now() +
              std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                  std::chrono::duration<double>(seconds));
    }
    return d;
  }
  bool expired() const {
    return !infinite && std::chrono::steady_clock::now() >= end;
  }
  double remaining_s() const {
    if (infinite) return 3600.0;
    return std::chrono::duration<double>(
               end - std::chrono::steady_clock::now())
        .count();
  }
};

Channel::Channel(Network* net, int peer, int fd)
    : net_(net), peer_(peer), dialer_(net->rank() > peer), fd_(fd) {}

Channel::~Channel() {
  int fd = fd_.exchange(-1);
  if (fd >= 0) ::close(fd);
  if (pending_fd_ >= 0) ::close(pending_fd_);
  for (auto& g : graveyard_) ::close(g.first);
}

void Channel::CloseFd() {
  NET_TRACE("closefd");
  // shutdown, don't close yet: a concurrent op thread may still hold this
  // fd number in a poll set, and closing would let the kernel reuse the
  // number for the REPLACEMENT socket — the blocked thread would then
  // read the resumed stream.  shutdown() wakes every blocked syscall on
  // it immediately; the number itself is reclaimed once two adoption
  // epochs have passed (ReapGraveyard) — by then no op loop can still be
  // between capturing the fd and its next syscall on it.
  int fd = fd_.exchange(-1);
  if (fd >= 0) {
    ::shutdown(fd, SHUT_RDWR);
    std::lock_guard<std::mutex> lk(smu_);
    graveyard_.push_back({fd, epoch_.load()});
  }
}

void Channel::ReapGraveyard() {
  std::lock_guard<std::mutex> lk(smu_);
  uint64_t cur = epoch_.load();
  size_t kept = 0;
  for (auto& g : graveyard_) {
    if (g.second + 2 <= cur) {
      ::close(g.first);
    } else {
      graveyard_[kept++] = g;
    }
  }
  graveyard_.resize(kept);
}

bool Channel::Aborted() const { return net_->AbortPending(); }

Status Channel::WriteBytes(int fd, const uint8_t* p, size_t n) {
  struct WT { std::chrono::steady_clock::time_point t0;
              ~WT() { NetCounters().write_us +=
                  std::chrono::duration_cast<std::chrono::microseconds>(
                      std::chrono::steady_clock::now() - t0).count(); }
  } _wt{std::chrono::steady_clock::now()};
  size_t sent = 0;
  while (sent < n) {
    ssize_t k = ::send(fd, p + sent,
                       std::min<size_t>(n - sent, kFrameChunk),
                       MSG_NOSIGNAL | MSG_DONTWAIT);
    if (k > 0) {
      sent += k;
      continue;
    }
    if (k < 0 && errno != EINTR && errno != EAGAIN &&
        errno != EWOULDBLOCK)
      return Status::Error(std::string("net: send failed: ") +
                           strerror(errno));
    pollfd pfd{fd, POLLOUT, 0};
    int pr = ::poll(&pfd, 1, 60000);
    if (pr < 0 && errno == EINTR) continue;
    if (pr <= 0) return Status::Error("net: send poll timeout");
  }
  return Status::OK();
}

// One gathered write per frame (header + payload in a single sendmsg):
// on sandboxed kernels every syscall costs tens of microseconds, so the
// frame protocol must not double them.
Status Channel::WriteFrameVec(int fd, uint32_t magic, uint64_t seq,
                              const uint8_t* payload, size_t n) {
  FrameHdr hdr{magic, static_cast<uint32_t>(n), seq};
  struct FT { Channel* c; uint32_t m; bool ok = false;
              ~FT() { if (!ok) {
                  if (getenv("HVD_TPU_NET_TRACE"))
                    fprintf(stderr, "[hvdnet] writeframe FAILED magic=%08x\n", m);
              } } } _ft{this, magic};
  struct iovec iov[2];
  iov[0].iov_base = &hdr;
  iov[0].iov_len = sizeof(hdr);
  iov[1].iov_base = const_cast<uint8_t*>(payload);
  iov[1].iov_len = n;
  struct msghdr msg {};
  msg.msg_iov = iov;
  msg.msg_iovlen = n > 0 ? 2 : 1;
  size_t total = sizeof(hdr) + n;
  size_t sent = 0;
  while (sent < total) {
    ssize_t k = ::sendmsg(fd, &msg, MSG_NOSIGNAL | MSG_DONTWAIT);
    if (k > 0) {
      sent += k;
      if (sent >= total) break;
      // Advance the iovecs past the bytes the kernel took.
      size_t skip = static_cast<size_t>(k);
      while (skip > 0 && msg.msg_iovlen > 0) {
        if (skip >= msg.msg_iov[0].iov_len) {
          skip -= msg.msg_iov[0].iov_len;
          msg.msg_iov++;
          msg.msg_iovlen--;
        } else {
          msg.msg_iov[0].iov_base =
              static_cast<uint8_t*>(msg.msg_iov[0].iov_base) + skip;
          msg.msg_iov[0].iov_len -= skip;
          skip = 0;
        }
      }
      continue;
    }
    if (k < 0 && errno != EINTR && errno != EAGAIN &&
        errno != EWOULDBLOCK)
      return Status::Error(std::string("net: send failed: ") +
                           strerror(errno));
    pollfd pfd{fd, POLLOUT, 0};
    int pr = ::poll(&pfd, 1, 60000);
    if (pr < 0 && errno == EINTR) continue;
    if (pr <= 0) return Status::Error("net: send poll timeout");
  }
  _ft.ok = true;
  return Status::OK();
}

Status Channel::WriteDataFrame(const uint8_t* payload, size_t n,
                               uint64_t seq) {
  std::lock_guard<std::mutex> lk(wmu_);
  int fd = fd_.load();
  if (fd < 0) return Status::Error("net: connection down");
  const NetChaosConfig& chaos = NetChaos();
  if (chaos.enabled()) {
    uint64_t idx = chaos_draws_++;
    if (chaos.delay_ms > 0) usleep(static_cast<int>(chaos.delay_ms * 1000));
    if (chaos.reset_pct > 0 &&
        NetChaosDraw(chaos.seed, net_->rank(), peer_, idx * 4 + 1) * 100.0 <
            chaos.reset_pct) {
      NetCounters().chaos_injected++;
      NET_TRACE("chaos reset seq=%llu", (unsigned long long)seq);
      CloseFd();
      return Status::Error("net: chaos connection reset");
    }
    if (chaos.drop_pct > 0 && NetResilience().enabled &&
        NetChaosDraw(chaos.seed, net_->rank(), peer_, idx * 4 + 2) * 100.0 <
            chaos.drop_pct) {
      // Swallow the frame: the receiver detects the sequence gap on the
      // next frame (or a stall on the last) and forces reconnect-resume.
      NetCounters().chaos_injected++;
      NET_TRACE("chaos drop seq=%llu len=%zu", (unsigned long long)seq, n);
      return Status::OK();
    }
    if (chaos.truncate_pct > 0 && NetResilience().enabled &&
        NetChaosDraw(chaos.seed, net_->rank(), peer_, idx * 4 + 3) * 100.0 <
            chaos.truncate_pct) {
      NetCounters().chaos_injected++;
      FrameHdr hdr{kMagicData, static_cast<uint32_t>(n), seq};
      WriteBytes(fd, reinterpret_cast<const uint8_t*>(&hdr), sizeof(hdr));
      WriteBytes(fd, payload, n / 2);
      CloseFd();
      return Status::Error("net: chaos truncated frame");
    }
  }
  return WriteFrameVec(fd, kMagicData, seq, payload, n);
}

Status Channel::WriteControlFrame(uint32_t magic, uint64_t seq) {
  std::lock_guard<std::mutex> lk(wmu_);
  int fd = fd_.load();
  if (fd < 0) return Status::Error("net: connection down");
  return WriteFrameVec(fd, magic, seq, nullptr, 0);
}

void Channel::SendAbort(uint64_t attempt_epoch) {
  if (fd_.load() < 0 || dead_) return;
  WriteControlFrame(kMagicAbort, attempt_epoch);  // best-effort
}

Status Channel::SendRecoveryFrame(bool verdict, uint64_t epoch,
                                  const std::vector<uint8_t>& payload,
                                  double deadline_s) {
  Deadline dl = Deadline::After(deadline_s);
  const uint32_t magic = verdict ? kMagicVerdict : kMagicReport;
  for (;;) {
    uint64_t ep = epoch_.load();
    Status st;
    {
      std::lock_guard<std::mutex> lk(wmu_);
      int fd = fd_.load();
      if (fd < 0) {
        st = Status::Error("net: connection down");
      } else {
        st = WriteFrameVec(fd, magic, epoch, payload.data(),
                           payload.size());
      }
    }
    if (st.ok()) return st;
    if (dl.expired())
      return Status::Retry("net: recovery frame send deadline to rank " +
                           std::to_string(peer_));
    Status rs = Recover(ep, dl);
    if (!rs.ok()) return rs;
  }
}

Status Channel::AwaitRecoveryFrame(bool verdict, uint64_t epoch,
                                   std::vector<uint8_t>* out,
                                   double deadline_s) {
  Deadline dl = Deadline::After(deadline_s);
  auto last_progress = std::chrono::steady_clock::now();
  for (;;) {
    uint64_t ep = epoch_.load();
    {
      std::lock_guard<std::mutex> lk(smu_);
      uint64_t have = verdict ? verdict_epoch_ : report_epoch_;
      if (have >= epoch) {
        *out = verdict ? verdict_ : report_;
        return Status::OK();
      }
    }
    if (dl.expired())
      return Status::Retry("net: recovery agreement deadline from rank " +
                           std::to_string(peer_));
    Status st;
    if (rmu_.try_lock()) {
      st = PumpOne(kPumpSliceMs);
      rmu_.unlock();
      if (st.type == StatusType::IN_PROGRESS) st = Status::OK();
    } else {
      std::unique_lock<std::mutex> lk(smu_);
      cv_.wait_for(lk, std::chrono::milliseconds(kPumpSliceMs));
      st = Status::OK();
    }
    bool stalled =
        dialer_ &&
        std::chrono::duration<double>(std::chrono::steady_clock::now() -
                                      last_progress)
                .count() *
                1000.0 >
            std::max(NetResilience().probe_ms, 1000.0);
    if (!st.ok() || stalled) {
      Status rs = Recover(ep, dl);
      if (!rs.ok()) return rs;
      last_progress = std::chrono::steady_clock::now();
    }
  }
}

// Reads and dispatches exactly one frame (caller holds rmu_).
constexpr size_t kRdBufCap = 64u << 10;

Status Channel::PumpOne(int slice_ms) {
  int fd = fd_.load();
  if (fd < 0) return Status::Error("net: connection down");
  auto _t0 = std::chrono::steady_clock::now();
  struct ReadT { std::chrono::steady_clock::time_point t0;
                 ~ReadT() { NetCounters().pump_read_us +=
                     std::chrono::duration_cast<std::chrono::microseconds>(
                         std::chrono::steady_clock::now() - t0).count(); }
  } _rt{_t0};
  if (rdbuf_.empty()) rdbuf_.resize(kRdBufCap);
  if (rd_epoch_ != epoch_.load()) {
    // Fresh connection: unparsed leftovers belong to the dead one and
    // the resume already retransmits from our parsed position.
    rd_off_ = rd_len_ = 0;
    rd_epoch_ = epoch_.load();
  }
  auto rd_avail = [&] { return rd_len_ - rd_off_; };
  // One batched refill: pull whatever the socket holds (many small
  // frames per syscall).  wait_ms bounds the poll when the socket is
  // dry; 0 bytes within it -> IN_PROGRESS.
  auto refill = [&](int wait_ms) -> int {
    if (rd_off_ > 0) {
      memmove(rdbuf_.data(), rdbuf_.data() + rd_off_, rd_avail());
      rd_len_ -= rd_off_;
      rd_off_ = 0;
    }
    for (;;) {
      ssize_t k = ::recv(fd, rdbuf_.data() + rd_len_,
                         rdbuf_.size() - rd_len_, MSG_DONTWAIT);
      if (k > 0) {
        rd_len_ += k;
        return 1;
      }
      if (k == 0) return -1;  // peer closed
      if (errno != EINTR && errno != EAGAIN && errno != EWOULDBLOCK)
        return -1;
      if (wait_ms <= 0) return 0;
      auto _p0 = std::chrono::steady_clock::now();
      pollfd pfd{fd, POLLIN, 0};
      int pr = ::poll(&pfd, 1, wait_ms);
      NetCounters().pump_wait_us +=
          std::chrono::duration_cast<std::chrono::microseconds>(
              std::chrono::steady_clock::now() - _p0).count();
      if (pr < 0 && errno == EINTR) return 0;
      if (pr <= 0) return 0;
      wait_ms = 0;  // readable now: one more recv, then report
    }
  };
  const int frame_ms =
      std::max(1000, static_cast<int>(NetResilience().probe_ms));
  if (rd_avail() < sizeof(FrameHdr)) {
    int rc = refill(slice_ms);
    if (rc < 0) return Status::Error("net: peer closed");
    if (rd_avail() == 0) return Status{StatusType::IN_PROGRESS, ""};
    // Partial header: the rest must land within the probe window — a
    // frame stuck half-delivered IS a faulty link.
    auto end = std::chrono::steady_clock::now() +
               std::chrono::milliseconds(frame_ms);
    while (rd_avail() < sizeof(FrameHdr)) {
      int left = static_cast<int>(
          std::chrono::duration_cast<std::chrono::milliseconds>(
              end - std::chrono::steady_clock::now()).count());
      if (left <= 0 || refill(left) < 0) {
        NET_TRACE("pump: lost mid-header avail=%zu", rd_avail());
        return Status::Error("net: connection lost mid-frame");
      }
    }
  }
  FrameHdr hdr;
  memcpy(&hdr, rdbuf_.data() + rd_off_, sizeof(hdr));
  rd_off_ += sizeof(hdr);
  // Consume `len` payload bytes into dst (buffer first, then direct
  // socket reads for the remainder — large payloads never take a
  // staging copy beyond what was already batched).
  auto consume = [&](uint8_t* dst, size_t len) -> bool {
    size_t from_buf = std::min(len, rd_avail());
    if (from_buf > 0) {
      memcpy(dst, rdbuf_.data() + rd_off_, from_buf);
      rd_off_ += from_buf;
    }
    if (len > from_buf) {
      if (!IoAllTimeout(fd, dst + from_buf, len - from_buf, frame_ms,
                        false))
        return false;
    }
    return true;
  };
  if (hdr.magic == kMagicAck) {
    // Byte-cumulative delivery ack: prune the replay tail up to it.
    // Clamp to the bytes actually held — large zero-copy ops advance
    // send/ack byte counters WITHOUT passing through the replay buffer.
    std::lock_guard<std::mutex> lk(smu_);
    if (hdr.seq > acked_bytes_) {
      acked_bytes_ = hdr.seq;
      if (acked_bytes_ > replay_base_) {
        size_t avail = replay_.size() - replay_off_;
        uint64_t want = acked_bytes_ - replay_base_;
        size_t drop = want > avail ? avail : static_cast<size_t>(want);
        replay_off_ += drop;
        replay_base_ = acked_bytes_;
        if (replay_off_ == replay_.size()) {
          replay_.clear();
          replay_off_ = 0;
        } else if (replay_off_ > (8u << 20) &&
                   replay_off_ * 2 >= replay_.size()) {
          replay_.erase(replay_.begin(),
                        replay_.begin() + replay_off_);
          replay_off_ = 0;
        }
      }
    }
    cv_.notify_all();
    return Status::OK();
  }
  if (hdr.magic == kMagicAbort) {
    net_->NoteAbort(hdr.seq);
    cv_.notify_all();
    return Status::OK();
  }
  if (hdr.magic == kMagicReport || hdr.magic == kMagicVerdict) {
    // Agreement frames live OUTSIDE the op stream (no data seq, no op
    // accounting) so an aborted attempt's residue can never displace or
    // impersonate them.  Latest payload per kind wins, fenced by epoch.
    if (hdr.len > 4096)
      return Status::Error("net: oversized recovery frame");
    std::vector<uint8_t> tmp(hdr.len);
    if (hdr.len > 0 && !consume(tmp.data(), hdr.len))
      return Status::Error("net: connection lost mid-frame");
    {
      std::lock_guard<std::mutex> lk(smu_);
      if (hdr.magic == kMagicReport) {
        if (hdr.seq >= report_epoch_) {
          report_epoch_ = hdr.seq;
          report_ = std::move(tmp);
        }
      } else if (hdr.seq >= verdict_epoch_) {
        verdict_epoch_ = hdr.seq;
        verdict_ = std::move(tmp);
      }
      cv_.notify_all();
    }
    return Status::OK();
  }
  if (hdr.magic != kMagicData || hdr.len > (64u << 20)) {
    NET_TRACE("pump: corrupt header magic=%08x len=%u seq=%llu",
              hdr.magic, hdr.len, (unsigned long long)hdr.seq);
    return Status::Error("net: corrupt frame header");
  }
  uint8_t* direct = nullptr;
  {
    std::lock_guard<std::mutex> lk(smu_);
    if (hdr.seq != recv_frames_) {
      NET_TRACE("seq gap: got=%llu want=%llu len=%u",
                (unsigned long long)hdr.seq,
                (unsigned long long)recv_frames_, hdr.len);
      return Status::Error("net: data frame sequence gap (frame dropped "
                           "or stream desynchronized)");
    }
    if (r_active_ && r_total_ - r_off_ >= hdr.len &&
        stash_.size() == stash_off_)
      direct = r_dst_ + r_off_;
  }
  if (direct != nullptr) {
    if (!consume(direct, hdr.len))
      return Status::Error("net: connection lost mid-frame");
    const std::function<void(size_t)>* cb = nullptr;
    size_t progress = 0;
    {
      std::lock_guard<std::mutex> lk(smu_);
      r_off_ += hdr.len;
      recv_bytes_ += hdr.len;
      recv_frames_++;
      if (r_cb_) { cb = r_cb_; progress = r_off_; }
      cv_.notify_all();
    }
    if (cb && *cb) {
    std::lock_guard<std::mutex> cl(cbmu_);
    (*cb)(progress);
  }
    return Status::OK();
  }
  std::vector<uint8_t> tmp(hdr.len);
  if (!consume(tmp.data(), hdr.len))
    return Status::Error("net: connection lost mid-frame");
  const std::function<void(size_t)>* cb = nullptr;
  size_t progress = 0;
  {
    std::lock_guard<std::mutex> lk(smu_);
    stash_.insert(stash_.end(), tmp.begin(), tmp.end());
    recv_bytes_ += hdr.len;
    recv_frames_++;
    // A resume retransmission coalesces several ops' bytes into one
    // frame, which the direct path above rejects (larger than the
    // active op's remainder) — feed the active op from the stash here,
    // or it would starve waiting for bytes that already arrived.
    if (r_active_ && r_off_ < r_total_) {
      size_t avail = stash_.size() - stash_off_;
      size_t take = std::min(avail, r_total_ - r_off_);
      if (take > 0) {
        memcpy(r_dst_ + r_off_, stash_.data() + stash_off_, take);
        stash_off_ += take;
        r_off_ += take;
        if (stash_off_ == stash_.size()) {
          stash_.clear();
          stash_off_ = 0;
        }
        if (r_cb_) { cb = r_cb_; progress = r_off_; }
      }
    }
    cv_.notify_all();
  }
  if (cb && *cb) {
    std::lock_guard<std::mutex> cl(cbmu_);
    (*cb)(progress);
  }
  return Status::OK();
}

// One wait-or-dispatch step for an op loop: become the frame reader if
// nobody else is, otherwise wait for their dispatch to make progress.
Status Channel::Pump(Deadline& dl, bool control, uint64_t /*op_id*/,
                     bool /*for_send*/) {
  if (!control && Aborted())
    return Status::Retry("net: collective attempt aborted by a peer");
  if (rmu_.try_lock()) {
    Status st = PumpOne(kPumpSliceMs);
    rmu_.unlock();
    if (st.type == StatusType::IN_PROGRESS) return Status::OK();
    if (!st.ok())
      NET_TRACE("pump error: %s", st.reason.c_str());
    return st;
  }
  auto _t0 = std::chrono::steady_clock::now();
  std::unique_lock<std::mutex> lk(smu_);
  cv_.wait_for(lk, std::chrono::milliseconds(kPumpWaitMs));
  NetCounters().cvwait_us +=
      std::chrono::duration_cast<std::chrono::microseconds>(
          std::chrono::steady_clock::now() - _t0).count();
  return Status::OK();
}

void Channel::ApplyResume(uint64_t peer_recv_bytes,
                          uint64_t peer_recv_frames,
                          uint64_t peer_recv_ops) {
  (void)peer_recv_ops;
  std::lock_guard<std::mutex> lk(smu_);
  NET_TRACE("apply resume: peer rb=%llu rf=%llu (my sb=%llu sf=%llu "
            "acked=%llu)",
            (unsigned long long)peer_recv_bytes,
            (unsigned long long)peer_recv_frames,
            (unsigned long long)send_bytes_,
            (unsigned long long)send_frames_,
            (unsigned long long)acked_bytes_);
  if (peer_recv_bytes > acked_bytes_) acked_bytes_ = peer_recv_bytes;
  send_frames_ = peer_recv_frames;
  cv_.notify_all();
}

// Retransmit the unacked tail [peer_recv_bytes, send_bytes_) from the
// replay buffer onto a freshly resumed socket.  Called by the resume
// completer BEFORE the fd is adopted, so no other writer can interleave.
bool Channel::RetransmitReplay(int fd, uint64_t peer_recv_bytes,
                               uint64_t peer_recv_frames) {
  // The missing span [peer_recv_bytes, send_bytes_) is covered by two
  // sources: the replay buffer (optimistic small ops) and, beyond it,
  // the still-live caller buffer of an active zero-copy large op —
  // that part is not re-sent here; the op's streaming loop re-runs
  // from the rewound offset once the fresh socket is adopted.
  std::vector<uint8_t> tail;
  {
    std::lock_guard<std::mutex> lk(smu_);
    if (peer_recv_bytes > acked_bytes_) acked_bytes_ = peer_recv_bytes;
    // Prune everything the peer confirms delivered (clamped: large
    // zero-copy ops never passed through the replay buffer).
    if (acked_bytes_ > replay_base_) {
      size_t avail = replay_.size() - replay_off_;
      uint64_t want = acked_bytes_ - replay_base_;
      size_t drop = want > avail ? avail : static_cast<size_t>(want);
      replay_off_ += drop;
      replay_base_ = acked_bytes_;
    }
    const uint64_t replay_end = replay_base_ +
        (replay_.size() - replay_off_);
    if (replay_end > peer_recv_bytes) {
      if (peer_recv_bytes < replay_base_)
        return false;  // bytes no longer held — unrecoverable link
      size_t start = replay_off_ +
          static_cast<size_t>(peer_recv_bytes - replay_base_);
      tail.assign(replay_.begin() + start, replay_.end());
    }
    const uint64_t covered = replay_end > peer_recv_bytes
                                 ? replay_end
                                 : peer_recv_bytes;
    if (send_bytes_ > covered) {
      // Beyond the replay: must be the active zero-copy op's bytes.
      if (!send_active_ || covered < s_op_start_abs_)
        return false;  // unrecoverable (op failed/aborted mid-flight)
      s_off_ = static_cast<size_t>(covered - s_op_start_abs_);
      send_bytes_ = covered;
    }
  }
  uint64_t seq = peer_recv_frames;
  size_t off = 0;
  while (off < tail.size()) {
    size_t k = std::min(tail.size() - off, kFrameChunk);
    if (!WriteFrameVec(fd, kMagicData, seq, tail.data() + off, k).ok())
      return false;
    off += k;
    seq++;
  }
  {
    std::lock_guard<std::mutex> lk(smu_);
    send_frames_ = seq;
    cv_.notify_all();
  }
  NET_TRACE("retransmitted %zu bytes from replay", tail.size());
  return true;
}

void Channel::AdoptResumed(int fd) {
  // Listener-thread half of reconnect-and-resume (this side accepts).
  ResumeWire theirs;
  if (!IoAllTimeout(fd, &theirs, sizeof(theirs), 2000, false)) {
    ::close(fd);
    return;
  }
  ResumeWire mine;
  {
    std::lock_guard<std::mutex> lk(smu_);
    mine = {recv_bytes_, recv_frames_, recv_ops_};
  }
  if (!IoAllTimeout(fd, &mine, sizeof(mine), 2000, true)) {
    ::close(fd);
    return;
  }
  CloseFd();
  ApplyResume(theirs.recv_bytes, theirs.recv_frames, theirs.recv_ops);
  if (!RetransmitReplay(fd, theirs.recv_bytes, theirs.recv_frames)) {
    ::close(fd);
    return;  // the dialer will retry; our op loops keep recovering
  }
  fd_.store(fd);
  epoch_++;
  NetCounters().reconnects++;
  NetCounters().last_recovery_ms.store(SteadyNowMs());
  NET_TRACE("adopt resumed fd=%d epoch=%llu", fd,
            (unsigned long long)epoch_.load());
  std::lock_guard<std::mutex> lk(smu_);
  cv_.notify_all();
}

void Channel::AdoptReset(int fd, uint64_t generation) {
  std::lock_guard<std::mutex> lk(smu_);
  if (pending_fd_ >= 0) ::close(pending_fd_);
  pending_fd_ = fd;
  pending_gen_ = generation;
  cv_.notify_all();
}

Status Channel::Recover(uint64_t failed_epoch, Deadline& dl) {
  std::lock_guard<std::mutex> rec(recover_mu_);
  if (epoch_.load() > failed_epoch && fd_.load() >= 0)
    return Status::OK();  // another thread already recovered this link
  const NetResilienceConfig& rc = NetResilience();
  if (!rc.enabled)
    return Status::Error("net: connection to rank " +
                         std::to_string(peer_) + " failed");
  if (dead_ || NetChaos().blackholed(net_->rank(), peer_)) {
    dead_ = true;
    net_->NoteBadLink(peer_);
    return Status::Retry("net: link to rank " + std::to_string(peer_) +
                         " is dead (reconnect refused)");
  }
  NetCounters().retries++;
  NetCounters().recovering_now++;
  NetCounters().last_recovery_ms.store(SteadyNowMs());
  {
    std::lock_guard<std::mutex> lk(smu_);
    NET_TRACE(
        "recover begin epoch=%llu dialer=%d sact=%d soff=%zu/%zu "
        "acked=%llu ract=%d roff=%zu/%zu stash=%zu sb=%llu rb=%llu",
        (unsigned long long)failed_epoch, dialer_ ? 1 : 0,
        send_active_ ? 1 : 0, s_off_, s_total_,
        (unsigned long long)acked_bytes_, r_active_ ? 1 : 0, r_off_,
        r_total_, stash_.size(),
        (unsigned long long)send_bytes_, (unsigned long long)recv_bytes_);
  }
  CloseFd();
  double budget = std::min(rc.reconnect_s, dl.remaining_s());
  auto end = std::chrono::steady_clock::now() +
             std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                 std::chrono::duration<double>(std::max(budget, 0.2)));
  Status out = Status::Retry("net: reconnect to rank " +
                             std::to_string(peer_) + " exhausted");
  if (dialer_) {
    int attempt = 0;
    while (std::chrono::steady_clock::now() < end) {
      std::string host;
      uint16_t port = 0;
      if (!ParseAddr(net_->table()[peer_], &host, &port)) break;
      int fd = DialOnce(host, port, 2.0);
      if (fd >= 0) {
        HelloWire hello{kMagicHello, net_->rank(), generation_.load()};
        ResumeWire mine;
        {
          std::lock_guard<std::mutex> lk(smu_);
          mine = {recv_bytes_, recv_frames_, recv_ops_};
        }
        ResumeWire theirs;
        if (IoAllTimeout(fd, &hello, sizeof(hello), 2000, true) &&
            IoAllTimeout(fd, &mine, sizeof(mine), 2000, true) &&
            IoAllTimeout(fd, &theirs, sizeof(theirs), 2000, false)) {
          ApplyResume(theirs.recv_bytes, theirs.recv_frames,
                      theirs.recv_ops);
          if (RetransmitReplay(fd, theirs.recv_bytes,
                               theirs.recv_frames)) {
            fd_.store(fd);
            epoch_++;
            NetCounters().reconnects++;
            out = Status::OK();
            break;
          }
        }
        ::close(fd);
      }
      // Bounded jittered backoff (deterministic: the chaos draw keyed by
      // the attempt index doubles as the jitter source).
      double jitter =
          NetChaosDraw(NetChaos().seed + 1, net_->rank(), peer_,
                       0xB0F0 + attempt);
      int backoff_ms = static_cast<int>(
          std::min(50.0 * (1 << std::min(attempt, 4)), 800.0) *
          (0.5 + 0.5 * jitter));
      usleep(backoff_ms * 1000);
      attempt++;
    }
  } else {
    // The lower rank waits for the dialer to come back through the
    // persistent listener (AdoptResumed swaps the socket in).
    std::unique_lock<std::mutex> lk(smu_);
    bool ok = cv_.wait_until(lk, end, [&] {
      return epoch_.load() > failed_epoch && fd_.load() >= 0;
    });
    if (ok) out = Status::OK();
  }
  NetCounters().recovering_now--;
  NetCounters().last_recovery_ms.store(SteadyNowMs());
  NET_TRACE("recover end ok=%d epoch=%llu", out.ok() ? 1 : 0,
            (unsigned long long)epoch_.load());
  if (out.ok()) ReapGraveyard();
  if (!out.ok()) net_->NoteBadLink(peer_);
  return out;
}

namespace {
struct OpTimer {
  std::chrono::steady_clock::time_point t0;
  std::atomic<int64_t>* us;
  std::atomic<int64_t>* ops;
  OpTimer(std::atomic<int64_t>* us_, std::atomic<int64_t>* ops_)
      : t0(std::chrono::steady_clock::now()), us(us_), ops(ops_) {}
  ~OpTimer() {
    *us += std::chrono::duration_cast<std::chrono::microseconds>(
               std::chrono::steady_clock::now() - t0)
               .count();
    (*ops)++;
  }
};
}  // namespace

Status Channel::Send(const uint8_t* buf, size_t n, bool control) {
  OpTimer _t(&NetCounters().send_us, &NetCounters().send_ops);
  if (!NetResilience().enabled) return RawSend(buf, n, control);
  if (n == 0) return Status::OK();
  if (!control && Aborted())
    return Status::Retry("net: collective attempt aborted");
  if (NetChaos().blackholed(net_->rank(), peer_)) {
    Deadline dl = Deadline::After(0.2);
    uint64_t ep = epoch_.load();
    CloseFd();
    return Recover(ep, dl);  // refuses immediately: dead link
  }
  // Small ops complete OPTIMISTICALLY: their bytes are copied into the
  // replay buffer as they stream, so the ack round-trip (which
  // dominates small ring steps) leaves the critical path and a resume
  // retransmits from the replay tail.  Large ops stream zero-copy and
  // ack-wait at the end — the RTT is amortized by the transfer itself,
  // and the replay memcpy would be a per-byte tax; their resume rewinds
  // s_off_ into the still-live caller buffer instead.
  const bool optimistic = n <= kOptimisticMax;
  {
    std::lock_guard<std::mutex> lk(smu_);
    send_active_ = true;
    s_buf_ = buf;
    s_total_ = n;
    s_off_ = 0;
    s_op_start_abs_ = send_bytes_;
  }
  Deadline dl =
      Deadline::After(control ? 0.0 : NetResilience().op_deadline_s);
  bool recovered = false;
  auto fail = [&](Status st) {
    std::lock_guard<std::mutex> lk(smu_);
    send_active_ = false;
    s_buf_ = nullptr;
    return st;
  };
  const uint64_t op_start = [&] {
    std::lock_guard<std::mutex> lk(smu_);
    return s_op_start_abs_;
  }();
  const uint64_t op_end = op_start + n;
  bool done = false;
  while (!done) {
    // Phase 1: stream frames from the current (possibly rewound) offset.
    for (;;) {
      size_t off;
      uint64_t ep = epoch_.load();
      size_t unacked;
      {
        std::lock_guard<std::mutex> lk(smu_);
        off = s_off_;
        unacked = static_cast<size_t>(send_bytes_ - acked_bytes_);
      }
      if (off >= n) break;
      if (!control && Aborted())
        return fail(Status::Retry("net: collective attempt aborted"));
      if (dl.expired())
        return fail(Status::Retry("net: send deadline exceeded to rank " +
                                  std::to_string(peer_)));
      if (optimistic && unacked >= kReplayCap) {
        // Backpressure: drain acks before streaming further.
        Status st = Pump(dl, control, 0, true);
        if (st.retryable()) return fail(st);
        if (!st.ok()) {
          Status rs = Recover(ep, dl);
          if (!rs.ok()) return fail(rs);
          recovered = true;
        }
        continue;
      }
      // Opportunistically drain pending ACKs (zero-timeout pump): the
      // replay tail must shrink in steady state, not at the cap.
      if (rmu_.try_lock()) {
        for (int i = 0; i < 8; ++i) {
          Status ps = PumpOne(0);
          if (ps.type == StatusType::IN_PROGRESS || !ps.ok()) break;
        }
        rmu_.unlock();
        // A reader that lost the rmu_ race to this drain may be asleep
        // on the cv with nothing left to notify it — wake it to retry.
        std::lock_guard<std::mutex> lk(smu_);
        cv_.notify_all();
      }
      size_t k = std::min(n - off, kFrameChunk);
      uint64_t seq;
      {
        std::lock_guard<std::mutex> lk(smu_);
        seq = send_frames_;
      }
      Status st = WriteDataFrame(buf + off, k, seq);
      if (st.ok()) {
        std::lock_guard<std::mutex> lk(smu_);
        if (epoch_.load() == ep) {
          if (optimistic) {
            if (replay_off_ == replay_.size()) {
              // Re-anchor an empty buffer: a preceding zero-copy large
              // op advanced the byte counters past replay_base_.
              replay_.clear();
              replay_off_ = 0;
              replay_base_ = send_bytes_;
            }
            replay_.insert(replay_.end(), buf + off, buf + off + k);
          }
          s_off_ = off + k;
          send_bytes_ += k;
          send_frames_++;
          continue;
        }
        // An adoption raced the write: the frame landed on a dead
        // socket with a stale seq — the resume already handled the
        // unacked span, so just retry this chunk on the fresh link.
        continue;
      }
      Status rs = Recover(ep, dl);
      if (!rs.ok()) return fail(rs);
      recovered = true;
    }
    if (optimistic) {
      done = true;
      break;
    }
    // Phase 2 (large ops): wait until the receiver confirms every byte —
    // only then may the caller reuse the buffer.  A resume rewinds
    // s_off_ into it and phase 1 re-runs.
    auto last_progress = std::chrono::steady_clock::now();
    uint64_t last_acked = 0;
    for (;;) {
      uint64_t ep = epoch_.load();
      bool rewound = false;
      {
        std::lock_guard<std::mutex> lk(smu_);
        if (acked_bytes_ >= op_end) {
          done = true;
          break;
        }
        if (s_off_ < s_total_) rewound = true;
        if (acked_bytes_ != last_acked) {
          last_acked = acked_bytes_;
          last_progress = std::chrono::steady_clock::now();
        }
      }
      if (rewound) break;  // back to phase 1
      if (!control && Aborted())
        return fail(Status::Retry("net: collective attempt aborted"));
      if (dl.expired())
        return fail(Status::Retry("net: ack deadline exceeded from rank " +
                                  std::to_string(peer_)));
      Status st = Pump(dl, control, 0, true);
      if (st.retryable()) return fail(st);
      double probe = NetResilience().probe_ms;
      if (control) probe = std::max(probe * 10.0, 2000.0);
      bool stalled =
          dialer_ &&
          std::chrono::duration<double>(
              std::chrono::steady_clock::now() - last_progress)
                  .count() *
                  1000.0 >
              probe;
      if (!st.ok() || stalled) {
        Status rs = Recover(ep, dl);
        if (!rs.ok()) return fail(rs);
        recovered = true;
        last_progress = std::chrono::steady_clock::now();
      }
    }
  }
  {
    std::lock_guard<std::mutex> lk(smu_);
    send_active_ = false;
    s_buf_ = nullptr;
  }
  if (recovered) NetCounters().resets_avoided++;
  return Status::OK();
}

Status Channel::Recv(uint8_t* dst, size_t n,
                     const std::function<void(size_t)>& on_progress,
                     bool control, double deadline_s) {
  OpTimer _t(&NetCounters().recv_us, &NetCounters().recv_ops);
  if (!NetResilience().enabled) return RawRecv(dst, n, on_progress, control);
  if (n == 0) return Status::OK();
  if (!control && Aborted())
    return Status::Retry("net: collective attempt aborted");
  if (NetChaos().blackholed(net_->rank(), peer_)) {
    Deadline dl = Deadline::After(0.2);
    uint64_t ep = epoch_.load();
    CloseFd();
    return Recover(ep, dl);
  }
  NET_TRACE("recv post n=%zu ctl=%d rops=%llu", n, control ? 1 : 0,
            (unsigned long long)recv_ops_);
  size_t drained = 0;
  {
    std::lock_guard<std::mutex> lk(smu_);
    size_t avail = stash_.size() - stash_off_;
    if (avail > 0) {
      drained = std::min(avail, n);
      memcpy(dst, stash_.data() + stash_off_, drained);
      stash_off_ += drained;
      if (stash_off_ == stash_.size()) {
        stash_.clear();
        stash_off_ = 0;
      } else if (stash_off_ > (1u << 20) &&
                 stash_off_ * 2 >= stash_.size()) {
        stash_.erase(stash_.begin(), stash_.begin() + stash_off_);
        stash_off_ = 0;
      }
    }
    if (drained < n) {
      r_active_ = true;
      r_dst_ = dst;
      r_total_ = n;
      r_off_ = drained;
      r_cb_ = on_progress ? &on_progress : nullptr;
    } else {
      recv_ops_++;
    }
  }
  if (drained >= n) {
    if (on_progress) {
      std::lock_guard<std::mutex> cl(cbmu_);
      on_progress(n);
    }
    uint64_t rb = 0;
    {
      std::lock_guard<std::mutex> lk(smu_);
      if (n >= kOptimisticMax ||
          recv_bytes_ - ack_sent_bytes_ >= kAckEveryBytes) {
        rb = recv_bytes_;
        ack_sent_bytes_ = rb;
      }
    }
    if (rb != 0 && !WriteControlFrame(kMagicAck, rb).ok()) CloseFd();
    return Status::OK();
  }
  if (drained > 0 && on_progress) {
    std::lock_guard<std::mutex> cl(cbmu_);
    on_progress(drained);
  }
  Deadline dl = Deadline::After(control ? deadline_s
                                        : NetResilience().op_deadline_s);
  bool recovered = false;
  auto fail = [&](Status st) {
    std::lock_guard<std::mutex> lk(smu_);
    r_active_ = false;
    r_cb_ = nullptr;
    return st;
  };
  auto last_progress = std::chrono::steady_clock::now();
  size_t last_off = drained;
  for (;;) {
    uint64_t ep = epoch_.load();
    {
      std::lock_guard<std::mutex> lk(smu_);
      if (r_off_ >= r_total_) break;
      if (r_off_ != last_off) {
        last_off = r_off_;
        last_progress = std::chrono::steady_clock::now();
      }
    }
    if (!control && Aborted())
      return fail(Status::Retry("net: collective attempt aborted"));
    if (dl.expired())
      return fail(Status::Retry("net: recv deadline exceeded from rank " +
                                std::to_string(peer_)));
    Status st = Pump(dl, control, 0, false);
    if (st.retryable()) return fail(st);
    // Same dialer-only probe rule as the ack wait (see Send).
    double probe = NetResilience().probe_ms;
    if (control) probe = std::max(probe * 10.0, 2000.0);
    bool stalled =
        dialer_ &&
        std::chrono::duration<double>(std::chrono::steady_clock::now() -
                                      last_progress)
                .count() *
                1000.0 >
            probe;
    if (!st.ok() || stalled) {
      Status rs = Recover(ep, dl);
      if (!rs.ok()) return fail(rs);
      recovered = true;
      last_progress = std::chrono::steady_clock::now();
    }
  }
  uint64_t rb = 0;
  {
    std::lock_guard<std::mutex> lk(smu_);
    r_active_ = false;
    r_cb_ = nullptr;
    recv_ops_++;
    if (n >= kOptimisticMax ||
        recv_bytes_ - ack_sent_bytes_ >= kAckEveryBytes) {
      rb = recv_bytes_;
      ack_sent_bytes_ = rb;
    }
    NET_TRACE("recv done rb=%llu n=%zu",
              (unsigned long long)recv_bytes_, n);
  }
  // A lost ACK is recovered by the resume handshake (the peer learns
  // recv_bytes_ from it), so a failed write only needs to break the
  // link loudly, not fail this completed op.
  if (rb != 0 && !WriteControlFrame(kMagicAck, rb).ok()) CloseFd();
  if (recovered) NetCounters().resets_avoided++;
  return Status::OK();
}

Status Channel::SendMsg(const std::vector<uint8_t>& payload,
                        bool control) {
  // One op (and one gathered frame) for len+payload: control messages
  // are small and flow every negotiation cycle — two ops apiece doubled
  // the control plane's syscall count.  The receiver still posts two
  // recvs, but both parse out of the batched read buffer.
  std::vector<uint8_t> wire(4 + payload.size());
  uint32_t len = static_cast<uint32_t>(payload.size());
  memcpy(wire.data(), &len, 4);
  if (!payload.empty())
    memcpy(wire.data() + 4, payload.data(), payload.size());
  return Send(wire.data(), wire.size(), control);
}

Status Channel::RecvMsg(std::vector<uint8_t>& payload, bool control,
                        double deadline_s) {
  // deadline_s > 0 bounds a control recv (the ring-recovery agreement is
  // a bounded rendezvous, unlike the open-ended negotiation wait).
  auto start = std::chrono::steady_clock::now();
  uint32_t len = 0;
  Status st =
      Recv(reinterpret_cast<uint8_t*>(&len), 4, nullptr, control,
           deadline_s);
  if (!st.ok()) return st;
  if (len > (256u << 20))
    return Status::Error("net: oversized control message");
  payload.resize(len);
  if (len == 0) return Status::OK();
  double remaining = 0.0;
  if (deadline_s > 0) {
    remaining = deadline_s -
                std::chrono::duration<double>(
                    std::chrono::steady_clock::now() - start)
                    .count();
    if (remaining <= 0.05) remaining = 0.05;
  }
  return Recv(payload.data(), len, nullptr, control, remaining);
}

Status Channel::Reset(uint64_t generation, double deadline_s) {
  std::lock_guard<std::mutex> rec(recover_mu_);
  CloseFd();
  {
    std::lock_guard<std::mutex> lk(smu_);
    send_active_ = false;
    s_buf_ = nullptr;
    s_total_ = s_off_ = 0;
    send_bytes_ = send_frames_ = acked_bytes_ = 0;
    replay_.clear();
    replay_off_ = 0;
    replay_base_ = 0;
    r_active_ = false;
    r_dst_ = nullptr;
    r_cb_ = nullptr;
    r_total_ = r_off_ = 0;
    recv_ops_ = recv_bytes_ = recv_frames_ = 0;
    ack_sent_bytes_ = 0;
    stash_.clear();
    stash_off_ = 0;
  }
  generation_.store(generation);
  auto end = std::chrono::steady_clock::now() +
             std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                 std::chrono::duration<double>(deadline_s));
  if (dialer_) {
    while (std::chrono::steady_clock::now() < end) {
      std::string host;
      uint16_t port = 0;
      if (!ParseAddr(net_->table()[peer_], &host, &port)) break;
      int fd = DialOnce(host, port, 2.0);
      if (fd >= 0) {
        HelloWire hello{kMagicHelloReset, net_->rank(), generation};
        if (IoAllTimeout(fd, &hello, sizeof(hello), 2000, true)) {
          fd_.store(fd);
          epoch_++;
          std::lock_guard<std::mutex> lk(smu_);
          cv_.notify_all();
          return Status::OK();
        }
        ::close(fd);
      }
      usleep(50000);
    }
  } else {
    std::unique_lock<std::mutex> lk(smu_);
    bool ok = cv_.wait_until(lk, end, [&] {
      return pending_fd_ >= 0 && pending_gen_ >= generation;
    });
    if (ok) {
      int fd = pending_fd_;
      pending_fd_ = -1;
      fd_.store(fd);
      epoch_++;
      cv_.notify_all();
      return Status::OK();
    }
  }
  return Status::Error("net: mesh reset could not re-link rank " +
                       std::to_string(peer_));
}

// --- raw (pre-resilience) wire protocol ------------------------------------

Status Channel::RawSend(const uint8_t* buf, size_t n, bool control) {
  int fd = fd_.load();
  if (fd < 0) return Status::Error("net: connection down");
  const NetChaosConfig& chaos = NetChaos();
  size_t sent = 0;
  while (sent < n) {
    if (chaos.enabled()) {
      uint64_t idx;
      {
        std::lock_guard<std::mutex> lk(wmu_);
        idx = chaos_draws_++;
      }
      if (chaos.delay_ms > 0)
        usleep(static_cast<int>(chaos.delay_ms * 1000));
      if (chaos.reset_pct > 0 &&
          NetChaosDraw(chaos.seed, net_->rank(), peer_, idx * 4 + 1) *
                  100.0 <
              chaos.reset_pct) {
        NetCounters().chaos_injected++;
        CloseFd();
        return Status::Error("net: chaos connection reset");
      }
    }
    pollfd pfd{fd, POLLOUT, 0};
    int pr = ::poll(&pfd, 1, control ? -1 : 60000);
    if (pr < 0 && errno == EINTR) continue;
    if (pr <= 0) return Status::Error("collective send timeout");
    ssize_t k = ::send(fd, buf + sent,
                       std::min<size_t>(n - sent, kFrameChunk),
                       MSG_NOSIGNAL | MSG_DONTWAIT);
    if (k < 0) {
      if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK)
        continue;
      return Status::Error("send failed in collective");
    }
    sent += k;
  }
  return Status::OK();
}

Status Channel::RawRecv(uint8_t* dst, size_t n,
                        const std::function<void(size_t)>& on_progress,
                        bool control) {
  int fd = fd_.load();
  if (fd < 0) return Status::Error("net: connection down");
  size_t received = 0;
  while (received < n) {
    pollfd pfd{fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, control ? -1 : 60000);
    if (pr < 0 && errno == EINTR) continue;
    if (pr <= 0) return Status::Error("collective recv timeout");
    ssize_t k = ::recv(fd, dst + received,
                       std::min<size_t>(n - received, kFrameChunk),
                       MSG_DONTWAIT);
    if (k == 0) return Status::Aborted("peer closed during collective");
    if (k < 0) {
      if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK)
        continue;
      return Status::Error("recv failed in collective");
    }
    received += k;
    if (on_progress) on_progress(received);
  }
  return Status::OK();
}

// ---------------------------------------------------------------------------
// Network
// ---------------------------------------------------------------------------

std::unique_ptr<Network> Network::Connect(int rank, int size,
                                          const std::string& coord_addr,
                                          Status* status) {
  std::string coord_host;
  uint16_t coord_port = 0;
  if (!ParseAddr(coord_addr, &coord_host, &coord_port)) {
    *status = Status::InvalidArgument("bad coordinator address " + coord_addr);
    return nullptr;
  }
  std::unique_ptr<Network> net(new Network(rank, size));

  // Every rank listens; rank 0 on the well-known port.  The listener
  // outlives the handshake: reconnect-and-resume re-enters through it.
  uint16_t my_port = 0;
  int listen_fd = Listen(rank == 0 ? coord_port : 0, &my_port);
  if (listen_fd < 0) {
    *status = Status::Error("cannot bind listener");
    return nullptr;
  }
  net->listen_fd_ = listen_fd;

  if (rank == 0) {
    // Accept size-1 workers; each announces {rank, host, port}.
    std::vector<std::string> table(size);
    table[0] = LocalHostname() + ":" + std::to_string(my_port);
    for (int i = 1; i < size; ++i) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        *status = Status::Error("accept failed");
        return nullptr;
      }
      SetNoDelay(fd);
      auto sock = std::make_unique<Socket>(fd);
      int32_t peer_rank;
      if (!sock->RecvAll(&peer_rank, 4).ok()) {
        *status = Status::Error("handshake recv failed");
        return nullptr;
      }
      std::vector<uint8_t> addr_buf;
      sock->RecvFrame(addr_buf);
      table[peer_rank].assign(addr_buf.begin(), addr_buf.end());
      net->peers_[peer_rank] = std::move(sock);
    }
    // Broadcast the address table.
    std::vector<uint8_t> blob;
    for (int i = 0; i < size; ++i) {
      uint32_t n = table[i].size();
      const uint8_t* np = reinterpret_cast<const uint8_t*>(&n);
      blob.insert(blob.end(), np, np + 4);
      blob.insert(blob.end(), table[i].begin(), table[i].end());
    }
    for (int i = 1; i < size; ++i) net->peers_[i]->SendFrame(blob);
    net->table_ = table;
    net->SetupShm(table, coord_addr);
  } else {
    int fd = DialRetry(coord_host, coord_port);
    if (fd < 0) {
      *status = Status::Error("cannot reach coordinator at " + coord_addr);
      return nullptr;
    }
    auto sock = std::make_unique<Socket>(fd);
    int32_t r32 = rank;
    sock->SendAll(&r32, 4);
    std::string my_addr = LocalHostname() + ":" + std::to_string(my_port);
    sock->SendFrame(std::vector<uint8_t>(my_addr.begin(), my_addr.end()));
    std::vector<uint8_t> blob;
    if (!sock->RecvFrame(blob).ok()) {
      *status = Status::Error("address table recv failed");
      return nullptr;
    }
    net->peers_[0] = std::move(sock);
    // Parse table.
    std::vector<std::string> table(size);
    size_t off = 0;
    for (int i = 0; i < size; ++i) {
      uint32_t n;
      memcpy(&n, blob.data() + off, 4);
      off += 4;
      table[i].assign(reinterpret_cast<const char*>(blob.data() + off), n);
      off += n;
    }
    // Full mesh: connect to all lower ranks (>0), accept from higher ranks.
    for (int peer = 1; peer < rank; ++peer) {
      std::string host;
      uint16_t port;
      ParseAddr(table[peer], &host, &port);
      int pfd = DialRetry(host, port);
      if (pfd < 0) {
        *status = Status::Error("cannot reach peer " + table[peer]);
        return nullptr;
      }
      auto psock = std::make_unique<Socket>(pfd);
      int32_t me = rank;
      psock->SendAll(&me, 4);
      net->peers_[peer] = std::move(psock);
    }
    for (int peer = rank + 1; peer < size; ++peer) {
      int pfd = ::accept(listen_fd, nullptr, nullptr);
      if (pfd < 0) {
        *status = Status::Error("peer accept failed");
        return nullptr;
      }
      SetNoDelay(pfd);
      auto psock = std::make_unique<Socket>(pfd);
      int32_t peer_rank;
      psock->RecvAll(&peer_rank, 4);
      net->peers_[peer_rank] = std::move(psock);
    }
    net->table_ = table;
    net->SetupShm(table, coord_addr);
  }
  net->MakeChannels();
  if (NetResilience().enabled) {
    net->listener_ = std::thread([n = net.get()] { n->ListenerLoop(); });
  }
  *status = Status::OK();
  return net;
}

void Network::MakeChannels() {
  channels_.resize(size_);
  for (int r = 0; r < size_; ++r) {
    int fd = peers_[r] ? peers_[r]->release() : -1;
    channels_[r] = std::make_unique<Channel>(this, r, fd);
  }
  peers_.clear();
  ring_order_.resize(size_);
  for (int i = 0; i < size_; ++i) ring_order_[i] = i;
}

Network::~Network() {
  listener_stop_ = true;
  if (listener_.joinable()) listener_.join();
  if (listen_fd_ >= 0) ::close(listen_fd_);
}

void Network::ListenerLoop() {
  // Reconnect router: a dialer coming back (same generation → resume the
  // in-flight transfers) or the fleet re-forming the mesh after a ring
  // renegotiation (higher generation → fresh link, zeroed state).
  while (!listener_stop_.load()) {
    pollfd pfd{listen_fd_, POLLIN, 0};
    int pr = ::poll(&pfd, 1, 200);
    if (pr <= 0) continue;
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) continue;
    SetNoDelay(fd);
    HelloWire hello{};
    if (!IoAllTimeout(fd, &hello, sizeof(hello), 2000, false) ||
        (hello.magic != kMagicHello && hello.magic != kMagicHelloReset) ||
        hello.rank < 0 || hello.rank >= size_ ||
        channels_.size() != static_cast<size_t>(size_)) {
      ::close(fd);
      continue;
    }
    Channel* ch = channels_[hello.rank].get();
    if (NetChaos().blackholed(rank_, hello.rank)) {
      ::close(fd);  // the drill: this pair stays unreachable
      continue;
    }
    if (hello.magic == kMagicHelloReset) {
      ch->AdoptReset(fd, hello.generation);
    } else {
      ch->AdoptResumed(fd);
    }
  }
}

std::vector<int> Network::ring_order() const {
  std::lock_guard<std::mutex> lk(ring_mu_);
  return ring_order_;
}

void Network::set_ring_order(const std::vector<int>& order) {
  std::lock_guard<std::mutex> lk(ring_mu_);
  ring_order_ = order;
}

void Network::BroadcastAbort() {
  uint64_t epoch = attempt_epoch_.load();
  NoteAbort(epoch);  // unblock our own op threads too
  for (int r = 0; r < size_; ++r) {
    if (r == rank_) continue;
    if (channels_[r]) channels_[r]->SendAbort(epoch);
  }
}

void Network::NoteBadLink(int peer) {
  std::lock_guard<std::mutex> lk(bad_mu_);
  bad_links_.insert(peer);
  last_bad_peer_ = peer;
}

std::vector<int> Network::bad_links() const {
  std::lock_guard<std::mutex> lk(bad_mu_);
  return std::vector<int>(bad_links_.begin(), bad_links_.end());
}

int Network::TakeLastBadPeer() {
  std::lock_guard<std::mutex> lk(bad_mu_);
  int p = last_bad_peer_;
  last_bad_peer_ = -1;
  return p;
}

Status Network::MeshReset(double deadline_s) {
  uint64_t gen = ++generation_;
  Status out = Status::OK();
  std::set<int> bad;
  {
    std::lock_guard<std::mutex> lk(bad_mu_);
    bad = bad_links_;
  }
  for (int r = 0; r < size_; ++r) {
    if (r == rank_ || !channels_[r]) continue;
    if (bad.count(r)) continue;  // a proven-dead link stays down; the
                                 // renegotiated ring routes around it
    Status st = channels_[r]->Reset(gen, deadline_s);
    if (!st.ok()) out = st;
  }
  return out;
}

void Network::SetupShm(const std::vector<std::string>& table,
                       const std::string& tag) {
  // A rank with HVD_TPU_DISABLE_SHM still runs the handshake bytes (as
  // "not participating") — a unilateral early-return would desynchronize
  // the shared data sockets for peers that do participate.
  const bool disabled = getenv("HVD_TPU_DISABLE_SHM") != nullptr;
  std::string my_host, host;
  uint16_t port;
  if (!ParseAddr(table[rank_], &my_host, &port)) return;
  std::vector<int> local;
  for (int r = 0; r < size_; ++r) {
    if (r != rank_ && ParseAddr(table[r], &host, &port) &&
        host == my_host) {
      local.push_back(r);
    }
  }
  if (local.empty()) return;

  // Segment names are scoped to this job by the coordinator address
  // (unique per launch/elastic round).
  std::string base = "/hvt_";
  for (char c : tag)
    base += (isalnum(static_cast<unsigned char>(c)) ? c : '_');

  // Phase 1: create all outgoing segments, then confirm creation with
  // each peer BEFORE anyone opens — opening only after the peer's create
  // is confirmed means a stale segment from a crashed job (which Create
  // unlinks and replaces) can never be the object the consumer maps.
  std::vector<std::unique_ptr<ShmChannel>> tx(size_);
  if (!disabled) {
    for (int r : local) {
      tx[r] = ShmChannel::Create(base + "_" + std::to_string(rank_) +
                                 "_" + std::to_string(r));
    }
  }
  for (int r : local) {
    uint8_t my_created = tx[r] != nullptr ? 1 : 0;
    uint8_t peer_created = 0;
    if (!peers_[r]->SendAll(&my_created, 1).ok() ||
        !peers_[r]->RecvAll(&peer_created, 1).ok()) {
      if (tx[r]) tx[r]->Unlink();
      tx[r].reset();
      continue;
    }
    // Phase 2: open the peer's (fresh) segment, report back.
    std::unique_ptr<ShmChannel> rx;
    if (!disabled && peer_created) {
      rx = ShmChannel::Open(base + "_" + std::to_string(r) + "_" +
                            std::to_string(rank_));
    }
    uint8_t my_rx_ok = rx != nullptr ? 1 : 0;
    uint8_t peer_rx_ok = 0;
    bool hs_ok = peers_[r]->SendAll(&my_rx_ok, 1).ok() &&
                 peers_[r]->RecvAll(&peer_rx_ok, 1).ok();
    // Phase 3: cross-memory-attach capability — my consumer end probes a
    // direct read of the producer's memory; the producer publishes
    // descriptors (zero staging copies) only if my probe succeeded.
    uint8_t my_cma = (hs_ok && rx != nullptr && rx->ProbeCma()) ? 1 : 0;
    uint8_t peer_cma = 0;
    if (hs_ok) {
      hs_ok = peers_[r]->SendAll(&my_cma, 1).ok() &&
              peers_[r]->RecvAll(&peer_cma, 1).ok();
    }
    if (tx[r]) {
      tx[r]->Unlink();  // both ends mapped (or unused): never leak
      if (hs_ok && peer_rx_ok) {
        if (peer_cma) tx[r]->EnableRefs();
        shm_tx_[r] = std::move(tx[r]);
      } else {
        tx[r].reset();
      }
    }
    if (hs_ok && my_rx_ok) shm_rx_[r] = std::move(rx);
  }
}

}  // namespace hvdtpu
