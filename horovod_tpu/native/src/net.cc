#include "net.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <net/if.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/ioctl.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstdio>
#include <algorithm>
#include <chrono>
#include <cstdlib>
#include <cstring>

namespace hvdtpu {

Socket::~Socket() {
  if (fd_ >= 0) ::close(fd_);
}

Status Socket::SendAll(const void* data, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  while (n > 0) {
    ssize_t k = ::send(fd_, p, n, MSG_NOSIGNAL);
    if (k < 0) {
      if (errno == EINTR) continue;
      return Status::Error(std::string("send: ") + strerror(errno));
    }
    p += k;
    n -= k;
  }
  return Status::OK();
}

Status Socket::RecvAll(void* data, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(data);
  while (n > 0) {
    ssize_t k = ::recv(fd_, p, n, 0);
    if (k < 0) {
      if (errno == EINTR) continue;
      return Status::Error(std::string("recv: ") + strerror(errno));
    }
    if (k == 0) return Status::Aborted("peer closed connection");
    p += k;
    n -= k;
  }
  return Status::OK();
}

Status Socket::SendFrame(const std::vector<uint8_t>& payload) {
  uint32_t len = static_cast<uint32_t>(payload.size());
  Status s = SendAll(&len, 4);
  if (!s.ok()) return s;
  return SendAll(payload.data(), payload.size());
}

Status Socket::RecvFrame(std::vector<uint8_t>& payload) {
  uint32_t len = 0;
  Status s = RecvAll(&len, 4);
  if (!s.ok()) return s;
  payload.resize(len);
  return RecvAll(payload.data(), len);
}

namespace {

void SetNoDelay(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  // Large kernel buffers keep the full-duplex ring streaming instead of
  // stalling on flow control (both directions carry MBs per step).
  // (No socket-level SO_SNDTIMEO/RCVTIMEO: control-plane waits — e.g. a
  // worker blocking on the address table while slow peers start up — are
  // legitimately longer than any collective timeout; the collective paths
  // bound their own waits with poll().)
  int bufsz = 4 << 20;
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &bufsz, sizeof(bufsz));
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bufsz, sizeof(bufsz));
}

int Listen(uint16_t port, uint16_t* bound_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 64) < 0) {
    ::close(fd);
    return -1;
  }
  socklen_t len = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  *bound_port = ntohs(addr.sin_port);
  return fd;
}

// Non-blocking connect bounded by timeout_s; on success the socket is
// returned in blocking mode.  Bounding connect() itself matters: against a
// black-holed address a blocking connect sits in the kernel SYN retry for
// minutes, which would blow any caller-side deadline.
static int ConnectTimeout(const addrinfo* res, double timeout_s) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  int rc = ::connect(fd, res->ai_addr, res->ai_addrlen);
  if (rc != 0) {
    if (errno != EINPROGRESS) {
      ::close(fd);
      return -1;
    }
    pollfd pfd{fd, POLLOUT, 0};
    int ms = static_cast<int>(timeout_s * 1000);
    if (poll(&pfd, 1, ms > 0 ? ms : 1) != 1) {
      ::close(fd);
      return -1;
    }
    int err = 0;
    socklen_t len = sizeof(err);
    if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len) != 0 || err != 0) {
      ::close(fd);
      return -1;
    }
  }
  fcntl(fd, F_SETFL, flags);  // back to blocking for the frame protocol
  return fd;
}

int DialRetry(const std::string& host, uint16_t port, int attempts = 600) {
  // --start-timeout: bound how long workers wait for the coordinator (and
  // for peer-mesh dials during startup) — reference horovodrun
  // --start-timeout; default stays ~60 s.  Deadline-based: retries plus
  // DNS/connect time all count against the budget.
  double timeout_s = attempts * 0.1;
  const char* st = getenv("HVD_TPU_START_TIMEOUT");
  if (!st) st = getenv("HOROVOD_START_TIMEOUT");
  if (st && atof(st) > 0) timeout_s = atof(st);
  auto deadline = std::chrono::steady_clock::now() +
      std::chrono::duration<double>(timeout_s);
  while (std::chrono::steady_clock::now() < deadline) {
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    char portstr[16];
    snprintf(portstr, sizeof(portstr), "%u", port);
    if (getaddrinfo(host.c_str(), portstr, &hints, &res) != 0 || !res) {
      usleep(100000);
      continue;
    }
    double remaining = std::chrono::duration<double>(
        deadline - std::chrono::steady_clock::now()).count();
    int fd = ConnectTimeout(res, std::min(remaining, 2.0));
    freeaddrinfo(res);
    if (fd >= 0) {
      SetNoDelay(fd);
      return fd;
    }
    usleep(100000);  // coordinator may not be up yet; retry until deadline
  }
  return -1;
}

bool ParseAddr(const std::string& addr, std::string* host, uint16_t* port) {
  auto pos = addr.rfind(':');
  if (pos == std::string::npos) return false;
  *host = addr.substr(0, pos);
  *port = static_cast<uint16_t>(atoi(addr.c_str() + pos + 1));
  return true;
}

std::string LocalHostname() {
  // HVD_TPU_IFACE / HOROVOD_GLOO_IFACE: advertise this interface's IPv4
  // to peers instead of the hostname (reference --network-interface /
  // HOROVOD_GLOO_IFACE semantics — on multi-NIC hosts gethostname() may
  // resolve to an address peers cannot route to).
  const char* ifn = getenv("HVD_TPU_IFACE");
  if (!ifn || !*ifn) ifn = getenv("HOROVOD_GLOO_IFACE");
  if (ifn && *ifn) {
    int fd = ::socket(AF_INET, SOCK_DGRAM, 0);
    if (fd >= 0) {
      ifreq ifr{};
      strncpy(ifr.ifr_name, ifn, IFNAMSIZ - 1);
      bool ok = ioctl(fd, SIOCGIFADDR, &ifr) == 0;
      ::close(fd);
      if (ok) {
        auto* sin = reinterpret_cast<sockaddr_in*>(&ifr.ifr_addr);
        char abuf[INET_ADDRSTRLEN];
        if (inet_ntop(AF_INET, &sin->sin_addr, abuf, sizeof(abuf))) {
          return abuf;
        }
      }
    }
  }
  char buf[256];
  if (gethostname(buf, sizeof(buf)) == 0) return buf;
  return "127.0.0.1";
}

}  // namespace

std::unique_ptr<Network> Network::Connect(int rank, int size,
                                          const std::string& coord_addr,
                                          Status* status) {
  std::string coord_host;
  uint16_t coord_port = 0;
  if (!ParseAddr(coord_addr, &coord_host, &coord_port)) {
    *status = Status::InvalidArgument("bad coordinator address " + coord_addr);
    return nullptr;
  }
  std::unique_ptr<Network> net(new Network(rank, size));

  // Every rank listens; rank 0 on the well-known port.
  uint16_t my_port = 0;
  int listen_fd = Listen(rank == 0 ? coord_port : 0, &my_port);
  if (listen_fd < 0) {
    *status = Status::Error("cannot bind listener");
    return nullptr;
  }

  if (rank == 0) {
    // Accept size-1 workers; each announces {rank, host, port}.
    std::vector<std::string> table(size);
    table[0] = LocalHostname() + ":" + std::to_string(my_port);
    for (int i = 1; i < size; ++i) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        *status = Status::Error("accept failed");
        return nullptr;
      }
      SetNoDelay(fd);
      auto sock = std::make_unique<Socket>(fd);
      int32_t peer_rank;
      if (!sock->RecvAll(&peer_rank, 4).ok()) {
        *status = Status::Error("handshake recv failed");
        return nullptr;
      }
      std::vector<uint8_t> addr_buf;
      sock->RecvFrame(addr_buf);
      table[peer_rank].assign(addr_buf.begin(), addr_buf.end());
      net->peers_[peer_rank] = std::move(sock);
    }
    // Broadcast the address table.
    std::vector<uint8_t> blob;
    for (int i = 0; i < size; ++i) {
      uint32_t n = table[i].size();
      const uint8_t* np = reinterpret_cast<const uint8_t*>(&n);
      blob.insert(blob.end(), np, np + 4);
      blob.insert(blob.end(), table[i].begin(), table[i].end());
    }
    for (int i = 1; i < size; ++i) net->peers_[i]->SendFrame(blob);
    net->SetupShm(table, coord_addr);
  } else {
    int fd = DialRetry(coord_host, coord_port);
    if (fd < 0) {
      *status = Status::Error("cannot reach coordinator at " + coord_addr);
      return nullptr;
    }
    auto sock = std::make_unique<Socket>(fd);
    int32_t r32 = rank;
    sock->SendAll(&r32, 4);
    std::string my_addr = LocalHostname() + ":" + std::to_string(my_port);
    sock->SendFrame(std::vector<uint8_t>(my_addr.begin(), my_addr.end()));
    std::vector<uint8_t> blob;
    if (!sock->RecvFrame(blob).ok()) {
      *status = Status::Error("address table recv failed");
      return nullptr;
    }
    net->peers_[0] = std::move(sock);
    // Parse table.
    std::vector<std::string> table(size);
    size_t off = 0;
    for (int i = 0; i < size; ++i) {
      uint32_t n;
      memcpy(&n, blob.data() + off, 4);
      off += 4;
      table[i].assign(reinterpret_cast<const char*>(blob.data() + off), n);
      off += n;
    }
    // Full mesh: connect to all lower ranks (>0), accept from higher ranks.
    for (int peer = 1; peer < rank; ++peer) {
      std::string host;
      uint16_t port;
      ParseAddr(table[peer], &host, &port);
      int pfd = DialRetry(host, port);
      if (pfd < 0) {
        *status = Status::Error("cannot reach peer " + table[peer]);
        return nullptr;
      }
      auto psock = std::make_unique<Socket>(pfd);
      int32_t me = rank;
      psock->SendAll(&me, 4);
      net->peers_[peer] = std::move(psock);
    }
    for (int peer = rank + 1; peer < size; ++peer) {
      int pfd = ::accept(listen_fd, nullptr, nullptr);
      if (pfd < 0) {
        *status = Status::Error("peer accept failed");
        return nullptr;
      }
      SetNoDelay(pfd);
      auto psock = std::make_unique<Socket>(pfd);
      int32_t peer_rank;
      psock->RecvAll(&peer_rank, 4);
      net->peers_[peer_rank] = std::move(psock);
    }
    net->SetupShm(table, coord_addr);
  }
  ::close(listen_fd);
  *status = Status::OK();
  return net;
}

void Network::SetupShm(const std::vector<std::string>& table,
                       const std::string& tag) {
  // A rank with HVD_TPU_DISABLE_SHM still runs the handshake bytes (as
  // "not participating") — a unilateral early-return would desynchronize
  // the shared data sockets for peers that do participate.
  const bool disabled = getenv("HVD_TPU_DISABLE_SHM") != nullptr;
  std::string my_host, host;
  uint16_t port;
  if (!ParseAddr(table[rank_], &my_host, &port)) return;
  std::vector<int> local;
  for (int r = 0; r < size_; ++r) {
    if (r != rank_ && ParseAddr(table[r], &host, &port) &&
        host == my_host) {
      local.push_back(r);
    }
  }
  if (local.empty()) return;

  // Segment names are scoped to this job by the coordinator address
  // (unique per launch/elastic round).
  std::string base = "/hvt_";
  for (char c : tag)
    base += (isalnum(static_cast<unsigned char>(c)) ? c : '_');

  // Phase 1: create all outgoing segments, then confirm creation with
  // each peer BEFORE anyone opens — opening only after the peer's create
  // is confirmed means a stale segment from a crashed job (which Create
  // unlinks and replaces) can never be the object the consumer maps.
  std::vector<std::unique_ptr<ShmChannel>> tx(size_);
  if (!disabled) {
    for (int r : local) {
      tx[r] = ShmChannel::Create(base + "_" + std::to_string(rank_) +
                                 "_" + std::to_string(r));
    }
  }
  for (int r : local) {
    uint8_t my_created = tx[r] != nullptr ? 1 : 0;
    uint8_t peer_created = 0;
    if (!peers_[r]->SendAll(&my_created, 1).ok() ||
        !peers_[r]->RecvAll(&peer_created, 1).ok()) {
      if (tx[r]) tx[r]->Unlink();
      tx[r].reset();
      continue;
    }
    // Phase 2: open the peer's (fresh) segment, report back.
    std::unique_ptr<ShmChannel> rx;
    if (!disabled && peer_created) {
      rx = ShmChannel::Open(base + "_" + std::to_string(r) + "_" +
                            std::to_string(rank_));
    }
    uint8_t my_rx_ok = rx != nullptr ? 1 : 0;
    uint8_t peer_rx_ok = 0;
    bool hs_ok = peers_[r]->SendAll(&my_rx_ok, 1).ok() &&
                 peers_[r]->RecvAll(&peer_rx_ok, 1).ok();
    // Phase 3: cross-memory-attach capability — my consumer end probes a
    // direct read of the producer's memory; the producer publishes
    // descriptors (zero staging copies) only if my probe succeeded.
    uint8_t my_cma = (hs_ok && rx != nullptr && rx->ProbeCma()) ? 1 : 0;
    uint8_t peer_cma = 0;
    if (hs_ok) {
      hs_ok = peers_[r]->SendAll(&my_cma, 1).ok() &&
              peers_[r]->RecvAll(&peer_cma, 1).ok();
    }
    if (tx[r]) {
      tx[r]->Unlink();  // both ends mapped (or unused): never leak
      if (hs_ok && peer_rx_ok) {
        if (peer_cma) tx[r]->EnableRefs();
        shm_tx_[r] = std::move(tx[r]);
      } else {
        tx[r].reset();
      }
    }
    if (hs_ok && my_rx_ok) shm_rx_[r] = std::move(rx);
  }
}

}  // namespace hvdtpu
