#include "runtime.h"

#include <algorithm>
#include <chrono>
#include <cstring>

namespace hvdtpu {

Runtime& Runtime::Get() {
  static Runtime* runtime = new Runtime();
  return *runtime;
}

Status Runtime::Init(int rank, int size, const std::string& coord_addr,
                     int64_t fusion_threshold, double cycle_time_ms,
                     double stall_warning_s, double stall_shutdown_s,
                     const std::string& timeline_file,
                     size_t cache_capacity) {
  if (initialized_) return Status::OK();
  Status st;
  net_ = Network::Connect(rank, size, coord_addr, &st);
  if (!net_) return st;
  worker_cache_ = ResponseCache(cache_capacity);
  ControllerConfig ccfg;
  ccfg.fusion_threshold_bytes = fusion_threshold;
  ccfg.stall_warning_s = stall_warning_s;
  ccfg.stall_shutdown_s = stall_shutdown_s;
  ccfg.cache_capacity = cache_capacity;
  controller_ = std::make_unique<Controller>(net_.get(), ccfg);
  controller_->set_timeline(&timeline_);
  fusion_threshold_ = fusion_threshold;
  cycle_time_ms_ = cycle_time_ms;
  if (!timeline_file.empty() && rank == 0)
    timeline_.Start(timeline_file, rank, size);
  stop_ = false;
  shutdown_requested_ = false;
  loop_exited_ = false;
  loop_dead_ = false;
  loop_error_ = Status::OK();
  counter_start_ = std::chrono::steady_clock::now();
  bytes_processed_ = 0;
  stall_warning_s_ = stall_warning_s;
  watchdog_stop_ = false;
  device_exec_start_ms_ = 0;
  watchdog_ = std::thread([this] { DeviceWatchdog(); });
  background_ = std::thread([this] { BackgroundLoop(); });
  initialized_ = true;
  return Status::OK();
}

void Runtime::Shutdown() {
  if (!initialized_) return;
  // Phase 1: announce shutdown on the wire and wait for the global
  // consensus exit (every rank requested it) — severs no straggler.
  shutdown_requested_ = true;
  enqueue_cv_.notify_all();
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::seconds(30);
  while (!loop_exited_ &&
         std::chrono::steady_clock::now() < deadline) {
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  // Phase 2 (fallback): peers that will never consent (hung or gone)
  // cannot hold this process hostage.
  stop_ = true;
  enqueue_cv_.notify_all();
  if (background_.joinable()) background_.join();
  {
    // Store + notify under the lock: an unlocked store can race the
    // watchdog's predicate evaluation and lose the wakeup (untimed idle
    // wait would then block join forever).
    std::lock_guard<std::mutex> lk(watch_mu_);
    watchdog_stop_ = true;
  }
  watch_cv_.notify_all();
  if (watchdog_.joinable()) watchdog_.join();
  timeline_.Stop();
  // Fail any remaining entries (FinalizeTensorQueue semantics,
  // tensor_queue.cc).
  std::vector<std::shared_ptr<TensorEntry>> leftovers;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& [n, e] : pending_) leftovers.push_back(e);
    for (auto& [n, e] : submitted_) leftovers.push_back(e);
    pending_.clear();
    pending_order_.clear();
    submitted_.clear();
  }
  for (auto& e : leftovers)
    Finish(e, Status::Aborted("runtime shut down with pending tensors"));
  net_.reset();
  controller_.reset();
  // Reset join/barrier state so an elastic re-init starts clean.
  {
    std::lock_guard<std::mutex> lk(sync_mu_);
    last_joined_rank_ = -2;
    barrier_released_ = false;
  }
  join_requested_ = false;
  barrier_requested_ = false;
  initialized_ = false;
}

int64_t Runtime::Enqueue(std::shared_ptr<TensorEntry> entry, Status* status) {
  if (!initialized_) {
    *status = Status::PreconditionError("runtime not initialized");
    return -1;
  }
  if (loop_dead_) {
    *status = Status::Error("collective runtime failed (" +
                            loop_error_.reason +
                            "); re-initialize to continue");
    return -1;
  }
  std::shared_ptr<HandleState> hs = std::make_shared<HandleState>();
  hs->entry = entry;
  int64_t id;
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (pending_.count(entry->name) || submitted_.count(entry->name)) {
      // DUPLICATE_NAME_ERROR (reference common.h:169-172).
      *status = Status::InvalidArgument(
          "a tensor named " + entry->name +
          " is already in flight; use distinct names for concurrent ops");
      return -1;
    }
    pending_[entry->name] = entry;
    pending_order_.push_back(entry->name);
  }
  {
    std::lock_guard<std::mutex> lk(handle_mu_);
    id = next_handle_++;
    handles_[id] = hs;
    name_to_handle_[entry->name] = id;
  }
  timeline_.Record(entry->name, "B", "NEGOTIATE");
  enqueue_cv_.notify_one();
  *status = Status::OK();
  return id;
}

bool Runtime::Poll(int64_t handle) {
  std::lock_guard<std::mutex> lk(handle_mu_);
  auto it = handles_.find(handle);
  return it == handles_.end() || it->second->done.load();
}

Status Runtime::Wait(int64_t handle) {
  std::unique_lock<std::mutex> lk(handle_mu_);
  auto it = handles_.find(handle);
  if (it == handles_.end()) return Status::InvalidArgument("unknown handle");
  auto hs = it->second;
  handle_cv_.wait(lk, [&] { return hs->done.load(); });
  return hs->status;
}

std::shared_ptr<TensorEntry> Runtime::GetEntry(int64_t handle) {
  std::lock_guard<std::mutex> lk(handle_mu_);
  auto it = handles_.find(handle);
  return it == handles_.end() ? nullptr : it->second->entry;
}

void Runtime::Release(int64_t handle) {
  std::lock_guard<std::mutex> lk(handle_mu_);
  auto it = handles_.find(handle);
  if (it != handles_.end()) {
    if (it->second->entry) name_to_handle_.erase(it->second->entry->name);
    handles_.erase(it);
  }
}

void Runtime::Finish(std::shared_ptr<TensorEntry>& e, const Status& s) {
  if (!e) return;
  timeline_.Record(e->name, "E", "OPERATION");
  int64_t hid = -1;
  std::shared_ptr<HandleState> hs;
  {
    std::lock_guard<std::mutex> lk(handle_mu_);
    auto it = name_to_handle_.find(e->name);
    if (it != name_to_handle_.end()) {
      hid = it->second;
      hs = handles_[hid];
    }
  }
  if (hs) {
    hs->status = s;
    hs->done = true;
    handle_cv_.notify_all();
  }
  if (e->callback) e->callback(s);
}

std::shared_ptr<TensorEntry> Runtime::TakeSubmitted(const std::string& name) {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = submitted_.find(name);
  if (it == submitted_.end()) return nullptr;
  auto e = it->second;
  submitted_.erase(it);
  return e;
}

void Runtime::BackgroundLoop() {
  using clock = std::chrono::steady_clock;
  while (!stop_) {
    auto cycle_start = clock::now();
    // 1. Drain pending into a RequestList.
    RequestList rl;
    {
      std::unique_lock<std::mutex> lk(mu_);
      // Sleep to cycle time, but wake the moment work arrives: a
      // latency-sensitive sequential op should not pay the full cycle
      // (the reference sleeps unconditionally, operations.cc:592-598 —
      // here bursty enqueues still batch into one round because they
      // accumulate while the previous round executes).
      enqueue_cv_.wait_for(
          lk, std::chrono::duration<double, std::milli>(
              cycle_time_ms_.load()),
          [this] {
            return stop_.load() || !pending_order_.empty() ||
                   join_requested_.load() || barrier_requested_.load() ||
                   shutdown_requested_.load();
          });
      for (const auto& name : pending_order_) {
        auto it = pending_.find(name);
        if (it == pending_.end()) continue;
        auto& e = it->second;
        Request q;
        q.type = e->type;
        q.rank = net_->rank();
        q.name = e->name;
        q.dtype = e->dtype;
        q.shape = e->shape;
        q.op = e->op;
        q.root_rank = e->root_rank;
        q.prescale = e->prescale;
        q.postscale = e->postscale;
        q.splits = e->splits;
        q.device = e->device;
        // Response-cache fast path: announce a previously-negotiated
        // tensor as one bit instead of the full request (reference
        // controller.cc:181-237).
        int32_t bit = (worker_cache_.enabled() && coord_cache_on_.load())
                          ? worker_cache_.Lookup(q)
                          : -1;
        if (bit >= 0) {
          SetBit(rl.cache_hits, static_cast<uint32_t>(bit));
        } else {
          rl.requests.push_back(std::move(q));
        }
        submitted_[name] = e;
      }
      for (const auto& [name, e] : submitted_) pending_.erase(name);
      pending_order_.clear();
    }
    rl.join = join_requested_.load();
    rl.barrier = barrier_requested_.load();
    rl.shutdown = shutdown_requested_.load() || stop_.load();

    // 2. Controller round.
    ResponseList responses;
    Status st = controller_->Exchange(rl, &responses);
    if (!st.ok()) {
      loop_error_ = st;
      loop_dead_ = true;
      // Fail everything in flight — submitted AND still-pending — so no
      // caller blocks on a handle that will never resolve; new enqueues
      // fail fast until re-init (elastic reset path).
      std::vector<std::shared_ptr<TensorEntry>> all;
      {
        std::lock_guard<std::mutex> lk(mu_);
        for (auto& [n, e] : submitted_) all.push_back(e);
        for (auto& [n, e] : pending_) all.push_back(e);
        submitted_.clear();
        pending_.clear();
        pending_order_.clear();
      }
      for (auto& e : all) Finish(e, st);
      // Unblock join()/barrier() waiters too — without clobbering a
      // release that was delivered but not yet consumed.
      {
        std::lock_guard<std::mutex> lk(sync_mu_);
        if (last_joined_rank_ == -2) last_joined_rank_ = -1;
        barrier_released_ = true;
      }
      sync_cv_.notify_all();
      break;
    }
    timeline_.MarkCycle();

    // 3. Self-heal any cache divergence: renegotiate bits the
    // coordinator no longer holds.
    for (uint32_t bit : responses.resend_bits) {
      std::string name = worker_cache_.NameForBit(bit);
      if (name.empty()) continue;
      worker_cache_.Invalidate(name);
      std::lock_guard<std::mutex> lk(mu_);
      auto it = submitted_.find(name);
      if (it != submitted_.end()) {
        pending_[name] = it->second;
        pending_order_.push_back(name);
        submitted_.erase(it);
      }
    }
    // 4. Execute responses in coordinator order (identical on all ranks).
    coord_cache_on_.store(responses.cache_on);
    coord_wire_compression_.store(responses.wire_compression);
    for (const auto& resp : responses.responses) ExecuteResponse(resp);
    worker_cache_.Touch(responses.valid_cache_bits);

    // 4. Join / barrier releases.
    if (responses.last_joined_rank >= 0) {
      std::lock_guard<std::mutex> lk(sync_mu_);
      last_joined_rank_ = responses.last_joined_rank;
      join_requested_ = false;
      sync_cv_.notify_all();
    }
    if (responses.barrier_release) {
      std::lock_guard<std::mutex> lk(sync_mu_);
      barrier_released_ = true;
      barrier_requested_ = false;
      sync_cv_.notify_all();
    }
    if (responses.shutdown) break;
    (void)cycle_start;
  }
  loop_exited_ = true;
}

void Runtime::ExecuteResponse(const Response& resp) {
  if (!resp.error.empty()) {
    for (const auto& name : resp.names) {
      worker_cache_.Invalidate(name);
      auto e = TakeSubmitted(name);
      if (e) Finish(e, Status::Error(resp.error));
    }
    return;
  }
  // Mirror the coordinator's cache-slot assignments using this rank's own
  // metadata for the lookup key.
  if (worker_cache_.enabled()) {
    for (size_t i = 0; i < resp.names.size() && i < resp.cache_bits.size();
         ++i) {
      if (resp.cache_bits[i] == UINT32_MAX) continue;
      std::shared_ptr<TensorEntry> e;
      {
        std::lock_guard<std::mutex> lk(mu_);
        auto it = submitted_.find(resp.names[i]);
        if (it != submitted_.end()) e = it->second;
      }
      if (!e) continue;  // joined rank: no local meta to cache
      Request q;
      q.type = e->type;
      q.rank = net_->rank();
      q.name = e->name;
      q.dtype = e->dtype;
      q.shape = e->shape;
      q.op = e->op;
      q.root_rank = e->root_rank;
      q.prescale = e->prescale;
      q.postscale = e->postscale;
      q.splits = e->splits;
      q.device = e->device;
      worker_cache_.InsertAt(resp.cache_bits[i], resp.names[i], q);
    }
  }
  switch (resp.type) {
    case RequestType::ALLREDUCE: {
      std::vector<std::shared_ptr<TensorEntry>> entries;
      for (const auto& name : resp.names) entries.push_back(
          TakeSubmitted(name));
      ExecuteAllreduce(resp, entries);
      break;
    }
    case RequestType::ALLGATHER:
      ExecuteAllgather(resp, TakeSubmitted(resp.names[0]));
      break;
    case RequestType::BROADCAST:
      ExecuteBroadcast(resp, TakeSubmitted(resp.names[0]));
      break;
    case RequestType::ALLTOALL:
      ExecuteAlltoall(resp, TakeSubmitted(resp.names[0]));
      break;
    default:
      break;
  }
}

void Runtime::ExecuteDeviceCollective(
    const Response& resp,
    std::vector<std::shared_ptr<TensorEntry>>& entries) {
  // Negotiated device-resident execution: the fused payload never touches
  // host memory — the registered executor runs it on HBM via the jitted
  // device plane (reference: NCCLAllreduce on device fusion buffers,
  // nccl_operations.cc:126-184).  Invoked in coordinator response order,
  // identical across ranks, so the executor's SPMD collectives line up
  // even when per-rank enqueue order diverged.
  //
  // Failure protocol (reference: NCCL async-error abort,
  // nccl_operations.cc:96-109 — an XLA collective cannot be aborted, so
  // failures must be caught BEFORE the SPMD dispatch): PREPARE runs every
  // locally-detectable check; the per-rank status is agreed across all
  // ranks over the wire; only unanimous OK proceeds to EXECUTE.  A second
  // agreement after EXECUTE converts any late failure into an ERROR on
  // every rank.  Either way every rank's entries resolve and the runtime
  // stays usable (like the coordinator's validation-error path).
  DeviceExecutorFn fn = device_executor_.load();
  Status st;
  std::vector<const char*> names(resp.names.size());
  for (size_t i = 0; i < resp.names.size(); ++i)
    names[i] = resp.names[i].c_str();
  char err[512];
  err[0] = '\0';

  // Watchdog marker covers the whole prepare/agree/execute/agree span:
  // a peer stuck in any of them leaves this rank blocked here too, and
  // the negotiation-plane stall inspector cannot see it.
  {
    std::lock_guard<std::mutex> lk(watch_mu_);
    device_exec_name_ = resp.names[0];
    device_exec_warned_ = false;
    device_exec_start_ms_ =
        std::chrono::duration_cast<std::chrono::milliseconds>(
            std::chrono::steady_clock::now().time_since_epoch())
            .count();
  }
  watch_cv_.notify_all();

  int32_t ok = 1;
  if (fn == nullptr) {
    ok = 0;
    snprintf(err, sizeof(err),
             "no device executor registered on rank %d",
             net_ ? net_->rank() : -1);
  } else {
    int rc = fn(kDevicePrepare, static_cast<int>(resp.type),
                static_cast<int>(names.size()), names.data(),
                resp.sizes.data(), static_cast<int>(resp.dtype),
                static_cast<int>(resp.op), resp.root_rank, resp.prescale,
                resp.postscale, err, sizeof(err));
    ok = (rc == 0);
  }
  int32_t first_bad = -1;
  Status ag = AgreeAllRanks(*net_, &ok, &first_bad);
  if (!ag.ok()) {
    if (fn != nullptr && ok) {
      // Drop the staged plan on transport failure too (symmetry with
      // the peer-failure path below), or the staged HBM inputs stay
      // referenced until the next device PREPARE.
      char abort_err[64];
      fn(kDeviceAbort, static_cast<int>(resp.type),
         static_cast<int>(names.size()), names.data(), resp.sizes.data(),
         static_cast<int>(resp.dtype), static_cast<int>(resp.op),
         resp.root_rank, resp.prescale, resp.postscale, abort_err,
         sizeof(abort_err));
    }
    device_exec_start_ms_ = 0;
    for (auto& e : entries)
      if (e) Finish(e, ag);
    return;
  }
  if (!ok) {
    if (fn != nullptr) {
      // Drop any state PREPARE staged (a rank whose own prepare failed
      // has nothing staged; abort is idempotent).
      char abort_err[64];
      fn(kDeviceAbort, static_cast<int>(resp.type),
         static_cast<int>(names.size()), names.data(), resp.sizes.data(),
         static_cast<int>(resp.dtype), static_cast<int>(resp.op),
         resp.root_rank, resp.prescale, resp.postscale, abort_err,
         sizeof(abort_err));
    }
    // Own error text only when this rank IS the (first) failing rank —
    // appending a local message to a peer's rank id would misattribute
    // one rank's error to another.
    st = (first_bad == net_->rank() && err[0] != '\0')
             ? Status::Error(err)
             : Status::Error("device executor failed on rank " +
                             std::to_string(first_bad));
    device_exec_start_ms_ = 0;
    for (auto& e : entries)
      if (e) Finish(e, st);
    return;
  }

  {
    timeline_.Record(resp.names[0], "B", "DEVICE_COLLECTIVE");
    int rc = fn(kDeviceExecute, static_cast<int>(resp.type),
                static_cast<int>(names.size()), names.data(),
                resp.sizes.data(), static_cast<int>(resp.dtype),
                static_cast<int>(resp.op), resp.root_rank, resp.prescale,
                resp.postscale, err, sizeof(err));
    timeline_.Record(resp.names[0], "E", "DEVICE_COLLECTIVE");
    int32_t exec_ok = (rc == 0);
    int32_t exec_bad = -1;
    ag = AgreeAllRanks(*net_, &exec_ok, &exec_bad);
    if (!ag.ok()) {
      device_exec_start_ms_ = 0;
      for (auto& e : entries)
        if (e) Finish(e, ag);
      return;
    }
    if (!exec_ok) {
      st = rc != 0 ? Status::Error(err[0] ? err : "device executor failed")
                   : Status::Error("device executor failed on rank " +
                                   std::to_string(exec_bad));
    } else {
      const int P = net_->size();
      int64_t total_elems = 0;
      switch (resp.type) {
        case RequestType::ALLGATHER:
          // sizes = per-rank first dims + trailing row_elems.
          if (resp.sizes.size() == static_cast<size_t>(P) + 1) {
            int64_t rows = 0;
            for (int r = 0; r < P; ++r) rows += resp.sizes[r];
            total_elems = rows * resp.sizes[P];
          }
          break;
        case RequestType::ALLTOALL:
          // sizes = P x P split matrix + trailing row_elems.
          if (resp.sizes.size() ==
              static_cast<size_t>(P) * P + 1) {
            int64_t rows = 0;
            for (size_t i = 0; i < static_cast<size_t>(P) * P; ++i)
              rows += resp.sizes[i];
            total_elems = rows * resp.sizes[static_cast<size_t>(P) * P];
          }
          break;
        default:  // allreduce (fused) / broadcast: element counts
          for (size_t i = 0;
               i < resp.names.size() && i < resp.sizes.size(); ++i)
            total_elems += resp.sizes[i];
          break;
      }
      bytes_processed_ += total_elems * DataTypeSize(resp.dtype);
    }
  }
  device_exec_start_ms_ = 0;
  for (auto& e : entries)
    if (e) Finish(e, st);
}

void Runtime::DeviceWatchdog() {
  std::unique_lock<std::mutex> lk(watch_mu_);
  while (!watchdog_stop_) {
    if (device_exec_start_ms_.load() == 0) {
      // Idle: block until a device response starts or shutdown — zero
      // wakeups for host-plane-only workloads.
      watch_cv_.wait(lk, [this] {
        return watchdog_stop_.load() || device_exec_start_ms_.load() != 0;
      });
      continue;
    }
    watch_cv_.wait_for(lk, std::chrono::milliseconds(200),
                       [this] { return watchdog_stop_.load(); });
    int64_t start = device_exec_start_ms_.load();
    if (start == 0 || device_exec_warned_.load()) continue;
    int64_t now = std::chrono::duration_cast<std::chrono::milliseconds>(
                      std::chrono::steady_clock::now().time_since_epoch())
                      .count();
    if ((now - start) / 1000.0 > stall_warning_s_) {
      device_exec_warned_ = true;
      fprintf(stderr,
              "[hvdtpu rank %d] WARNING: device response '%s' in flight "
              "for %.0fs; a peer rank may be stuck or dead inside the "
              "device collective\n",
              net_ ? net_->rank() : -1, device_exec_name_.c_str(),
              (now - start) / 1000.0);
    }
  }
}

void Runtime::ExecuteAllreduce(
    const Response& resp,
    std::vector<std::shared_ptr<TensorEntry>>& entries) {
  last_fused_names_ = static_cast<int64_t>(resp.names.size());
  if (resp.device) {
    ExecuteDeviceCollective(resp, entries);
    return;
  }
  // resp.sizes[i] = element count of names[i] (authoritative — joined ranks
  // have no local entry and synthesize zeros).
  int64_t total_elems = 0;
  for (auto n : resp.sizes) total_elems += n;
  const size_t elem = DataTypeSize(resp.dtype);
  const size_t total_bytes = total_elems * elem;

  // Single-tensor fast path: run the ring in place on the caller's output
  // buffer — no fusion arena, at most one copy (zero when submitted
  // in-place with input == output).  Fusion only ever pays for itself
  // when it batches multiple tensors.
  uint8_t* fb;
  bool in_place = resp.names.size() == 1 && entries[0] &&
                  entries[0]->input && entries[0]->output;
  if (in_place) {
    fb = static_cast<uint8_t*>(entries[0]->output);
    if (entries[0]->output != entries[0]->input) {
      timeline_.Record(resp.names[0], "B", "MEMCPY_IN_FUSION_BUFFER");
      memcpy(fb, entries[0]->input, total_bytes);
      timeline_.Record(resp.names[0], "E", "MEMCPY_IN_FUSION_BUFFER");
    }
  } else {
    if (fusion_buffer_.size() < total_bytes)
      fusion_buffer_.resize(total_bytes);
    fb = fusion_buffer_.data();

    // Pack (MemcpyInFusionBuffer, collective_operations.cc).
    timeline_.Record(resp.names[0], "B", "MEMCPY_IN_FUSION_BUFFER");
    int64_t off = 0;
    for (size_t i = 0; i < resp.names.size(); ++i) {
      int64_t nbytes = resp.sizes[i] * elem;
      if (entries[i] && entries[i]->input) {
        memcpy(fb + off, entries[i]->input, nbytes);
      } else {
        memset(fb + off, 0, nbytes);  // joined-rank zero proxy
      }
      off += nbytes;
    }
    timeline_.Record(resp.names[0], "E", "MEMCPY_IN_FUSION_BUFFER");
  }

  if (resp.prescale != 1.0)
    ScaleBuffer(fb, total_elems, resp.dtype, resp.prescale);

  // Ring-recovery restore: a renegotiated retry re-packs the fusion
  // buffer from the entries' inputs, which the ring never touches — so
  // the resilient wrapper skips its clean-path snapshot copy.  The only
  // shape it cannot rebuild is a truly in-place submission (input ==
  // output): that one falls back to the wrapper's internal snapshot.
  std::function<void()> repack;
  if (!in_place || entries[0]->input != entries[0]->output) {
    repack = [&, fb]() {
      if (in_place) {
        memcpy(fb, entries[0]->input, total_bytes);
      } else {
        int64_t off = 0;
        for (size_t i = 0; i < resp.names.size(); ++i) {
          int64_t nbytes = resp.sizes[i] * elem;
          if (entries[i] && entries[i]->input) {
            memcpy(fb + off, entries[i]->input, nbytes);
          } else {
            memset(fb + off, 0, nbytes);
          }
          off += nbytes;
        }
      }
      if (resp.prescale != 1.0)
        ScaleBuffer(fb, total_elems, resp.dtype, resp.prescale);
    };
  }

  timeline_.Record(resp.names[0], "B", "RING_ALLREDUCE");
  Status st;
  // Algorithm choice comes from the RESPONSE (coordinator-stamped), not
  // local state: the tuner flips the toggle on rank 0 mid-run and every
  // rank must execute the same schedule for the same Response.
  if (resp.op == ReduceOp::ADASUM) {
    st = (resp.hierarchical && local_size_ > 1)
             ? HierarchicalAdasum(*net_, fb, total_elems, resp.dtype,
                                  local_size_)
             : AdasumAllreduce(*net_, fb, total_elems, resp.dtype);
  } else if (resp.hierarchical && local_size_ > 1) {
    st = HierarchicalAllreduce(*net_, fb, total_elems, resp.dtype, resp.op,
                               local_size_);
  } else {
    st = RingAllreduce(*net_, fb, total_elems, resp.dtype, resp.op,
                       repack ? &repack : nullptr);
  }
  timeline_.Record(resp.names[0], "E", "RING_ALLREDUCE");

  if (st.ok()) bytes_processed_ += total_bytes;
  if (st.ok()) {
    if (resp.op == ReduceOp::AVERAGE) {
      // Integer Average floor-divides in the integer domain (compiled-
      // path contract); float dtypes scale.
      if (!FloorAverageInt(fb, total_elems, resp.dtype, net_->size()))
        ScaleBuffer(fb, total_elems, resp.dtype, 1.0 / net_->size());
    }
    if (resp.postscale != 1.0)
      ScaleBuffer(fb, total_elems, resp.dtype, resp.postscale);
    if (!in_place) {
      // Unpack.
      int64_t off = 0;
      for (size_t i = 0; i < resp.names.size(); ++i) {
        int64_t nbytes = resp.sizes[i] * elem;
        if (entries[i] && entries[i]->output)
          memcpy(entries[i]->output, fb + off, nbytes);
        off += nbytes;
      }
    }
  }
  for (auto& e : entries)
    if (e) Finish(e, st);
}

void Runtime::ExecuteAllgather(const Response& resp,
                               std::shared_ptr<TensorEntry> entry) {
  if (resp.device) {
    std::vector<std::shared_ptr<TensorEntry>> entries{entry};
    ExecuteDeviceCollective(resp, entries);
    return;
  }
  const int size = net_->size();
  const int rank = net_->rank();
  const size_t elem = DataTypeSize(resp.dtype);
  // resp.sizes = [first_dim per rank ..., row_elems]; row_elems from the
  // coordinator so joined ranks (no local entry) can still size their ring
  // blocks and forward peers' data.
  const int64_t row_elems = resp.sizes[size];
  std::vector<int64_t> bytes(size), offsets(size);
  int64_t total = 0;
  for (int r = 0; r < size; ++r) {
    bytes[r] = resp.sizes[r] * row_elems * elem;
    offsets[r] = total;
    total += bytes[r];
  }
  auto out = std::make_shared<std::vector<uint8_t>>(
      std::max<int64_t>(total, 1));
  if (entry && entry->input)
    memcpy(out->data() + offsets[rank], entry->input, bytes[rank]);
  if (entry) timeline_.Record(entry->name, "B", "RING_ALLGATHER");
  // Always route through HierarchicalAllgatherv: it owns the schedule
  // marker and degrades to the flat ring itself when local_size == 1.
  Status st = HierarchicalAllgatherv(
      *net_, out->data(), bytes, offsets,
      (resp.hierarchical && local_size_ > 1) ? local_size_ : 1);
  if (entry) {
    timeline_.Record(entry->name, "E", "RING_ALLGATHER");
    entry->var_output = out;
    entry->out_first_dims.assign(resp.sizes.begin(),
                                 resp.sizes.begin() + size);
    Finish(entry, st);
  }
}

void Runtime::ExecuteBroadcast(const Response& resp,
                               std::shared_ptr<TensorEntry> entry) {
  if (resp.device) {
    std::vector<std::shared_ptr<TensorEntry>> entries{entry};
    ExecuteDeviceCollective(resp, entries);
    return;
  }
  const size_t elem = DataTypeSize(resp.dtype);
  const int64_t nbytes = resp.sizes[0] * elem;
  std::vector<uint8_t> scratch;
  void* buf;
  if (entry && entry->output) {
    if (net_->rank() == resp.root_rank && entry->input != entry->output)
      memcpy(entry->output, entry->input, nbytes);
    buf = entry->output;
  } else {
    scratch.resize(nbytes);
    buf = scratch.data();  // joined-rank proxy participates in the chain
  }
  Status st = ChainBroadcast(*net_, buf, nbytes, resp.root_rank);
  if (entry) Finish(entry, st);
}

void Runtime::ExecuteAlltoall(const Response& resp,
                              std::shared_ptr<TensorEntry> entry) {
  if (resp.device) {
    std::vector<std::shared_ptr<TensorEntry>> entries{entry};
    ExecuteDeviceCollective(resp, entries);
    return;
  }
  const int size = net_->size();
  const int rank = net_->rank();
  const size_t elem = DataTypeSize(resp.dtype);
  // resp.sizes = row-split matrix [src * size + dst] + trailing row_elems
  // (coordinator-supplied so joined ranks size their exchanges correctly).
  const int64_t row_elems = resp.sizes[static_cast<size_t>(size) * size];
  std::vector<int64_t> send_bytes(size), recv_bytes(size);
  int64_t total_recv = 0;
  for (int d = 0; d < size; ++d)
    send_bytes[d] =
        (entry ? resp.sizes[static_cast<size_t>(rank) * size + d] : 0) *
        row_elems * elem;
  for (int s = 0; s < size; ++s) {
    recv_bytes[s] = resp.sizes[static_cast<size_t>(s) * size + rank] *
                    row_elems * elem;
    total_recv += recv_bytes[s];
  }
  auto out = std::make_shared<std::vector<uint8_t>>(
      std::max<int64_t>(total_recv, 1));
  const uint8_t* send =
      entry ? static_cast<const uint8_t*>(entry->input) : out->data();
  Status st = PairwiseAlltoallv(*net_, send, send_bytes, out->data(),
                                recv_bytes);
  if (entry) {
    entry->var_output = out;
    entry->out_first_dims.resize(size);
    for (int s = 0; s < size; ++s)
      entry->out_first_dims[s] =
          resp.sizes[static_cast<size_t>(s) * size + rank];
    Finish(entry, st);
  }
}

int Runtime::JoinBlocking() {
  join_requested_ = true;
  enqueue_cv_.notify_one();
  std::unique_lock<std::mutex> lk(sync_mu_);
  // -2 = idle sentinel; >= 0 = released (last joined rank); -1 = the
  // background loop died (loop_dead_ unblock) — waiting for >= 0 only
  // would strand the caller forever on loop failure.
  sync_cv_.wait(lk,
                [this] { return last_joined_rank_ != -2 || stop_; });
  int r = last_joined_rank_;
  last_joined_rank_ = -2;
  return r;
}

Status Runtime::BarrierBlocking() {
  barrier_requested_ = true;
  enqueue_cv_.notify_one();
  std::unique_lock<std::mutex> lk(sync_mu_);
  sync_cv_.wait(lk, [this] { return barrier_released_ || stop_; });
  barrier_released_ = false;
  return Status::OK();
}

void Runtime::SetTopology(int local_size, bool hierarchical_allreduce,
                          bool hierarchical_allgather) {
  local_size_ = local_size;
  // Seed the coordinator's per-response stamping with the configured
  // algorithm choice (the tuner may override later via SetTunedToggles).
  if (controller_)
    controller_->SetAlgoToggles(hierarchical_allreduce,
                                hierarchical_allgather, tuned_cache_on_);
}

void Runtime::SetTunedToggles(bool hierarchical_allreduce,
                              bool hierarchical_allgather,
                              bool cache_enabled) {
  tuned_cache_on_ = cache_enabled;
  if (controller_)
    controller_->SetAlgoToggles(hierarchical_allreduce,
                                hierarchical_allgather, cache_enabled);
}

void Runtime::SetScheduleTable(int kind, std::vector<ScheduleSegment> segs) {
  // Coordinator-only effect (workers adopt the per-response stamp from
  // the response stream), mirroring SetWireCompression.
  if (controller_) controller_->SetScheduleTable(kind, std::move(segs));
}

void Runtime::SetCacheOn(bool cache_enabled) {
  tuned_cache_on_ = cache_enabled;
  if (controller_) controller_->SetCacheOn(cache_enabled);
}

void Runtime::SetWireCompression(int code) {
  // Coordinator-only effect: workers (and rank 0's own executor) adopt
  // the choice from the response stream, so setting it here on a
  // non-coordinator rank is a deliberate no-op.
  if (controller_) controller_->SetWireCompression(code);
}

void Runtime::SetParams(int64_t fusion_threshold, double cycle_time_ms) {
  if (fusion_threshold > 0 && controller_)
    controller_->SetFusionThreshold(fusion_threshold);
  if (cycle_time_ms > 0) cycle_time_ms_ = cycle_time_ms;
}

void Runtime::ReadCounters(int64_t* bytes, double* seconds) {
  auto now = std::chrono::steady_clock::now();
  *bytes = bytes_processed_.exchange(0);
  *seconds = std::chrono::duration<double>(now - counter_start_).count();
  counter_start_ = now;
}

void Runtime::StartTimeline(const std::string& filename) {
  timeline_.Start(filename, net_ ? net_->rank() : 0,
                  net_ ? net_->size() : 1);
}

std::string Runtime::StalledJson() {
  if (!initialized_ || !controller_) return "[]";
  return controller_->StalledJson();
}

void Runtime::StopTimeline() { timeline_.Stop(); }

}  // namespace hvdtpu
