// extern "C" API consumed by the ctypes layer (native/controller.py).
//
// Capability parity with the reference's C API (operations.cc:703-915:
// horovod_init/shutdown/rank/size + EnqueueTensor* reached through the
// framework bridges) — here a single flat C surface since the only bridge
// is Python/numpy.
#include <cstring>
#include <memory>
#include <mutex>
#include <string>

#include "runtime.h"

using namespace hvdtpu;

namespace {
std::mutex g_err_mu;
std::string g_last_error;

void SetError(const std::string& msg) {
  std::lock_guard<std::mutex> lk(g_err_mu);
  g_last_error = msg;
}

std::shared_ptr<TensorEntry> MakeEntry(const char* name, RequestType type,
                                       const void* input, void* output,
                                       int ndim, const int64_t* shape,
                                       int dtype) {
  auto e = std::make_shared<TensorEntry>();
  e->name = name;
  e->type = type;
  e->dtype = static_cast<DataType>(dtype);
  e->shape.assign(shape, shape + ndim);
  e->input = input;
  e->output = output;
  return e;
}

int64_t EnqueueChecked(std::shared_ptr<TensorEntry> e) {
  Status st;
  int64_t h = Runtime::Get().Enqueue(std::move(e), &st);
  if (h < 0) SetError(st.reason);
  return h;
}
}  // namespace

extern "C" {

int hvd_native_init(int rank, int size, const char* coord_addr,
                    int64_t fusion_threshold, double cycle_time_ms,
                    double stall_warning_s, double stall_shutdown_s,
                    const char* timeline_file, int64_t cache_capacity) {
  Status st = Runtime::Get().Init(rank, size, coord_addr, fusion_threshold,
                                  cycle_time_ms, stall_warning_s,
                                  stall_shutdown_s,
                                  timeline_file ? timeline_file : "",
                                  cache_capacity < 0 ? 0 : cache_capacity);
  if (!st.ok()) {
    SetError(st.reason);
    return -1;
  }
  return 0;
}

void hvd_native_shutdown() { Runtime::Get().Shutdown(); }

int hvd_native_initialized() { return Runtime::Get().initialized() ? 1 : 0; }
int hvd_native_rank() { return Runtime::Get().rank(); }
int hvd_native_size() { return Runtime::Get().size(); }

int64_t hvd_native_allreduce(const char* name, const void* input,
                             void* output, int ndim, const int64_t* shape,
                             int dtype, int op, double prescale,
                             double postscale) {
  auto e = MakeEntry(name, RequestType::ALLREDUCE, input, output, ndim,
                     shape, dtype);
  e->op = static_cast<ReduceOp>(op);
  e->prescale = prescale;
  e->postscale = postscale;
  return EnqueueChecked(std::move(e));
}

// Device-resident enqueue: the payload stays in accelerator HBM; the
// runtime negotiates/fuses/caches as usual and hands the fused response to
// the registered device executor instead of the host rings.
int64_t hvd_native_allreduce_device(const char* name, int ndim,
                                    const int64_t* shape, int dtype, int op,
                                    double prescale, double postscale) {
  auto e = MakeEntry(name, RequestType::ALLREDUCE, nullptr, nullptr, ndim,
                     shape, dtype);
  e->op = static_cast<ReduceOp>(op);
  e->prescale = prescale;
  e->postscale = postscale;
  e->device = true;
  return EnqueueChecked(std::move(e));
}

int64_t hvd_native_broadcast_device(const char* name, int ndim,
                                    const int64_t* shape, int dtype,
                                    int root_rank) {
  auto e = MakeEntry(name, RequestType::BROADCAST, nullptr, nullptr, ndim,
                     shape, dtype);
  e->root_rank = root_rank;
  e->device = true;
  return EnqueueChecked(std::move(e));
}

int64_t hvd_native_allgather_device(const char* name, int ndim,
                                    const int64_t* shape, int dtype) {
  auto e = MakeEntry(name, RequestType::ALLGATHER, nullptr, nullptr, ndim,
                     shape, dtype);
  e->device = true;
  return EnqueueChecked(std::move(e));
}

int64_t hvd_native_alltoall_device(const char* name, int ndim,
                                   const int64_t* shape, int dtype,
                                   const int64_t* splits, int nsplits) {
  auto e = MakeEntry(name, RequestType::ALLTOALL, nullptr, nullptr, ndim,
                     shape, dtype);
  e->splits.assign(splits, splits + nsplits);
  e->device = true;
  return EnqueueChecked(std::move(e));
}

void hvd_native_set_device_executor(DeviceExecutorFn fn) {
  Runtime::Get().SetDeviceExecutor(fn);
}

int64_t hvd_native_allgather(const char* name, const void* input, int ndim,
                             const int64_t* shape, int dtype) {
  return EnqueueChecked(MakeEntry(name, RequestType::ALLGATHER, input,
                                  nullptr, ndim, shape, dtype));
}

int64_t hvd_native_broadcast(const char* name, const void* input,
                             void* output, int ndim, const int64_t* shape,
                             int dtype, int root_rank) {
  auto e = MakeEntry(name, RequestType::BROADCAST, input, output, ndim,
                     shape, dtype);
  e->root_rank = root_rank;
  return EnqueueChecked(std::move(e));
}

int64_t hvd_native_alltoall(const char* name, const void* input, int ndim,
                            const int64_t* shape, int dtype,
                            const int64_t* splits, int nsplits) {
  auto e = MakeEntry(name, RequestType::ALLTOALL, input, nullptr, ndim,
                     shape, dtype);
  e->splits.assign(splits, splits + nsplits);
  return EnqueueChecked(std::move(e));
}

int hvd_native_poll(int64_t handle) {
  return Runtime::Get().Poll(handle) ? 1 : 0;
}

// Blocks; returns 0 on success. Does not release the handle.
int hvd_native_wait(int64_t handle) {
  Status st = Runtime::Get().Wait(handle);
  if (!st.ok()) {
    SetError(st.reason);
    return -1;
  }
  return 0;
}

// Variable-size results (allgather/alltoall).
int64_t hvd_native_result_bytes(int64_t handle) {
  auto e = Runtime::Get().GetEntry(handle);
  if (!e || !e->var_output) return -1;
  return static_cast<int64_t>(e->var_output->size());
}

int hvd_native_result_dims(int64_t handle, int64_t* dims, int max_dims) {
  auto e = Runtime::Get().GetEntry(handle);
  if (!e) return -1;
  int n = static_cast<int>(e->out_first_dims.size());
  for (int i = 0; i < n && i < max_dims; ++i) dims[i] = e->out_first_dims[i];
  return n;
}

int hvd_native_result_copy(int64_t handle, void* dst, int64_t nbytes) {
  auto e = Runtime::Get().GetEntry(handle);
  if (!e || !e->var_output ||
      nbytes < static_cast<int64_t>(e->var_output->size()))
    return -1;
  memcpy(dst, e->var_output->data(), e->var_output->size());
  return 0;
}

void hvd_native_release(int64_t handle) { Runtime::Get().Release(handle); }

int hvd_native_join() { return Runtime::Get().JoinBlocking(); }

int hvd_native_barrier() {
  Status st = Runtime::Get().BarrierBlocking();
  return st.ok() ? 0 : -1;
}

void hvd_native_set_topology(int local_size, int hierarchical_allreduce,
                             int hierarchical_allgather) {
  Runtime::Get().SetTopology(local_size, hierarchical_allreduce != 0,
                             hierarchical_allgather != 0);
}

// Test/observability hook: 0 = flat ring, 1 = hierarchical (schedule used
// by this process's most recent allgather).
int hvd_native_last_allgather_schedule() {
  return LastAllgatherSchedule();
}

// 0 = flat ring / flat VHDD, 1 = hierarchical (this process's most
// recent allreduce/Adasum) — the allreduce analog of the hook above.
int hvd_native_last_allreduce_schedule() {
  return LastAllreduceSchedule();
}

// 0 = flat/none, 1 = pipelined chain, 2 = zero-copy CMA star.
int hvd_native_last_allreduce_fanout() { return LastAllreduceFanout(); }
int hvd_native_last_bcast_schedule() { return LastBroadcastSchedule(); }

// Test/observability hooks: peak scratch bytes of the Adasum VHDD path.
int64_t hvd_native_adasum_scratch_peak() { return AdasumScratchPeak(); }
void hvd_native_adasum_scratch_reset() { ResetAdasumScratchPeak(); }

// Names in the most recent (possibly fused) allreduce Response executed
// by this rank — live evidence of the current fusion threshold.
int64_t hvd_native_last_fused_names() {
  return Runtime::Get().LastFusedNames();
}

void hvd_native_set_params(int64_t fusion_threshold, double cycle_time_ms) {
  Runtime::Get().SetParams(fusion_threshold, cycle_time_ms);
}

// Categorical autotune toggles (reference parameter_manager.h:91-93):
// rank 0's tuner flips {hierarchical allreduce, hierarchical allgather,
// response cache} per sample; the coordinator distributes the choice
// through the response stream so every rank stays schedule-consistent.
void hvd_native_set_tuned_toggles(int hierarchical_allreduce,
                                  int hierarchical_allgather,
                                  int cache_enabled) {
  Runtime::Get().SetTunedToggles(hierarchical_allreduce != 0,
                                 hierarchical_allgather != 0,
                                 cache_enabled != 0);
}

// Per-payload schedule dispatch table (topology-probed): rank 0
// installs a piecewise-constant payload_bytes -> {flat(0), hier(1)}
// map per op kind (0 = allreduce, 1 = allgather); the coordinator
// stamps each response's choice from its FINAL fused payload, so
// table swaps stay rank-consistent like every other stream stamp.
// max_bytes must be ascending with the last entry == INT64_MAX;
// malformed tables are ignored.
void hvd_native_set_schedule_table(int kind, const int64_t* max_bytes,
                                   const int32_t* hierarchical, int n) {
  std::vector<ScheduleSegment> segs;
  segs.reserve(n > 0 ? n : 0);
  for (int i = 0; i < n; ++i)
    segs.push_back({max_bytes[i], hierarchical[i] != 0});
  Runtime::Get().SetScheduleTable(kind, std::move(segs));
}

// Response-cache toggle alone (the dispatch plane owns the schedule
// choice once a table is installed; flipping the cache must not
// clobber it the way set_tuned_toggles' whole-range reinstall would).
void hvd_native_set_cache_enabled(int cache_enabled) {
  Runtime::Get().SetCacheOn(cache_enabled != 0);
}

// Eager wire compression (quantized collective engine): rank 0's
// config/tuner picks the device-plane wire format; the coordinator
// stamps it per round (ResponseList::wire_compression) so every rank
// builds the same staged-buffer program mid-flip.  The getter returns
// the stream-adopted value (0 none, 1 bf16, 2 int8, 3 int4, 4 fp16).
void hvd_native_set_wire_compression(int code) {
  Runtime::Get().SetWireCompression(code);
}

int hvd_native_wire_compression() {
  return Runtime::Get().WireCompression();
}

void hvd_native_counters(int64_t* bytes, double* seconds) {
  Runtime::Get().ReadCounters(bytes, seconds);
}

// Self-healing wire fabric counters (net.cc escalation ladder), consumed
// by hvd.net / hvd.metrics and by hang reports to tell "retrying,
// deadline not yet reached" from "wedged".  Layout (n capped):
//   [0] retries          — recovery attempts, any rung
//   [1] reconnects       — connections re-established and resumed
//   [2] renegotiations   — ring re-formations around a dead link
//   [3] resets_avoided   — ops/collectives completed after >= 1 recovery
//   [4] chaos_injected   — faults the seeded chaos layer fired
//   [5] recovering_now   — channels currently mid-recovery (> 0 means a
//                          retry ladder is live right now)
//   [6] last_recovery_age_ms — ms since the last recovery activity
//                              (-1: never)
//   [7..10] dev diagnostics: wall us inside channel Send/Recv + op
//           counts (protocol-cost attribution; not exported to metrics)
int hvd_native_net_counters(int64_t* out, int n) {
  NetCountersState& c = NetCounters();
  int64_t vals[15] = {
      c.retries.load(),        c.reconnects.load(),
      c.renegotiations.load(), c.resets_avoided.load(),
      c.chaos_injected.load(), c.recovering_now.load(),
      c.last_recovery_ms.load() == 0
          ? -1
          : SteadyNowMs() - c.last_recovery_ms.load(),
      c.send_us.load(),        c.recv_us.load(),
      c.send_ops.load(),       c.recv_ops.load(),
      c.pump_wait_us.load(),   c.pump_read_us.load(),
      c.write_us.load(),       c.cvwait_us.load()};
  int m = n < 15 ? n : 15;
  for (int i = 0; i < m; ++i) out[i] = vals[i];
  return m;
}

// Stall-inspector snapshot for the Python-side hang-diagnosis watchdog:
// fills buf with a JSON array of tensors past the warning window (name,
// request type, age, missing + submitted rank lists).  Returns the number
// of bytes written (truncated to cap-1), or the full length when buf is
// NULL — call twice to size.  Coordinator-only; other ranks get "[]".
int hvd_native_stalled_json(char* buf, int cap) {
  std::string s = Runtime::Get().StalledJson();
  int n = static_cast<int>(s.size());
  if (!buf || cap <= 0) return n;
  int c = n < cap - 1 ? n : cap - 1;
  memcpy(buf, s.data(), c);
  buf[c] = '\0';
  return c;
}

void hvd_native_start_timeline(const char* filename) {
  Runtime::Get().StartTimeline(filename);
}

void hvd_native_stop_timeline() { Runtime::Get().StopTimeline(); }

const char* hvd_native_last_error() {
  std::lock_guard<std::mutex> lk(g_err_mu);
  return g_last_error.c_str();
}

}  // extern "C"
