// Same-host bulk transport: one-directional POSIX-shm channels.
//
// TPU-native equivalent of the reference's shared-memory staging for
// same-node ranks (MPIHierarchicalAllgather's POSIX shm window,
// mpi_operations.cc MEMCPY_IN_SHARED_BUFFER): local peers move collective
// payloads through a double-buffered shared segment (two memcpys, no
// kernel socket copies, no syscalls on the bulk path) while remote peers
// stay on TCP.  Synchronization is head/tail atomics in the segment —
// no tokens on the sockets, so the control plane is untouched.
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>

#include "common.h"

namespace hvdtpu {

class ShmChannel {
 public:
  static constexpr size_t kSlots = 2;
  static constexpr size_t kSlotBytes = 4 << 20;

  struct Hdr {
    std::atomic<uint64_t> head;  // chunks published by the producer
    char pad0[64 - sizeof(std::atomic<uint64_t>)];
    std::atomic<uint64_t> tail;  // chunks consumed by the consumer
    char pad1[64 - sizeof(std::atomic<uint64_t>)];
    uint64_t lens[kSlots];
    // Cross-memory-attach descriptors: addrs[slot] != 0 marks the chunk
    // as a reference into the producer's address space (the consumer
    // pulls it with process_vm_readv — zero staging copies) instead of
    // data in the slot.
    uint64_t addrs[kSlots];
    int32_t producer_pid;
    uint64_t probe_magic;          // consumer CMA capability probe value
    uint64_t producer_probe_addr;  // producer's own VA of probe_magic
    // Set by a producer that aborted a transfer without draining: any
    // still-published descriptor may point at reused memory, so the
    // consumer must treat reads after this as failed, never as data.
    std::atomic<uint32_t> poisoned;
  };

  // Producer side (the sending rank) creates; consumer opens.  Both
  // return nullptr on failure (no /dev/shm, permission, size) — callers
  // fall back to TCP.
  static std::unique_ptr<ShmChannel> Create(const std::string& name);
  static std::unique_ptr<ShmChannel> Open(const std::string& name);
  ~ShmChannel();

  // Remove the name (mapping stays valid); call once both ends mapped so
  // a crash cannot leak the segment.
  void Unlink();

  // Producer: wait (bounded) for a free slot, copy n <= kSlotBytes in,
  // publish.
  Status Push(const uint8_t* data, size_t n);

  // Producer, CMA mode: publish a descriptor for an arbitrarily large
  // region of this process's memory; the consumer pulls it directly.
  // The caller MUST call WaitDrained() before reusing/modifying the
  // region (the consumer reads it asynchronously).
  Status PushRef(const uint8_t* data, size_t n);
  Status WaitDrained();

  // Consumer: wait (bounded) for a published chunk and land up to
  // max_n bytes at dst (slot memcpy or direct process_vm_readv for
  // descriptors); *got reports the chunk size.
  Status PopInto(uint8_t* dst, size_t max_n, size_t* got);

  // Consumer-side CMA capability: can this process read the producer's
  // memory? (probed once against probe_magic).
  bool ProbeCma();
  // Producer side: enable descriptor publishing (set after the peer
  // reported a successful probe).
  void EnableRefs() { use_refs_ = true; }
  bool refs_enabled() const { return use_refs_; }
  // Mark the channel unusable: consumers' in-flight/later reads fail
  // instead of trusting descriptors into memory the producer may have
  // freed (producer-side error teardown).
  void Poison() { hdr_->poisoned.store(1, std::memory_order_release); }

 private:
  ShmChannel() = default;
  Hdr* hdr_ = nullptr;
  uint8_t* slots_ = nullptr;
  void* map_ = nullptr;
  size_t map_bytes_ = 0;
  std::string name_;
  bool use_refs_ = false;
};

}  // namespace hvdtpu
