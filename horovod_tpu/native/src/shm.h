// Same-host bulk transport: one-directional POSIX-shm channels.
//
// TPU-native equivalent of the reference's shared-memory staging for
// same-node ranks (MPIHierarchicalAllgather's POSIX shm window,
// mpi_operations.cc MEMCPY_IN_SHARED_BUFFER): local peers move collective
// payloads through a double-buffered shared segment (two memcpys, no
// kernel socket copies, no syscalls on the bulk path) while remote peers
// stay on TCP.  Synchronization is head/tail atomics in the segment —
// no tokens on the sockets, so the control plane is untouched.
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <memory>
#include <string>

#include "common.h"

namespace hvdtpu {

class ShmChannel {
 public:
  static constexpr size_t kSlots = 2;
  static constexpr size_t kSlotBytes = 4 << 20;

  struct Hdr {
    std::atomic<uint64_t> head;  // chunks published by the producer
    char pad0[64 - sizeof(std::atomic<uint64_t>)];
    std::atomic<uint64_t> tail;  // chunks consumed by the consumer
    char pad1[64 - sizeof(std::atomic<uint64_t>)];
    uint64_t lens[kSlots];
  };

  // Producer side (the sending rank) creates; consumer opens.  Both
  // return nullptr on failure (no /dev/shm, permission, size) — callers
  // fall back to TCP.
  static std::unique_ptr<ShmChannel> Create(const std::string& name);
  static std::unique_ptr<ShmChannel> Open(const std::string& name);
  ~ShmChannel();

  // Remove the name (mapping stays valid); call once both ends mapped so
  // a crash cannot leak the segment.
  void Unlink();

  // Producer: wait (bounded) for a free slot, copy n <= kSlotBytes in,
  // publish.
  Status Push(const uint8_t* data, size_t n);

  // Consumer: wait (bounded) for a published chunk, hand the mapped bytes
  // to consume(ptr, len), release the slot.
  Status Pop(const std::function<void(const uint8_t*, size_t)>& consume);

 private:
  ShmChannel() = default;
  Hdr* hdr_ = nullptr;
  uint8_t* slots_ = nullptr;
  void* map_ = nullptr;
  size_t map_bytes_ = 0;
  std::string name_;
};

}  // namespace hvdtpu
