// Coordinator/worker control-plane protocol.
//
// Capability parity with the reference Controller (controller.h:37-223,
// controller.cc:69-449 ComputeResponseList): workers announce ready tensors
// each cycle; rank 0 counts announcements per tensor, validates cross-rank
// consistency (dtype/shape/op/root/scale — controller.cc:482-706), fuses
// ready allreduces under the fusion threshold (FuseResponses,
// controller.cc:777-914), and broadcasts the ResponseList.  Join / barrier /
// shutdown ride the same rounds.  The transport is the synchronous
// gather+bcast of MPIController (mpi_controller.cc:108-199) over TCP.
// A StallInspector (stall_inspector.h:31-100) flags tensors reported by
// some-but-not-all ranks past a warning window.
#pragma once

#include <atomic>
#include <chrono>
#include <cstdint>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <vector>

#include "cache.h"
#include "net.h"
#include "timeline.h"
#include "wire.h"

namespace hvdtpu {

struct ControllerConfig {
  int64_t fusion_threshold_bytes = 64 * 1024 * 1024;
  double stall_warning_s = 60.0;
  double stall_shutdown_s = 0.0;  // 0 = never
  size_t cache_capacity = 1024;   // response cache entries (0 = disabled)
};

// One segment of a per-payload schedule dispatch table: payloads up to
// max_bytes (inclusive) use the hierarchical schedule iff hierarchical.
// A table is a sorted (ascending max_bytes) list whose last segment has
// max_bytes == INT64_MAX, so every payload maps to exactly one choice.
struct ScheduleSegment {
  int64_t max_bytes;
  bool hierarchical;
};

// Op kinds with a flat/hierarchical schedule choice (indices into the
// coordinator's table array; broadcast/alltoall have no such choice).
enum ScheduleKind { kScheduleAllreduce = 0, kScheduleAllgather = 1 };
constexpr int kNumScheduleKinds = 2;

class Controller {
 public:
  Controller(Network* net, const ControllerConfig& cfg)
      : net_(net), cfg_(cfg), cache_(cfg.cache_capacity) {}

  // Synchronous round: every rank calls this every cycle. Returns the
  // coordinator's ResponseList.
  Status Exchange(const RequestList& mine, ResponseList* out);

  // Autotune hook (ParameterManager, reference parameter_manager.h:42-246):
  // adjust the coordinator's fusion threshold at runtime.
  void SetFusionThreshold(int64_t bytes) {
    fusion_threshold_.store(bytes);
  }

  // Per-payload schedule dispatch (topology-probed): a piecewise-
  // constant map payload bytes -> {flat, hierarchical} per op kind.
  // The coordinator consults it once each response's FINAL (fused)
  // payload is known and stamps the choice into Response::hierarchical,
  // so mid-run table swaps (probe install, tuner crossover shifts) stay
  // rank-consistent exactly like the wire_compression stamp.  An empty
  // or unsorted segment list is rejected (table unchanged).
  void SetScheduleTable(int kind, std::vector<ScheduleSegment> segs);

  // Response-cache toggle alone (the dispatch plane owns the schedule
  // choice; the cache categorical is still a plain global).
  void SetCacheOn(bool cache_on) { cache_on_.store(cache_on); }

  // Legacy global toggles (reference parameter_manager.h:91-93): now a
  // degenerate single-segment table per kind — the whole payload range
  // maps to one schedule.  Kept as the config/tuner entry point for
  // jobs without a probe-seeded table.
  void SetAlgoToggles(bool hier_allreduce, bool hier_allgather,
                      bool cache_on) {
    SetScheduleTable(kScheduleAllreduce,
                     {{INT64_MAX, hier_allreduce}});
    SetScheduleTable(kScheduleAllgather,
                     {{INT64_MAX, hier_allgather}});
    cache_on_.store(cache_on);
  }

  // Eager wire-compression choice (quantized collective engine): set by
  // rank 0's config/tuner, stamped into every round's ResponseList
  // (ResponseList::wire_compression) so the device-plane executor picks
  // the same staged wire format on every rank mid-flip.
  void SetWireCompression(int code) { wire_compression_.store(code); }

  // Coordinator-side timeline: per-rank NEGOTIATE ready instants are
  // recorded as each rank's report arrives (reference timeline.cc:496-541).
  void set_timeline(Timeline* t) { timeline_ = t; }

  // Stall-inspector snapshot for the flight-recorder escalation path
  // (debug/hang.py): JSON array of tensors past the warning window, each
  // naming the stuck collective, its age and the per-tensor missing /
  // submitted rank lists.  Coordinator-only (other ranks see "[]").
  // Thread-safe against the background loop's Coordinate().
  std::string StalledJson();
  int64_t effective_fusion_threshold() const {
    int64_t dyn = fusion_threshold_.load();
    return dyn > 0 ? dyn : cfg_.fusion_threshold_bytes;
  }

 private:
  ResponseList Coordinate(std::vector<RequestList>& lists);
  void AbsorbCacheHits(const std::vector<RequestList>& lists,
                       ResponseList& rl);
  void CheckStalls(ResponseList& rl);
  void StampSchedules(ResponseList& rl);

  struct PendingTensor {
    Request first;                       // first-reported metadata
    std::map<int32_t, Request> by_rank;  // all reports
    std::chrono::steady_clock::time_point first_report;
    bool stall_warned = false;
  };

  void RecordReady(const std::string& name, int32_t rank);

  Network* net_;
  ControllerConfig cfg_;
  Timeline* timeline_ = nullptr;
  std::atomic<int64_t> fusion_threshold_{0};  // 0 -> use cfg_ value
  std::atomic<bool> cache_on_{true};
  std::atomic<int> wire_compression_{0};
  // Per-kind dispatch tables (default: everything flat — the seed
  // repo's pre-probe behavior).  sched_mu_ guards installs from the
  // application/probe thread against the background loop's stamping.
  std::mutex sched_mu_;
  std::vector<ScheduleSegment> sched_[kNumScheduleKinds] = {
      {{INT64_MAX, false}}, {{INT64_MAX, false}}};
  // Missing (non-joined, not-yet-reported) ranks for one pending tensor.
  std::vector<int32_t> MissingRanks(const PendingTensor& pt) const;

  // Coordinator-only state (persists across rounds).  table_mu_ lets
  // StalledJson() — called from an application watchdog thread — read
  // table_/joined_ while the background loop's Coordinate() mutates them.
  std::mutex table_mu_;
  ResponseCache cache_;
  std::map<std::string, PendingTensor> table_;
  std::vector<std::string> arrival_order_;
  std::set<int32_t> joined_;
  std::set<int32_t> barriered_;
  std::set<int32_t> shutdown_;
  int32_t last_join_rank_ = -1;
};

}  // namespace hvdtpu
