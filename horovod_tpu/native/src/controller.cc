#include "controller.h"

#include <cstdio>
#include <sstream>

namespace hvdtpu {

namespace {

std::string ShapeStr(const std::vector<int64_t>& s) {
  std::ostringstream os;
  os << "[";
  for (size_t i = 0; i < s.size(); ++i) os << (i ? "," : "") << s[i];
  os << "]";
  return os.str();
}

// Cross-rank consistency validation (reference controller.cc:482-706).
std::string Validate(const std::map<int32_t, Request>& by_rank) {
  const Request* first = nullptr;
  int32_t first_rank = 0;
  for (const auto& [rank, q] : by_rank) {
    if (!first) {
      first = &q;
      first_rank = rank;
      continue;
    }
    std::ostringstream err;
    if (q.type != first->type) {
      err << "mismatched collective type between rank " << first_rank
          << " and rank " << rank;
      return err.str();
    }
    if (q.dtype != first->dtype) {
      err << "mismatched dtype between rank " << first_rank << " and rank "
          << rank;
      return err.str();
    }
    if (q.op != first->op) {
      err << "mismatched reduce op between rank " << first_rank
          << " and rank " << rank;
      return err.str();
    }
    if (q.prescale != first->prescale || q.postscale != first->postscale) {
      err << "mismatched prescale/postscale factors";
      return err.str();
    }
    if (q.device != first->device) {
      // Reference validates device placement consistency the same way
      // (controller.cc:482-706): a collective must be all-HBM or all-host.
      err << "mismatched device placement: rank " << first_rank << " is "
          << (first->device ? "device" : "host") << ", rank " << rank
          << " is " << (q.device ? "device" : "host");
      return err.str();
    }
    if (q.type == RequestType::ALLREDUCE ||
        q.type == RequestType::BROADCAST) {
      if (q.shape != first->shape) {
        err << "mismatched shape: rank " << first_rank << " has "
            << ShapeStr(first->shape) << ", rank " << rank << " has "
            << ShapeStr(q.shape);
        return err.str();
      }
    }
    if (q.type == RequestType::ALLGATHER && !q.shape.empty() &&
        !first->shape.empty()) {
      // All dims but the first must match (controller.cc:576-648).
      if (std::vector<int64_t>(q.shape.begin() + 1, q.shape.end()) !=
          std::vector<int64_t>(first->shape.begin() + 1,
                               first->shape.end())) {
        err << "mismatched allgather trailing dims";
        return err.str();
      }
    }
    if (q.type == RequestType::BROADCAST && q.root_rank != first->root_rank) {
      err << "mismatched broadcast root";
      return err.str();
    }
  }
  return "";
}

int64_t NumElements(const std::vector<int64_t>& shape) {
  int64_t n = 1;
  for (auto d : shape) n *= d;
  return n;
}

}  // namespace

Status Controller::Exchange(const RequestList& mine, ResponseList* out) {
  Writer w;
  SerializeRequestList(mine, w);
  // Control-profile channel transfers: resilient to connection resets
  // (reconnect-and-resume through the persistent listeners) but with the
  // raw protocol's open-ended patience — a worker blocked in a long
  // device collective between rounds is not a network fault.
  if (net_->rank() == 0) {
    std::vector<RequestList> lists(net_->size());
    lists[0] = mine;
    for (int r = 1; r < net_->size(); ++r) {
      std::vector<uint8_t> frame;
      Status st = net_->chan(r)->RecvMsg(frame);
      if (!st.ok()) return st;
      Reader rd(frame.data(), frame.size());
      lists[r] = DeserializeRequestList(rd);
    }
    ResponseList rl = Coordinate(lists);
    Writer rw;
    SerializeResponseList(rl, rw);
    for (int r = 1; r < net_->size(); ++r) {
      Status st = net_->chan(r)->SendMsg(rw.buf);
      if (!st.ok()) return st;
    }
    *out = rl;
  } else {
    Status st = net_->coordinator_chan()->SendMsg(w.buf);
    if (!st.ok()) return st;
    std::vector<uint8_t> frame;
    st = net_->coordinator_chan()->RecvMsg(frame);
    if (!st.ok()) return st;
    Reader rd(frame.data(), frame.size());
    *out = DeserializeResponseList(rd);
  }
  return Status::OK();
}

void Controller::AbsorbCacheHits(const std::vector<RequestList>& lists,
                                 ResponseList& rl) {
  // Translate each rank's cache-hit bits into table_ entries using the
  // coordinator's cached per-rank metadata (reference fast path,
  // controller.cc:181-237).  Bits hit by *every* non-joined rank are
  // reported back as valid_cache_bits for the deterministic LRU touch.
  const int size = net_->size();
  std::map<uint32_t, int> hit_counts;
  for (int r = 0; r < size; ++r) {
    const auto& bits = lists[r].cache_hits;
    for (size_t word = 0; word < bits.size(); ++word) {
      uint64_t w = bits[word];
      while (w) {
        uint32_t bit = word * 64 + __builtin_ctzll(w);
        w &= w - 1;
        if (!cache_.has_bit(bit)) {
          rl.resend_bits.push_back(bit);  // tell the rank to renegotiate
          continue;
        }
        const CachedTensor& ct = cache_.Get(bit);
        const std::string& name = ct.meta.name;
        auto it = table_.find(name);
        if (it == table_.end()) {
          PendingTensor pt;
          pt.first = ct.meta;
          pt.first_report = std::chrono::steady_clock::now();
          table_.emplace(name, std::move(pt));
          arrival_order_.push_back(name);
          it = table_.find(name);
        }
        auto rm = ct.by_rank.find(r);
        if (!it->second.by_rank.count(r)) RecordReady(name, r);
        it->second.by_rank[r] = rm != ct.by_rank.end() ? rm->second
                                                       : ct.meta;
        hit_counts[bit]++;
      }
    }
  }
  int needed = 0;
  for (int r = 0; r < size; ++r)
    if (!joined_.count(r)) needed++;
  for (const auto& [bit, count] : hit_counts)
    if (count >= needed && needed > 0)
      rl.valid_cache_bits.push_back(bit);
  cache_.Touch(rl.valid_cache_bits);
}

ResponseList Controller::Coordinate(std::vector<RequestList>& lists) {
  // One lock for the whole round: table_/arrival_order_/joined_ mutate
  // throughout, and StalledJson() (watchdog thread) must never observe a
  // half-built round.  Rounds are short (validation + response building,
  // no network I/O happens under Coordinate), so the watchdog's read
  // waits at most one round.
  std::lock_guard<std::mutex> table_lk(table_mu_);
  const int size = net_->size();
  ResponseList rl;
  const bool cache_on = cache_on_.load();
  rl.cache_on = cache_on;
  rl.wire_compression = wire_compression_.load();

  // Absorb flags + requests.
  for (int r = 0; r < size; ++r) {
    if (lists[r].join) joined_.insert(r);
    if (lists[r].barrier) barriered_.insert(r);
    if (lists[r].shutdown) shutdown_.insert(r);
    for (auto& q : lists[r].requests) {
      auto it = table_.find(q.name);
      if (it == table_.end()) {
        PendingTensor pt;
        pt.first = q;
        pt.first_report = std::chrono::steady_clock::now();
        pt.by_rank[r] = q;
        table_.emplace(q.name, std::move(pt));
        arrival_order_.push_back(q.name);
        RecordReady(q.name, r);
      } else {
        if (!it->second.by_rank.count(r)) RecordReady(q.name, r);
        it->second.by_rank[r] = q;
      }
      // Note: a full request for a cached name does NOT invalidate the
      // coordinator entry — other ranks may still be announcing via its
      // bit this very round; the entry is refreshed when the tensor's
      // response is rebuilt below.
    }
  }
  if (cache_.enabled()) AbsorbCacheHits(lists, rl);

  // Find ready tensors (reported by every non-joined rank), preserving
  // arrival order for deterministic fusion across iterations.
  std::vector<std::string> ready;
  for (const auto& name : arrival_order_) {
    auto it = table_.find(name);
    if (it == table_.end()) continue;
    size_t needed = 0;
    for (int r = 0; r < size; ++r)
      if (!joined_.count(r)) needed++;
    size_t have = 0;
    for (const auto& [r, q] : it->second.by_rank)
      if (!joined_.count(r)) have++;
    if (have >= needed && needed > 0) ready.push_back(name);
  }

  // Build responses: validate, then fuse compatible allreduces under the
  // threshold (FuseResponses, controller.cc:777-914).
  Response* open_fusion = nullptr;
  int64_t open_bytes = 0;
  for (const auto& name : ready) {
    PendingTensor& pt = table_[name];
    std::string err = Validate(pt.by_rank);
    const Request& q = pt.first;
    // Cache slot for this tensor: reuse its bit or assign a fresh one;
    // refresh the per-rank metadata (reference ResponseCache put path).
    uint32_t cache_bit = UINT32_MAX;
    // cache_on gates NEW bit assignment only: bits already announced
    // this round were honored by AbsorbCacheHits above, so a flip never
    // strands an in-flight announcement (it drains via resend_bits).
    if (err.empty() && cache_.enabled() && cache_on) {
      int32_t b = cache_.BitForName(name);
      cache_bit = b >= 0 ? static_cast<uint32_t>(b) : cache_.Assign(name);
      cache_.InsertAt(cache_bit, name, q);
      cache_.GetMutable(cache_bit).by_rank = pt.by_rank;
    }
    if (!err.empty()) {
      Response resp;
      resp.type = q.type;
      resp.names = {name};
      resp.error = err;
      rl.responses.push_back(resp);
      cache_.Invalidate(name);
      open_fusion = nullptr;
    } else if (q.type == RequestType::ALLREDUCE) {
      int64_t bytes = NumElements(q.shape) * DataTypeSize(q.dtype);
      bool fusible =
          open_fusion != nullptr && open_fusion->dtype == q.dtype &&
          open_fusion->op == q.op && open_fusion->prescale == q.prescale &&
          open_fusion->postscale == q.postscale &&
          open_fusion->device == q.device &&
          open_bytes + bytes <= effective_fusion_threshold();
      if (fusible) {
        open_fusion->names.push_back(name);
        open_fusion->sizes.push_back(NumElements(q.shape));
        open_fusion->cache_bits.push_back(cache_bit);
        open_bytes += bytes;
      } else {
        Response resp;
        resp.type = q.type;
        resp.names = {name};
        resp.dtype = q.dtype;
        resp.op = q.op;
        resp.prescale = q.prescale;
        resp.postscale = q.postscale;
        resp.device = q.device;
        resp.sizes = {NumElements(q.shape)};
        resp.cache_bits = {cache_bit};
        rl.responses.push_back(resp);
        open_fusion = &rl.responses.back();
        open_bytes = bytes;
      }
    } else if (q.type == RequestType::ALLGATHER) {
      Response resp;
      resp.type = q.type;
      resp.names = {name};
      resp.dtype = q.dtype;
      // sizes = first dims per rank (0 for joined ranks), then row_elems
      // (product of trailing dims) as the final element so joined ranks can
      // size their ring blocks.
      for (int r = 0; r < size; ++r) {
        auto itq = pt.by_rank.find(r);
        resp.sizes.push_back(
            itq == pt.by_rank.end() || itq->second.shape.empty()
                ? 0 : itq->second.shape[0]);
      }
      int64_t row_elems = 1;
      for (size_t d = 1; d < q.shape.size(); ++d) row_elems *= q.shape[d];
      resp.sizes.push_back(row_elems);
      resp.device = q.device;
      resp.cache_bits = {cache_bit};
      rl.responses.push_back(resp);
      open_fusion = nullptr;
    } else if (q.type == RequestType::BROADCAST) {
      Response resp;
      resp.type = q.type;
      resp.names = {name};
      resp.dtype = q.dtype;
      resp.root_rank = q.root_rank;
      resp.device = q.device;
      resp.sizes = {NumElements(q.shape)};
      resp.cache_bits = {cache_bit};
      rl.responses.push_back(resp);
      open_fusion = nullptr;
    } else if (q.type == RequestType::ALLTOALL) {
      Response resp;
      resp.type = q.type;
      resp.names = {name};
      resp.dtype = q.dtype;
      // sizes = row-split matrix, row-major [src * size + dst], then
      // row_elems appended; joined ranks contribute zero rows.
      resp.sizes.assign(static_cast<size_t>(size) * size, 0);
      for (int r = 0; r < size; ++r) {
        auto itq = pt.by_rank.find(r);
        if (itq == pt.by_rank.end()) continue;
        for (int d = 0; d < size && d < (int)itq->second.splits.size(); ++d)
          resp.sizes[static_cast<size_t>(r) * size + d] =
              itq->second.splits[d];
      }
      int64_t a2a_row_elems = 1;
      for (size_t d = 1; d < q.shape.size(); ++d) a2a_row_elems *= q.shape[d];
      resp.sizes.push_back(a2a_row_elems);
      resp.device = q.device;
      resp.cache_bits = {cache_bit};
      rl.responses.push_back(resp);
      open_fusion = nullptr;
    }
    table_.erase(name);
  }
  if (!ready.empty()) {
    // Compact arrival order.
    std::vector<std::string> rest;
    for (const auto& n : arrival_order_)
      if (table_.count(n)) rest.push_back(n);
    arrival_order_ = std::move(rest);
  }

  // Join: when every rank has joined, release and report the last rank.
  if (!joined_.empty() && static_cast<int>(joined_.size()) == size) {
    rl.last_joined_rank = *joined_.rbegin();
    joined_.clear();
  }
  // Barrier: release when all ranks are waiting.
  if (static_cast<int>(barriered_.size()) == size) {
    rl.barrier_release = true;
    barriered_.clear();
  }
  // Shutdown once every rank asked for it.
  if (static_cast<int>(shutdown_.size()) == size) rl.shutdown = true;

  StampSchedules(rl);
  CheckStalls(rl);
  return rl;
}

void Controller::SetScheduleTable(int kind,
                                  std::vector<ScheduleSegment> segs) {
  if (kind < 0 || kind >= kNumScheduleKinds || segs.empty()) return;
  // Reject malformed tables (unsorted, or not covering the full payload
  // range) instead of stamping from them: a bad install must not make
  // the dispatch undefined for some payload size.
  for (size_t i = 1; i < segs.size(); ++i)
    if (segs[i].max_bytes <= segs[i - 1].max_bytes) return;
  if (segs.back().max_bytes != INT64_MAX) return;
  std::lock_guard<std::mutex> lk(sched_mu_);
  sched_[kind] = std::move(segs);
}

void Controller::StampSchedules(ResponseList& rl) {
  // Per-payload dispatch: stamp each response's schedule choice once
  // its FINAL (post-fusion) payload is known.  The stamp — not any
  // rank-local state — is what execution consults, so a mid-run table
  // swap can never split the fleet across schedules for one Response.
  std::lock_guard<std::mutex> lk(sched_mu_);
  auto choose = [this](int kind, int64_t bytes) {
    for (const auto& seg : sched_[kind])
      if (bytes <= seg.max_bytes) return seg.hierarchical;
    return false;  // unreachable: last segment is INT64_MAX
  };
  for (auto& resp : rl.responses) {
    if (!resp.error.empty()) continue;
    const int64_t elem = DataTypeSize(resp.dtype);
    if (resp.type == RequestType::ALLREDUCE) {
      int64_t elems = 0;
      for (auto n : resp.sizes) elems += n;
      resp.hierarchical = choose(kScheduleAllreduce, elems * elem);
    } else if (resp.type == RequestType::ALLGATHER) {
      // sizes = per-rank first dims + trailing row_elems: the wire
      // payload is the FULL gathered result every rank ends up holding.
      int64_t dims = 0;
      for (size_t i = 0; i + 1 < resp.sizes.size(); ++i)
        dims += resp.sizes[i];
      resp.hierarchical =
          choose(kScheduleAllgather, dims * resp.sizes.back() * elem);
    }
  }
}

void Controller::RecordReady(const std::string& name, int32_t rank) {
  // Per-rank NEGOTIATE ready instant — the reference timeline's #1
  // debugging feature: which rank is late for which tensor
  // (timeline.cc:496-541).  pid = the reporting rank, so each rank's
  // readiness renders on its own process row.
  if (timeline_ && timeline_->active())
    timeline_->Record(name, "i", "NEGOTIATE_READY",
                      "{\"rank\":" + std::to_string(rank) + "}", rank);
}

std::vector<int32_t> Controller::MissingRanks(const PendingTensor& pt) const {
  std::vector<int32_t> missing;
  for (int r = 0; r < net_->size(); ++r)
    if (!pt.by_rank.count(r) && !joined_.count(r)) missing.push_back(r);
  return missing;
}

namespace {
std::string RankListStr(const std::vector<int32_t>& ranks) {
  std::string s = "[";
  for (size_t i = 0; i < ranks.size(); ++i)
    s += (i ? "," : "") + std::to_string(ranks[i]);
  return s + "]";
}

std::string JsonEscape(const std::string& in) {
  std::string out;
  for (char c : in) {
    if (c == '"' || c == '\\') out += '\\';
    if (static_cast<unsigned char>(c) < 0x20) { out += ' '; continue; }
    out += c;
  }
  return out;
}
}  // namespace

std::string Controller::StalledJson() {
  std::lock_guard<std::mutex> lk(table_mu_);
  auto now = std::chrono::steady_clock::now();
  std::ostringstream os;
  os << "[";
  bool first = true;
  for (const auto& [name, pt] : table_) {
    double age = std::chrono::duration<double>(now - pt.first_report).count();
    if (age <= cfg_.stall_warning_s) continue;
    std::vector<int32_t> submitted;
    for (const auto& [r, q] : pt.by_rank) submitted.push_back(r);
    os << (first ? "" : ",") << "{\"name\":\"" << JsonEscape(name)
       << "\",\"type\":" << static_cast<int>(pt.first.type)
       << ",\"age_s\":" << age
       << ",\"missing\":" << RankListStr(MissingRanks(pt))
       << ",\"submitted\":" << RankListStr(submitted) << "}";
    first = false;
  }
  os << "]";
  return os.str();
}

void Controller::CheckStalls(ResponseList& rl) {
  auto now = std::chrono::steady_clock::now();
  for (auto& [name, pt] : table_) {
    double age = std::chrono::duration<double>(now - pt.first_report).count();
    if (cfg_.stall_shutdown_s > 0 && age > cfg_.stall_shutdown_s) {
      Response resp;
      resp.type = pt.first.type;
      resp.names = {name};
      // The error every blocked rank sees must name the culprits, not
      // just the tensor — rank lists are the actionable half of a stall
      // post-mortem (which host to inspect / evict).
      resp.error = "stalled for " + std::to_string((int)age) +
                   "s; missing rank(s) " + RankListStr(MissingRanks(pt)) +
                   " never submitted within the shutdown window";
      rl.responses.push_back(resp);
      continue;
    }
    if (!pt.stall_warned && age > cfg_.stall_warning_s) {
      pt.stall_warned = true;
      fprintf(stderr,
              "[hvd_tpu coordinator] WARNING: tensor %s submitted by some "
              "ranks but rank(s) %s have not yet (%.0fs); possible stall\n",
              name.c_str(), RankListStr(MissingRanks(pt)).c_str(), age);
    }
  }
  // Purge entries flagged as errors by the stall shutdown above.
  for (const auto& resp : rl.responses)
    if (!resp.error.empty())
      for (const auto& n : resp.names) table_.erase(n);
}

}  // namespace hvdtpu
