#include "controller.h"

#include <cstdio>
#include <sstream>

namespace hvdtpu {

namespace {

std::string ShapeStr(const std::vector<int64_t>& s) {
  std::ostringstream os;
  os << "[";
  for (size_t i = 0; i < s.size(); ++i) os << (i ? "," : "") << s[i];
  os << "]";
  return os.str();
}

// Cross-rank consistency validation (reference controller.cc:482-706).
std::string Validate(const std::map<int32_t, Request>& by_rank) {
  const Request* first = nullptr;
  int32_t first_rank = 0;
  for (const auto& [rank, q] : by_rank) {
    if (!first) {
      first = &q;
      first_rank = rank;
      continue;
    }
    std::ostringstream err;
    if (q.type != first->type) {
      err << "mismatched collective type between rank " << first_rank
          << " and rank " << rank;
      return err.str();
    }
    if (q.dtype != first->dtype) {
      err << "mismatched dtype between rank " << first_rank << " and rank "
          << rank;
      return err.str();
    }
    if (q.op != first->op) {
      err << "mismatched reduce op between rank " << first_rank
          << " and rank " << rank;
      return err.str();
    }
    if (q.prescale != first->prescale || q.postscale != first->postscale) {
      err << "mismatched prescale/postscale factors";
      return err.str();
    }
    if (q.type == RequestType::ALLREDUCE ||
        q.type == RequestType::BROADCAST) {
      if (q.shape != first->shape) {
        err << "mismatched shape: rank " << first_rank << " has "
            << ShapeStr(first->shape) << ", rank " << rank << " has "
            << ShapeStr(q.shape);
        return err.str();
      }
    }
    if (q.type == RequestType::ALLGATHER && !q.shape.empty() &&
        !first->shape.empty()) {
      // All dims but the first must match (controller.cc:576-648).
      if (std::vector<int64_t>(q.shape.begin() + 1, q.shape.end()) !=
          std::vector<int64_t>(first->shape.begin() + 1,
                               first->shape.end())) {
        err << "mismatched allgather trailing dims";
        return err.str();
      }
    }
    if (q.type == RequestType::BROADCAST && q.root_rank != first->root_rank) {
      err << "mismatched broadcast root";
      return err.str();
    }
  }
  return "";
}

int64_t NumElements(const std::vector<int64_t>& shape) {
  int64_t n = 1;
  for (auto d : shape) n *= d;
  return n;
}

}  // namespace

Status Controller::Exchange(const RequestList& mine, ResponseList* out) {
  Writer w;
  SerializeRequestList(mine, w);
  if (net_->rank() == 0) {
    std::vector<RequestList> lists(net_->size());
    lists[0] = mine;
    for (int r = 1; r < net_->size(); ++r) {
      std::vector<uint8_t> frame;
      Status st = net_->peer(r)->RecvFrame(frame);
      if (!st.ok()) return st;
      Reader rd(frame.data(), frame.size());
      lists[r] = DeserializeRequestList(rd);
    }
    ResponseList rl = Coordinate(lists);
    Writer rw;
    SerializeResponseList(rl, rw);
    for (int r = 1; r < net_->size(); ++r) {
      Status st = net_->peer(r)->SendFrame(rw.buf);
      if (!st.ok()) return st;
    }
    *out = rl;
  } else {
    Status st = net_->coordinator()->SendFrame(w.buf);
    if (!st.ok()) return st;
    std::vector<uint8_t> frame;
    st = net_->coordinator()->RecvFrame(frame);
    if (!st.ok()) return st;
    Reader rd(frame.data(), frame.size());
    *out = DeserializeResponseList(rd);
  }
  return Status::OK();
}

ResponseList Controller::Coordinate(std::vector<RequestList>& lists) {
  const int size = net_->size();
  ResponseList rl;

  // Absorb flags + requests.
  for (int r = 0; r < size; ++r) {
    if (lists[r].join) joined_.insert(r);
    if (lists[r].barrier) barriered_.insert(r);
    if (lists[r].shutdown) shutdown_.insert(r);
    for (auto& q : lists[r].requests) {
      auto it = table_.find(q.name);
      if (it == table_.end()) {
        PendingTensor pt;
        pt.first = q;
        pt.first_report = std::chrono::steady_clock::now();
        pt.by_rank[r] = q;
        table_.emplace(q.name, std::move(pt));
        arrival_order_.push_back(q.name);
      } else {
        it->second.by_rank[r] = q;
      }
    }
  }

  // Find ready tensors (reported by every non-joined rank), preserving
  // arrival order for deterministic fusion across iterations.
  std::vector<std::string> ready;
  for (const auto& name : arrival_order_) {
    auto it = table_.find(name);
    if (it == table_.end()) continue;
    size_t needed = 0;
    for (int r = 0; r < size; ++r)
      if (!joined_.count(r)) needed++;
    size_t have = 0;
    for (const auto& [r, q] : it->second.by_rank)
      if (!joined_.count(r)) have++;
    if (have >= needed && needed > 0) ready.push_back(name);
  }

  // Build responses: validate, then fuse compatible allreduces under the
  // threshold (FuseResponses, controller.cc:777-914).
  Response* open_fusion = nullptr;
  int64_t open_bytes = 0;
  for (const auto& name : ready) {
    PendingTensor& pt = table_[name];
    std::string err = Validate(pt.by_rank);
    const Request& q = pt.first;
    if (!err.empty()) {
      Response resp;
      resp.type = q.type;
      resp.names = {name};
      resp.error = err;
      rl.responses.push_back(resp);
      open_fusion = nullptr;
    } else if (q.type == RequestType::ALLREDUCE) {
      int64_t bytes = NumElements(q.shape) * DataTypeSize(q.dtype);
      bool fusible =
          open_fusion != nullptr && open_fusion->dtype == q.dtype &&
          open_fusion->op == q.op && open_fusion->prescale == q.prescale &&
          open_fusion->postscale == q.postscale &&
          open_bytes + bytes <= cfg_.fusion_threshold_bytes;
      if (fusible) {
        open_fusion->names.push_back(name);
        open_fusion->sizes.push_back(NumElements(q.shape));
        open_bytes += bytes;
      } else {
        Response resp;
        resp.type = q.type;
        resp.names = {name};
        resp.dtype = q.dtype;
        resp.op = q.op;
        resp.prescale = q.prescale;
        resp.postscale = q.postscale;
        resp.sizes = {NumElements(q.shape)};
        rl.responses.push_back(resp);
        open_fusion = &rl.responses.back();
        open_bytes = bytes;
      }
    } else if (q.type == RequestType::ALLGATHER) {
      Response resp;
      resp.type = q.type;
      resp.names = {name};
      resp.dtype = q.dtype;
      // sizes = first dims per rank (0 for joined ranks), then row_elems
      // (product of trailing dims) as the final element so joined ranks can
      // size their ring blocks.
      for (int r = 0; r < size; ++r) {
        auto itq = pt.by_rank.find(r);
        resp.sizes.push_back(
            itq == pt.by_rank.end() || itq->second.shape.empty()
                ? 0 : itq->second.shape[0]);
      }
      int64_t row_elems = 1;
      for (size_t d = 1; d < q.shape.size(); ++d) row_elems *= q.shape[d];
      resp.sizes.push_back(row_elems);
      rl.responses.push_back(resp);
      open_fusion = nullptr;
    } else if (q.type == RequestType::BROADCAST) {
      Response resp;
      resp.type = q.type;
      resp.names = {name};
      resp.dtype = q.dtype;
      resp.root_rank = q.root_rank;
      resp.sizes = {NumElements(q.shape)};
      rl.responses.push_back(resp);
      open_fusion = nullptr;
    } else if (q.type == RequestType::ALLTOALL) {
      Response resp;
      resp.type = q.type;
      resp.names = {name};
      resp.dtype = q.dtype;
      // sizes = row-split matrix, row-major [src * size + dst], then
      // row_elems appended; joined ranks contribute zero rows.
      resp.sizes.assign(static_cast<size_t>(size) * size, 0);
      for (int r = 0; r < size; ++r) {
        auto itq = pt.by_rank.find(r);
        if (itq == pt.by_rank.end()) continue;
        for (int d = 0; d < size && d < (int)itq->second.splits.size(); ++d)
          resp.sizes[static_cast<size_t>(r) * size + d] =
              itq->second.splits[d];
      }
      int64_t a2a_row_elems = 1;
      for (size_t d = 1; d < q.shape.size(); ++d) a2a_row_elems *= q.shape[d];
      resp.sizes.push_back(a2a_row_elems);
      rl.responses.push_back(resp);
      open_fusion = nullptr;
    }
    table_.erase(name);
  }
  if (!ready.empty()) {
    // Compact arrival order.
    std::vector<std::string> rest;
    for (const auto& n : arrival_order_)
      if (table_.count(n)) rest.push_back(n);
    arrival_order_ = std::move(rest);
  }

  // Join: when every rank has joined, release and report the last rank.
  if (!joined_.empty() && static_cast<int>(joined_.size()) == size) {
    rl.last_joined_rank = *joined_.rbegin();
    joined_.clear();
  }
  // Barrier: release when all ranks are waiting.
  if (static_cast<int>(barriered_.size()) == size) {
    rl.barrier_release = true;
    barriered_.clear();
  }
  // Shutdown once every rank asked for it.
  if (static_cast<int>(shutdown_.size()) == size) rl.shutdown = true;

  CheckStalls(rl);
  return rl;
}

void Controller::CheckStalls(ResponseList& rl) {
  auto now = std::chrono::steady_clock::now();
  for (auto& [name, pt] : table_) {
    double age = std::chrono::duration<double>(now - pt.first_report).count();
    if (cfg_.stall_shutdown_s > 0 && age > cfg_.stall_shutdown_s) {
      Response resp;
      resp.type = pt.first.type;
      resp.names = {name};
      resp.error = "stalled for " + std::to_string((int)age) +
                   "s; missing ranks exceeded shutdown window";
      rl.responses.push_back(resp);
      continue;
    }
    if (!pt.stall_warned && age > cfg_.stall_warning_s) {
      pt.stall_warned = true;
      std::string missing;
      for (int r = 0; r < net_->size(); ++r)
        if (!pt.by_rank.count(r) && !joined_.count(r))
          missing += (missing.empty() ? "" : ",") + std::to_string(r);
      fprintf(stderr,
              "[hvd_tpu coordinator] WARNING: tensor %s submitted by some "
              "ranks but rank(s) [%s] have not yet (%.0fs); possible stall\n",
              name.c_str(), missing.c_str(), age);
    }
  }
  // Purge entries flagged as errors by the stall shutdown above.
  for (const auto& resp : rl.responses)
    if (!resp.error.empty())
      for (const auto& n : resp.names) table_.erase(n);
}

}  // namespace hvdtpu
