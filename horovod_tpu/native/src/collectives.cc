#include "collectives.h"

#include <poll.h>
#include <sys/socket.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <cmath>
#include <functional>
#include <thread>
#include <cstring>

namespace hvdtpu {

namespace {

// --- fp16 / bf16 <-> fp32 (reference half.cc capability, portable) --------

inline float HalfToFloat(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t mant = h & 0x3ffu;
  uint32_t f;
  if (exp == 0) {
    if (mant == 0) {
      f = sign;
    } else {  // subnormal
      exp = 127 - 15 + 1;
      while ((mant & 0x400u) == 0) { mant <<= 1; exp--; }
      mant &= 0x3ffu;
      f = sign | (exp << 23) | (mant << 13);
    }
  } else if (exp == 0x1f) {
    f = sign | 0x7f800000u | (mant << 13);
  } else {
    f = sign | ((exp + 127 - 15) << 23) | (mant << 13);
  }
  float out;
  memcpy(&out, &f, 4);
  return out;
}

inline uint16_t FloatToHalf(float v) {
  uint32_t f;
  memcpy(&f, &v, 4);
  uint32_t sign = (f >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((f >> 23) & 0xff) - 127 + 15;
  uint32_t mant = f & 0x7fffffu;
  if (exp <= 0) {
    if (exp < -10) return static_cast<uint16_t>(sign);
    mant |= 0x800000u;
    uint32_t shift = 14 - exp;
    return static_cast<uint16_t>(sign | (mant >> shift));
  }
  if (exp >= 0x1f) return static_cast<uint16_t>(sign | 0x7c00u);
  return static_cast<uint16_t>(sign | (exp << 10) | (mant >> 13));
}

inline float Bf16ToFloat(uint16_t b) {
  uint32_t f = static_cast<uint32_t>(b) << 16;
  float out;
  memcpy(&out, &f, 4);
  return out;
}

inline uint16_t FloatToBf16(float v) {
  uint32_t f;
  memcpy(&f, &v, 4);
  // round-to-nearest-even
  uint32_t rounding = ((f >> 16) & 1u) + 0x7fffu;
  return static_cast<uint16_t>((f + rounding) >> 16);
}

// --- reduction kernels -----------------------------------------------------

template <typename T>
void ReduceT(T* dst, const T* src, int64_t n, ReduceOp op) {
  switch (op) {
    case ReduceOp::SUM:
    case ReduceOp::AVERAGE:  // divide happens at unpack
    case ReduceOp::ADASUM:   // handled elsewhere; fallthrough sum for safety
      for (int64_t i = 0; i < n; ++i) dst[i] += src[i];
      break;
    case ReduceOp::MIN:
      for (int64_t i = 0; i < n; ++i) dst[i] = std::min(dst[i], src[i]);
      break;
    case ReduceOp::MAX:
      for (int64_t i = 0; i < n; ++i) dst[i] = std::max(dst[i], src[i]);
      break;
    case ReduceOp::PRODUCT:
      for (int64_t i = 0; i < n; ++i) dst[i] *= src[i];
      break;
  }
}

template <typename U, float (*ToF)(U), U (*FromF)(float)>
void Reduce16(U* dst, const U* src, int64_t n, ReduceOp op) {
  for (int64_t i = 0; i < n; ++i) {
    float a = ToF(dst[i]);
    float b = ToF(src[i]);
    float r;
    switch (op) {
      case ReduceOp::MIN: r = std::min(a, b); break;
      case ReduceOp::MAX: r = std::max(a, b); break;
      case ReduceOp::PRODUCT: r = a * b; break;
      default: r = a + b; break;
    }
    dst[i] = FromF(r);
  }
}

void ReduceBuf(void* dst, const void* src, int64_t count, DataType dtype,
               ReduceOp op) {
  switch (dtype) {
    case DataType::FLOAT32:
      ReduceT(static_cast<float*>(dst), static_cast<const float*>(src),
              count, op);
      break;
    case DataType::FLOAT64:
      ReduceT(static_cast<double*>(dst), static_cast<const double*>(src),
              count, op);
      break;
    case DataType::INT32:
      ReduceT(static_cast<int32_t*>(dst), static_cast<const int32_t*>(src),
              count, op);
      break;
    case DataType::INT64:
      ReduceT(static_cast<int64_t*>(dst), static_cast<const int64_t*>(src),
              count, op);
      break;
    case DataType::UINT8:
    case DataType::BOOL:
      ReduceT(static_cast<uint8_t*>(dst), static_cast<const uint8_t*>(src),
              count, op);
      break;
    case DataType::INT8:
      ReduceT(static_cast<int8_t*>(dst), static_cast<const int8_t*>(src),
              count, op);
      break;
    case DataType::FLOAT16:
      Reduce16<uint16_t, HalfToFloat, FloatToHalf>(
          static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(src),
          count, op);
      break;
    case DataType::BFLOAT16:
      Reduce16<uint16_t, Bf16ToFloat, FloatToBf16>(
          static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(src),
          count, op);
      break;
  }
}

// One-directional streams: shared memory when the peer is on this host
// (Network::shm_tx/shm_rx — two memcpys, no syscalls on the bulk path),
// TCP otherwise.  Used standalone for chains/broadcasts and paired by
// FullDuplex for ring steps.

Status SendStream(Network& net, int peer, const uint8_t* buf, size_t n) {
  if (n == 0) return Status::OK();
  if (ShmChannel* ch = net.shm_tx(peer)) {
    if (ch->refs_enabled() && n >= (1u << 20)) {
      // Cross-memory attach: publish slot-sized descriptors into this
      // process's memory; the consumer pulls each directly (zero staging
      // copies) while later chunks are being published — keeping the
      // receiver's incremental reduction pipelined.  Must drain before
      // returning: the ring reuses the region in later steps.
      size_t off = 0;
      while (off < n) {
        size_t k = std::min(n - off, ShmChannel::kSlotBytes);
        Status st = ch->PushRef(buf + off, k);
        if (!st.ok()) return st;
        off += k;
      }
      return ch->WaitDrained();
    }
    size_t off = 0;
    while (off < n) {
      size_t k = std::min(n - off, ShmChannel::kSlotBytes);
      Status st = ch->Push(buf + off, k);
      if (!st.ok()) return st;
      off += k;
    }
    return Status::OK();
  }
  // TCP: the resilient channel (framing + acks + reconnect-and-resume
  // when HVD_TPU_NET_RESILIENCE is on; raw 4 MB chunks otherwise).
  return net.chan(peer)->Send(buf, n);
}

Status RecvStream(Network& net, int peer, uint8_t* dst, size_t n,
                  const std::function<void(size_t)>& on_recv = nullptr) {
  if (n == 0) return Status::OK();
  if (ShmChannel* ch = net.shm_rx(peer)) {
    size_t off = 0;
    while (off < n) {
      size_t got = 0;
      Status st = ch->PopInto(dst + off, n - off, &got);
      if (!st.ok()) return st;
      off += got;
      if (on_recv) on_recv(off);
    }
    return Status::OK();
  }
  return net.chan(peer)->Recv(dst, n, on_recv);
}

// Full-duplex transfer: simultaneously stream nsend bytes toward
// send_peer and nrecv bytes from recv_peer.  ``on_recv(total)``, when
// set, is invoked as the received prefix grows so the caller can overlap
// per-chunk work (reduction) with the remaining transfer.
// Threaded variant for large or shm transfers: the send stream runs on
// its own thread so both directions (and the on_recv reduction) proceed
// in parallel — a single-threaded poll loop serializes the kernel copies
// of the two directions onto one core and halves duplex throughput.
Status FullDuplexThreaded(Network& net, int send_peer,
                          const uint8_t* send_buf, size_t nsend,
                          int recv_peer, uint8_t* recv_buf, size_t nrecv,
                          const std::function<void(size_t)>& on_recv) {
  // Persistent helper thread instead of a per-call std::thread: the ring
  // calls this 2(P-1) times per allreduce, and the spawn+join cost
  // rivals the transfer itself at small payloads.
  Status send_st = Status::OK();
  net.duplex_helper().Run(
      [&] { send_st = SendStream(net, send_peer, send_buf, nsend); });
  Status st = RecvStream(net, recv_peer, recv_buf, nrecv, on_recv);
  net.duplex_helper().Wait();
  return st.ok() ? send_st : st;
}

// Zero-copy CMA star delivery of [buf, buf+total) from `root` to every
// other member (the reference's shared-memory window for fan-outs,
// MEMCPY_IN_SHARED_BUFFER in mpi_operations.cc): the root publishes
// cross-memory descriptors per member and all members pull directly
// from the root's memory CONCURRENTLY — one copy per member, none for
// the root, no per-hop forwarding.  The root picks and announces the
// mode in-band (one flag byte per member) so capability asymmetries can
// never desynchronize the framing.  When *used_star comes back false
// the caller runs its chain fallback.
//
// `star_min`: payloads below it skip the star AND the mode-byte
// exchange entirely — `total` is coordinator-provided and identical on
// every member, so the short-circuit is symmetric (SendStream's CMA
// path has the same >=1MB cutoff: descriptor+syscall overhead beats a
// shm-slot memcpy only on large payloads).
//
// `skip_off`/`skip_len` (indexed BY RANK, both or neither): each
// member's own block is excluded from its spans — the allgather case,
// where a member already holds its contribution; at most two
// descriptors per member around the hole.
Status StarFanout(Network& net, uint8_t* buf, int64_t total, int root,
                  const std::vector<int>& members, bool force_chain,
                  int64_t star_min, bool* used_star,
                  const std::vector<int64_t>* skip_off = nullptr,
                  const std::vector<int64_t>* skip_len = nullptr) {
  const int rank = net.rank();
  *used_star = false;
  if (total < star_min) return Status::OK();
  uint8_t star = 0;
  if (rank == root) {
    star = force_chain ? 0 : 1;
    for (int peer : members) {
      if (peer == root) continue;
      ShmChannel* ch = net.shm_tx(peer);
      if (ch == nullptr || !ch->refs_enabled()) star = 0;
    }
    for (int peer : members) {
      if (peer == root) continue;
      Status st = SendStream(net, peer, &star, 1);
      if (!st.ok()) return st;
    }
  } else {
    Status st = RecvStream(net, root, &star, 1);
    if (!st.ok()) return st;
  }
  *used_star = star != 0;
  if (!star || total == 0) return Status::OK();
  // Spans for rank r: [0, total) minus r's own block (when skipping).
  auto spans_for = [&](int r, std::pair<int64_t, int64_t> out[2]) {
    int64_t s0 = skip_off ? (*skip_off)[r] : 0;
    int64_t s1 = s0 + (skip_len ? (*skip_len)[r] : 0);
    int n = 0;
    if (s1 <= 0 || s0 >= total) {
      out[n++] = {0, total};
    } else {
      if (s0 > 0) out[n++] = {0, s0};
      if (s1 < total) out[n++] = {s1, total};
    }
    return n;
  };
  if (rank == root) {
    // On ANY failure mid-star, poison EVERY member channel before
    // returning: live descriptors into a buffer the failed op will free
    // must not let a slow member complete a "successful" pull from
    // reused memory (only the failing channel self-poisons).
    auto poison_all = [&] {
      for (int peer : members)
        if (peer != root)
          if (ShmChannel* ch = net.shm_tx(peer)) ch->Poison();
    };
    std::pair<int64_t, int64_t> spans[2];
    for (int peer : members) {
      if (peer == root) continue;
      int n = spans_for(peer, spans);
      for (int s = 0; s < n; ++s) {
        if (spans[s].second == spans[s].first) continue;
        Status st = net.shm_tx(peer)->PushRef(
            buf + spans[s].first, spans[s].second - spans[s].first);
        if (!st.ok()) {
          poison_all();
          return st;
        }
      }
    }
    // Drain AFTER publishing to every member: the pulls overlap.
    for (int peer : members) {
      if (peer == root) continue;
      Status st = net.shm_tx(peer)->WaitDrained();
      if (!st.ok()) {
        poison_all();
        return st;
      }
    }
    return Status::OK();
  }
  std::pair<int64_t, int64_t> spans[2];
  int n = spans_for(rank, spans);
  for (int s = 0; s < n; ++s) {
    const int64_t want = spans[s].second - spans[s].first;
    if (want == 0) continue;
    size_t got = 0;
    Status st = net.shm_rx(root)->PopInto(
        buf + spans[s].first, static_cast<size_t>(want), &got);
    if (!st.ok()) return st;
    if (static_cast<int64_t>(got) != want)
      return Status::Error("star fanout: descriptor length mismatch");
  }
  return Status::OK();
}

// Chunk-pipelined intra-node chain: the leader streams the payload down
// leader -> leader+1 -> ... -> leader+L-1; downstream ranks start
// forwarding while upstream bytes are still in flight.  Shared by the
// hierarchical allreduce/allgather/Adasum fan-out phases (the
// StarFanout fallback when a channel lacks cross-memory attach).
Status ChainFanout(Network& net, uint8_t* buf, int64_t nbytes, int rank,
                   int leader, int local_size) {
  const int pos = rank - leader;
  const int64_t kChunk = 4 << 20;
  for (int64_t off = 0; off < nbytes; off += kChunk) {
    int64_t k = std::min(kChunk, nbytes - off);
    if (pos > 0) {
      Status st = RecvStream(net, rank - 1, buf + off, k);
      if (!st.ok()) return st;
    }
    if (pos < local_size - 1) {
      Status st = SendStream(net, rank + 1, buf + off, k);
      if (!st.ok()) return st;
    }
  }
  return Status::OK();
}

Status FullDuplex(Network& net, int send_peer, const uint8_t* send_buf,
                  size_t nsend, int recv_peer, uint8_t* recv_buf,
                  size_t nrecv,
                  const std::function<void(size_t)>& on_recv = nullptr) {
  if (NetResilience().enabled || net.shm_tx(send_peer) != nullptr ||
      net.shm_rx(recv_peer) != nullptr || nsend + nrecv >= (4u << 20)) {
    // Resilient mode always takes the threaded variant: the interleaved
    // single-thread poll loop below speaks the raw byte protocol and
    // cannot parse frames.
    return FullDuplexThreaded(net, send_peer, send_buf, nsend, recv_peer,
                              recv_buf, nrecv, on_recv);
  }
  const int send_fd = net.chan(send_peer)->fd();
  const int recv_fd = net.chan(recv_peer)->fd();
  size_t sent = 0, received = 0;
  while (sent < nsend || received < nrecv) {
    struct pollfd fds[2];
    int nf = 0;
    int send_i = -1, recv_i = -1;
    if (sent < nsend) {
      fds[nf] = {send_fd, POLLOUT, 0};
      send_i = nf++;
    }
    if (received < nrecv) {
      fds[nf] = {recv_fd, POLLIN, 0};
      recv_i = nf++;
    }
    int pr = ::poll(fds, nf, 60000);
    if (pr < 0 && errno == EINTR) continue;
    if (pr <= 0)
      return Status::Error("collective transfer timeout/poll error");
    if (send_i >= 0 && (fds[send_i].revents & (POLLOUT | POLLERR))) {
      ssize_t k = ::send(send_fd, send_buf + sent,
                         std::min<size_t>(nsend - sent, 4 << 20),
                         MSG_NOSIGNAL | MSG_DONTWAIT);
      if (k < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR)
        return Status::Error("send failed in collective");
      if (k > 0) sent += k;
    }
    if (recv_i >= 0 && (fds[recv_i].revents & (POLLIN | POLLERR | POLLHUP))) {
      ssize_t k = ::recv(recv_fd, recv_buf + received,
                         std::min<size_t>(nrecv - received, 4 << 20),
                         MSG_DONTWAIT);
      if (k == 0) return Status::Aborted("peer closed during collective");
      if (k < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR)
        return Status::Error("recv failed in collective");
      if (k > 0) {
        received += k;
        if (on_recv) on_recv(received);
      }
    }
  }
  return Status::OK();
}

}  // namespace

namespace {
template <typename T>
void FloorDivT(T* p, int64_t count, int64_t d) {
  for (int64_t i = 0; i < count; ++i) {
    int64_t v = static_cast<int64_t>(p[i]);
    int64_t q = v / d;
    if (v % d != 0 && v < 0) q -= 1;  // d (world size) is positive
    p[i] = static_cast<T>(q);
  }
}
}  // namespace

bool FloorAverageInt(void* buf, int64_t count, DataType dtype,
                     int64_t divisor) {
  switch (dtype) {
    case DataType::UINT8:
      FloorDivT(static_cast<uint8_t*>(buf), count, divisor);
      return true;
    case DataType::INT8:
      FloorDivT(static_cast<int8_t*>(buf), count, divisor);
      return true;
    case DataType::INT32:
      FloorDivT(static_cast<int32_t*>(buf), count, divisor);
      return true;
    case DataType::INT64:
      FloorDivT(static_cast<int64_t*>(buf), count, divisor);
      return true;
    default:
      return false;
  }
}

void ScaleBuffer(void* buf, int64_t count, DataType dtype, double factor) {
  if (factor == 1.0) return;
  switch (dtype) {
    case DataType::FLOAT32: {
      float* p = static_cast<float*>(buf);
      for (int64_t i = 0; i < count; ++i) p[i] *= factor;
      break;
    }
    case DataType::FLOAT64: {
      double* p = static_cast<double*>(buf);
      for (int64_t i = 0; i < count; ++i) p[i] *= factor;
      break;
    }
    case DataType::FLOAT16: {
      uint16_t* p = static_cast<uint16_t*>(buf);
      for (int64_t i = 0; i < count; ++i)
        p[i] = FloatToHalf(HalfToFloat(p[i]) * factor);
      break;
    }
    case DataType::BFLOAT16: {
      uint16_t* p = static_cast<uint16_t*>(buf);
      for (int64_t i = 0; i < count; ++i)
        p[i] = FloatToBf16(Bf16ToFloat(p[i]) * factor);
      break;
    }
    case DataType::INT32: {
      int32_t* p = static_cast<int32_t*>(buf);
      for (int64_t i = 0; i < count; ++i)
        p[i] = static_cast<int32_t>(p[i] * factor);
      break;
    }
    case DataType::INT64: {
      int64_t* p = static_cast<int64_t*>(buf);
      for (int64_t i = 0; i < count; ++i)
        p[i] = static_cast<int64_t>(p[i] * factor);
      break;
    }
    default:
      break;
  }
}

Status RingAllreduceGroup(Network& net, void* vbuf, int64_t count,
                          DataType dtype, ReduceOp op,
                          const std::vector<int>& members) {
  const int m = static_cast<int>(members.size());
  if (m <= 1 || count == 0) return Status::OK();
  int idx = -1;
  for (int i = 0; i < m; ++i)
    if (members[i] == net.rank()) idx = i;
  if (idx < 0)
    return Status::InvalidArgument("rank not in allreduce group");
  uint8_t* buf = static_cast<uint8_t*>(vbuf);
  const size_t elem = DataTypeSize(dtype);

  // Segment boundaries (last segment may be short).
  const int64_t seg = (count + m - 1) / m;
  auto seg_start = [&](int s) { return std::min<int64_t>(seg * s, count); };
  auto seg_count = [&](int s) {
    return std::min<int64_t>(seg, count - seg_start(s));
  };

  const int right = members[(idx + 1) % m];
  const int left = members[(idx - 1 + m) % m];
  // Reused across calls: a fresh segment-sized allocation per op would
  // pay tens of ms of page faults on large tensors.
  static thread_local std::vector<uint8_t> scratch;
  if (scratch.size() < static_cast<size_t>(seg * elem))
    scratch.resize(seg * elem);

  // Reduce-scatter then allgather (bandwidth-optimal ring).  The
  // reduction of each received chunk runs incrementally inside the
  // transfer (on_recv), overlapping compute with the remaining wire time
  // instead of serializing a full-segment reduce after each step.
  for (int t = 0; t < m - 1; ++t) {
    int send_s = ((idx - t) % m + m) % m;
    int recv_s = ((idx - t - 1) % m + m) % m;
    uint8_t* recv_dst = buf + seg_start(recv_s) * elem;
    size_t reduced = 0;  // elements of this segment already reduced
    auto reduce_prefix = [&](size_t received_bytes) {
      size_t avail = received_bytes / elem;
      if (avail > reduced) {
        ReduceBuf(recv_dst + reduced * elem,
                  scratch.data() + reduced * elem,
                  static_cast<int64_t>(avail - reduced), dtype, op);
        reduced = avail;
      }
    };
    Status st = FullDuplex(net, right, buf + seg_start(send_s) * elem,
                           seg_count(send_s) * elem, left, scratch.data(),
                           seg_count(recv_s) * elem, reduce_prefix);
    if (!st.ok()) return st;
  }
  for (int t = 0; t < m - 1; ++t) {
    int send_s = ((idx + 1 - t) % m + m) % m;
    int recv_s = ((idx - t) % m + m) % m;
    Status st = FullDuplex(net, right, buf + seg_start(send_s) * elem,
                           seg_count(send_s) * elem, left,
                           buf + seg_start(recv_s) * elem,
                           seg_count(recv_s) * elem);
    if (!st.ok()) return st;
  }
  return Status::OK();
}

// ---------------------------------------------------------------------------
// Graded ring recovery (rungs 3-4 of the escalation ladder).  Rungs 1-2 —
// per-frame deadlines/acks and reconnect-and-resume — live inside the
// Channel layer (net.cc) and are transparent here.  When a reconnect
// exhausts, the flat ring collectives below agree the failure across the
// fleet through the coordinator star, re-form the ring with the dead link
// never an adjacency, reset the mesh at a fresh generation, and retry the
// attempt from a pre-collective snapshot.  Only when renegotiation
// exhausts (or the coordinator link itself is dead) does the error
// propagate into HorovodInternalError → elastic reset.
// ---------------------------------------------------------------------------

namespace {

// A cyclic order of 0..P-1 in which no pair in `bad` is adjacent.
// Deterministic DFS (identical on every rank, though only rank 0 runs
// it); returns false when no such cycle exists (e.g. a rank with P-1
// dead links).
bool RingOrderDfs(int P, const std::set<std::pair<int, int>>& bad,
                  std::vector<int>& order, std::vector<bool>& used,
                  int64_t* budget) {
  auto is_bad = [&](int a, int b) {
    return bad.count({std::min(a, b), std::max(a, b)}) != 0;
  };
  if (static_cast<int>(order.size()) == P)
    return !is_bad(order.back(), order.front());
  if ((*budget)-- <= 0) return false;
  for (int cand = 0; cand < P; ++cand) {
    if (used[cand] || is_bad(order.back(), cand)) continue;
    used[cand] = true;
    order.push_back(cand);
    if (RingOrderDfs(P, bad, order, used, budget)) return true;
    order.pop_back();
    used[cand] = false;
  }
  return false;
}

bool ComputeRingOrder(int P, const std::set<std::pair<int, int>>& bad,
                      std::vector<int>* out) {
  std::vector<int> order{0};
  std::vector<bool> used(P, false);
  used[0] = true;
  int64_t budget = 1 << 20;
  if (!RingOrderDfs(P, bad, order, used, &budget)) return false;
  *out = order;
  return true;
}

// Post-attempt rendezvous at the coordinator: every rank reports
// {ok, bad_peer}; rank 0 replies {action} and, on RETRY, the permuted
// ring order plus the merged bad-link pair list.  Runs after EVERY
// resilient flat collective — a link can die so late that some ranks
// complete the attempt while others abort, and those ranks must retry
// too or the fleet deadlocks half-retried.
constexpr int32_t kRingProceed = 0;
constexpr int32_t kRingRetry = 1;
constexpr int32_t kRingFail = 2;

void PutI32(std::vector<uint8_t>& b, int32_t v) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(&v);
  b.insert(b.end(), p, p + 4);
}

int32_t GetI32(const std::vector<uint8_t>& b, size_t i) {
  int32_t v;
  memcpy(&v, b.data() + i * 4, 4);
  return v;
}

Status AgreeRingRecovery(Network& net, bool my_ok, int my_bad_peer,
                         int32_t* action, std::vector<int>* order) {
  const int size = net.size();
  const double deadline = NetResilience().op_deadline_s;
  const uint64_t epoch = net.attempt_epoch();
  order->clear();
  if (net.rank() == 0) {
    bool all_ok = my_ok;
    bool coord_fail = false;  // a rank beyond even coordinator reach
    std::set<std::pair<int, int>> bad;
    auto note = [&](int a, int b) {
      if (a >= 0 && b >= 0 && a != b)
        bad.insert({std::min(a, b), std::max(a, b)});
    };
    note(0, my_bad_peer);
    for (int b : net.bad_links()) note(0, b);
    for (int r = 1; r < size; ++r) {
      std::vector<uint8_t> msg;
      Status st = net.chan(r)->AwaitRecoveryFrame(false, epoch, &msg,
                                                  deadline);
      if (!st.ok() || msg.size() < 8) {
        all_ok = false;
        coord_fail = true;
        continue;
      }
      if (GetI32(msg, 0) == 0) all_ok = false;
      note(r, GetI32(msg, 1));
    }
    std::vector<uint8_t> resp;
    if (all_ok) {
      PutI32(resp, kRingProceed);
      *action = kRingProceed;
    } else {
      std::vector<int> new_order;
      bool can = !coord_fail && NetResilience().renegotiate &&
                 !bad.empty() && ComputeRingOrder(size, bad, &new_order);
      if (can) {
        PutI32(resp, kRingRetry);
        PutI32(resp, size);
        for (int v : new_order) PutI32(resp, v);
        PutI32(resp, static_cast<int32_t>(bad.size()));
        for (auto& pr : bad) {
          PutI32(resp, pr.first);
          PutI32(resp, pr.second);
        }
        *action = kRingRetry;
        *order = new_order;
      } else {
        PutI32(resp, kRingFail);
        *action = kRingFail;
      }
    }
    for (int r = 1; r < size; ++r) {
      Status st = net.chan(r)->SendRecoveryFrame(true, epoch, resp,
                                                 deadline);
      (void)st;  // a lost verdict surfaces as that rank's own failure
    }
    return Status::OK();
  }
  std::vector<uint8_t> report;
  PutI32(report, my_ok ? 1 : 0);
  PutI32(report, my_bad_peer);
  // Re-send the report each await slice: agreement frames live outside
  // the op stream and the replay buffer, so one lost to a reset between
  // write and delivery would otherwise never be retransmitted (the
  // frames are epoch-fenced and latest-wins — re-sending is free).
  std::vector<uint8_t> resp;
  Status st;
  auto agree_end = std::chrono::steady_clock::now() +
                   std::chrono::duration_cast<
                       std::chrono::steady_clock::duration>(
                       std::chrono::duration<double>(deadline));
  for (;;) {
    double remaining = std::chrono::duration<double>(
                           agree_end - std::chrono::steady_clock::now())
                           .count();
    if (remaining <= 0)
      return Status::Retry("ring recovery: agreement deadline");
    st = net.chan(0)->SendRecoveryFrame(false, epoch, report, remaining);
    if (!st.ok()) return st;
    st = net.chan(0)->AwaitRecoveryFrame(true, epoch, &resp,
                                         std::min(remaining, 2.0));
    if (st.ok()) break;
    if (!st.retryable()) return st;
  }
  if (resp.size() < 4)
    return Status::Error("ring recovery: short response");
  *action = GetI32(resp, 0);
  if (getenv("HVD_TPU_NET_TRACE"))
    fprintf(stderr, "[hvdagree r%d] worker got action=%d resp=%zu\n",
            net.rank(), *action, resp.size());
  if (*action == kRingRetry) {
    int n = GetI32(resp, 1);
    for (int i = 0; i < n; ++i) order->push_back(GetI32(resp, 2 + i));
    int nbad = GetI32(resp, 2 + n);
    // Record every bad pair touching this rank so MeshReset skips them
    // symmetrically on BOTH endpoints.
    for (int i = 0; i < nbad; ++i) {
      int a = GetI32(resp, 3 + n + 2 * i);
      int b = GetI32(resp, 3 + n + 2 * i + 1);
      if (a == net.rank()) net.NoteBadLink(b);
      if (b == net.rank()) net.NoteBadLink(a);
    }
  }
  return Status::OK();
}

// Run a flat ring collective under the full escalation ladder.
// `snapshot`/`restore` bracket the in-place mutation so a renegotiated
// retry reruns from the original input.
Status RunResilientRing(
    Network& net, const std::function<void()>& snapshot,
    const std::function<void()>& restore,
    const std::function<Status(const std::vector<int>&)>& fn) {
  if (!NetResilience().enabled || net.size() <= 1)
    return fn(net.ring_order());
  if (!NetResilience().renegotiate) {
    // Rung 3 off: reconnect-and-resume (inside the channels) still
    // heals transient faults transparently, but there is no
    // renegotiation and therefore no per-collective agreement or
    // snapshot to pay for — exhausted reconnects escalate directly.
    net.BeginAttempt();
    return fn(net.ring_order());
  }
  if (snapshot) snapshot();
  int renegs = 0;
  bool recovered_any = false;
  for (;;) {
    net.BeginAttempt();
    Status st = fn(net.ring_order());
    if (getenv("HVD_TPU_NET_TRACE"))
      fprintf(stderr, "[hvdring r%d] fn st=%d %s\n", net.rank(),
              (int)st.type, st.reason.c_str());
    // EVERY failure joins the agreement — including non-retryable ones
    // (e.g. a same-host neighbor's shm op timing out because the abort
    // broadcast cannot unblock shared memory): skipping it would leave
    // the fleet's agreement one report short and convert a repairable
    // link death into a blanket kRingFail.  Genuinely symmetric
    // validation errors carry no bad link, so the coordinator answers
    // kRingFail and the error still surfaces unchanged.
    int bad_peer = net.TakeLastBadPeer();
    if (!st.ok()) net.BroadcastAbort();
    int32_t action = kRingProceed;
    std::vector<int> order;
    Status ag = AgreeRingRecovery(net, st.ok(), st.ok() ? -1 : bad_peer,
                                  &action, &order);
    if (!ag.ok()) return st.ok() ? ag : st;
    if (action == kRingProceed) {
      if (recovered_any && st.ok()) NetCounters().resets_avoided++;
      return st;
    }
    if (action == kRingFail)
      return st.ok() ? Status::Error(
                           "ring recovery: fleet agreed the collective "
                           "cannot be repaired")
                     : st;
    if (++renegs > NetResilience().max_renegotiations)
      return Status::Error("ring recovery: renegotiation limit reached");
    net.set_ring_order(order);
    Status mr = net.MeshReset(NetResilience().reconnect_s * 2 + 5.0);
    if (!mr.ok()) return mr;
    NetCounters().renegotiations++;
    NetCounters().last_recovery_ms.store(SteadyNowMs());
    recovered_any = true;
    if (restore) restore();
  }
}

}  // namespace

namespace {
// Schedule marker for tests/observability (0 = flat ring / flat VHDD,
// 1 = hierarchical) — the allreduce analog of g_allgather_schedule;
// stored only by COMPLETED top-level entry points (RingAllreduceGroup
// runs inside hierarchical phases and must not clobber it).
std::atomic<int> g_allreduce_schedule{0};
}  // namespace

int LastAllreduceSchedule() { return g_allreduce_schedule.load(); }

Status RingAllreduce(Network& net, void* vbuf, int64_t count, DataType dtype,
                     ReduceOp op, const std::function<void()>* restore) {
  const size_t nbytes = count * DataTypeSize(dtype);
  uint8_t* buf = static_cast<uint8_t*>(vbuf);
  Status st;
  if (restore != nullptr && *restore) {
    // The caller can rebuild buf from still-intact inputs: no
    // pre-collective snapshot copy on the clean path at all.
    st = RunResilientRing(
        net, nullptr, *restore, [&](const std::vector<int>& members) {
          return RingAllreduceGroup(net, vbuf, count, dtype, op, members);
        });
  } else {
    // Fallback (true in-place aliasing, hierarchical degenerate paths):
    // the ring mutates buf, so a renegotiated retry needs the original
    // addends back — one memcpy per collective when resilience is on.
    thread_local std::vector<uint8_t> snap;
    st = RunResilientRing(
        net,
        [&] {
          if (snap.size() < nbytes) snap.resize(nbytes);
          memcpy(snap.data(), buf, nbytes);
        },
        [&] { memcpy(buf, snap.data(), nbytes); },
        [&](const std::vector<int>& members) {
          return RingAllreduceGroup(net, vbuf, count, dtype, op, members);
        });
  }
  if (st.ok()) g_allreduce_schedule.store(0);
  return st;
}

namespace {
// Schedule markers for tests/observability: most recent hierarchical
// allreduce/Adasum fan-out and most recent broadcast on this process
// (0 = flat/none, 1 = pipelined chain, 2 = zero-copy CMA star).
std::atomic<int> g_allreduce_fanout{0};
std::atomic<int> g_bcast_schedule{0};

bool ForceChainEnv(const char* name) {
  const char* v = getenv(name);
  return v && std::string(v) == "chain";
}

// Star cutoff for full-payload fan-outs (allreduce/Adasum/broadcast):
// below this the chain's shm-slot memcpys beat CMA descriptor+syscall
// overhead (same rationale as SendStream's CMA threshold).
constexpr int64_t kStarMinBytes = 1 << 20;

// Shared phase-3 delivery for the hierarchical allreduce family
// (allreduce + Adasum): star-or-chain under HVD_TPU_AR_FANOUT, with
// the completed schedule recorded in g_allreduce_fanout.
Status StarOrChainArFanout(Network& net, void* vbuf, int64_t nbytes,
                           int rank, int leader,
                           const std::vector<int>& local_members,
                           int local_size) {
  static const bool force_chain = ForceChainEnv("HVD_TPU_AR_FANOUT");
  bool used_star = false;
  Status st = StarFanout(net, static_cast<uint8_t*>(vbuf), nbytes, leader,
                         local_members, force_chain, kStarMinBytes,
                         &used_star);
  if (!st.ok()) return st;
  if (used_star) {
    g_allreduce_fanout.store(2);
    return st;
  }
  st = ChainFanout(net, static_cast<uint8_t*>(vbuf), nbytes, rank, leader,
                   local_size);
  if (st.ok()) g_allreduce_fanout.store(1);
  return st;
}
}  // namespace

int LastAllreduceFanout() { return g_allreduce_fanout.load(); }
int LastBroadcastSchedule() { return g_bcast_schedule.load(); }

Status HierarchicalAllreduce(Network& net, void* vbuf, int64_t count,
                             DataType dtype, ReduceOp op, int local_size) {
  const int size = net.size();
  const int rank = net.rank();
  if (local_size <= 1 || size % local_size != 0 || size == local_size) {
    g_allreduce_fanout.store(0);
    return RingAllreduce(net, vbuf, count, dtype, op);
  }
  const int node = rank / local_size;
  const int leader = node * local_size;

  // Phase 1: intra-node allreduce (short hops — ICI analog).
  std::vector<int> local_members(local_size);
  for (int i = 0; i < local_size; ++i) local_members[i] = leader + i;
  Status st = RingAllreduceGroup(net, vbuf, count, dtype, op,
                                 local_members);
  if (!st.ok()) return st;

  // Phase 2: node leaders reduce across nodes (long hops — DCN analog).
  // Phase-1 result is the node total for SUM/MIN/MAX/PRODUCT, so the
  // leader ring produces the global reduction directly.
  const int n_nodes = size / local_size;
  if (rank == leader) {
    std::vector<int> leaders(n_nodes);
    for (int i = 0; i < n_nodes; ++i) leaders[i] = i * local_size;
    st = RingAllreduceGroup(net, vbuf, count, dtype, op, leaders);
    if (!st.ok()) return st;
  }

  // Phase 3: leaders deliver the global result within their node —
  // zero-copy CMA star when the payload is large and every
  // leader->member channel supports cross-memory attach, pipelined
  // chain otherwise (HVD_TPU_AR_FANOUT=chain forces the chain for
  // benchmarking).  Markers record only schedules that COMPLETED — a
  // failed fan-out must not read as the schedule that never ran.
  st = StarOrChainArFanout(net, vbuf, count * DataTypeSize(dtype),
                           rank, leader, local_members, local_size);
  if (st.ok()) g_allreduce_schedule.store(1);
  return st;
}

namespace {
// Schedule marker for tests/observability (0 flat ring, 1 hierarchical).
std::atomic<int> g_allgather_schedule{0};

// Ring allgatherv restricted to `members`; bytes/offsets are indexed by
// member *position* (block i belongs to members[i]).
Status RingAllgathervGroup(Network& net, uint8_t* buf,
                           const std::vector<int64_t>& bytes,
                           const std::vector<int64_t>& offsets,
                           const std::vector<int>& members) {
  const int m = static_cast<int>(members.size());
  if (m <= 1) return Status::OK();
  int idx = -1;
  for (int i = 0; i < m; ++i)
    if (members[i] == net.rank()) idx = i;
  if (idx < 0)
    return Status::InvalidArgument("rank not in allgather group");
  const int right = members[(idx + 1) % m];
  const int left = members[(idx - 1 + m) % m];
  for (int t = 0; t < m - 1; ++t) {
    int send_b = ((idx - t) % m + m) % m;
    int recv_b = ((idx - t - 1) % m + m) % m;
    Status st = FullDuplex(net, right, buf + offsets[send_b],
                           bytes[send_b], left, buf + offsets[recv_b],
                           bytes[recv_b]);
    if (!st.ok()) return st;
  }
  return Status::OK();
}
}  // namespace

int LastAllgatherSchedule() { return g_allgather_schedule.load(); }

Status RingAllgatherv(Network& net, uint8_t* buf,
                      const std::vector<int64_t>& bytes,
                      const std::vector<int64_t>& offsets) {
  // No schedule-marker store here: internal users (Adasum gather+tree,
  // VHDD reassembly) must not clobber the user-level allgather hook —
  // HierarchicalAllgatherv is the marker-setting entry point.
  //
  // No retry snapshot needed: the ring never rewrites a rank's own
  // block, and every other block is pure output.
  return RunResilientRing(
      net, nullptr, nullptr, [&](const std::vector<int>& members) {
        // bytes/offsets are indexed BY RANK; the group ring indexes by
        // member POSITION — remap for permuted (renegotiated) orders.
        std::vector<int64_t> pb(members.size()), po(members.size());
        for (size_t i = 0; i < members.size(); ++i) {
          pb[i] = bytes[members[i]];
          po[i] = offsets[members[i]];
        }
        return RingAllgathervGroup(net, buf, pb, po, members);
      });
}

Status HierarchicalAllgatherv(Network& net, uint8_t* buf,
                              const std::vector<int64_t>& bytes,
                              const std::vector<int64_t>& offsets,
                              int local_size) {
  const int size = net.size();
  const int rank = net.rank();
  if (local_size <= 1 || size % local_size != 0 || size == local_size) {
    g_allgather_schedule.store(0);
    return RingAllgatherv(net, buf, bytes, offsets);
  }
  g_allgather_schedule.store(1);
  const int node = rank / local_size;
  const int leader = node * local_size;
  const int n_nodes = size / local_size;

  // Phase 1: node members stage their block on the leader (intra-node
  // hops — shm/CMA when available; the reference's shared-memory window,
  // MEMCPY_IN_SHARED_BUFFER).  SendStream/RecvStream chunk internally.
  if (rank == leader) {
    for (int i = 1; i < local_size; ++i) {
      int peer = leader + i;
      Status st = RecvStream(net, peer, buf + offsets[peer], bytes[peer]);
      if (!st.ok()) return st;
    }
  } else {
    Status st = SendStream(net, leader, buf + offsets[rank], bytes[rank]);
    if (!st.ok()) return st;
  }

  // Phase 2: leaders ring-allgatherv node-level blocks (rank order makes
  // each node's member regions contiguous).
  if (rank == leader) {
    std::vector<int64_t> node_bytes(n_nodes), node_offs(n_nodes);
    std::vector<int> leaders(n_nodes);
    for (int b = 0; b < n_nodes; ++b) {
      leaders[b] = b * local_size;
      node_offs[b] = offsets[static_cast<size_t>(b) * local_size];
      int64_t tot = 0;
      for (int i = 0; i < local_size; ++i)
        tot += bytes[static_cast<size_t>(b) * local_size + i];
      node_bytes[b] = tot;
    }
    Status st = RingAllgathervGroup(net, buf, node_bytes, node_offs,
                                    leaders);
    if (!st.ok()) return st;
  }

  // Phase 3: deliver the assembled result to the node's members.
  //
  // Fast path — zero-copy STAR (the reference's shared-memory-window
  // analog, MEMCPY_IN_SHARED_BUFFER): when every leader->member channel
  // supports cross-memory attach, the leader publishes at most two CMA
  // descriptors per member (the buffer minus that member's own block)
  // and all members pull directly from the leader's memory
  // CONCURRENTLY — one copy per member, none for the leader, no
  // per-hop forwarding.  Fallback — pipelined chain, skipping each
  // receiver's own block.  The leader picks and announces the mode
  // in-band (one flag byte per member) so capability asymmetries can
  // never desynchronize the framing.
  const int pos = rank - leader;
  int64_t total = 0;
  for (auto b : bytes) total += b;
  auto minus = [](int64_t s, int64_t e, int64_t bs, int64_t be,
                  std::pair<int64_t, int64_t> out[2]) {
    int n = 0;
    if (be <= s || bs >= e) {
      out[n++] = {s, e};
    } else {
      if (bs > s) out[n++] = {s, bs};
      if (be < e) out[n++] = {be, e};
    }
    return n;
  };

  // Star via the shared StarFanout (skip spans exclude each member's
  // own block — it already holds its contribution).  star_min = 0: the
  // leader staging already dominates small allgathers, and the
  // schedule-marker tests pin tiny payloads to the star path.
  // HVD_TPU_AG_FANOUT=chain forces the chain (benchmark head-to-head
  // comparison knob, like HVD_TPU_ADASUM_ALGO).
  {
    static const bool force_chain = ForceChainEnv("HVD_TPU_AG_FANOUT");
    std::vector<int> members(local_size);
    for (int i = 0; i < local_size; ++i) members[i] = leader + i;
    bool used_star = false;
    Status st = StarFanout(net, buf, total, leader, members, force_chain,
                           0, &used_star, &offsets, &bytes);
    if (!st.ok()) return st;
    // Observability: 1 = hierarchical chain fan-out, 2 = hierarchical
    // CMA star (this rank's node; tests assert the intended path
    // actually ran).  Stored only for schedules that COMPLETED.
    if (used_star) {
      g_allgather_schedule.store(2);
      return st;
    }
  }
  const int64_t kChunk = 4 << 20;
  for (int64_t off = 0; off < total; off += kChunk) {
    const int64_t end = std::min(off + kChunk, total);
    std::pair<int64_t, int64_t> spans[2];
    if (pos > 0) {
      int n = minus(off, end, offsets[rank], offsets[rank] + bytes[rank],
                    spans);
      for (int i = 0; i < n; ++i) {
        Status st = RecvStream(net, rank - 1, buf + spans[i].first,
                               spans[i].second - spans[i].first);
        if (!st.ok()) return st;
      }
    }
    if (pos < local_size - 1) {
      const int nxt = rank + 1;
      int n = minus(off, end, offsets[nxt], offsets[nxt] + bytes[nxt],
                    spans);
      for (int i = 0; i < n; ++i) {
        Status st = SendStream(net, nxt, buf + spans[i].first,
                               spans[i].second - spans[i].first);
        if (!st.ok()) return st;
      }
    }
  }
  g_allgather_schedule.store(1);
  return Status::OK();
}

Status ChainBroadcast(Network& net, void* vbuf, int64_t nbytes, int root) {
  const int size = net.size();
  const int rank = net.rank();
  if (size == 1 || nbytes == 0) return Status::OK();
  uint8_t* buf = static_cast<uint8_t*>(vbuf);
  // Zero-copy CMA star when the payload is large and every root->rank
  // channel supports cross-memory attach (single-host broadcast: one
  // concurrent pull per rank instead of size-1 chained
  // store-and-forward hops); pipelined chain otherwise
  // (HVD_TPU_BCAST_FANOUT=chain forces it).  Small broadcasts skip the
  // star and its O(size) mode-byte exchange entirely — nbytes is known
  // identically on every rank, so the short-circuit is symmetric.
  {
    static const bool force_chain = ForceChainEnv("HVD_TPU_BCAST_FANOUT");
    std::vector<int> all(size);
    for (int i = 0; i < size; ++i) all[i] = i;
    bool used_star = false;
    Status st = StarFanout(net, buf, nbytes, root, all, force_chain,
                           kStarMinBytes, &used_star);
    if (!st.ok()) return st;
    if (used_star) {
      g_bcast_schedule.store(2);
      return st;
    }
  }
  // Rotate so root is position 0 in the chain; forward chunk-by-chunk so
  // the chain pipelines (downstream ranks start receiving while upstream
  // bytes are still in flight) instead of store-and-forwarding the whole
  // payload at each hop.
  int pos = ((rank - root) % size + size) % size;
  int prev = (rank - 1 + size) % size;
  int next = (rank + 1) % size;
  const int64_t kChunk = 4 << 20;
  for (int64_t off = 0; off < nbytes; off += kChunk) {
    int64_t k = std::min(kChunk, nbytes - off);
    if (pos > 0) {
      Status st = RecvStream(net, prev, buf + off, k);
      if (!st.ok()) return st;
    }
    if (pos < size - 1) {
      Status st = SendStream(net, next, buf + off, k);
      if (!st.ok()) return st;
    }
  }
  g_bcast_schedule.store(1);
  return Status::OK();
}

Status AgreeAllRanks(Network& net, int32_t* ok, int32_t* first_bad_rank) {
  *first_bad_rank = (*ok != 0) ? -1 : net.rank();
  if (net.size() == 1) return Status::OK();
  // Star over the mesh sockets (raw 8-byte exchange, no framing): rank 0
  // gathers [ok, rank], ANDs, broadcasts [all_ok, first_bad].  Safe on
  // the shared sockets because every rank reaches this call at the same
  // point of the identical coordinator response schedule.
  int32_t msg[2] = {*ok, *first_bad_rank};
  if (net.rank() == 0) {
    for (int r = 1; r < net.size(); ++r) {
      int32_t peer[2];
      Status st = net.chan(r)->Recv(reinterpret_cast<uint8_t*>(peer),
                                    sizeof(peer), nullptr, true);
      if (!st.ok()) return st;
      if (peer[0] == 0 && (msg[1] < 0 || peer[1] < msg[1])) msg[1] = peer[1];
      msg[0] &= peer[0];
    }
    for (int r = 1; r < net.size(); ++r) {
      Status st = net.chan(r)->Send(reinterpret_cast<const uint8_t*>(msg),
                                    sizeof(msg), true);
      if (!st.ok()) return st;
    }
  } else {
    Status st = net.coordinator_chan()->Send(
        reinterpret_cast<const uint8_t*>(msg), sizeof(msg), true);
    if (!st.ok()) return st;
    st = net.coordinator_chan()->Recv(reinterpret_cast<uint8_t*>(msg),
                                      sizeof(msg), nullptr, true);
    if (!st.ok()) return st;
  }
  *ok = msg[0];
  *first_bad_rank = msg[1];
  return Status::OK();
}

Status PairwiseAlltoallv(Network& net, const uint8_t* send,
                         const std::vector<int64_t>& send_bytes,
                         uint8_t* recv,
                         const std::vector<int64_t>& recv_bytes) {
  const int size = net.size();
  const int rank = net.rank();
  std::vector<int64_t> soff(size + 1, 0), roff(size + 1, 0);
  for (int i = 0; i < size; ++i) {
    soff[i + 1] = soff[i] + send_bytes[i];
    roff[i + 1] = roff[i] + recv_bytes[i];
  }
  // Self copy.
  memcpy(recv + roff[rank], send + soff[rank], send_bytes[rank]);
  for (int d = 1; d < size; ++d) {
    int to = (rank + d) % size;
    int from = (rank - d + size) % size;
    Status st = FullDuplex(net, to, send + soff[to], send_bytes[to],
                           from, recv + roff[from], recv_bytes[from]);
    if (!st.ok()) return st;
  }
  return Status::OK();
}

namespace {

template <typename T>
void AdasumPair(T* a, const T* b, int64_t n) {
  double dot = 0, na = 0, nb = 0;
  for (int64_t i = 0; i < n; ++i) {
    double x = static_cast<double>(a[i]);
    double y = static_cast<double>(b[i]);
    dot += x * y;
    na += x * x;
    nb += y * y;
  }
  double ac = na > 0 ? 1.0 - dot / (2.0 * na) : 1.0;
  double bc = nb > 0 ? 1.0 - dot / (2.0 * nb) : 1.0;
  for (int64_t i = 0; i < n; ++i)
    a[i] = static_cast<T>(ac * static_cast<double>(a[i]) +
                          bc * static_cast<double>(b[i]));
}

template <typename T>
void AdasumTree(std::vector<std::vector<uint8_t>>& bufs, int64_t n) {
  // Pair (0,1),(2,3)... then pairs-of-pairs — same tree as ops/adasum.py.
  size_t m = bufs.size();
  std::vector<int> live(m);
  for (size_t i = 0; i < m; ++i) live[i] = static_cast<int>(i);
  while (live.size() > 1) {
    std::vector<int> nxt;
    for (size_t i = 0; i + 1 < live.size(); i += 2) {
      AdasumPair(reinterpret_cast<T*>(bufs[live[i]].data()),
                 reinterpret_cast<const T*>(bufs[live[i + 1]].data()), n);
      nxt.push_back(live[i]);
    }
    if (live.size() % 2 == 1) nxt.push_back(live.back());
    live = nxt;
  }
  if (live[0] != 0) bufs[0] = bufs[live[0]];
}

// Scratch-memory instrumentation for the VHDD path (tested: the schedule
// must stay O(|t|), unlike the old gather+tree's O(P*|t|)).
std::atomic<int64_t> g_adasum_scratch_peak{0};

int BitRev(int i, int bits) {
  int r = 0;
  for (int b = 0; b < bits; ++b) r = (r << 1) | ((i >> b) & 1);
  return r;
}

// Vector-halving distance-doubling Adasum on a typed working buffer
// (reference chunked pairwise VHDD, adasum.h:168-395 / adasum_mpi.cc:
// 107-110; same schedule as the compiled ladder in ops/adasum.py).
// O(|t|) scratch; members.size() must be a power of two.  Chunked wire
// transfers are inherited from SendStream/RecvStream/FullDuplex (4 MB
// chunks / shm slots).  `members` lets hierarchical schedules run the
// ladder over node leaders only (reference adasum_gpu_operations.cc).
template <typename T>
Status AdasumVHDDImpl(Network& net, T* data, int64_t count,
                      const std::vector<int>& members) {
  const int P = static_cast<int>(members.size());
  int rank = -1;  // index within the group
  for (int i = 0; i < P; ++i)
    if (members[i] == net.rank()) rank = i;
  if (rank < 0)
    return Status::InvalidArgument("rank not in adasum group");
  const int levels = __builtin_ctz(P);
  const int64_t L = ((count + P - 1) / P) * P;
  int64_t scratch = 0;
  auto track = [&](int64_t bytes) {
    scratch += bytes;
    int64_t prev = g_adasum_scratch_peak.load();
    while (scratch > prev &&
           !g_adasum_scratch_peak.compare_exchange_weak(prev, scratch)) {
    }
  };

  std::vector<T> x(L, T(0));
  track(L * sizeof(T));
  memcpy(x.data(), data, count * sizeof(T));
  std::vector<T> recv(L / 2);
  track((L / 2) * sizeof(T));

  int64_t cur = L;
  for (int level = 0; level < levels; ++level) {
    const int d = 1 << level;
    const int partner = members[rank ^ d];
    const int64_t half = cur / 2;
    const int bit = (rank >> level) & 1;
    T* lower = x.data();
    T* upper = x.data() + half;
    T* keep = bit == 0 ? lower : upper;
    T* send = bit == 0 ? upper : lower;
    Status st = FullDuplex(
        net, partner, reinterpret_cast<const uint8_t*>(send),
        half * sizeof(T), partner, reinterpret_cast<uint8_t*>(recv.data()),
        half * sizeof(T));
    if (!st.ok()) return st;
    // Role assignment matches ops/adasum.py: "a" is the lower (bit==0)
    // block's logical vector, "b" the upper's, so the group-summed
    // partials are the true full-vector dot and norms.
    const T* a = bit == 0 ? keep : recv.data();
    const T* b = bit == 0 ? recv.data() : keep;
    double partials[3] = {0.0, 0.0, 0.0};  // dot, ||a||^2, ||b||^2
    for (int64_t i = 0; i < half; ++i) {
      const double av = static_cast<double>(a[i]);
      const double bv = static_cast<double>(b[i]);
      partials[0] += av * bv;
      partials[1] += av * av;
      partials[2] += bv * bv;
    }
    // Sum the 24-byte partials over the 2d-member group by recursive
    // doubling: log2(2d) pairwise exchanges instead of a 2*(2d-1)-step
    // ring — the scalar reduction is latency-bound, especially on the
    // cross-node (DCN-analog) levels.  Commutative fp addition makes the
    // per-rank results bitwise identical.
    const int group = 2 * d;
    const int base = (rank / group) * group;
    for (int h = 1; h < group; h <<= 1) {
      const int peer = members[base + ((rank - base) ^ h)];
      double incoming[3];
      Status gst = FullDuplex(
          net, peer, reinterpret_cast<const uint8_t*>(partials),
          sizeof(partials), peer, reinterpret_cast<uint8_t*>(incoming),
          sizeof(incoming));
      if (!gst.ok()) return gst;
      partials[0] += incoming[0];
      partials[1] += incoming[1];
      partials[2] += incoming[2];
    }
    const double dot = partials[0], na = partials[1], nb = partials[2];
    const double ac = na > 0 ? 1.0 - dot / (2.0 * na) : 1.0;
    const double bc = nb > 0 ? 1.0 - dot / (2.0 * nb) : 1.0;
    T* dst = x.data();
    for (int64_t i = 0; i < half; ++i)
      dst[i] = static_cast<T>(ac * static_cast<double>(a[i]) +
                              bc * static_cast<double>(b[i]));
    cur = half;
  }

  // Each rank holds fragment bit_reverse(rank); the reordering happens in
  // the allgather's offset table (no post-pass).
  const int64_t frag = L / P;
  std::vector<T> mine(x.begin(), x.begin() + frag);
  track(frag * sizeof(T));
  x.clear();
  x.shrink_to_fit();
  track(-L * static_cast<int64_t>(sizeof(T)));
  recv.clear();
  recv.shrink_to_fit();
  track(-(L / 2) * static_cast<int64_t>(sizeof(T)));

  std::vector<T> full(L);
  track(L * sizeof(T));
  std::vector<int64_t> bytes(P, frag * sizeof(T)), offs(P);
  for (int r = 0; r < P; ++r)
    offs[r] = static_cast<int64_t>(BitRev(r, levels)) * frag * sizeof(T);
  memcpy(reinterpret_cast<uint8_t*>(full.data()) + offs[rank], mine.data(),
         frag * sizeof(T));
  Status st = RingAllgathervGroup(
      net, reinterpret_cast<uint8_t*>(full.data()), bytes, offs, members);
  if (!st.ok()) return st;
  memcpy(data, full.data(), count * sizeof(T));
  return Status::OK();
}

// Non-power-of-two fallback: gather + coefficient tree (exact, O(P*|t|)
// scratch — the reference restricts Adasum to power-of-two worlds,
// tensorflow/__init__.py:146-147).
template <typename T>
Status AdasumGatherTree(Network& net, T* data, int64_t count) {
  const int size = net.size();
  const size_t nbytes = count * sizeof(T);
  std::vector<std::vector<uint8_t>> bufs(size);
  std::vector<int64_t> bytes(size, nbytes), offsets(size);
  std::vector<uint8_t> gathered(nbytes * size);
  for (int i = 0; i < size; ++i) offsets[i] = i * nbytes;
  memcpy(gathered.data() + net.rank() * nbytes, data, nbytes);
  Status st = RingAllgatherv(net, gathered.data(), bytes, offsets);
  if (!st.ok()) return st;
  for (int i = 0; i < size; ++i)
    bufs[i].assign(gathered.begin() + i * nbytes,
                   gathered.begin() + (i + 1) * nbytes);
  AdasumTree<T>(bufs, count);
  memcpy(data, bufs[0].data(), nbytes);
  return Status::OK();
}

// HVD_TPU_ADASUM_ALGO=tree forces the gather+tree fallback at any world
// size so the two algorithms can be benchmarked head-to-head at the same
// np (the reference exposes no such knob; pow2 worlds always take VHDD).
inline bool ForceAdasumTree() {
  static const bool force = [] {
    const char* v = getenv("HVD_TPU_ADASUM_ALGO");
    return v && std::string(v) == "tree";
  }();
  return force;
}

template <typename T>
Status AdasumTyped(Network& net, T* data, int64_t count) {
  const int P = net.size();
  if (ForceAdasumTree() || (P & (P - 1)))
    return AdasumGatherTree<T>(net, data, count);
  std::vector<int> all(P);
  for (int i = 0; i < P; ++i) all[i] = i;
  return AdasumVHDDImpl<T>(net, data, count, all);
}

// Run `fn(float* work)` on an fp32 copy of a 16-bit buffer, writing the
// result back in the wire dtype (fp32 accumulation for fp16/bf16 — the
// reference's fp16 Adasum kernel policy).
template <typename Fn>
Status With16BitAsFloat(void* vbuf, int64_t count, DataType dtype, Fn fn) {
  std::vector<float> work(count);
  uint16_t* raw = static_cast<uint16_t*>(vbuf);
  if (dtype == DataType::FLOAT16) {
    for (int64_t i = 0; i < count; ++i) work[i] = HalfToFloat(raw[i]);
  } else {
    for (int64_t i = 0; i < count; ++i) work[i] = Bf16ToFloat(raw[i]);
  }
  Status st = fn(work.data());
  if (!st.ok()) return st;
  if (dtype == DataType::FLOAT16) {
    for (int64_t i = 0; i < count; ++i) raw[i] = FloatToHalf(work[i]);
  } else {
    for (int64_t i = 0; i < count; ++i) raw[i] = FloatToBf16(work[i]);
  }
  return Status::OK();
}

// Typed Adasum over a rank subgroup (node leaders) with the same 16-bit
// conversion policy as the public entry point.
Status AdasumGroup(Network& net, void* vbuf, int64_t count, DataType dtype,
                   const std::vector<int>& members) {
  switch (dtype) {
    case DataType::FLOAT64:
      return AdasumVHDDImpl<double>(net, static_cast<double*>(vbuf), count,
                                    members);
    case DataType::FLOAT32:
      return AdasumVHDDImpl<float>(net, static_cast<float*>(vbuf), count,
                                   members);
    case DataType::FLOAT16:
    case DataType::BFLOAT16:
      return With16BitAsFloat(vbuf, count, dtype, [&](float* w) {
        return AdasumVHDDImpl<float>(net, w, count, members);
      });
    default:
      return Status::InvalidArgument(
          "eager Adasum supports float16/bfloat16/float32/float64");
  }
}

}  // namespace

int64_t AdasumScratchPeak() { return g_adasum_scratch_peak.load(); }
void ResetAdasumScratchPeak() { g_adasum_scratch_peak.store(0); }

Status AdasumAllreduce(Network& net, void* vbuf, int64_t count,
                       DataType dtype) {
  const int size = net.size();
  if (size == 1 || count == 0) return Status::OK();
  Status st;
  switch (dtype) {
    case DataType::FLOAT64:
      st = AdasumTyped<double>(net, static_cast<double*>(vbuf), count);
      break;
    case DataType::FLOAT32:
      st = AdasumTyped<float>(net, static_cast<float*>(vbuf), count);
      break;
    case DataType::FLOAT16:
    case DataType::BFLOAT16:
      // fp32 accumulation for 16-bit wires (reference fp16 Adasum kernels,
      // adasum.h AVX/F16C specializations — portable here).
      st = With16BitAsFloat(vbuf, count, dtype, [&](float* w) {
        return AdasumTyped<float>(net, w, count);
      });
      break;
    default:
      return Status::InvalidArgument(
          "eager Adasum supports float16/bfloat16/float32/float64");
  }
  if (st.ok()) g_allreduce_schedule.store(0);
  return st;
}

namespace {

Status HierarchicalAdasumImpl(Network& net, void* vbuf, int64_t count,
                              DataType dtype, int local_size);

}  // namespace

Status HierarchicalAdasum(Network& net, void* vbuf, int64_t count,
                          DataType dtype, int local_size) {
  // Reference AdasumGpuAllreduceOp (adasum_gpu_operations.cc:38-…):
  // intra-node reduction, cross-node VHDD between node leaders, intra-node
  // fan-out, with local averaging folded in (operations.cc:968-975; the
  // Adasum coefficients are scale-invariant, so Adasum(node sums)/L ==
  // Adasum(node means)).
  // Validate dtype BEFORE phase 1: the intra-node sum would succeed on
  // every rank while phase-2 AdasumGroup failed on leaders only, leaving
  // non-leaders stalled in the fan-out — all ranks must fail
  // symmetrically, like the flat path does.
  if (dtype != DataType::FLOAT16 && dtype != DataType::BFLOAT16 &&
      dtype != DataType::FLOAT32 && dtype != DataType::FLOAT64)
    return Status::InvalidArgument(
        "eager Adasum supports float16/bfloat16/float32/float64");
  if (dtype == DataType::FLOAT16 || dtype == DataType::BFLOAT16) {
    // fp32 accumulation for 16-bit wires across ALL phases, matching the
    // flat path (which converts the whole buffer before any reduction):
    // fp16 intra-node partial sums would overflow at moderate local_size
    // and the hierarchical result would diverge from the flat one.
    return With16BitAsFloat(vbuf, count, dtype, [&](float* w) {
      return HierarchicalAdasumImpl(net, w, count, DataType::FLOAT32,
                                    local_size);
    });
  }
  return HierarchicalAdasumImpl(net, vbuf, count, dtype, local_size);
}

namespace {

Status HierarchicalAdasumImpl(Network& net, void* vbuf, int64_t count,
                              DataType dtype, int local_size) {
  const int size = net.size();
  const int rank = net.rank();
  const int n_nodes = local_size > 0 ? size / local_size : 0;
  if (local_size <= 1 || size % local_size != 0 || size == local_size ||
      (n_nodes & (n_nodes - 1)) != 0) {
    g_allreduce_fanout.store(0);
    return AdasumAllreduce(net, vbuf, count, dtype);
  }
  if (count == 0) return Status::OK();
  const int node = rank / local_size;
  const int leader = node * local_size;

  // Phase 1: intra-node sum (short hops — ICI analog).
  std::vector<int> local_members(local_size);
  for (int i = 0; i < local_size; ++i) local_members[i] = leader + i;
  Status st = RingAllreduceGroup(net, vbuf, count, dtype, ReduceOp::SUM,
                                 local_members);
  if (!st.ok()) return st;

  // Phase 2: node leaders combine node sums with the VHDD ladder
  // (long hops — DCN analog), then fold in the local average.
  if (rank == leader) {
    std::vector<int> leaders(n_nodes);
    for (int i = 0; i < n_nodes; ++i) leaders[i] = i * local_size;
    st = AdasumGroup(net, vbuf, count, dtype, leaders);
    if (!st.ok()) return st;
    ScaleBuffer(vbuf, count, dtype, 1.0 / local_size);
  }

  // Phase 3: leaders deliver the result within their node (same star-
  // or-chain schedule as HierarchicalAllreduce phase 3; markers record
  // only completed schedules).
  st = StarOrChainArFanout(net, vbuf, count * DataTypeSize(dtype),
                           rank, leader, local_members, local_size);
  if (st.ok()) g_allreduce_schedule.store(1);
  return st;
}

}  // namespace

}  // namespace hvdtpu
