#include "collectives.h"

#include <poll.h>
#include <sys/socket.h>

#include <algorithm>
#include <cerrno>
#include <cmath>
#include <functional>
#include <thread>
#include <cstring>

namespace hvdtpu {

namespace {

// --- fp16 / bf16 <-> fp32 (reference half.cc capability, portable) --------

inline float HalfToFloat(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t mant = h & 0x3ffu;
  uint32_t f;
  if (exp == 0) {
    if (mant == 0) {
      f = sign;
    } else {  // subnormal
      exp = 127 - 15 + 1;
      while ((mant & 0x400u) == 0) { mant <<= 1; exp--; }
      mant &= 0x3ffu;
      f = sign | (exp << 23) | (mant << 13);
    }
  } else if (exp == 0x1f) {
    f = sign | 0x7f800000u | (mant << 13);
  } else {
    f = sign | ((exp + 127 - 15) << 23) | (mant << 13);
  }
  float out;
  memcpy(&out, &f, 4);
  return out;
}

inline uint16_t FloatToHalf(float v) {
  uint32_t f;
  memcpy(&f, &v, 4);
  uint32_t sign = (f >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((f >> 23) & 0xff) - 127 + 15;
  uint32_t mant = f & 0x7fffffu;
  if (exp <= 0) {
    if (exp < -10) return static_cast<uint16_t>(sign);
    mant |= 0x800000u;
    uint32_t shift = 14 - exp;
    return static_cast<uint16_t>(sign | (mant >> shift));
  }
  if (exp >= 0x1f) return static_cast<uint16_t>(sign | 0x7c00u);
  return static_cast<uint16_t>(sign | (exp << 10) | (mant >> 13));
}

inline float Bf16ToFloat(uint16_t b) {
  uint32_t f = static_cast<uint32_t>(b) << 16;
  float out;
  memcpy(&out, &f, 4);
  return out;
}

inline uint16_t FloatToBf16(float v) {
  uint32_t f;
  memcpy(&f, &v, 4);
  // round-to-nearest-even
  uint32_t rounding = ((f >> 16) & 1u) + 0x7fffu;
  return static_cast<uint16_t>((f + rounding) >> 16);
}

// --- reduction kernels -----------------------------------------------------

template <typename T>
void ReduceT(T* dst, const T* src, int64_t n, ReduceOp op) {
  switch (op) {
    case ReduceOp::SUM:
    case ReduceOp::AVERAGE:  // divide happens at unpack
    case ReduceOp::ADASUM:   // handled elsewhere; fallthrough sum for safety
      for (int64_t i = 0; i < n; ++i) dst[i] += src[i];
      break;
    case ReduceOp::MIN:
      for (int64_t i = 0; i < n; ++i) dst[i] = std::min(dst[i], src[i]);
      break;
    case ReduceOp::MAX:
      for (int64_t i = 0; i < n; ++i) dst[i] = std::max(dst[i], src[i]);
      break;
    case ReduceOp::PRODUCT:
      for (int64_t i = 0; i < n; ++i) dst[i] *= src[i];
      break;
  }
}

template <typename U, float (*ToF)(U), U (*FromF)(float)>
void Reduce16(U* dst, const U* src, int64_t n, ReduceOp op) {
  for (int64_t i = 0; i < n; ++i) {
    float a = ToF(dst[i]);
    float b = ToF(src[i]);
    float r;
    switch (op) {
      case ReduceOp::MIN: r = std::min(a, b); break;
      case ReduceOp::MAX: r = std::max(a, b); break;
      case ReduceOp::PRODUCT: r = a * b; break;
      default: r = a + b; break;
    }
    dst[i] = FromF(r);
  }
}

void ReduceBuf(void* dst, const void* src, int64_t count, DataType dtype,
               ReduceOp op) {
  switch (dtype) {
    case DataType::FLOAT32:
      ReduceT(static_cast<float*>(dst), static_cast<const float*>(src),
              count, op);
      break;
    case DataType::FLOAT64:
      ReduceT(static_cast<double*>(dst), static_cast<const double*>(src),
              count, op);
      break;
    case DataType::INT32:
      ReduceT(static_cast<int32_t*>(dst), static_cast<const int32_t*>(src),
              count, op);
      break;
    case DataType::INT64:
      ReduceT(static_cast<int64_t*>(dst), static_cast<const int64_t*>(src),
              count, op);
      break;
    case DataType::UINT8:
    case DataType::BOOL:
      ReduceT(static_cast<uint8_t*>(dst), static_cast<const uint8_t*>(src),
              count, op);
      break;
    case DataType::INT8:
      ReduceT(static_cast<int8_t*>(dst), static_cast<const int8_t*>(src),
              count, op);
      break;
    case DataType::FLOAT16:
      Reduce16<uint16_t, HalfToFloat, FloatToHalf>(
          static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(src),
          count, op);
      break;
    case DataType::BFLOAT16:
      Reduce16<uint16_t, Bf16ToFloat, FloatToBf16>(
          static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(src),
          count, op);
      break;
  }
}

// One-directional streams: shared memory when the peer is on this host
// (Network::shm_tx/shm_rx — two memcpys, no syscalls on the bulk path),
// TCP otherwise.  Used standalone for chains/broadcasts and paired by
// FullDuplex for ring steps.

Status SendStream(Network& net, int peer, const uint8_t* buf, size_t n) {
  if (n == 0) return Status::OK();
  if (ShmChannel* ch = net.shm_tx(peer)) {
    if (ch->refs_enabled() && n >= (1u << 20)) {
      // Cross-memory attach: publish slot-sized descriptors into this
      // process's memory; the consumer pulls each directly (zero staging
      // copies) while later chunks are being published — keeping the
      // receiver's incremental reduction pipelined.  Must drain before
      // returning: the ring reuses the region in later steps.
      size_t off = 0;
      while (off < n) {
        size_t k = std::min(n - off, ShmChannel::kSlotBytes);
        Status st = ch->PushRef(buf + off, k);
        if (!st.ok()) return st;
        off += k;
      }
      return ch->WaitDrained();
    }
    size_t off = 0;
    while (off < n) {
      size_t k = std::min(n - off, ShmChannel::kSlotBytes);
      Status st = ch->Push(buf + off, k);
      if (!st.ok()) return st;
      off += k;
    }
    return Status::OK();
  }
  Socket* sock = net.peer(peer);
  size_t sent = 0;
  while (sent < n) {
    pollfd pfd{sock->fd(), POLLOUT, 0};
    int pr = ::poll(&pfd, 1, 60000);
    if (pr < 0 && errno == EINTR) continue;
    if (pr <= 0) return Status::Error("collective send timeout");
    ssize_t k = ::send(sock->fd(), buf + sent,
                       std::min<size_t>(n - sent, 4 << 20),
                       MSG_NOSIGNAL | MSG_DONTWAIT);
    if (k < 0) {
      if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK)
        continue;
      return Status::Error("send failed in collective");
    }
    sent += k;
  }
  return Status::OK();
}

Status RecvStream(Network& net, int peer, uint8_t* dst, size_t n,
                  const std::function<void(size_t)>& on_recv = nullptr) {
  if (n == 0) return Status::OK();
  if (ShmChannel* ch = net.shm_rx(peer)) {
    size_t off = 0;
    while (off < n) {
      size_t got = 0;
      Status st = ch->PopInto(dst + off, n - off, &got);
      if (!st.ok()) return st;
      off += got;
      if (on_recv) on_recv(off);
    }
    return Status::OK();
  }
  Socket* sock = net.peer(peer);
  size_t received = 0;
  while (received < n) {
    pollfd pfd{sock->fd(), POLLIN, 0};
    int pr = ::poll(&pfd, 1, 60000);
    if (pr < 0 && errno == EINTR) continue;
    if (pr <= 0) return Status::Error("collective recv timeout");
    ssize_t k = ::recv(sock->fd(), dst + received,
                       std::min<size_t>(n - received, 4 << 20),
                       MSG_DONTWAIT);
    if (k == 0) return Status::Aborted("peer closed during collective");
    if (k < 0) {
      if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK)
        continue;
      return Status::Error("recv failed in collective");
    }
    received += k;
    if (on_recv) on_recv(received);
  }
  return Status::OK();
}

// Full-duplex transfer: simultaneously stream nsend bytes toward
// send_peer and nrecv bytes from recv_peer.  ``on_recv(total)``, when
// set, is invoked as the received prefix grows so the caller can overlap
// per-chunk work (reduction) with the remaining transfer.
// Threaded variant for large or shm transfers: the send stream runs on
// its own thread so both directions (and the on_recv reduction) proceed
// in parallel — a single-threaded poll loop serializes the kernel copies
// of the two directions onto one core and halves duplex throughput.
Status FullDuplexThreaded(Network& net, int send_peer,
                          const uint8_t* send_buf, size_t nsend,
                          int recv_peer, uint8_t* recv_buf, size_t nrecv,
                          const std::function<void(size_t)>& on_recv) {
  Status send_st = Status::OK();
  std::thread sender(
      [&] { send_st = SendStream(net, send_peer, send_buf, nsend); });
  Status st = RecvStream(net, recv_peer, recv_buf, nrecv, on_recv);
  sender.join();
  return st.ok() ? send_st : st;
}

Status FullDuplex(Network& net, int send_peer, const uint8_t* send_buf,
                  size_t nsend, int recv_peer, uint8_t* recv_buf,
                  size_t nrecv,
                  const std::function<void(size_t)>& on_recv = nullptr) {
  if (net.shm_tx(send_peer) != nullptr ||
      net.shm_rx(recv_peer) != nullptr || nsend + nrecv >= (4u << 20)) {
    return FullDuplexThreaded(net, send_peer, send_buf, nsend, recv_peer,
                              recv_buf, nrecv, on_recv);
  }
  Socket* send_sock = net.peer(send_peer);
  Socket* recv_sock = net.peer(recv_peer);
  size_t sent = 0, received = 0;
  while (sent < nsend || received < nrecv) {
    struct pollfd fds[2];
    int nf = 0;
    int send_i = -1, recv_i = -1;
    if (sent < nsend) {
      fds[nf] = {send_sock->fd(), POLLOUT, 0};
      send_i = nf++;
    }
    if (received < nrecv) {
      fds[nf] = {recv_sock->fd(), POLLIN, 0};
      recv_i = nf++;
    }
    int pr = ::poll(fds, nf, 60000);
    if (pr < 0 && errno == EINTR) continue;
    if (pr <= 0)
      return Status::Error("collective transfer timeout/poll error");
    if (send_i >= 0 && (fds[send_i].revents & (POLLOUT | POLLERR))) {
      ssize_t k = ::send(send_sock->fd(), send_buf + sent,
                         std::min<size_t>(nsend - sent, 4 << 20),
                         MSG_NOSIGNAL | MSG_DONTWAIT);
      if (k < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR)
        return Status::Error("send failed in collective");
      if (k > 0) sent += k;
    }
    if (recv_i >= 0 && (fds[recv_i].revents & (POLLIN | POLLERR | POLLHUP))) {
      ssize_t k = ::recv(recv_sock->fd(), recv_buf + received,
                         std::min<size_t>(nrecv - received, 4 << 20),
                         MSG_DONTWAIT);
      if (k == 0) return Status::Aborted("peer closed during collective");
      if (k < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR)
        return Status::Error("recv failed in collective");
      if (k > 0) {
        received += k;
        if (on_recv) on_recv(received);
      }
    }
  }
  return Status::OK();
}

}  // namespace

void ScaleBuffer(void* buf, int64_t count, DataType dtype, double factor) {
  if (factor == 1.0) return;
  switch (dtype) {
    case DataType::FLOAT32: {
      float* p = static_cast<float*>(buf);
      for (int64_t i = 0; i < count; ++i) p[i] *= factor;
      break;
    }
    case DataType::FLOAT64: {
      double* p = static_cast<double*>(buf);
      for (int64_t i = 0; i < count; ++i) p[i] *= factor;
      break;
    }
    case DataType::FLOAT16: {
      uint16_t* p = static_cast<uint16_t*>(buf);
      for (int64_t i = 0; i < count; ++i)
        p[i] = FloatToHalf(HalfToFloat(p[i]) * factor);
      break;
    }
    case DataType::BFLOAT16: {
      uint16_t* p = static_cast<uint16_t*>(buf);
      for (int64_t i = 0; i < count; ++i)
        p[i] = FloatToBf16(Bf16ToFloat(p[i]) * factor);
      break;
    }
    case DataType::INT32: {
      int32_t* p = static_cast<int32_t*>(buf);
      for (int64_t i = 0; i < count; ++i)
        p[i] = static_cast<int32_t>(p[i] * factor);
      break;
    }
    case DataType::INT64: {
      int64_t* p = static_cast<int64_t*>(buf);
      for (int64_t i = 0; i < count; ++i)
        p[i] = static_cast<int64_t>(p[i] * factor);
      break;
    }
    default:
      break;
  }
}

Status RingAllreduceGroup(Network& net, void* vbuf, int64_t count,
                          DataType dtype, ReduceOp op,
                          const std::vector<int>& members) {
  const int m = static_cast<int>(members.size());
  if (m <= 1 || count == 0) return Status::OK();
  int idx = -1;
  for (int i = 0; i < m; ++i)
    if (members[i] == net.rank()) idx = i;
  if (idx < 0)
    return Status::InvalidArgument("rank not in allreduce group");
  uint8_t* buf = static_cast<uint8_t*>(vbuf);
  const size_t elem = DataTypeSize(dtype);

  // Segment boundaries (last segment may be short).
  const int64_t seg = (count + m - 1) / m;
  auto seg_start = [&](int s) { return std::min<int64_t>(seg * s, count); };
  auto seg_count = [&](int s) {
    return std::min<int64_t>(seg, count - seg_start(s));
  };

  const int right = members[(idx + 1) % m];
  const int left = members[(idx - 1 + m) % m];
  // Reused across calls: a fresh segment-sized allocation per op would
  // pay tens of ms of page faults on large tensors.
  static thread_local std::vector<uint8_t> scratch;
  if (scratch.size() < static_cast<size_t>(seg * elem))
    scratch.resize(seg * elem);

  // Reduce-scatter then allgather (bandwidth-optimal ring).  The
  // reduction of each received chunk runs incrementally inside the
  // transfer (on_recv), overlapping compute with the remaining wire time
  // instead of serializing a full-segment reduce after each step.
  for (int t = 0; t < m - 1; ++t) {
    int send_s = ((idx - t) % m + m) % m;
    int recv_s = ((idx - t - 1) % m + m) % m;
    uint8_t* recv_dst = buf + seg_start(recv_s) * elem;
    size_t reduced = 0;  // elements of this segment already reduced
    auto reduce_prefix = [&](size_t received_bytes) {
      size_t avail = received_bytes / elem;
      if (avail > reduced) {
        ReduceBuf(recv_dst + reduced * elem,
                  scratch.data() + reduced * elem,
                  static_cast<int64_t>(avail - reduced), dtype, op);
        reduced = avail;
      }
    };
    Status st = FullDuplex(net, right, buf + seg_start(send_s) * elem,
                           seg_count(send_s) * elem, left, scratch.data(),
                           seg_count(recv_s) * elem, reduce_prefix);
    if (!st.ok()) return st;
  }
  for (int t = 0; t < m - 1; ++t) {
    int send_s = ((idx + 1 - t) % m + m) % m;
    int recv_s = ((idx - t) % m + m) % m;
    Status st = FullDuplex(net, right, buf + seg_start(send_s) * elem,
                           seg_count(send_s) * elem, left,
                           buf + seg_start(recv_s) * elem,
                           seg_count(recv_s) * elem);
    if (!st.ok()) return st;
  }
  return Status::OK();
}

Status RingAllreduce(Network& net, void* vbuf, int64_t count, DataType dtype,
                     ReduceOp op) {
  std::vector<int> all(net.size());
  for (int i = 0; i < net.size(); ++i) all[i] = i;
  return RingAllreduceGroup(net, vbuf, count, dtype, op, all);
}

Status HierarchicalAllreduce(Network& net, void* vbuf, int64_t count,
                             DataType dtype, ReduceOp op, int local_size) {
  const int size = net.size();
  const int rank = net.rank();
  if (local_size <= 1 || size % local_size != 0 || size == local_size)
    return RingAllreduce(net, vbuf, count, dtype, op);
  const int node = rank / local_size;
  const int leader = node * local_size;

  // Phase 1: intra-node allreduce (short hops — ICI analog).
  std::vector<int> local_members(local_size);
  for (int i = 0; i < local_size; ++i) local_members[i] = leader + i;
  Status st = RingAllreduceGroup(net, vbuf, count, dtype, op,
                                 local_members);
  if (!st.ok()) return st;

  // Phase 2: node leaders reduce across nodes (long hops — DCN analog).
  // Phase-1 result is the node total for SUM/MIN/MAX/PRODUCT, so the
  // leader ring produces the global reduction directly.
  const int n_nodes = size / local_size;
  if (rank == leader) {
    std::vector<int> leaders(n_nodes);
    for (int i = 0; i < n_nodes; ++i) leaders[i] = i * local_size;
    st = RingAllreduceGroup(net, vbuf, count, dtype, op, leaders);
    if (!st.ok()) return st;
  }

  // Phase 3: leaders broadcast the global result within their node.
  const size_t nbytes = count * DataTypeSize(dtype);
  if (local_size > 1) {
    // Chain within the node: leader → leader+1 → ... → leader+L-1,
    // chunk-pipelined (intra-node hops ride shm when available).
    int pos = rank - leader;
    uint8_t* bbuf = static_cast<uint8_t*>(vbuf);
    const int64_t kChunk = 4 << 20;
    for (int64_t off = 0; off < static_cast<int64_t>(nbytes);
         off += kChunk) {
      int64_t k = std::min(kChunk, static_cast<int64_t>(nbytes) - off);
      if (pos > 0) {
        st = RecvStream(net, rank - 1, bbuf + off, k);
        if (!st.ok()) return st;
      }
      if (pos < local_size - 1) {
        st = SendStream(net, rank + 1, bbuf + off, k);
        if (!st.ok()) return st;
      }
    }
  }
  return Status::OK();
}

Status RingAllgatherv(Network& net, uint8_t* buf,
                      const std::vector<int64_t>& bytes,
                      const std::vector<int64_t>& offsets) {
  const int size = net.size();
  const int rank = net.rank();
  if (size == 1) return Status::OK();
  const int right = (rank + 1) % size;
  const int left = (rank - 1 + size) % size;
  for (int t = 0; t < size - 1; ++t) {
    int send_b = ((rank - t) % size + size) % size;
    int recv_b = ((rank - t - 1) % size + size) % size;
    Status st = FullDuplex(net, right, buf + offsets[send_b],
                           bytes[send_b], left, buf + offsets[recv_b],
                           bytes[recv_b]);
    if (!st.ok()) return st;
  }
  return Status::OK();
}

Status ChainBroadcast(Network& net, void* vbuf, int64_t nbytes, int root) {
  const int size = net.size();
  const int rank = net.rank();
  if (size == 1 || nbytes == 0) return Status::OK();
  uint8_t* buf = static_cast<uint8_t*>(vbuf);
  // Rotate so root is position 0 in the chain; forward chunk-by-chunk so
  // the chain pipelines (downstream ranks start receiving while upstream
  // bytes are still in flight) instead of store-and-forwarding the whole
  // payload at each hop.
  int pos = ((rank - root) % size + size) % size;
  int prev = (rank - 1 + size) % size;
  int next = (rank + 1) % size;
  const int64_t kChunk = 4 << 20;
  for (int64_t off = 0; off < nbytes; off += kChunk) {
    int64_t k = std::min(kChunk, nbytes - off);
    if (pos > 0) {
      Status st = RecvStream(net, prev, buf + off, k);
      if (!st.ok()) return st;
    }
    if (pos < size - 1) {
      Status st = SendStream(net, next, buf + off, k);
      if (!st.ok()) return st;
    }
  }
  return Status::OK();
}

Status PairwiseAlltoallv(Network& net, const uint8_t* send,
                         const std::vector<int64_t>& send_bytes,
                         uint8_t* recv,
                         const std::vector<int64_t>& recv_bytes) {
  const int size = net.size();
  const int rank = net.rank();
  std::vector<int64_t> soff(size + 1, 0), roff(size + 1, 0);
  for (int i = 0; i < size; ++i) {
    soff[i + 1] = soff[i] + send_bytes[i];
    roff[i + 1] = roff[i] + recv_bytes[i];
  }
  // Self copy.
  memcpy(recv + roff[rank], send + soff[rank], send_bytes[rank]);
  for (int d = 1; d < size; ++d) {
    int to = (rank + d) % size;
    int from = (rank - d + size) % size;
    Status st = FullDuplex(net, to, send + soff[to], send_bytes[to],
                           from, recv + roff[from], recv_bytes[from]);
    if (!st.ok()) return st;
  }
  return Status::OK();
}

namespace {

template <typename T>
void AdasumPair(T* a, const T* b, int64_t n) {
  double dot = 0, na = 0, nb = 0;
  for (int64_t i = 0; i < n; ++i) {
    double x = static_cast<double>(a[i]);
    double y = static_cast<double>(b[i]);
    dot += x * y;
    na += x * x;
    nb += y * y;
  }
  double ac = na > 0 ? 1.0 - dot / (2.0 * na) : 1.0;
  double bc = nb > 0 ? 1.0 - dot / (2.0 * nb) : 1.0;
  for (int64_t i = 0; i < n; ++i)
    a[i] = static_cast<T>(ac * static_cast<double>(a[i]) +
                          bc * static_cast<double>(b[i]));
}

template <typename T>
void AdasumTree(std::vector<std::vector<uint8_t>>& bufs, int64_t n) {
  // Pair (0,1),(2,3)... then pairs-of-pairs — same tree as ops/adasum.py.
  size_t m = bufs.size();
  std::vector<int> live(m);
  for (size_t i = 0; i < m; ++i) live[i] = static_cast<int>(i);
  while (live.size() > 1) {
    std::vector<int> nxt;
    for (size_t i = 0; i + 1 < live.size(); i += 2) {
      AdasumPair(reinterpret_cast<T*>(bufs[live[i]].data()),
                 reinterpret_cast<const T*>(bufs[live[i + 1]].data()), n);
      nxt.push_back(live[i]);
    }
    if (live.size() % 2 == 1) nxt.push_back(live.back());
    live = nxt;
  }
  if (live[0] != 0) bufs[0] = bufs[live[0]];
}

}  // namespace

Status AdasumAllreduce(Network& net, void* vbuf, int64_t count,
                       DataType dtype) {
  const int size = net.size();
  if (size == 1 || count == 0) return Status::OK();
  if (dtype != DataType::FLOAT32 && dtype != DataType::FLOAT64)
    return Status::InvalidArgument(
        "eager Adasum supports float32/float64");
  const size_t elem = DataTypeSize(dtype);
  const size_t nbytes = count * elem;
  // Gather all contributions (simple but exact; VHDD schedule is a later
  // optimization — the compiled path handles large tensors).
  std::vector<std::vector<uint8_t>> bufs(size);
  std::vector<int64_t> bytes(size, nbytes), offsets(size);
  std::vector<uint8_t> gathered(nbytes * size);
  for (int i = 0; i < size; ++i) offsets[i] = i * nbytes;
  memcpy(gathered.data() + net.rank() * nbytes, vbuf, nbytes);
  Status st = RingAllgatherv(net, gathered.data(), bytes, offsets);
  if (!st.ok()) return st;
  for (int i = 0; i < size; ++i)
    bufs[i].assign(gathered.begin() + i * nbytes,
                   gathered.begin() + (i + 1) * nbytes);
  if (dtype == DataType::FLOAT32)
    AdasumTree<float>(bufs, count);
  else
    AdasumTree<double>(bufs, count);
  memcpy(vbuf, bufs[0].data(), nbytes);
  return Status::OK();
}

}  // namespace hvdtpu
