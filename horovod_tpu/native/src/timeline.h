// Chrome-tracing timeline writer.
//
// Capability parity with the reference Timeline (timeline.h:36-168,
// timeline.cc:443-640): per-tensor phases (NEGOTIATE → operation →
// activities) written as Chrome trace events on a dedicated writer thread,
// enabled by HOROVOD_TIMELINE / HVD_TPU_TIMELINE or started at runtime.
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <mutex>
#include <queue>
#include <string>
#include <thread>

namespace hvdtpu {

class Timeline {
 public:
  ~Timeline() { Stop(); }
  // size: communicator size — one process_name/process_sort_index
  // metadata row is emitted per rank up front, so per-rank events (pid =
  // rank) render as one labeled row per rank in chrome://tracing instead
  // of interleaving on the recorder's pid.
  void Start(const std::string& filename, int rank, int size = 1);
  void Stop();
  bool active() const { return active_; }

  // ph: "B" begin / "E" end / "i" instant. category groups rows.  args,
  // when non-empty, is a pre-rendered JSON object body (e.g. {"rank":2})
  // attached to the event — used for the per-rank NEGOTIATE ready instants
  // (reference timeline.cc:496-541).  pid < 0 means "the recording
  // rank"; events that belong to a specific rank (negotiate readiness)
  // pass that rank so the trace attributes them to the right row.
  void Record(const std::string& name, const char* ph,
              const std::string& category, const std::string& args = "",
              int pid = -1);
  void MarkCycle();

 private:
  void WriterLoop();
  struct Event {
    std::string name;
    std::string cat;
    char ph;
    int64_t ts_us;
    std::string args;
    int pid;
  };
  std::atomic<bool> active_{false};
  bool stop_requested_ = false;
  int rank_ = 0;
  FILE* file_ = nullptr;
  bool first_event_ = true;
  std::chrono::steady_clock::time_point t0_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::queue<Event> queue_;
  std::thread writer_;
};

}  // namespace hvdtpu
