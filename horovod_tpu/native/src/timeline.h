// Chrome-tracing timeline writer.
//
// Capability parity with the reference Timeline (timeline.h:36-168,
// timeline.cc:443-640): per-tensor phases (NEGOTIATE → operation →
// activities) written as Chrome trace events on a dedicated writer thread,
// enabled by HOROVOD_TIMELINE / HVD_TPU_TIMELINE or started at runtime.
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <mutex>
#include <queue>
#include <string>
#include <thread>

namespace hvdtpu {

class Timeline {
 public:
  ~Timeline() { Stop(); }
  void Start(const std::string& filename, int rank);
  void Stop();
  bool active() const { return active_; }

  // ph: "B" begin / "E" end / "i" instant. category groups rows.  args,
  // when non-empty, is a pre-rendered JSON object body (e.g. {"rank":2})
  // attached to the event — used for the per-rank NEGOTIATE ready instants
  // (reference timeline.cc:496-541).
  void Record(const std::string& name, const char* ph,
              const std::string& category, const std::string& args = "");
  void MarkCycle();

 private:
  void WriterLoop();
  struct Event {
    std::string name;
    std::string cat;
    char ph;
    int64_t ts_us;
    std::string args;
  };
  std::atomic<bool> active_{false};
  bool stop_requested_ = false;
  int rank_ = 0;
  FILE* file_ = nullptr;
  bool first_event_ = true;
  std::chrono::steady_clock::time_point t0_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::queue<Event> queue_;
  std::thread writer_;
};

}  // namespace hvdtpu
