// The native runtime: background thread, tensor queue, fusion buffer,
// execution of negotiated collectives, async handles.
//
// Capability parity with the reference core (operations.cc:353-587
// BackgroundThreadLoop / RunLoopOnce, tensor_queue.h:28-66 TensorQueue with
// duplicate-name rejection, fusion_buffer_manager.h FusionBufferManager,
// global_state.h HorovodGlobalState): framework threads enqueue named
// tensors; the background thread announces them to the controller each
// cycle, packs ready fused sets into the fusion buffer, runs the TCP ring
// data plane, and resolves handles.
#pragma once

#include <atomic>
#include <condition_variable>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "cache.h"
#include "collectives.h"
#include "common.h"
#include "controller.h"
#include "net.h"
#include "timeline.h"
#include "wire.h"

namespace hvdtpu {

// Device-executor callback (registered from Python via ctypes): executes one
// negotiated, possibly-fused Response whose entries are accelerator-resident
// — the TPU analog of NCCLAllreduce::Execute running on device buffers
// inside the negotiated runtime (reference nccl_operations.cc:126-184).
// Invoked on the background thread in coordinator response order (identical
// on every rank, so SPMD-dispatched device collectives line up).
//
// Two-phase protocol (the analog of the reference's async-error abort,
// nccl_operations.cc:96-109, which this plane cannot replicate because an
// XLA collective in flight cannot be aborted): PREPARE runs every check
// that can fail *before* any SPMD dispatch (executor wiring, spanning JAX
// world, dtype, staged inputs); the runtime then agrees the per-rank
// PREPARE status across all ranks over the wire, and only a unanimous OK
// proceeds to EXECUTE — so a rank that would fail can no longer strand its
// peers inside the device collective.  ABORT drops state staged by a
// PREPARE whose agreement failed.  A second agreement after EXECUTE turns
// any late failure into an ERROR on every rank.
// Returns 0 on success; nonzero with a message written into err.
enum DeviceExecPhase {
  kDevicePrepare = 0,
  kDeviceExecute = 1,
  kDeviceAbort = 2,
};
typedef int (*DeviceExecutorFn)(int phase, int request_type, int n,
                                const char** names, const int64_t* sizes,
                                int dtype, int op, int root_rank,
                                double prescale, double postscale, char* err,
                                int err_cap);

struct HandleState {
  std::atomic<bool> done{false};
  Status status;
  std::shared_ptr<TensorEntry> entry;  // keeps var_output alive
};

class Runtime {
 public:
  static Runtime& Get();

  Status Init(int rank, int size, const std::string& coord_addr,
              int64_t fusion_threshold, double cycle_time_ms,
              double stall_warning_s, double stall_shutdown_s,
              const std::string& timeline_file,
              size_t cache_capacity = 1024);
  void Shutdown();
  bool initialized() const { return initialized_; }
  int rank() const { return net_ ? net_->rank() : 0; }
  int size() const { return net_ ? net_->size() : 1; }

  // Returns handle id, or -1 with *status set (e.g. duplicate name).
  int64_t Enqueue(std::shared_ptr<TensorEntry> entry, Status* status);
  bool Poll(int64_t handle);
  Status Wait(int64_t handle);  // blocks; does NOT release
  std::shared_ptr<TensorEntry> GetEntry(int64_t handle);
  void Release(int64_t handle);

  int JoinBlocking();
  Status BarrierBlocking();
  // Autotune hooks: runtime-adjustable knobs + data-plane byte counters.
  void SetParams(int64_t fusion_threshold, double cycle_time_ms);
  void ReadCounters(int64_t* bytes, double* seconds);
  // Node topology for hierarchical collectives (ranks grouped into nodes
  // of local_size consecutive ranks; ICI-intra / DCN-inter analog).
  void SetTopology(int local_size, bool hierarchical_allreduce,
                   bool hierarchical_allgather);
  // Eager wire compression (quantized collective engine): forwarded to
  // the coordinator, which stamps it into every round's ResponseList;
  // WireCompression() returns the stream-adopted value — NEVER the
  // locally-set one — so a rank 0 flip cannot race peers mid-round.
  void SetWireCompression(int code);
  int WireCompression() const { return coord_wire_compression_.load(); }
  // Categorical autotune toggles (reference parameter_manager.h:91-93):
  // forwarded to the coordinator, which stamps each Response's algorithm
  // choice and distributes the cache toggle — execution consults the
  // RESPONSE, never local state, so mid-run flips stay rank-consistent.
  void SetTunedToggles(bool hierarchical_allreduce,
                       bool hierarchical_allgather, bool cache_enabled);
  // Per-payload schedule dispatch table (topology probe / tuner
  // refinement): forwarded to the coordinator, which stamps each
  // response's schedule from its FINAL fused payload size.  Coordinator-
  // only effect, like SetWireCompression.
  void SetScheduleTable(int kind, std::vector<ScheduleSegment> segs);
  void SetCacheOn(bool cache_enabled);
  void SetDeviceExecutor(DeviceExecutorFn fn) { device_executor_ = fn; }
  void StartTimeline(const std::string& filename);
  void StopTimeline();
  // Stall-inspector snapshot (controller::StalledJson); "[]" when not
  // initialized or not the coordinator.
  std::string StalledJson();
  // Test/observability hook: names in the most recent (possibly fused)
  // allreduce Response this rank executed — shows the live fusion
  // threshold's effect (autotune integration evidence).
  int64_t LastFusedNames() const { return last_fused_names_.load(); }

 private:
  Runtime() = default;
  void BackgroundLoop();
  void ExecuteResponse(const Response& resp);
  void ExecuteAllreduce(const Response& resp,
                        std::vector<std::shared_ptr<TensorEntry>>& entries);
  void ExecuteAllgather(const Response& resp,
                        std::shared_ptr<TensorEntry> entry);
  void ExecuteBroadcast(const Response& resp,
                        std::shared_ptr<TensorEntry> entry);
  void ExecuteAlltoall(const Response& resp,
                       std::shared_ptr<TensorEntry> entry);
  void ExecuteDeviceCollective(
      const Response& resp,
      std::vector<std::shared_ptr<TensorEntry>>& entries);
  std::shared_ptr<TensorEntry> TakeSubmitted(const std::string& name);
  void Finish(std::shared_ptr<TensorEntry>& e, const Status& s);

  std::atomic<bool> initialized_{false};
  std::atomic<bool> stop_{false};
  // Graceful teardown: Shutdown() requests, the loop announces it on the
  // wire each round, and only global consensus (responses.shutdown)
  // breaks the loop — so the coordinator keeps serving rounds until
  // every rank is ready to leave (a hard stop would sever stragglers
  // mid-negotiation).
  std::atomic<bool> shutdown_requested_{false};
  std::atomic<bool> loop_exited_{false};
  std::atomic<bool> loop_dead_{false};
  std::unique_ptr<Network> net_;
  std::unique_ptr<Controller> controller_;
  std::thread background_;
  std::atomic<double> cycle_time_ms_{1.0};

  std::mutex mu_;
  std::condition_variable enqueue_cv_;
  // Pending = enqueued, not yet announced. Submitted = announced, awaiting
  // response. Both keyed by name; duplicate names across the union rejected.
  std::map<std::string, std::shared_ptr<TensorEntry>> pending_;
  std::vector<std::string> pending_order_;
  std::map<std::string, std::shared_ptr<TensorEntry>> submitted_;

  std::mutex handle_mu_;
  std::condition_variable handle_cv_;
  int64_t next_handle_ = 0;
  std::map<int64_t, std::shared_ptr<HandleState>> handles_;
  std::map<std::string, int64_t> name_to_handle_;

  // Join/barrier signaling.
  std::mutex sync_mu_;
  std::condition_variable sync_cv_;
  std::atomic<bool> join_requested_{false};
  std::atomic<bool> barrier_requested_{false};
  int last_joined_rank_ = -2;  // -2 = no join completed yet
  bool barrier_released_ = false;

  std::vector<uint8_t> fusion_buffer_;
  // Worker-side response cache mirror (bit table mirrors the coordinator's
  // assignments received in responses).
  ResponseCache worker_cache_{1024};
  int64_t fusion_threshold_ = 64 * 1024 * 1024;
  std::atomic<int64_t> bytes_processed_{0};
  int local_size_ = 1;
  // The hierarchical toggles live in the Controller (stamped onto each
  // Response); execution consults resp.hierarchical ONLY — no local
  // mirror exists to drift out of sync.
  bool tuned_cache_on_ = true;
  // Coordinator's distributed cache toggle (ResponseList::cache_on),
  // adopted each round: gates this worker's bit announcements.
  std::atomic<bool> coord_cache_on_{true};
  // Coordinator's wire-compression stamp, adopted each round before the
  // round's responses execute (ResponseList::wire_compression).
  std::atomic<int> coord_wire_compression_{0};
  std::atomic<DeviceExecutorFn> device_executor_{nullptr};
  std::atomic<int64_t> last_fused_names_{0};
  std::chrono::steady_clock::time_point counter_start_;
  Timeline timeline_;
  Status loop_error_;

  // Device-response stall watchdog: the negotiation-plane stall inspector
  // (controller.cc) cannot see a device Response stuck inside the
  // executor (e.g. one rank's jit blocked on a dead peer's collective),
  // because the background thread itself is the one blocked.  A separate
  // thread watches the in-flight marker and warns after stall_warning_s
  // (reference: the stall inspector watches the full op lifetime).
  void DeviceWatchdog();
  std::thread watchdog_;
  std::atomic<bool> watchdog_stop_{false};
  std::atomic<int64_t> device_exec_start_ms_{0};  // 0 = none in flight
  std::atomic<bool> device_exec_warned_{false};
  std::mutex watch_mu_;
  std::condition_variable watch_cv_;
  std::string device_exec_name_;  // guarded by watch_mu_
  double stall_warning_s_ = 60.0;
};

}  // namespace hvdtpu
