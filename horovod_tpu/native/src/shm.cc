#include "shm.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sched.h>
#include <sys/uio.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>
#include <cstring>

namespace hvdtpu {

namespace {

constexpr size_t kMapBytes =
    sizeof(ShmChannel::Hdr) + ShmChannel::kSlots * ShmChannel::kSlotBytes;
constexpr uint64_t kProbeMagic = 0x48764474707531ULL;

// Bounded wait on a shm condition: brief spin for the multi-core
// streaming case, then sched_yield — on an oversubscribed or single-CPU
// host a pure spin PREVENTS the peer from running until the spinner's
// timeslice ends, and a usleep(50) pays ~wakeup-latency per ring-slot
// handoff (measured: shm lost to TCP at 1MB payloads on a 1-core box
// because blocking socket reads hand the CPU to the producer
// immediately).  yield gives the same immediate handoff; micro-sleeps
// only as the deep fallback.  60 s deadline like the socket paths.
template <typename Cond>
Status WaitFor(Cond cond, const char* what) {
  for (int i = 0; i < 64; ++i) {
    if (cond()) return Status::OK();
  }
  for (int i = 0; i < 4096; ++i) {
    if (cond()) return Status::OK();
    ::sched_yield();
  }
  auto deadline =
      std::chrono::steady_clock::now() + std::chrono::seconds(60);
  while (!cond()) {
    if (std::chrono::steady_clock::now() > deadline)
      return Status::Error(std::string("shm channel timeout: ") + what);
    ::usleep(50);
  }
  return Status::OK();
}

}  // namespace

std::unique_ptr<ShmChannel> ShmChannel::Create(const std::string& name) {
  int fd = ::shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0 && errno == EEXIST) {
    // Stale segment from a crashed prior job: replace it.
    ::shm_unlink(name.c_str());
    fd = ::shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  }
  if (fd < 0) return nullptr;
  if (::ftruncate(fd, kMapBytes) != 0) {
    ::close(fd);
    ::shm_unlink(name.c_str());
    return nullptr;
  }
  void* map = ::mmap(nullptr, kMapBytes, PROT_READ | PROT_WRITE,
                     MAP_SHARED, fd, 0);
  ::close(fd);
  if (map == MAP_FAILED) {
    ::shm_unlink(name.c_str());
    return nullptr;
  }
  auto ch = std::unique_ptr<ShmChannel>(new ShmChannel());
  ch->map_ = map;
  ch->map_bytes_ = kMapBytes;
  ch->name_ = name;
  ch->hdr_ = new (map) Hdr();
  ch->hdr_->head.store(0, std::memory_order_relaxed);
  ch->hdr_->tail.store(0, std::memory_order_relaxed);
  memset(ch->hdr_->addrs, 0, sizeof(ch->hdr_->addrs));
  ch->hdr_->producer_pid = ::getpid();
  ch->hdr_->probe_magic = kProbeMagic;
  ch->hdr_->poisoned.store(0, std::memory_order_relaxed);
  ch->hdr_->producer_probe_addr =
      reinterpret_cast<uint64_t>(&ch->hdr_->probe_magic);
  ch->slots_ = static_cast<uint8_t*>(map) + sizeof(Hdr);
  return ch;
}

std::unique_ptr<ShmChannel> ShmChannel::Open(const std::string& name) {
  int fd = -1;
  // The creator may not have finished Create yet: retry briefly.
  for (int i = 0; i < 200 && fd < 0; ++i) {
    fd = ::shm_open(name.c_str(), O_RDWR, 0600);
    if (fd < 0) ::usleep(10000);
  }
  if (fd < 0) return nullptr;
  struct stat st{};
  if (::fstat(fd, &st) != 0 ||
      static_cast<size_t>(st.st_size) < kMapBytes) {
    // Racing the creator's ftruncate: wait for the full size.
    for (int i = 0; i < 200; ++i) {
      ::usleep(10000);
      if (::fstat(fd, &st) == 0 &&
          static_cast<size_t>(st.st_size) >= kMapBytes) {
        break;
      }
    }
    if (static_cast<size_t>(st.st_size) < kMapBytes) {
      ::close(fd);
      return nullptr;
    }
  }
  void* map = ::mmap(nullptr, kMapBytes, PROT_READ | PROT_WRITE,
                     MAP_SHARED, fd, 0);
  ::close(fd);
  if (map == MAP_FAILED) return nullptr;
  auto ch = std::unique_ptr<ShmChannel>(new ShmChannel());
  ch->map_ = map;
  ch->map_bytes_ = kMapBytes;
  ch->name_ = name;
  ch->hdr_ = static_cast<Hdr*>(map);
  ch->slots_ = static_cast<uint8_t*>(map) + sizeof(Hdr);
  return ch;
}

ShmChannel::~ShmChannel() {
  if (map_ != nullptr) ::munmap(map_, map_bytes_);
}

void ShmChannel::Unlink() {
  if (!name_.empty()) {
    ::shm_unlink(name_.c_str());
    name_.clear();
  }
}

Status ShmChannel::Push(const uint8_t* data, size_t n) {
  if (n > kSlotBytes)
    return Status::Error("shm Push: chunk of " + std::to_string(n) +
                         " bytes exceeds slot size " +
                         std::to_string(kSlotBytes));
  uint64_t head = hdr_->head.load(std::memory_order_relaxed);
  Status st = WaitFor(
      [&] {
        return head - hdr_->tail.load(std::memory_order_acquire) < kSlots;
      },
      "producer waiting for a free slot");
  if (!st.ok()) return st;
  size_t slot = head % kSlots;
  memcpy(slots_ + slot * kSlotBytes, data, n);
  hdr_->lens[slot] = n;
  hdr_->addrs[slot] = 0;
  hdr_->head.store(head + 1, std::memory_order_release);
  return Status::OK();
}

Status ShmChannel::PushRef(const uint8_t* data, size_t n) {
  // No size guard here: a descriptor publishes (addr, n) without copying
  // into the slot, and the consumer chunk-reads arbitrarily large regions.
  uint64_t head = hdr_->head.load(std::memory_order_relaxed);
  Status st = WaitFor(
      [&] {
        return head - hdr_->tail.load(std::memory_order_acquire) < kSlots;
      },
      "producer waiting for a free ref slot");
  if (!st.ok()) {
    // Aborting with descriptors possibly still published: the region may
    // be reused by the caller — the consumer must not trust later reads.
    hdr_->poisoned.store(1, std::memory_order_release);
    return st;
  }
  size_t slot = head % kSlots;
  hdr_->lens[slot] = n;
  hdr_->addrs[slot] = reinterpret_cast<uint64_t>(data);
  hdr_->head.store(head + 1, std::memory_order_release);
  return Status::OK();
}

Status ShmChannel::WaitDrained() {
  uint64_t head = hdr_->head.load(std::memory_order_relaxed);
  Status st = WaitFor(
      [&] {
        return hdr_->tail.load(std::memory_order_acquire) >= head;
      },
      "producer waiting for the consumer to finish reading");
  if (!st.ok()) hdr_->poisoned.store(1, std::memory_order_release);
  return st;
}

Status ShmChannel::PopInto(uint8_t* dst, size_t max_n, size_t* got) {
  uint64_t tail = hdr_->tail.load(std::memory_order_relaxed);
  Status st = WaitFor(
      [&] {
        return hdr_->head.load(std::memory_order_acquire) > tail;
      },
      "consumer waiting for a chunk");
  if (!st.ok()) return st;
  size_t slot = tail % kSlots;
  size_t n = hdr_->lens[slot];
  if (n > max_n)
    return Status::Error("shm chunk larger than receive window");
  if (hdr_->addrs[slot] != 0) {
    // Descriptor: pull straight from the producer's memory.
    size_t off = 0;
    while (off < n) {
      iovec liov{dst + off, std::min<size_t>(n - off, 8 << 20)};
      iovec riov{reinterpret_cast<void*>(hdr_->addrs[slot] + off),
                 liov.iov_len};
      ssize_t k = ::process_vm_readv(hdr_->producer_pid, &liov, 1,
                                     &riov, 1, 0);
      if (k <= 0)
        return Status::Error("process_vm_readv failed mid-transfer");
      off += static_cast<size_t>(k);
    }
  } else {
    memcpy(dst, slots_ + slot * kSlotBytes, n);
  }
  if (hdr_->poisoned.load(std::memory_order_acquire))
    return Status::Error("shm channel poisoned by an aborted producer");
  *got = n;
  hdr_->tail.store(tail + 1, std::memory_order_release);
  return Status::OK();
}

bool ShmChannel::ProbeCma() {
  // The probe target address is the PRODUCER's VA of probe_magic —
  // published by the producer itself (this process maps the segment at a
  // different address).
  uint64_t magic = 0;
  iovec liov{&magic, sizeof(magic)};
  iovec riov{reinterpret_cast<void*>(hdr_->producer_probe_addr),
             sizeof(magic)};
  ssize_t k = ::process_vm_readv(hdr_->producer_pid, &liov, 1, &riov, 1,
                                 0);
  return k == sizeof(magic) && magic == kProbeMagic;
}

}  // namespace hvdtpu
