// CPU data-plane collectives over the TCP full mesh.
//
// Capability parity with the reference's CPU op backends (MPI/Gloo ops,
// ops/mpi_operations.cc, ops/gloo_operations.cc): ring allreduce
// (reduce-scatter + allgather, the bandwidth-optimal schedule NCCL uses),
// chain broadcast, ring allgatherv, pairwise alltoallv; dtype-dispatched
// reduction kernels incl. fp16/bf16 with fp32 accumulation
// (reference half.cc), Adasum (gather + coefficient tree, ops/adasum/).
#pragma once

#include <cstdint>
#include <functional>
#include <vector>

#include "common.h"
#include "net.h"

namespace hvdtpu {

// In-place allreduce of buf (count elements of dtype) across all ranks.
// ``restore`` (optional): rewinds buf to its pre-collective contents for
// a renegotiated retry — when the caller can re-pack from still-intact
// inputs (the runtime's fusion path), the resilient wrapper skips its
// internal pre-collective snapshot copy entirely.
Status RingAllreduce(Network& net, void* buf, int64_t count, DataType dtype,
                     ReduceOp op,
                     const std::function<void()>* restore = nullptr);

// Ring allreduce restricted to `members` (sorted rank list containing the
// caller) — building block for hierarchical schedules.
Status RingAllreduceGroup(Network& net, void* buf, int64_t count,
                          DataType dtype, ReduceOp op,
                          const std::vector<int>& members);

// Hierarchical allreduce (reference NCCLHierarchicalAllreduce,
// nccl_operations.cc:186-260 / MPIHierarchicalAllgather shape): ranks are
// grouped into nodes of `local_size` consecutive ranks; phase 1 reduces
// within the node, phase 2 ring-reduces across node leaders, phase 3
// broadcasts within the node.  On TPU pods the analogous grouping is
// intra-slice (ICI) vs inter-slice (DCN).  Falls back to the flat ring when
// the topology doesn't divide evenly.
Status HierarchicalAllreduce(Network& net, void* buf, int64_t count,
                             DataType dtype, ReduceOp op, int local_size);

// buf holds this rank's my_bytes at offset offsets[rank]; fills the rest.
// offsets/bytes per rank; buf has total size sum(bytes).
Status RingAllgatherv(Network& net, uint8_t* buf,
                      const std::vector<int64_t>& bytes,
                      const std::vector<int64_t>& offsets);

// Hierarchical allgather (reference MPIHierarchicalAllgather,
// mpi_operations.cc:186-341: node-leader gather staged through shared
// memory, cross-node exchange, intra-node fan-out): phase 1 gathers node
// members' blocks to the node leader over intra-node hops (shm/CMA when
// available), phase 2 ring-allgathervs node-level blocks across leaders,
// phase 3 fans the full result down the intra-node chain, chunk-pipelined.
// Falls back to the flat ring when the topology doesn't divide evenly.
Status HierarchicalAllgatherv(Network& net, uint8_t* buf,
                              const std::vector<int64_t>& bytes,
                              const std::vector<int64_t>& offsets,
                              int local_size);

// Test/observability hook: schedule used by the most recent allgather on
// this process (0 = flat ring, 1 = hierarchical with chain
// fan-out, 2 = hierarchical with CMA star fan-out).
int LastAllgatherSchedule();
// Schedule of the most recent allreduce/Adasum on this process (0 =
// flat ring / flat VHDD, 1 = hierarchical) — the allreduce analog of
// the allgather hook above; stored only for schedules that COMPLETED.
int LastAllreduceSchedule();
// Most recent hierarchical allreduce/Adasum fan-out and most recent
// broadcast schedule (0 = flat/none, 1 = chain, 2 = zero-copy CMA star).
int LastAllreduceFanout();
int LastBroadcastSchedule();

// In-place broadcast of buf from root (chain schedule).
Status ChainBroadcast(Network& net, void* buf, int64_t nbytes, int root);

// Cross-rank status agreement: *ok in/out (1 = this rank OK); after the
// call *ok is the AND over all ranks and *first_bad_rank the lowest rank
// that reported failure (-1 when unanimous OK).  Star exchange over the
// mesh sockets; callers must invoke it at the same point of the same
// response schedule on every rank (the runtime does, in coordinator
// response order).  The TPU-side analog of the reference's NCCL
// async-error agreement (nccl_operations.cc:96-109).
Status AgreeAllRanks(Network& net, int32_t* ok, int32_t* first_bad_rank);

// send: concatenated segments for each destination (send_bytes[d] each);
// recv: filled with segments from each source (recv_bytes[s] each).
Status PairwiseAlltoallv(Network& net, const uint8_t* send,
                         const std::vector<int64_t>& send_bytes,
                         uint8_t* recv,
                         const std::vector<int64_t>& recv_bytes);

// Adasum allreduce: chunked pairwise vector-halving distance-doubling with
// grouped scalar reductions for the adaptive coefficients (reference
// adasum.h:168-395, adasum_mpi.cc:107-110; same numerics as ops/adasum.py).
// O(|t|) scratch on power-of-two worlds; gather + coefficient tree fallback
// otherwise.  fp16/bf16 accepted with fp32 accumulation.
Status AdasumAllreduce(Network& net, void* buf, int64_t count,
                       DataType dtype);

// Hierarchical Adasum (reference adasum_gpu_operations.cc:38-…): intra-node
// sum, cross-node VHDD between node leaders, local-average fold-in,
// intra-node fan-out.  Falls back to flat Adasum when the topology doesn't
// divide evenly or the node count is not a power of two.
Status HierarchicalAdasum(Network& net, void* buf, int64_t count,
                          DataType dtype, int local_size);

// Test/observability hooks: peak scratch bytes allocated by the VHDD path
// since the last reset (proves the O(|t|) memory bound).
int64_t AdasumScratchPeak();
void ResetAdasumScratchPeak();

// Elementwise scale in place (used for prescale/postscale/average).
// Integer dtypes truncate toward zero (double multiply + C cast).
void ScaleBuffer(void* buf, int64_t count, DataType dtype, double factor);

// Integer Average: exact floor-divide in the integer domain, matching the
// compiled path's contract (ops/collective.py _compiled_allreduce — float
// widening cannot promise exactness, and truncation disagrees with floor
// for negative sums).  No-op for non-integer dtypes.  Returns true if it
// handled the dtype.
bool FloorAverageInt(void* buf, int64_t count, DataType dtype,
                     int64_t divisor);

}  // namespace hvdtpu
