// TCP transport: full-mesh peer connections bootstrapped through the
// coordinator (rank 0).
//
// Capability parity with the reference's Gloo context creation
// (gloo/gloo_context.cc:66-160: TCP devices + rendezvous KV): rank 0 binds
// the address the launcher exported (HVD_TPU_CONTROLLER_ADDR), workers dial
// in, the address table is broadcast, then every pair connects directly.
#pragma once

#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "common.h"
#include "shm.h"

namespace hvdtpu {

// Persistent helper thread for full-duplex streaming: the data plane
// overlaps one send with one recv per ring round, and spawning a fresh
// std::thread per round (2(P-1) spawns per allreduce) costs more than
// the transfer at small payloads.  One lazily-started helper per
// Network; the background thread is the only submitter.
class DuplexHelper {
 public:
  ~DuplexHelper() { Stop(); }

  // Runs fn on the helper thread; pair with Wait() before touching the
  // buffers fn captures.  Single-submitter contract (the background
  // thread): overlapping Run calls would overwrite the in-flight task's
  // closure (whose by-reference captures then dangle) — abort loudly
  // instead of corrupting silently.
  void Run(std::function<void()> fn) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (busy_) {
        fprintf(stderr,
                "DuplexHelper: overlapping Run (single-submitter "
                "contract violated)\n");
        std::abort();
      }
      if (!started_) {
        started_ = true;
        th_ = std::thread([this] { Loop(); });
      }
      task_ = std::move(fn);
      has_task_ = true;
      done_ = false;
      busy_ = true;
    }
    cv_.notify_all();
  }

  void Wait() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [this] { return done_; });
    busy_ = false;
  }

  void Stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (!started_) return;
      stop_ = true;
    }
    cv_.notify_all();
    if (th_.joinable()) th_.join();
    std::lock_guard<std::mutex> lk(mu_);
    started_ = false;
    stop_ = false;
  }

 private:
  void Loop() {
    for (;;) {
      std::function<void()> fn;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || has_task_; });
        if (stop_) return;
        fn = std::move(task_);
        has_task_ = false;
      }
      fn();
      {
        std::lock_guard<std::mutex> lk(mu_);
        done_ = true;
      }
      cv_.notify_all();
    }
  }

  std::thread th_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::function<void()> task_;
  bool started_ = false;
  bool has_task_ = false;
  bool done_ = false;
  bool busy_ = false;
  bool stop_ = false;
};

class Socket {
 public:
  explicit Socket(int fd) : fd_(fd) {}
  ~Socket();
  Socket(const Socket&) = delete;

  Status SendAll(const void* data, size_t n);
  Status RecvAll(void* data, size_t n);
  // Frame = u32 little-endian length + payload.
  Status SendFrame(const std::vector<uint8_t>& payload);
  Status RecvFrame(std::vector<uint8_t>& payload);
  int fd() const { return fd_; }

 private:
  int fd_;
};

class Network {
 public:
  // Establish the full mesh. coord_addr: "host:port" of rank 0's listener.
  // Returns nullptr + error status on failure.
  static std::unique_ptr<Network> Connect(int rank, int size,
                                          const std::string& coord_addr,
                                          Status* status);
  ~Network() = default;

  Socket* peer(int r) { return peers_[r].get(); }
  Socket* coordinator() { return peers_[0].get(); }
  int rank() const { return rank_; }
  int size() const { return size_; }

  // Same-host shared-memory channels (null when the peer is remote or
  // shm setup failed — callers fall back to the TCP socket).
  ShmChannel* shm_tx(int r) { return shm_tx_[r].get(); }  // me → r
  ShmChannel* shm_rx(int r) { return shm_rx_[r].get(); }  // r → me

  DuplexHelper& duplex_helper() { return duplex_helper_; }

 private:
  Network(int rank, int size) : rank_(rank), size_(size) {
    peers_.resize(size);
    shm_tx_.resize(size);
    shm_rx_.resize(size);
  }
  void SetupShm(const std::vector<std::string>& table,
                const std::string& tag);
  int rank_;
  int size_;
  std::vector<std::unique_ptr<Socket>> peers_;
  std::vector<std::unique_ptr<ShmChannel>> shm_tx_;
  std::vector<std::unique_ptr<ShmChannel>> shm_rx_;
  DuplexHelper duplex_helper_;
};

}  // namespace hvdtpu
