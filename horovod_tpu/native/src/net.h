// TCP transport: full-mesh peer connections bootstrapped through the
// coordinator (rank 0), with a self-healing resilient channel layer.
//
// Capability parity with the reference's Gloo context creation
// (gloo/gloo_context.cc:66-160: TCP devices + rendezvous KV): rank 0 binds
// the address the launcher exported (HVD_TPU_CONTROLLER_ADDR), workers dial
// in, the address table is broadcast, then every pair connects directly.
//
// Resilience (HVD_TPU_NET_RESILIENCE, default on): every logical transfer
// between a pair of ranks is framed — a 16-byte header carrying a magic,
// the payload length and a per-direction frame sequence number — and
// acknowledged at operation granularity.  A broken connection (reset,
// dropped frame detected as a sequence gap, truncation) is re-established
// through the pair's persistent listeners and the transfer RESUMES from
// the last fully delivered frame, bounded by a per-operation deadline.
// Only when reconnection exhausts does the failure surface to the caller,
// where the ring-level recovery (collectives.cc) can re-form the ring
// around the dead link before escalating to the elastic reset.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <utility>
#include <vector>

#include "common.h"
#include "shm.h"

namespace hvdtpu {

// ---------------------------------------------------------------------------
// Resilience configuration (parsed once from env; uniform across the fleet
// because the launcher exports the knobs to every worker).
// ---------------------------------------------------------------------------
struct NetResilienceConfig {
  bool enabled = true;         // HVD_TPU_NET_RESILIENCE
  double probe_ms = 10000.0;   // HVD_TPU_NET_PROBE_MS: no-progress window
                               // before a mid-stream reconnect attempt
  double reconnect_s = 10.0;   // HVD_TPU_NET_RECONNECT_S: budget per
                               // reconnect-and-resume attempt
  double op_deadline_s = 60.0; // HVD_TPU_NET_OP_DEADLINE_S: total budget per
                               // logical transfer including recoveries
  int max_renegotiations = 2;  // HVD_TPU_NET_MAX_RENEG: ring re-formations
                               // per collective before escalating
  bool renegotiate = true;     // HVD_TPU_NET_RENEGOTIATE
};
const NetResilienceConfig& NetResilience();

// ---------------------------------------------------------------------------
// Seeded wire chaos (HVD_TPU_CHAOS_NET_*): deterministic fault injection in
// the native socket layer so the whole escalation ladder drills in CI
// without root.  Draws are a pure function of (seed, rank, peer, per-channel
// draw index) — channel writes are serialized, so the schedule replays
// bit-for-bit from its seed.
// ---------------------------------------------------------------------------
struct NetChaosConfig {
  uint64_t seed = 0;          // HVD_TPU_CHAOS_NET_SEED
  double drop_pct = 0.0;      // HVD_TPU_CHAOS_NET_DROP_PCT: swallow a data
                              // frame (receiver sees a sequence gap)
  double reset_pct = 0.0;     // HVD_TPU_CHAOS_NET_RESET_PCT: kill the
                              // connection before a data frame
  double delay_ms = 0.0;      // HVD_TPU_CHAOS_NET_DELAY_MS: per-frame delay
  double truncate_pct = 0.0;  // HVD_TPU_CHAOS_NET_TRUNCATE: write a partial
                              // frame, then kill the connection
  // HVD_TPU_CHAOS_NET_BLACKHOLE="a-b[,c-d]": the listed rank pairs lose
  // connectivity permanently once the mesh is up (reconnects refused) —
  // the renegotiation drill.
  std::set<std::pair<int, int>> blackhole;
  bool enabled() const {
    return drop_pct > 0 || reset_pct > 0 || delay_ms > 0 ||
           truncate_pct > 0 || !blackhole.empty();
  }
  bool blackholed(int a, int b) const {
    return blackhole.count({std::min(a, b), std::max(a, b)}) != 0;
  }
};
const NetChaosConfig& NetChaos();

// Deterministic draw in [0, 1) from (seed, rank, peer, index).
double NetChaosDraw(uint64_t seed, int rank, int peer, uint64_t index);

// ---------------------------------------------------------------------------
// Observability: the ladder's counters, exported through c_api to
// hvd.metrics (hvd_net_*_total) and to hang reports ("retrying, deadline
// not yet reached" vs "wedged").
// ---------------------------------------------------------------------------
struct NetCountersState {
  std::atomic<int64_t> retries{0};          // recovery attempts, any rung
  std::atomic<int64_t> reconnects{0};       // re-established connections
  std::atomic<int64_t> renegotiations{0};   // ring re-formations
  std::atomic<int64_t> resets_avoided{0};   // ops/collectives completed
                                            // after >= 1 recovery
  std::atomic<int64_t> chaos_injected{0};   // faults the chaos layer fired
  std::atomic<int> recovering_now{0};       // channels mid-recovery
  std::atomic<int64_t> last_recovery_ms{0}; // steady-clock ms of the last
                                            // recovery activity
  // Dev/diagnosis accumulators (exported in the trailing counter slots):
  // wall microseconds inside channel Send/Recv + op counts.
  std::atomic<int64_t> send_us{0};
  std::atomic<int64_t> recv_us{0};
  std::atomic<int64_t> send_ops{0};
  std::atomic<int64_t> recv_ops{0};
  std::atomic<int64_t> pump_wait_us{0};   // PumpOne first poll (arrival)
  std::atomic<int64_t> pump_read_us{0};   // PumpOne header+payload reads
  std::atomic<int64_t> write_us{0};       // WriteBytes total
  std::atomic<int64_t> cvwait_us{0};      // Pump cv fallback waits
};
NetCountersState& NetCounters();
int64_t SteadyNowMs();

class Network;

// Persistent helper thread for full-duplex streaming: the data plane
// overlaps one send with one recv per ring round, and spawning a fresh
// std::thread per round (2(P-1) spawns per allreduce) costs more than
// the transfer at small payloads.  One lazily-started helper per
// Network; the background thread is the only submitter.
class DuplexHelper {
 public:
  ~DuplexHelper() { Stop(); }

  // Runs fn on the helper thread; pair with Wait() before touching the
  // buffers fn captures.  Single-submitter contract (the background
  // thread): overlapping Run calls would overwrite the in-flight task's
  // closure (whose by-reference captures then dangle) — abort loudly
  // instead of corrupting silently.
  void Run(std::function<void()> fn) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (busy_) {
        fprintf(stderr,
                "DuplexHelper: overlapping Run (single-submitter "
                "contract violated)\n");
        std::abort();
      }
      if (!started_) {
        started_ = true;
        th_ = std::thread([this] { Loop(); });
      }
      task_ = std::move(fn);
      has_task_ = true;
      done_ = false;
      busy_ = true;
    }
    cv_.notify_all();
  }

  void Wait() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [this] { return done_; });
    busy_ = false;
  }

  void Stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (!started_) return;
      stop_ = true;
    }
    cv_.notify_all();
    if (th_.joinable()) th_.join();
    std::lock_guard<std::mutex> lk(mu_);
    started_ = false;
    stop_ = false;
  }

 private:
  void Loop() {
    for (;;) {
      std::function<void()> fn;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || has_task_; });
        if (stop_) return;
        fn = std::move(task_);
        has_task_ = false;
      }
      fn();
      {
        std::lock_guard<std::mutex> lk(mu_);
        done_ = true;
      }
      cv_.notify_all();
    }
  }

  std::thread th_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::function<void()> task_;
  bool started_ = false;
  bool has_task_ = false;
  bool done_ = false;
  bool busy_ = false;
  bool stop_ = false;
};

class Socket {
 public:
  explicit Socket(int fd) : fd_(fd) {}
  ~Socket();
  Socket(const Socket&) = delete;

  Status SendAll(const void* data, size_t n);
  Status RecvAll(void* data, size_t n);
  // Frame = u32 little-endian length + payload.
  Status SendFrame(const std::vector<uint8_t>& payload);
  Status RecvFrame(std::vector<uint8_t>& payload);
  int fd() const { return fd_; }
  int release() { int f = fd_; fd_ = -1; return f; }

 private:
  int fd_;
};

// One resilient bidirectional link to a peer.  In resilient mode every
// logical transfer is framed + acked and survives connection loss via
// reconnect-and-resume; in raw mode the wire bytes are identical to the
// pre-resilience protocol.  Thread contract: at most one in-flight send
// op and one in-flight recv op at a time (the collective schedules
// guarantee it); the two may run on different threads (FullDuplex).
class Channel {
 public:
  Channel(Network* net, int peer, int fd);
  ~Channel();

  // One logical transfer of exactly n bytes.  on_progress(delivered) is
  // invoked at frame granularity as the delivered prefix grows (never
  // for bytes a resume might rewrite).  `control` ops (negotiation
  // frames) never reconnect on mere inactivity — a peer legitimately
  // blocked in a long device collective is not a network fault — only
  // on hard socket errors, and wait without deadline like the raw
  // protocol did.
  Status Send(const uint8_t* buf, size_t n, bool control = false);
  // deadline_s bounds a CONTROL recv (the ring-recovery agreement is a
  // bounded rendezvous, unlike the open-ended negotiation wait); 0 keeps
  // the control default (no deadline).  Data ops always use the
  // configured op deadline.
  Status Recv(uint8_t* dst, size_t n,
              const std::function<void(size_t)>& on_progress = nullptr,
              bool control = false, double deadline_s = 0.0);
  // Length-prefixed message atop Send/Recv (controller exchange).
  Status SendMsg(const std::vector<uint8_t>& payload, bool control = true);
  Status RecvMsg(std::vector<uint8_t>& payload, bool control = true,
                 double deadline_s = 0.0);

  // Best-effort tiny frames outside the op stream.
  void SendAbort(uint64_t attempt_epoch);

  // Ring-recovery agreement frames: typed, epoch-keyed, OUTSIDE the op
  // stream — an aborted attempt's residue (partial data frames stashed
  // after the matching op died) can never be misread as an agreement
  // message.  The inbox keeps the latest payload per kind; epochs fence
  // stale attempts.
  Status SendRecoveryFrame(bool verdict, uint64_t epoch,
                           const std::vector<uint8_t>& payload,
                           double deadline_s);
  Status AwaitRecoveryFrame(bool verdict, uint64_t epoch,
                            std::vector<uint8_t>* out, double deadline_s);

  // Listener-thread hand-off: a freshly accepted reconnect (resume) or
  // reset socket for this channel.
  void AdoptResumed(int fd);
  void AdoptReset(int fd, uint64_t generation);
  // Close the current socket and rebuild the link from scratch at
  // `generation` (ring renegotiation: in-flight bytes of the aborted
  // attempt are discarded on both sides).
  Status Reset(uint64_t generation, double deadline_s);

  int peer() const { return peer_; }
  bool connected() const { return fd_.load() >= 0; }
  int fd() const { return fd_.load(); }  // raw-mode duplex poll loop only

 private:
  friend class Network;
  struct Deadline;
  Status WriteFrameVec(int fd, uint32_t magic, uint64_t seq,
                       const uint8_t* payload, size_t n);
  Status RawSend(const uint8_t* buf, size_t n, bool control);
  Status RawRecv(uint8_t* dst, size_t n,
                 const std::function<void(size_t)>& on_progress,
                 bool control);
  // Retransmit the unacked replay tail on a freshly resumed socket
  // (called by the resume completer with the new fd, pre-adoption).
  bool RetransmitReplay(int fd, uint64_t peer_recv_bytes,
                        uint64_t peer_recv_frames);
  Status WriteBytes(int fd, const uint8_t* p, size_t n);
  Status WriteDataFrame(const uint8_t* payload, size_t n, uint64_t seq);
  Status WriteControlFrame(uint32_t magic, uint64_t seq);
  // Reads + dispatches one incoming frame (data -> the registered recv
  // op or the stash; ack -> sender state; abort -> the network's abort
  // flag).  Returns IN_PROGRESS when the poll slice elapsed quietly.
  Status PumpOne(int slice_ms);
  Status Pump(Deadline& dl, bool control, uint64_t op_id, bool for_send);
  Status Recover(uint64_t failed_epoch, Deadline& dl);
  void ApplyResume(uint64_t peer_recv_bytes, uint64_t peer_recv_frames,
                   uint64_t peer_recv_ops);
  void CloseFd();
  void ReapGraveyard();
  bool Aborted() const;

  Network* net_;
  int peer_;
  bool dialer_;  // this side re-dials on reconnect (higher rank dials)
  std::atomic<int> fd_{-1};
  std::atomic<uint64_t> epoch_{0};  // bumps on every adoption
  std::atomic<uint64_t> generation_{0};

  std::mutex wmu_;  // serializes frame writes
  std::mutex rmu_;  // one frame reader at a time
  std::mutex smu_;  // guards the op/resume state below
  // Serializes recv-progress callback invocations: the registering Recv
  // thread (stash drain) and a concurrent dispatcher (the Send thread's
  // opportunistic pump on the SAME channel — 2-member rings / Adasum
  // pairs) may both deliver progress, and the ring's incremental
  // reducer is not thread-safe.  Out-of-order progress values are fine
  // (the reducer ignores non-monotone callbacks); concurrency is not.
  std::mutex cbmu_;
  std::condition_variable cv_;

  // send side.  Sends are OPTIMISTIC: an op completes once its bytes
  // are streamed AND copied into the replay buffer — the ack round-trip
  // leaves the critical path (the old op-granularity ack wait cost one
  // scheduler round-trip per ring step).  Byte-cumulative ACKs prune
  // the replay tail asynchronously; a resume retransmits from it, so
  // the caller's buffer is never needed after Send returns.
  bool send_active_ = false;
  const uint8_t* s_buf_ = nullptr;
  size_t s_total_ = 0, s_off_ = 0;
  uint64_t s_op_start_abs_ = 0;  // send_bytes_ at the active op's start
  uint64_t send_bytes_ = 0;    // cumulative payload bytes streamed
  uint64_t send_frames_ = 0;   // next data frame seq
  uint64_t acked_bytes_ = 0;   // peer-confirmed delivered bytes
  std::vector<uint8_t> replay_;  // unacked tail [replay_base_, send_bytes_)
  size_t replay_off_ = 0;        // consumed prefix of replay_
  uint64_t replay_base_ = 0;     // cumulative offset of replay_[replay_off_]

  // recv side
  bool r_active_ = false;
  uint8_t* r_dst_ = nullptr;
  size_t r_total_ = 0, r_off_ = 0;
  const std::function<void(size_t)>* r_cb_ = nullptr;
  uint64_t recv_ops_ = 0;
  uint64_t recv_bytes_ = 0;   // cumulative fully-delivered payload bytes
  uint64_t recv_frames_ = 0;  // next expected data frame seq
  uint64_t ack_sent_bytes_ = 0;  // recv_bytes_ at the last ACK we sent
  // Delivered bytes awaiting their recv op (the sender streams
  // optimistically, so ring frames routinely land before the matching
  // Recv posts).  Vector + consumed-offset, drained with memcpy — a
  // byte-deque here cost ~500us per 256 KB op.
  std::vector<uint8_t> stash_;
  size_t stash_off_ = 0;

  // Buffered reader (touched only by the rmu_ holder): one recv
  // syscall pulls many small frames (headers, ACKs, control messages) —
  // per-frame recvs tripled the syscall count of a ring step.  Cleared
  // on adoption (epoch change): resume retransmits from the peer's
  // parsed position, so unparsed leftovers are stale duplicates.
  std::vector<uint8_t> rdbuf_;
  size_t rd_off_ = 0, rd_len_ = 0;
  uint64_t rd_epoch_ = 0;

  // ring-recovery agreement inbox (guarded by smu_; latest per kind)
  uint64_t report_epoch_ = 0;
  std::vector<uint8_t> report_;
  uint64_t verdict_epoch_ = 0;
  std::vector<uint8_t> verdict_;

  // recovery
  std::mutex recover_mu_;
  int pending_fd_ = -1;        // adopted socket awaiting a Reset() consumer
  uint64_t pending_gen_ = 0;
  uint64_t chaos_draws_ = 0;   // per-channel deterministic draw index
  bool dead_ = false;          // reconnect refused (blackholed pair)
  // (fd, burial epoch) of shutdown sockets awaiting safe close.
  std::vector<std::pair<int, uint64_t>> graveyard_;
};

class Network {
 public:
  // Establish the full mesh. coord_addr: "host:port" of rank 0's listener.
  // Returns nullptr + error status on failure.
  static std::unique_ptr<Network> Connect(int rank, int size,
                                          const std::string& coord_addr,
                                          Status* status);
  ~Network();

  Channel* chan(int r) { return channels_[r].get(); }
  Channel* coordinator_chan() { return channels_[0].get(); }
  int rank() const { return rank_; }
  int size() const { return size_; }
  const std::vector<std::string>& table() const { return table_; }

  // Same-host shared-memory channels (null when the peer is remote or
  // shm setup failed — callers fall back to the TCP socket).
  ShmChannel* shm_tx(int r) { return shm_tx_[r].get(); }  // me → r
  ShmChannel* shm_rx(int r) { return shm_rx_[r].get(); }  // r → me

  DuplexHelper& duplex_helper() { return duplex_helper_; }

  // --- ring recovery state (collectives.cc) -------------------------------
  // The member order flat ring collectives run in; renegotiation swaps a
  // permutation in so a dead link is never a ring adjacency again.
  std::vector<int> ring_order() const;
  void set_ring_order(const std::vector<int>& order);
  // Collective attempt bookkeeping: every resilient flat collective bumps
  // the epoch; ABORT frames carry the sender's epoch and poison only
  // attempts at or after it (a stale abort from a finished attempt is
  // inert).
  uint64_t BeginAttempt() { return ++attempt_epoch_; }
  uint64_t attempt_epoch() const { return attempt_epoch_.load(); }
  void NoteAbort(uint64_t epoch) {
    uint64_t prev = abort_seen_.load();
    while (epoch > prev && !abort_seen_.compare_exchange_weak(prev, epoch)) {
    }
    abort_cv_notify();
  }
  bool AbortPending() const {
    return abort_seen_.load() >= attempt_epoch_.load() &&
           attempt_epoch_.load() > 0;
  }
  void BroadcastAbort();
  // Dead links this process has proven (reconnect exhausted): fed to the
  // coordinator's ring re-formation.
  void NoteBadLink(int peer);
  std::vector<int> bad_links() const;
  int TakeLastBadPeer();
  // Tear down and re-establish every TCP link at a fresh generation
  // (post-renegotiation resync: discards the aborted attempt's in-flight
  // bytes on both sides of every pair).
  Status MeshReset(double deadline_s);
  uint64_t generation() const { return generation_.load(); }

  void abort_cv_notify() {}

 private:
  friend class Channel;
  Network(int rank, int size) : rank_(rank), size_(size) {
    peers_.resize(size);
    shm_tx_.resize(size);
    shm_rx_.resize(size);
  }
  void SetupShm(const std::vector<std::string>& table,
                const std::string& tag);
  void MakeChannels();
  void ListenerLoop();

  int rank_;
  int size_;
  std::vector<std::unique_ptr<Socket>> peers_;   // init-time only
  std::vector<std::unique_ptr<Channel>> channels_;
  std::vector<std::unique_ptr<ShmChannel>> shm_tx_;
  std::vector<std::unique_ptr<ShmChannel>> shm_rx_;
  std::vector<std::string> table_;  // advertised host:port per rank
  int listen_fd_ = -1;
  std::thread listener_;
  std::atomic<bool> listener_stop_{false};
  DuplexHelper duplex_helper_;

  mutable std::mutex ring_mu_;
  std::vector<int> ring_order_;
  std::atomic<uint64_t> attempt_epoch_{0};
  std::atomic<uint64_t> abort_seen_{0};
  std::atomic<uint64_t> generation_{0};
  mutable std::mutex bad_mu_;
  std::set<int> bad_links_;
  int last_bad_peer_ = -1;
};

}  // namespace hvdtpu
