// TCP transport: full-mesh peer connections bootstrapped through the
// coordinator (rank 0).
//
// Capability parity with the reference's Gloo context creation
// (gloo/gloo_context.cc:66-160: TCP devices + rendezvous KV): rank 0 binds
// the address the launcher exported (HVD_TPU_CONTROLLER_ADDR), workers dial
// in, the address table is broadcast, then every pair connects directly.
#pragma once

#include <memory>
#include <string>
#include <vector>

#include "common.h"
#include "shm.h"

namespace hvdtpu {

class Socket {
 public:
  explicit Socket(int fd) : fd_(fd) {}
  ~Socket();
  Socket(const Socket&) = delete;

  Status SendAll(const void* data, size_t n);
  Status RecvAll(void* data, size_t n);
  // Frame = u32 little-endian length + payload.
  Status SendFrame(const std::vector<uint8_t>& payload);
  Status RecvFrame(std::vector<uint8_t>& payload);
  int fd() const { return fd_; }

 private:
  int fd_;
};

class Network {
 public:
  // Establish the full mesh. coord_addr: "host:port" of rank 0's listener.
  // Returns nullptr + error status on failure.
  static std::unique_ptr<Network> Connect(int rank, int size,
                                          const std::string& coord_addr,
                                          Status* status);
  ~Network() = default;

  Socket* peer(int r) { return peers_[r].get(); }
  Socket* coordinator() { return peers_[0].get(); }
  int rank() const { return rank_; }
  int size() const { return size_; }

  // Same-host shared-memory channels (null when the peer is remote or
  // shm setup failed — callers fall back to the TCP socket).
  ShmChannel* shm_tx(int r) { return shm_tx_[r].get(); }  // me → r
  ShmChannel* shm_rx(int r) { return shm_rx_[r].get(); }  // r → me

 private:
  Network(int rank, int size) : rank_(rank), size_(size) {
    peers_.resize(size);
    shm_tx_.resize(size);
    shm_rx_.resize(size);
  }
  void SetupShm(const std::vector<std::string>& table,
                const std::string& tag);
  int rank_;
  int size_;
  std::vector<std::unique_ptr<Socket>> peers_;
  std::vector<std::unique_ptr<ShmChannel>> shm_tx_;
  std::vector<std::unique_ptr<ShmChannel>> shm_rx_;
};

}  // namespace hvdtpu
