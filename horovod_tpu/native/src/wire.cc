#include "wire.h"

namespace hvdtpu {

static void SerializeRequest(const Request& q, Writer& w) {
  w.u8(static_cast<uint8_t>(q.type));
  w.i32(q.rank);
  w.str(q.name);
  w.u8(static_cast<uint8_t>(q.dtype));
  w.vec_i64(q.shape);
  w.u8(static_cast<uint8_t>(q.op));
  w.i32(q.root_rank);
  w.f64(q.prescale);
  w.f64(q.postscale);
  w.vec_i64(q.splits);
  w.u8(q.device ? 1 : 0);
}

static Request DeserializeRequest(Reader& r) {
  Request q;
  q.type = static_cast<RequestType>(r.u8());
  q.rank = r.i32();
  q.name = r.str();
  q.dtype = static_cast<DataType>(r.u8());
  q.shape = r.vec_i64();
  q.op = static_cast<ReduceOp>(r.u8());
  q.root_rank = r.i32();
  q.prescale = r.f64();
  q.postscale = r.f64();
  q.splits = r.vec_i64();
  q.device = r.u8() != 0;
  return q;
}

void SerializeRequestList(const RequestList& rl, Writer& w) {
  w.u32(static_cast<uint32_t>(rl.requests.size()));
  for (const auto& q : rl.requests) SerializeRequest(q, w);
  w.vec_u64(rl.cache_hits);
  w.u8(rl.join ? 1 : 0);
  w.u8(rl.barrier ? 1 : 0);
  w.u8(rl.shutdown ? 1 : 0);
}

RequestList DeserializeRequestList(Reader& r) {
  RequestList rl;
  uint32_t n = r.u32();
  rl.requests.reserve(n);
  for (uint32_t i = 0; i < n; ++i) rl.requests.push_back(DeserializeRequest(r));
  rl.cache_hits = r.vec_u64();
  rl.join = r.u8() != 0;
  rl.barrier = r.u8() != 0;
  rl.shutdown = r.u8() != 0;
  return rl;
}

static void SerializeResponse(const Response& s, Writer& w) {
  w.u8(static_cast<uint8_t>(s.type));
  w.u32(static_cast<uint32_t>(s.names.size()));
  for (const auto& n : s.names) w.str(n);
  w.str(s.error);
  w.u8(static_cast<uint8_t>(s.dtype));
  w.u8(static_cast<uint8_t>(s.op));
  w.i32(s.root_rank);
  w.f64(s.prescale);
  w.f64(s.postscale);
  w.vec_i64(s.sizes);
  w.vec_u32(s.cache_bits);
  w.u8(s.device ? 1 : 0);
  w.u8(s.hierarchical ? 1 : 0);
}

static Response DeserializeResponse(Reader& r) {
  Response s;
  s.type = static_cast<RequestType>(r.u8());
  uint32_t n = r.u32();
  s.names.reserve(n);
  for (uint32_t i = 0; i < n; ++i) s.names.push_back(r.str());
  s.error = r.str();
  s.dtype = static_cast<DataType>(r.u8());
  s.op = static_cast<ReduceOp>(r.u8());
  s.root_rank = r.i32();
  s.prescale = r.f64();
  s.postscale = r.f64();
  s.sizes = r.vec_i64();
  s.cache_bits = r.vec_u32();
  s.device = r.u8() != 0;
  s.hierarchical = r.u8() != 0;
  return s;
}

void SerializeResponseList(const ResponseList& rl, Writer& w) {
  w.u32(static_cast<uint32_t>(rl.responses.size()));
  for (const auto& s : rl.responses) SerializeResponse(s, w);
  w.vec_u32(rl.valid_cache_bits);
  w.vec_u32(rl.resend_bits);
  w.u8(rl.shutdown ? 1 : 0);
  w.u8(rl.barrier_release ? 1 : 0);
  w.i32(rl.last_joined_rank);
  w.u8(rl.cache_on ? 1 : 0);
  w.i32(rl.wire_compression);
}

ResponseList DeserializeResponseList(Reader& r) {
  ResponseList rl;
  uint32_t n = r.u32();
  rl.responses.reserve(n);
  for (uint32_t i = 0; i < n; ++i)
    rl.responses.push_back(DeserializeResponse(r));
  rl.valid_cache_bits = r.vec_u32();
  rl.resend_bits = r.vec_u32();
  rl.shutdown = r.u8() != 0;
  rl.barrier_release = r.u8() != 0;
  rl.last_joined_rank = r.i32();
  rl.cache_on = r.u8() != 0;
  rl.wire_compression = r.i32();
  return rl;
}

}  // namespace hvdtpu
