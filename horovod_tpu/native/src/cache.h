// Response cache: skip re-announcing tensors negotiated in earlier
// iterations.
//
// Capability parity with the reference ResponseCache + CacheCoordinator
// (response_cache.h:45-169, controller.cc:181-237 fast path): training
// iterations repeat the same tensor set, so after the first negotiation a
// worker announces a cached tensor as one *bit* in its RequestList instead
// of a full Request (name + shape + params).  The coordinator intersects
// bits across ranks; fully-hit tensors are constructed from cached
// metadata.  Determinism note (the subtle part, reference
// controller.cc:368-378): bit assignment and eviction are decided by the
// coordinator alone and mirrored by workers at response time, so the
// name→bit tables never diverge.
#pragma once

#include <cstdint>
#include <list>
#include <map>
#include <string>
#include <vector>

#include "wire.h"

namespace hvdtpu {

struct CachedTensor {
  Request meta;                       // this rank's meta (worker cache) or
                                      // first-reporter meta (coordinator)
  std::map<int32_t, Request> by_rank; // coordinator only: per-rank metas
};

class ResponseCache {
 public:
  explicit ResponseCache(size_t capacity = 1024) : capacity_(capacity) {}
  size_t capacity() const { return capacity_; }
  bool enabled() const { return capacity_ > 0; }

  // Worker: bit for (name, meta) if cached and meta matches; -1 otherwise.
  int32_t Lookup(const Request& q) const {
    auto it = name_to_bit_.find(q.name);
    if (it == name_to_bit_.end()) return -1;
    const CachedTensor& ct = entries_.at(it->second);
    const Request& m = ct.meta;
    if (m.type != q.type || m.dtype != q.dtype || m.op != q.op ||
        m.root_rank != q.root_rank || m.prescale != q.prescale ||
        m.postscale != q.postscale || m.shape != q.shape ||
        m.splits != q.splits || m.device != q.device)
      return -1;
    return static_cast<int32_t>(it->second);
  }

  bool has_bit(uint32_t bit) const { return entries_.count(bit) != 0; }

  int32_t BitForName(const std::string& name) const {
    auto it = name_to_bit_.find(name);
    return it == name_to_bit_.end() ? -1 : static_cast<int32_t>(it->second);
  }

  std::string NameForBit(uint32_t bit) const {
    auto it = bit_to_name_.find(bit);
    return it == bit_to_name_.end() ? std::string() : it->second;
  }

  const CachedTensor& Get(uint32_t bit) const { return entries_.at(bit); }
  CachedTensor& GetMutable(uint32_t bit) { return entries_[bit]; }

  // Coordinator: choose a bit for a new tensor (existing bit, recycled
  // free bit, or a fresh one).  Eviction happens in InsertAt so the
  // coordinator and every worker run the *identical* eviction sequence —
  // the determinism requirement the reference calls out
  // (controller.cc:368-378).
  uint32_t Assign(const std::string& name) {
    int32_t existing = BitForName(name);
    if (existing >= 0) return static_cast<uint32_t>(existing);
    if (!free_bits_.empty()) {
      uint32_t bit = free_bits_.back();
      free_bits_.pop_back();
      return bit;
    }
    return next_bit_++;
  }

  // Install (or replace) the entry at a coordinator-chosen bit, evicting
  // the LRU entry when at capacity.  Called in response order on every
  // rank, so all caches evolve identically.
  void InsertAt(uint32_t bit, const std::string& name, const Request& meta) {
    if (entries_.count(bit)) {
      EraseBit(bit);
    } else if (entries_.size() >= capacity_ && !lru_.empty()) {
      uint32_t victim = lru_.back();
      EraseBit(victim);
      free_bits_.push_back(victim);
    }
    // A stale entry under the same name at a different bit is superseded.
    auto old = name_to_bit_.find(name);
    if (old != name_to_bit_.end() && old->second != bit) {
      uint32_t stale = old->second;
      EraseBit(stale);
      free_bits_.push_back(stale);
    }
    PlaceBit(bit, name);
    entries_[bit].meta = meta;
  }

  // LRU touch for the bits hit this round (broadcast by the coordinator so
  // every rank applies the identical ordering update).
  void Touch(const std::vector<uint32_t>& bits) {
    for (uint32_t b : bits) {
      auto it = lru_pos_.find(b);
      if (it == lru_pos_.end()) continue;
      lru_.erase(it->second);
      lru_.push_front(b);
      lru_pos_[b] = lru_.begin();
    }
  }

  void Invalidate(const std::string& name) {
    auto it = name_to_bit_.find(name);
    if (it != name_to_bit_.end()) {
      uint32_t bit = it->second;
      EraseBit(bit);
      free_bits_.push_back(bit);
    }
  }

  size_t size() const { return entries_.size(); }

 private:
  void PlaceBit(uint32_t bit, const std::string& name) {
    entries_[bit] = CachedTensor{};
    name_to_bit_[name] = bit;
    bit_to_name_[bit] = name;
    lru_.push_front(bit);
    lru_pos_[bit] = lru_.begin();
  }

  void EraseBit(uint32_t bit) {
    auto nit = bit_to_name_.find(bit);
    if (nit != bit_to_name_.end()) {
      name_to_bit_.erase(nit->second);
      bit_to_name_.erase(nit);
    }
    entries_.erase(bit);
    auto lit = lru_pos_.find(bit);
    if (lit != lru_pos_.end()) {
      lru_.erase(lit->second);
      lru_pos_.erase(lit);
    }
  }

  size_t capacity_;
  uint32_t next_bit_ = 0;
  std::vector<uint32_t> free_bits_;
  std::map<uint32_t, CachedTensor> entries_;
  std::map<std::string, uint32_t> name_to_bit_;
  std::map<uint32_t, std::string> bit_to_name_;
  std::list<uint32_t> lru_;
  std::map<uint32_t, std::list<uint32_t>::iterator> lru_pos_;
};

// Bit-vector helpers (cache_hits is a packed u64 vector on the wire).
inline void SetBit(std::vector<uint64_t>& v, uint32_t bit) {
  size_t word = bit / 64;
  if (v.size() <= word) v.resize(word + 1, 0);
  v[word] |= (1ull << (bit % 64));
}

inline bool TestBit(const std::vector<uint64_t>& v, uint32_t bit) {
  size_t word = bit / 64;
  return word < v.size() && (v[word] & (1ull << (bit % 64)));
}

}  // namespace hvdtpu
