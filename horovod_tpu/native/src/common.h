// Core types for the native eager-path runtime.
//
// TPU-native equivalent of the reference's horovod/common/common.h:113-281
// (Status, DataType, TensorTableEntry) — rebuilt, not ported: no framework
// Tensor/OpContext abstraction is needed because the eager path always
// operates on host buffers handed over from Python (numpy / dlpack), and
// device-resident collectives go through the compiled XLA path instead.
#pragma once

#include <atomic>
#include <cstdint>
#include <cstring>
#include <functional>
#include <memory>
#include <string>
#include <vector>

namespace hvdtpu {

enum class StatusType : uint8_t { OK = 0, UNKNOWN_ERROR, PRECONDITION_ERROR,
                                 ABORTED, INVALID_ARGUMENT, IN_PROGRESS,
                                 RETRYABLE };

struct Status {
  StatusType type = StatusType::OK;
  std::string reason;
  static Status OK() { return Status(); }
  static Status Error(const std::string& msg) {
    return Status{StatusType::UNKNOWN_ERROR, msg};
  }
  static Status PreconditionError(const std::string& msg) {
    return Status{StatusType::PRECONDITION_ERROR, msg};
  }
  static Status InvalidArgument(const std::string& msg) {
    return Status{StatusType::INVALID_ARGUMENT, msg};
  }
  static Status Aborted(const std::string& msg) {
    return Status{StatusType::ABORTED, msg};
  }
  // A transport failure the ring-level recovery may retry (reconnect
  // exhausted on one link, or a peer's abort of the attempt): never
  // returned to callers — collectives.cc either renegotiates the ring
  // or converts it to a terminal error.
  static Status Retry(const std::string& msg) {
    return Status{StatusType::RETRYABLE, msg};
  }
  bool ok() const { return type == StatusType::OK; }
  bool retryable() const { return type == StatusType::RETRYABLE; }
};

// Matches the Python/dtype codes in native/controller.py. Subset of the
// reference's 10-dtype enum (message.h:30-41) + bfloat16 (TPU-native).
enum class DataType : uint8_t {
  UINT8 = 0, INT8 = 1, INT32 = 2, INT64 = 3,
  FLOAT16 = 4, FLOAT32 = 5, FLOAT64 = 6, BOOL = 7, BFLOAT16 = 8,
};

inline size_t DataTypeSize(DataType t) {
  switch (t) {
    case DataType::UINT8: case DataType::INT8: case DataType::BOOL: return 1;
    case DataType::FLOAT16: case DataType::BFLOAT16: return 2;
    case DataType::INT32: case DataType::FLOAT32: return 4;
    case DataType::INT64: case DataType::FLOAT64: return 8;
  }
  return 1;
}

enum class ReduceOp : uint8_t { AVERAGE = 0, SUM = 1, ADASUM = 2, MIN = 3,
                                MAX = 4, PRODUCT = 5 };

enum class RequestType : uint8_t { ALLREDUCE = 0, ALLGATHER = 1,
                                   BROADCAST = 2, ALLTOALL = 3, JOIN = 4,
                                   BARRIER = 5 };

// A pending collective owned by this rank (reference TensorTableEntry,
// common.h:223-281). Input/output are host buffers kept alive by Python
// until the callback fires.
struct TensorEntry {
  std::string name;
  RequestType type = RequestType::ALLREDUCE;
  DataType dtype = DataType::FLOAT32;
  std::vector<int64_t> shape;
  ReduceOp op = ReduceOp::SUM;
  int32_t root_rank = 0;
  double prescale = 1.0;
  double postscale = 1.0;
  const void* input = nullptr;
  void* output = nullptr;          // for allreduce/broadcast: same size as in
  // Device-resident entry: payload lives in accelerator HBM and is executed
  // by the registered device executor (the TPU analog of the reference's
  // device-buffer fusion inside the negotiated runtime,
  // nccl_operations.cc:126-184); input/output stay null.
  bool device = false;
  std::vector<int64_t> splits;     // alltoall send splits (first-dim rows)
  // Variable-size outputs (allgather/alltoall): runtime allocates and Python
  // copies out; holds the buffer until handle collected.
  std::shared_ptr<std::vector<uint8_t>> var_output;
  std::vector<int64_t> out_first_dims;  // per-rank first dims (allgather) or
                                        // received splits (alltoall)
  std::function<void(const Status&)> callback;
  int64_t num_elements() const {
    int64_t n = 1;
    for (auto d : shape) n *= d;
    return n;
  }
  size_t byte_size() const { return num_elements() * DataTypeSize(dtype); }
};

}  // namespace hvdtpu
