#include "timeline.h"

namespace hvdtpu {

void Timeline::Start(const std::string& filename, int rank, int size) {
  if (active_) return;
  file_ = fopen(filename.c_str(), "w");
  if (!file_) return;
  rank_ = rank;
  t0_ = std::chrono::steady_clock::now();
  fprintf(file_, "[\n");
  first_event_ = true;
  // One labeled process row per rank (pid = rank), sorted by rank: the
  // writer thread has not started yet, so writing directly is safe.
  for (int r = 0; r < size; ++r) {
    fprintf(file_,
            "%s{\"name\":\"process_name\",\"ph\":\"M\",\"pid\":%d,"
            "\"tid\":0,\"args\":{\"name\":\"rank %d\"}},\n"
            "{\"name\":\"process_sort_index\",\"ph\":\"M\",\"pid\":%d,"
            "\"tid\":0,\"args\":{\"sort_index\":%d}}",
            first_event_ ? "" : ",\n", r, r, r, r);
    first_event_ = false;
  }
  stop_requested_ = false;
  active_ = true;
  writer_ = std::thread([this] { WriterLoop(); });
}

void Timeline::Stop() {
  if (!active_) return;
  {
    std::lock_guard<std::mutex> lk(mu_);
    stop_requested_ = true;
  }
  cv_.notify_all();
  if (writer_.joinable()) writer_.join();
  fprintf(file_, "\n]\n");
  fclose(file_);
  file_ = nullptr;
  active_ = false;
}

void Timeline::Record(const std::string& name, const char* ph,
                      const std::string& category, const std::string& args,
                      int pid) {
  if (!active_) return;
  int64_t ts = std::chrono::duration_cast<std::chrono::microseconds>(
                   std::chrono::steady_clock::now() - t0_).count();
  {
    std::lock_guard<std::mutex> lk(mu_);
    queue_.push(Event{name, category, ph[0], ts, args,
                      pid < 0 ? rank_ : pid});
  }
  cv_.notify_one();
}

void Timeline::MarkCycle() { Record("CYCLE", "i", "cycle"); }

void Timeline::WriterLoop() {
  std::unique_lock<std::mutex> lk(mu_);
  while (true) {
    cv_.wait(lk, [this] { return !queue_.empty() || stop_requested_; });
    while (!queue_.empty()) {
      Event ev = queue_.front();
      queue_.pop();
      lk.unlock();
      fprintf(file_, "%s{\"name\":\"%s\",\"cat\":\"%s\",\"ph\":\"%c\","
              "\"ts\":%lld,\"pid\":%d,\"tid\":0%s",
              first_event_ ? "" : ",\n", ev.name.c_str(), ev.cat.c_str(),
              ev.ph, static_cast<long long>(ev.ts_us), ev.pid,
              ev.ph == 'i' ? ",\"s\":\"g\"" : "");
      if (!ev.args.empty()) fprintf(file_, ",\"args\":%s", ev.args.c_str());
      fprintf(file_, "}");
      first_event_ = false;
      lk.lock();
    }
    if (stop_requested_ && queue_.empty()) break;
  }
}

}  // namespace hvdtpu
