// Wire messages + serialization for the controller protocol.
//
// Capability parity with the reference's Request/Response message layer
// (message.h:50-251, wire/message.fbs) — rebuilt with a hand-rolled
// length-prefixed binary format instead of FlatBuffers (no third-party
// dependency; messages are small and on the control plane only).
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "common.h"

namespace hvdtpu {

// One tensor announcement from a rank (reference Request, message.h:56-139).
struct Request {
  RequestType type = RequestType::ALLREDUCE;
  int32_t rank = 0;
  std::string name;
  DataType dtype = DataType::FLOAT32;
  std::vector<int64_t> shape;
  ReduceOp op = ReduceOp::SUM;
  int32_t root_rank = 0;
  double prescale = 1.0;
  double postscale = 1.0;
  std::vector<int64_t> splits;  // alltoall: rows destined per rank
  bool device = false;          // payload is accelerator-resident (HBM)
};

// What every worker sends each cycle.
struct RequestList {
  std::vector<Request> requests;
  std::vector<uint64_t> cache_hits;  // cache-bit vector (response cache)
  bool join = false;                 // this rank called join()
  bool barrier = false;              // this rank waits at a barrier
  bool shutdown = false;             // this rank is shutting down
};

// Coordinator's answer for one (possibly fused) collective
// (reference Response, message.h:159-210).
struct Response {
  RequestType type = RequestType::ALLREDUCE;
  std::vector<std::string> names;        // fused tensor names, in order
  std::string error;                     // non-empty → deliver error
  DataType dtype = DataType::FLOAT32;
  ReduceOp op = ReduceOp::SUM;
  int32_t root_rank = 0;
  double prescale = 1.0;
  double postscale = 1.0;
  // allgather: first dims per rank, flattened [name0_rank0.. name0_rankN,
  // name1_rank0 ...]; alltoall: recv splits matrix row-major [src][dst].
  std::vector<int64_t> sizes;
  // Cache slot per name (aligned with ``names``; UINT32_MAX = uncached).
  std::vector<uint32_t> cache_bits;
  // Execute through the registered device executor on HBM buffers instead
  // of the host TCP data plane (all fused entries are device-resident).
  bool device = false;
  // Algorithm choice stamped by the COORDINATOR (allreduce: hierarchical
  // vs flat ring; allgather: hierarchical vs flat allgatherv): the tuner
  // flips these per sample on rank 0 (reference's categorical autotune
  // parameters, parameter_manager.h:91-93), and per-response stamping is
  // what keeps every rank executing the same schedule mid-flip.
  bool hierarchical = false;
};

struct ResponseList {
  std::vector<Response> responses;
  std::vector<uint32_t> valid_cache_bits;  // intersection across ranks
  // Bits a rank announced that the coordinator no longer holds: the rank
  // must invalidate its entry and resend a full request (self-healing on
  // any cache divergence).
  std::vector<uint32_t> resend_bits;
  bool shutdown = false;                   // all ranks done → stop loop
  bool barrier_release = false;
  int32_t last_joined_rank = -1;           // all ranks joined → returned
  // Coordinator's current response-cache toggle (autotuned categorical,
  // reference parameter_manager.h:93): workers stop announcing bits when
  // the coordinator turned caching off; outstanding bits from the
  // transition window still resolve (or self-heal via resend_bits).
  bool cache_on = true;
  // Coordinator's current eager wire-compression choice (quantized
  // collective engine; 0 none, 1 bf16, 2 int8, 3 int4, 4 fp16).  Stamped per
  // round like cache_on: workers adopt it BEFORE executing the round's
  // responses, so the device-plane executor on every rank builds the
  // same staged-buffer program even when the tuner flips mid-run.
  int32_t wire_compression = 0;
};

// --- serialization ---------------------------------------------------------

class Writer {
 public:
  std::vector<uint8_t> buf;
  void u8(uint8_t v) { buf.push_back(v); }
  void u32(uint32_t v) { append(&v, 4); }
  void i32(int32_t v) { append(&v, 4); }
  void i64(int64_t v) { append(&v, 8); }
  void u64(uint64_t v) { append(&v, 8); }
  void f64(double v) { append(&v, 8); }
  void str(const std::string& s) {
    u32(static_cast<uint32_t>(s.size()));
    append(s.data(), s.size());
  }
  void vec_i64(const std::vector<int64_t>& v) {
    u32(static_cast<uint32_t>(v.size()));
    append(v.data(), v.size() * 8);
  }
  void vec_u64(const std::vector<uint64_t>& v) {
    u32(static_cast<uint32_t>(v.size()));
    append(v.data(), v.size() * 8);
  }
  void vec_u32(const std::vector<uint32_t>& v) {
    u32(static_cast<uint32_t>(v.size()));
    append(v.data(), v.size() * 4);
  }
 private:
  void append(const void* p, size_t n) {
    const uint8_t* b = static_cast<const uint8_t*>(p);
    buf.insert(buf.end(), b, b + n);
  }
};

class Reader {
 public:
  Reader(const uint8_t* p, size_t n) : p_(p), end_(p + n) {}
  uint8_t u8() { return *take(1); }
  uint32_t u32() { uint32_t v; memcpy(&v, take(4), 4); return v; }
  int32_t i32() { int32_t v; memcpy(&v, take(4), 4); return v; }
  int64_t i64() { int64_t v; memcpy(&v, take(8), 8); return v; }
  uint64_t u64() { uint64_t v; memcpy(&v, take(8), 8); return v; }
  double f64() { double v; memcpy(&v, take(8), 8); return v; }
  std::string str() {
    uint32_t n = u32();
    const uint8_t* p = take(n);
    return std::string(reinterpret_cast<const char*>(p), n);
  }
  std::vector<int64_t> vec_i64() {
    uint32_t n = u32();
    std::vector<int64_t> v(n);
    memcpy(v.data(), take(n * 8), n * 8);
    return v;
  }
  std::vector<uint64_t> vec_u64() {
    uint32_t n = u32();
    std::vector<uint64_t> v(n);
    memcpy(v.data(), take(n * 8), n * 8);
    return v;
  }
  std::vector<uint32_t> vec_u32() {
    uint32_t n = u32();
    std::vector<uint32_t> v(n);
    memcpy(v.data(), take(n * 4), n * 4);
    return v;
  }
  bool overflowed() const { return overflow_; }
 private:
  const uint8_t* take(size_t n) {
    if (p_ + n > end_) { overflow_ = true; static uint8_t z[8] = {0}; return z; }
    const uint8_t* r = p_;
    p_ += n;
    return r;
  }
  const uint8_t* p_;
  const uint8_t* end_;
  bool overflow_ = false;
};

void SerializeRequestList(const RequestList& rl, Writer& w);
RequestList DeserializeRequestList(Reader& r);
void SerializeResponseList(const ResponseList& rl, Writer& w);
ResponseList DeserializeResponseList(Reader& r);

}  // namespace hvdtpu
