"""Bindings to the native (C++) eager-path runtime.

The reference's core runtime is C++: background thread, TensorQueue,
controller protocol, fusion buffer, response cache (horovod/common/*.cc).
Our native runtime lives in ``horovod_tpu/native/src`` and is loaded via
ctypes (the reference uses ctypes for its basics layer too,
common/basics.py:22-75).  Until the shared library is built/attached this
module exposes ``attach()`` returning None so the pure-JAX paths keep
working.
"""

from __future__ import annotations

from typing import Optional


def attach(rank: Optional[int] = None, size: Optional[int] = None,
           coord_addr: Optional[str] = None) -> Optional[object]:
    """Attach the native controller if the shared library is available."""
    try:
        from . import controller
        if coord_addr is not None:
            return controller.NativeController(rank or 0, size or 1,
                                               coord_addr)
        return controller.NativeController.from_env()
    except Exception:
        from ..utils import logging as log
        log.debug("native runtime unavailable; eager path uses JAX regime")
        return None
