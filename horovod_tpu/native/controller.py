"""ctypes bindings to the native runtime (libhvdtpu_core.so).

The analog of the reference's ``HorovodBasics`` ctypes layer
(common/basics.py:22-75) plus the per-op enqueue wrappers the torch bridge
generates (torch/mpi_ops_v2.cc).  All eager ops are synchronous at this
level; async handles are layered above in ops/collective.py.
"""

from __future__ import annotations

import contextlib
import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

from ..core import config as _config

_DTYPE_CODES = {
    np.dtype(np.uint8): 0,
    np.dtype(np.int8): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.float16): 4,
    np.dtype(np.float32): 5,
    np.dtype(np.float64): 6,
    np.dtype(np.bool_): 7,
}
try:  # bfloat16 — the TPU-native wire format (C++ kernels: code 8)
    import ml_dtypes as _ml_dtypes
    _DTYPE_CODES[np.dtype(_ml_dtypes.bfloat16)] = 8
except ImportError:
    pass


_CODE_TO_DTYPE = {v: k for k, v in _DTYPE_CODES.items()}

# Device-executor callback signature (runtime.h DeviceExecutorFn): executes
# one negotiated, possibly-fused device-resident Response on the background
# thread, in coordinator response order.  Two-phase (runtime.h
# DeviceExecPhase): PREPARE(0) stages inputs + runs every locally-
# detectable check, EXECUTE(1) dispatches the SPMD collective, ABORT(2)
# drops staged state when a peer's prepare failed.
_PHASE_PREPARE, _PHASE_EXECUTE, _PHASE_ABORT = 0, 1, 2
_DEVICE_EXEC_FN = ctypes.CFUNCTYPE(
    ctypes.c_int,                        # return: 0 ok
    ctypes.c_int,                        # phase (DeviceExecPhase)
    ctypes.c_int, ctypes.c_int,          # request_type, n
    ctypes.POINTER(ctypes.c_char_p),     # names
    ctypes.POINTER(ctypes.c_int64),      # sizes (element counts)
    ctypes.c_int, ctypes.c_int,          # dtype code, reduce op
    ctypes.c_int,                        # root_rank
    ctypes.c_double, ctypes.c_double,    # prescale, postscale
    ctypes.POINTER(ctypes.c_char), ctypes.c_int)  # err buf, err cap


def _lib_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "libhvdtpu_core.so")


def _ensure_built() -> str:
    path = _lib_path()
    if not os.path.exists(path):
        src = os.path.join(os.path.dirname(path), "src")
        subprocess.run(["make", "-C", src], check=True,
                       capture_output=True)
    return path


_lib = None


def load_library():
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(_ensure_built())
    lib.hvd_native_init.restype = ctypes.c_int
    lib.hvd_native_init.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_char_p,
        ctypes.c_int64]
    lib.hvd_native_rank.restype = ctypes.c_int
    lib.hvd_native_size.restype = ctypes.c_int
    lib.hvd_native_initialized.restype = ctypes.c_int
    for fn in ("hvd_native_allreduce", "hvd_native_allgather",
               "hvd_native_broadcast", "hvd_native_alltoall"):
        getattr(lib, fn).restype = ctypes.c_int64
    lib.hvd_native_allreduce.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int,
        ctypes.c_double, ctypes.c_double]
    lib.hvd_native_allgather.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
    lib.hvd_native_broadcast.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int]
    lib.hvd_native_alltoall.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
    lib.hvd_native_poll.restype = ctypes.c_int
    lib.hvd_native_poll.argtypes = [ctypes.c_int64]
    lib.hvd_native_wait.restype = ctypes.c_int
    lib.hvd_native_wait.argtypes = [ctypes.c_int64]
    lib.hvd_native_result_bytes.restype = ctypes.c_int64
    lib.hvd_native_result_bytes.argtypes = [ctypes.c_int64]
    lib.hvd_native_result_dims.restype = ctypes.c_int
    lib.hvd_native_result_dims.argtypes = [
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
    lib.hvd_native_result_copy.restype = ctypes.c_int
    lib.hvd_native_result_copy.argtypes = [
        ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64]
    lib.hvd_native_release.argtypes = [ctypes.c_int64]
    lib.hvd_native_join.restype = ctypes.c_int
    lib.hvd_native_barrier.restype = ctypes.c_int
    lib.hvd_native_last_error.restype = ctypes.c_char_p
    lib.hvd_native_stalled_json.restype = ctypes.c_int
    lib.hvd_native_stalled_json.argtypes = [
        ctypes.POINTER(ctypes.c_char), ctypes.c_int]
    lib.hvd_native_start_timeline.argtypes = [ctypes.c_char_p]
    lib.hvd_native_set_params.argtypes = [ctypes.c_int64, ctypes.c_double]
    lib.hvd_native_set_tuned_toggles.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.hvd_native_set_schedule_table.argtypes = [
        ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
    lib.hvd_native_set_cache_enabled.argtypes = [ctypes.c_int]
    lib.hvd_native_set_wire_compression.argtypes = [ctypes.c_int]
    lib.hvd_native_wire_compression.restype = ctypes.c_int
    lib.hvd_native_set_topology.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.hvd_native_last_allgather_schedule.restype = ctypes.c_int
    lib.hvd_native_last_allreduce_schedule.restype = ctypes.c_int
    lib.hvd_native_last_allreduce_fanout.restype = ctypes.c_int
    lib.hvd_native_last_bcast_schedule.restype = ctypes.c_int
    lib.hvd_native_adasum_scratch_peak.restype = ctypes.c_int64
    lib.hvd_native_last_fused_names.restype = ctypes.c_int64
    lib.hvd_native_counters.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double)]
    lib.hvd_native_net_counters.restype = ctypes.c_int
    lib.hvd_native_net_counters.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
    lib.hvd_native_allreduce_device.restype = ctypes.c_int64
    lib.hvd_native_allreduce_device.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int, ctypes.c_int, ctypes.c_double, ctypes.c_double]
    lib.hvd_native_broadcast_device.restype = ctypes.c_int64
    lib.hvd_native_broadcast_device.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int, ctypes.c_int]
    lib.hvd_native_allgather_device.restype = ctypes.c_int64
    lib.hvd_native_allgather_device.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int]
    lib.hvd_native_alltoall_device.restype = ctypes.c_int64
    lib.hvd_native_alltoall_device.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int, ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
    lib.hvd_native_set_device_executor.argtypes = [_DEVICE_EXEC_FN]
    _lib = lib
    return lib


def _dtype_code(arr: np.ndarray) -> int:
    code = _DTYPE_CODES.get(arr.dtype)
    if code is None:
        raise TypeError(f"unsupported dtype {arr.dtype} for native path")
    return code


def _shape_arg(arr: np.ndarray):
    shape = (ctypes.c_int64 * max(arr.ndim, 1))(*(arr.shape or (1,)))
    return arr.ndim, shape


class NativeError(RuntimeError):
    pass


class NativeController:
    """Synchronous eager collectives through the native runtime."""

    def __init__(self, rank: int, size: int, coord_addr: str):
        self._lib = load_library()
        cfg = _config.Config.from_env()
        # Timeline/merge anchor: the native runtime's steady-clock t0 is
        # set inside hvd_native_init (Timeline::Start); bracketing the
        # call and taking the midpoint bounds the anchor to half the
        # init time (ms-scale — triage precision, not profiling).
        import time as _time
        _t0 = _time.time()
        rc = self._lib.hvd_native_init(
            rank, size, coord_addr.encode(),
            cfg.fusion_threshold_bytes, cfg.cycle_time_ms,
            1e9 if cfg.stall_check_disable else cfg.stall_warning_time_seconds,
            cfg.stall_shutdown_time_seconds,
            cfg.timeline_filename.encode(), cfg.cache_capacity)
        if rc != 0:
            raise NativeError(self._last_error())
        from ..debug import flight as _flight
        _flight.set_identity(rank=rank, world=size)
        _flight.set_meta("native_init_wall", (_t0 + _time.time()) / 2.0)
        _flight.record("native.attach", None, rank=rank, size=size,
                       coord_addr=coord_addr)
        # Metric children cached on the instance: _wait runs per eager
        # op, the registry lookup must not.
        from ..metrics.registry import registry as _metrics_registry
        _mreg = _metrics_registry()
        self._m_ops = _mreg.counter(
            "hvd_native_ops_total",
            "Completed native-runtime eager operations")
        self._m_fused = _mreg.gauge(
            "hvd_native_last_fused_names",
            "Names in the most recent fused allreduce Response")
        # Node topology for hierarchical collectives (from the launcher's
        # env contract; reference HOROVOD_HIERARCHICAL_ALLREDUCE knob).
        local_size = int(_config.get_env("LOCAL_SIZE", "1") or 1)
        self._lib.hvd_native_set_topology(
            local_size, 1 if cfg.hierarchical_allreduce else 0,
            1 if cfg.hierarchical_allgather else 0)
        # Seed the eager wire format from HVD_TPU_COMPRESSION.  Only the
        # coordinator's call takes effect (Runtime::SetWireCompression is
        # a no-op elsewhere); every rank adopts the choice from the
        # response stream, so a mixed-env fleet stays consistent.
        from ..ops.compression import WIRE_CODES
        self._lib.hvd_native_set_wire_compression(
            WIRE_CODES.get(cfg.compression, 0))
        self._counters = {}
        # Negotiated device plane: HBM-resident tensors enqueued with
        # *_device keep their payload on the accelerator; the registered
        # executor runs each fused Response through the jitted device plane
        # (reference: device-buffer fusion inside the negotiated runtime,
        # nccl_operations.cc:126-184).
        self._device_lock = threading.Lock()
        self._device_inputs = {}   # name -> jax.Array awaiting execution
        self._device_results = {}  # name -> executed result
        self._device_cb = None     # keep the CFUNCTYPE alive (GC hazard)
        self._device_exec_impl = None
        self._device_plan = None   # staged by PREPARE, consumed by EXECUTE
        # Register the executor NOW, not lazily on first device op: every
        # rank of the communicator must be able to participate in a device
        # Response (joined ranks contribute zero proxies) even if it never
        # submitted a device tensor itself — a rank without an executor
        # would strand its peers inside the SPMD collective.  Building the
        # impl touches no jax state; the spanning check happens at
        # enqueue/execution time.
        try:
            from ..ops.eager import _negotiated_executor
            self.set_device_executor(_negotiated_executor(self))
        except ImportError:
            pass
        # Autotune (reference ParameterManager): rank 0 owns fusion and
        # algorithm decisions, so the tuner runs there; numeric params
        # apply via SetParams, categorical toggles via SetTunedToggles
        # (the coordinator stamps each Response so every rank executes
        # the same schedule mid-flip).
        self._autotune = None
        self._autotune_pause = False
        # Per-payload dispatch table (ops/dispatch.py): installed by the
        # init()-time topology probe; once present, the tuner's two
        # hierarchical dims become bounded crossover shifts over it and
        # the coordinator stamps every response from the table.
        self._dispatch_table = None
        self._local_size = local_size
        self._autotune_kwargs = None
        if cfg.autotune and rank == 0:
            from ..autotune import ParameterManager
            self._autotune_kwargs = dict(
                apply_fn=self._apply_tuned,
                log_file=cfg.autotune_log or None,
                max_samples=cfg.autotune_bayes_opt_max_samples,
                warmup_samples=cfg.autotune_warmup_samples,
                steps_per_sample=cfg.autotune_steps_per_sample,
                gp_noise=cfg.autotune_gaussian_process_noise,
                initial_toggles=(cfg.hierarchical_allreduce,
                                 cfg.hierarchical_allgather,
                                 cfg.cache_capacity > 0),
                # Per-toggle: hierarchical variants are dead with a
                # single node; the cache cannot be enabled at capacity 0.
                tune_toggles=(local_size > 1, local_size > 1,
                              cfg.cache_capacity > 0),
                initial_compression=cfg.compression,
                # The wire-format categorical only changes anything on
                # the negotiated device plane: skip it when that plane
                # is switched off (same can't-take-effect gating as the
                # hierarchical/cache toggles), and respect — never
                # explore — an explicitly-pinned HVD_TPU_COMPRESSION.
                tune_compression=(
                    _config.get_env(_config.COMPRESSION) is None and
                    os.environ.get("HVD_TPU_EAGER_DEVICE_PLANE",
                                   "1") != "0"),
                initial_overlap=(cfg.overlap_bucket_bytes if cfg.overlap
                                 else 0),
                # The bucket-size dimension only takes effect for jobs
                # that opted into overlap (HVD_TPU_OVERLAP or an
                # optimizer overlap= argument reading the session
                # value); an explicit HVD_TPU_OVERLAP_BUCKET_BYTES pins
                # it — the operator chose, the tuner must not explore.
                tune_overlap=(
                    cfg.overlap and
                    _config.get_env(_config.OVERLAP_BUCKET_BYTES)
                    is None),
                # Multi-rank jobs explore bucket SIZES only: the tuned
                # session value is rank-0-local (not coordinated like
                # the response-stream wire stamp), and an on<->off flip
                # changes the eager collective NAME sequence (barrier
                # auto-names vs the queue's leaf-indexed names) —
                # rank 0 flipping alone would desync negotiation.
                # Size flips are name-invariant, hence safe; a
                # single-rank job may try off too.
                overlap_choices=(None if size == 1 else tuple(
                    c for c in ParameterManager.OVERLAP_CHOICES if c)))
            # Built NOW (worker scripts assert the tuner engaged right
            # after init); a probing job's bootstrap rebuilds it once in
            # shift mode before any window is scored (probe traffic is
            # excluded via autotune_paused, so no warmup is lost).
            self._autotune = ParameterManager(**self._autotune_kwargs)
            # Register with the closed loop (autotune.set_active_manager)
            # so the drift plane can open re-tune episodes and the
            # tuning memory can warm-start / write back.
            from .. import autotune as _autotune_mod
            _autotune_mod.set_active_manager(self._autotune)

    @contextlib.contextmanager
    def autotune_paused(self):
        """Suppress autotune ticks (and the lazy tuner build) for ops
        inside the scope — the dispatch probe's traffic is pinned-arm
        measurement, not a workload the tuner should score or warm up
        on."""
        prev = self._autotune_pause
        self._autotune_pause = True
        try:
            yield
        finally:
            self._autotune_pause = prev

    def adopt_dispatch_table(self, table) -> None:
        """Install a probe-built dispatch table (ops/dispatch.py
        DispatchTable): native coordinator tables on rank 0, and rebase
        the autotuner's two hierarchical booleans into bounded crossover
        SHIFTS over this table (the probe result is the warm start; the
        tuner may move each kind's crossover by one bucket per unit of
        shift, never flip the whole range blind)."""
        self._dispatch_table = table
        if self.rank() != 0:
            return
        from ..ops import dispatch as _dispatch
        for kind in _dispatch.KINDS:
            bounds, choices = table.to_native(kind)
            self.set_schedule_table(kind, bounds, choices)
        if self._autotune_kwargs is None:
            return
        if self._autotune is not None and (
                self._autotune.frozen or self._autotune._samples > 0):
            # A live mid-run tuner (elastic re-probe): keep its state —
            # its proposals now apply through the dispatch branch of
            # _apply_tuned, bounded by the fresh table.
            return
        # A kind the operator pinned (explicit HVD_TPU_HIERARCHICAL_*)
        # stays pinned at shift 0: the tuner must refine measurements,
        # not overrule an explicit operator decision.
        tunable = tuple(
            _config.get_env(knob) is None and self._local_size > 1
            for knob in (_config.HIERARCHICAL_ALLREDUCE,
                         _config.HIERARCHICAL_ALLGATHER))
        old_tune = self._autotune_kwargs.get("tune_toggles", True)
        cache_tunable = old_tune[2] if isinstance(old_tune, (tuple, list)) \
            else bool(old_tune)
        self._autotune_kwargs.update(
            dispatch_shifts=True,
            initial_toggles=(0, 0,
                             self._autotune_kwargs["initial_toggles"][2]),
            tune_toggles=tunable + (cache_tunable,))
        from .. import autotune as _autotune_mod
        from ..autotune import ParameterManager
        self._autotune = ParameterManager(**self._autotune_kwargs)
        _autotune_mod.set_active_manager(self._autotune)

    def _apply_tuned(self, fusion, cycle, hier_allreduce, hier_allgather,
                     cache_enabled, compression="none", overlap=None):
        from ..ops.compression import WIRE_CODES
        self._lib.hvd_native_set_params(int(fusion), float(cycle))
        if self._dispatch_table is not None:
            # Dispatch mode: the two hierarchical dims are crossover
            # SHIFTS over the probe-seeded table — applied as fresh
            # per-bucket tables so the cache flip below can never
            # clobber the dispatch plane the way the whole-range
            # set_tuned_toggles reinstall would.
            from ..ops import dispatch as _dispatch
            shifted = self._dispatch_table.shifted(
                {"allreduce": int(hier_allreduce),
                 "allgather": int(hier_allgather)})
            for kind in _dispatch.KINDS:
                bounds, choices = shifted.to_native(kind)
                self.set_schedule_table(kind, bounds, choices)
            _dispatch.set_active(shifted, reason="autotune")
            self._lib.hvd_native_set_cache_enabled(
                1 if cache_enabled else 0)
        else:
            self._lib.hvd_native_set_tuned_toggles(
                1 if hier_allreduce else 0, 1 if hier_allgather else 0,
                1 if cache_enabled else 0)
        # Coordinator-stamped per round (ResponseList::wire_compression):
        # workers adopt the flip at the round boundary, never mid-batch.
        self._lib.hvd_native_set_wire_compression(
            WIRE_CODES.get(compression, 0))
        if overlap is not None:
            # Overlap bucket size (0 = bucketing off): applied to the
            # overlap engine's session value — reaches EAGER dispatch at
            # the next step (value-invariant, so mid-run flips are
            # safe).  Compiled traces deliberately ignore it (a rank-
            # local tuned value must not shape a cross-rank SPMD
            # program; they read the env knobs), so this dimension's
            # measured effect — like fusion/cycle — is native-plane.
            from ..ops import overlap as _overlap_mod
            _overlap_mod.set_session_bucket_bytes(int(overlap))

    def wire_compression(self) -> str:
        """The response-stream-adopted eager wire format ("none" until
        the first round after the coordinator stamped one)."""
        from ..ops.compression import WIRE_NAMES
        return WIRE_NAMES.get(
            int(self._lib.hvd_native_wire_compression()), "none")

    @classmethod
    def from_env(cls) -> "NativeController":
        addr = _config.get_env("CONTROLLER_ADDR")
        if not addr:
            raise NativeError("HVD_TPU_CONTROLLER_ADDR not set")
        rank = int(_config.get_env("CONTROLLER_RANK",
                                   _config.get_env("RANK", "0")))
        size = int(_config.get_env("CONTROLLER_SIZE",
                                   _config.get_env("SIZE", "1")))
        return cls(rank, size, addr)

    def _last_error(self) -> str:
        return (self._lib.hvd_native_last_error() or b"").decode()

    def _auto_name(self, kind: str, name: Optional[str]) -> bytes:
        if name is not None:
            return name.encode()
        # Deterministic auto names: call order must match across ranks, the
        # same contract as the reference's handle-indexed auto names.
        n = self._counters.get(kind, 0)
        self._counters[kind] = n + 1
        return f"{kind}.noname.{n}".encode()

    def _wait(self, handle: int):
        if handle < 0:
            raise NativeError(self._last_error())
        if self._lib.hvd_native_wait(handle) != 0:
            err = self._last_error()
            self._lib.hvd_native_release(handle)
            from ..debug import flight as _flight
            _flight.record("collective.error", None, error=err[:256])
            raise NativeError(err)
        self._m_ops.inc()
        self._m_fused.set(self._lib.hvd_native_last_fused_names())
        self._autotune_tick()

    def _autotune_tick(self):
        if self._autotune is None or self._autotune_pause:
            return
        nbytes = ctypes.c_int64()
        secs = ctypes.c_double()
        self._lib.hvd_native_counters(ctypes.byref(nbytes),
                                      ctypes.byref(secs))
        self._autotune.record_bytes(nbytes.value)

    # -- negotiated device plane ------------------------------------------

    def set_device_executor(self, impl) -> None:
        """Register the device-plane executor.  ``impl(request_type, names,
        sizes, np_dtype, op, root_rank, prescale, postscale, inputs)`` runs
        one negotiated Response on device and returns {name: result} for the
        locally-submitted names (missing names are joined-rank zero
        proxies the impl synthesizes itself)."""
        self._device_exec_impl = impl
        if self._device_cb is not None:
            return
        controller = self

        def _cb(phase, rtype, n, names_p, sizes_p, dtype_code, op, root,
                prescale, postscale, err, err_cap):
            try:
                if phase == _PHASE_ABORT:
                    # A peer's prepare failed: drop the staged plan (the
                    # inputs stay in _device_inputs until device_finish
                    # pops them on the error path).
                    controller._device_plan = None
                    return 0
                if phase == _PHASE_PREPARE:
                    names = [names_p[i].decode() for i in range(n)]
                    # sizes length depends on the request type (matches
                    # the Response.sizes layout): allreduce/broadcast =
                    # element counts per name; allgather = per-rank dims
                    # + row_elems; alltoall = P x P matrix + row_elems.
                    P = controller.size()
                    if rtype == 1:
                        n_sizes = P + 1
                    elif rtype == 3:
                        n_sizes = P * P + 1
                    else:
                        n_sizes = n
                    sizes = [int(sizes_p[i]) for i in range(n_sizes)]
                    np_dtype = _CODE_TO_DTYPE[dtype_code]
                    with controller._device_lock:
                        inputs = {nm: controller._device_inputs[nm]
                                  for nm in names
                                  if nm in controller._device_inputs}
                    # Every check that can fail without touching the SPMD
                    # plane runs here, so a doomed rank is discovered
                    # BEFORE peers enter the unabortable collective.
                    validate = getattr(controller._device_exec_impl,
                                       "validate", None)
                    if validate is not None:
                        validate(rtype, names, sizes, np_dtype, op, root)
                    controller._device_plan = (
                        rtype, names, sizes, np_dtype, op, root,
                        prescale, postscale, inputs)
                    return 0
                # EXECUTE: unanimous OK was agreed across ranks.
                plan = controller._device_plan
                controller._device_plan = None
                if plan is None:
                    raise RuntimeError(
                        "device executor: EXECUTE without a prepared plan")
                results = controller._device_exec_impl(*plan)
                with controller._device_lock:
                    controller._device_results.update(results)
                return 0
            except BaseException as e:  # noqa: BLE001 — must not unwind into C
                msg = repr(e).encode()[: max(err_cap - 1, 0)]
                ctypes.memmove(err, msg + b"\x00", len(msg) + 1)
                return 1

        self._device_cb = _DEVICE_EXEC_FN(_cb)
        self._lib.hvd_native_set_device_executor(self._device_cb)

    def _device_dtype_code(self, arr) -> int:
        code = _DTYPE_CODES.get(np.dtype(arr.dtype))
        if code is None:
            raise TypeError(
                f"unsupported dtype {arr.dtype} for the device plane")
        return code

    def _device_shape_arg(self, arr):
        shape = (ctypes.c_int64 * max(arr.ndim, 1))(*(arr.shape or (1,)))
        return arr.ndim, shape

    def allreduce_device_submit(self, arr, op: int = 1,
                                prescale: float = 1.0,
                                postscale: float = 1.0,
                                name: Optional[str] = None
                                ) -> Tuple[int, str]:
        nm = self._auto_name("allreduce", name).decode()
        with self._device_lock:
            self._device_inputs[nm] = arr
        ndim, shape = self._device_shape_arg(arr)
        h = self._lib.hvd_native_allreduce_device(
            nm.encode(), ndim, shape, self._device_dtype_code(arr), op,
            prescale, postscale)
        if h < 0:
            with self._device_lock:
                self._device_inputs.pop(nm, None)
            raise NativeError(self._last_error())
        return h, nm

    def broadcast_device_submit(self, arr, root_rank: int = 0,
                                name: Optional[str] = None
                                ) -> Tuple[int, str]:
        nm = self._auto_name("broadcast", name).decode()
        with self._device_lock:
            self._device_inputs[nm] = arr
        ndim, shape = self._device_shape_arg(arr)
        h = self._lib.hvd_native_broadcast_device(
            nm.encode(), ndim, shape, self._device_dtype_code(arr),
            root_rank)
        if h < 0:
            with self._device_lock:
                self._device_inputs.pop(nm, None)
            raise NativeError(self._last_error())
        return h, nm

    def allgather_device_submit(self, arr, name: Optional[str] = None
                                ) -> Tuple[int, str]:
        nm = self._auto_name("allgather", name).decode()
        with self._device_lock:
            self._device_inputs[nm] = arr
        ndim, shape = self._device_shape_arg(arr)
        h = self._lib.hvd_native_allgather_device(
            nm.encode(), ndim, shape, self._device_dtype_code(arr))
        if h < 0:
            with self._device_lock:
                self._device_inputs.pop(nm, None)
            raise NativeError(self._last_error())
        return h, nm

    def alltoall_device_submit(self, arr,
                               splits: Optional[Sequence[int]] = None,
                               name: Optional[str] = None
                               ) -> Tuple[int, str]:
        size = self.size()
        if splits is None:
            if arr.shape[0] % size != 0:
                raise ValueError("alltoall dim0 not divisible by size")
            splits = [arr.shape[0] // size] * size
        nm = self._auto_name("alltoall", name).decode()
        with self._device_lock:
            self._device_inputs[nm] = arr
        sp = (ctypes.c_int64 * len(splits))(*splits)
        ndim, shape = self._device_shape_arg(arr)
        h = self._lib.hvd_native_alltoall_device(
            nm.encode(), ndim, shape, self._device_dtype_code(arr), sp,
            len(splits))
        if h < 0:
            with self._device_lock:
                self._device_inputs.pop(nm, None)
            raise NativeError(self._last_error())
        return h, nm

    def allgather_device(self, arr, name: Optional[str] = None):
        h, nm = self.allgather_device_submit(arr, name=name)
        return self.device_finish(h, nm)

    def alltoall_device(self, arr, splits: Optional[Sequence[int]] = None,
                        name: Optional[str] = None):
        """Returns (received, received_splits) like the host path."""
        h, nm = self.alltoall_device_submit(arr, splits=splits, name=name)
        return self.device_finish(h, nm)

    def device_finish(self, h: int, name: str):
        """Wait for a *_device_submit handle and collect the on-device
        result (the payload never visited host memory)."""
        try:
            self._wait(h)
        except NativeError:
            with self._device_lock:
                self._device_inputs.pop(name, None)
                self._device_results.pop(name, None)
            raise
        self._lib.hvd_native_release(h)
        with self._device_lock:
            self._device_inputs.pop(name, None)
            out = self._device_results.pop(name, None)
        return out

    def allreduce_device(self, arr, op: int = 1, prescale: float = 1.0,
                         postscale: float = 1.0,
                         name: Optional[str] = None):
        h, nm = self.allreduce_device_submit(
            arr, op=op, prescale=prescale, postscale=postscale, name=name)
        return self.device_finish(h, nm)

    def broadcast_device(self, arr, root_rank: int = 0,
                         name: Optional[str] = None):
        h, nm = self.broadcast_device_submit(arr, root_rank=root_rank,
                                             name=name)
        return self.device_finish(h, nm)

    # -- collectives -------------------------------------------------------

    def allreduce_async_(self, arr: np.ndarray, out: np.ndarray,
                         op: int = 1, prescale: float = 1.0,
                         postscale: float = 1.0,
                         name: Optional[str] = None) -> int:
        """In-place-capable async allreduce: arr/out may alias. Returns a
        native handle; pass to wait()/release(). Caller must keep arr/out
        alive until wait() returns (the reference's async handle contract,
        torch/mpi_ops.py:843-882)."""
        ndim, shape = _shape_arg(arr)
        h = self._lib.hvd_native_allreduce(
            self._auto_name("allreduce", name),
            arr.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p),
            ndim, shape, _dtype_code(arr), op, prescale, postscale)
        if h < 0:
            raise NativeError(self._last_error())
        return h

    def wait(self, handle: int):
        self._wait(handle)
        self._lib.hvd_native_release(handle)

    def poll(self, handle: int) -> bool:
        return bool(self._lib.hvd_native_poll(handle))

    def allreduce_submit(self, arr: np.ndarray, op: int = 1,
                         prescale: float = 1.0, postscale: float = 1.0,
                         name: Optional[str] = None
                         ) -> Tuple[int, np.ndarray, np.ndarray]:
        """Enqueue an allreduce; returns (handle, in_buf, out_buf).  The
        caller must keep both buffers alive until the matching
        ``allreduce_finish`` (true-async contract: the background runtime
        streams from/to them while the op is in flight)."""
        arr = np.asarray(arr, order="C")  # keeps 0-d shape
        out = np.empty_like(arr)
        ndim, shape = _shape_arg(arr)
        h = self._lib.hvd_native_allreduce(
            self._auto_name("allreduce", name),
            arr.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p),
            ndim, shape, _dtype_code(arr), op, prescale, postscale)
        if h < 0:
            raise NativeError(self._last_error())
        return h, arr, out

    def allreduce_finish(self, h: int, out: np.ndarray) -> np.ndarray:
        self._wait(h)
        self._lib.hvd_native_release(h)
        return out

    def allreduce(self, arr: np.ndarray, op: int = 1,
                  prescale: float = 1.0, postscale: float = 1.0,
                  name: Optional[str] = None) -> np.ndarray:
        h, _arr, out = self.allreduce_submit(arr, op=op, prescale=prescale,
                                             postscale=postscale, name=name)
        return self.allreduce_finish(h, out)

    def grouped_allreduce(self, arrs, op: int = 1, prescale: float = 1.0,
                          postscale: float = 1.0,
                          name: Optional[str] = None):
        """Enqueue a group atomically and wait on all (reference GroupTable
        semantics, group_table.h:30-59): all members are in flight together
        so the background runtime fuses them into shared ring launches."""
        base = (name or
                self._auto_name("grouped", None).decode())
        outs, handles = [], []
        for i, arr in enumerate(arrs):
            arr = np.asarray(arr, order="C")  # keeps 0-d shape
            out = np.empty_like(arr)
            outs.append(out)
            handles.append(self.allreduce_async_(
                arr, out, op=op, prescale=prescale, postscale=postscale,
                name=f"{base}.{i}"))
        for h in handles:
            self.wait(h)
        return outs

    def allgather_submit(self, arr: np.ndarray,
                         name: Optional[str] = None
                         ) -> Tuple[int, np.ndarray]:
        arr = np.asarray(arr, order="C")  # keeps 0-d shape
        ndim, shape = _shape_arg(arr)
        h = self._lib.hvd_native_allgather(
            self._auto_name("allgather", name),
            arr.ctypes.data_as(ctypes.c_void_p), ndim, shape,
            _dtype_code(arr))
        if h < 0:
            raise NativeError(self._last_error())
        return h, arr

    def allgather_finish(self, h: int, arr: np.ndarray) -> np.ndarray:
        self._wait(h)
        nbytes = self._lib.hvd_native_result_bytes(h)
        dims = (ctypes.c_int64 * self.size())()
        self._lib.hvd_native_result_dims(h, dims, self.size())
        total_rows = sum(dims)
        out = np.empty((total_rows,) + arr.shape[1:], dtype=arr.dtype)
        assert out.nbytes >= nbytes
        self._lib.hvd_native_result_copy(
            h, out.ctypes.data_as(ctypes.c_void_p), out.nbytes)
        self._lib.hvd_native_release(h)
        return out

    def allgather(self, arr: np.ndarray,
                  name: Optional[str] = None) -> np.ndarray:
        h, arr = self.allgather_submit(arr, name=name)
        return self.allgather_finish(h, arr)

    def broadcast_submit(self, arr: np.ndarray, root_rank: int = 0,
                         name: Optional[str] = None
                         ) -> Tuple[int, np.ndarray, np.ndarray]:
        arr = np.asarray(arr, order="C")  # keeps 0-d shape
        out = arr.copy()
        ndim, shape = _shape_arg(arr)
        h = self._lib.hvd_native_broadcast(
            self._auto_name("broadcast", name),
            arr.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p),
            ndim, shape, _dtype_code(arr), root_rank)
        if h < 0:
            raise NativeError(self._last_error())
        return h, arr, out

    def broadcast_finish(self, h: int, out: np.ndarray) -> np.ndarray:
        self._wait(h)
        self._lib.hvd_native_release(h)
        return out

    def broadcast(self, arr: np.ndarray, root_rank: int = 0,
                  name: Optional[str] = None) -> np.ndarray:
        h, _arr, out = self.broadcast_submit(arr, root_rank=root_rank,
                                             name=name)
        return self.broadcast_finish(h, out)

    def alltoall_submit(self, arr: np.ndarray,
                        splits: Optional[Sequence[int]] = None,
                        name: Optional[str] = None
                        ) -> Tuple[int, np.ndarray]:
        arr = np.asarray(arr, order="C")  # keeps 0-d shape
        size = self.size()
        if splits is None:
            if arr.shape[0] % size != 0:
                raise ValueError("alltoall dim0 not divisible by size")
            splits = [arr.shape[0] // size] * size
        sp = (ctypes.c_int64 * len(splits))(*splits)
        ndim, shape = _shape_arg(arr)
        h = self._lib.hvd_native_alltoall(
            self._auto_name("alltoall", name),
            arr.ctypes.data_as(ctypes.c_void_p), ndim, shape,
            _dtype_code(arr), sp, len(splits))
        if h < 0:
            raise NativeError(self._last_error())
        return h, arr

    def alltoall_finish(self, h: int, arr: np.ndarray
                        ) -> Tuple[np.ndarray, np.ndarray]:
        self._wait(h)
        size = self.size()
        dims = (ctypes.c_int64 * size)()
        self._lib.hvd_native_result_dims(h, dims, size)
        recv_splits = np.array(list(dims), dtype=np.int32)
        out = np.empty((int(recv_splits.sum()),) + arr.shape[1:],
                       dtype=arr.dtype)
        self._lib.hvd_native_result_copy(
            h, out.ctypes.data_as(ctypes.c_void_p), max(out.nbytes, 1))
        self._lib.hvd_native_release(h)
        return out, recv_splits

    def alltoall(self, arr: np.ndarray,
                 splits: Optional[Sequence[int]] = None,
                 name: Optional[str] = None
                 ) -> Tuple[np.ndarray, np.ndarray]:
        h, arr = self.alltoall_submit(arr, splits=splits, name=name)
        return self.alltoall_finish(h, arr)

    def join(self) -> int:
        return self._lib.hvd_native_join()

    def barrier(self):
        if self._lib.hvd_native_barrier() != 0:
            raise NativeError(self._last_error())

    def last_fused_names(self) -> int:
        """Names in the most recent (possibly fused) allreduce Response —
        live evidence of the current fusion threshold (autotune)."""
        return self._lib.hvd_native_last_fused_names()

    def stalled(self) -> list:
        """Stall-inspector snapshot (coordinator only; [] elsewhere):
        tensors past the warning window, each with ``name``, request
        ``type``, ``age_s`` and the ``missing`` / ``submitted`` rank
        lists — the evidence the hang-report escalation consumes
        (debug/hang.py)."""
        import json
        n = self._lib.hvd_native_stalled_json(None, 0)
        buf = ctypes.create_string_buffer(max(n + 1, 3))
        self._lib.hvd_native_stalled_json(buf, len(buf))
        try:
            return json.loads(buf.value.decode() or "[]")
        except ValueError:
            # The table can change between the sizing and filling calls;
            # a truncated fill parses as garbage exactly once — treat as
            # "nothing stalled" and let the next poll see stable state.
            return []

    def set_schedule_table(self, kind, max_bytes, hierarchical) -> None:
        """Install one op kind's per-payload dispatch table on the
        coordinator (``hvd_native_set_schedule_table``): payloads up to
        ``max_bytes[i]`` use the hierarchical schedule iff
        ``hierarchical[i]``.  ``max_bytes`` must be ascending and end
        with INT64_MAX (ops/dispatch.py DispatchTable.to_native emits
        this shape).  Coordinator-only effect, like the wire stamp."""
        if isinstance(kind, int):
            code = kind
        else:
            # Single home of the name -> native ScheduleKind mapping.
            from ..ops.dispatch import KIND_CODES
            code = KIND_CODES[kind]
        n = len(max_bytes)
        mb = (ctypes.c_int64 * n)(*[int(b) for b in max_bytes])
        ch = (ctypes.c_int32 * n)(*[1 if c else 0 for c in hierarchical])
        self._lib.hvd_native_set_schedule_table(code, mb, ch, n)

    def set_cache_enabled(self, enabled: bool) -> None:
        """Response-cache toggle alone (does not touch the dispatch
        tables the way ``hvd_native_set_tuned_toggles`` would)."""
        self._lib.hvd_native_set_cache_enabled(1 if enabled else 0)

    def last_allgather_schedule(self) -> int:
        """0 = flat ring, 1 = hierarchical (chain fan-out),
        2 = hierarchical (CMA star fan-out) — most recent allgather."""
        return self._lib.hvd_native_last_allgather_schedule()

    def last_allreduce_schedule(self) -> int:
        """0 = flat ring / flat VHDD, 1 = hierarchical — schedule of
        this process's most recent allreduce/Adasum (the allreduce
        analog of ``last_allgather_schedule``)."""
        return self._lib.hvd_native_last_allreduce_schedule()

    def schedules(self) -> dict:
        """Most recent schedule per op kind, one dict for dashboards and
        drill assertions: allreduce/allgather report flat (0) vs
        hierarchical (1, or 2 for the allgather CMA-star fan-out);
        broadcast reports its fan-out (1 chain, 2 CMA star)."""
        return {"allreduce": self.last_allreduce_schedule(),
                "allgather": self.last_allgather_schedule(),
                "broadcast": self.last_bcast_schedule()}

    def last_allreduce_fanout(self) -> int:
        """0 = flat/none, 1 = chain, 2 = zero-copy CMA star — phase-3
        fan-out of the most recent hierarchical allreduce/Adasum."""
        return self._lib.hvd_native_last_allreduce_fanout()

    def last_bcast_schedule(self) -> int:
        """0 = none yet, 1 = pipelined chain, 2 = zero-copy CMA star —
        most recent broadcast."""
        return self._lib.hvd_native_last_bcast_schedule()

    def adasum_scratch_peak(self) -> int:
        """Peak scratch bytes of the Adasum VHDD path since last reset."""
        return self._lib.hvd_native_adasum_scratch_peak()

    NET_COUNTER_FIELDS = ("retries", "reconnects", "renegotiations",
                          "resets_avoided", "chaos_injected",
                          "recovering_now", "last_recovery_age_ms")

    def net_counters(self) -> dict:
        """Self-healing wire fabric counters (net.cc escalation ladder):
        recovery attempts / resumed reconnects / ring renegotiations /
        collectives completed after >= 1 recovery, plus the live
        ``recovering_now`` channel count and the age of the last
        recovery activity (-1 = never) — the hang-report evidence for
        "retrying, deadline not yet reached" vs "wedged"."""
        buf = (ctypes.c_int64 * len(self.NET_COUNTER_FIELDS))()
        n = self._lib.hvd_native_net_counters(buf, len(buf))
        return {k: int(buf[i]) for i, k in
                enumerate(self.NET_COUNTER_FIELDS[:n])}

    def adasum_scratch_reset(self) -> None:
        self._lib.hvd_native_adasum_scratch_reset()

    def rank(self) -> int:
        return self._lib.hvd_native_rank()

    def size(self) -> int:
        return self._lib.hvd_native_size()

    def start_timeline(self, filename: str):
        import time as _time
        t0 = _time.time()
        self._lib.hvd_native_start_timeline(filename.encode())
        # Merge anchor for runtime-started timelines (debug/merge.py).
        from ..debug import flight as _flight
        _flight.set_meta("timeline_start_wall", (t0 + _time.time()) / 2.0)

    def stop_timeline(self):
        self._lib.hvd_native_stop_timeline()

    def shutdown(self):
        if self._autotune is not None:
            # Deregister from the closed loop: a drift firing after
            # shutdown must not reach a tuner whose apply path is gone.
            from .. import autotune as _autotune_mod
            if _autotune_mod.active_manager() is self._autotune:
                _autotune_mod.set_active_manager(None)
        self._lib.hvd_native_shutdown()
