"""Elastic training: state objects with commit/restore/sync and the retry
loop (reference horovod/common/elastic.py:26-175)."""

from .state import State, ObjectState, TpuState, run
