"""Elastic state objects and the `run` retry loop.

Capability parity with the reference (horovod/common/elastic.py:26-175):

* ``State.commit()`` — snapshot to host memory + check for host updates.
* ``State.restore()`` — roll back to the last committed snapshot after a
  ``HorovodInternalError``.
* ``State.sync()`` — broadcast state from rank 0 to (re)joining workers.
* ``run(train_fn)`` — wraps a training function so collective failures
  restore state and re-rendezvous, and host-set changes re-rendezvous
  without restore (HostsUpdatedInterrupt).  ``sync()`` runs after every
  reset regardless of the interrupt's skip hint — see run()'s docstring.

TPU-native reset: instead of the reference's cheap ``shutdown(); init()``
(tensorflow/elastic.py:64-66), the TPU backend re-creates the mesh (and, when
the world changed, re-initializes the distributed runtime) — see ``_reset``.
"""

from __future__ import annotations

import copy
import functools
import queue
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from ..core.exceptions import HorovodInternalError, HostsUpdatedInterrupt
from ..core.state import global_state
from ..debug import flight as _flight
from ..utils import logging as log


def _elastic_counter(name: str, help: str, **labels):
    """Elastic lifecycle events in the hvd.metrics registry — commit/
    restore/sync/reset rates are the fleet-health signals the driver's
    free-text prints never made queryable."""
    from ..metrics.registry import registry
    return registry().counter(name, help, **labels)


class State:
    """Base elastic state with commit/restore/sync and host-update checks."""

    def __init__(self, **kwargs):
        self._host_messages: "queue.Queue" = queue.Queue()
        self._last_updated_timestamp = 0
        self._reset_callbacks = []
        # Commit seniority for sync-root election (elect_sync_root): a
        # freshly (re)spawned worker carries 0, survivors the number of
        # commits their state has seen.
        self._sync_generation = 0
        for k, v in kwargs.items():
            setattr(self, k, v)

    def register_reset_callbacks(self, callbacks):
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        self._host_messages = queue.Queue()
        self.reset()
        for cb in self._reset_callbacks:
            cb()

    def on_hosts_updated(self, timestamp, update_res):
        self._host_messages.put((timestamp, update_res))

    def commit(self):
        self.save()
        self._sync_generation += 1
        _flight.record("elastic.commit", None,
                       generation=self._sync_generation)
        _elastic_counter("hvd_elastic_commits_total",
                         "Elastic state commits").inc()
        notification_manager.announce_commit(self._sync_generation)
        notification_manager.poll()
        self.check_host_updates()

    def elect_sync_root(self) -> int:
        """Agree on which rank's state seeds ``sync()``: the lowest rank
        holding the highest commit generation.

        Broadcasting from a hardcoded rank 0 loses committed progress
        whenever a freshly respawned process is seated at rank 0 of the
        new round (e.g. a cascade respawn of the first host's slot 0):
        its constructor-initial state would overwrite every survivor's.
        The reference sidesteps this by keeping previously-assigned hosts
        first in the host order (elastic/driver.py host assignment); that
        is slot-granular here, so the root is elected explicitly from
        commit seniority instead."""
        from ..optimizers import allgather_object
        gens = allgather_object(int(self._sync_generation),
                                name="elastic.sync.generation")
        self._elected_generation = max(gens)
        return int(max(range(len(gens)), key=lambda r: (gens[r], -r)))

    def adopt_sync_generation(self):
        """Call once sync's broadcasts COMPLETE: only then does this
        worker actually hold the root's state and deserve its seniority.
        Adopting at election time would let a fresh worker whose sync
        died mid-broadcast claim a generation it never received — and
        win a tie-break in the retry round's election."""
        g = getattr(self, "_elected_generation", None)
        if g is not None:
            self._sync_generation = max(self._sync_generation, g)
            self._elected_generation = None

    def check_host_updates(self):
        """Raise HostsUpdatedInterrupt if the host set changed since the last
        commit (reference common/elastic.py:60-96)."""
        updated = False
        skip_sync = True
        while not self._host_messages.empty():
            timestamp, update_res = self._host_messages.get()
            if timestamp > self._last_updated_timestamp:
                self._last_updated_timestamp = timestamp
                updated = True
                # update_res True means only additions (no state lost).
                skip_sync = skip_sync and bool(update_res)
        if updated:
            raise HostsUpdatedInterrupt(skip_sync=skip_sync)

    # Subclass interface ---------------------------------------------------
    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError

    def reset(self):
        pass


class ObjectState(State):
    """Elastic state backed by arbitrary picklable attributes (reference
    common/elastic.py ObjectState): everything passed as kwargs is
    committed/restored/synced by value."""

    def __init__(self, bcast_object: Optional[Callable] = None, **kwargs):
        if bcast_object is None:
            from ..optimizers import broadcast_object
            bcast_object = broadcast_object
        self._bcast_object = bcast_object
        self._saved_state = dict(kwargs)
        super().__init__(**kwargs)

    def save(self):
        new_state = {}
        for k in self._saved_state:
            new_state[k] = copy.deepcopy(getattr(self, k))
        self._saved_state = new_state

    def restore(self):
        for k, v in self._saved_state.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self, root: Optional[int] = None):
        _flight.record("elastic.sync", None, root=root)
        if self._saved_state:
            if root is None:
                root = self.elect_sync_root()
            synced = self._bcast_object(self._saved_state, root_rank=root)
            self._saved_state = synced
            self.restore()
        self.adopt_sync_generation()


def _is_zero_sharded(x) -> bool:
    from ..checkpoint import is_zero_state
    return is_zero_state(x)


def _has_zero_sharded(tree) -> bool:
    from ..checkpoint import has_zero_leaves
    return has_zero_leaves(tree)


def _is_data_iterator(x) -> bool:
    """Duck-typed checkpointable-iterator protocol (hvd.data.DataLoader
    and friends): live objects with threads/queues cannot ride the
    deepcopy snapshot path — their ``state_dict()`` does instead."""
    return (not isinstance(x, (dict, list, tuple))
            and callable(getattr(x, "state_dict", None))
            and callable(getattr(x, "load_state_dict", None)))


# Dedicated engine-step directory for iterator state when a TpuState
# carries data iterators but no ZeRO-sharded trees (with ZeRO trees the
# state rides those steps' manifests instead).
_DATA_DIR_KEY = "data_iters"


class _AsyncCommitter:
    """One-deep background disk flush: ``submit`` hands the previous
    step's ``save_extracted`` to a daemon thread and returns; ``wait``
    joins it and re-raises its failure.  The NEXT ``commit()`` waits
    first (the satellite's "commit barrier only at the next commit"), so
    disk durability leaves the hot path but a flush can never overlap
    the next step's writes to the same directory."""

    def __init__(self):
        self._thread: Optional["threading.Thread"] = None
        self._exc: Optional[BaseException] = None

    def submit(self, fn: Callable[[], Any]) -> None:
        import threading
        self.wait()

        def _run():
            try:
                # Background marker: the flush's engine I/O must not be
                # charged to the training step's checkpoint component
                # (metrics/attribution.py) — it overlaps compute by
                # design.
                from ..checkpoint.engine import background_io
                with background_io():
                    fn()
            except BaseException as e:  # noqa: BLE001 — surfaced at wait()
                self._exc = e

        self._thread = threading.Thread(
            target=_run, name="hvd-tpu-async-commit", daemon=True)
        self._thread.start()

    def wait(self) -> None:
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        exc, self._exc = self._exc, None
        if exc is not None:
            raise exc

    @property
    def pending(self) -> bool:
        return self._thread is not None


class TpuState(ObjectState):
    """Elastic state for JAX training: params/opt_state pytrees snapshotted
    to host memory on commit, broadcast from rank 0 on sync (the analog of
    TorchState handlers, torch/elastic/state.py:27-80).

    ZeRO-sharded optimizer state (``ZeroShardedOptimizer``) is
    rank-DISTINCT, so it cannot ride the sync broadcast — pass
    ``checkpoint_dir`` and the sharded leaves get a durable lifecycle
    through ``horovod_tpu.checkpoint`` instead: ``commit()`` writes
    every rank's shard plus a rank-0 manifest under
    ``<checkpoint_dir>/<tree_key>/``, and ``sync()`` after a reset
    restores the newest committed step, *resharding* the flat moment
    buffers when the elastic world resized.  Thread the state through
    ``shard_map`` with ``checkpoint.zero_state_specs`` (global flat
    buffers partitioned over the data axis) so commits can see every
    local shard.  Use a fresh ``checkpoint_dir`` per training run: the
    engine's run fingerprint refuses cross-run saves/restores with a
    pointed error (HVD_TPU_CKPT_ALLOW_FOREIGN=1 overrides), but
    structurally identical runs are indistinguishable.

    Checkpointable data iterators (``hvd.data.DataLoader`` — anything
    with ``state_dict``/``load_state_dict``) passed as kwargs get the
    iterator lifecycle: ``commit()`` snapshots their state (and, with a
    ``checkpoint_dir``, persists it in the engine manifest alongside
    the ZeRO shards), ``restore()`` rolls them back, and ``sync()``
    broadcasts the committed position — then each loader reshards its
    remaining epoch to the new world (``load_state_dict`` re-resolves
    topology).  A mid-epoch restore resumes with no duplicated and no
    dropped samples; see docs/data.md.

    Peer-to-peer hot recovery (``peer_recovery``, default
    ``HVD_TPU_RECOVERY`` = on): ``commit()`` also places each rank's
    committed shard (data-iterator state riding along, as on disk) in
    the in-memory replica tier — its own copy locally, a buddy copy
    with ``recovery.replica_holder(rank)`` — and ``sync()`` tries to
    reassemble the state from fleet memory BEFORE reading the disk
    manifest, so an elastic resize after a single-rank loss restores in
    peer-exchange time with disk as the correlated-failure fallback.
    Works with no ``checkpoint_dir`` at all (disk-free restarts), at
    the durability of the surviving processes' memory.  ``sync()``
    records which path won (``peer`` / ``disk`` / ``none``) in
    ``hvd.metrics``, the flight recorder, and hang reports.

    Async snapshot commit (``async_commit``, default
    ``HVD_TPU_ASYNC_COMMIT`` = off; single-controller only — a
    multi-controller save barriers on a collective that cannot run on a
    background thread): ``commit()`` extracts the host payload, places
    replicas, and hands the disk write to a background committer; the
    commit barrier moves to the NEXT ``commit()``/``sync()``, so both
    durability tiers leave the hot path.  See docs/recovery.md."""

    def __init__(self, params=None, opt_state=None, checkpoint_dir=None,
                 checkpoint_keep: int = 3, checkpoint_mesh=None,
                 peer_recovery: Optional[bool] = None,
                 async_commit: Optional[bool] = None, **kwargs):
        # Knob defaults single-sourced from core.config.Config (the
        # PR 4 flight-knob convention), env override per state object.
        from ..core.config import Config, get_bool
        self._peer_explicit = peer_recovery is not None
        self._peer_recovery = (get_bool("RECOVERY", Config.recovery)
                               if peer_recovery is None
                               else bool(peer_recovery))
        self._async_commit = (get_bool("ASYNC_COMMIT",
                                       Config.async_commit)
                              if async_commit is None
                              else bool(async_commit))
        self._committer = _AsyncCommitter()
        self._extract_disabled = set()
        # (key, step) pairs whose async flush died before the replica
        # seal: pruned from _ckpt_committed_step at the next barrier.
        self._ckpt_failed = set()
        self._tree_keys = []
        self._data_keys = [k for k, v in kwargs.items()
                           if _is_data_iterator(v)]
        data_objs = {k: kwargs.pop(k) for k in self._data_keys}
        self._saved_data = {k: v.state_dict() for k, v in data_objs.items()}
        self._checkpoint_dir = checkpoint_dir
        self._checkpoint_keep = checkpoint_keep
        self._checkpoint_mesh = checkpoint_mesh
        self._ckpt_next_step = {}
        # Step of the last FULLY committed checkpoint per tree key:
        # engine manifest on disk AND the in-memory snapshot both done.
        # sync() restores this step, not blindly the newest on disk — a
        # crash after the engine commit but before super().commit()
        # leaves a disk step one ahead of the rolled-back params, and
        # pairing those would be exactly the torn state the engine
        # exists to prevent.
        self._ckpt_committed_step = {}
        if params is not None:
            self._tree_keys.append("params")
            kwargs["params"] = params
        if opt_state is not None:
            self._tree_keys.append("opt_state")
            kwargs["opt_state"] = opt_state
        super().__init__(**kwargs)
        for k, v in data_objs.items():
            setattr(self, k, v)
        # Closed-loop tuning memory (autotune.announce_model): the
        # state's pytrees ARE the model identity — announce the
        # leaf-spec fingerprint so an autotuned job warm-starts from
        # (and freezes back into) the persistent tuned-config store.
        # Best-effort, and a no-op on every process without an active
        # tuner (everyone but rank 0 of an --autotune job).
        try:
            from .. import autotune as _autotune
            if _autotune.active_manager() is not None:
                trees = {k: getattr(self, k) for k in self._tree_keys}
                if trees:
                    _autotune.announce_model(trees)
        except Exception:  # noqa: BLE001 — memory never blocks training
            pass

    def _mesh(self):
        if self._checkpoint_mesh is not None:
            return self._checkpoint_mesh
        from ..core import basics
        return basics.mesh()

    def _zero_dir(self, key: str):
        import os
        return os.path.join(self._checkpoint_dir, key)

    def _next_ckpt_step(self, key: str) -> int:
        # Monotonic across full job relaunches: seeded from the newest
        # committed step on disk (NOT the sync generation, which resets
        # to 0 on relaunch and would make gc_steps delete fresh commits
        # while `latest` kept electing the stale pre-relaunch step).
        # Ranks agree without a collective because (a) seeding happens
        # at sync(), when every member — survivor or fresh — reads the
        # same committed disk state (the cache is cleared there, so a
        # committer crash that tore a step cannot leave survivors'
        # counters ahead of a respawned rank's disk-derived seed), and
        # (b) between syncs the counters advance in lockstep, with
        # save_zero_state's post-commit barrier making the manifest
        # durable before any process moves on.
        if key not in self._ckpt_next_step:
            latest = None
            if self._checkpoint_dir is not None:
                from ..checkpoint import latest_step
                latest = latest_step(self._zero_dir(key))
            self._ckpt_next_step[key] = 0 if latest is None else latest + 1
        return self._ckpt_next_step[key]

    def _async_effective(self) -> bool:
        if not self._async_commit:
            return False
        if global_state.initialized and global_state.process_count > 1:
            # Multi-controller save_extracted barriers on a collective;
            # running it on a background thread would interleave with
            # training collectives.  Degrade to the synchronous write.
            return False
        return True

    def _extract_for_commit(self, key: str):
        """Extracted host payload for one ZeRO tree, or None when the
        state is not globally threaded AND nothing requires it (peer
        replication enabled only by default, no checkpoint_dir) — then
        the tier degrades exactly like the pre-recovery behavior of a
        dir-less TpuState.  The failure is latched per key: one warning
        and one failed extraction attempt, not one per commit."""
        if key in self._extract_disabled:
            return None
        from ..checkpoint import extract_zero_state
        try:
            return extract_zero_state(getattr(self, key),
                                      mesh=self._mesh())
        except ValueError:
            if self._checkpoint_dir is not None or self._peer_explicit:
                raise
            self._extract_disabled.add(key)
            log.warning(
                "TpuState.%s: cannot extract ZeRO shards for peer "
                "replication (state not threaded with zero_state_specs);"
                " the in-memory recovery tier is disabled for it", key)
            return None

    def _prune_failed_steps(self):
        """Drop committed-step records whose async flush failed before
        any tier held the step — a pinned ghost step would force sync's
        peer AND disk lookups to miss and silently restore one step
        behind the params."""
        while self._ckpt_failed:
            k, s = self._ckpt_failed.pop()
            if self._ckpt_committed_step.get(k) == s:
                self._ckpt_committed_step.pop(k)

    def _seal_replicas(self, saved_steps: Dict[str, int], exts: dict):
        if not self._peer_recovery:
            return
        from .. import recovery
        for k, step in saved_steps.items():
            if k in exts:
                recovery.seal_commit(k, step, ext=exts[k])

    def commit(self):
        saved_steps = {}
        exts = {}
        # Iterator state is captured ONCE here and stamped into every
        # manifest this commit writes: the committed step atomically
        # pairs optimizer moments with the input position, so a restore
        # can never resume the data stream at a different step.
        data_states = {k: getattr(self, k).state_dict()
                       for k in self._data_keys}
        extra = None
        if data_states:
            from ..checkpoint import DATA_ITERS_KEY
            extra = {DATA_ITERS_KEY: data_states}
        zero_keys = [k for k in self._tree_keys
                     if _has_zero_sharded(getattr(self, k))]
        if zero_keys and (self._checkpoint_dir is not None
                          or self._peer_recovery):
            from ..checkpoint import save_extracted
            from ..recovery.chaos import chaos
            # Async commit barrier: the previous step's background
            # flush must land (and surface its failure) before this
            # step writes the same directories.
            try:
                self._committer.wait()
            finally:
                self._prune_failed_steps()
            use_async = self._async_effective()
            for k in zero_keys:
                ext = self._extract_for_commit(k)
                if ext is None:
                    continue
                step = self._next_ckpt_step(k)
                root = (None if self._checkpoint_dir is None
                        else self._zero_dir(k))
                keep = self._checkpoint_keep

                def _flush(k=k, ext=ext, step=step, root=root,
                           sealing=use_async):
                    """Replication + disk write + (async mode) seal —
                    the whole durability tail of one commit.  Runs
                    inline in sync mode, on the committer thread in
                    async mode, so BOTH tiers leave the hot path.  An
                    async failure BEFORE the seal marks the step failed
                    (``_ckpt_failed``) so the committed-step record —
                    already updated by the time the background failure
                    lands — cannot pin a step that exists in no tier."""
                    try:
                        if self._peer_recovery:
                            from .. import recovery
                            recovery.replicate(k, step, ext, extra=extra)
                        # Chaos drill: the commit window where the
                        # replica is placed (unsealed) but the step is
                        # not yet committed anywhere.  In async mode
                        # the scheduled crash surfaces at the next
                        # commit barrier.
                        chaos().maybe_crash("after_replicate", step)
                        if root is None and self._peer_recovery and \
                                global_state.initialized and \
                                global_state.process_count > 1:
                            # Disk-free multi-controller: the disk
                            # path's pre-commit barrier is what kept
                            # one rank from sealing step N+1
                            # (overwriting its only sealed copy of N)
                            # while a slower rank had not yet
                            # replicated N+1 — without it a kill in
                            # that skew window would leave NO fully
                            # covered step.  Replication needs the
                            # same barrier.
                            from ..ops import collective as C
                            C.barrier()
                        if sealing and self._peer_recovery:
                            # Async mode: seal BEFORE the disk write,
                            # not after — the replica tier's commit
                            # record must not depend on the disk flush
                            # succeeding, or a disk failure would void
                            # an already-successful replication and
                            # sync() would pair step-N params with
                            # step-(N-1) moments.
                            from .. import recovery
                            recovery.seal_commit(k, step, ext=ext)
                    except BaseException:
                        if sealing:
                            # Failed before the seal: the step exists
                            # in NO tier, and the committed-step record
                            # (updated on the main thread) must not pin
                            # it — pruned at the next barrier.
                            self._ckpt_failed.add((k, step))
                        raise
                    if root is not None:
                        save_extracted(root, ext, step, keep=keep,
                                       extra=extra)

                if use_async:
                    # The seal rides the background flush: the replica
                    # tier's commit record lands when the flush does —
                    # a crash before it restores the previous sealed
                    # step, the exact durability the disk tier offers
                    # for an unflushed manifest.
                    self._committer.submit(_flush)
                else:
                    _flush()
                    exts[k] = ext  # sealed after super().commit()
                self._ckpt_next_step[k] = step + 1
                saved_steps[k] = step
        if self._checkpoint_dir is not None and data_states \
                and not saved_steps:
            # No ZeRO tree to ride: iterator state gets its own
            # (tiny) engine step — same durability protocol.
            step = self._next_ckpt_step(_DATA_DIR_KEY)
            self._commit_data_step(step, data_states)
            self._ckpt_next_step[_DATA_DIR_KEY] = step + 1
            saved_steps[_DATA_DIR_KEY] = step
        try:
            super().commit()
        except HostsUpdatedInterrupt:
            # The base commit raises AFTER save() snapshotted — the
            # step IS fully committed (disk AND snapshot); the interrupt
            # only re-runs rendezvous.  Record it, or the next sync()
            # would pair current params with one-step-old moments.
            # Replica entries seal here too: they carry the same commit.
            self._ckpt_committed_step.update(saved_steps)
            self._seal_replicas(saved_steps, exts)
            raise
        self._ckpt_committed_step.update(saved_steps)
        self._seal_replicas(saved_steps, exts)

    def _read_data_iters_from_disk(self, chosen: dict):
        """The committed iterator-state payload: from the chosen (or
        newest committed) step of a ZeRO tree's manifest when one
        exists, else from the dedicated iterator-state directory."""
        if self._checkpoint_dir is None:
            return None
        from ..checkpoint import is_committed, restore_data_state
        keys = [k for k in self._tree_keys
                if _has_zero_sharded(getattr(self, k))]
        keys.append(_DATA_DIR_KEY)
        for k in keys:
            d = self._zero_dir(k)
            step = chosen.get(k)
            if step is not None and not is_committed(d, step):
                step = None
            try:
                state = restore_data_state(d, step=step)
            except (OSError, ValueError, KeyError):
                continue
            if state:
                return state
        return None

    def _commit_data_step(self, step: int, data_states: dict) -> None:
        """One process (rank 0) writes the dedicated iterator-state
        step; a barrier makes it durable before anyone moves on (the
        save_zero_state protocol in miniature)."""
        from ..checkpoint import save_data_state
        writer = True
        barrier = None
        if global_state.initialized and global_state.process_count > 1:
            from ..ops import collective as C
            writer = global_state.process_rank == 0
            barrier = C.barrier
        if writer:
            save_data_state(self._zero_dir(_DATA_DIR_KEY), data_states,
                            step=step, keep=self._checkpoint_keep)
        if barrier is not None:
            barrier()

    def save(self):
        # Device→host snapshot so a TPU reset cannot lose it.
        for k in self._tree_keys:
            setattr(self, "_host_" + k, jax.tree_util.tree_map(
                lambda x: np.asarray(x), getattr(self, k)))
        for k in self._data_keys:
            self._saved_data[k] = getattr(self, k).state_dict()
        super().save()

    def restore(self):
        super().restore()
        for k in self._tree_keys:
            host = getattr(self, "_host_" + k, None)
            if host is not None:
                setattr(self, k, jax.tree_util.tree_map(
                    lambda x: jax.numpy.asarray(x), host))
        for k in self._data_keys:
            getattr(self, k).load_state_dict(
                copy.deepcopy(self._saved_data[k]))

    def _record_recovery_path(self, path: str, key: str,
                              step: Optional[int], reason: str):
        """Fold a non-peer restore decision into the same observability
        surface peer restores use (metrics + flight + last_report), so
        hang reports can attribute EVERY recovery, not just the hot
        ones."""
        import time
        from .. import recovery
        from ..metrics.registry import registry
        registry().counter("hvd_recovery_restores_total",
                           "Recovery restore decisions by path",
                           path=path).inc()
        recovery.record_report(recovery.RecoveryReport(
            path=path, key=key, step=step, reason=reason,
            wall=time.time()))
        _flight.record("recovery.restore.done", key, path=path,
                       step=step)

    def sync(self, root: Optional[int] = None):
        from ..optimizers import broadcast_parameters
        _flight.record("elastic.sync", None, root=root)
        if root is None:
            root = self.elect_sync_root()
        # A pending async flush must land before this sync trusts disk
        # state.  Its failure degrades (the replica tier seals before
        # the disk write, so it usually still covers the step; a
        # pre-seal failure is pruned from the committed record) rather
        # than killing the round — but it can mean this sync restores
        # the PREVIOUS committed moments under newer live params, so
        # say so loudly.
        try:
            self._committer.wait()
        except Exception as e:  # noqa: BLE001 — surfaced, not fatal here
            log.warning(
                "async checkpoint flush failed (%r); the disk tier may "
                "lag the replica tier this round, and if the failure "
                "preceded the replica seal this sync restores the "
                "previous committed step", e)
        finally:
            self._prune_failed_steps()
        # Membership changed: drop cached commit-step counters so every
        # member (survivor or fresh) re-seeds from the same committed
        # disk state — a survivor's counter may be ahead of disk if the
        # previous committer crashed mid-step.
        self._ckpt_next_step.clear()
        # Agree on WHICH step to restore: the root survivor's record of
        # the last fully committed step (disk + snapshot).  A disk step
        # with no surviving in-memory commit is a torn commit — params
        # rolled back past it, so restoring it would pair step-K moments
        # with step-K-1 params.  Fresh roots (relaunch) have no record
        # and take the newest committed disk step.
        from ..core.state import global_state
        chosen = dict(self._ckpt_committed_step)
        if global_state.initialized and global_state.size > 1:
            from ..optimizers import broadcast_object
            chosen = broadcast_object(chosen, root_rank=root)
            self._ckpt_committed_step = dict(chosen)
        if self._checkpoint_dir is None:
            # Disk-free mode has no disk `latest` to re-seed the cleared
            # step counters from: seed from the agreed committed record,
            # so fresh members and survivors keep committing at the
            # SAME, still-monotonic steps.  Restarting at 0 would both
            # desync mixed rounds and leave a superseded world's
            # higher-step replicas unprunable (seal's stale-world sweep
            # compares steps) — resident forever and able to outvote
            # the live run in a newest-covered-step election.
            for k, s in chosen.items():
                self._ckpt_next_step[k] = int(s) + 1
        peer_extra = None
        for k in self._tree_keys:
            tree = getattr(self, k)
            if _has_zero_sharded(tree):
                # Rank-distinct shards cannot ride the broadcast — rank
                # 0's slice would overwrite every other rank's.  First
                # choice: reassemble the committed step from the
                # fleet's replica memory (peer restore — seconds, no
                # disk round-trip); the gather is a collective, and its
                # input is identical on every member, so the peer-vs-
                # disk decision is fleet-consistent by construction.
                if self._peer_recovery:
                    from .. import recovery
                    try:
                        new_tree, pextra, _rep = recovery.peer_restore(
                            k, tree, mesh=self._mesh(),
                            step=chosen.get(k))
                        setattr(self, k, new_tree)
                        if peer_extra is None and pextra:
                            peer_extra = pextra
                        continue
                    except recovery.PeerRestoreUnavailable as e:
                        log.info("recovery: peer restore unavailable "
                                 "for %s (%s); falling back to the "
                                 "disk manifest", k, e)
                # Disk fallback: restore the newest committed engine
                # step, resharding the flat moment buffers when the
                # elastic world resized.
                if self._checkpoint_dir is not None:
                    from ..checkpoint import (is_committed, latest_step,
                                              restore_zero_state)
                    step = chosen.get(k)
                    if step is not None and not is_committed(
                            self._zero_dir(k), step):
                        step = None  # recorded step GC'd or torn: fall back
                    if step is None:
                        step = latest_step(self._zero_dir(k))
                    if step is not None:
                        setattr(self, k, restore_zero_state(
                            self._zero_dir(k), tree, mesh=self._mesh(),
                            step=step))
                        if self._peer_recovery:
                            self._record_recovery_path(
                                "disk", k, step,
                                "peer coverage unavailable; disk "
                                "manifest restored")
                        continue
                    if self._peer_recovery:
                        self._record_recovery_path(
                            "none", k, None,
                            "no peer coverage and no committed disk "
                            "step (pre-first-commit or lost state)")
                else:
                    if self._peer_recovery:
                        self._record_recovery_path(
                            "none", k, None,
                            "no peer coverage and no checkpoint_dir "
                            "(disk-free mode, pre-first-commit or "
                            "fleet memory lost)")
                    log.warning(
                        "TpuState.%s holds ZeRO-sharded leaves and "
                        "neither the peer tier nor a checkpoint_dir "
                        "can restore them; skipping sync for them — a "
                        "world resize will NOT restore these moments "
                        "(see docs/recovery.md)", k)
                # No committed step (or no dir): the ZeRO leaves stay
                # local (identical init state before the first commit),
                # but replicated leaves living alongside them — e.g. a
                # chained transform's count/schedule state — must still
                # reach rejoining workers.
                flat, treedef = jax.tree_util.tree_flatten(
                    tree, is_leaf=_is_zero_sharded)
                plain = [i for i, l in enumerate(flat)
                         if not _is_zero_sharded(l)]
                if plain:
                    synced = broadcast_parameters(
                        [flat[i] for i in plain], root_rank=root)
                    for i, v in zip(plain, synced):
                        flat[i] = v
                    setattr(self, k, jax.tree_util.tree_unflatten(
                        treedef, flat))
                continue
            setattr(self, k, broadcast_parameters(tree, root_rank=root))
        # Data iterators: seed the committed position from disk (a full
        # relaunch has no in-memory record), then let the elected
        # root's view win — survivors carry the same committed state
        # they wrote, so mixed survivor/fresh rounds converge.  Loading
        # re-seats each loader in the CURRENT topology: the remaining
        # epoch reshards N→M with no duplicated and no dropped samples.
        if self._data_keys:
            disk = None
            if peer_extra:
                # A peer restore carries the SAME committed extra the
                # disk manifest would — the atomic moments+input pairing
                # survives the disk-free path.
                from ..checkpoint import DATA_ITERS_KEY
                disk = peer_extra.get(DATA_ITERS_KEY)
            if not disk:
                disk = self._read_data_iters_from_disk(chosen)
            if disk:
                for k, v in disk.items():
                    if k in self._data_keys:
                        self._saved_data[k] = v
            if global_state.initialized and global_state.size > 1:
                from ..optimizers import broadcast_object
                self._saved_data = broadcast_object(self._saved_data,
                                                    root_rank=root)
            for k in self._data_keys:
                getattr(self, k).load_state_dict(
                    copy.deepcopy(self._saved_data[k]))
        # Sync the plain-object part too.
        object_keys = [k for k in self._saved_state
                       if k not in self._tree_keys]
        if object_keys:
            from ..optimizers import broadcast_object
            synced = broadcast_object(
                {k: getattr(self, k) for k in object_keys}, root_rank=root)
            for k, v in synced.items():
                setattr(self, k, v)
        # Persist the synced state into the restorable snapshots BEFORE
        # claiming the root's seniority: otherwise a pre-first-commit
        # failure would restore() this worker to constructor-initial
        # state while it carries the adopted generation — and a later
        # election could crown that initial state.
        self.save()
        self.adopt_sync_generation()


def _reset():
    """TPU-native world reset: tear down and re-init the runtime so a new
    rendezvous round can change the world size (reference
    tensorflow/elastic.py:64-66 does shutdown()+init())."""
    from ..core import basics
    _flight.record("elastic.reset", None)
    basics.shutdown()
    basics.init()
    # Re-zero the metrics aggregator's step counter: its sync cadence is
    # a collective schedule keyed on the LOCAL step count, and a new
    # round mixes survivors (counter mid-flight) with fresh spawns
    # (counter 0).  Every member passes through this reset (survivor) or
    # process start (fresh), so zeroing here re-aligns the fleet — a
    # survivor syncing at a step a newcomer hasn't reached would pair
    # its metrics allgather with the newcomer's next training
    # collective.
    from ..metrics.aggregate import aggregator
    aggregator().reset()


def run(func: Callable) -> Callable:
    """Decorator running ``func(state, ...)`` under the elastic retry loop
    (reference common/elastic.py:151-175).

    Deviation from the reference: ``sync()`` runs after EVERY reset,
    regardless of the interrupt's ``skip_sync`` hint.  Sync is a
    collective — participation must be all-or-none per rendezvous round —
    but different workers can reach the same round through different
    paths (commit-time interrupt vs collective failure vs fresh spawn),
    each carrying a different hint: honoring it deadlocks the round, with
    newly-added workers waiting in sync while survivors proceed to the
    next named collective.  One broadcast per round change is cheap
    insurance."""

    @functools.wraps(func)
    def wrapper(state: State, *args, **kwargs):
        notification_manager.init()
        notification_manager.register_listener(state)
        import time as _time
        from ..metrics.registry import registry as _mreg
        sync_gauge = _mreg().gauge(
            "hvd_elastic_sync_seconds",
            "Duration of the last elastic state sync")
        try:
            while True:
                t0 = _time.perf_counter()
                state.sync()
                _elastic_counter("hvd_elastic_syncs_total",
                                 "Elastic state syncs").inc()
                sync_gauge.set(_time.perf_counter() - t0)
                # The sync's restore/broadcast work (peer or disk
                # restore, state broadcast) happened BETWEEN runs: re-
                # anchor the attribution marks now, after it, so those
                # checkpoint/comm seconds are never charged to the
                # first step of the new round (_reset's re-anchor runs
                # before sync and cannot cover it).
                from ..metrics.attribution import (
                    attribution as _attr_engine, enabled as _attr_enabled)
                if _attr_enabled():
                    _attr_engine().reanchor()
                try:
                    return func(state, *args, **kwargs)
                except HorovodInternalError as e:
                    log.warning("collective failure (%s); restoring last "
                                "committed state and re-initializing", e)
                    _flight.record("elastic.restore", None, cause="failure")
                    _elastic_counter(
                        "hvd_elastic_resets_total",
                        "Elastic retry-loop resets by cause",
                        cause="failure").inc()
                    state.restore()
                except HostsUpdatedInterrupt:
                    log.info("host set updated; re-initializing")
                    _elastic_counter(
                        "hvd_elastic_resets_total",
                        "Elastic retry-loop resets by cause",
                        cause="hosts_updated").inc()
                _reset()
                state.on_reset()
        finally:
            notification_manager.remove_listener(state)

    return wrapper


class WorkerNotificationManager:
    """Surfaces host-update events from the elastic driver to registered
    State objects (reference runner/elastic/worker.py's notification
    service).  Pull-based: ``poll()`` — called from ``State.commit()`` —
    checks the rendezvous KV's host-event key; the reference's push RPC
    also only takes effect at commit, so semantics match."""

    def __init__(self):
        self._listeners = []
        self._enabled = False
        self._last_ts = 0.0

    def init(self):
        import os
        self._enabled = bool(os.environ.get("HVD_TPU_RENDEZVOUS_ADDR")
                             and os.environ.get("HVD_TPU_ELASTIC_SLOT"))

    def register_listener(self, state: State):
        self._listeners.append(state)

    def remove_listener(self, state: State):
        if state in self._listeners:
            self._listeners.remove(state)

    def announce_commit(self, generation: int):
        """Publish this job's commit generation to the launcher's
        rendezvous KV (``elastic/commit``).  The launcher side —
        ``ElasticDriver.last_commit()``, consumed by the fleet gateway's
        scheduler — uses it as the evidence for checkpoint-mediated
        preemption: shrink a victim only once it has committed.
        Fleet-managed jobs only (``HVD_TPU_FLEET_JOB_ID``, stamped by
        the gateway's runner): a plain elastic job has no consumer for
        the key and must not pay an HTTP round-trip per commit.  Rank 0
        only (commits advance in lockstep, one announcement covers the
        fleet); a publish failure is absorbed — telemetry never kills
        training."""
        if not self._enabled:
            return
        import json
        import os
        import time
        if not os.environ.get("HVD_TPU_FLEET_JOB_ID"):
            return
        if global_state.initialized and global_state.rank != 0:
            return
        addr = os.environ.get("HVD_TPU_RENDEZVOUS_ADDR")
        if not addr:
            return
        from ..runner.rendezvous import http_put
        try:
            http_put(addr, "elastic", "commit", json.dumps({
                "ts": time.time(), "generation": int(generation),
                "slot": os.environ.get("HVD_TPU_ELASTIC_SLOT", ""),
            }).encode(), timeout=5)
        except Exception:  # noqa: BLE001 — an announcement, not a barrier
            pass

    def poll(self):
        if not self._enabled:
            return
        from ..runner.worker import poll_host_event
        event = poll_host_event(self._last_ts)
        if event is not None:
            self._last_ts = event["ts"]
            # Stale events (for a round this worker already joined via the
            # failure path) must not trigger another interrupt — it would
            # block waiting for a round the driver never publishes.
            if event.get("round", 1 << 30) <= global_state.elastic_round:
                return
            self.handle_hosts_updated(event["ts"],
                                      bool(event.get("added_only")))

    def handle_hosts_updated(self, timestamp, update_res):
        for listener in self._listeners:
            listener.on_hosts_updated(timestamp, update_res)


notification_manager = WorkerNotificationManager()
