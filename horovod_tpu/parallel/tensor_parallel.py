"""Tensor (model) parallel building blocks — Megatron-style column/row
parallel projections over a mesh axis, for use inside ``shard_map``.

The reference framework is data-parallel only (SURVEY.md §2.3); tensor
parallelism is part of this framework's TPU-native scope.  The math:

* column-parallel: ``Y_shard = X @ W[:, shard]`` — no communication; the
  activation comes out feature-sharded.
* row-parallel: ``Y = psum_over_axis(X_shard @ W[shard, :])`` — one psum
  (or reduce_scatter when the consumer is sequence-sharded, the
  Megatron-SP fusion).

Weights are stored pre-sharded (each member holds only its shard), so the
framework never materializes the full matrix — FSDP-style memory scaling on
top of TP.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def column_parallel(x: jax.Array, w_shard: jax.Array,
                    b_shard: Optional[jax.Array] = None) -> jax.Array:
    """(..., d_in) @ (d_in, d_out/P) -> (..., d_out/P); no communication."""
    y = jnp.einsum("...i,io->...o", x, w_shard)
    if b_shard is not None:
        y = y + b_shard
    return y


def row_parallel(x_shard: jax.Array, w_shard: jax.Array, axis_name: str,
                 b: Optional[jax.Array] = None,
                 scatter_sequence: bool = False) -> jax.Array:
    """(..., d_in/P) @ (d_in/P, d_out) -> psum -> (..., d_out).

    With ``scatter_sequence=True`` the psum becomes a reduce_scatter over the
    sequence dimension (dim -2), returning a sequence-sharded activation —
    the Megatron sequence-parallel fusion that halves the bytes on the wire.
    """
    partial = jnp.einsum("...i,io->...o", x_shard, w_shard)
    if scatter_sequence:
        y = lax.psum_scatter(partial, axis_name, scatter_dimension=partial.ndim - 2,
                             tiled=True)
    else:
        y = lax.psum(partial, axis_name)
    if b is not None:
        y = y + b
    return y


def gather_sequence(x: jax.Array, axis_name: str, dim: int = 1) -> jax.Array:
    """All-gather a sequence-sharded activation back to full length along
    ``dim`` (entry into a tensor-parallel region)."""
    return lax.all_gather(x, axis_name, axis=dim, tiled=True)


def vocab_parallel_logits(x: jax.Array, embed_shard: jax.Array,
                          axis_name: str) -> jax.Array:
    """Compute logits against a vocab-sharded embedding: each member holds
    vocab/P rows; the full logits stay sharded on the vocab dim."""
    return jnp.einsum("...d,vd->...v", x, embed_shard)


def vocab_parallel_cross_entropy(logits_shard: jax.Array, labels: jax.Array,
                                 vocab_shard_size: int,
                                 axis_name: str) -> jax.Array:
    """Cross-entropy over vocab-sharded logits without gathering the full
    vocab: two psums (max and sum-exp) plus a masked label pick."""
    idx = lax.axis_index(axis_name)
    lo = idx * vocab_shard_size
    lf = logits_shard.astype(jnp.float32)
    local_max = lf.max(axis=-1)
    global_max = lax.pmax(local_max, axis_name)
    shifted = lf - global_max[..., None]
    sum_exp = lax.psum(jnp.exp(shifted).sum(axis=-1), axis_name)
    # Pick the label logit if it lives in this shard, else 0; psum completes.
    local_label = labels - lo
    in_shard = (local_label >= 0) & (local_label < vocab_shard_size)
    safe_label = jnp.clip(local_label, 0, vocab_shard_size - 1)
    picked = jnp.take_along_axis(shifted, safe_label[..., None],
                                 axis=-1)[..., 0]
    label_logit = lax.psum(jnp.where(in_shard, picked, 0.0), axis_name)
    return jnp.log(sum_exp) - label_logit
