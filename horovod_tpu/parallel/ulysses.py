"""Ulysses-style sequence parallelism — all-to-all head/sequence resharding.

The second of the two standard long-context layouts (beside ring attention,
ring_attention.py).  The reference's only sequence-layout primitive is
alltoall (SURVEY.md §5.7: "the building block a Ulysses-style SP would
use"); this module is that layout made first-class on TPU:

1. activations arrive sequence-sharded: (B, S/P, H, D);
2. one ``all_to_all`` trades the sequence shards for head shards:
   (B, S, H/P, D) — every device now sees the **full** sequence for a
   subset of heads;
3. plain (flash) attention runs locally — no per-step ring hops, one
   collective each way, which on ICI is a single fused all-to-all;
4. a second ``all_to_all`` restores sequence sharding.

Compared with ring attention: 2 collectives total instead of P ppermute
rounds (better for moderate P / long S), but requires heads % P == 0 and
peak activation memory holds the full sequence for H/P heads.

Call inside ``shard_map`` with the sequence axis sharded over
``axis_name``; differentiable by JAX AD (all_to_all transposes to itself).
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax
from ..compat import axis_size

from . import ring_attention as ra


def _seq_to_head_sharded(x, axis_name):
    # (…, B, S/P, H, D) → (…, B, S, H/P, D); leading stack dims allowed.
    nd = x.ndim
    return lax.all_to_all(x, axis_name, split_axis=nd - 2,
                          concat_axis=nd - 3, tiled=True)


def _head_to_seq_sharded(x, axis_name):
    # (…, B, S, H/P, D) → (…, B, S/P, H, D)
    nd = x.ndim
    return lax.all_to_all(x, axis_name, split_axis=nd - 3,
                          concat_axis=nd - 2, tiled=True)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, causal: bool = True,
                      scale: Optional[float] = None) -> jax.Array:
    """Exact attention over a sequence-sharded axis via head resharding.

    q, k, v: (B, S_local, H, D) shards; returns the (B, S_local, H, D)
    output shard.  Requires H divisible by the axis size.
    """
    sp = axis_size(axis_name)
    h = q.shape[2]
    if h % sp != 0:
        raise ValueError(
            f"ulysses_attention needs heads ({h}) divisible by the "
            f"sequence-parallel degree ({sp}); use ring_attention for "
            "head counts that don't divide")
    if sp == 1:
        return ra.full_attention(q, k, v, causal=causal, scale=scale)
    # One fused all-to-all for q/k/v (stacked on a leading dim) + one for
    # the output: 2 collective launches per attention, not 4.
    import jax.numpy as jnp
    qkv = _seq_to_head_sharded(jnp.stack([q, k, v]), axis_name)
    oh = ra.full_attention(qkv[0], qkv[1], qkv[2], causal=causal,
                           scale=scale)
    return _head_to_seq_sharded(oh, axis_name)
