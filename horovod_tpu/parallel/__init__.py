"""``hvd.parallel`` — the mesh-axis toolbox behind the flagship models.

One package per parallelism axis, composable inside one ``shard_map``:

* :mod:`.mesh` — named-axis mesh construction and the canonical axis
  vocabulary (``DATA``/``FSDP``/``TENSOR``/``SEQUENCE``/``PIPELINE``/
  ``EXPERT``).
* :mod:`.tensor_parallel` — Megatron column/row-parallel matmuls and
  the sequence-parallel gather/scatter pair.
* :mod:`.ring_attention` — exact blockwise ring attention (sequence
  stays sharded through attention); :mod:`.ulysses` — the all_to_all
  head-scatter alternative.
* :mod:`.pipeline` — GPipe and 1F1B microbatch schedules over a
  ``ppermute`` stage ring, plus the bubble-fraction arithmetic the
  attribution engine charges (docs/parallel.md).
* :mod:`.moe` — top-k token routing with capacity-bounded all_to_all
  dispatch/combine, load-balancing aux loss, dropped-token accounting,
  and the optional int8/int4 block-scaled dispatch wire.

Import the submodules for the full surface; the names re-exported here
are the stable API (docs/api.md).
"""

from . import mesh
from . import moe
from . import pipeline
from . import ring_attention
from . import tensor_parallel
from . import ulysses

from .mesh import (
    DATA, EXPERT, FSDP, PIPELINE, SEQUENCE, TENSOR,
    create_mesh, data_parallel_mesh, parse_mesh_spec,
)
from .moe import (
    MoEParams, MoEStats, RoutingInfo, dispatch_wire_bytes,
    expert_capacity, init_moe_params, moe_layer, moe_load_balancing_loss,
    top_k_routing,
)
from .pipeline import (
    Schedule1F1B, bubble_fraction, build_1f1b_schedule, note_bubble,
    pipeline_apply, pipeline_apply_1f1b, stack_microbatches,
    unstack_microbatches,
)
# NB: the ring_attention FUNCTION is deliberately NOT re-exported here —
# binding it onto the package would shadow the `parallel.ring_attention`
# SUBMODULE (`from horovod_tpu.parallel import ring_attention as ra`
# would silently hand back the function).  Reach it via the submodule.
from .ring_attention import full_attention, reference_attention
from .tensor_parallel import (
    column_parallel, gather_sequence, row_parallel,
    vocab_parallel_cross_entropy, vocab_parallel_logits,
)
from .ulysses import ulysses_attention

__all__ = [
    "mesh", "moe", "pipeline", "ring_attention", "tensor_parallel",
    "ulysses",
    "DATA", "EXPERT", "FSDP", "PIPELINE", "SEQUENCE", "TENSOR",
    "create_mesh", "data_parallel_mesh", "parse_mesh_spec",
    "MoEParams", "MoEStats", "RoutingInfo", "dispatch_wire_bytes",
    "expert_capacity", "init_moe_params", "moe_layer",
    "moe_load_balancing_loss", "top_k_routing",
    "Schedule1F1B", "bubble_fraction", "build_1f1b_schedule",
    "note_bubble", "pipeline_apply", "pipeline_apply_1f1b",
    "stack_microbatches", "unstack_microbatches",
    "full_attention", "reference_attention",
    "column_parallel", "gather_sequence", "row_parallel",
    "vocab_parallel_cross_entropy", "vocab_parallel_logits",
    "ulysses_attention",
]
