"""Device-mesh construction and topology helpers.

The reference's communicator axes are GLOBAL / LOCAL (per-node) / CROSS
(one-per-node) built via MPI_COMM_TYPE_SHARED splits (mpi_context.cc:140-156).
The TPU-native equivalent is a ``jax.sharding.Mesh`` whose axes map onto the
physical interconnect: intra-slice axes ride ICI, the inter-slice axis rides
DCN.  ``mesh_utils.create_device_mesh`` gives ICI-topology-aware device
ordering; ``create_hybrid_device_mesh`` keeps the DCN axis outermost.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

# Canonical axis names used across the framework.
DATA = "data"       # data parallel (allreduce axis)
FSDP = "fsdp"       # sharded data parallel (zero-style weight sharding)
TENSOR = "model"    # tensor/model parallel (megatron-style)
SEQUENCE = "seq"    # sequence/context parallel (ring attention / ulysses)
PIPELINE = "pipe"   # pipeline parallel
EXPERT = "expert"   # expert parallel (MoE alltoall)


def create_mesh(shape: Dict[str, int], devices=None, allow_split_physical_axes: bool = True):
    """Create a Mesh from {axis_name: size}. Product must equal device count.

    Axis order in ``shape`` is the logical-to-physical assignment order:
    earlier axes change slowest, so put DCN-spanning axes (usually ``data``)
    first and the most communication-intense axes (``model``/``seq``) last —
    they land on adjacent ICI neighbors.
    """
    import jax
    from jax.experimental import mesh_utils

    names = tuple(shape.keys())
    dims = tuple(int(v) for v in shape.values())
    pool = list(devices) if devices is not None else jax.devices()
    total = int(np.prod(dims))
    if total > len(pool):
        raise ValueError(f"mesh shape {shape} has {total} slots but there are "
                         f"only {len(pool)} devices")
    pool = pool[:total]
    try:
        dev_array = mesh_utils.create_device_mesh(
            dims, devices=pool,
            allow_split_physical_axes=allow_split_physical_axes)
    except Exception:
        dev_array = np.array(pool).reshape(dims)
    return jax.sharding.Mesh(dev_array, names)


def data_parallel_mesh():
    """1-D mesh over all devices, axis "data" — the Horovod-equivalent
    communicator."""
    import jax
    return create_mesh({DATA: jax.device_count()})


def parse_mesh_spec(spec: str) -> Dict[str, int]:
    """Parse "data:8,model:4" → {"data": 8, "model": 4}."""
    out: Dict[str, int] = {}
    for part in spec.split(","):
        if not part.strip():
            continue
        name, _, dim = part.partition(":")
        out[name.strip()] = int(dim)
    return out


def local_mesh_axes(mesh) -> Tuple[str, ...]:
    return tuple(mesh.axis_names)


def axis_size(mesh, name: str) -> int:
    return dict(zip(mesh.axis_names, mesh.devices.shape))[name]
