"""Pipeline parallelism — GPipe-style microbatch schedule inside shard_map.

Stages are laid out along a mesh axis; activations travel stage→stage over
``lax.ppermute`` (one ICI hop when the pipeline axis is laid out along a
physical ring).  The whole schedule is a ``lax.scan`` over
``n_microbatches + n_stages - 1`` ticks, so XLA sees a static loop: forward
sends are overlapped with the next microbatch's compute, and the backward
pass — obtained by differentiating through the scan — reverses the permutes
automatically.

The reference framework has no pipeline support (SURVEY.md §2.3); this is
TPU-native scope.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from ..compat import axis_size


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any,
                   x_microbatches: jax.Array,
                   axis_name: str,
                   remat: bool = True) -> jax.Array:
    """Run ``stage_fn`` as a pipeline over ``axis_name``.

    Args:
      stage_fn: ``(params_for_this_stage, activation) -> activation`` with
        identical activation shapes in and out (embed/unembed live outside
        the pipeline).
      stage_params: this member's stage parameters (shard the full stacked
        stage dim over the pipeline axis in the caller's in_specs).
      x_microbatches: (n_micro, mb, ...) input; consumed by stage 0.
      axis_name: the pipeline mesh axis.
      remat: rematerialize each stage in the backward pass.

    Returns:
      (n_micro, mb, ...) outputs — valid on the **last** stage; other stages
      hold zeros (reduce with a stage mask, see ``last_stage_mask``).
    """
    n_stages = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = x_microbatches.shape[0]
    ticks = n_micro + n_stages - 1
    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    # Forward chain i -> i+1; the last stage sends to 0 (its payload is
    # ignored there — stage 0 always injects a fresh microbatch) keeping the
    # permutation a pure ring for ICI friendliness.
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    act0 = jnp.zeros_like(x_microbatches[0])
    out0 = jnp.zeros_like(x_microbatches)

    def body(carry, t):
        act, outbuf = carry
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        x_t = lax.dynamic_index_in_dim(x_microbatches, mb_idx, axis=0,
                                       keepdims=False)
        a_in = jnp.where(stage == 0, x_t, act)
        y = fn(stage_params, a_in)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        write = jnp.logical_and(stage == n_stages - 1, t >= n_stages - 1)
        updated = lax.dynamic_update_index_in_dim(outbuf, y, out_idx, axis=0)
        outbuf = jnp.where(write, updated, outbuf)
        act = lax.ppermute(y, axis_name, perm)
        return (act, outbuf), None

    (_, outbuf), _ = lax.scan(body, (act0, out0), jnp.arange(ticks))
    return outbuf


def last_stage_mask(axis_name: str) -> jax.Array:
    """1.0 on the last pipeline stage, 0.0 elsewhere — for masking losses
    computed from ``pipeline_apply`` output before a psum over the axis."""
    n_stages = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    return (stage == n_stages - 1).astype(jnp.float32)


def stack_microbatches(batch: jax.Array, n_micro: int) -> jax.Array:
    """(B, ...) -> (n_micro, B // n_micro, ...)."""
    if batch.shape[0] % n_micro != 0:
        raise ValueError(
            f"batch {batch.shape[0]} not divisible by {n_micro} microbatches")
    return batch.reshape(n_micro, batch.shape[0] // n_micro, *batch.shape[1:])


def unstack_microbatches(x: jax.Array) -> jax.Array:
    """(n_micro, mb, ...) -> (n_micro * mb, ...)."""
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])
