"""Pipeline parallelism — GPipe and 1F1B microbatch schedules inside
shard_map.

Stages are laid out along a mesh axis; activations travel stage→stage over
``lax.ppermute`` (one ICI hop when the pipeline axis is laid out along a
physical ring).

Two schedules:

* **GPipe** (:func:`pipeline_apply`): a ``lax.scan`` over
  ``n_microbatches + n_stages - 1`` ticks, so XLA sees a static loop;
  the backward pass — obtained by differentiating through the scan —
  reverses the permutes automatically.  Autodiff stashes one activation
  per scan tick, so the stash grows with ``n_micro``.

* **1F1B** (:func:`pipeline_apply_1f1b`): the Megatron one-forward-
  one-backward schedule as a ``jax.custom_vjp``.  The primal forward IS
  the GPipe tick loop (outputs are bit-identical); the backward replays
  forward and backward work interleaved along a host-precomputed static
  schedule table, holding a rolling activation stash bounded by the
  pipeline depth — O(``n_stages``) microbatch inputs, not O(``n_micro``)
  tick residuals.  The backward rematerializes stage forwards (the
  memory/compute trade 1F1B-with-remat makes); gradients equal GPipe's
  up to summation order.

Bubble arithmetic: with P stages and M microbatches both schedules idle
``(P-1)/(M+P-1)`` of their work slots (1F1B's win is memory, not bubble).
:func:`bubble_fraction` is the analytic bound; the schedule builder
measures the realized fraction from its own table, and
:func:`note_bubble` feeds the bubble share of a measured pipeline span to
the step-attribution engine as the ``pipeline_bubble`` wall component.

The reference framework has no pipeline support (SURVEY.md §2.3); this is
TPU-native scope.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from ..compat import axis_size

# Schedule-table op kinds (static int32 constants baked into the scan).
_IDLE, _FWD, _BWD = 0, 1, 2


def _gpipe_forward(fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any,
                   x_microbatches: jax.Array,
                   axis_name: str) -> jax.Array:
    """The GPipe tick loop — shared by :func:`pipeline_apply` and the
    1F1B primal so their outputs are bit-identical by construction."""
    n_stages = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = x_microbatches.shape[0]
    ticks = n_micro + n_stages - 1
    # Forward chain i -> i+1; the last stage sends to 0 (its payload is
    # ignored there — stage 0 always injects a fresh microbatch) keeping the
    # permutation a pure ring for ICI friendliness.
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    act0 = jnp.zeros_like(x_microbatches[0])
    out0 = jnp.zeros_like(x_microbatches)

    def body(carry, t):
        act, outbuf = carry
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        x_t = lax.dynamic_index_in_dim(x_microbatches, mb_idx, axis=0,
                                       keepdims=False)
        a_in = jnp.where(stage == 0, x_t, act)
        y = fn(stage_params, a_in)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        write = jnp.logical_and(stage == n_stages - 1, t >= n_stages - 1)
        updated = lax.dynamic_update_index_in_dim(outbuf, y, out_idx, axis=0)
        outbuf = jnp.where(write, updated, outbuf)
        act = lax.ppermute(y, axis_name, perm)
        return (act, outbuf), None

    (_, outbuf), _ = lax.scan(body, (act0, out0), jnp.arange(ticks))
    return outbuf


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any,
                   x_microbatches: jax.Array,
                   axis_name: str,
                   remat: bool = True) -> jax.Array:
    """Run ``stage_fn`` as a GPipe pipeline over ``axis_name``.

    Args:
      stage_fn: ``(params_for_this_stage, activation) -> activation`` with
        identical activation shapes in and out (embed/unembed live outside
        the pipeline).
      stage_params: this member's stage parameters (shard the full stacked
        stage dim over the pipeline axis in the caller's in_specs).
      x_microbatches: (n_micro, mb, ...) input; consumed by stage 0.
        ``n_micro < n_stages`` is legal — the pipeline just never fills
        (bubble fraction ``(P-1)/(M+P-1)`` grows accordingly); the fill/
        drain ticks recompute clamped microbatches whose results are
        never written to the output buffer.
      axis_name: the pipeline mesh axis.
      remat: rematerialize each stage in the backward pass.

    Returns:
      (n_micro, mb, ...) outputs — valid on the **last** stage; other stages
      hold zeros (reduce with a stage mask, see ``last_stage_mask``).
    """
    if x_microbatches.shape[0] < 1:
        raise ValueError("need at least one microbatch")
    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    return _gpipe_forward(fn, stage_params, x_microbatches, axis_name)


# ---------------------------------------------------------------------------
# 1F1B schedule
# ---------------------------------------------------------------------------

def bubble_fraction(n_stages: int, n_micro: int) -> float:
    """Analytic pipeline-bubble fraction ``(P-1)/(M+P-1)`` — the share of
    work slots each stage idles in either schedule (GPipe drains what 1F1B
    interleaves; the slot count is the same)."""
    if n_stages < 1 or n_micro < 1:
        raise ValueError(f"need n_stages, n_micro >= 1, got "
                         f"{(n_stages, n_micro)}")
    return (n_stages - 1) / (n_micro + n_stages - 1)


class Schedule1F1B(NamedTuple):
    """Static per-(stage, slot) op tables for the 1F1B backward replay."""
    kind: np.ndarray        # (n_stages, n_slots) int32 in {IDLE, FWD, BWD}
    mb: np.ndarray          # (n_stages, n_slots) int32 microbatch, -1 idle
    n_slots: int
    stash_depth: int        # max live microbatch inputs held by any stage
    measured_bubble: float  # idle work slots / total slots, from the table


def build_1f1b_schedule(n_stages: int, n_micro: int) -> Schedule1F1B:
    """Greedy discrete-event build of the non-interleaved 1F1B schedule.

    One op (forward of one microbatch, backward of one microbatch, or
    idle) per stage per slot.  Dependencies: F(s, m) needs F(s-1, m) a
    slot earlier (activation hop); B(s, m) needs B(s+1, m) a slot earlier
    (cotangent hop) and F(s, m) already done.  Each stage admits a new
    forward only while forwards-minus-backwards stays below
    ``n_stages - s`` — the Megatron warmup depth plus one — which bounds
    the live activation stash by the pipeline depth, independent of
    ``n_micro``.  The builder verifies every invariant and measures the
    realized bubble fraction from its own table.
    """
    P, M = int(n_stages), int(n_micro)
    if P < 1 or M < 1:
        raise ValueError(f"need n_stages, n_micro >= 1, got {(P, M)}")
    f_done = [0] * P             # forwards completed per stage
    b_done = [0] * P             # backwards completed per stage
    f_slot = [[-1] * M for _ in range(P)]   # slot F(s, m) ran
    b_slot = [[-1] * M for _ in range(P)]   # slot B(s, m) ran
    kind_rows, mb_rows = [], []
    t = 0
    cap = 4 * (M + P) + 8        # safety: greedy must finish well before
    while any(b < M for b in b_done):
        if t >= cap:
            raise AssertionError("1F1B schedule builder failed to converge")
        krow, mrow = [_IDLE] * P, [-1] * P
        for s in range(P):
            # Backward first (that is what 1F1B means after warmup).
            m = b_done[s]
            b_ready = (m < M and f_slot[s][m] != -1
                       and (s == P - 1 or (0 <= b_slot[s + 1][m] < t)))
            if b_ready:
                krow[s], mrow[s] = _BWD, m
                b_slot[s][m] = t
                b_done[s] += 1
                continue
            m = f_done[s]
            f_ready = (m < M and (s == 0 or (0 <= f_slot[s - 1][m] < t))
                       and f_done[s] - b_done[s] < P - s)
            if f_ready:
                krow[s], mrow[s] = _FWD, m
                f_slot[s][m] = t
                f_done[s] += 1
        kind_rows.append(krow)
        mb_rows.append(mrow)
        t += 1
    n_slots = t
    kind = np.array(kind_rows, dtype=np.int32).T     # (P, n_slots)
    mb = np.array(mb_rows, dtype=np.int32).T

    # --- invariants -----------------------------------------------------
    # A stage's activation buffer holds microbatch m from the slot the
    # input arrives (upstream F + 1 hop; own F slot for stage 0) until its
    # backward retires it.  Live sets are contiguous microbatch ranges, so
    # a depth-D ring indexed mb % D is clobber-free iff D >= max live.
    depth = 0
    for s in range(P):
        for m in range(M):
            assert f_slot[s][m] != -1 and b_slot[s][m] != -1
            assert f_slot[s][m] <= b_slot[s][m]
            if s > 0:
                assert f_slot[s][m] > f_slot[s - 1][m]
            if s < P - 1:
                assert b_slot[s][m] > b_slot[s + 1][m]
        enter = [f_slot[0][m] if s == 0 else f_slot[s - 1][m] + 1
                 for m in range(M)]
        for tt in range(n_slots):
            live = sum(1 for m in range(M)
                       if enter[m] <= tt <= b_slot[s][m])
            depth = max(depth, live)
    assert depth <= P + 1, f"stash depth {depth} exceeds pipeline bound"
    measured = 1.0 - (2.0 * M * P) / (P * n_slots)
    return Schedule1F1B(kind=kind, mb=mb, n_slots=n_slots,
                        stash_depth=depth, measured_bubble=measured)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _pipeline_1f1b(stage_fn, axis_name, stage_params, x_microbatches):
    return _gpipe_forward(stage_fn, stage_params, x_microbatches, axis_name)


def _1f1b_fwd(stage_fn, axis_name, stage_params, x_microbatches):
    out = _gpipe_forward(stage_fn, stage_params, x_microbatches, axis_name)
    return out, (stage_params, x_microbatches)


def _1f1b_bwd(stage_fn, axis_name, residuals, g):
    """Backward replay on the 1F1B table: forwards rematerialize stage
    inputs into a rolling depth-``stash_depth`` ring, backwards consume
    them as cotangents hop back up the ring.  Every member executes both
    lanes every slot and masks by its table entry — the same masked-SPMD
    idiom as the GPipe fill/drain ticks — which keeps all collectives
    (including any inside ``stage_fn``) unconditional."""
    stage_params, x_microbatches = residuals
    n_stages = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = x_microbatches.shape[0]
    sched = build_1f1b_schedule(n_stages, n_micro)
    D = sched.stash_depth
    kind_tab = jnp.asarray(sched.kind)
    mb_tab = jnp.asarray(sched.mb)
    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    bwd_perm = [(i, (i - 1) % n_stages) for i in range(n_stages)]
    up = (stage - 1) % n_stages
    down = (stage + 1) % n_stages

    act0 = jnp.zeros_like(x_microbatches[0])
    abuf0 = jnp.zeros((D,) + act0.shape, act0.dtype)
    cotq0 = jnp.zeros((D,) + act0.shape, g.dtype)
    dparams0 = jax.tree_util.tree_map(jnp.zeros_like, stage_params)
    dx0 = jnp.zeros_like(x_microbatches)

    def body(carry, t):
        fwd_msg, bwd_msg, abuf, cotq, dparams, dxbuf = carry
        k = kind_tab[stage, t]
        m = jnp.clip(mb_tab[stage, t], 0, n_micro - 1)
        slot = m % D

        # --- ingest last slot's hops (tables say who actually sent) -----
        tp = jnp.maximum(t - 1, 0)
        got_act = (t > 0) & (stage > 0) & (kind_tab[up, tp] == _FWD)
        m_up = jnp.clip(mb_tab[up, tp], 0, n_micro - 1)
        abuf = jnp.where(
            got_act,
            lax.dynamic_update_index_in_dim(abuf, fwd_msg, m_up % D, axis=0),
            abuf)
        got_cot = ((t > 0) & (stage < n_stages - 1)
                   & (kind_tab[down, tp] == _BWD))
        m_dn = jnp.clip(mb_tab[down, tp], 0, n_micro - 1)
        cotq = jnp.where(
            got_cot,
            lax.dynamic_update_index_in_dim(cotq, bwd_msg, m_dn % D, axis=0),
            cotq)

        # --- forward lane: rematerialize, stash the input, send down ----
        x_t = lax.dynamic_index_in_dim(x_microbatches, m, axis=0,
                                       keepdims=False)
        stashed = lax.dynamic_index_in_dim(abuf, slot, axis=0,
                                           keepdims=False)
        a_in = jnp.where(stage == 0, x_t, stashed)
        abuf = jnp.where(
            k == _FWD,
            lax.dynamic_update_index_in_dim(abuf, a_in, slot, axis=0),
            abuf)
        y = stage_fn(stage_params, a_in)

        # --- backward lane: vjp at the stashed input, send up -----------
        a_b = lax.dynamic_index_in_dim(abuf, slot, axis=0, keepdims=False)
        g_m = lax.dynamic_index_in_dim(g, m, axis=0, keepdims=False)
        cot_in = jnp.where(stage == n_stages - 1, g_m,
                           lax.dynamic_index_in_dim(cotq, slot, axis=0,
                                                    keepdims=False))
        _, vjp_fn = jax.vjp(stage_fn, stage_params, a_b)
        dp_m, da = vjp_fn(cot_in)
        is_b = (k == _BWD)
        dparams = jax.tree_util.tree_map(
            lambda acc, d: acc + jnp.where(is_b, d, jnp.zeros_like(d)),
            dparams, dp_m)
        dx_new = lax.dynamic_update_index_in_dim(dxbuf, da, m, axis=0)
        dxbuf = jnp.where(is_b & (stage == 0), dx_new, dxbuf)

        fwd_msg = lax.ppermute(y, axis_name, fwd_perm)
        bwd_msg = lax.ppermute(da, axis_name, bwd_perm)
        return (fwd_msg, bwd_msg, abuf, cotq, dparams, dxbuf), None

    carry0 = (act0, jnp.zeros_like(act0, dtype=g.dtype), abuf0, cotq0,
              dparams0, dx0)
    (_, _, _, _, dparams, dxbuf), _ = lax.scan(body, carry0,
                                               jnp.arange(sched.n_slots))
    dxbuf = jnp.where(stage == 0, dxbuf, jnp.zeros_like(dxbuf))
    return dparams, dxbuf


_pipeline_1f1b.defvjp(_1f1b_fwd, _1f1b_bwd)


def pipeline_apply_1f1b(stage_fn: Callable[[Any, jax.Array], jax.Array],
                        stage_params: Any,
                        x_microbatches: jax.Array,
                        axis_name: str) -> jax.Array:
    """Run ``stage_fn`` as a pipeline with the 1F1B backward schedule.

    Same contract as :func:`pipeline_apply`; outputs are bit-identical to
    GPipe's (the primal is the same tick loop).  Differentiating through
    it runs the Megatron 1F1B backward: activation stash bounded by the
    pipeline depth (``build_1f1b_schedule(...).stash_depth <= n_stages+1``
    microbatch inputs) instead of one residual per scan tick, at the cost
    of rematerializing stage forwards.  ``stage_params`` must be a pytree
    of inexact (float) arrays.
    """
    if x_microbatches.shape[0] < 1:
        raise ValueError("need at least one microbatch")
    return _pipeline_1f1b(stage_fn, axis_name, stage_params, x_microbatches)


def note_bubble(n_stages: int, n_micro: int, span_seconds: float) -> float:
    """Attribute the bubble share of a measured pipeline span to the
    ``pipeline_bubble`` wall component of the step-attribution engine.
    Returns the bubble seconds credited."""
    bubble = bubble_fraction(n_stages, n_micro) * max(0.0, span_seconds)
    from ..metrics import attribution
    attribution.note_pipeline_bubble(bubble)
    return bubble


def last_stage_mask(axis_name: str) -> jax.Array:
    """1.0 on the last pipeline stage, 0.0 elsewhere — for masking losses
    computed from ``pipeline_apply`` output before a psum over the axis."""
    n_stages = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    return (stage == n_stages - 1).astype(jnp.float32)


def stack_microbatches(batch: jax.Array, n_micro: int) -> jax.Array:
    """(B, ...) -> (n_micro, B // n_micro, ...)."""
    if batch.shape[0] % n_micro != 0:
        raise ValueError(
            f"batch {batch.shape[0]} not divisible by {n_micro} microbatches")
    return batch.reshape(n_micro, batch.shape[0] // n_micro, *batch.shape[1:])


def unstack_microbatches(x: jax.Array) -> jax.Array:
    """(n_micro, mb, ...) -> (n_micro * mb, ...)."""
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])
