"""Ring attention — sequence/context parallelism over a mesh axis.

Long-context training shards the sequence dimension across devices.  The
reference framework has no attention code (it is model-agnostic middleware —
SURVEY.md §5.7); the only primitive it offers for sequence layouts is
alltoall.  TPU-native, we make sequence parallelism first-class with ring
attention: Q stays resident, K/V shards rotate around the ring via
``lax.ppermute`` (riding ICI neighbor links), and each step accumulates a
blockwise-softmax partial (flash-attention online normalization, fp32
accumulators).  Communication per step is the K/V block — overlap with the
block matmul is XLA's latency-hiding scheduler's job.

Layout: q, k, v are (batch, seq_local, heads, head_dim) shards of the global
(batch, seq_local * ring_size, heads, head_dim) arrays, sequence-major across
the axis: rank i holds positions [i*seq_local, (i+1)*seq_local).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _block_attn(q, k, v, q_offset, kv_offset, causal, scale, m, l, o):
    """One blockwise attention step with online softmax accumulation.

    q: (B, Sq, H, D); k, v: (B, Sk, H, D); m, l: (B, H, Sq); o: (B, Sq, H, D).
    All accumulators fp32.
    """
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale  # (B,H,Sq,Sk)
    if causal:
        sq = q.shape[1]
        sk = k.shape[1]
        q_pos = q_offset + jnp.arange(sq)
        k_pos = kv_offset + jnp.arange(sk)
        mask = q_pos[:, None] >= k_pos[None, :]          # (Sq, Sk)
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))               # (B,H,Sq)
    # exp(_NEG_INF - _NEG_INF) would be 1; clamp so fully-masked blocks stay 0.
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
    alpha = jnp.where(m <= _NEG_INF / 2, 0.0, alpha)
    l_new = l * alpha + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, vf)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str, causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Exact attention over a sequence-sharded axis via K/V ring rotation.

    Call inside ``shard_map``; returns the local (B, Sq, H, D) output shard.
    """
    sp = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    m = jnp.full((b, h, sq), _NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((b, h, sq), dtype=jnp.float32)
    o = jnp.zeros((b, sq, h, d), dtype=jnp.float32)
    q_offset = idx * sq

    # Send K/V to the left neighbor each step; after t steps we hold the
    # shard originating from rank (idx + t) % sp.
    perm = [(i, (i - 1) % sp) for i in range(sp)]

    def body(t, carry):
        k_t, v_t, m_t, l_t, o_t = carry
        kv_rank = (idx + t) % sp
        kv_offset = kv_rank * sq
        m_t, l_t, o_t = _block_attn(q, k_t, v_t, q_offset, kv_offset,
                                    causal, scale, m_t, l_t, o_t)
        k_nxt = lax.ppermute(k_t, axis_name, perm)
        v_nxt = lax.ppermute(v_t, axis_name, perm)
        return k_nxt, v_nxt, m_t, l_t, o_t

    if sp == 1:
        _, _, m, l, o = body(0, (k, v, m, l, o))
    else:
        # Static python loop: sp is small and static; lets XLA pipeline the
        # ppermutes against the matmuls without a loop-carried dependence on
        # trip count.
        carry = (k, v, m, l, o)
        for t in range(sp):
            carry = body(t, carry)
        _, _, m, l, o = carry

    l = jnp.maximum(l, 1e-20)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def full_attention(q, k, v, causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Unsharded reference attention (same layout), used by tests and by the
    flagship model when sequence parallelism is off."""
    b, sq, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        q_pos = jnp.arange(sq)
        k_pos = jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
