"""Ring attention — sequence/context parallelism over a mesh axis.

Long-context training shards the sequence dimension across devices.  The
reference framework has no attention code (it is model-agnostic middleware —
SURVEY.md §5.7); the only primitive it offers for sequence layouts is
alltoall.  TPU-native, we make sequence parallelism first-class with ring
attention: Q stays resident, K/V shards rotate around the ring via
``lax.ppermute`` (riding ICI neighbor links), and each step accumulates a
blockwise-softmax partial (flash-attention online normalization, fp32
accumulators).  Communication per step is the K/V block — overlap with the
block matmul is XLA's latency-hiding scheduler's job.

Two compute paths per ring step:

* **Pallas flash kernel** (default on TPU): each step runs the fused
  ``ops/flash_attention.py`` kernel over the resident Q and the visiting
  K/V shard, returning (out, logsumexp); partials merge exactly via
  ``combine_blocks``.  The custom VJP re-walks the ring, accumulating dK/dV
  *onto the rotating shards* so each gradient lands back on its owner after
  a full revolution.
* **XLA fallback** (CPU tests, unsupported shapes): the original blockwise
  einsum recurrence, differentiated by JAX AD.

Layout: q, k, v are (batch, seq_local, heads, head_dim) shards of the global
(batch, seq_local * ring_size, heads, head_dim) arrays, sequence-major across
the axis: rank i holds positions [i*seq_local, (i+1)*seq_local).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from ..compat import axis_size

_NEG_INF = -1e30


def _flash_enabled(seq_k: Optional[int] = None) -> bool:
    """Dispatch policy for the fused kernel. ``HVD_TPU_FLASH=1/0`` forces;
    in auto mode, use it on TPU once the key sequence is long enough that
    the kernel's O(S) memory + tiling beat XLA's fused attention (measured
    on v5e: +18% BERT-Base train throughput already at S=512; tune with
    ``HVD_TPU_FLASH_MIN_SEQ``)."""
    v = os.environ.get("HVD_TPU_FLASH", "auto")
    if v == "0":
        return False
    if v == "1":
        return True
    if jax.default_backend() != "tpu":
        return False
    try:
        min_seq = int(os.environ.get("HVD_TPU_FLASH_MIN_SEQ", "512"))
    except ValueError:
        min_seq = 512
    return seq_k is None or seq_k >= min_seq


def _block_attn(q, k, v, q_offset, kv_offset, causal, scale, m, l, o):
    """One blockwise attention step with online softmax accumulation.

    q: (B, Sq, H, D); k, v: (B, Sk, H, D); m, l: (B, H, Sq); o: (B, Sq, H, D).
    All accumulators fp32.
    """
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale  # (B,H,Sq,Sk)
    if causal:
        sq = q.shape[1]
        sk = k.shape[1]
        q_pos = q_offset + jnp.arange(sq)
        k_pos = kv_offset + jnp.arange(sk)
        mask = q_pos[:, None] >= k_pos[None, :]          # (Sq, Sk)
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))               # (B,H,Sq)
    # exp(_NEG_INF - _NEG_INF) would be 1; clamp so fully-masked blocks stay 0.
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
    alpha = jnp.where(m <= _NEG_INF / 2, 0.0, alpha)
    l_new = l * alpha + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, vf)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def _ring_perm(sp):
    return [(i, (i - 1) % sp) for i in range(sp)]


def _ring_attention_xla(q, k, v, axis_name, causal, scale):
    sp = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape

    m = jnp.full((b, h, sq), _NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((b, h, sq), dtype=jnp.float32)
    o = jnp.zeros((b, sq, h, d), dtype=jnp.float32)
    q_offset = idx * sq

    # Send K/V to the left neighbor each step; after t steps we hold the
    # shard originating from rank (idx + t) % sp.
    perm = _ring_perm(sp)

    def body(t, carry):
        k_t, v_t, m_t, l_t, o_t = carry
        kv_rank = (idx + t) % sp
        kv_offset = kv_rank * sq
        m_t, l_t, o_t = _block_attn(q, k_t, v_t, q_offset, kv_offset,
                                    causal, scale, m_t, l_t, o_t)
        k_nxt = lax.ppermute(k_t, axis_name, perm)
        v_nxt = lax.ppermute(v_t, axis_name, perm)
        return k_nxt, v_nxt, m_t, l_t, o_t

    if sp == 1:
        _, _, m, l, o = body(0, (k, v, m, l, o))
    else:
        # Static python loop: sp is small and static; lets XLA pipeline the
        # ppermutes against the matmuls without a loop-carried dependence on
        # trip count.
        carry = (k, v, m, l, o)
        for t in range(sp):
            carry = body(t, carry)
        _, _, m, l, o = carry

    l = jnp.maximum(l, 1e-20)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Flash-kernel ring path (custom VJP; dK/dV ride the ring home)
# ---------------------------------------------------------------------------

def _ring_flash_forward(q, k, v, axis_name, causal, scale):
    from ..ops import flash_attention as fa
    sp = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    sq = q.shape[1]
    perm = _ring_perm(sp)
    q_offset = idx * sq

    o = lse = None
    k_t, v_t = k, v
    for t in range(sp):
        kv_rank = lax.rem(idx + t, sp)
        o_t, lse_t = fa.flash_attention_with_lse(
            q, k_t, v_t, causal=causal, scale=scale,
            q_offset=q_offset, kv_offset=kv_rank * sq)
        o_t = o_t.astype(jnp.float32)
        if o is None:
            o, lse = o_t, lse_t
        else:
            o, lse = fa.combine_blocks(o, lse, o_t, lse_t)
        if t < sp - 1:
            k_t = lax.ppermute(k_t, axis_name, perm)
            v_t = lax.ppermute(v_t, axis_name, perm)
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q, k, v, axis_name, causal, scale):
    out, _ = _ring_flash_forward(q, k, v, axis_name, causal, scale)
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal, scale):
    out, lse = _ring_flash_forward(q, k, v, axis_name, causal, scale)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, res, g):
    from ..ops import flash_attention as fa
    q, k, v, out, lse = res
    sp = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    sq = q.shape[1]
    perm = _ring_perm(sp)

    interpret = fa._use_interpret()
    blocks = fa._supported(q, k)
    bq, bk = blocks

    # (B, S, H, D) → (B, H, S, D) once for the whole walk.
    qt = q.transpose(0, 2, 1, 3)
    dot = g.astype(q.dtype).transpose(0, 2, 1, 3)
    outt = out.transpose(0, 2, 1, 3)
    delta = jnp.sum(dot.astype(jnp.float32) * outt.astype(jnp.float32),
                    axis=-1)                                   # (B, H, Sq)

    dq = jnp.zeros(qt.shape, jnp.float32)
    k_t, v_t = k, v
    dk_t = jnp.zeros(k.shape, jnp.float32)
    dv_t = jnp.zeros(v.shape, jnp.float32)
    for t in range(sp):
        kv_rank = lax.rem(idx + t, sp)
        offsets = jnp.stack([
            (idx * sq).astype(jnp.int32),
            (kv_rank * sq).astype(jnp.int32)]).reshape(1, 2)
        dq_b, dk_b, dv_b = fa._bwd_call(
            qt, k_t.transpose(0, 2, 1, 3), v_t.transpose(0, 2, 1, 3),
            dot, lse, delta, offsets, causal=causal, scale=scale,
            block_q=bq, block_k=bk, interpret=interpret)
        dq = dq + dq_b.astype(jnp.float32)
        dk_t = dk_t + dk_b.transpose(0, 2, 1, 3).astype(jnp.float32)
        dv_t = dv_t + dv_b.transpose(0, 2, 1, 3).astype(jnp.float32)
        # Rotate after every step (sp total): each K/V shard — and the
        # gradient accumulating on it — completes a full revolution and
        # lands back on its owner.
        if sp > 1:
            k_t = lax.ppermute(k_t, axis_name, perm)
            v_t = lax.ppermute(v_t, axis_name, perm)
            dk_t = lax.ppermute(dk_t, axis_name, perm)
            dv_t = lax.ppermute(dv_t, axis_name, perm)
    return (dq.transpose(0, 2, 1, 3).astype(q.dtype),
            dk_t.astype(k.dtype), dv_t.astype(v.dtype))


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str, causal: bool = True,
                   scale: Optional[float] = None,
                   use_flash: Optional[bool] = None) -> jax.Array:
    """Exact attention over a sequence-sharded axis via K/V ring rotation.

    Call inside ``shard_map``; returns the local (B, Sq, H, D) output shard.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    from ..ops import flash_attention as fa
    if use_flash is None:
        use_flash = _flash_enabled(k.shape[1])
    # Even when requested, the kernel path needs tileable shapes — the
    # backward walk has no per-step XLA fallback.
    use_flash = use_flash and fa._supported(q, k) is not None
    if use_flash:
        return _ring_flash(q, k, v, axis_name, causal, float(scale))
    return _ring_attention_xla(q, k, v, axis_name, causal, scale)


def reference_attention(q, k, v, causal: bool = True,
                        scale: Optional[float] = None) -> jax.Array:
    """Pure-XLA unsharded attention — the numerics oracle for tests."""
    b, sq, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        q_pos = jnp.arange(sq)
        k_pos = jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def full_attention(q, k, v, causal: bool = True,
                   scale: Optional[float] = None,
                   use_flash: Optional[bool] = None) -> jax.Array:
    """Unsharded attention (same layout as ring_attention). Dispatches to
    the fused Pallas kernel on TPU, XLA einsums elsewhere."""
    if use_flash is None:
        from ..ops import flash_attention as fa
        use_flash = (_flash_enabled(k.shape[1]) and
                     fa._supported(q, k) is not None)
    if use_flash:
        from ..ops import flash_attention as fa
        return fa.flash_attention(q, k, v, causal=causal, scale=scale)
    return reference_attention(q, k, v, causal=causal, scale=scale)
