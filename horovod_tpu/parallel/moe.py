"""Expert parallelism — switch-style Mixture-of-Experts with all-to-all
token routing over a mesh axis.

The reference's only layout-shuffling primitive is alltoall with uneven
splits (operations.cc:1136-1198, SURVEY.md §2.3 "the only primitive that
would serve EP/SP-style layouts").  TPU-native, expert parallelism is a
first-class layer: top-k gating with capacity, dispatch einsum into a
(experts, capacity, d) buffer — static shapes so XLA can tile the MXU — and
two ``lax.all_to_all`` exchanges riding ICI.  Dropped tokens (over capacity)
pass through on the residual path, standard Switch Transformer semantics.

Wire format: the dispatch/combine exchanges optionally ride the EQuARX
block-scaled int8/int4 wire from ``ops/quantization.py`` — each destination
rank's chunk is quantized independently (payload + one fp32 scale per
block travel as two all_to_alls), dequantized to fp32 on arrival.  The
combine einsum always accumulates in fp32; the wire dtype is never the
accumulation dtype (the module-wide contract of ops/quantization.py).
"""

from __future__ import annotations

import math
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..compat import axis_size
from ..ops.quantization import QuantSpec, wire_bytes


class MoEParams(NamedTuple):
    gate: jax.Array    # (d_model, n_experts_total) — replicated
    w_in: jax.Array    # (n_local, d_model, d_ff)   — sharded over expert axis
    w_out: jax.Array   # (n_local, d_ff, d_model)   — sharded over expert axis


class RoutingInfo(NamedTuple):
    """Static-shape routing decision for one batch of local tokens."""
    dispatch: jax.Array   # (T, E, C) f32 in {0, 1} — token t → expert e slot c
    combine: jax.Array    # (T, E, C) f32 — dispatch weighted by gate prob
    aux_loss: jax.Array   # scalar f32 — load-balancing auxiliary loss
    dropped: jax.Array    # scalar f32 — (token, route) slots over capacity
    capacity: int         # static per-expert slot count


class MoEStats(NamedTuple):
    """Per-call accounting returned by ``moe_layer(..., return_stats=True)``."""
    aux_loss: jax.Array   # scalar f32
    dropped: jax.Array    # scalar f32 — dropped (token, route) assignments
    routed: jax.Array     # scalar f32 — total (token, route) assignments (T*k)
    capacity: int


def init_moe_params(key, d_model: int, d_ff: int, n_experts_total: int,
                    n_local: int, dtype=jnp.float32) -> MoEParams:
    k1, k2, k3 = jax.random.split(key, 3)
    s_in = 1.0 / math.sqrt(d_model)
    s_out = 1.0 / math.sqrt(d_ff)
    return MoEParams(
        gate=(jax.random.normal(k1, (d_model, n_experts_total)) * s_in
              ).astype(dtype),
        w_in=(jax.random.normal(k2, (n_local, d_model, d_ff)) * s_in
              ).astype(dtype),
        w_out=(jax.random.normal(k3, (n_local, d_ff, d_model)) * s_out
               ).astype(dtype),
    )


def expert_capacity(tokens: int, n_experts: int, capacity_factor: float,
                    top_k: int = 1) -> int:
    """Per-expert slot count: ``ceil(tokens * top_k / n_experts * factor)``,
    clamped to at least 1 so a small ``capacity_factor`` (or tiny microbatch)
    can never round the buffer to zero slots and drop every token."""
    cap = int(math.ceil(tokens * top_k / n_experts * capacity_factor))
    return max(1, cap)


def top_k_routing(logits: jax.Array, capacity: int,
                  top_k: int = 1) -> RoutingInfo:
    """Top-k token→expert routing with capacity and drop accounting.

    Args:
      logits: (T, E) gating logits (any float dtype; softmax runs in fp32).
      capacity: static per-expert slot count (see :func:`expert_capacity`).
      top_k: routes per token.  Slots are filled greedily in gate-prob
        order; each route's combine weight is its raw softmax prob (the
        ``top_k=1`` case is exactly Switch Transformer semantics).

    Expert positions are assigned in token order, k-th choices after all
    (k-1)-th choices — an expert that overflows on earlier choices drops
    later ones, and the dropped count includes both.
    """
    t, e = logits.shape
    if top_k < 1 or top_k > e:
        raise ValueError(f"top_k must be in [1, {e}], got {top_k}")
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_p, top_i = lax.top_k(probs, top_k)                       # (T, k)

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    counts = jnp.zeros((e,), jnp.float32)    # slots claimed so far per expert
    kept = jnp.float32(0.0)
    for j in range(top_k):
        onehot = jax.nn.one_hot(top_i[:, j], e, dtype=jnp.float32)  # (T, E)
        position = jnp.cumsum(onehot, axis=0) - 1.0 + counts[None, :]
        keep = (position < capacity) & (onehot > 0)                 # (T, E)
        pos_cap = jax.nn.one_hot(position.astype(jnp.int32), capacity,
                                 dtype=jnp.float32) * keep[..., None]
        dispatch = dispatch + pos_cap
        combine = combine + pos_cap * top_p[:, j][:, None, None]
        kept = kept + jnp.sum(keep.astype(jnp.float32))
        counts = counts + jnp.sum(onehot, axis=0)

    routed = jnp.float32(t * top_k)
    dropped = routed - kept
    # GShard/Switch load-balancing loss: fraction-of-routes per expert
    # (pre-drop, so overflow pressure is visible) × mean gate prob, scaled
    # by E so a perfectly uniform router scores 1.0.
    frac = counts / routed
    mean_prob = jnp.mean(probs, axis=0)
    aux = jnp.float32(e) * jnp.sum(frac * mean_prob)
    return RoutingInfo(dispatch=dispatch, combine=combine, aux_loss=aux,
                       dropped=dropped, capacity=capacity)


def _all_to_all_wire(v: jax.Array, axis_name: str,
                     quant: Optional[QuantSpec]) -> jax.Array:
    """Exchange rows of ``v`` (leading dim = mesh axis size) over
    ``axis_name``, optionally on the block-scaled quantized wire.

    Each destination's chunk ``v[p]`` is quantized independently so the
    receiver can dequantize without cross-rank metadata: the int8/int4
    payload and the fp32 per-block scales travel as two all_to_alls —
    exactly the EQuARX first-pass wire.  Output is fp32.

    The primitive lives in ops/xla_collectives.py (the compiled-plane
    collective layer); this alias keeps the historical call site.
    """
    from ..ops import xla_collectives as XC
    return XC.all_to_all_wire(v, axis_name, quant)


def dispatch_wire_bytes(ep: int, n_local: int, capacity: int, d_model: int,
                        quant: Optional[QuantSpec] = None) -> int:
    """Analytic bytes one member puts on the wire for ONE dispatch (or
    combine) all_to_all.  Quantization is per destination chunk, so the
    quantized wire is ``ep`` independent payload+scales rows."""
    chunk = n_local * capacity * d_model
    if quant is None:
        return 4 * ep * chunk
    return ep * wire_bytes(chunk, quant)


def moe_layer(params: MoEParams, x: jax.Array, axis_name: str,
              capacity_factor: float = 1.25,
              activation: Callable = jax.nn.gelu,
              top_k: int = 1,
              quant: Optional[QuantSpec] = None,
              return_stats: bool = False):
    """Apply an expert-parallel MoE MLP to local tokens.

    Args:
      params: local shard of the MoE parameters (n_local experts held here).
      x: (tokens, d_model) local token activations.
      axis_name: the expert-parallel mesh axis (size P; total experts
        E = P * n_local).
      capacity_factor: slack over the uniform-routing slot count; capacity
        is clamped to >= 1 (see :func:`expert_capacity`).
      top_k: routes per token (1 = Switch semantics, the default).
      quant: optional block-scaled wire format for the two all_to_all
        exchanges; compute and combine stay fp32.
      return_stats: also return :class:`MoEStats` (aux loss, drop counts).

    Returns:
      (tokens, d_model) combined expert outputs (zeros for dropped tokens —
      add the residual in the caller), or ``(out, MoEStats)`` when
      ``return_stats`` is set.
    """
    ep = axis_size(axis_name)
    t, d = x.shape
    n_local = params.w_in.shape[0]
    n_experts = ep * n_local
    capacity = expert_capacity(t, n_experts, capacity_factor, top_k)

    logits = jnp.einsum("td,de->te", x, params.gate)
    route = top_k_routing(logits, capacity, top_k)

    # --- dispatch: (T,E,C) x (T,d) -> (E,C,d), exchange over experts ----
    x_send = jnp.einsum("tec,td->ecd", route.dispatch, x.astype(jnp.float32))
    x_send = x_send.reshape(ep, n_local, capacity, d)
    # all_to_all: dim0 indexes destination rank before, source rank after.
    x_recv = _all_to_all_wire(x_send, axis_name, quant)           # (P,L,C,d)
    tokens = x_recv.transpose(1, 0, 2, 3).reshape(
        n_local, ep * capacity, d)                                # (L,P*C,d)

    # --- expert MLPs (batched over local experts; big MXU matmuls) ------
    h = activation(jnp.einsum("lcd,ldf->lcf", tokens,
                              params.w_in.astype(jnp.float32)))
    y = jnp.einsum("lcf,lfd->lcd", h, params.w_out.astype(jnp.float32))

    # --- return route: reverse the exchange, combine (fp32 accumulate) --
    y = y.reshape(n_local, ep, capacity, d).transpose(1, 0, 2, 3)
    y_back = _all_to_all_wire(y, axis_name, quant)                # (P,L,C,d)
    y_back = y_back.reshape(n_experts, capacity, d)
    out = jnp.einsum("tec,ecd->td", route.combine, y_back)
    out = out.astype(x.dtype)
    if not return_stats:
        return out
    stats = MoEStats(aux_loss=route.aux_loss, dropped=route.dropped,
                     routed=jnp.float32(t * top_k), capacity=capacity)
    return out, stats


def moe_load_balancing_loss(x: jax.Array, gate: jax.Array,
                            n_experts: int) -> jax.Array:
    """Switch Transformer auxiliary load-balancing loss (mean over tokens of
    fraction-routed × mean-prob per expert, scaled by E)."""
    logits = jnp.einsum("td,de->te", x, gate)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)
    frac = jnp.mean(jax.nn.one_hot(expert_idx, n_experts), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    return n_experts * jnp.sum(frac * mean_prob)
