"""Expert parallelism — switch-style Mixture-of-Experts with all-to-all
token routing over a mesh axis.

The reference's only layout-shuffling primitive is alltoall with uneven
splits (operations.cc:1136-1198, SURVEY.md §2.3 "the only primitive that
would serve EP/SP-style layouts").  TPU-native, expert parallelism is a
first-class layer: top-1 gating with capacity, dispatch einsum into a
(experts, capacity, d) buffer — static shapes so XLA can tile the MXU — and
two ``lax.all_to_all`` exchanges riding ICI.  Dropped tokens (over capacity)
pass through on the residual path, standard Switch Transformer semantics.
"""

from __future__ import annotations

import math
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from ..compat import axis_size


class MoEParams(NamedTuple):
    gate: jax.Array    # (d_model, n_experts_total) — replicated
    w_in: jax.Array    # (n_local, d_model, d_ff)   — sharded over expert axis
    w_out: jax.Array   # (n_local, d_ff, d_model)   — sharded over expert axis


def init_moe_params(key, d_model: int, d_ff: int, n_experts_total: int,
                    n_local: int, dtype=jnp.float32) -> MoEParams:
    k1, k2, k3 = jax.random.split(key, 3)
    s_in = 1.0 / math.sqrt(d_model)
    s_out = 1.0 / math.sqrt(d_ff)
    return MoEParams(
        gate=(jax.random.normal(k1, (d_model, n_experts_total)) * s_in
              ).astype(dtype),
        w_in=(jax.random.normal(k2, (n_local, d_model, d_ff)) * s_in
              ).astype(dtype),
        w_out=(jax.random.normal(k3, (n_local, d_ff, d_model)) * s_out
               ).astype(dtype),
    )


def moe_layer(params: MoEParams, x: jax.Array, axis_name: str,
              capacity_factor: float = 1.25,
              activation: Callable = jax.nn.gelu) -> jax.Array:
    """Apply an expert-parallel MoE MLP to local tokens.

    Args:
      params: local shard of the MoE parameters (n_local experts held here).
      x: (tokens, d_model) local token activations.
      axis_name: the expert-parallel mesh axis (size P; total experts
        E = P * n_local).
    Returns:
      (tokens, d_model) combined expert outputs (zeros for dropped tokens —
      add the residual in the caller).
    """
    ep = axis_size(axis_name)
    t, d = x.shape
    n_local = params.w_in.shape[0]
    n_experts = ep * n_local
    capacity = max(1, int(math.ceil(t / n_experts * capacity_factor)))

    # --- top-1 gating with capacity ------------------------------------
    logits = jnp.einsum("td,de->te", x, params.gate)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                      # (T,)
    gate_prob = jnp.take_along_axis(probs, expert_idx[:, None],
                                    axis=-1)[:, 0]               # (T,)
    onehot = jax.nn.one_hot(expert_idx, n_experts,
                            dtype=jnp.float32)                   # (T, E)
    position = jnp.einsum("te,te->te", jnp.cumsum(onehot, axis=0) - 1.0,
                          onehot)
    keep = (position < capacity) & (onehot > 0)                  # (T, E)
    pos_cap = jax.nn.one_hot(position.astype(jnp.int32), capacity,
                             dtype=jnp.float32) * keep[..., None]
    dispatch = pos_cap                                            # (T, E, C)
    combine = dispatch * gate_prob[:, None, None]                 # (T, E, C)

    # --- dispatch: (T,E,C) x (T,d) -> (E,C,d), exchange over experts ----
    x_send = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    x_send = x_send.reshape(ep, n_local, capacity, d)
    # all_to_all: dim0 indexes destination rank before, source rank after.
    x_recv = lax.all_to_all(x_send, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)                          # (P,L,C,d)
    tokens = x_recv.transpose(1, 0, 2, 3).reshape(
        n_local, ep * capacity, d)                                # (L,P*C,d)

    # --- expert MLPs (batched over local experts; big MXU matmuls) ------
    h = activation(jnp.einsum("lcd,ldf->lcf", tokens,
                              params.w_in.astype(jnp.float32)))
    y = jnp.einsum("lcf,lfd->lcd", h, params.w_out.astype(jnp.float32))

    # --- return route: reverse the exchange, combine ---------------------
    y = y.reshape(n_local, ep, capacity, d).transpose(1, 0, 2, 3)
    y_back = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)                          # (P,L,C,d)
    y_back = y_back.reshape(n_experts, capacity, d)
    out = jnp.einsum("tec,ecd->td", combine, y_back)
    return out.astype(x.dtype)


def moe_load_balancing_loss(x: jax.Array, gate: jax.Array,
                            n_experts: int) -> jax.Array:
    """Switch Transformer auxiliary load-balancing loss (mean over tokens of
    fraction-routed × mean-prob per expert, scaled by E)."""
    logits = jnp.einsum("td,de->te", x, gate)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)
    frac = jnp.mean(jax.nn.one_hot(expert_idx, n_experts), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    return n_experts * jnp.sum(frac * mean_prob)
