"""Version-compat shims for the installed JAX.

``shard_map`` has moved twice upstream: ``jax.experimental.shard_map``
(<= 0.4.x, kwarg ``check_rep``) -> ``jax.shard_map`` (>= 0.5, kwarg
renamed to ``check_vma``).  Code in this repo is written against the
new spelling; this module exposes a ``shard_map`` that accepts the new
signature on every supported JAX and translates for old ones, so the
models, tests, and examples share one import site instead of each
guessing the installed version.
"""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map  # JAX >= 0.5: check_vma kwarg
    _NATIVE_CHECK_VMA = True
except ImportError:  # JAX <= 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map
    _NATIVE_CHECK_VMA = False


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis, inside ``shard_map``/``pmap``.

    ``jax.lax.axis_size`` only exists on newer JAX; older versions get
    the same static int from the constant-folding path of ``psum(1)``
    (a non-tracer operand is multiplied by the axis size eagerly).
    """
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def shard_map(f, mesh=None, in_specs=None, out_specs=None, **kwargs):
    """``jax.shard_map`` with the modern keyword surface on any JAX.

    On pre-0.5 JAX the replication-check kwarg was named ``check_rep``;
    a ``check_vma`` argument is translated so call sites never branch on
    the installed version.
    """
    if not _NATIVE_CHECK_VMA and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)
