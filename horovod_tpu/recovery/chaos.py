"""Deterministic chaos layer: seeded fault injection for recovery drills.

Real fleets lose ranks at random; CI must lose them *reproducibly*.
Every injection here is a pure function of the knobs — no wall clock, no
``random`` module state — so every incarnation of every rank (including
respawns after a kill) derives the identical schedule, and a failing
drill replays bit-for-bit from its seed.

Knobs (all ``HVD_TPU_CHAOS_*``; the layer is inert unless at least one
is set):

* ``CHAOS_SEED`` — the schedule seed.  :meth:`Chaos.kill_epoch` draws a
  deterministic kill step from it, so soak tests get a *seeded* schedule
  rather than a hardcoded one.
* ``CHAOS_KILL_STEPS`` — explicit ``"rank@step[,rank@step...]"`` kill
  schedule consumed by :meth:`Chaos.maybe_kill` (training loops call it
  once per step; the marked rank hard-exits mid-step).
* ``CHAOS_COMMIT_CRASH`` — ``"<point>[@step]"``: crash inside the commit
  window at a named point (``after_replicate`` — replica sent, disk not
  yet committed; ``pre_manifest`` — shards written, manifest not).
  Process-local one-shot: it fires once and disarms, so a respawned
  worker that replays the same step does not crash-loop (cross-respawn
  one-shotness is the caller's marker file, as in the churn soak).
* ``CHAOS_SLOW_PEER_MS`` — injected latency in the peer replica
  serving/push path (slow-peer drills).
* ``CHAOS_TORN_RANKS`` — comma list of ranks whose replica payloads are
  corrupted *after* checksumming (torn replication: the buddy's copy no
  longer matches what the owner committed; restore must detect and
  refuse it).
"""

from __future__ import annotations

import hashlib
import time
from typing import Dict, Optional, Set, Tuple


class ChaosKill(SystemExit):
    """A scheduled rank kill.  SystemExit subclass so an uninjected
    training loop dies (driver sees a worker failure — the drill) while
    tests can still catch it precisely."""


class ChaosCrash(RuntimeError):
    """A scheduled commit-window crash."""


def _cfg(name: str, default: Optional[str] = None) -> Optional[str]:
    from ..core.config import get_env
    return get_env(name, default)


def _parse_kills(spec: str) -> Dict[int, Set[int]]:
    """``"rank@step,..."`` → {rank: {steps}}.  Malformed entries are
    ignored (a typo'd drill knob must not take down a real job)."""
    out: Dict[int, Set[int]] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or "@" not in part:
            continue
        r, _, s = part.partition("@")
        try:
            out.setdefault(int(r), set()).add(int(s))
        except ValueError:
            continue
    return out


def _parse_crash(spec: str) -> Tuple[str, Optional[int]]:
    spec = (spec or "").strip()
    if not spec:
        return "", None
    point, _, step = spec.partition("@")
    try:
        return point, int(step) if step else None
    except ValueError:
        return point, None


class Chaos:
    """One parsed injection schedule.  Construct directly in tests;
    production code goes through the env-backed :func:`chaos`."""

    def __init__(self, seed: int = 0, kill_steps: str = "",
                 commit_crash: str = "", slow_peer_ms: float = 0.0,
                 torn_ranks: str = ""):
        self.seed = int(seed)
        self.kills = _parse_kills(kill_steps)
        self.crash_point, self.crash_step = _parse_crash(commit_crash)
        self.slow_peer_ms = float(slow_peer_ms)
        self.torn_ranks = {int(x) for x in torn_ranks.split(",")
                           if x.strip().lstrip("-").isdigit()}
        self._crash_armed = True

    @classmethod
    def from_env(cls) -> "Chaos":
        from ..core.config import get_float, get_int
        return cls(seed=get_int("CHAOS_SEED", 0),
                   kill_steps=_cfg("CHAOS_KILL_STEPS", "") or "",
                   commit_crash=_cfg("CHAOS_COMMIT_CRASH", "") or "",
                   slow_peer_ms=get_float("CHAOS_SLOW_PEER_MS", 0.0),
                   torn_ranks=_cfg("CHAOS_TORN_RANKS", "") or "")

    @property
    def enabled(self) -> bool:
        return bool(self.kills or self.crash_point or self.torn_ranks
                    or self.slow_peer_ms > 0 or self.seed)

    # -- seeded draws ------------------------------------------------------

    def draw(self, key: str, lo: int, hi: int) -> int:
        """Deterministic integer in ``[lo, hi)`` from ``(seed, key)`` —
        the schedule primitive.  sha256, not ``random``: identical on
        every platform and every incarnation."""
        if hi <= lo:
            return int(lo)
        h = hashlib.sha256(f"{self.seed}:{key}".encode()).digest()
        return lo + int.from_bytes(h[:8], "big") % (hi - lo)

    def kill_epoch(self, key: str, lo: int, hi: int) -> int:
        """A seeded kill step for the entity named ``key`` (a slot id, a
        rank) within a window — the churn soak's schedule source."""
        return self.draw(f"kill:{key}", lo, hi)

    # -- kill schedule -----------------------------------------------------

    def should_kill(self, rank: int, step: int) -> bool:
        return int(step) in self.kills.get(int(rank), ())

    def maybe_kill(self, rank: int, step: int, hard: bool = False):
        """Raise :class:`ChaosKill` (or ``os._exit(1)`` when ``hard`` —
        a crash no exception handler can absorb, the real-preemption
        shape) when the schedule marks this (rank, step)."""
        if not self.should_kill(rank, step):
            return
        if hard:
            import os
            os._exit(1)
        raise ChaosKill(f"chaos: scheduled kill of rank {rank} at "
                        f"step {step}")

    # -- commit-window crashes ---------------------------------------------

    def should_crash(self, point: str, step: Optional[int] = None) -> bool:
        if not self._crash_armed or self.crash_point != point:
            return False
        return self.crash_step is None or step is None \
            or int(step) == self.crash_step

    def maybe_crash(self, point: str, step: Optional[int] = None):
        if self.should_crash(point, step):
            self._crash_armed = False
            raise ChaosCrash(f"chaos: scheduled crash at commit point "
                             f"{point!r} (step {step})")

    # -- replication-path injections ---------------------------------------

    def torn(self, rank: int) -> bool:
        """True when ``rank``'s replica payload should be corrupted en
        route to its buddy (torn-replication drill)."""
        return int(rank) in self.torn_ranks

    def slow_peer(self) -> None:
        if self.slow_peer_ms > 0:
            time.sleep(self.slow_peer_ms / 1e3)


_chaos: Optional[Chaos] = None


def chaos() -> Chaos:
    """The process-wide schedule, parsed from env on first use."""
    global _chaos
    if _chaos is None:
        _chaos = Chaos.from_env()
    return _chaos


def reset_chaos() -> None:
    """Drop the cached schedule (tests that mutate CHAOS_* env)."""
    global _chaos
    _chaos = None
