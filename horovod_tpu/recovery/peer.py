"""Commit-time replication and restore-time peer reassembly.

Replication (:func:`replicate`, called from ``TpuState.commit``): the
host values the checkpoint engine extracted for the disk shards — the
exact bytes ``write_shard`` would encode — are placed twice: the owner's
copy into the local :mod:`store`, the buddy copy into its holder's
(same-process store in single-controller jobs; an HTTP push to the
holder's rendezvous-published replica endpoint otherwise).  Entries seal
(:func:`seal_commit`) only once the owner's commit fully lands, so the
peer tier inherits the engine's manifest-last invariant.

Peer restore (:func:`peer_restore`, tried by ``TpuState.sync`` before
the disk manifest): every member of the NEW world contributes its sealed
entries over one ``allgather_object`` — the same collective plane the
job already speaks, so a restore moves bytes over the fast wire, not the
filesystem.  The merged view must cover every rank of the old world at
one (step, world, fingerprint) with a valid checksum; anything less
(buddy pair died together, torn replication, empty stores after a full
relaunch) raises :class:`PeerRestoreUnavailable` and the caller falls
back to disk.  Reassembly reuses the checkpoint engine verbatim — an
in-memory :class:`~..checkpoint.engine.RestoredStep` over the gathered
shards, resharded N→M by the same arithmetic — so a peer restore is
bit-identical to restoring the same step from the disk manifest.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..debug import flight as _flight
from ..utils import logging as log
from . import buddy as B
# Direct-name imports: the package exports a `store()` accessor that
# shadows the submodule attribute, so `from . import store` could bind
# the function depending on import order.
from .store import ReplicaEntry, payload_checksum, verify_entry
from .store import store as _rstore


class PeerRestoreUnavailable(Exception):
    """The in-memory tier cannot cover the requested state; fall back
    to the disk manifest (or fresh init)."""


@dataclasses.dataclass
class RecoveryReport:
    """What the last restore decision did — surfaced in ``hvd.metrics``,
    flight events and hang reports so an operator can attribute a
    recovery to its path after the fact."""

    path: str                 # "peer" | "disk" | "none"
    key: str = ""
    step: Optional[int] = None
    world_from: Optional[int] = None
    world_to: Optional[int] = None
    bytes_moved: int = 0
    seconds: float = 0.0
    reason: str = ""          # why this path (e.g. the peer-miss cause)
    wall: float = 0.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


_report_lock = threading.Lock()
_last_report: Optional[RecoveryReport] = None


def record_report(report: RecoveryReport) -> RecoveryReport:
    global _last_report
    with _report_lock:
        _last_report = report
    return report


def last_report() -> Optional[RecoveryReport]:
    with _report_lock:
        return _last_report


def _registry():
    from ..metrics.registry import registry
    return registry()


def _stride() -> int:
    """Buddy ring stride: configured, else the local world size so a
    rank's replica lands on a DIFFERENT host (a whole-host preemption
    then kills no buddy pair) — 1 when topology is unknown."""
    from ..core.config import Config, get_int
    from ..core.state import global_state
    s = get_int("RECOVERY_STRIDE", Config.recovery_stride)
    if s > 0:
        return s
    return max(1, int(global_state.local_size or 1))


# ---------------------------------------------------------------------------
# Commit-time replication
# ---------------------------------------------------------------------------

def replicate(key: str, step: int, ext, extra: Optional[dict] = None,
              stride: Optional[int] = None, push: bool = True) -> int:
    """Place one commit's payloads (an ``ExtractedState`` from
    ``checkpoint.zero.extract_zero_state``) into the replica tier:
    own copies locally, buddy copies with their holders.  Returns the
    bytes replicated.  Entries are PENDING until :func:`seal_commit`."""
    from ..checkpoint import manifest as M
    from ..checkpoint.zero import fingerprint_extra

    stride = _stride() if stride is None else int(stride)
    manifest = M.Manifest(step=int(step), world_size=ext.world,
                          leaves=ext.specs,
                          extra=fingerprint_extra(ext, extra))
    mjson = manifest.to_json()
    st = _rstore()
    reg = _registry()
    total = 0
    remote_pushed = 0
    for rank, values in sorted(ext.rank_values.items()):
        arrays = {spec.key: v for spec, v in zip(ext.specs, values)
                  if v is not None}
        entry = ReplicaEntry(
            key=key, rank=int(rank), step=int(step), world=ext.world,
            fingerprint=ext.fingerprint, manifest_json=mjson,
            arrays=arrays, checksum=payload_checksum(arrays))
        st.put_own(entry)
        total += entry.nbytes()
        holder = B.replica_holder(rank, ext.world, stride)
        if holder is None:
            continue
        if holder in ext.rank_values:
            # The holder's store IS this process's store (always true in
            # single-controller jobs, where every rank is addressable).
            st.put_held(entry)
        elif push:
            from . import transport as T
            addr = T.lookup_addr(holder)
            if addr is not None and T.push_replica(addr, entry):
                remote_pushed += 1
            else:
                reg.counter("hvd_recovery_push_failures_total",
                            "Replica pushes that never reached the "
                            "buddy").inc()
                log.warning(
                    "recovery: replica push rank %d -> holder %d failed"
                    " (peer tier degraded for this rank at step %d)",
                    rank, holder, step)
    reg.counter("hvd_recovery_replications_total",
                "Commit-time replica placements").inc()
    reg.counter("hvd_recovery_replica_bytes_total",
                "Bytes placed in the replica tier").inc(total)
    reg.gauge("hvd_recovery_store_bytes",
              "Resident bytes in the local replica store").set(
        st.total_bytes())
    _flight.record("recovery.replicate", key, step=int(step),
                   world=ext.world, bytes=total, stride=stride,
                   remote_pushed=remote_pushed)
    return total


def seal_commit(key: str, step: int, ext=None,
                stride: Optional[int] = None, push: bool = True) -> None:
    """Two-phase marker: the owner's commit fully landed — promote the
    pending entries (local store + any remote holders)."""
    _rstore().seal(key, int(step))
    if ext is None or not push:
        return
    stride = _stride() if stride is None else int(stride)
    from . import transport as T
    for rank in sorted(ext.rank_values):
        holder = B.replica_holder(rank, ext.world, stride)
        if holder is None or holder in ext.rank_values:
            continue
        addr = T.lookup_addr(holder)
        if addr is not None:
            T.push_seal(addr, key, int(step))


# ---------------------------------------------------------------------------
# Restore-time peer reassembly
# ---------------------------------------------------------------------------

def _gather_entries(key: str) -> List[ReplicaEntry]:
    """Every member's sealed contribution, merged, over the CURRENT
    world (degrades to the local store's view in single-process jobs).

    Two-phase to keep the wire at ~1x the state: owner payloads first
    (every member needs every shard to rebuild the full buffers
    regardless), then buddy copies ONLY for (step, world, rank)
    positions no surviving owner covered — in the common single-rank-
    loss case that second gather moves one shard, not a duplicate of
    the whole state.  Both gathers run unconditionally on every member
    and filter on the (identical) phase-one result, so the fleet stays
    collective-consistent.  Owner copies never transit a transfer, so
    preferring them also minimizes torn-copy exposure."""
    from ..optimizers import allgather_object
    own_local = _rstore().contribution(key, role="own")
    gathered = allgather_object(own_local, name="recovery.peer.gather")
    own = [e for contrib in gathered for e in contrib]
    covered = {(e.step, e.world, e.fingerprint, e.rank) for e in own}
    held_local = [e for e in _rstore().contribution(key, role="held")
                  if (e.step, e.world, e.fingerprint, e.rank)
                  not in covered]
    gathered_held = allgather_object(held_local,
                                     name="recovery.peer.gather_held")
    return own + [e for contrib in gathered_held for e in contrib]


def _coverage(entries: List[ReplicaEntry], reg) -> Tuple[
        Dict[Tuple[int, int, str], Dict[int, ReplicaEntry]], int]:
    """Group valid entries by (step, world, fingerprint); first copy per
    rank wins (owner copies sort first in each contribution).  Returns
    the groups and the number of torn copies detected."""
    groups: Dict[Tuple[int, int, str], Dict[int, ReplicaEntry]] = {}
    torn = 0
    for e in entries:
        if not verify_entry(e):
            torn += 1
            reg.counter("hvd_recovery_torn_replicas_total",
                        "Replica copies failing checksum verification"
                        ).inc()
            log.warning(
                "recovery: torn replica detected (key=%s rank=%d "
                "step=%d) — copy excluded from coverage", e.key, e.rank,
                e.step)
            continue
        g = groups.setdefault((e.step, e.world, e.fingerprint), {})
        g.setdefault(e.rank, e)
    return groups, torn


def peer_restore(key: str, like, mesh=None,
                 axis_name: Optional[str] = None,
                 step: Optional[int] = None):
    """Rebuild ``like``'s state for the CURRENT world from the fleet's
    replica memory.  ``step`` pins the commit to restore (the elastic
    sync path passes its agreed committed step); None takes the newest
    fully covered one.  Returns ``(state, manifest_extra, report)`` or
    raises :class:`PeerRestoreUnavailable` with the coverage reason.

    Collective: every member of the current world must call this (the
    gather runs on the collective plane), and with the same ``step`` —
    the elastic sync path guarantees both.
    """
    from ..checkpoint import engine as E
    from ..checkpoint import zero as Z

    reg = _registry()
    t0 = time.perf_counter()
    _flight.record("recovery.restore.begin", key,
                   step=step if step is None else int(step))
    entries = _gather_entries(key)

    if mesh is None:
        from ..core import basics
        mesh = basics.mesh()
    ax = Z._default_axis(axis_name)
    world_new = Z._axis_world(mesh, ax)

    # Replicas of a DIFFERENT run (another structure sharing this
    # process's store) are a miss, not an error: filter on the restore
    # target's world-size-invariant fingerprint before voting, the same
    # cross-run guard the disk engine applies — with the same
    # HVD_TPU_CKPT_ALLOW_FOREIGN escape hatch.
    from ..checkpoint import manifest as M
    target_plans, _, _ = Z._plan_tree(like, max(1, world_new),
                                      validate=False)
    target_fp = M.spec_fingerprint([p.spec for p in target_plans])
    foreign = 0
    if not Z._foreign_allowed():
        matched = [e for e in entries if e.fingerprint == target_fp]
        foreign = len(entries) - len(matched)
        entries = matched
    groups, torn = _coverage(entries, reg)

    covered = {g: ranks for g, ranks in groups.items()
               if set(ranks) >= set(range(g[1]))}
    chosen = None
    if step is not None:
        for g in covered:
            if g[0] == int(step):
                chosen = g
                break
    elif covered:
        chosen = max(covered, key=lambda g: g[0])
    if chosen is None:
        if not entries:
            reason = "no sealed replicas in fleet memory (fresh " \
                     "relaunch or replication disabled)"
            if foreign:
                reason += f"; {foreign} foreign-run entries ignored"
        else:
            newest = max(groups, key=lambda g: g[0], default=None)
            want = int(step) if step is not None else \
                (newest[0] if newest else -1)
            missing = []
            for g, ranks in groups.items():
                if g[0] == want:
                    missing = sorted(set(range(g[1])) - set(ranks))
                    break
            reason = (f"coverage gap at step {want}: missing old-world "
                      f"ranks {missing} (buddy pair lost together)"
                      if missing else
                      f"no replica group covers step {want}")
            if torn:
                reason += f"; {torn} torn cop{'y' if torn == 1 else 'ies'}" \
                          " excluded"
        reg.counter("hvd_recovery_restores_total",
                    "Recovery restore decisions by path",
                    path="peer_miss").inc()
        _flight.record("recovery.restore.miss", key, reason=reason)
        raise PeerRestoreUnavailable(reason)

    ranks = covered[chosen]
    step_c, world_old, _fp = chosen
    manifest = _manifest_of(ranks[0])
    shards = [ranks[r].arrays for r in range(world_old)]
    restored = E.RestoredStep(manifest, shards, world_new)
    state = Z.rebuild_restored(restored, like)
    bytes_moved = sum(ranks[r].nbytes() for r in range(world_old))
    dt = time.perf_counter() - t0
    report = record_report(RecoveryReport(
        path="peer", key=key, step=step_c, world_from=world_old,
        world_to=world_new, bytes_moved=bytes_moved, seconds=dt,
        reason="full coverage in fleet memory", wall=time.time()))
    reg.counter("hvd_recovery_restores_total",
                "Recovery restore decisions by path", path="peer").inc()
    reg.counter("hvd_recovery_restore_bytes_total",
                "Bytes reassembled from the replica tier").inc(
        bytes_moved)
    reg.gauge("hvd_recovery_restore_seconds",
              "Duration of the last recovery restore").set(dt)
    _flight.record("recovery.restore.done", key, path="peer",
                   step=step_c, world_from=world_old,
                   world_to=world_new, bytes=bytes_moved)
    log.info("recovery: peer-restored %s step %d (world %d -> %d, "
             "%.1f MB in %.3f s)", key, step_c, world_old, world_new,
             bytes_moved / 1e6, dt)
    return state, dict(manifest.extra), report


def _manifest_of(entry: ReplicaEntry):
    from ..checkpoint import manifest as M
    return M.Manifest.from_json(entry.manifest_json)
