"""Replica transport: per-rank HTTP endpoints on the shared
``BackgroundHTTPServer`` scaffold (the rendezvous/metrics/debug serving
idiom), published to the rendezvous KV as ``recovery/replica_addr_<rank>``.

* ``PUT /recovery/replica`` — receive a buddy's pushed payload
  (commit-time replication; the body is :func:`store.entry_to_bytes`).
* ``PUT /recovery/seal/<key>/<step>`` — the owner's commit-completed
  marker for its pushed payloads (two-phase: a payload is never served
  until sealed).
* ``GET /recovery/replica/<key>/<rank>`` — serve a sealed entry
  (operator tooling / targeted fetches; the elastic peer-restore path
  itself gathers over the collective plane, which every member already
  speaks).
* ``PUT /recovery/kv/<key>`` / ``GET /recovery/kv/<key>`` — one-shot
  mailbox for serving-plane KV-page migration bundles (disaggregated
  prefill/decode, ``serving/disagg.py``): the prefill replica PUTs an
  encoded bundle, the decode replica GETs it — the GET *pops* (a
  bundle is adopted exactly once), and the mailbox is bounded
  (:data:`_KV_MAILBOX_CAP` bundles, oldest dropped loudly) so a
  crashed consumer cannot OOM the producer's transport.
* ``GET /healthz`` — liveness.

Requests are HMAC-gated with the launch secret exactly like the debug
endpoints — replica payloads are raw optimizer state, nothing a stranger
on the network should read or write.  The slow-peer chaos knob
(``HVD_TPU_CHAOS_SLOW_PEER_MS``) injects its latency in the handlers, so
drills exercise the same code path a congested host would.
"""

from __future__ import annotations

import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

# Direct-name imports: the package exports a `store()` accessor that
# shadows the submodule attribute, so `from . import store` would bind
# the function here, not the module.
from .store import ReplicaEntry, entry_from_bytes, entry_to_bytes
from .store import store as _store
from .chaos import chaos

_SCOPE = "recovery"

# KV-migration mailbox: key -> encoded bundle, insertion-ordered so
# overflow drops the OLDEST (its producer will retry or time out
# loudly; silently dropping the newest would starve fresh handoffs
# behind abandoned ones).
_KV_MAILBOX_CAP = 64
_kv_mailbox: "dict[str, bytes]" = {}
_kv_lock = threading.Lock()


def _authorized(headers, method: str, key: str,
                body: bytes = b"") -> bool:
    """``key`` is the FULL resource path after the scope and ``body``
    the payload — both are signed, so a captured signature authorizes
    exactly one request, never a forged payload or another resource."""
    from ..runner.rendezvous import request_authorized
    return request_authorized(headers, method, _SCOPE, key, body)


def _sign(req, method: str, key: str, body: bytes = b"") -> None:
    from ..runner.rendezvous import sign_request
    sign_request(req, method, _SCOPE, key, body)


class _RecoveryHandler(BaseHTTPRequestHandler):
    server_version = "hvd_tpu_recovery"

    def log_message(self, fmt, *args):  # silence request logging
        pass

    def _send(self, code: int, body: bytes = b"",
              ctype: str = "application/octet-stream"):
        self.send_response(code)
        if body:
            self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def do_PUT(self):
        parts = self.path.strip("/").split("/")
        chaos().slow_peer()
        if parts[:2] == [_SCOPE, "replica"] and len(parts) == 2:
            length = int(self.headers.get("Content-Length", 0))
            payload = self.rfile.read(length)
            if not _authorized(self.headers, "PUT", "replica", payload):
                return self._send(403)
            try:
                entry = entry_from_bytes(payload)
            except Exception:  # noqa: BLE001 — a torn PUT must not kill
                return self._send(400)
            _store().put_held(entry)
            return self._send(200)
        if parts[:2] == [_SCOPE, "seal"] and len(parts) == 4:
            if not _authorized(self.headers, "PUT",
                               "/".join(parts[1:])):
                return self._send(403)
            try:
                _store().seal(parts[2], int(parts[3]))
            except ValueError:
                return self._send(400)
            return self._send(200)
        if parts[:2] == [_SCOPE, "kv"] and len(parts) == 3:
            length = int(self.headers.get("Content-Length", 0))
            payload = self.rfile.read(length)
            if not _authorized(self.headers, "PUT",
                               f"kv/{parts[2]}", payload):
                return self._send(403)
            with _kv_lock:
                while len(_kv_mailbox) >= _KV_MAILBOX_CAP:
                    dropped = next(iter(_kv_mailbox))
                    del _kv_mailbox[dropped]
                    from ..utils import logging as log
                    log.warning(
                        "recovery: kv mailbox full — dropped oldest "
                        "bundle %s", dropped)
                _kv_mailbox[parts[2]] = payload
            return self._send(200)
        self._send(404)

    def do_GET(self):
        parts = self.path.strip("/").split("/")
        if parts == ["healthz"]:
            return self._send(200, b"ok", ctype="text/plain")
        chaos().slow_peer()
        if parts[:2] == [_SCOPE, "replica"] and len(parts) == 4:
            if not _authorized(self.headers, "GET",
                               "/".join(parts[1:])):
                return self._send(403)
            try:
                entry = _store().get(parts[2], int(parts[3]))
            except ValueError:
                return self._send(400)
            if entry is None or not entry.sealed:
                return self._send(404)
            return self._send(200, entry_to_bytes(entry))
        if parts[:2] == [_SCOPE, "kv"] and len(parts) == 3:
            if not _authorized(self.headers, "GET", f"kv/{parts[2]}"):
                return self._send(403)
            with _kv_lock:
                blob = _kv_mailbox.pop(parts[2], None)
            if blob is None:
                return self._send(404)
            return self._send(200, blob)
        self._send(404)


class _RecoveryHTTPServer(ThreadingHTTPServer):
    daemon_threads = True


class RecoveryServer:
    """Replica endpoints on a background daemon thread."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        from ..runner.rendezvous import BackgroundHTTPServer
        self._impl = BackgroundHTTPServer(
            _RecoveryHTTPServer((host, port), _RecoveryHandler))

    @property
    def port(self) -> int:
        return self._impl.port

    def start(self) -> int:
        return self._impl.start()

    def stop(self) -> None:
        self._impl.stop()


_serve_lock = threading.Lock()
_server: Optional[RecoveryServer] = None


def serve(port: int = 0, host: str = "0.0.0.0") -> RecoveryServer:
    """Start (or return) the module-level replica endpoint — idempotent
    so elastic re-``init()`` keeps one server across rounds."""
    global _server
    with _serve_lock:
        if _server is None:
            s = RecoveryServer(host=host, port=port)
            s.start()
            _server = s
        return _server


def stop_serving() -> None:
    global _server
    with _serve_lock:
        if _server is not None:
            _server.stop()
            _server = None


def replica_addr_key(rank: int) -> str:
    return f"replica_addr_{int(rank)}"


def serve_and_publish(rank: int, rdv_addr: Optional[str] = None,
                      port: int = 0) -> Optional[str]:
    """Start the replica endpoint and publish its ``host:port`` under
    ``recovery/replica_addr_<rank>`` on the rendezvous KV, so buddies
    can push and operators can fetch.  Returns the published address
    (None when no rendezvous address is known)."""
    rdv_addr = rdv_addr or os.environ.get("HVD_TPU_RENDEZVOUS_ADDR")
    s = serve(port=port)
    if rdv_addr is None:
        return None
    from ..runner.rendezvous import advertised_host, http_put
    addr = f"{advertised_host()}:{s.port}"
    http_put(rdv_addr, _SCOPE, replica_addr_key(rank), addr.encode())
    return addr


def lookup_addr(rank: int, rdv_addr: Optional[str] = None,
                timeout: float = 3.0) -> Optional[str]:
    """Buddy endpoint lookup via the shared KV poller (hvd.net.poll_kv —
    the same deadline-bounded loop the elastic worker uses), so a
    transient rendezvous fault during a commit window retries instead of
    silently degrading the peer tier."""
    rdv_addr = rdv_addr or os.environ.get("HVD_TPU_RENDEZVOUS_ADDR")
    if not rdv_addr:
        return None
    from .. import net as _net
    try:
        raw = _net.poll_kv(rdv_addr, _SCOPE, replica_addr_key(rank),
                           deadline_s=timeout, interval_s=0.2,
                           timeout_s=timeout)
    except (_net.DeadlineExceeded, PermissionError):
        return None
    return raw.decode() if raw else None


def _push_retry_policy():
    """Replica pushes get exactly ONE bounded retry within the commit
    window (the satellite contract): a transient fault must not leave a
    rank uncovered until the next commit, but the commit latency budget
    cannot absorb a long ladder."""
    import dataclasses
    from .. import net as _net
    return dataclasses.replace(_net.Policy.from_env(), attempts=2)


def _request(addr: str, path: str, method: str, sig_key: str,
             body: Optional[bytes] = None, timeout: float = 5.0) -> bool:
    import urllib.request
    from .. import net as _net
    from ..metrics.registry import registry as _reg
    req = urllib.request.Request(f"http://{addr}{path}", data=body,
                                 method=method)
    _sign(req, method, sig_key, body or b"")
    attempts = {"n": 0}

    def run() -> bytes:
        attempts["n"] += 1
        return _net.request_bytes(
            req, timeout=timeout, name=f"recovery.{method.lower()}",
            policy=_net.Policy(attempts=1))

    try:
        _net.retry_call(run, policy=_push_retry_policy(),
                        name=f"recovery.{sig_key}")
        if attempts["n"] > 1:
            _reg().counter(
                "hvd_recovery_push_retries_total",
                "Replica pushes that succeeded only on a retry").inc()
        return True
    except OSError:
        return False


def push_replica(addr: str, entry: ReplicaEntry,
                 timeout: float = 5.0) -> bool:
    """PUT one payload to a buddy's replica endpoint (best-effort with
    one bounded retry: a transiently failed push is re-sent within the
    commit window and counted in hvd_recovery_push_retries_total; only
    a persistent failure degrades the peer tier for that rank)."""
    return _request(addr, f"/{_SCOPE}/replica", "PUT", "replica",
                    body=entry_to_bytes(entry), timeout=timeout)


def push_seal(addr: str, key: str, step: int,
              timeout: float = 5.0) -> bool:
    return _request(addr, f"/{_SCOPE}/seal/{key}/{int(step)}", "PUT",
                    f"seal/{key}/{int(step)}", body=b"",
                    timeout=timeout)


def fetch_replica(addr: str, key: str, rank: int,
                  timeout: float = 5.0) -> Optional[ReplicaEntry]:
    """GET one sealed entry from a peer's endpoint; None when absent or
    unreachable.  Transport faults ride the hvd.net retry ladder; a 404
    (entry genuinely absent) does not."""
    import urllib.error
    import urllib.request
    from .. import net as _net
    req = urllib.request.Request(
        f"http://{addr}/{_SCOPE}/replica/{key}/{int(rank)}")
    _sign(req, "GET", f"replica/{key}/{int(rank)}")
    try:
        body = _net.request_bytes(req, timeout=timeout,
                                  name="recovery.fetch")
        return entry_from_bytes(body)
    except (urllib.error.HTTPError, OSError, ValueError):
        return None


def push_kv(addr: str, key: str, blob: bytes,
            timeout: float = 10.0) -> bool:
    """PUT one KV-migration bundle into a peer's one-shot mailbox
    (serving-plane page handoff).  Rides the same signed request +
    bounded-retry ladder as replica pushes."""
    return _request(addr, f"/{_SCOPE}/kv/{key}", "PUT", f"kv/{key}",
                    body=blob, timeout=timeout)


def fetch_kv(addr: str, key: str,
             timeout: float = 10.0) -> Optional[bytes]:
    """GET (and consume — the server pops) one KV-migration bundle;
    None when absent or unreachable."""
    import urllib.error
    import urllib.request
    from .. import net as _net
    req = urllib.request.Request(f"http://{addr}/{_SCOPE}/kv/{key}")
    _sign(req, "GET", f"kv/{key}")
    try:
        return _net.request_bytes(req, timeout=timeout,
                                  name="recovery.fetch_kv")
    except (urllib.error.HTTPError, OSError):
        return None
