"""Buddy topology for pairwise shard replication.

The assignment is a ring shift: rank *r*'s committed shard is replicated
into the memory of ``replica_holder(r) = (r + 1) % world`` (its "buddy").
A ring — rather than disjoint pairs — works for every world size
including odd ones, spreads the replication traffic evenly (each rank
sends one shard and receives one), and gives the failure matrix a clean
shape:

* a **single rank** dies → its shard survives in its buddy's memory and
  the whole old world is still collectively reconstructible;
* two **adjacent** ranks die (*r* and ``(r+1) % world`` — a "buddy
  pair", e.g. both slots of one preempted host when ranks are placed
  contiguously) → rank *r*'s shard is gone from memory and recovery
  falls back to the disk manifest;
* two **non-adjacent** ranks die → both shards survive (each buddy is
  still alive) and the peer path still covers the full old world.

Placement caveat the docs spell out: contiguous rank placement puts a
host's ranks next to each other on the ring, so a whole-host loss kills
buddy pairs.  ``replica_holder(r, world, stride=local_size)`` shifts by
the local world size instead, pushing every buddy onto a *different*
host — then only a correlated two-HOST loss forces the disk fallback.

Everything here is pure integer arithmetic — golden-tested, shared by
the commit-time replicator and the restore-time coverage check.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def replica_holder(rank: int, world: int, stride: int = 1) -> Optional[int]:
    """The rank that holds ``rank``'s replica (its buddy), or None when
    the world is too small to replicate (world 1, or a stride that maps
    every rank onto itself)."""
    if world <= 1:
        return None
    stride = max(1, int(stride)) % world
    if stride == 0:
        stride = 1
    holder = (int(rank) + stride) % world
    return None if holder == int(rank) else holder


def replica_held(rank: int, world: int, stride: int = 1) -> Optional[int]:
    """The rank whose replica ``rank`` holds — the inverse of
    :func:`replica_holder`."""
    if world <= 1:
        return None
    stride = max(1, int(stride)) % world
    if stride == 0:
        stride = 1
    held = (int(rank) - stride) % world
    return None if held == int(rank) else held


def buddy_map(world: int, stride: int = 1) -> Dict[int, Optional[int]]:
    """{rank: replica_holder(rank)} for the whole world."""
    return {r: replica_holder(r, world, stride) for r in range(world)}


def uncovered_ranks(dead: List[int], world: int,
                    stride: int = 1) -> List[int]:
    """Old-world ranks whose shard survives in NO live memory after the
    ranks in ``dead`` die: the rank itself is dead AND so is its buddy.
    Empty list == the peer path can still reconstruct the full state."""
    gone = set(int(d) for d in dead)
    out = []
    for r in sorted(gone):
        holder = replica_holder(r, world, stride)
        if holder is None or holder in gone:
            out.append(r)
    return out
