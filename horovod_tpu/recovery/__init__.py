"""Peer-to-peer hot recovery — the in-memory tier between a failure and
the disk manifest.

At production scale MTBF makes restart latency a first-order throughput
term: the classic elastic design (arXiv:1802.05799) restores every
resize from the last committed step on disk.  But with per-rank ZeRO
shards already partitioned across the fleet (arXiv:2004.13336), the
surviving ranks collectively hold everything a replacement needs.  This
package keeps it that way on purpose:

* :mod:`buddy` — the pairwise ring: rank *r*'s committed shard is
  replicated into ``replica_holder(r)``'s memory, stride-shifted so
  buddies land on different hosts;
* :mod:`store` — the per-process replica memory (sealed/pending
  two-phase entries, checksummed payloads);
* :mod:`peer` — commit-time replication and the restore-time peer
  reassembly ``TpuState.sync`` tries before touching disk;
* :mod:`transport` — the rendezvous-published HTTP replica endpoints
  buddy pushes ride between processes;
* :mod:`chaos` — deterministic fault injection (seeded kills,
  commit-window crashes, slow peers, torn replication) so the recovery
  paths are *drilled*, not assumed.

Decision visibility: every restore records a :class:`RecoveryReport`
(path peer/disk/none, bytes, latency) into ``hvd.metrics``, the flight
recorder, and — via ``debug/hang.py`` — hang reports.

See ``docs/recovery.md`` for the failure matrix and knob table.
"""

from .buddy import buddy_map, replica_held, replica_holder, uncovered_ranks
from .chaos import Chaos, ChaosCrash, ChaosKill, chaos, reset_chaos
from .peer import (
    PeerRestoreUnavailable, RecoveryReport, last_report, peer_restore,
    record_report, replicate, seal_commit,
)
from .store import (
    ReplicaEntry, ReplicaStore, entry_from_bytes, entry_to_bytes,
    payload_checksum, reset_store, store, verify_entry,
)
from . import transport

__all__ = [
    "buddy_map", "replica_held", "replica_holder", "uncovered_ranks",
    "Chaos", "ChaosCrash", "ChaosKill", "chaos", "reset_chaos",
    "PeerRestoreUnavailable", "RecoveryReport", "last_report",
    "peer_restore", "record_report", "replicate", "seal_commit",
    "ReplicaEntry", "ReplicaStore", "entry_from_bytes", "entry_to_bytes",
    "payload_checksum", "reset_store", "store", "verify_entry",
    "transport",
]
