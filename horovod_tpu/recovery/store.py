"""In-memory replica store: the RAM tier committed shards live in.

Every rank keeps two kinds of entries, one per ``(tree key, old rank)``:

* **own** — the bytes this rank itself committed (its shard of each
  ZeRO tree plus the manifest that describes them), kept so a surviving
  rank can serve *itself* during a peer restore without touching disk;
* **held** — the buddy copy: the same payload for the rank whose
  replica this rank holds (``buddy.replica_held``), received at commit
  time over the replication path.

Entries carry the full commit identity (step, old world size, run
fingerprint, manifest JSON) plus a content checksum computed by the
*owner* before the payload leaves its process — a buddy copy that was
torn in flight (chaos drill: ``HVD_TPU_CHAOS_TORN_RANKS``) fails
verification at restore time and is treated as absent, never silently
restored.

Two-phase commit marker: entries are stored **unsealed** when the
payload arrives and **sealed** only after the owner's full commit
completed (disk manifest + in-memory snapshot).  The peer-restore
coverage check only counts sealed entries, so a rank that died *inside*
its commit window cannot contribute a half-committed step — the exact
invariant the disk engine's manifest-last protocol provides, replayed
in memory.

Arrays are held decoded (numpy views of the extracted host values, the
same bytes ``write_shard`` would encode), so a peer restore is pure
memory traffic — no npz decode, no file IO.  The wire form
(:func:`entry_to_bytes`) is npz + a JSON header, used by the HTTP
transport between processes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import buddy as B
from .chaos import chaos


@dataclasses.dataclass
class ReplicaEntry:
    """One rank's committed payload for one tree key."""

    key: str                  # tree key ("opt_state", "params", ...)
    rank: int                 # old-world rank whose shard this is
    step: int
    world: int                # world size at commit
    fingerprint: str          # run fingerprint (leaf-spec sha256)
    manifest_json: str        # the step's manifest (specs + extra)
    arrays: Dict[str, np.ndarray]
    checksum: str
    sealed: bool = False

    def nbytes(self) -> int:
        return sum(int(a.nbytes) for a in self.arrays.values())


def payload_checksum(arrays: Dict[str, np.ndarray]) -> str:
    """Content hash of a payload: key order, dtype, shape and bytes per
    array.  Stamped by the owner before the payload leaves its process;
    verified before any restore uses a copy."""
    h = hashlib.sha256()
    for k in sorted(arrays):
        a = np.ascontiguousarray(arrays[k])
        h.update(f"{k}|{a.dtype}|{a.shape}\n".encode())
        h.update(a.tobytes())
    return h.hexdigest()


def verify_entry(entry: ReplicaEntry) -> bool:
    return payload_checksum(entry.arrays) == entry.checksum


def entry_to_bytes(entry: ReplicaEntry) -> bytes:
    """Wire form: JSON header line + npz payload (the transport and the
    allgather both move this)."""
    head = json.dumps({
        "key": entry.key, "rank": entry.rank, "step": entry.step,
        "world": entry.world, "fingerprint": entry.fingerprint,
        "manifest_json": entry.manifest_json,
        "checksum": entry.checksum, "sealed": entry.sealed,
    }).encode()
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in entry.arrays.items()})
    return len(head).to_bytes(8, "big") + head + buf.getvalue()


def entry_from_bytes(data: bytes) -> ReplicaEntry:
    n = int.from_bytes(data[:8], "big")
    meta = json.loads(data[8:8 + n].decode())
    with np.load(io.BytesIO(data[8 + n:])) as z:
        arrays = {k: z[k] for k in z.files}
    return ReplicaEntry(arrays=arrays, **meta)


class _Slot:
    """One ``(key, rank)`` position: the last sealed (restorable) entry
    plus at most one pending (committed-but-not-yet-sealed) entry.  The
    previous sealed entry survives until the NEXT one seals, so a crash
    inside the commit window never costs the peer tier its last good
    step."""

    __slots__ = ("sealed", "pending")

    def __init__(self):
        self.sealed: Optional[ReplicaEntry] = None
        self.pending: Optional[ReplicaEntry] = None


class ReplicaStore:
    """Process-local replica memory.  In multi-controller jobs each
    process stores its own ranks' entries plus the buddy copies pushed
    to it; in single-controller jobs (one process is every rank) it
    holds the whole fleet's — which is exactly what lets the fast tests
    drill rank death by dropping entries."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tables: Dict[str, Dict[Tuple[str, int], _Slot]] = {
            "own": {}, "held": {}}

    def _slot(self, role: str, key: str, rank: int) -> _Slot:
        table = self._tables[role]
        k = (key, int(rank))
        if k not in table:
            table[k] = _Slot()
        return table[k]

    # -- writes ------------------------------------------------------------

    def put_own(self, entry: ReplicaEntry) -> None:
        with self._lock:
            self._put(self._slot("own", entry.key, entry.rank), entry)

    def put_held(self, entry: ReplicaEntry) -> None:
        """Store a buddy copy.  The torn-replication chaos drill
        corrupts the copy here — after the owner checksummed it, the
        way a real torn transfer would."""
        if chaos().torn(entry.rank) and entry.arrays:
            arrays = {k: np.array(v, copy=True)
                      for k, v in entry.arrays.items()}
            for k in sorted(arrays):
                a = arrays[k]
                if not a.size:
                    continue
                # Flip the first payload byte (byte-level, so any
                # dtype/shape — including 0-d scalars — tears).
                raw = np.frombuffer(a.tobytes(), np.uint8).copy()
                raw[0] ^= 0xFF
                arrays[k] = np.frombuffer(
                    raw.tobytes(), a.dtype).reshape(a.shape)
                break
            entry = dataclasses.replace(entry, arrays=arrays)
        with self._lock:
            self._put(self._slot("held", entry.key, entry.rank), entry)

    @staticmethod
    def _put(slot: _Slot, entry: ReplicaEntry) -> None:
        # An entry that arrives already sealed (a fetch-based repair of
        # a committed step) lands directly in the sealed position.
        if entry.sealed:
            slot.sealed, slot.pending = entry, None
        else:
            slot.pending = entry

    def seal(self, key: str, step: int) -> None:
        """Promote pending entries of ``(key, step)`` to sealed — the
        owner's commit fully landed.  Sealing also prunes slots for
        ranks OUTSIDE the sealed world (a superseded larger world's
        tail ranks): a stale world must not win a later coverage vote.

        In-world slots that have nothing at this step are deliberately
        LEFT ALONE — a buddy's push may still be in flight (or have
        failed, a counted non-fatal degrade), and dropping its older
        sealed copy would destroy the fleet's only redundancy for that
        rank.  Worst case one stale entry lingers per slot until the
        next successful put."""
        step = int(step)
        with self._lock:
            world = None
            for table in self._tables.values():
                for k in list(table):
                    if k[0] != key:
                        continue
                    slot = table[k]
                    if slot.pending is not None and \
                            slot.pending.step == step:
                        slot.pending.sealed = True
                        slot.sealed, slot.pending = slot.pending, None
                        world = slot.sealed.world
            if world is None:
                return  # seal arrived before any payload: nothing known
            for table in self._tables.values():
                for k in list(table):
                    if k[0] != key or k[1] < world:
                        continue
                    slot = table[k]
                    if slot.pending is None and (
                            slot.sealed is None
                            or slot.sealed.step < step):
                        table.pop(k)

    # -- reads -------------------------------------------------------------

    def get(self, key: str, rank: int) -> Optional[ReplicaEntry]:
        """The newest sealed entry for ``(key, rank)`` — owner copy
        preferred (never torn by a bad transfer)."""
        with self._lock:
            for role in ("own", "held"):
                slot = self._tables[role].get((key, int(rank)))
                if slot is not None and slot.sealed is not None:
                    return slot.sealed
        return None

    def contribution(self, key: str,
                     role: Optional[str] = None) -> List[ReplicaEntry]:
        """Every sealed entry this process can serve for a peer restore
        of ``key`` — own entries first so the merge prefers the owner's
        copy when both survive.  ``role`` restricts to one table (the
        two-phase restore gather ships own payloads first and held
        buddy copies only for ranks with no surviving owner)."""
        roles = ("own", "held") if role is None else (role,)
        with self._lock:
            out = []
            for r in roles:
                for k in sorted(self._tables[r]):
                    if k[0] != key:
                        continue
                    slot = self._tables[r][k]
                    if slot.sealed is not None:
                        out.append(slot.sealed)
        return out

    def keys(self) -> List[str]:
        with self._lock:
            return sorted({k for t in self._tables.values() for (k, _r)
                           in t})

    def total_bytes(self) -> int:
        """Resident payload bytes, deduplicated: in single-controller
        stores the own and held slots alias the SAME entry object (the
        arrays are shared references), which must not be priced twice —
        operators size host RAM from this gauge."""
        with self._lock:
            seen = set()
            total = 0
            for table in self._tables.values():
                for slot in table.values():
                    for e in (slot.sealed, slot.pending):
                        if e is not None and id(e) not in seen:
                            seen.add(id(e))
                            total += e.nbytes()
            return total

    # -- lifecycle ---------------------------------------------------------

    def reset_key(self, key: str) -> None:
        with self._lock:
            for table in self._tables.values():
                for k in [k for k in table if k[0] == key]:
                    table.pop(k)

    def clear(self) -> None:
        with self._lock:
            for table in self._tables.values():
                table.clear()

    def simulate_death(self, ranks: List[int], world: int,
                       stride: int = 1) -> None:
        """Test/drill helper for single-controller stores: losing rank
        *r* loses its own entries AND the buddy copies *it* was holding
        (of ``replica_held(r)``) — its whole memory, exactly what a
        process death takes."""
        with self._lock:
            for r in ranks:
                for k in [k for k in self._tables["own"]
                          if k[1] == int(r)]:
                    self._tables["own"].pop(k)
                held_src = B.replica_held(int(r), world, stride)
                if held_src is not None:
                    for k in [k for k in self._tables["held"]
                              if k[1] == held_src]:
                        self._tables["held"].pop(k)


_store: Optional[ReplicaStore] = None
_store_lock = threading.Lock()


def store() -> ReplicaStore:
    global _store
    with _store_lock:
        if _store is None:
            _store = ReplicaStore()
        return _store


def reset_store() -> None:
    global _store
    with _store_lock:
        _store = None
