"""Autotuning of runtime parameters — the ParameterManager.

Capability parity with the reference's autotune subsystem
(parameter_manager.h:42-246 + optim/bayesian_optimization.cc +
optim/gaussian_process.cc): joint Bayesian optimization of {fusion
threshold bytes, cycle time ms} AND the categorical toggles
{hierarchical_allreduce, hierarchical_allgather, cache_enabled}
(parameter_manager.h:91-93), scored by data-plane throughput
(bytes/sec) over sample windows, with an optional CSV log
(HOROVOD_AUTOTUNE_LOG).  Rebuilt in numpy: RBF-kernel Gaussian-process
regression with expected-improvement acquisition maximized over a random
candidate set (the reference uses Eigen + LBFGS for the same acquisition);
the categorical toggles ride the same GP as relaxed [0,1] dimensions
rounded at application, instead of the reference's nested grids.

The tuner runs on rank 0 (the coordinator owns fusion decisions); tuned
parameters are applied through the native runtime's SetParams hook.
"""

from __future__ import annotations

import math
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np


class GaussianProcess:
    """GP regression with an RBF kernel and observation noise."""

    def __init__(self, length_scale: float = 1.0, noise: float = 1e-4,
                 signal_var: float = 1.0):
        self.length_scale = length_scale
        self.noise = noise
        self.signal_var = signal_var
        self._x: Optional[np.ndarray] = None
        self._y: Optional[np.ndarray] = None
        self._k_inv: Optional[np.ndarray] = None
        self._y_mean = 0.0
        self._y_std = 1.0

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.signal_var * np.exp(-0.5 * d2 / self.length_scale ** 2)

    def fit(self, x: np.ndarray, y: np.ndarray):
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        y = np.asarray(y, dtype=np.float64)
        self._y_mean = float(y.mean())
        self._y_std = float(y.std()) or 1.0
        yn = (y - self._y_mean) / self._y_std
        k = self._kernel(x, x) + self.noise * np.eye(len(x))
        self._k_inv = np.linalg.inv(k)
        self._x, self._y = x, yn

    def predict(self, x_star: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        x_star = np.atleast_2d(np.asarray(x_star, dtype=np.float64))
        if self._x is None:
            mu = np.zeros(len(x_star))
            sigma = np.full(len(x_star), math.sqrt(self.signal_var))
            return mu * self._y_std + self._y_mean, sigma * self._y_std
        ks = self._kernel(x_star, self._x)
        mu = ks @ self._k_inv @ self._y
        kss = self.signal_var * np.ones(len(x_star))
        var = kss - np.einsum("ij,jk,ik->i", ks, self._k_inv, ks)
        sigma = np.sqrt(np.maximum(var, 1e-12))
        return mu * self._y_std + self._y_mean, sigma * self._y_std


def expected_improvement(mu: np.ndarray, sigma: np.ndarray,
                         best: float, xi: float = 0.01) -> np.ndarray:
    """EI acquisition (reference bayesian_optimization.cc)."""
    from math import erf, sqrt
    z = (mu - best - xi) / sigma
    cdf = 0.5 * (1.0 + np.vectorize(erf)(z / sqrt(2.0)))
    pdf = np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
    return (mu - best - xi) * cdf + sigma * pdf


class BayesianOptimizer:
    """Maximize an unknown function over a box via GP + EI."""

    def __init__(self, bounds: Sequence[Tuple[float, float]],
                 seed: int = 0, n_candidates: int = 512,
                 noise: float = 0.8,
                 pinned: Optional[dict] = None):
        self.bounds = np.asarray(bounds, dtype=np.float64)
        self.rng = np.random.RandomState(seed)
        self.n_candidates = n_candidates
        # dim index -> NORMALIZED value, clamped into every candidate:
        # letting candidates vary a dimension whose observations are
        # pinned keeps posterior sigma maximal there, so EI chases
        # unrealizable points and the free dims ride along as noise.
        self.pinned = dict(pinned or {})
        # The GP standardizes scores to zero-mean/unit-std internally, so
        # this noise level acts on unit-scale observations — directly
        # comparable to the reference's alpha knob
        # (--autotune-gaussian-process-noise, default 0.8).
        self.gp = GaussianProcess(length_scale=0.3, noise=noise)
        self.xs: List[np.ndarray] = []
        self.ys: List[float] = []

    def _normalize(self, x):
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return (np.asarray(x) - lo) / (hi - lo)

    def _denormalize(self, u):
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return lo + np.asarray(u) * (hi - lo)

    def observe(self, x, y: float):
        self.xs.append(self._normalize(x))
        self.ys.append(float(y))
        self.gp.fit(np.stack(self.xs), np.asarray(self.ys))

    def _pin(self, u: np.ndarray) -> np.ndarray:
        for i, v in self.pinned.items():
            u[..., i] = v
        return u

    def suggest(self, focus: Optional[Sequence[int]] = None) -> np.ndarray:
        """Propose the next point by EI over a random candidate set.

        ``focus`` (dim indices) prioritizes a subset of the space: half
        the candidates hold every NON-focus dim at the incumbent best
        observation while the focus dims sweep their full range — the
        acquisition then spends its budget where the caller's evidence
        (e.g. a comm-dominated attribution window) says the payoff is,
        without forbidding the free-roaming half from correcting a wrong
        hunch.  Pinned dims stay pinned either way."""
        if len(self.xs) < 3:  # bootstrap with random exploration
            return self._denormalize(self._pin(
                self.rng.rand(len(self.bounds))))
        cand = self._pin(self.rng.rand(self.n_candidates,
                                       len(self.bounds)))
        if focus:
            incumbent = self.xs[int(np.argmax(self.ys))]
            hold = [i for i in range(len(self.bounds))
                    if i not in set(focus)]
            if hold:
                cand[: self.n_candidates // 2, hold] = incumbent[hold]
            cand = self._pin(cand)
        mu, sigma = self.gp.predict(cand)
        ei = expected_improvement(mu, sigma, max(self.ys))
        return self._denormalize(cand[int(np.argmax(ei))])

    def best(self) -> Tuple[np.ndarray, float]:
        i = int(np.argmax(self.ys))
        return self._denormalize(self.xs[i]), self.ys[i]


class ParameterManager:
    """Tunes {log2(fusion bytes), cycle ms} JOINTLY with the categorical
    toggles {hierarchical_allreduce, hierarchical_allgather, cache_enabled}
    against observed throughput.

    Reference semantics (parameter_manager.h:91-93, 225-236): the three
    booleans are CategoricalParameter<bool>s chained with the joint
    Bayesian numeric parameters; scores are throughput bytes/sec over
    sample windows; after ``max_samples`` windows the best parameters are
    frozen.  TPU-native difference: instead of the reference's nested
    categorical grids, the toggles are relaxed to [0,1] dimensions of the
    SAME GP and rounded at application — one joint surrogate over the
    mixed space — with a deterministic bootstrap plan that tries both
    values of every toggle before EI takes over (so e.g. hierarchical
    allreduce is demonstrably tried OFF on a single host, where it loses
    — BENCH_EAGER.json hierarchical rows).
    """

    # log2(bytes): 1 MB .. 256 MB; cycle: 0.5 .. 25 ms; three relaxed
    # booleans {hierarchical_allreduce, hierarchical_allgather, cache};
    # one relaxed trinary (wire compression, rounded into thirds); one
    # relaxed quaternary (overlap bucket bytes, rounded into quarters).
    BOUNDS = [(20.0, 28.0), (0.5, 25.0),
              (0.0, 1.0), (0.0, 1.0), (0.0, 1.0), (0.0, 1.0), (0.0, 1.0)]

    # Wire-format categorical (quantized collective engine): tuned like
    # the boolean toggles, as a relaxed [0,1] dimension of the same GP
    # rounded into thirds at application.  int4 is deliberately absent —
    # without error feedback (an optimizer-state concern the runtime
    # cannot provide) it trades too much gradient fidelity to auto-pick.
    COMPRESSION_CHOICES = ("none", "bf16", "int8")

    # Overlap bucket-size categorical (backward-overlap scheduler,
    # ops/overlap.py): 0 = bucketing off (the per-leaf barrier
    # schedule), else the bucket size in bytes — log2-spaced because
    # the overlap/launch-overhead trade is multiplicative.  Tuned
    # jointly with fusion/cycle/compression: the schedule is
    # value-invariant (bit parity); an explicit
    # HVD_TPU_OVERLAP_BUCKET_BYTES pins the dimension.  Callers may
    # restrict the grid via ``overlap_choices`` — the native controller
    # excludes 0 on multi-rank jobs, because a live on<->off flip is
    # rank-0-local and changes the eager collective NAME sequence
    # (barrier auto-names vs the queue's leaf names), which would
    # desync negotiation; bucket-SIZE flips are name-invariant.
    OVERLAP_CHOICES = (0, 2 << 20, 8 << 20, 32 << 20)

    # Crossover-shift grid for dispatch mode (see ``dispatch_shifts``):
    # the probe-seeded table is the warm start (shift 0); ±1 moves every
    # crossover boundary of that op kind by one payload bucket.
    SHIFT_CHOICES = (-1, 0, 1)

    # GP dims the attribution plane can act on: the comm knobs —
    # dispatch shifts / hierarchical toggles (2, 3), wire compression
    # (5) and the overlap bucket size (6).  Fusion/cycle stay
    # free-roaming: they trade comm batching against host latency and a
    # comm-dominant window does not disambiguate the direction.
    _COMM_DIMS = (2, 3, 5, 6)
    # A window counts as comm-bound when exposed comm is at least this
    # share of the wall AND the largest non-compute component — compute
    # is excluded from the comparison because no tuned knob shrinks the
    # model's arithmetic, so comm stays the biggest *actionable* lever
    # even under a compute-heavy step.
    _COMM_FOCUS_MIN = 0.15

    def __init__(self, apply_fn, max_samples: int = 20,
                 window_seconds: float = 2.0,
                 log_file: Optional[str] = None, seed: int = 0,
                 warmup_samples: int = 3, steps_per_sample: int = 0,
                 gp_noise: float = 0.8,
                 initial_toggles: Tuple[bool, bool, bool] =
                 (False, False, True),
                 tune_toggles: bool = True,
                 initial_compression: str = "none",
                 tune_compression: bool = False,
                 initial_overlap: int = 0,
                 tune_overlap: bool = False,
                 overlap_choices=None,
                 dispatch_shifts: bool = False,
                 attribution_source=None):
        """apply_fn(fusion_bytes: int, cycle_ms: float, hierarchical_
        allreduce: bool, hierarchical_allgather: bool, cache_enabled:
        bool, compression: str, overlap_bucket_bytes: int) applies
        parameters to the runtime (native SetParams + SetTunedToggles +
        SetWireCompression + the overlap engine's session bucket size).

        ``warmup_samples`` windows are discarded (not fed to the GP) to
        skip compile/cache-cold noise; ``steps_per_sample > 0`` closes a
        window every N traffic reports instead of by wall-clock — the
        reference's step-counted sampling (--autotune-steps-per-sample).
        ``initial_toggles`` seeds the bootstrap plan with the configured
        algorithm choice.  ``tune_toggles`` is a per-toggle bool triple
        (a plain bool applies to all three): a pinned toggle stays at
        its initial value and is never explored — flipping a toggle
        that cannot take effect (hierarchical with one node, cache with
        capacity 0) would burn sample budget re-measuring an identical
        configuration.  ``initial_compression``/``tune_compression`` do
        the same for the wire-format categorical (COMPRESSION_CHOICES);
        an explicitly-configured format stays pinned.
        ``initial_overlap``/``tune_overlap`` handle the overlap
        bucket-size categorical (``overlap_choices``, default
        OVERLAP_CHOICES, 0 = off): the bootstrap demonstrably tries
        each choice (overlap OFF against each bucket size, when 0 is in
        the grid) before EI takes over, and an explicitly-configured
        size (HVD_TPU_OVERLAP_BUCKET_BYTES, or any off-grid value) pins
        the dimension.

        ``dispatch_shifts``: once a topology-probed dispatch table is
        installed (ops/dispatch.py), the two hierarchical dims stop
        being blind whole-range booleans and become bounded crossover
        SHIFTS in {-1, 0, +1} over that table — the probe result is the
        warm start, the GP only refines where the flat/hier boundary
        sits.  ``initial_toggles[0:2]`` are then initial shifts (ints)
        and apply_fn receives shift ints in those positions.

        ``attribution_source``: zero-arg callable returning the current
        attribution window's wall-component shares (or None) — default
        the process-global observatory
        (``metrics.attribution.window_shares``).  When the window is
        comm-bound the bootstrap plan tries the comm arms (dispatch
        shifts, compression, bucket size) before the host-side ones and
        the EI acquisition focuses the comm dims; every decision record
        (CSV line, ``autotune.decision`` flight event, journal entry)
        carries the attribution vector that motivated it."""
        self._apply = apply_fn
        self._dispatch_shifts = bool(dispatch_shifts)
        if self._dispatch_shifts:
            init_toggles = (
                min(max(int(initial_toggles[0]), -1), 1),
                min(max(int(initial_toggles[1]), -1), 1),
                bool(initial_toggles[2]))
        else:
            init_toggles = tuple(bool(t) for t in initial_toggles)
        if isinstance(tune_toggles, (tuple, list)):
            tunable = tuple(bool(t) for t in tune_toggles)
        else:
            tunable = (bool(tune_toggles),) * 3
        if initial_compression not in self.COMPRESSION_CHOICES:
            # int4/fp16 (or a typo) cannot be represented in the tuned
            # space: respect it by pinning, never by silently replacing.
            tune_compression = False
        self._initial_compression = initial_compression
        self._tune_compression = bool(tune_compression)
        self._overlap_choices = (tuple(int(c) for c in overlap_choices)
                                 if overlap_choices else
                                 self.OVERLAP_CHOICES)
        initial_overlap = int(initial_overlap)
        if initial_overlap not in self._overlap_choices:
            # An explicit off-grid bucket size: respect by pinning.
            tune_overlap = False
        self._initial_overlap = initial_overlap
        self._tune_overlap = bool(tune_overlap)
        # Pin the GP's candidate dims for non-tunable toggles (toggle
        # bounds are [0,1], so normalized == raw value; shift dims pin
        # at the center of their third).
        pinned = {2 + i: self._toggle_coord(i, init_toggles[i])
                  for i in range(3) if not tunable[i]}
        if not self._tune_compression:
            pinned[5] = self._compression_x(initial_compression)
        if not self._tune_overlap:
            pinned[6] = self._overlap_x(initial_overlap)
        self._opt = BayesianOptimizer(
            self.BOUNDS, seed=seed, noise=gp_noise, pinned=pinned)
        self._max_samples = max_samples
        self._window = window_seconds
        self._warmup_left = max(0, warmup_samples)
        self._steps_per_sample = max(0, steps_per_sample)
        self._steps_in_window = 0
        self._log_file = log_file
        self._samples = 0
        self._frozen = False
        self._current = None
        self._initial_toggles = init_toggles
        self._tunable = tunable
        # Deterministic categorical bootstrap (the reference's grids try
        # every categorical value; here: the configured combo, then each
        # TUNABLE toggle flipped once, then each non-initial wire format
        # once, then each non-initial overlap bucket size once — so
        # "overlap off vs each bucket size" is a controlled comparison).
        # Numeric dims stay GP-proposed.  Entries are tagged with the
        # knob category they vary ("comm" = dispatch/hierarchical,
        # compression, overlap bucket; "host" = cache) so a comm-bound
        # attribution window can pull the comm arms forward without
        # losing any arm.
        self._toggle_plan = self._build_plan()
        # The plan holds the numeric dims FIXED across the toggle flips:
        # a controlled comparison, so fusion/cycle variation (which can
        # swing throughput far more than ~20%) cannot confound the
        # categorical signal.  The reference's nested grids get the same
        # property structurally.
        self._plan_numeric = None
        self._window_start = time.perf_counter()
        self._bytes = 0
        # The observatory signal: shares of the last closed attribution
        # window (captured per _observe), default source the
        # process-global engine.  Guarded — the tuner must run with the
        # observatory disabled or absent.
        if attribution_source is None:
            attribution_source = _default_attribution_source
        self._attr_source = attribution_source
        self._last_attr: Optional[dict] = None
        # Decision trail: every applied config with the score it earned
        # and the attribution vector that motivated it (bounded).
        self._journal: List[dict] = []
        # Closed-loop state: the frozen config's measured score (the
        # pre-drift baseline a re-tune episode is gated against), the
        # bounded-episode countdown, the last-known-good rollback
        # target, and the loop's lifetime counters.
        self._frozen_score: Optional[float] = None
        self._retune_left = 0
        self._retune_scores: List[Tuple[float, tuple]] = []
        self._retune_baseline: Optional[float] = None
        self._retune_focus: Optional[str] = None
        self._known_good: Optional[tuple] = None
        self._retunes = 0
        self._rollbacks = 0
        self._warm_started = False
        self._last_outcome: Optional[dict] = None
        # Tuning memory (fleet/tuning.py): attached by announce_model /
        # attach_memory; the frozen best writes back through it.
        self._memory = None
        self._memory_key: Optional[str] = None
        # One-shot reason override for the next proposal (warm_start
        # applies through _propose but must record as warm_start).
        self._pending_reason: Optional[str] = None
        # Autotune decisions feed the metrics registry: which parameters
        # are live right now, how many sample windows were scored, and
        # whether the tuner froze — queryable next to the throughput
        # they produced instead of buried in the CSV log.
        from .metrics.registry import registry as _metrics_registry
        _mreg = _metrics_registry()
        self._m_samples = _mreg.counter(
            "hvd_autotune_samples_total",
            "Scored autotune sample windows")
        self._m_decisions = _mreg.counter(
            "hvd_autotune_decisions_total",
            "Parameter applications by the autotuner")
        self._m_fusion = _mreg.gauge(
            "hvd_autotune_fusion_bytes",
            "Fusion threshold currently applied by the autotuner")
        self._m_cycle = _mreg.gauge(
            "hvd_autotune_cycle_ms",
            "Cycle time currently applied by the autotuner")
        self._m_frozen = _mreg.gauge(
            "hvd_autotune_frozen",
            "1 once the autotuner froze its best parameters")
        # The closed loop's own observability (ISSUE 12): how often the
        # drift plane re-opened the tuner, how often the episode rolled
        # back, whether this job started from the tuning memory, and the
        # last episode's score vs its pre-drift baseline.
        self._m_retunes = _mreg.counter(
            "hvd_autotune_retunes_total",
            "Drift-triggered bounded re-tune episodes")
        self._m_rollbacks = _mreg.counter(
            "hvd_autotune_rollbacks_total",
            "Re-tune episodes rolled back to the last-known-good config")
        self._m_warm = _mreg.counter(
            "hvd_autotune_warm_starts_total",
            "Tuners seeded from the persistent tuning memory")
        self._m_score_ratio = _mreg.gauge(
            "hvd_autotune_score_ratio",
            "Last re-tune episode's best score / pre-drift baseline")
        self._reason = "bootstrap"
        self._propose()

    def _build_plan(self) -> List[Tuple[str, tuple]]:
        """The deterministic categorical bootstrap as (category, tail)
        entries — tail is the 5-wide categorical suffix appended to the
        plan's fixed numerics."""
        if not (any(self._tunable) or self._tune_compression or
                self._tune_overlap):
            return []
        t0 = self._initial_toggles + (self._initial_compression,
                                      self._initial_overlap)
        plan: List[Tuple[str, tuple]] = [("base", t0)]
        for i in range(3):
            if not self._tunable[i]:
                continue
            # Alternatives per dim: a boolean flips once; a
            # dispatch-mode shift dim tries each other crossover
            # shift (so ±1 are both demonstrably measured against
            # the probe's warm start before EI takes over).
            if self._dispatch_shifts and i < 2:
                alts = [s for s in self.SHIFT_CHOICES if s != t0[i]]
            else:
                alts = [not t0[i]]
            cat = "comm" if i < 2 else "host"
            plan += [(cat, tuple(a if j == i else t0[j] for j in range(3))
                      + (self._initial_compression, self._initial_overlap))
                     for a in alts]
        if self._tune_compression:
            plan += [("comm", self._initial_toggles
                      + (c, self._initial_overlap))
                     for c in self.COMPRESSION_CHOICES
                     if c != self._initial_compression]
        if self._tune_overlap:
            plan += [("comm", self._initial_toggles
                      + (self._initial_compression, o))
                     for o in self._overlap_choices
                     if o != self._initial_overlap]
        return plan

    def _refresh_attr(self) -> Optional[dict]:
        """Snapshot the attribution window's shares (guarded — the
        observatory may be off, absent, or mid-reset)."""
        try:
            shares = self._attr_source() if self._attr_source else None
        except Exception:  # noqa: BLE001 — telemetry never kills tuning
            shares = None
        if shares:
            self._last_attr = {k: round(float(v), 4)
                               for k, v in shares.items()}
        return self._last_attr

    def _comm_focus(self) -> bool:
        """True when the last attribution window says the step is
        comm-bound — exposed comm at least _COMM_FOCUS_MIN of the wall
        and the largest non-compute component."""
        attr = self._last_attr
        if not attr:
            return False
        comm = attr.get("comm_exposed", 0.0)
        others = [attr.get(k, 0.0) for k in ("input", "checkpoint", "host")]
        return comm >= self._COMM_FOCUS_MIN and comm >= max(others, default=0)

    @property
    def frozen(self) -> bool:
        return self._frozen

    @property
    def current(self):
        """(fusion_bytes, cycle_ms, hier_allreduce, hier_allgather,
        cache_enabled, compression, overlap_bucket_bytes)"""
        return self._current

    def _toggle_coord(self, i: int, v) -> float:
        """Normalized GP coordinate of one toggle value: booleans sit at
        the interval ends; dispatch-mode shift dims at the center of
        their third (stable rounding, like compression)."""
        if self._dispatch_shifts and i < 2:
            return (min(max(int(v), -1), 1) + 1 + 0.5) / 3.0
        return 1.0 if v else 0.0

    def _round_toggles(self, x) -> Tuple:
        out = []
        for i in range(3):
            if not self._tunable[i]:
                out.append(self._initial_toggles[i])
            elif self._dispatch_shifts and i < 2:
                out.append(min(int(float(x[2 + i]) * 3), 2) - 1)
            else:
                out.append(bool(x[2 + i] >= 0.5))
        return tuple(out)

    @classmethod
    def _compression_x(cls, comp: str) -> float:
        """Normalized GP coordinate of a wire format: the center of its
        third (so rounding is stable against GP jitter)."""
        choices = cls.COMPRESSION_CHOICES
        idx = choices.index(comp) if comp in choices else 0
        return (idx + 0.5) / len(choices)

    def _round_compression(self, x) -> str:
        if not self._tune_compression:
            return self._initial_compression
        n = len(self.COMPRESSION_CHOICES)
        idx = min(int(float(x[5]) * n), n - 1)
        return self.COMPRESSION_CHOICES[idx]

    def _overlap_x(self, overlap: int) -> float:
        """Normalized GP coordinate of an overlap bucket size: the
        center of its grid cell (stable rounding, like compression)."""
        choices = self._overlap_choices
        idx = choices.index(overlap) if overlap in choices else 0
        return (idx + 0.5) / len(choices)

    def _round_overlap(self, x) -> int:
        if not self._tune_overlap:
            return self._initial_overlap
        n = len(self._overlap_choices)
        idx = min(int(float(x[6]) * n), n - 1)
        return self._overlap_choices[idx]

    def _propose(self):
        # A re-tune episode is GP territory: the tuner may have frozen
        # before exhausting the bootstrap plan (max_samples below the
        # plan length), and replaying stale pre-drift arms here would
        # bypass the episode's comm focus and mislabel the decision
        # trail as "bootstrap".
        if self._toggle_plan and self._retune_left == 0:
            if self._plan_numeric is None:
                x = self._opt.suggest()
                self._plan_numeric = (int(2 ** x[0]), float(x[1]))
            # Attribution-guided ordering: a comm-bound window pulls the
            # first comm arm (dispatch shift / wire format / bucket
            # size) forward — every arm is still measured exactly once,
            # only the order adapts to where the step's time went.
            idx = 0
            if self._comm_focus():
                idx = next((j for j, (cat, _) in
                            enumerate(self._toggle_plan)
                            if cat == "comm"), 0)
            self._reason = "bootstrap"
            self._current = self._plan_numeric + \
                self._toggle_plan.pop(idx)[1]
        else:
            # Comm focus comes from either live attribution or the drift
            # event that opened a re-tune episode (its dominant
            # component is the evidence even when the window shares are
            # not wired up).
            comm = self._comm_focus() or (
                self._retune_left > 0
                and self._retune_focus == "comm_exposed")
            focus = self._COMM_DIMS if comm else None
            x = self._opt.suggest(focus=focus)
            self._reason = ("retune" if self._retune_left > 0 else
                            ("ei_comm_focus" if focus else "ei"))
            self._current = ((int(2 ** x[0]), float(x[1]))
                             + self._round_toggles(x)
                             + (self._round_compression(x),)
                             + (self._round_overlap(x),))
        if self._pending_reason:
            self._reason = self._pending_reason
            self._pending_reason = None
        self._apply(*self._current)
        self._record_applied()

    def _record_applied(self):
        self._m_decisions.inc()
        self._m_fusion.set(self._current[0])
        self._m_cycle.set(self._current[1])
        # Flight event: autotune decisions were metrics-only, invisible
        # to the drift diagnoser — a regression that starts right after
        # a parameter application should name the tuner as the suspect
        # (debug/regression.py correlates perf.drift onsets against
        # these).
        from .debug import flight as _flight
        # In dispatch mode slots 2/3 are crossover SHIFTS (ints) over
        # the probe-seeded table, not whole-range booleans — record the
        # raw value either way so the drift diagnoser quotes what was
        # actually applied.
        _flight.record(
            "autotune.decision", None,
            fusion_bytes=int(self._current[0]),
            cycle_ms=round(float(self._current[1]), 3),
            hierarchical_allreduce=(int(self._current[2])
                                    if self._dispatch_shifts
                                    else bool(self._current[2])),
            hierarchical_allgather=(int(self._current[3])
                                    if self._dispatch_shifts
                                    else bool(self._current[3])),
            cache_enabled=bool(self._current[4]),
            compression=self._current[5],
            overlap_bucket_bytes=int(self._current[6]),
            frozen=self._frozen,
            # The explainability payload: why THIS proposal — which
            # phase chose it and the attribution vector that motivated
            # the ordering/focus, so a tuning trajectory reads from the
            # flight stream alone.
            reason=self._reason,
            attr=self._last_attr)

    def record_bytes(self, nbytes: int):
        """Feed data-plane traffic; closes a window when enough time passed
        (or, in step-counted mode, after steps_per_sample reports)."""
        if self._frozen:
            return
        self._bytes += int(nbytes)
        now = time.perf_counter()
        elapsed = now - self._window_start
        if self._steps_per_sample > 0:
            self._steps_in_window += 1
            if self._steps_in_window < self._steps_per_sample:
                return
        elif elapsed < self._window:
            return
        score = self._bytes / max(elapsed, 1e-9)
        self._observe(score)
        self._bytes = 0
        self._steps_in_window = 0
        self._window_start = now

    def _x_of_current(self) -> np.ndarray:
        return np.array(
            [math.log2(self._current[0]), self._current[1]]
            + [self._toggle_coord(i, self._current[2 + i])
               for i in range(3)]
            # De-normalize the categorical coordinates back into their
            # raw [0,1] bounds (observe() re-normalizes; toggle bounds
            # are [0,1] so this is the identity for them too).
            + [self._compression_x(self._current[5]),
               self._overlap_x(self._current[6])])

    def _observe(self, score: float):
        self._refresh_attr()
        if self._warmup_left > 0:
            # Warmup windows (compile/cache-cold noise) are logged but
            # not fed to the GP and do not count toward max_samples.
            # The current proposal stays applied and NO plan entry is
            # consumed — the bootstrap's categorical arms all replay
            # after warmup ends, so discarded windows can never cost
            # bootstrap coverage (regression-tested,
            # tests/test_tuning_loop.py).
            self._warmup_left -= 1
            self._log(score, tag="warmup")
            return
        if self._retune_left > 0:
            # Bounded drift-triggered episode: score the candidate,
            # remember it, and either propose the next or resolve the
            # episode (accept vs regression-gated rollback).
            self._opt.observe(self._x_of_current(), score)
            self._retune_scores.append((float(score), self._current))
            self._log(score, tag="retune")
            self._retune_left -= 1
            if self._retune_left > 0:
                self._propose()
            else:
                self._finish_retune()
            return
        self._opt.observe(self._x_of_current(), score)
        self._log(score)
        self._samples += 1
        self._m_samples.inc()
        if self._samples >= self._max_samples:
            best_x, best_y = self._opt.best()
            self._current = ((int(2 ** best_x[0]), float(best_x[1]))
                             + tuple(self._round_toggles(best_x))
                             + (self._round_compression(best_x),)
                             + (self._round_overlap(best_x),))
            self._reason = "final"
            self._apply(*self._current)
            self._record_applied()
            self._frozen = True
            self._frozen_score = float(best_y)
            self._m_frozen.set(1)
            self._log(best_y, tag="final")
            self._memory_put()
        else:
            self._propose()

    def _log(self, score: float, tag: str = "sample"):
        # Journal first (always on): the in-memory decision trail the
        # loop status / regression report's tuning section quote.
        self._journal.append({
            "tag": tag, "score": float(score),
            "config": self.config_dict(), "attr": self._last_attr,
            "reason": self._reason})
        if len(self._journal) > 256:
            del self._journal[:64]
        if not self._log_file:
            return
        # Attribution column: ";"-joined k=v (never a comma — the CSV
        # stays 10 naively-splittable columns), "-" when the
        # observatory had nothing for this window.
        attr = "-" if not self._last_attr else ";".join(
            f"{k}={v:.3f}" for k, v in sorted(self._last_attr.items()))
        try:
            with open(self._log_file, "a") as f:
                f.write(f"{tag},{self._current[0]},{self._current[1]:.3f},"
                        f"{int(self._current[2])},{int(self._current[3])},"
                        f"{int(self._current[4])},{self._current[5]},"
                        f"{int(self._current[6])},{score:.1f},{attr}\n")
        except OSError:
            pass

    # -- the closed loop: configs as records, re-tune, rollback, memory ----

    def config_dict(self, config: Optional[tuple] = None) -> dict:
        """One applied config as the named record every surface shares —
        the journal, the tuning-memory store, the flight events and the
        regression report's tuning section all speak this shape."""
        c = config if config is not None else self._current
        shifts = self._dispatch_shifts
        return {
            "fusion_bytes": int(c[0]),
            "cycle_ms": round(float(c[1]), 4),
            "hierarchical_allreduce": int(c[2]) if shifts else bool(c[2]),
            "hierarchical_allgather": int(c[3]) if shifts else bool(c[3]),
            "cache_enabled": bool(c[4]),
            "compression": str(c[5]),
            "overlap_bucket_bytes": int(c[6]),
        }

    def _config_from_dict(self, d: dict) -> tuple:
        """The inverse of :meth:`config_dict`, clamped into this tuner's
        space: pinned dims keep their pinned values (an operator's
        explicit knob outranks a stored record), off-grid categorical
        values fall back to the initials, numerics clamp into BOUNDS."""
        toggles = []
        for i, key in enumerate(("hierarchical_allreduce",
                                 "hierarchical_allgather",
                                 "cache_enabled")):
            if not self._tunable[i]:
                toggles.append(self._initial_toggles[i])
                continue
            v = d.get(key, self._initial_toggles[i])
            if self._dispatch_shifts and i < 2:
                toggles.append(min(max(int(v), -1), 1))
            else:
                toggles.append(bool(v))
        comp = d.get("compression", self._initial_compression)
        if not self._tune_compression or comp not in \
                self.COMPRESSION_CHOICES:
            comp = self._initial_compression
        try:
            ov = int(d.get("overlap_bucket_bytes", self._initial_overlap))
        except (TypeError, ValueError):
            ov = self._initial_overlap
        if not self._tune_overlap or ov not in self._overlap_choices:
            ov = self._initial_overlap
        lo_f, hi_f = 2 ** int(self.BOUNDS[0][0]), 2 ** int(self.BOUNDS[0][1])
        fusion = min(max(int(d.get("fusion_bytes", lo_f)), lo_f), hi_f)
        lo_c, hi_c = self.BOUNDS[1]
        cycle = min(max(float(d.get("cycle_ms", lo_c)), lo_c), hi_c)
        return (fusion, cycle) + tuple(toggles) + (comp, ov)

    def gp_dims(self) -> tuple:
        """Descriptor tuple of the knob space this tuner optimizes over.

        Stored with every tuning-memory record: the GP dimensionality
        has grown twice already (the PR 5 compression dim, the PR 11
        shift rebase) and a record tuned over a different space must be
        refused, not silently mis-seeded — fleet/tuning.py compares
        these tuples verbatim."""
        hier = "shift3" if self._dispatch_shifts else "bool"
        return ("log2_fusion:20-28", "cycle_ms:0.5-25",
                f"hier_allreduce:{hier}", f"hier_allgather:{hier}",
                "cache:bool",
                "compression:" + "|".join(self.COMPRESSION_CHOICES),
                "overlap:" + "|".join(str(c)
                                      for c in self._overlap_choices))

    def journal(self) -> List[dict]:
        """The decision trail: every scored window's config, score and
        motivating attribution vector (bounded to the recent ~256)."""
        return list(self._journal)

    def loop_status(self) -> dict:
        """What the feedback loop is doing right now — quoted by the
        regression report's ``tuning`` section and ``hvd.debug``."""
        return {
            "frozen": self._frozen,
            "samples": self._samples,
            "retuning": self._retune_left > 0,
            "retune_windows_left": self._retune_left,
            "retunes": self._retunes,
            "rollbacks": self._rollbacks,
            "warm_started": self._warm_started,
            "frozen_score": self._frozen_score,
            "current": self.config_dict(),
            "last_outcome": self._last_outcome,
        }

    def attach_memory(self, store, key: str) -> None:
        """Bind a tuning-memory store: the frozen best (and every
        accepted re-tune) writes back under ``key``."""
        self._memory = store
        self._memory_key = key

    def _memory_put(self) -> None:
        if self._memory is None or not self._memory_key:
            return
        try:
            from .fleet import tuning as _tuning
            self._memory.put(self._memory_key, _tuning.make_record(
                self.config_dict(), score=self._frozen_score,
                dims=self.gp_dims()))
        except Exception as e:  # noqa: BLE001 — memory is best-effort
            from .utils import logging as log
            log.warning("autotune memory: write-back failed: %r", e)

    def warm_start(self, record: dict, source: str = "memory") -> bool:
        """Seed this tuner from a stored tuned config: the bootstrap
        collapses to the seeded combo (the categorical sweep already ran
        on the job that stored it) and the stored score anchors the GP,
        so EI only *refines*.  Only meaningful before any scored sample;
        returns False once tuning started.  Raises ``ValueError`` on a
        knob-space mismatch — callers that reached this point should
        have dim-checked at the store (fleet/tuning.py does)."""
        if self._frozen or self._samples > 0 or self._retune_left > 0:
            return False
        dims = list(record.get("dims") or [])
        if dims != list(self.gp_dims()):
            raise ValueError(
                f"tuned-config record was stored over knob space {dims}, "
                f"but this tuner optimizes {list(self.gp_dims())} — "
                "refusing to mis-seed; delete the stale record or let "
                "the job tune cold")
        t = self._config_from_dict(record.get("config") or {})
        self._initial_toggles = t[2:5]
        self._initial_compression = t[5]
        self._initial_overlap = t[6]
        self._plan_numeric = (t[0], float(t[1]))
        self._toggle_plan = [("base", t[2:7])]
        score = record.get("score")
        if score is not None:
            # The stored score anchors the incumbent for EI (the key
            # fixes model/world/topology, so the bytes/sec scale is the
            # same run-to-run).
            try:
                self._opt.observe(
                    np.array([math.log2(t[0]), t[1]]
                             + [self._toggle_coord(i, t[2 + i])
                                for i in range(3)]
                             + [self._compression_x(t[5]),
                                self._overlap_x(t[6])]), float(score))
            except Exception:  # noqa: BLE001
                pass
        self._warm_started = True
        self._m_warm.inc()
        from .debug import flight as _flight
        _flight.record("autotune.warm_start", self._memory_key,
                       source=source, stored_score=score,
                       config=self.config_dict(t))
        self._pending_reason = "warm_start"
        self._propose()
        return True

    def request_retune(self, reason: str = "drift",
                       windows: Optional[int] = None,
                       focus_component: Optional[str] = None) -> bool:
        """Open a bounded re-tune episode on a frozen tuner (the drift
        plane's entry point, autotune.notify_drift).  ``windows`` sample
        windows are scored (the incumbent first, under the post-drift
        conditions, then GP proposals — comm-focused when
        ``focus_component`` is comm_exposed), after which the episode
        resolves: the best candidate is adopted unless it regresses past
        the pre-drift baseline by HVD_TPU_AUTOTUNE_ROLLBACK_PCT, in
        which case the tuner rolls back to the last-known-good config.
        Returns False when the tuner is still exploring or already in an
        episode."""
        if not self._frozen or self._retune_left > 0:
            return False
        from .core import config as _config
        if windows is None:
            windows = _config.get_int(
                "AUTOTUNE_RETUNE_WINDOWS",
                _config.Config.autotune_retune_windows)
        windows = max(1, int(windows))
        self._known_good = self._current
        self._retune_baseline = self._frozen_score
        self._retune_scores = []
        self._retune_left = windows
        self._retune_focus = focus_component
        self._frozen = False
        self._m_frozen.set(0)
        self._retunes += 1
        self._m_retunes.inc()
        # Fresh window accounting: record_bytes early-returned for the
        # whole frozen stretch, so the marks are stale.
        self._bytes = 0
        self._steps_in_window = 0
        self._window_start = time.perf_counter()
        self._reason = "retune_incumbent"
        from .debug import flight as _flight
        _flight.record("autotune.retune", None, reason=reason,
                       windows=windows, focus=focus_component,
                       baseline_score=self._retune_baseline,
                       incumbent=self.config_dict())
        # The incumbent stays applied for the first episode window — a
        # post-drift measurement of the last-known-good config, so the
        # journal shows what the regression actually costs and the GP
        # learns the new level before proposing alternatives.
        return True

    def _finish_retune(self) -> None:
        best_score, best_cfg = max(self._retune_scores,
                                   key=lambda e: e[0])
        from .core import config as _config
        from .debug import flight as _flight
        pct = _config.get_float("AUTOTUNE_ROLLBACK_PCT",
                                _config.Config.autotune_rollback_pct)
        baseline = self._retune_baseline
        ratio = (best_score / baseline) if baseline else None
        if ratio is not None:
            self._m_score_ratio.set(ratio)
        rolled = (baseline is not None and self._known_good is not None
                  and best_score < baseline * (1.0 - pct / 100.0))
        if rolled:
            # Regression gate: nothing the episode tried recovers the
            # pre-drift baseline (an external cause, or a genuinely bad
            # direction) — roll back to the journaled last-known-good
            # entry and keep its score as the standing baseline.
            self._current = self._known_good
            self._reason = "rollback"
            self._apply(*self._current)
            self._record_applied()
            self._rollbacks += 1
            self._m_rollbacks.inc()
            _flight.record(
                "autotune.rollback", None,
                best_score=round(best_score, 1),
                baseline_score=round(baseline, 1),
                score_ratio=round(ratio, 4) if ratio else None,
                restored=self.config_dict())
            outcome = "rolled_back"
        else:
            confirmed = best_cfg == self._known_good
            self._current = best_cfg
            self._reason = "retuned"
            self._apply(*self._current)
            self._record_applied()
            self._frozen_score = best_score
            outcome = "confirmed" if confirmed else "accepted"
            self._memory_put()
        self._frozen = True
        self._m_frozen.set(1)
        self._retune_left = 0
        self._last_outcome = {
            "action": "retune", "outcome": outcome,
            "best_score": best_score, "baseline_score": baseline,
            "score_ratio": ratio, "windows": len(self._retune_scores),
            "config": self.config_dict(),
        }
        # The regression diagnoser recognizes the resolution: the last
        # report's ``tuning`` section now records what the loop did
        # about the drift (and the rewritten JSON on disk says so too).
        try:
            from .debug import regression as _regression
            _regression.record_tuning(dict(self._last_outcome))
        except Exception:  # noqa: BLE001 — diagnosis never kills tuning
            pass


# ---------------------------------------------------------------------------
# the process-global loop surface (rank 0 owns the tuner; everywhere
# else these are cheap no-ops)
# ---------------------------------------------------------------------------

def _default_attribution_source():
    """The process-global observatory's window shares (None when the
    observatory is off or has no closed window yet)."""
    from .metrics import attribution as _attr
    if not _attr.enabled():
        return None
    return _attr.attribution().window_shares()


_active_manager: Optional[ParameterManager] = None


def set_active_manager(pm: Optional[ParameterManager]) -> None:
    """Register the live tuner (the native controller's, on rank 0) so
    the drift plane and the tuning memory can reach it.  Pass None to
    clear (tests, shutdown)."""
    global _active_manager
    _active_manager = pm


def active_manager() -> Optional[ParameterManager]:
    return _active_manager


def loop_status() -> Optional[dict]:
    """The active tuner's closed-loop status (None when this process
    owns no tuner) — what the regression report's tuning section and
    hang reports quote."""
    pm = _active_manager
    return pm.loop_status() if pm is not None else None


# Drift suspects the tuner can plausibly act on: its own past decision,
# the dispatch plane it shifts, the overlap scheduler it sizes.  A drift
# whose dominant component is exposed comm is tunable even under a
# non-tunable suspect (net slowdown, no suspect at all): the comm knobs
# exist precisely to trade wire time, and the episode's regression gate
# rolls back when they turn out not to help.
TUNABLE_SUSPECTS = frozenset({"autotune", "dispatch", "overlap"})
TUNABLE_COMPONENTS = frozenset({"comm_exposed"})


def notify_drift(event, report: Optional[dict] = None) -> bool:
    """Close the loop on one confirmed drift: decide whether a bounded
    re-tune episode is warranted, start it, and record the decision in
    the regression report's ``tuning`` section either way.  Called by
    the drift detector (metrics/baseline.py) after the report is built;
    returns True when an episode started."""
    from .core import config as _config
    pm = _active_manager
    suspect = None
    if report:
        s = report.get("suspect") or None
        if s:
            suspect = s.get("subsystem")
    component = getattr(event, "component", None)
    tunable = suspect in TUNABLE_SUSPECTS or component in TUNABLE_COMPONENTS
    action = {"considered": True, "suspect": suspect,
              "component": component}
    started = False
    if pm is None:
        action.update(action="none", why="no active tuner in this process")
    elif not _config.get_bool("AUTOTUNE_RETUNE",
                              _config.Config.autotune_retune):
        action.update(action="none", why="HVD_TPU_AUTOTUNE_RETUNE=0")
    elif not tunable:
        action.update(
            action="none",
            why=f"suspect {suspect!r} / component {component!r} is not a "
                "tunable subsystem")
    elif not pm.frozen:
        action.update(action="none",
                      why="tuner still exploring (not frozen)")
    else:
        started = pm.request_retune(reason=f"drift:{component}",
                                    focus_component=component)
        action.update(action="retune" if started else "none",
                      outcome="started" if started else "refused")
    try:
        from .debug import regression as _regression
        _regression.record_tuning(action)
    except Exception:  # noqa: BLE001
        pass
    return started


def announce_model(tree=None, fingerprint: Optional[str] = None,
                   world: Optional[int] = None,
                   store=None) -> Optional[str]:
    """Tell the tuning memory what this job trains: computes the
    leaf-spec fingerprint of ``tree`` (the PR 1 checkpoint fingerprint —
    world-size-invariant), builds the (fingerprint, world, topology)
    memory key, warm-starts the active tuner from a stored record when
    the knob space still matches, and binds the store for freeze-time
    write-back.  Returns the key (None when this process owns no tuner,
    the memory knob is off, or no fingerprint is derivable).  Wired
    automatically into ``TpuState``; call directly from custom loops."""
    pm = _active_manager
    if pm is None:
        return None
    from .core import config as _config
    if not _config.get_bool("AUTOTUNE_MEMORY",
                            _config.Config.autotune_memory):
        return None
    from .utils import logging as log
    try:
        from .fleet import tuning as _tuning
        if fingerprint is None:
            if tree is None:
                return None
            fingerprint = _tuning.model_fingerprint(tree)
        if world is None:
            from .core.state import global_state
            world = max(int(getattr(global_state, "process_count", 1)
                            or 1), 1)
        key = _tuning.config_key(fingerprint, world,
                                 _tuning.topology_signature())
        if store is None:
            store = _tuning.resolve_store()
        pm.attach_memory(store, key)
        try:
            rec = store.get(key, dims=pm.gp_dims())
        except _tuning.TuningSchemaMismatch as e:
            # Loud and pointed, never fatal: a stale record must not
            # mis-seed the job, and the job must still train.
            log.error("autotune memory: %s", e)
            from .debug import flight as _flight
            _flight.record("autotune.memory_reject", key, error=str(e))
            return key
        if rec is not None:
            pm.warm_start(rec)
        return key
    except Exception as e:  # noqa: BLE001 — memory is best-effort
        log.warning("autotune memory: announce failed: %r", e)
        return None
