"""Autotuning of runtime parameters — the ParameterManager.

Capability parity with the reference's autotune subsystem
(parameter_manager.h:42-246 + optim/bayesian_optimization.cc +
optim/gaussian_process.cc): joint Bayesian optimization of {fusion
threshold bytes, cycle time ms} AND the categorical toggles
{hierarchical_allreduce, hierarchical_allgather, cache_enabled}
(parameter_manager.h:91-93), scored by data-plane throughput
(bytes/sec) over sample windows, with an optional CSV log
(HOROVOD_AUTOTUNE_LOG).  Rebuilt in numpy: RBF-kernel Gaussian-process
regression with expected-improvement acquisition maximized over a random
candidate set (the reference uses Eigen + LBFGS for the same acquisition);
the categorical toggles ride the same GP as relaxed [0,1] dimensions
rounded at application, instead of the reference's nested grids.

The tuner runs on rank 0 (the coordinator owns fusion decisions); tuned
parameters are applied through the native runtime's SetParams hook.
"""

from __future__ import annotations

import math
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np


class GaussianProcess:
    """GP regression with an RBF kernel and observation noise."""

    def __init__(self, length_scale: float = 1.0, noise: float = 1e-4,
                 signal_var: float = 1.0):
        self.length_scale = length_scale
        self.noise = noise
        self.signal_var = signal_var
        self._x: Optional[np.ndarray] = None
        self._y: Optional[np.ndarray] = None
        self._k_inv: Optional[np.ndarray] = None
        self._y_mean = 0.0
        self._y_std = 1.0

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.signal_var * np.exp(-0.5 * d2 / self.length_scale ** 2)

    def fit(self, x: np.ndarray, y: np.ndarray):
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        y = np.asarray(y, dtype=np.float64)
        self._y_mean = float(y.mean())
        self._y_std = float(y.std()) or 1.0
        yn = (y - self._y_mean) / self._y_std
        k = self._kernel(x, x) + self.noise * np.eye(len(x))
        self._k_inv = np.linalg.inv(k)
        self._x, self._y = x, yn

    def predict(self, x_star: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        x_star = np.atleast_2d(np.asarray(x_star, dtype=np.float64))
        if self._x is None:
            mu = np.zeros(len(x_star))
            sigma = np.full(len(x_star), math.sqrt(self.signal_var))
            return mu * self._y_std + self._y_mean, sigma * self._y_std
        ks = self._kernel(x_star, self._x)
        mu = ks @ self._k_inv @ self._y
        kss = self.signal_var * np.ones(len(x_star))
        var = kss - np.einsum("ij,jk,ik->i", ks, self._k_inv, ks)
        sigma = np.sqrt(np.maximum(var, 1e-12))
        return mu * self._y_std + self._y_mean, sigma * self._y_std


def expected_improvement(mu: np.ndarray, sigma: np.ndarray,
                         best: float, xi: float = 0.01) -> np.ndarray:
    """EI acquisition (reference bayesian_optimization.cc)."""
    from math import erf, sqrt
    z = (mu - best - xi) / sigma
    cdf = 0.5 * (1.0 + np.vectorize(erf)(z / sqrt(2.0)))
    pdf = np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
    return (mu - best - xi) * cdf + sigma * pdf


class BayesianOptimizer:
    """Maximize an unknown function over a box via GP + EI."""

    def __init__(self, bounds: Sequence[Tuple[float, float]],
                 seed: int = 0, n_candidates: int = 512,
                 noise: float = 0.8,
                 pinned: Optional[dict] = None):
        self.bounds = np.asarray(bounds, dtype=np.float64)
        self.rng = np.random.RandomState(seed)
        self.n_candidates = n_candidates
        # dim index -> NORMALIZED value, clamped into every candidate:
        # letting candidates vary a dimension whose observations are
        # pinned keeps posterior sigma maximal there, so EI chases
        # unrealizable points and the free dims ride along as noise.
        self.pinned = dict(pinned or {})
        # The GP standardizes scores to zero-mean/unit-std internally, so
        # this noise level acts on unit-scale observations — directly
        # comparable to the reference's alpha knob
        # (--autotune-gaussian-process-noise, default 0.8).
        self.gp = GaussianProcess(length_scale=0.3, noise=noise)
        self.xs: List[np.ndarray] = []
        self.ys: List[float] = []

    def _normalize(self, x):
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return (np.asarray(x) - lo) / (hi - lo)

    def _denormalize(self, u):
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return lo + np.asarray(u) * (hi - lo)

    def observe(self, x, y: float):
        self.xs.append(self._normalize(x))
        self.ys.append(float(y))
        self.gp.fit(np.stack(self.xs), np.asarray(self.ys))

    def _pin(self, u: np.ndarray) -> np.ndarray:
        for i, v in self.pinned.items():
            u[..., i] = v
        return u

    def suggest(self) -> np.ndarray:
        if len(self.xs) < 3:  # bootstrap with random exploration
            return self._denormalize(self._pin(
                self.rng.rand(len(self.bounds))))
        cand = self._pin(self.rng.rand(self.n_candidates,
                                       len(self.bounds)))
        mu, sigma = self.gp.predict(cand)
        ei = expected_improvement(mu, sigma, max(self.ys))
        return self._denormalize(cand[int(np.argmax(ei))])

    def best(self) -> Tuple[np.ndarray, float]:
        i = int(np.argmax(self.ys))
        return self._denormalize(self.xs[i]), self.ys[i]


class ParameterManager:
    """Tunes {log2(fusion bytes), cycle ms} JOINTLY with the categorical
    toggles {hierarchical_allreduce, hierarchical_allgather, cache_enabled}
    against observed throughput.

    Reference semantics (parameter_manager.h:91-93, 225-236): the three
    booleans are CategoricalParameter<bool>s chained with the joint
    Bayesian numeric parameters; scores are throughput bytes/sec over
    sample windows; after ``max_samples`` windows the best parameters are
    frozen.  TPU-native difference: instead of the reference's nested
    categorical grids, the toggles are relaxed to [0,1] dimensions of the
    SAME GP and rounded at application — one joint surrogate over the
    mixed space — with a deterministic bootstrap plan that tries both
    values of every toggle before EI takes over (so e.g. hierarchical
    allreduce is demonstrably tried OFF on a single host, where it loses
    — BENCH_EAGER.json hierarchical rows).
    """

    # log2(bytes): 1 MB .. 256 MB; cycle: 0.5 .. 25 ms; three relaxed
    # booleans {hierarchical_allreduce, hierarchical_allgather, cache};
    # one relaxed trinary (wire compression, rounded into thirds); one
    # relaxed quaternary (overlap bucket bytes, rounded into quarters).
    BOUNDS = [(20.0, 28.0), (0.5, 25.0),
              (0.0, 1.0), (0.0, 1.0), (0.0, 1.0), (0.0, 1.0), (0.0, 1.0)]

    # Wire-format categorical (quantized collective engine): tuned like
    # the boolean toggles, as a relaxed [0,1] dimension of the same GP
    # rounded into thirds at application.  int4 is deliberately absent —
    # without error feedback (an optimizer-state concern the runtime
    # cannot provide) it trades too much gradient fidelity to auto-pick.
    COMPRESSION_CHOICES = ("none", "bf16", "int8")

    # Overlap bucket-size categorical (backward-overlap scheduler,
    # ops/overlap.py): 0 = bucketing off (the per-leaf barrier
    # schedule), else the bucket size in bytes — log2-spaced because
    # the overlap/launch-overhead trade is multiplicative.  Tuned
    # jointly with fusion/cycle/compression: the schedule is
    # value-invariant (bit parity); an explicit
    # HVD_TPU_OVERLAP_BUCKET_BYTES pins the dimension.  Callers may
    # restrict the grid via ``overlap_choices`` — the native controller
    # excludes 0 on multi-rank jobs, because a live on<->off flip is
    # rank-0-local and changes the eager collective NAME sequence
    # (barrier auto-names vs the queue's leaf names), which would
    # desync negotiation; bucket-SIZE flips are name-invariant.
    OVERLAP_CHOICES = (0, 2 << 20, 8 << 20, 32 << 20)

    # Crossover-shift grid for dispatch mode (see ``dispatch_shifts``):
    # the probe-seeded table is the warm start (shift 0); ±1 moves every
    # crossover boundary of that op kind by one payload bucket.
    SHIFT_CHOICES = (-1, 0, 1)

    def __init__(self, apply_fn, max_samples: int = 20,
                 window_seconds: float = 2.0,
                 log_file: Optional[str] = None, seed: int = 0,
                 warmup_samples: int = 3, steps_per_sample: int = 0,
                 gp_noise: float = 0.8,
                 initial_toggles: Tuple[bool, bool, bool] =
                 (False, False, True),
                 tune_toggles: bool = True,
                 initial_compression: str = "none",
                 tune_compression: bool = False,
                 initial_overlap: int = 0,
                 tune_overlap: bool = False,
                 overlap_choices=None,
                 dispatch_shifts: bool = False):
        """apply_fn(fusion_bytes: int, cycle_ms: float, hierarchical_
        allreduce: bool, hierarchical_allgather: bool, cache_enabled:
        bool, compression: str, overlap_bucket_bytes: int) applies
        parameters to the runtime (native SetParams + SetTunedToggles +
        SetWireCompression + the overlap engine's session bucket size).

        ``warmup_samples`` windows are discarded (not fed to the GP) to
        skip compile/cache-cold noise; ``steps_per_sample > 0`` closes a
        window every N traffic reports instead of by wall-clock — the
        reference's step-counted sampling (--autotune-steps-per-sample).
        ``initial_toggles`` seeds the bootstrap plan with the configured
        algorithm choice.  ``tune_toggles`` is a per-toggle bool triple
        (a plain bool applies to all three): a pinned toggle stays at
        its initial value and is never explored — flipping a toggle
        that cannot take effect (hierarchical with one node, cache with
        capacity 0) would burn sample budget re-measuring an identical
        configuration.  ``initial_compression``/``tune_compression`` do
        the same for the wire-format categorical (COMPRESSION_CHOICES);
        an explicitly-configured format stays pinned.
        ``initial_overlap``/``tune_overlap`` handle the overlap
        bucket-size categorical (``overlap_choices``, default
        OVERLAP_CHOICES, 0 = off): the bootstrap demonstrably tries
        each choice (overlap OFF against each bucket size, when 0 is in
        the grid) before EI takes over, and an explicitly-configured
        size (HVD_TPU_OVERLAP_BUCKET_BYTES, or any off-grid value) pins
        the dimension.

        ``dispatch_shifts``: once a topology-probed dispatch table is
        installed (ops/dispatch.py), the two hierarchical dims stop
        being blind whole-range booleans and become bounded crossover
        SHIFTS in {-1, 0, +1} over that table — the probe result is the
        warm start, the GP only refines where the flat/hier boundary
        sits.  ``initial_toggles[0:2]`` are then initial shifts (ints)
        and apply_fn receives shift ints in those positions."""
        self._apply = apply_fn
        self._dispatch_shifts = bool(dispatch_shifts)
        if self._dispatch_shifts:
            init_toggles = (
                min(max(int(initial_toggles[0]), -1), 1),
                min(max(int(initial_toggles[1]), -1), 1),
                bool(initial_toggles[2]))
        else:
            init_toggles = tuple(bool(t) for t in initial_toggles)
        if isinstance(tune_toggles, (tuple, list)):
            tunable = tuple(bool(t) for t in tune_toggles)
        else:
            tunable = (bool(tune_toggles),) * 3
        if initial_compression not in self.COMPRESSION_CHOICES:
            # int4/fp16 (or a typo) cannot be represented in the tuned
            # space: respect it by pinning, never by silently replacing.
            tune_compression = False
        self._initial_compression = initial_compression
        self._tune_compression = bool(tune_compression)
        self._overlap_choices = (tuple(int(c) for c in overlap_choices)
                                 if overlap_choices else
                                 self.OVERLAP_CHOICES)
        initial_overlap = int(initial_overlap)
        if initial_overlap not in self._overlap_choices:
            # An explicit off-grid bucket size: respect by pinning.
            tune_overlap = False
        self._initial_overlap = initial_overlap
        self._tune_overlap = bool(tune_overlap)
        # Pin the GP's candidate dims for non-tunable toggles (toggle
        # bounds are [0,1], so normalized == raw value; shift dims pin
        # at the center of their third).
        pinned = {2 + i: self._toggle_coord(i, init_toggles[i])
                  for i in range(3) if not tunable[i]}
        if not self._tune_compression:
            pinned[5] = self._compression_x(initial_compression)
        if not self._tune_overlap:
            pinned[6] = self._overlap_x(initial_overlap)
        self._opt = BayesianOptimizer(
            self.BOUNDS, seed=seed, noise=gp_noise, pinned=pinned)
        self._max_samples = max_samples
        self._window = window_seconds
        self._warmup_left = max(0, warmup_samples)
        self._steps_per_sample = max(0, steps_per_sample)
        self._steps_in_window = 0
        self._log_file = log_file
        self._samples = 0
        self._frozen = False
        self._current = None
        self._initial_toggles = init_toggles
        self._tunable = tunable
        # Deterministic categorical bootstrap (the reference's grids try
        # every categorical value; here: the configured combo, then each
        # TUNABLE toggle flipped once, then each non-initial wire format
        # once, then each non-initial overlap bucket size once — so
        # "overlap off vs each bucket size" is a controlled comparison).
        # Numeric dims stay GP-proposed.
        if any(self._tunable) or self._tune_compression or \
                self._tune_overlap:
            t0 = self._initial_toggles + (self._initial_compression,
                                          self._initial_overlap)
            self._toggle_plan = [t0]
            for i in range(3):
                if not self._tunable[i]:
                    continue
                # Alternatives per dim: a boolean flips once; a
                # dispatch-mode shift dim tries each other crossover
                # shift (so ±1 are both demonstrably measured against
                # the probe's warm start before EI takes over).
                if self._dispatch_shifts and i < 2:
                    alts = [s for s in self.SHIFT_CHOICES if s != t0[i]]
                else:
                    alts = [not t0[i]]
                self._toggle_plan += [
                    tuple(a if j == i else t0[j] for j in range(3))
                    + (self._initial_compression, self._initial_overlap)
                    for a in alts]
            if self._tune_compression:
                self._toggle_plan += [
                    self._initial_toggles + (c, self._initial_overlap)
                    for c in self.COMPRESSION_CHOICES
                    if c != self._initial_compression]
            if self._tune_overlap:
                self._toggle_plan += [
                    self._initial_toggles + (self._initial_compression, o)
                    for o in self._overlap_choices
                    if o != self._initial_overlap]
        else:
            self._toggle_plan = []
        # The plan holds the numeric dims FIXED across the toggle flips:
        # a controlled comparison, so fusion/cycle variation (which can
        # swing throughput far more than ~20%) cannot confound the
        # categorical signal.  The reference's nested grids get the same
        # property structurally.
        self._plan_numeric = None
        self._window_start = time.perf_counter()
        self._bytes = 0
        # Autotune decisions feed the metrics registry: which parameters
        # are live right now, how many sample windows were scored, and
        # whether the tuner froze — queryable next to the throughput
        # they produced instead of buried in the CSV log.
        from .metrics.registry import registry as _metrics_registry
        _mreg = _metrics_registry()
        self._m_samples = _mreg.counter(
            "hvd_autotune_samples_total",
            "Scored autotune sample windows")
        self._m_decisions = _mreg.counter(
            "hvd_autotune_decisions_total",
            "Parameter applications by the autotuner")
        self._m_fusion = _mreg.gauge(
            "hvd_autotune_fusion_bytes",
            "Fusion threshold currently applied by the autotuner")
        self._m_cycle = _mreg.gauge(
            "hvd_autotune_cycle_ms",
            "Cycle time currently applied by the autotuner")
        self._m_frozen = _mreg.gauge(
            "hvd_autotune_frozen",
            "1 once the autotuner froze its best parameters")
        self._propose()

    @property
    def frozen(self) -> bool:
        return self._frozen

    @property
    def current(self):
        """(fusion_bytes, cycle_ms, hier_allreduce, hier_allgather,
        cache_enabled, compression, overlap_bucket_bytes)"""
        return self._current

    def _toggle_coord(self, i: int, v) -> float:
        """Normalized GP coordinate of one toggle value: booleans sit at
        the interval ends; dispatch-mode shift dims at the center of
        their third (stable rounding, like compression)."""
        if self._dispatch_shifts and i < 2:
            return (min(max(int(v), -1), 1) + 1 + 0.5) / 3.0
        return 1.0 if v else 0.0

    def _round_toggles(self, x) -> Tuple:
        out = []
        for i in range(3):
            if not self._tunable[i]:
                out.append(self._initial_toggles[i])
            elif self._dispatch_shifts and i < 2:
                out.append(min(int(float(x[2 + i]) * 3), 2) - 1)
            else:
                out.append(bool(x[2 + i] >= 0.5))
        return tuple(out)

    @classmethod
    def _compression_x(cls, comp: str) -> float:
        """Normalized GP coordinate of a wire format: the center of its
        third (so rounding is stable against GP jitter)."""
        choices = cls.COMPRESSION_CHOICES
        idx = choices.index(comp) if comp in choices else 0
        return (idx + 0.5) / len(choices)

    def _round_compression(self, x) -> str:
        if not self._tune_compression:
            return self._initial_compression
        n = len(self.COMPRESSION_CHOICES)
        idx = min(int(float(x[5]) * n), n - 1)
        return self.COMPRESSION_CHOICES[idx]

    def _overlap_x(self, overlap: int) -> float:
        """Normalized GP coordinate of an overlap bucket size: the
        center of its grid cell (stable rounding, like compression)."""
        choices = self._overlap_choices
        idx = choices.index(overlap) if overlap in choices else 0
        return (idx + 0.5) / len(choices)

    def _round_overlap(self, x) -> int:
        if not self._tune_overlap:
            return self._initial_overlap
        n = len(self._overlap_choices)
        idx = min(int(float(x[6]) * n), n - 1)
        return self._overlap_choices[idx]

    def _propose(self):
        if self._toggle_plan:
            if self._plan_numeric is None:
                x = self._opt.suggest()
                self._plan_numeric = (int(2 ** x[0]), float(x[1]))
            self._current = self._plan_numeric + self._toggle_plan.pop(0)
        else:
            x = self._opt.suggest()
            self._current = ((int(2 ** x[0]), float(x[1]))
                             + self._round_toggles(x)
                             + (self._round_compression(x),)
                             + (self._round_overlap(x),))
        self._apply(*self._current)
        self._record_applied()

    def _record_applied(self):
        self._m_decisions.inc()
        self._m_fusion.set(self._current[0])
        self._m_cycle.set(self._current[1])
        # Flight event: autotune decisions were metrics-only, invisible
        # to the drift diagnoser — a regression that starts right after
        # a parameter application should name the tuner as the suspect
        # (debug/regression.py correlates perf.drift onsets against
        # these).
        from .debug import flight as _flight
        # In dispatch mode slots 2/3 are crossover SHIFTS (ints) over
        # the probe-seeded table, not whole-range booleans — record the
        # raw value either way so the drift diagnoser quotes what was
        # actually applied.
        _flight.record(
            "autotune.decision", None,
            fusion_bytes=int(self._current[0]),
            cycle_ms=round(float(self._current[1]), 3),
            hierarchical_allreduce=(int(self._current[2])
                                    if self._dispatch_shifts
                                    else bool(self._current[2])),
            hierarchical_allgather=(int(self._current[3])
                                    if self._dispatch_shifts
                                    else bool(self._current[3])),
            cache_enabled=bool(self._current[4]),
            compression=self._current[5],
            overlap_bucket_bytes=int(self._current[6]),
            frozen=self._frozen)

    def record_bytes(self, nbytes: int):
        """Feed data-plane traffic; closes a window when enough time passed
        (or, in step-counted mode, after steps_per_sample reports)."""
        if self._frozen:
            return
        self._bytes += int(nbytes)
        now = time.perf_counter()
        elapsed = now - self._window_start
        if self._steps_per_sample > 0:
            self._steps_in_window += 1
            if self._steps_in_window < self._steps_per_sample:
                return
        elif elapsed < self._window:
            return
        score = self._bytes / max(elapsed, 1e-9)
        self._observe(score)
        self._bytes = 0
        self._steps_in_window = 0
        self._window_start = now

    def _x_of_current(self) -> np.ndarray:
        return np.array(
            [math.log2(self._current[0]), self._current[1]]
            + [self._toggle_coord(i, self._current[2 + i])
               for i in range(3)]
            # De-normalize the categorical coordinates back into their
            # raw [0,1] bounds (observe() re-normalizes; toggle bounds
            # are [0,1] so this is the identity for them too).
            + [self._compression_x(self._current[5]),
               self._overlap_x(self._current[6])])

    def _observe(self, score: float):
        if self._warmup_left > 0:
            # Warmup windows (compile/cold-cache noise) are logged but not
            # fed to the GP and do not count toward max_samples.  The
            # current proposal stays applied — re-proposing here would
            # burn bootstrap-plan entries on discarded windows.
            self._warmup_left -= 1
            self._log(score, tag="warmup")
            return
        self._opt.observe(self._x_of_current(), score)
        self._log(score)
        self._samples += 1
        self._m_samples.inc()
        if self._samples >= self._max_samples:
            best_x, best_y = self._opt.best()
            self._current = ((int(2 ** best_x[0]), float(best_x[1]))
                             + tuple(self._round_toggles(best_x))
                             + (self._round_compression(best_x),)
                             + (self._round_overlap(best_x),))
            self._apply(*self._current)
            self._record_applied()
            self._frozen = True
            self._m_frozen.set(1)
            self._log(best_y, tag="final")
        else:
            self._propose()

    def _log(self, score: float, tag: str = "sample"):
        if not self._log_file:
            return
        try:
            with open(self._log_file, "a") as f:
                f.write(f"{tag},{self._current[0]},{self._current[1]:.3f},"
                        f"{int(self._current[2])},{int(self._current[3])},"
                        f"{int(self._current[4])},{self._current[5]},"
                        f"{int(self._current[6])},{score:.1f}\n")
        except OSError:
            pass
