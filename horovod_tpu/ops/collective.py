"""Public collective ops: allreduce / allgather / broadcast / alltoall /
reducescatter / join / barrier — with compiled and eager paths.

The reference exposes seven ``EnqueueTensor*`` entry points feeding a
background negotiation loop (operations.cc:919-1226).  TPU-native, each op is
**two-mode** (the plan in SURVEY.md §7.3, mirroring the reference's TF
graph/eager split at tensorflow/__init__.py:400-403):

* **Compiled path** — called on tracers inside ``jit``/``shard_map``: lowers
  directly to ``jax.lax`` collectives over a named mesh axis.  XLA schedules,
  fuses and overlaps them on ICI; no controller, no fusion buffer — the
  compiler owns what Horovod's background thread did at runtime.
* **Eager path** — called on concrete arrays: dispatches through
  ``ops.eager`` (native controller / multi-process JAX / single-process).

Reduce-op codes match the reference C API (operations.cc:911-913).
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from ..core import handles as _handles
from ..core.state import global_state, DATA_AXIS
from ..debug import flight as _flight
from . import eager as _eager
from .adasum import adasum_allreduce, adasum_tree


class ReduceOp(int):
    pass


# Reference reduce-op codes: horovod_reduce_op_average/sum/adasum
# (operations.cc:905-915); Min/Max/Product are post-0.21 additions kept for
# forward compatibility.
Average = ReduceOp(0)
Sum = ReduceOp(1)
Adasum = ReduceOp(2)
Min = ReduceOp(3)
Max = ReduceOp(4)
Product = ReduceOp(5)


# Per-kind metric children cached after first use (the registry lookup
# costs a lock + dict walk; the cached child is a straight attribute).
_coll_metrics = {}

# Per-(kind, schedule) exposed-comm seconds — the dispatch-plane
# attribution surface: a drift report can say WHICH schedule's wire
# time grew, so a bad dispatch decision is a nameable suspect.
_sched_metrics = {}


def _schedule_seconds(kind: str, schedule: str):
    rec = _sched_metrics.get((kind, schedule))
    if rec is None:
        from ..metrics.registry import registry
        rec = registry().counter(
            "hvd_collective_schedule_seconds_total",
            "Eager collective wall seconds by the dispatch table's "
            "schedule choice (flat vs hier) — exposed-comm attribution "
            "per schedule", kind=kind, schedule=schedule)
        _sched_metrics[(kind, schedule)] = rec
    return rec


def _collective_metrics(kind: str):
    rec = _coll_metrics.get(kind)
    if rec is None:
        from ..metrics.registry import (DEFAULT_TIME_BUCKETS, registry)
        reg = registry()
        rec = (
            reg.counter("hvd_collective_ops_total",
                        "Eager collective operations", kind=kind),
            reg.counter("hvd_collective_bytes_total",
                        "Eager collective payload bytes", kind=kind),
            reg.histogram("hvd_collective_latency_seconds",
                          "Eager collective wall time (enqueue to "
                          "result)", buckets=DEFAULT_TIME_BUCKETS,
                          kind=kind),
            reg.counter("hvd_wire_bytes_raw_total",
                        "Pre-compression payload bytes offered to the "
                        "wire", kind=kind),
            reg.counter("hvd_wire_bytes_sent_total",
                        "Payload bytes after the selected wire format",
                        kind=kind),
            reg.gauge("hvd_wire_compression_ratio",
                      "raw/sent wire-byte ratio of the most recent op",
                      kind=kind),
        )
        _coll_metrics[kind] = rec
    return rec


# Overlap-submission marker: the bucket queue's sync-fallback submits
# (no controller / tracer input: allreduce_async degrades to the sync
# allreduce inside _op_range) land in BOTH the latency histogram and
# the queue's own exposed-seconds counter.  The native/device async
# paths never touch the histogram, so the step attribution cannot just
# subtract the full exposed total from the histogram delta — this scope
# prices exactly the overlap-managed share that doubled into the
# histogram (hvd_overlap_fallback_latency_seconds_total), and
# metrics/attribution.py subtracts that.
_overlap_submit = threading.local()
_overlap_fallback_lat = None


@contextlib.contextmanager
def overlap_submit_scope():
    """Mark this thread as inside the overlap scheduler's bucket
    submission (ops/overlap.py EagerBucketQueue.launch)."""
    prev = getattr(_overlap_submit, "active", False)
    _overlap_submit.active = True
    try:
        yield
    finally:
        _overlap_submit.active = prev


def _overlap_fallback_metric():
    global _overlap_fallback_lat
    if _overlap_fallback_lat is None:
        from ..metrics.registry import registry
        _overlap_fallback_lat = registry().counter(
            "hvd_overlap_fallback_latency_seconds_total",
            "Latency-histogram seconds recorded by overlap-submitted "
            "sync-fallback collectives — the overlap share the step "
            "attribution subtracts from the histogram delta so "
            "overlap-managed wire time is counted once")
    return _overlap_fallback_lat


# Comm-side chaos (HVD_TPU_CHAOS_COMM_DELAY_MS): the wire analog of the
# input pipeline's HVD_TPU_CHAOS_INPUT_DELAY_MS drill — every eager
# collective pays a deterministic extra delay inside its measured span,
# so the observatory sees comm_exposed grow and the closed-loop drill
# (tests/test_tuning_loop.py) can inject a comm regression without
# touching real hardware.  Read once and cached (this sits on the hot
# path); reset_comm_chaos() re-reads the knob, the drill's mid-run
# flip.  Inert unless the knob is set.
_comm_chaos_delay: Optional[float] = None


def _chaos_comm_delay_s() -> float:
    global _comm_chaos_delay
    if _comm_chaos_delay is None:
        from ..core.config import get_float
        d = max(0.0, get_float("CHAOS_COMM_DELAY_MS", 0.0)) / 1e3
        _comm_chaos_delay = d
        if d:
            # Flight-recorded at activation, like data.chaos_delay: the
            # drift diagnoser's causal window must contain the cause.
            _flight.record("net.chaos_delay", "eager", delay_ms=d * 1e3)
    return _comm_chaos_delay


def reset_comm_chaos() -> None:
    """Re-read HVD_TPU_CHAOS_COMM_DELAY_MS at the next collective."""
    global _comm_chaos_delay
    _comm_chaos_delay = None


def _wire_sent_bytes(tensor, comp) -> Optional[int]:
    """Bytes the EAGER transport actually moves for ``tensor`` (None
    when unknown).  Cast compressors genuinely shrink the payload before
    transport; quantized formats only value-emulate on the eager host
    paths — their byte savings live on the negotiated device plane,
    whose executor prices the real staged wire under
    ``kind="device_plane"`` — so they count raw here."""
    nbytes = getattr(tensor, "nbytes", None)
    if nbytes is None:
        return None
    if comp is None or not hasattr(tensor, "dtype"):
        return nbytes
    import jax.numpy as jnp
    if not jnp.issubdtype(tensor.dtype, jnp.floating):
        return nbytes
    if getattr(comp, "wire_dtype", None) is not None:
        return int(getattr(tensor, "size", 0)) * \
            jnp.dtype(comp.wire_dtype).itemsize
    return nbytes


@contextlib.contextmanager
def _op_range(kind: str, name, tensor, comp=None):
    """Profiler span + metrics around an eager collective (NVTX-range
    analog, utils/profiler.py); payload size mirrors the reference's
    grouped-bytes annotation (operations.cc:1018-1033).  The same span
    feeds ``hvd_collective_{ops,bytes}_total``, the latency histogram
    and the wire-byte raw/sent counters in the ``hvd.metrics``
    registry; ``comp`` (a Compressor class) annotates the chosen wire
    format on the flight event and prices the sent bytes."""
    from ..utils.profiler import op_range
    from . import dispatch as _dispatch
    nbytes = getattr(tensor, "nbytes", None)
    ops, bts, lat, raw_c, sent_c, ratio_g = _collective_metrics(kind)
    # Dispatch annotation (advisory mirror of the coordinator's
    # response-stream stamp): which schedule the active table picks for
    # this payload — the hang-report evidence of which path a stuck
    # collective took, like PR 5's wire= annotation.  The table keys on
    # the payload the COORDINATOR stamps from: for allgather that is
    # the FULL gathered result, not this rank's contribution (equal
    # first dims assumed — the local estimate; uneven gathers may sit
    # one bucket off near a crossover).
    ann_bytes = nbytes
    if ann_bytes is not None and kind == "allgather":
        ann_bytes = ann_bytes * communicator_size()
    sched = _dispatch.annotate(kind, ann_bytes)
    # Flight recorder: the enqueue event is what a hang report quotes —
    # an op stuck inside the yield never reaches the done event, so the
    # dangling enqueue IS the evidence of where the rank blocked.
    fields = {"op": kind, "bytes": nbytes}
    if comp is not None:
        fields["wire"] = comp.wire
    if sched is not None:
        fields["schedule"] = sched
    _flight.record("collective.enqueue", name, **fields)
    t0 = time.perf_counter()
    try:
        with op_range(f"hvd.{kind}.{name or 'unnamed'}", nbytes):
            yield
    finally:
        chaos = _chaos_comm_delay_s()
        if chaos:
            time.sleep(chaos)  # inside the timed span: latency pays it
        ops.inc()
        if nbytes:
            bts.inc(float(nbytes))
            sent = _wire_sent_bytes(tensor, comp)
            raw_c.inc(float(nbytes))
            if sent:
                sent_c.inc(float(sent))
                ratio_g.set(nbytes / sent)
        dt = time.perf_counter() - t0
        lat.observe(dt)
        if sched is not None:
            _schedule_seconds(kind, sched).inc(dt)
        if getattr(_overlap_submit, "active", False):
            _overlap_fallback_metric().inc(dt)
        _flight.record("collective.done", name, op=kind, dur_s=dt)


def _is_tracer(tensor) -> bool:
    try:
        import jax
        return isinstance(tensor, jax.core.Tracer)
    except Exception:
        return False


def _default_axis(axis_name: Optional[str]) -> str:
    if axis_name is not None:
        return axis_name
    return DATA_AXIS


def _axis_size(axis_name: str) -> int:
    from ..compat import axis_size
    return axis_size(axis_name)


# ---------------------------------------------------------------------------
# wire compression (quantized collective engine, ops/quantization.py)
# ---------------------------------------------------------------------------

def _resolve_compression(compression):
    """Normalize a ``compression=`` argument (Compressor class, name
    string, or None) to a real compressor class or None.  A None
    argument falls back to the session default (the HVD_TPU_COMPRESSION
    knob captured by ``init()``), so the eager plane honors the
    configured wire format without every call site threading it."""
    from .compression import NoneCompressor, by_name
    if compression is None:
        cfg = global_state.config
        name = getattr(cfg, "compression", "none") if cfg else "none"
        if name in ("", "none"):
            return None
        compression = by_name(name)
    if isinstance(compression, str):
        compression = by_name(compression)
    if compression is None or compression is NoneCompressor or \
            getattr(compression, "wire", "none") == "none":
        return None
    return compression


def _compressible(tensor, op: int) -> bool:
    """A lossy wire only composes with Sum/Average over floats."""
    import jax.numpy as jnp
    return op in (Sum, Average) and hasattr(tensor, "dtype") and \
        jnp.issubdtype(tensor.dtype, jnp.floating)


def _check_compressible(tensor, op: int, explicit: bool) -> bool:
    """Gate the compressed path.  An explicitly-requested compressor on
    an incompatible op/dtype raises (silent fp32 fallback would misstate
    the wire); the session-default knob degrades silently — it must not
    break integer broadcasts or Min/Max reductions that share the API."""
    ok = _compressible(tensor, op)
    if not ok and explicit:
        raise ValueError(
            "compression requires a floating tensor and op Sum/Average "
            f"(got dtype {getattr(tensor, 'dtype', None)}, op {int(op)})")
    return ok


def _eager_wire_emulate(comp, tensor):
    """Eager-path value semantics for a quantized wire: round the local
    contribution to the wire grid (Q = quantize∘dequantize) so results
    match the compiled two-pass schedule's first pass.  The *byte*
    compression on the eager planes lives in the negotiated device-plane
    executor (response-stream wire format); host TCP rings still move
    the original dtype."""
    from .quantization import qdq_host
    return qdq_host(tensor, comp.spec())


def _eager_rs_wire_emulate(comp, tensor):
    """Reducescatter variant of the wire emulation: the compiled
    schedule (``compressed_reducescatter``) quantizes each destination
    chunk as its own row — blocks never straddle chunk boundaries — so
    value parity requires chunk-local Q here too, not one flat Q over
    the whole tensor."""
    world = communicator_size()
    rows = getattr(tensor, "shape", (0,))[0] if \
        getattr(tensor, "ndim", 0) else 0
    if world <= 1 or rows == 0 or rows % world:
        # Degenerate/invalid dims: plain emulation; eager.reducescatter
        # raises the dim error with its own message.
        return _eager_wire_emulate(comp, tensor)
    chunk = rows // world
    parts = [_eager_wire_emulate(comp, tensor[i * chunk: (i + 1) * chunk])
             for i in range(world)]
    if _eager._is_device_array(tensor):
        import jax.numpy as jnp
        return jnp.concatenate(parts, axis=0)
    import numpy as np_
    return np_.concatenate(parts, axis=0)


# ---------------------------------------------------------------------------
# allreduce
# ---------------------------------------------------------------------------

def _compiled_allreduce(tensor, op: int, axis_name: str,
                        prescale_factor: float, postscale_factor: float,
                        comp=None):
    import jax.numpy as jnp
    from jax import lax

    # Contract (both paths): out.dtype == in.dtype.  Integer Average is
    # computed exactly in the integer domain (psum + floor-div) — float
    # widening cannot promise exactness under jit, where float64
    # canonicalizes to float32 unless x64 is enabled.  Fractional scale
    # factors on integers still go through float (casting 0.5 into an int
    # dtype would zero the reduction); values beyond the float mantissa
    # are the caller's precision trade-off there.
    in_dtype = tensor.dtype
    is_int = not jnp.issubdtype(in_dtype, jnp.inexact)
    needs_float = (prescale_factor != 1.0 or postscale_factor != 1.0) \
        and is_int
    if needs_float:
        tensor = tensor.astype(jnp.float32)
    if prescale_factor != 1.0:
        tensor = tensor * jnp.asarray(prescale_factor, dtype=tensor.dtype)
    if op == Sum:
        out = lax.psum(tensor, axis_name)
    elif op == Average:
        if is_int and not needs_float:
            out = lax.psum(tensor, axis_name) // _axis_size(axis_name)
        else:
            out = lax.pmean(tensor, axis_name)
    elif op == Min:
        out = lax.pmin(tensor, axis_name)
    elif op == Max:
        out = lax.pmax(tensor, axis_name)
    elif op == Product:
        out = jnp.prod(lax.all_gather(tensor, axis_name), axis=0)
    elif op == Adasum:
        if isinstance(axis_name, (tuple, list)) and len(axis_name) == 2:
            # Hierarchical Adasum over (local, cross) mesh axes
            # (reference adasum_gpu_operations.cc:38-…): intra-axis
            # reduce-scatter, cross-axis VHDD, intra-axis all-gather.
            # ``comp`` (quantized/cast wire) rides the intra-node
            # phases — Adasum on top of compressed hierarchical
            # reduction (ops/adasum.py).
            from .adasum import adasum_allreduce_hierarchical
            spec = comp.spec() if comp is not None else None
            out = adasum_allreduce_hierarchical(
                tensor, axis_name[0], axis_name[1], spec=spec,
                wire_dtype=(comp.wire_dtype if comp is not None and
                            spec is None else None))
        else:
            if comp is not None:
                raise ValueError(
                    "compression with op=Adasum requires a (local, "
                    "cross) axis_name pair — the compressed wire rides "
                    "the hierarchical schedule's intra-node phases")
            out = adasum_allreduce(tensor, axis_name)
    else:
        raise ValueError(f"unknown reduce op {op}")
    if postscale_factor != 1.0:
        out = out * jnp.asarray(postscale_factor, dtype=out.dtype)
    if out.dtype != in_dtype:
        out = out.astype(in_dtype)
    return out


@functools.lru_cache(maxsize=256)
def _eager_op_fn_f32acc(op: int, prescale_factor: float,
                        postscale_factor: float):
    """Stack reducer for cast-compressed eager payloads: upcast the wire
    dtype to fp32 before accumulating, cast back after — the same
    accumulation contract as the compiled two-pass schedule.  Cached for
    the same reducer-identity reason as ``_eager_op_fn``."""
    base = _eager_op_fn(op, prescale_factor, postscale_factor)

    def fn(stack):
        import jax.numpy as jnp
        if not jnp.issubdtype(stack.dtype, jnp.floating):
            return base(stack)
        return base(stack.astype(jnp.float32)).astype(stack.dtype)
    return fn


@functools.lru_cache(maxsize=256)
def _eager_op_fn(op: int, prescale_factor: float, postscale_factor: float):
    """Build a stack-reducer callable((P, ...)) -> (...) for the eager path.
    Cached so repeat calls return the same callable — the eager device
    plane's jit cache is keyed on reducer identity."""
    def fn(stack):
        import jax.numpy as jnp
        x = stack
        # Same contract as the compiled path: integer Average stays exact
        # in the integer domain (sum + floor-div); fractional scale
        # factors on integers go through float32 with one trailing
        # truncation.
        is_int = not jnp.issubdtype(stack.dtype, jnp.inexact)
        needs_float = (prescale_factor != 1.0 or
                       postscale_factor != 1.0) and is_int
        if needs_float:
            x = x.astype(jnp.float32)
        if prescale_factor != 1.0:
            x = x * jnp.asarray(prescale_factor, dtype=x.dtype)
        if op == Sum:
            out = x.sum(axis=0)
        elif op == Average:
            if is_int and not needs_float:
                out = x.sum(axis=0) // x.shape[0]
            else:
                out = x.mean(axis=0)
        elif op == Min:
            out = x.min(axis=0)
        elif op == Max:
            out = x.max(axis=0)
        elif op == Product:
            out = jnp.prod(x, axis=0)
        elif op == Adasum:
            out = adasum_tree(x)
        else:
            raise ValueError(f"unknown reduce op {op}")
        if postscale_factor != 1.0:
            out = out * jnp.asarray(postscale_factor, dtype=out.dtype)
        # Dtype fidelity: integer reductions promote (numpy sums uint8/
        # int32 to the platform int) — the contract is out.dtype ==
        # in.dtype, like the wire backends.
        if out.dtype != stack.dtype:
            out = out.astype(stack.dtype)
        return out
    return fn


def allreduce(tensor,
              op: int = Average,
              axis_name: Optional[str] = None,
              prescale_factor: float = 1.0,
              postscale_factor: float = 1.0,
              name: Optional[str] = None,
              compression=None):
    """Allreduce a tensor across the communicator.

    Inside jit/shard_map: reduces over mesh axis ``axis_name`` (default
    "data").  Eagerly: reduces across processes.  Prescale/postscale mirror
    the reference's fused scale kernels (nccl_operations.cc:153-172).

    ``compression`` (``hvd.Compression.{fp16,bf16,int8,int4}``, a name
    string, or None for the HVD_TPU_COMPRESSION session default) selects
    the wire format.  Compiled path: the op routes through the two-pass
    schedule in ``ops.quantization`` — both wire passes move compressed
    bytes, accumulation is always fp32.  Eager path: quantized formats
    round contributions and results to the wire grid (byte compression
    happens on the negotiated device plane via the response-stream wire
    format); cast formats compress the payload and reduce with fp32
    accumulation in the jitted regimes (the native host rings reduce in
    the wire dtype — see docs/compression.md).
    """
    explicit = compression is not None
    if _is_tracer(tensor):
        # The session-default knob is eager-plane scope ONLY: a compiled
        # gradient reduction must opt in explicitly (DistributedOptimizer
        # (compression=…)), because lossy quantization without the
        # optimizer's error-feedback residual silently degrades
        # convergence — the env var must not do that behind a jit.
        comp = _resolve_compression(compression) if explicit else None
        hier2 = isinstance(axis_name, (tuple, list)) and len(axis_name) == 2
        if comp is not None and op == Adasum:
            # Adasum-on-compressed-hierarchical-reduction: the wire
            # rides the intra-node phases; _compiled_allreduce threads
            # the compressor through (and raises on a flat axis, where
            # there is no intra-node wire to compress).
            return _compiled_allreduce(tensor, op, axis_name,
                                       prescale_factor, postscale_factor,
                                       comp=comp)
        if comp is not None and _check_compressible(tensor, op, explicit):
            from . import quantization as Q
            spec = comp.spec()
            if hier2:
                # Two-level compressed schedule over (local, cross)
                # axes: cross-node bytes shrink by the local world size
                # AND the wire format (Q.compressed_allreduce_
                # hierarchical).
                return Q.compressed_allreduce_hierarchical(
                    tensor, axis_name[0], axis_name[1], op, spec=spec,
                    wire_dtype=None if spec is not None
                    else comp.wire_dtype,
                    prescale=prescale_factor,
                    postscale=postscale_factor)
            return Q.compressed_allreduce(
                tensor, _default_axis(axis_name), op, spec=spec,
                wire_dtype=None if spec is not None else comp.wire_dtype,
                prescale=prescale_factor, postscale=postscale_factor)
        return _compiled_allreduce(tensor, op, _default_axis(axis_name),
                                   prescale_factor, postscale_factor)
    comp = _resolve_compression(compression)
    if comp is not None and not _check_compressible(tensor, op, explicit):
        comp = None
    with _op_range("allreduce", name, tensor, comp=comp):
        if comp is not None and comp.bits is not None:
            # fp32 accumulation even when the tensor dtype is bf16/fp16:
            # the emulated wire values must sum the way the compiled
            # two-pass schedule sums them.
            x = _eager_wire_emulate(comp, tensor)
            out = _eager.allreduce(
                x, op_fn=_eager_op_fn_f32acc(op, prescale_factor,
                                             postscale_factor),
                name=name, op_code=int(op), prescale=prescale_factor,
                postscale=postscale_factor)
            return _eager_wire_emulate(comp, out)
        if comp is not None:
            cx, ctx = comp.compress(tensor)
            out = _eager.allreduce(
                cx, op_fn=_eager_op_fn_f32acc(op, prescale_factor,
                                              postscale_factor),
                name=name, op_code=int(op), prescale=prescale_factor,
                postscale=postscale_factor)
            return comp.decompress(out, ctx)
        return _eager.allreduce(
            tensor, op_fn=_eager_op_fn(op, prescale_factor, postscale_factor),
            name=name, op_code=int(op), prescale=prescale_factor,
            postscale=postscale_factor)


def grouped_allreduce(tensors: Sequence,
                      op: int = Average,
                      axis_name: Optional[str] = None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      name: Optional[str] = None,
                      compression=None) -> List:
    """Allreduce a group atomically (reference: EnqueueTensorAllreduces with a
    shared group id, operations.cc:1041-1048; GroupTable group_table.h:30-59).
    On the compiled path XLA fuses the group into combined collectives; on
    the native eager path all members enqueue together so the runtime's
    fusion buffer batches them into shared ring launches.

    ``compression`` applies per member on the compiled and direct eager
    paths; on the negotiated controller planes the wire format comes
    from the coordinator's response-stream stamp instead (the fused
    Response is one payload — per-member formats cannot compose with
    fusion), so the argument only rounds members to the wire grid there.
    """
    tensors = list(tensors)
    comp = _resolve_compression(compression)
    first = tensors[0] if tensors else None
    ctl = global_state.controller
    if first is not None and not _is_tracer(first) and ctl is not None:
        if comp is not None and comp.bits is not None:
            # Round quantized-wire members to the wire grid before the
            # negotiated enqueue, mirroring the single-op eager path;
            # the byte compression itself is the response-stream wire
            # format's job (one format per fused Response).
            tensors = [_eager_wire_emulate(comp, t)
                       if _compressible(t, op) else t for t in tensors]
        from .eager import _ctl as _ctl_call, _is_device_array, \
            _negotiated_device_ready
        if all(_is_device_array(t) for t in tensors) and \
                _negotiated_device_ready(ctl):
            # Grouped DEVICE allreduce: all members enqueue together on
            # the negotiated device plane, so placement-keyed fusion
            # batches them into one fused HBM Response — no host copy.
            base = name or ctl._auto_name("grouped", None).decode()

            def _grouped_device():
                handles = []
                try:
                    for i, t in enumerate(tensors):
                        handles.append(ctl.allreduce_device_submit(
                            t, op=int(op), prescale=prescale_factor,
                            postscale=postscale_factor,
                            name=f"{base}.{i}"))
                    return [ctl.device_finish(*h) for h in handles]
                except BaseException:
                    # A submit failed mid-group (e.g. unsupported dtype
                    # at member i): drain the already-submitted handles
                    # so their native handles release and their staged
                    # HBM inputs unpin, then re-raise the original.
                    for h in handles:
                        try:
                            ctl.device_finish(*h)
                        except Exception:  # noqa: BLE001 — best effort
                            pass
                    raise
            return _ctl_call(_grouped_device)
        import numpy as _np
        return _ctl_call(ctl.grouped_allreduce,
                         [_np.asarray(t) for t in tensors], op=int(op),
                         prescale=prescale_factor,
                         postscale=postscale_factor, name=name)
    return [
        allreduce(t, op=op, axis_name=axis_name,
                  prescale_factor=prescale_factor,
                  postscale_factor=postscale_factor,
                  name=None if name is None else f"{name}.{i}",
                  compression=compression)
        for i, t in enumerate(tensors)
    ]


# ---------------------------------------------------------------------------
# allgather
# ---------------------------------------------------------------------------

def allgather(tensor, axis_name: Optional[str] = None,
              name: Optional[str] = None):
    """Gather tensors from all members, concatenated along dim 0.

    Compiled path requires equal shapes (static under XLA); the eager path
    supports unequal first dimensions like the reference
    (controller.cc:576-648).
    """
    if _is_tracer(tensor):
        from jax import lax
        return lax.all_gather(tensor, _default_axis(axis_name), tiled=True)
    with _op_range("allgather", name, tensor):
        return _eager.allgather(tensor, name=name)


# ---------------------------------------------------------------------------
# broadcast
# ---------------------------------------------------------------------------

def broadcast(tensor, root_rank: int = 0, axis_name: Optional[str] = None,
              name: Optional[str] = None):
    """Broadcast the root member's value to all members."""
    if _is_tracer(tensor):
        import jax.numpy as jnp
        from jax import lax
        ax = _default_axis(axis_name)
        # Masked psum: one reduction instead of a full gather; XLA lowers
        # this to an ICI broadcast-like pattern.
        idx = lax.axis_index(ax)
        mask = (idx == root_rank).astype(tensor.dtype)
        return lax.psum(tensor * mask, ax)
    with _op_range("broadcast", name, tensor):
        return _eager.broadcast(tensor, root_rank=root_rank, name=name)


# ---------------------------------------------------------------------------
# alltoall
# ---------------------------------------------------------------------------

def alltoall(tensor, splits: Optional[Sequence[int]] = None,
             axis_name: Optional[str] = None, name: Optional[str] = None):
    """Distribute dim-0 slices to each member; returns (received,
    received_splits) on the eager path (reference operations.cc:1136-1198);
    the compiled path requires equal splits (static shapes) and returns just
    the received tensor."""
    if _is_tracer(tensor):
        from jax import lax
        if splits is not None:
            raise ValueError(
                "compiled-path alltoall requires equal splits (splits=None); "
                "uneven splits need the eager path")
        return lax.all_to_all(tensor, _default_axis(axis_name),
                              split_axis=0, concat_axis=0, tiled=True)
    with _op_range("alltoall", name, tensor):
        return _eager.alltoall(tensor, splits=splits, name=name)


# ---------------------------------------------------------------------------
# reducescatter
# ---------------------------------------------------------------------------

def reducescatter(tensor, op: int = Average,
                  axis_name: Optional[str] = None,
                  name: Optional[str] = None,
                  compression=None):
    """Reduce then scatter equal dim-0 chunks (rank i gets chunk i).

    ``compression`` routes the compiled path through the one-pass
    quantized/cast reduce-scatter in ``ops.quantization`` (compressed
    wire, fp32 accumulation, full-precision output shard — ZeRO's
    gradient sharding rides this).  The eager path rounds the input to
    the wire grid for quantized formats (value parity with compiled).
    """
    explicit = compression is not None
    if _is_tracer(tensor):
        # Session default is eager-scope only — see allreduce.
        comp = _resolve_compression(compression) if explicit else None
        ax = _default_axis(axis_name)
        if comp is not None and _check_compressible(tensor, op, explicit):
            from . import quantization as Q
            spec = comp.spec()
            return Q.compressed_reducescatter(
                tensor, ax, op, spec=spec,
                wire_dtype=None if spec is not None else comp.wire_dtype)
        from jax import lax
        out = lax.psum_scatter(tensor, ax, scatter_dimension=0, tiled=True)
        if op == Average:
            out = out / _axis_size(ax)
        elif op != Sum:
            raise ValueError("compiled reducescatter supports Sum/Average")
        return out
    from . import eager
    comp = _resolve_compression(compression)
    if comp is not None and not _check_compressible(tensor, op, explicit):
        comp = None
    code = Sum if op == Sum else Average
    with _op_range("reducescatter", name, tensor, comp=comp):
        if comp is not None and comp.bits is not None:
            # One-pass schedule: quantize contributions, fp32-accumulate;
            # the output shard is NOT requantized — emulate accordingly,
            # with chunk-local block boundaries matching the compiled
            # schedule.
            x = _eager_rs_wire_emulate(comp, tensor)
            return eager.reducescatter(
                x, op_fn=_eager_op_fn_f32acc(code, 1.0, 1.0), name=name,
                op_code=int(code))
        if comp is not None:
            cx, ctx = comp.compress(tensor)
            out = eager.reducescatter(
                cx, op_fn=_eager_op_fn_f32acc(code, 1.0, 1.0), name=name,
                op_code=int(code))
            return comp.decompress(out, ctx)
        return eager.reducescatter(tensor,
                                   op_fn=_eager_op_fn(code, 1.0, 1.0),
                                   name=name, op_code=int(code))


# ---------------------------------------------------------------------------
# join / barrier
# ---------------------------------------------------------------------------

def communicator_size() -> int:
    """Size of the *eager* communicator: the native controller's world when
    attached, else the process count.  (``size()`` is chip-level and may
    exceed this in single-controller multi-device runs.)"""
    ctl = global_state.controller
    if ctl is not None:
        return ctl.size()
    return global_state.process_count


def join() -> int:
    return _eager.join()


def barrier() -> None:
    _eager.barrier()


# ---------------------------------------------------------------------------
# async handle API (eager path; reference torch/mpi_ops.py:843-882)
#
# With the native controller attached the op is genuinely in flight after
# *_async returns (the background runtime negotiates + streams while the
# caller computes); poll() answers completion without blocking and
# synchronize() finalizes.  Without a controller (single-process / jax
# regimes) the op completes synchronously and the handle wraps the result —
# the same degradation the reference has when size()==1.
# ---------------------------------------------------------------------------

def _native_async(submit, finish) -> int:
    """Submit through the native controller, return a managed handle whose
    wait finalizes (and releases) the native op exactly once.  Both legs go
    through eager._ctl so transport failures map to HorovodInternalError
    like the sync path."""
    from .eager import _ctl as _ctl_call
    ctl = global_state.controller
    submitted = _ctl_call(submit, ctl)
    h = submitted[0]
    return _handles.handle_manager.allocate(_handles.Handle(
        poll_fn=lambda: ctl.poll(h),
        wait_fn=lambda: _ctl_call(finish, ctl, submitted)))


def allreduce_async(tensor, op: int = Average, name: Optional[str] = None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    compression=None) -> int:
    """``compression`` carries the eager quantized/cast wire semantics
    onto the async path (the overlap scheduler's bucket dispatch rides
    this): quantized formats round the contribution and the result to
    the wire grid exactly like the synchronous ``allreduce``; cast
    formats shrink the in-flight payload and restore the dtype at
    ``synchronize``.  Explicit incompatible requests raise like the
    sync path; the session default degrades silently."""
    explicit = compression is not None
    comp = _resolve_compression(compression) if explicit else None
    if comp is not None and not _check_compressible(tensor, op, explicit):
        comp = None
    if comp is not None and (global_state.controller is None
                             or _is_tracer(tensor)):
        # Synchronous fallback: delegate to the sync compressed path
        # wholesale — same code, so the fp32 accumulation of wire
        # values survives (re-wrapping a plain async here would sum in
        # the tensor dtype and diverge from allreduce(compression=…)
        # for bf16/fp16 tensors).
        result = allreduce(tensor, op=op, name=name,
                           prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor,
                           compression=comp)
        return _handles.handle_manager.allocate(
            _handles.Handle(result=result))
    if comp is not None and comp.bits is not None:
        x = _eager_wire_emulate(comp, tensor)
        inner = allreduce_async(x, op=op, name=name,
                                prescale_factor=prescale_factor,
                                postscale_factor=postscale_factor)
        return _handles.handle_manager.allocate(_handles.Handle(
            poll_fn=lambda: poll(inner),
            wait_fn=lambda: _eager_wire_emulate(comp, synchronize(inner))))
    if comp is not None:
        cx, ctx = comp.compress(tensor)
        inner = allreduce_async(cx, op=op, name=name,
                                prescale_factor=prescale_factor,
                                postscale_factor=postscale_factor)
        return _handles.handle_manager.allocate(_handles.Handle(
            poll_fn=lambda: poll(inner),
            wait_fn=lambda: comp.decompress(synchronize(inner), ctx)))
    if global_state.controller is not None and not _is_tracer(tensor):
        return _native_async(
            lambda ctl: ctl.allreduce_submit(
                np.asarray(tensor), op=int(op), prescale=prescale_factor,
                postscale=postscale_factor, name=name),
            lambda ctl, s: ctl.allreduce_finish(s[0], s[2]))
    result = allreduce(tensor, op=op, name=name,
                       prescale_factor=prescale_factor,
                       postscale_factor=postscale_factor)
    return _handles.handle_manager.allocate(_handles.Handle(result=result))


def allgather_async(tensor, name: Optional[str] = None) -> int:
    if global_state.controller is not None and not _is_tracer(tensor):
        return _native_async(
            lambda ctl: ctl.allgather_submit(np.asarray(tensor), name=name),
            lambda ctl, s: ctl.allgather_finish(s[0], s[1]))
    result = allgather(tensor, name=name)
    return _handles.handle_manager.allocate(_handles.Handle(result=result))


def broadcast_async(tensor, root_rank: int = 0,
                    name: Optional[str] = None) -> int:
    if global_state.controller is not None and not _is_tracer(tensor):
        return _native_async(
            lambda ctl: ctl.broadcast_submit(
                np.asarray(tensor), root_rank=root_rank, name=name),
            lambda ctl, s: ctl.broadcast_finish(s[0], s[2]))
    result = broadcast(tensor, root_rank=root_rank, name=name)
    return _handles.handle_manager.allocate(_handles.Handle(result=result))


def alltoall_async(tensor, splits=None, name: Optional[str] = None) -> int:
    if global_state.controller is not None and not _is_tracer(tensor):
        return _native_async(
            lambda ctl: ctl.alltoall_submit(
                np.asarray(tensor), splits=splits, name=name),
            lambda ctl, s: ctl.alltoall_finish(s[0], s[1]))
    result = alltoall(tensor, splits=splits, name=name)
    return _handles.handle_manager.allocate(_handles.Handle(result=result))


def poll(handle: int) -> bool:
    return _handles.handle_manager.poll(handle)


def synchronize(handle: int):
    return _handles.handle_manager.synchronize(handle)
