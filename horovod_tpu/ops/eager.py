"""Eager (op-by-op) collectives across processes.

The reference's eager contract is "any rank may enqueue named tensors in any
order"; a background controller negotiates readiness and a data-plane backend
(NCCL/MPI/Gloo) executes (operations.cc:919-1226, controller.cc:69-449).  On
TPU there are three eager regimes, dispatched here in priority order:

1. **Native controller attached** (launcher-run jobs): the C++ runtime
   negotiates names across processes and executes over its TCP data plane
   (the Gloo-analog) or hands fused HBM buffers to XLA.  This is the only
   path with full dynamic-name negotiation semantics.
2. **Multi-process JAX** (jax.distributed initialized): collectives are
   expressed as a jitted global computation over a process-axis mesh —
   the array is built from per-process shards, reduced in-graph over ICI/DCN,
   and read back replicated.
3. **Single process**: the communicator has one member; ops are identities
   (sum over one contribution), matching Horovod semantics for size()==1.

Ordering contract: regime 2 (no controller) is *SPMD end to end* — every
process must issue the same eager collectives in the same order (both the
device plane and the host-numpy path lower to the same jitted mesh
collectives).  Divergent per-process op order deadlocks inside the XLA
collective with no stall warning; there is no cheap detection point because
the divergence happens inside compiled code.  When dynamic per-rank op
order is needed, run under the launcher: regime 1's controller negotiates
names (host tensors over TCP, HBM tensors via the negotiated device plane),
and its stall inspector covers the negotiation plane.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import numpy as np

from ..core.state import global_state
from ..debug import flight as _flight


def _np(tensor):
    return np.asarray(tensor)


def _controller():
    return global_state.controller


def _is_device_array(tensor) -> bool:
    """Concrete jax.Array (device-resident HBM buffer, not a tracer)."""
    try:
        import jax
        return isinstance(tensor, jax.Array) and \
            not isinstance(tensor, jax.core.Tracer)
    except Exception:
        return False


def _device_allreduce(tensor, op_fn, ctl):
    """Device-resident eager allreduce: the TPU analog of the reference's
    on-device NCCL data plane (nccl_operations.cc:126-184) — the tensor
    stays in HBM end to end, no host round-trip.

    Regimes:
    * multi-process JAX (jax.distributed initialized, e.g. by the launcher's
      chip-partition bootstrap): the per-process shard is assembled into a
      global array **from its existing device buffer**, reduced by a jitted
      collective riding ICI/DCN, and returned replicated — still a
      jax.Array.
    * single process, world size 1: identity reduce on device.
    * single jax process inside a larger TCP world: no ICI path exists to
      the other ranks — returns None so the caller uses the host TCP plane
      (the CPU/test backend).

    CONTRACT: the multi-process device plane is an SPMD collective — every
    process must issue device-plane ops in the same order with matching
    shapes and input *kinds* (all jax.Array or all host arrays for a given
    logical tensor); there is no name-based reordering like the controller
    plane.  That matches normal SPMD training code.  Set
    ``HVD_TPU_EAGER_DEVICE_PLANE=0`` to force every eager op through the
    controller's named-tensor negotiation (host plane) when per-rank code
    paths genuinely diverge.
    """
    import os
    if os.environ.get("HVD_TPU_EAGER_DEVICE_PLANE", "1") == "0":
        return None
    import jax
    comm_size = ctl.size() if ctl is not None else global_state.process_count
    if jax.process_count() > 1:
        if jax.process_count() != comm_size:
            # The JAX world does not span the whole communicator (e.g. one
            # jax.distributed world per host in a multi-host launch): a
            # device-plane reduce would silently drop remote ranks.  Host
            # plane handles it.
            return None
        if ctl is None:
            # With a controller attached, _negotiated_device_ready
            # guarantees alignment before the executor reaches here.
            _check_rank_aligned()
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = _cached_process_mesh()
        me = mesh.devices.flat[jax.process_index()]
        local = jax.device_put(tensor[None], me)  # D2D at most; never host
        sharding = NamedSharding(mesh, P("proc"))
        global_shape = (jax.process_count(),) + tuple(tensor.shape)
        garr = jax.make_array_from_single_device_arrays(
            global_shape, sharding, [local])
        return _jitted_global(op_fn)(garr)
    if comm_size == 1 and global_state.process_count == 1:
        return _jitted_local(op_fn)(tensor[None])
    return None


def _negotiated_device_ready(ctl) -> bool:
    """True when HBM-resident eager tensors can take the *negotiated*
    device plane: named-tensor negotiation, fusion and the response cache
    run exactly as for host tensors, but the fused payload executes through
    the jitted device collective instead of host rings (the reference's
    device-buffer fusion inside the negotiated runtime,
    nccl_operations.cc:126-184).

    Requires a spanning JAX world (jax.process_count() == communicator
    size) **and** rank alignment (jax.process_index() == ctl.rank()) —
    the executor maps coordinator rank-indexed tables (allgather dims[r],
    the alltoall split-matrix row, the broadcast root shard) onto the
    'proc' mesh ordered by JAX process index, so a user-initialized JAX
    world whose process ids are ordered differently from controller ranks
    would silently misroute segments and pick the wrong broadcast root.
    On mismatch the host plane handles the tensor (and the controller's
    device-placement validation fails mixed placements cleanly).  The
    coordinator's response order is identical on every rank, so the
    executor's SPMD collectives line up even when per-rank enqueue order
    diverged.  (The executor itself is registered at controller
    construction — see NativeController.__init__.)
    """
    import os
    if os.environ.get("HVD_TPU_EAGER_DEVICE_PLANE", "1") == "0":
        return False
    if getattr(ctl, "_negotiated_device_ok", False):
        return True
    try:
        import jax
        spanning = jax.process_count() == ctl.size()
        aligned = jax.process_index() == ctl.rank()
        ok = spanning and aligned
        if spanning and not aligned and \
                not getattr(ctl, "_warned_rank_misalign", False):
            # One-time heads-up: this rank routes HBM tensors to the host
            # plane.  If *other* ranks are aligned they submit device
            # requests for the same names and the coordinator's placement
            # validation delivers a clean cross-rank ERROR (reference
            # semantics for inconsistent submissions, controller.cc
            # validation) — set HVD_TPU_EAGER_DEVICE_PLANE=0 on all ranks
            # for uniform host-plane behavior instead.
            from ..utils import logging as _logging
            _logging.warning(
                "jax.process_index() %d != controller rank %d; HBM "
                "tensors use the host plane on this rank. For uniform "
                "behavior across ranks set HVD_TPU_EAGER_DEVICE_PLANE=0.",
                jax.process_index(), ctl.rank())
            ctl._warned_rank_misalign = True
    except Exception:
        ok = False
    if ok:
        # Cache only the positive result: a world that is still forming
        # (jax.distributed not yet spanning) must be re-checked on later
        # calls, or every HBM tensor would silently take the host plane
        # for the life of the process.  The executor itself is registered
        # at controller construction (see NativeController.__init__).
        ctl._negotiated_device_ok = True
    return ok


def _negotiated_executor(ctl):
    """Build the device-plane executor for one controller: executes a
    negotiated (possibly fused) Response entirely on device.  Runs on the
    native background thread in coordinator response order.

    Design invariant: the *global* (collective-bearing) program depends
    only on coordinator-provided response data (op, scales, root, sizes,
    dtype) — identical on every rank including joined zero-proxy ranks —
    so SPMD programs always line up.  Per-tensor staging and
    split/reshape/assembly happen in LOCAL (collective-free) programs,
    so rank-divergent pre/post-processing (only ranks with a local entry
    do it) needs no cross-process rendezvous.

    Amortization (VERDICT r4 #3): rebuilding the staging graph with
    eager jnp ops cost ~3 ms of fixed dispatch per Response.  Steady
    gradient traffic repeats the same response signatures every step, so
    the executor caches, per (rtype, sizes, present-mask, shapes, dtype,
    op, root, scales) signature, three compiled programs — local pack,
    global collective, local split — plus the pre-bound mesh/sharding;
    a cache hit is three compiled calls and one global-array assembly.
    The reference amortizes per-launch cost the same way via its fusion
    buffer (nccl_operations.cc:126-184)."""

    import os
    from collections import OrderedDict
    # LRU-bounded like every other cache in this module: variable-shape
    # traffic (ragged allgather dims, per-step alltoall split tables)
    # would otherwise accrete compiled programs without limit.
    cache: "OrderedDict" = OrderedDict()
    cache_cap = int(os.environ.get("HVD_TPU_DEVICE_EXEC_CACHE", "256"))
    ctl._device_exec_cache = cache
    ctl._device_exec_cache_hits = 0
    # Response-signature cache hit rate + fusion batch size feed the
    # metrics registry: fusion efficiency and negotiation amortization
    # are exactly the continuously-collected numbers systematic
    # bottleneck analysis needs (arXiv:1810.11112).
    from ..metrics.registry import registry as _metrics_registry
    _mreg = _metrics_registry()
    _m_hits = _mreg.counter("hvd_response_cache_hits_total",
                            "Device-plane response-signature cache hits")
    _m_misses = _mreg.counter(
        "hvd_response_cache_misses_total",
        "Device-plane response-signature cache misses (compiles)")
    _m_fused = _mreg.histogram(
        "hvd_fusion_batch_names",
        "Tensors per negotiated device-plane Response (fusion batch)",
        buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
    _m_staged = _mreg.counter(
        "hvd_device_plane_bytes_total",
        "Payload bytes executed on the negotiated device plane")
    _m_wire_raw = _mreg.counter(
        "hvd_wire_bytes_raw_total",
        "Pre-compression payload bytes offered to the wire",
        kind="device_plane")
    _m_wire_sent = _mreg.counter(
        "hvd_wire_bytes_sent_total",
        "Payload bytes after the selected wire format",
        kind="device_plane")

    def _build(rtype, sizes, present, shapes, np_dtype, op, root,
               prescale, postscale, comp, mesh):
        """Compile the per-signature programs; returns run(*present_args)
        -> tuple of outputs for the present names, in names order.
        ``comp`` is the coordinator-stamped wire format ("none"/"bf16"/
        "fp16"/"int8"/"int4") — already gated by ``impl`` to fused
        allreduces over floats with Sum/Average."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as PS
        from .collective import _eager_op_fn_f32acc
        dtype = jnp.dtype(np_dtype)
        P = ctl.size()
        me = ctl.rank()
        me_dev = mesh.devices.flat[jax.process_index()]
        in_sharding = NamedSharding(mesh, PS("proc"))

        def _assemble_and_run(coll_jit, local):
            local = jax.device_put(local, me_dev)
            garr = jax.make_array_from_single_device_arrays(
                (P,) + tuple(local.shape[1:]), in_sharding, [local])
            out = coll_jit(garr)
            # Replicated output: this process's shard IS the full result.
            return out.addressable_shards[0].data

        if rtype in (0, 2):  # ALLREDUCE (possibly fused) / BROADCAST
            offs = [0]
            for sz in sizes:
                offs.append(offs[-1] + sz)
            # f32acc: float stacks (including bf16/fp16 payloads a cast
            # compressor produced) accumulate in fp32 and cast back —
            # the wire dtype is never the accumulation dtype, matching
            # the compiled two-pass schedule.  Integer stacks reduce
            # exactly as before.
            base = (_eager_op_fn_f32acc(op, prescale, postscale)
                    if rtype == 0 else _take_fn(root))
            pres_idx = [i for i in range(len(sizes)) if present[i]]

            def _fused(args, fused_dtype):
                # Missing names are joined-rank zero proxies (reference
                # GetTensorEntriesFromResponse, tensor_queue.cc); the
                # fused layout is names order, as on the host plane.
                it = iter(args)
                parts = [jnp.ravel(next(it)).astype(fused_dtype)
                         if present[i]
                         else jnp.zeros((sizes[i],), dtype=fused_dtype)
                         for i in range(len(sizes))]
                return parts[0] if len(parts) == 1 \
                    else jnp.concatenate(parts)

            if comp != "none":
                # Compressed wire: the staged buffer — the only array the
                # sharded→replicated program moves between processes —
                # holds the wire format, not fp32; the reduction runs on
                # dequantized fp32 after the gather.  One program per
                # (signature, wire) key: a coordinator flip recompiles
                # rather than reusing a stale layout.
                from .quantization import (QuantSpec, default_block,
                                           unpack_int4, quantize)
                L = offs[-1]
                if comp in ("bf16", "fp16"):
                    wire_dt = jnp.bfloat16 if comp == "bf16" \
                        else jnp.float16

                    def pack_fn(*args):
                        return _fused(args, jnp.float32).astype(
                            wire_dt)[None]

                    def reduce_fn(stack):
                        return _reduce_f32(stack.astype(jnp.float32))
                else:
                    spec = QuantSpec(bits=8 if comp == "int8" else 4,
                                     block=default_block())
                    nb = -(-max(L, 1) // spec.block)
                    packed_w = spec.block if spec.bits == 8 \
                        else spec.block // 2

                    def pack_fn(*args):
                        q, scales = quantize(_fused(args, jnp.float32),
                                             spec)
                        qb = jax.lax.bitcast_convert_type(
                            q, jnp.uint8).reshape(-1)
                        sb = jax.lax.bitcast_convert_type(
                            scales, jnp.uint8).reshape(-1)
                        return jnp.concatenate([qb, sb])[None]

                    def reduce_fn(stack):
                        qb = stack[:, : nb * packed_w].reshape(
                            P, nb, packed_w)
                        q = jax.lax.bitcast_convert_type(qb, jnp.int8)
                        if spec.bits == 4:
                            q = unpack_int4(q)
                        sb = stack[:, nb * packed_w:].reshape(P, nb, 4)
                        scales = jax.lax.bitcast_convert_type(
                            sb, jnp.float32)
                        deq = q.astype(jnp.float32) * scales[..., None]
                        return _reduce_f32(
                            deq.reshape(P, -1)[:, :max(L, 1)])

                def _reduce_f32(contrib):
                    # fp32 accumulation always; zero proxies count as
                    # members, matching the host plane's stack mean.
                    if prescale != 1.0:
                        contrib = contrib * prescale
                    acc = contrib.sum(axis=0)
                    if op == 0:  # Average
                        acc = acc / P
                    if postscale != 1.0:
                        acc = acc * postscale
                    return acc

                def split_fn(out):
                    return tuple(
                        out[offs[i]: offs[i] + sizes[i]]
                        .reshape(shapes[j]).astype(dtype)
                        for j, i in enumerate(pres_idx))

                base = reduce_fn
            else:
                def pack_fn(*args):
                    return _fused(args, dtype)[None]

                def split_fn(out):
                    return tuple(
                        out[offs[i]: offs[i] + sizes[i]].reshape(shapes[j])
                        for j, i in enumerate(pres_idx))

            pack_jit = jax.jit(pack_fn)
            coll_jit = _jitted_global(base)
            split_jit = jax.jit(split_fn)

            def run(*args):
                local_out = _assemble_and_run(coll_jit, pack_jit(*args))
                if not pres_idx:
                    return ()
                return split_jit(local_out)

            return run

        # Variable-size collectives stage at EXACT concatenated offsets
        # and combine with a one-hot SUM (each position gets exactly one
        # rank's contribution), so staged memory is bounded by the total
        # payload — not by P x max-segment padding, which under skewed
        # splits (one rank 1000x the others) wasted quadratic-ish HBM
        # (VERDICT r3 #7).  The wire is the same-width unsigned-int
        # BITCAST of the payload: integer one-hot sum is bit-exact for
        # every pattern (float +x would lose -0.0: (-0.0)+(+0.0)=+0.0),
        # and the bitcast is free on device.  bool rides a uint8 cast.
        _UINT_OF_WIDTH = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32,
                          8: jnp.uint64}
        if dtype == jnp.bool_:
            wire_dtype = jnp.uint8

            def _wire(x):
                return x.astype(jnp.uint8)

            def _unwire(x):
                return x.astype(dtype)
        elif jnp.issubdtype(dtype, jnp.floating):
            wire_dtype = _UINT_OF_WIDTH[dtype.itemsize]

            def _wire(x):
                return jax.lax.bitcast_convert_type(x, wire_dtype)

            def _unwire(x):
                return jax.lax.bitcast_convert_type(x, dtype)
        else:
            wire_dtype = dtype

            def _wire(x):
                return x

            def _unwire(x):
                return x

        have = bool(present[0])
        tail = tuple(shapes[0][1:]) if have else ()
        n_in = (int(np.prod(shapes[0])) if have and shapes[0] else
                (1 if have else 0))

        if rtype == 1:  # ALLGATHER: sizes = per-rank dims[P] + row_elems
            dims = sizes[:P]
            row_elems = sizes[P]
            offs = np.concatenate(
                [[0], np.cumsum([d * row_elems for d in dims])])
            L = int(offs[-1])
            my_off = int(offs[me])

            def pack_fn(*args):
                flat = jnp.zeros((max(L, 1),), dtype=wire_dtype)
                if have and n_in:
                    flat = flat.at[my_off: my_off + n_in].set(
                        _wire(jnp.ravel(args[0])))
                return flat[None]

            def split_fn(summed):
                return (_unwire(summed[:L]).reshape(
                    (sum(dims),) + tail),)

            extra = None
        elif rtype == 3:  # ALLTOALL: sizes = split matrix[P*P] + row_elems
            mat = sizes[: P * P]
            row_elems = sizes[P * P]
            # Global layout grouped by destination: block d holds
            # [seg(src0->d), seg(src1->d), ...]; every rank extracts its
            # own (contiguous) destination block after the sum.
            seg = [[mat[s * P + d] * row_elems for s in range(P)]
                   for d in range(P)]
            block_off = np.concatenate(
                [[0], np.cumsum([sum(seg[d]) for d in range(P)])])
            L = int(block_off[-1])
            start = int(block_off[me])
            total = sum(mat[src * P + me] for src in range(P))

            def pack_fn(*args):
                flat = jnp.zeros((max(L, 1),), dtype=wire_dtype)
                if have and n_in:
                    av = _wire(jnp.ravel(args[0]))
                    off_in = 0
                    for d in range(P):
                        n_el = seg[d][me]
                        if n_el:
                            pos = int(block_off[d]) + sum(seg[d][:me])
                            flat = flat.at[pos: pos + n_el].set(
                                av[off_in: off_in + n_el])
                            off_in += n_el
                return flat[None]

            def split_fn(summed):
                return (_unwire(
                    summed[start: start + total * row_elems]).reshape(
                    (total,) + tail),)

            extra = np.array(
                [mat[src * P + me] for src in range(P)], dtype=np.int32)
        else:
            raise ValueError(
                f"device plane does not execute request type {rtype}")

        pack_jit = jax.jit(pack_fn)
        coll_jit = _jitted_global(_sum0_samedtype)
        split_jit = jax.jit(split_fn)
        staged_bytes = 2 * max(L, 1) * jnp.dtype(wire_dtype).itemsize

        def run(*args):
            local_out = _assemble_and_run(coll_jit, pack_jit(*args))
            ctl._device_staged_bytes = staged_bytes
            if not have:
                return ()
            out = split_jit(local_out)[0]
            # Copy recv_splits per call: the cached closure's array must
            # not alias what callers receive (and may mutate).
            return ((out, extra.copy()) if extra is not None else out,)

        return run

    def impl(rtype, names, sizes, np_dtype, op, root, prescale, postscale,
             inputs):
        import jax
        # Wire format for this Response: the coordinator's per-round
        # stamp (ResponseList::wire_compression) — identical on every
        # rank for the same Response, so the per-signature programs line
        # up even when the tuner flips it mid-run.  A lossy wire only
        # composes with fused float allreduces under Sum/Average; the
        # gate below depends only on Response data, so it is itself
        # rank-consistent.
        comp = "none"
        try:
            comp = ctl.wire_compression()
        except Exception:  # noqa: BLE001 — controllers without the
            pass           # stamp (e.g. test doubles)
        # Float check via jnp: ml_dtypes' bfloat16 — THE TPU gradient
        # dtype — registers as numpy kind 'V', so np.issubdtype would
        # silently exclude it from compression.
        import jax.numpy as jnp
        if rtype != 0 or int(op) not in (0, 1) or \
                not jnp.issubdtype(jnp.dtype(np_dtype), jnp.floating) or \
                not sizes or sum(int(s) for s in sizes) == 0:
            comp = "none"
        # Flight recorder: one event per negotiated Response, on the
        # background thread — if the SPMD collective below never returns
        # (a peer died inside XLA, where no stall inspector can see),
        # this dangling negotiate.execute event names the fused batch
        # that hung.
        _flight.record("negotiate.execute", names[0] if names else None,
                       rtype=rtype, n=len(names), wire=comp)
        mesh = _cached_process_mesh()
        if getattr(ctl, "_device_exec_mesh", None) is not mesh:
            # Elastic world rebuild: the cached programs bake in the old
            # mesh/devices (bootstrap clears the module-level jit caches;
            # this clears the per-signature ones).
            cache.clear()
            ctl._device_exec_mesh = mesh
        if jax.process_count() != ctl.size():
            raise RuntimeError(
                "device plane unavailable (no spanning JAX world)")
        sizes = [int(s) for s in sizes]
        present = tuple(nm in inputs for nm in names)
        pres_names = [nm for nm in names if nm in inputs]
        shapes = tuple(tuple(inputs[nm].shape) for nm in pres_names)
        # Names stay OUT of the key: auto-generated tensor names change
        # per step while the payload signature repeats — that repetition
        # is exactly what the cache amortizes.
        key = (rtype, tuple(sizes), present, shapes,
               str(np.dtype(np_dtype)), int(op), int(root),
               float(prescale), float(postscale), comp)
        run = cache.get(key)
        if run is None:
            run = _build(rtype, sizes, present, shapes, np_dtype,
                         int(op), int(root), float(prescale),
                         float(postscale), comp, mesh)
            cache[key] = run
            while len(cache) > cache_cap:
                cache.popitem(last=False)
            _m_misses.inc()
        else:
            cache.move_to_end(key)
            ctl._device_exec_cache_hits += 1
            _m_hits.inc()
        _m_fused.observe(len(names))
        if rtype in (0, 2):
            raw = float(sum(sizes)) * np.dtype(np_dtype).itemsize
            _m_staged.inc(raw)
            _m_wire_raw.inc(raw)
            if comp == "none":
                sent = raw
            else:
                from .quantization import QuantSpec, wire_bytes
                n_el = sum(sizes)
                if comp in ("bf16", "fp16"):
                    sent = float(n_el * 2)
                else:
                    from .quantization import default_block
                    sent = float(wire_bytes(n_el, QuantSpec(
                        8 if comp == "int8" else 4, default_block())))
            _m_wire_sent.inc(sent)
        outs = run(*(inputs[nm] for nm in pres_names))
        if rtype in (0, 2):
            return dict(zip(pres_names, outs))
        return {pres_names[0]: outs[0]} if outs else {}

    def validate(rtype, names, sizes, np_dtype, op, root):
        """PREPARE-phase check (runs before the cross-rank status
        agreement): every condition that would make ``impl`` fail without
        entering the SPMD collective must be detected here, so a doomed
        rank turns into a clean cross-rank ERROR instead of stranding
        peers inside an unabortable device collective (the reference
        aborts NCCL comms on async errors, nccl_operations.cc:96-109;
        XLA offers no abort, so the check must happen up front)."""
        import os
        if os.environ.get("HVD_TPU_EAGER_DEVICE_PLANE", "1") == "0":
            raise RuntimeError(
                "device plane disabled on this rank "
                "(HVD_TPU_EAGER_DEVICE_PLANE=0)")
        import jax
        if jax.process_count() != ctl.size() or \
                jax.process_index() != ctl.rank():
            raise RuntimeError(
                "device plane unavailable (no spanning/aligned JAX "
                f"world: processes {jax.process_count()}/{ctl.size()}, "
                f"index {jax.process_index()} vs rank {ctl.rank()})")
        import jax.numpy as jnp
        # Real dtype probe: jax silently downcasts dtypes it lacks (e.g.
        # float64 with x64 disabled), which would desync the SPMD dispatch
        # — reject here, before the cross-rank OK agreement.
        probe = jnp.zeros((0,), dtype=np_dtype)
        if probe.dtype != np.dtype(np_dtype):
            raise TypeError(
                f"device plane lacks dtype {np.dtype(np_dtype)} "
                f"(jax yields {probe.dtype}; e.g. x64 disabled)")
        if rtype not in (0, 1, 2, 3):
            raise ValueError(
                f"device plane does not execute request type {rtype}")

    impl.validate = validate
    return impl


def _ctl(fn, *args, **kwargs):
    """Run a native-controller call, mapping transport/collective failures
    to HorovodInternalError so the elastic retry loop can restore state
    (the reference maps failed-op statuses the same way,
    torch/mpi_ops.py synchronize / tensorflow/elastic.py:53-66)."""
    from ..native.controller import NativeError
    from ..core.exceptions import HorovodInternalError
    try:
        return fn(*args, **kwargs)
    except NativeError as e:
        raise HorovodInternalError(str(e)) from e


def _check_rank_aligned():
    """Regime-2 (no-controller) collectives place shards over the process
    mesh and read results back in communicator-rank order (broadcast root
    selection, gather concatenation): a jax.distributed world whose
    process ids are permuted relative to communicator ranks would either
    silently misroute data (device path, placed by process index) or
    device_put to a non-addressable device (host path, placed by rank).
    init() already rejects this when jax.distributed came up first; this
    covers worlds formed after init().  Fail loudly — no fallback exists
    in this regime."""
    import jax
    if jax.process_index() != global_state.process_rank:
        raise RuntimeError(
            "eager collectives: jax.process_index() "
            f"{jax.process_index()} != communicator rank "
            f"{global_state.process_rank}; a jax.distributed world "
            "ordered differently from the communicator cannot run "
            "rank-indexed collectives. Initialize jax.distributed "
            "with process_id == rank (the launcher does this) or "
            "run under the launcher.")


def _process_mesh():
    """A 1-D mesh with exactly one device per process, for process-level
    eager collectives (regime 2)."""
    import jax
    devices = []
    seen = set()
    for d in jax.devices():
        if d.process_index not in seen:
            seen.add(d.process_index)
            devices.append(d)
    return jax.sharding.Mesh(np.array(devices), ("proc",))


@functools.lru_cache(maxsize=None)
def _cached_process_mesh():
    return _process_mesh()


def _global_over_processes(x: np.ndarray):
    """Build a (P, *x.shape) global array, shard p = process p's x."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    _check_rank_aligned()
    mesh = _cached_process_mesh()
    sharding = NamedSharding(mesh, P("proc"))
    p = global_state.process_count
    global_shape = (p,) + x.shape
    local = jax.device_put(x[None], mesh.devices.flat[global_state.process_rank])
    return jax.make_array_from_single_device_arrays(global_shape, sharding, [local])


def _replicated_out(mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P())


@functools.lru_cache(maxsize=256)
def _jitted_global(fn):
    """jit cache keyed on the reducer's identity: eager collectives are the
    hot path, so every call must reuse the compiled executable (a fresh
    jax.jit wrapper per call would re-trace each time)."""
    import jax
    mesh = _cached_process_mesh()
    return jax.jit(fn, out_shardings=_replicated_out(mesh))


@functools.lru_cache(maxsize=256)
def _jitted_local(fn):
    import jax
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _take_fn(index: int):
    return lambda a: a[index]


@functools.lru_cache(maxsize=64)
def _take_col_fn(index: int):
    return lambda a: a[:, index]


def _identity(a):
    return a


def _sum0(a):
    return a.sum(0)


def _sum0_samedtype(a):
    """Dtype-preserving stack sum for one-hot staging wires: jnp.sum
    promotes narrow ints (uint16 -> uint32), and un-bitcasting a widened
    wire would split every element in two.  The cast back is exact here
    because each position holds exactly one rank's value (zeros
    elsewhere), so the sum never exceeds the wire dtype."""
    return a.sum(0).astype(a.dtype)


def _run_global(fn, garr):
    out = _jitted_global(fn)(garr)
    return np.asarray(out.addressable_shards[0].data)


def allreduce(tensor, op_fn, name: Optional[str] = None,
              op_code: Optional[int] = None,
              prescale: float = 1.0, postscale: float = 1.0):
    """op_fn: callable(stack: (P, ...) array) -> reduced array; op_code is
    the ReduceOp code for the native controller path (which does not take
    callables across the C boundary)."""
    ctl = _controller()
    if _is_device_array(tensor):
        if ctl is not None:
            # Negotiated device plane: controller negotiation, fusion and
            # response cache run as usual; the fused payload executes on
            # HBM via the registered executor (never copies to host).
            if _negotiated_device_ready(ctl):
                return _ctl(ctl.allreduce_device, tensor,
                            op=1 if op_code is None else int(op_code),
                            prescale=prescale, postscale=postscale,
                            name=name)
        else:
            # No controller: direct SPMD device plane (multi-process JAX /
            # single process); None → no device path to the other ranks.
            out = _device_allreduce(tensor, op_fn, ctl)
            if out is not None:
                return out
    if ctl is not None:
        return _ctl(ctl.allreduce, _np(tensor),
                    op=1 if op_code is None else int(op_code),
                    prescale=prescale, postscale=postscale, name=name)
    if global_state.process_count == 1:
        x = _np(tensor)
        return op_fn(x[None])
    garr = _global_over_processes(_np(tensor))
    return _run_global(op_fn, garr)


def allreduce_device_async(tensor, op_code: int = 1,
                           prescale: float = 1.0, postscale: float = 1.0,
                           name: Optional[str] = None):
    """Submit an HBM-resident tensor on the negotiated device plane and
    return a zero-arg finisher (the overlap scheduler's bucket dispatch
    rides this: submits stay on device, the background runtime
    negotiates + fuses while the caller computes, ``finisher()`` blocks
    and yields the on-device result).  Caller must have checked
    ``_negotiated_device_ready`` — this function assumes a controller."""
    ctl = _controller()
    submitted = _ctl(ctl.allreduce_device_submit, tensor, op=int(op_code),
                     prescale=prescale, postscale=postscale, name=name)

    def fin(_s=submitted):
        return _ctl(ctl.device_finish, *_s)
    return fin


def _flatten01(a):
    return a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:])


def _device_allgather(tensor, ctl):
    """Device-plane allgather for equal per-rank dim-0 (the SPMD common
    case): the payload never leaves HBM.  Unequal dims return None — the
    host plane does the pad/displacement dance.

    The defensive per-call sizes exchange costs one extra (tiny) device
    collective; SPMD training code whose gather shapes are equal by
    construction can skip it with ``HVD_TPU_EAGER_EQUAL_ALLGATHER=1``.
    WARNING: under that knob, genuinely ragged inputs make each process
    compile a different global shape and the mesh collective can HANG
    (no stall warning — see the module's ordering-contract note); only
    set it when equal shapes are guaranteed."""
    if getattr(tensor, "ndim", 0) < 1:
        return None
    import os
    import jax.numpy as jnp
    if os.environ.get("HVD_TPU_EAGER_EQUAL_ALLGATHER", "0") != "1":
        rows = int(tensor.shape[0])
        sizes = _device_allreduce(
            jnp.asarray(_one_hot_sizes(rows)), _sum0, ctl)
        if sizes is None:
            return None
        if not bool((np.asarray(sizes) == rows).all()):
            return None  # ragged: host plane
    return _device_allreduce(tensor, _flatten01, ctl)


def allgather(tensor, name: Optional[str] = None):
    """Concatenate along dim 0 across processes (unequal dim-0 allowed)."""
    ctl = _controller()
    if _is_device_array(tensor):
        if ctl is not None:
            # Negotiated device plane (unequal dims come from the
            # coordinator's size table, so no extra sizes exchange).
            if getattr(tensor, "ndim", 0) >= 1 and \
                    _negotiated_device_ready(ctl):
                return _ctl(ctl.allgather_device, tensor, name=name)
        else:
            # Direct SPMD device plane (no controller).  With a controller
            # attached, direct mesh collectives from the caller thread
            # would race the negotiated device responses executing on the
            # background thread over the same process mesh.
            out = _device_allgather(tensor, ctl)
            if out is not None:
                return out
    if ctl is not None:
        return _ctl(ctl.allgather, _np(tensor), name=name)
    if global_state.process_count == 1:
        return _np(tensor)
    # Unequal first dims need a size exchange first; gather sizes, then pad,
    # gather payloads, and slice (reference: controller.cc:576-648 does the
    # same displacement math on the coordinator).
    x = _np(tensor)
    sizes = allreduce(_one_hot_sizes(x.shape[0]), op_fn=_sum0)
    max_rows = int(sizes.max())
    padded = np.zeros((max_rows,) + x.shape[1:], dtype=x.dtype)
    padded[: x.shape[0]] = x
    garr = _global_over_processes(padded)
    gathered = _run_global(_identity, garr)  # (P, max_rows, ...)
    parts = [gathered[p, : int(sizes[p])] for p in range(len(sizes))]
    return np.concatenate(parts, axis=0)


def _one_hot_sizes(rows: int) -> np.ndarray:
    sizes = np.zeros((global_state.process_count,), dtype=np.int64)
    sizes[global_state.process_rank] = rows
    return sizes


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    ctl = _controller()
    if _is_device_array(tensor):
        if ctl is not None:
            if _negotiated_device_ready(ctl):
                return _ctl(ctl.broadcast_device, tensor,
                            root_rank=root_rank, name=name)
        else:
            # Broadcast shapes match across ranks by contract, so the
            # device plane applies directly (select the root's shard).
            out = _device_allreduce(tensor, _take_fn(root_rank), ctl)
            if out is not None:
                return out
    if ctl is not None:
        return _ctl(ctl.broadcast, _np(tensor), root_rank=root_rank,
                    name=name)
    if global_state.process_count == 1:
        return _np(tensor)
    garr = _global_over_processes(_np(tensor))
    return _run_global(_take_fn(root_rank), garr)


def alltoall(tensor, splits: Optional[Sequence[int]] = None,
             name: Optional[str] = None):
    """Split dim 0 by ``splits`` (default: equal), piece i to process i;
    returns (received, received_splits) like the reference
    (operations.cc:1136-1198)."""
    ctl = _controller()
    if ctl is not None:
        if _is_device_array(tensor) and getattr(tensor, "ndim", 0) >= 1 \
                and _negotiated_device_ready(ctl):
            return _ctl(ctl.alltoall_device, tensor, splits=splits,
                        name=name)
        return _ctl(ctl.alltoall, _np(tensor), splits=splits, name=name)
    x = _np(tensor)
    p = global_state.process_count
    if splits is None:
        if x.shape[0] % p != 0:
            raise ValueError(
                f"alltoall dim0 {x.shape[0]} not divisible by size {p}")
        splits = [x.shape[0] // p] * p
    splits = list(splits)
    if p == 1:
        return x[: splits[0]], np.array(splits, dtype=np.int32)
    # Exchange split tables, then route each segment via a padded gather.
    split_table = allgather(np.array([splits], dtype=np.int64))  # (P, P)
    offsets = np.concatenate([[0], np.cumsum(splits)]).astype(np.int64)
    max_seg = int(split_table.max())
    segs = np.zeros((p, max_seg) + x.shape[1:], dtype=x.dtype)
    for dest in range(p):
        seg = x[offsets[dest]: offsets[dest + 1]]
        segs[dest, : seg.shape[0]] = seg
    garr = _global_over_processes(segs)  # (P_src, P_dest, max_seg, ...)
    me = global_state.process_rank
    all_segs = _run_global(_take_col_fn(me), garr)  # (P_src, max_seg, ...)
    recv_splits = split_table[:, me]
    parts = [all_segs[src, : int(recv_splits[src])] for src in range(p)]
    return (np.concatenate(parts, axis=0),
            recv_splits.astype(np.int32))


def reducescatter(tensor, op_fn, name: Optional[str] = None,
                  op_code: Optional[int] = None):
    """Reduce across processes then scatter equal dim-0 chunks."""
    reduced = allreduce(tensor, op_fn=op_fn, name=name, op_code=op_code)
    p = global_state.process_count
    rows = reduced.shape[0]
    if rows % p != 0:
        raise ValueError(f"reducescatter dim0 {rows} not divisible by {p}")
    chunk = rows // p
    me = global_state.process_rank
    return reduced[me * chunk: (me + 1) * chunk]


def barrier() -> None:
    ctl = _controller()
    if ctl is not None:
        _ctl(ctl.barrier)
        return
    if global_state.process_count == 1:
        return
    allreduce(np.zeros((1,), dtype=np.float32), op_fn=_sum0)


def join() -> int:
    """Signal this rank has no more data; returns last joined rank.

    Reference: the Join op lets ranks with uneven data exit allreduce
    gracefully with zero-filled proxies (operations.cc:1202-1226).  In the
    eager regimes without a controller there is nothing pending to proxy, so
    join degenerates to a barrier returning the highest rank.
    """
    ctl = _controller()
    if ctl is not None:
        return _ctl(ctl.join)
    barrier()
    return global_state.process_count - 1
