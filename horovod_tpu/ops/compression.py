"""Gradient wire compression.

Capability parity with the reference's ``Compression`` classes
(horovod/torch/compression.py, horovod/tensorflow/compression.py): compress a
tensor before the allreduce, decompress after.  TPU-native note: on the
compiled path XLA fuses the casts into the collective's producer/consumer, so
fp16/bf16 compression halves ICI bytes at no extra kernel cost.  On TPU,
bfloat16 is the natural wire format (same exponent range as fp32 — no loss
scaling needed), so it is the default "compressed" type here, with fp16
retained for parity.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface: compress() -> (compressed, ctx); decompress(compressed, ctx)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast floating tensors to fp16 for the wire; restore dtype after."""

    @staticmethod
    def compress(tensor):
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor.astype(jnp.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class BF16Compressor(Compressor):
    """Cast floating tensors to bfloat16 — the TPU-native wire format."""

    @staticmethod
    def compress(tensor):
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor.astype(jnp.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class Compression:
    """Namespace matching ``hvd.Compression.{none,fp16}`` plus TPU bf16."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
