"""Gradient wire compression.

Capability parity with the reference's ``Compression`` classes
(horovod/torch/compression.py, horovod/tensorflow/compression.py), grown
into the selector surface of the quantized collective engine.  Two kinds
of compressor:

* **Cast compressors** (fp16/bf16) keep the reference's
  ``compress() → collective → decompress()`` shape for API parity, but
  the collective layer recognizes them (``wire_dtype``) and routes the
  allreduce through the two-pass fp32-accumulation schedule in
  ``ops.quantization`` — the old shape let ``psum`` accumulate in the
  wire dtype, losing mantissa as the world grows.
* **Quantized compressors** (int8/int4) carry a block-scaled wire format
  (``spec``) that only exists *inside* the collective (per-block absmax
  scales ride next to the payload); ``compress()``/``decompress()`` are
  identities and ``ops.collective.allreduce(compression=…)`` /
  ``reducescatter(compression=…)`` do the real work.  Passing one to a
  code path that only knows the compress/collective/decompress shape
  degrades to an uncompressed wire, never to corrupt math.

TPU-native note: all four formats are pure ``jnp`` on the compiled path,
so XLA fuses the (de)quantize/casts into the collective's
producer/consumer — wire bytes drop ~2x (bf16) / ~4x (int8) / ~8x (int4)
at no extra kernel launch.
"""

from __future__ import annotations

import jax.numpy as jnp

from .quantization import QuantSpec, default_block


class Compressor:
    """Interface: compress() -> (compressed, ctx); decompress(compressed, ctx).

    Class attributes read by the collective layer:
      ``wire``       — format name ("none", "fp16", "bf16", "int8", "int4")
      ``wire_dtype`` — cast wire dtype, or None
      ``bits``       — quantized wire bits, or None
    """

    wire = "none"
    wire_dtype = None
    bits = None

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError

    @classmethod
    def spec(cls):
        """QuantSpec for quantized compressors (block size read from the
        HVD_TPU_QUANT_BLOCK knob at call time), else None."""
        if cls.bits is None:
            return None
        return QuantSpec(bits=cls.bits, block=default_block())


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast floating tensors to fp16 for the wire; restore dtype after."""

    wire = "fp16"
    wire_dtype = jnp.float16

    @staticmethod
    def compress(tensor):
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor.astype(jnp.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class BF16Compressor(Compressor):
    """Cast floating tensors to bfloat16 — the TPU-native wire format."""

    wire = "bf16"
    wire_dtype = jnp.bfloat16

    @staticmethod
    def compress(tensor):
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor.astype(jnp.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class _QuantizedCompressor(Compressor):
    """Block-scaled quantized wire.  compress/decompress are identities:
    the format lives inside the collective (the two-pass schedule needs
    the scales next to the payload and fp32 accumulation between the
    passes), not around it."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class Int8Compressor(_QuantizedCompressor):
    """Per-block absmax int8 wire (~4x fewer bytes than fp32)."""

    wire = "int8"
    bits = 8


class Int4Compressor(_QuantizedCompressor):
    """Per-block absmax int4 wire, packed two per int8 (~8x fewer bytes
    than fp32).  Coarse: pair with error feedback
    (``DistributedOptimizer(compression=Compression.int4)``) for
    convergence parity."""

    wire = "int4"
    bits = 4


class Compression:
    """Namespace matching ``hvd.Compression.{none,fp16}`` plus the
    TPU-native bf16 and the quantized engine's int8/int4."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
    int4 = Int4Compressor


_BY_NAME = {
    "none": NoneCompressor,
    "fp16": FP16Compressor,
    "bf16": BF16Compressor,
    "int8": Int8Compressor,
    "int4": Int4Compressor,
}

# Response-stream codes for the native wire_compression stamp
# (wire.h ResponseList::wire_compression).
WIRE_CODES = {"none": 0, "bf16": 1, "int8": 2, "int4": 3, "fp16": 4}
WIRE_NAMES = {v: k for k, v in WIRE_CODES.items()}


def by_name(name):
    """Resolve a knob string ("int8", "bf16", …) to a compressor class;
    unknown names resolve to none (a typo'd knob must not kill a job —
    the chosen format is observable in metrics/flight events)."""
    return _BY_NAME.get((name or "none").strip().lower(), NoneCompressor)
