"""Cross-replica synchronized batch normalization for JAX.

Capability parity with the reference's SyncBatchNormalization
(tensorflow/sync_batch_norm.py, torch/sync_batch_norm.py: batch moments
allreduced across ranks so small per-rank batches normalize as one global
batch).  TPU-native: inside shard_map the moments psum over the data axis —
one fused pmean pair, which XLA overlaps with surrounding compute.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def sync_batch_norm(x: jax.Array,
                    scale: jax.Array,
                    bias: jax.Array,
                    running_mean: jax.Array,
                    running_var: jax.Array,
                    axis_name: Optional[str] = "data",
                    training: bool = True,
                    momentum: float = 0.9,
                    eps: float = 1e-5
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Normalize ``x`` over all dims but the last, with moments averaged
    across ``axis_name``.

    Returns (normalized, new_running_mean, new_running_var).
    """
    xf = x.astype(jnp.float32)
    reduce_dims = tuple(range(x.ndim - 1))
    if training:
        mean = jnp.mean(xf, axis=reduce_dims)
        mean_sq = jnp.mean(xf * xf, axis=reduce_dims)
        if axis_name is not None:
            mean = lax.pmean(mean, axis_name)
            mean_sq = lax.pmean(mean_sq, axis_name)
        var = mean_sq - mean * mean
        new_mean = momentum * running_mean + (1 - momentum) * mean
        new_var = momentum * running_var + (1 - momentum) * var
    else:
        mean, var = running_mean, running_var
        new_mean, new_var = running_mean, running_var
    inv = lax.rsqrt(var + eps)
    out = (xf - mean) * inv * scale + bias
    return out.astype(x.dtype), new_mean, new_var
