"""GSPMD-native ZeRO sharding plane — NamedSharding constraints the XLA
partitioner schedules.

The shard_map-era compiled plane spells every collective explicitly
(``optimizers.ZeroShardedOptimizer`` emits reduce-scatter / allgather
per bucket).  This module is the other idiom the north star needs —
the NamedSharding + ``jax.jit`` pattern that scales the same
application code from 8 chips to superclusters without changing it
(SNIPPETS [2]/[3]): tensors carry ``jax.sharding.NamedSharding``
annotations, ``with_sharding_constraint`` pins the ZeRO residency
(optimizer state sharded at stage >= 1, gradients at stage >= 2,
parameters at stage 3), and the XLA partitioner inserts AND SCHEDULES
the reduce-scatters and parameter allgathers — the automatic
cross-replica weight-update sharding of arXiv:2004.13336, with the
stage-3 forward gathers placed by XLA's latency-hiding scheduler ahead
of the layers that consume them (the compiler-side mirror of
``ops.overlap.gather_in_forward``).

Layout note: GSPMD shards a leaf on its leading axis (rows), the
shard_map plane shards the padded FLAT value.  For row-major arrays
whose dim 0 divides the axis size these coincide element-for-element;
for the rest GSPMD falls back to replication (disclosed by
``residency_report``) while the flat plane always shards.  Checkpoints
interoperate either way: the engine stores logical values + flat
shards, and restore re-slices for whichever plane consumes them.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import numpy as np

from ..core.state import DATA_AXIS


def _jax():
    import jax
    return jax


def named_sharding(mesh, *spec):
    """``NamedSharding(mesh, PartitionSpec(*spec))`` — the one-liner
    every GSPMD annotation reduces to."""
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec(*spec))


def _shardable(leaf, world: int) -> bool:
    shape = getattr(leaf, "shape", ())
    return len(shape) >= 1 and shape[0] % world == 0 and shape[0] > 0


def leaf_spec(leaf, mesh, sharded: bool, axis: str = DATA_AXIS):
    """The ``PartitionSpec`` for one leaf: dim-0 sharded over ``axis``
    when requested and divisible, replicated otherwise."""
    from jax.sharding import PartitionSpec as P
    world = int(mesh.shape[axis])
    if sharded and _shardable(leaf, world):
        return P(axis)
    return P()


def zero_shardings(tree, mesh, sharded: bool, axis: str = DATA_AXIS):
    """NamedSharding pytree for ``tree``: dim-0 sharded over ``axis``
    where divisible (``sharded=True``), replicated otherwise."""
    jax = _jax()
    return jax.tree_util.tree_map(
        lambda l: named_sharding(mesh, *leaf_spec(l, mesh, sharded, axis)),
        tree)


def place(tree, mesh, sharded: bool, axis: str = DATA_AXIS):
    """``device_put`` every leaf at its ZeRO residency."""
    jax = _jax()
    return jax.tree_util.tree_map(
        lambda l: jax.device_put(
            l, named_sharding(mesh, *leaf_spec(l, mesh, sharded, axis))),
        tree)


def constrain(tree, mesh, sharded: bool, axis: str = DATA_AXIS):
    """``with_sharding_constraint`` every leaf — the in-trace pin the
    partitioner must honor (this is what makes gradient shards REAL at
    stage >= 2: the constraint forces the reduce-scatter early, so the
    full gradient's liveness ends inside the backward)."""
    jax = _jax()
    return jax.tree_util.tree_map(
        lambda l: jax.lax.with_sharding_constraint(
            l, named_sharding(mesh, *leaf_spec(l, mesh, sharded, axis))),
        tree)


class ZeroStepFns(NamedTuple):
    """The jitted GSPMD train-step bundle ``make_zero_train_step``
    returns: ``init(params)`` places params+state at their stage
    residency, ``step(params, opt_state, batch)`` runs one update with
    the ZeRO constraints compiled in."""

    init: Any
    step: Any
    stage: int


def make_zero_train_step(loss_fn, tx, mesh, stage: Optional[int] = None,
                         axis: str = DATA_AXIS):
    """Build the GSPMD-native ZeRO training step.

    ``loss_fn(params, batch) -> scalar`` is written for the GLOBAL
    (logical) batch — no axis names, no collectives; ``tx`` is a plain
    optax transformation.  The returned ``step`` is ``jax.jit`` over
    the mesh with:

    * batch sharded over ``axis`` (data parallelism),
    * optimizer state constrained dim-0-sharded (stage >= 1),
    * gradients constrained dim-0-sharded before the update
      (stage >= 2 — the partitioner reduce-scatters them and frees the
      full tree inside the backward),
    * parameters constrained dim-0-sharded end-to-end (stage 3 — the
      partitioner inserts per-tensor forward allgathers and schedules
      them ahead of first use).

    The XLA partitioner owns every collective: the same step scales to
    any mesh shape without touching this code.
    """
    jax = _jax()
    import optax  # noqa: F401 — documented dependency of tx

    if stage is None:
        from ..core.config import Config, get_int
        stage = get_int("ZERO_STAGE", Config.zero_stage)
    stage = int(stage)
    if stage not in (1, 2, 3):
        raise ValueError(f"ZeRO stage must be 1, 2 or 3, got {stage}")

    params_sharded = stage >= 3
    batch_sh = named_sharding(mesh, axis)

    def init(params):
        params = place(params, mesh, params_sharded, axis)
        opt_state = jax.jit(
            tx.init,
            out_shardings=zero_shardings(
                jax.eval_shape(tx.init, params), mesh, True, axis))(params)
        return params, opt_state

    def _step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        if stage >= 2:
            grads = constrain(grads, mesh, True, axis)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(
            lambda p, u: p + u.astype(p.dtype), params, updates)
        if params_sharded:
            params = constrain(params, mesh, True, axis)
        return params, opt_state, loss

    compiled = {}  # one jit wrapper per (params, state) treedef pair

    def step(params, opt_state, batch):
        key = (jax.tree_util.tree_structure(params),
               jax.tree_util.tree_structure(opt_state))
        fn = compiled.get(key)
        if fn is None:
            p_sh = zero_shardings(params, mesh, params_sharded, axis)
            s_sh = zero_shardings(opt_state, mesh, True, axis)
            fn = jax.jit(
                _step,
                in_shardings=(p_sh, s_sh, batch_sh),
                out_shardings=(p_sh, s_sh, named_sharding(mesh)))
            compiled[key] = fn
        return fn(params, opt_state, batch)

    return ZeroStepFns(init=init, step=step, stage=stage)


def per_device_bytes(tree) -> dict:
    """{device: resident bytes} across every leaf's addressable shards
    — the measured per-rank residency the ZeRO memory claims are graded
    on (``bench.py --bench zero``; replicated leaves count their full
    size on every device)."""
    jax = _jax()
    out: dict = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            for shard in leaf.addressable_shards:
                # .nbytes on the shard's jax.Array — never np.asarray,
                # which would device-to-host copy the whole state just
                # to read a byte count.
                out[shard.device] = out.get(shard.device, 0) + \
                    int(shard.data.nbytes)
        elif hasattr(leaf, "nbytes"):
            dev = "host"
            out[dev] = out.get(dev, 0) + int(leaf.nbytes)
    return out


def residency_report(tree, mesh, axis: str = DATA_AXIS) -> dict:
    """Residency accounting for a pytree: total logical bytes, max
    per-device resident bytes, the 1/world ideal, and which leaves
    could not shard (dim 0 not divisible) — the disclosure surface for
    the stage-3 <= 1.3x-of-ideal acceptance bar."""
    jax = _jax()
    world = int(mesh.shape[axis])
    total = 0
    unsharded = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        n = int(getattr(leaf, "nbytes", 0))
        total += n
        if not _shardable(leaf, world):
            unsharded.append(jax.tree_util.keystr(path))
    per_dev = per_device_bytes(tree)
    max_dev = max(per_dev.values()) if per_dev else 0
    return {
        "total_bytes": total,
        "max_device_bytes": max_dev,
        "ideal_bytes": total // world,
        "ratio_to_ideal": (max_dev * world / total) if total else 0.0,
        "unsharded_leaves": unsharded,
        "world": world,
    }
