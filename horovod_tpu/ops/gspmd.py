"""GSPMD-native ZeRO sharding plane — NamedSharding constraints the XLA
partitioner schedules.

The shard_map-era compiled plane spells every collective explicitly
(``optimizers.ZeroShardedOptimizer`` emits reduce-scatter / allgather
per bucket).  This module is the other idiom the north star needs —
the NamedSharding + ``jax.jit`` pattern that scales the same
application code from 8 chips to superclusters without changing it
(SNIPPETS [2]/[3]): tensors carry ``jax.sharding.NamedSharding``
annotations, ``with_sharding_constraint`` pins the ZeRO residency
(optimizer state sharded at stage >= 1, gradients at stage >= 2,
parameters at stage 3), and the XLA partitioner inserts AND SCHEDULES
the reduce-scatters and parameter allgathers — the automatic
cross-replica weight-update sharding of arXiv:2004.13336, with the
stage-3 forward gathers placed by XLA's latency-hiding scheduler ahead
of the layers that consume them (the compiler-side mirror of
``ops.overlap.gather_in_forward``).

Layout note: GSPMD shards a leaf on its leading axis (rows), the
shard_map plane shards the padded FLAT value.  For row-major arrays
whose dim 0 divides the axis size these coincide element-for-element;
for the rest GSPMD falls back to replication (disclosed by
``residency_report``) while the flat plane always shards.  Checkpoints
interoperate either way: the engine stores logical values + flat
shards, and restore re-slices for whichever plane consumes them.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import numpy as np

from ..core.state import DATA_AXIS


def _jax():
    import jax
    return jax


def named_sharding(mesh, *spec):
    """``NamedSharding(mesh, PartitionSpec(*spec))`` — the one-liner
    every GSPMD annotation reduces to."""
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec(*spec))


def _shardable(leaf, world: int) -> bool:
    shape = getattr(leaf, "shape", ())
    return len(shape) >= 1 and shape[0] % world == 0 and shape[0] > 0


def _axis_world(mesh, axis) -> int:
    """Total shard count for ``axis`` — a mesh axis name or a tuple of
    names (e.g. ``("local", "cross")``), whose world is the product."""
    if isinstance(axis, str):
        return int(mesh.shape[axis])
    world = 1
    for ax in axis:
        world *= int(mesh.shape[ax])
    return world


def _dim0_spec(axis):
    """The PartitionSpec dim-0 entry for ``axis``: the bare name, or the
    tuple (dim 0 sharded over the axes jointly, local-major — matching
    the nested hierarchical collective layouts)."""
    return axis if isinstance(axis, str) else tuple(axis)


def leaf_spec(leaf, mesh, sharded: bool, axis=DATA_AXIS):
    """The ``PartitionSpec`` for one leaf: dim-0 sharded over ``axis``
    (a name or an axis tuple) when requested and divisible, replicated
    otherwise."""
    from jax.sharding import PartitionSpec as P
    world = _axis_world(mesh, axis)
    if sharded and _shardable(leaf, world):
        return P(_dim0_spec(axis))
    return P()


def zero_shardings(tree, mesh, sharded: bool, axis=DATA_AXIS):
    """NamedSharding pytree for ``tree``: dim-0 sharded over ``axis``
    where divisible (``sharded=True``), replicated otherwise."""
    jax = _jax()
    return jax.tree_util.tree_map(
        lambda l: named_sharding(mesh, *leaf_spec(l, mesh, sharded, axis)),
        tree)


def place(tree, mesh, sharded: bool, axis=DATA_AXIS):
    """``device_put`` every leaf at its ZeRO residency."""
    jax = _jax()
    return jax.tree_util.tree_map(
        lambda l: jax.device_put(
            l, named_sharding(mesh, *leaf_spec(l, mesh, sharded, axis))),
        tree)


def constrain(tree, mesh, sharded: bool, axis=DATA_AXIS):
    """``with_sharding_constraint`` every leaf — the in-trace pin the
    partitioner must honor (this is what makes gradient shards REAL at
    stage >= 2: the constraint forces the reduce-scatter early, so the
    full gradient's liveness ends inside the backward)."""
    jax = _jax()
    return jax.tree_util.tree_map(
        lambda l: jax.lax.with_sharding_constraint(
            l, named_sharding(mesh, *leaf_spec(l, mesh, sharded, axis))),
        tree)


class ZeroStepFns(NamedTuple):
    """The jitted GSPMD train-step bundle ``make_zero_train_step``
    returns: ``init(params)`` places params+state at their stage
    residency, ``step(params, opt_state, batch)`` runs one update with
    the ZeRO constraints compiled in."""

    init: Any
    step: Any
    stage: int


def make_zero_train_step(loss_fn, tx, mesh, stage: Optional[int] = None,
                         axis=DATA_AXIS, compression=None):
    """Build the GSPMD-native ZeRO training step.

    ``loss_fn(params, batch) -> scalar`` is written for the GLOBAL
    (logical) batch — no axis names, no collectives; ``tx`` is a plain
    optax transformation.  The returned ``step`` is ``jax.jit`` over
    the mesh with:

    * batch sharded over ``axis`` (data parallelism),
    * optimizer state constrained dim-0-sharded (stage >= 1),
    * gradients constrained dim-0-sharded before the update
      (stage >= 2 — the partitioner reduce-scatters them and frees the
      full tree inside the backward),
    * parameters constrained dim-0-sharded end-to-end (stage 3 — the
      partitioner inserts per-tensor forward allgathers and schedules
      them ahead of first use).

    ``axis`` is a mesh axis name or a ``("local", "cross")`` tuple —
    the tuple shards over the product and unlocks the hierarchical
    compressed schedules below.

    ``compression`` (``hvd.Compression.{fp16,bf16,int8,int4}``, a name,
    or None → the session ``HVD_TPU_COMPRESSION`` knob) puts the
    gradient synchronization on the compressed wire INSIDE the compiled
    step (``ops/xla_collectives.py``): the gradients are computed
    per-shard in a ``shard_map`` island, error-feedback-corrected
    (quantized wires carry a flat fp32 residual in the returned
    ``_ZeroState``-wrapped optimizer state — checkpointed with the
    moments), and allreduced on the two-pass quantized/cast schedule
    with fp32 accumulation; with a tuple ``axis`` the hierarchical
    schedule is selected per payload bucket at trace time from the
    PR 11 dispatch table.  Two contract changes under compression:
    (1) ``loss_fn`` must AVERAGE over the batch dimension (the standard
    data-parallel contract — the global mean is recovered as the mean
    of per-shard means); (2) the optimizer state is wrapped in
    ``optimizers._ZeroState`` (``inner``/``sizes``/``residual``) so the
    sharded checkpoint engine carries the residual.  With the wire
    resolved to none, this function is BIT-IDENTICAL to the
    uncompressed builder — same trace, same treedefs, no wrapper.

    The XLA partitioner owns every structural collective (the stage-3
    parameter gathers stay fp32 XLA-scheduled gathers; the shard_map
    plane's ``gather_in_forward`` owns the quantized-gather opt-in);
    the same step scales to any mesh shape without touching this code.
    """
    jax = _jax()
    import optax  # noqa: F401 — documented dependency of tx

    if stage is None:
        from ..core.config import Config, get_int
        stage = get_int("ZERO_STAGE", Config.zero_stage)
    stage = int(stage)
    if stage not in (1, 2, 3):
        raise ValueError(f"ZeRO stage must be 1, 2 or 3, got {stage}")

    from . import xla_collectives as XC
    spec, wire_dtype = XC.resolve_wire(compression)
    compressed = spec is not None or wire_dtype is not None

    params_sharded = stage >= 3
    axes = XC.axes_of(axis)
    world = _axis_world(mesh, axis)
    batch_sh = named_sharding(mesh, _dim0_spec(axis))

    def init(params):
        params = place(params, mesh, params_sharded, axis)
        opt_state = jax.jit(
            tx.init,
            out_shardings=zero_shardings(
                jax.eval_shape(tx.init, params), mesh, True, axis))(params)
        if not compressed:
            return params, opt_state
        import jax.numpy as jnp

        from ..optimizers import _ZeroState
        residual = None
        if spec is not None:
            # One flat fp32 residual element per (rank, param element):
            # globally (world * n,), sharded over the dp axis so each
            # rank holds exactly its own (n,) error view.
            residual = place(jax.tree_util.tree_map(
                lambda p: jnp.zeros((world * p.size,), jnp.float32),
                params), mesh, True, axis)
        sizes = jax.tree_util.tree_map(
            lambda p: jnp.asarray(p.size, jnp.int32), params)
        return params, _ZeroState(inner=opt_state, sizes=sizes,
                                  residual=residual)

    def _step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        if stage >= 2:
            grads = constrain(grads, mesh, True, axis)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(
            lambda p, u: p + u.astype(p.dtype), params, updates)
        if params_sharded:
            params = constrain(params, mesh, True, axis)
        return params, opt_state, loss

    def _island(params, residual, batch):
        """Per-shard grads + EF + compressed allreduce, as a shard_map
        island inside the jitted step: under automatic partitioning the
        unreduced per-shard gradient never exists as a logical value,
        so the quantized wire needs this one explicit-SPMD region.  The
        rest of the step (optimizer update, param add, residency
        constraints) stays on the automatic plane."""
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from ..compat import shard_map
        from . import collective as C
        from . import quantization as Q

        def body(p, r, b):
            loss, g = jax.value_and_grad(loss_fn)(p, b)
            new_r = r
            if spec is not None:
                fed = jax.tree_util.tree_map(
                    lambda gi, ri: gi.astype(jnp.float32)
                    + ri.reshape(gi.shape), g, r)
                # Flat qdq == the exact first-pass wire error here: the
                # schedule pads to world*block, whose blocks are a
                # superset of the flat-padded blocks (extra blocks are
                # all-zero and quantize exactly).
                new_r = jax.tree_util.tree_map(
                    lambda f: jnp.ravel(f) - jnp.ravel(Q.qdq(f, spec)),
                    fed)
                g = jax.tree_util.tree_map(
                    lambda f, gi: f.astype(gi.dtype), fed, g)
            g = jax.tree_util.tree_map(
                lambda t: XC.allreduce_scheduled(
                    t, C.Average, axes, spec=spec, wire_dtype=wire_dtype),
                g)
            loss = lax.pmean(loss, XC.axis_arg(axes))
            return loss, g, new_r

        dp = P(_dim0_spec(axis))
        return shard_map(body, mesh=mesh,
                         in_specs=(P(), dp, dp),
                         out_specs=(P(), P(), dp),
                         check_vma=False)(params, residual, batch)

    def _step_compressed(params, opt_state, batch):
        loss, grads, new_residual = _island(params, opt_state.residual,
                                            batch)
        if stage >= 2:
            grads = constrain(grads, mesh, True, axis)
        updates, inner = tx.update(grads, opt_state.inner, params)
        params = jax.tree_util.tree_map(
            lambda p, u: p + u.astype(p.dtype), params, updates)
        if params_sharded:
            params = constrain(params, mesh, True, axis)
        opt_state = opt_state._replace(inner=inner,
                                       residual=new_residual)
        return params, opt_state, loss

    compiled = {}  # one jit wrapper per (params, state) treedef pair

    def step(params, opt_state, batch):
        key = (jax.tree_util.tree_structure(params),
               jax.tree_util.tree_structure(opt_state))
        entry = compiled.get(key)
        if entry is None:
            p_sh = zero_shardings(params, mesh, params_sharded, axis)
            s_sh = zero_shardings(opt_state, mesh, True, axis)
            fn = jax.jit(
                _step_compressed if compressed else _step,
                in_shardings=(p_sh, s_sh, batch_sh),
                out_shardings=(p_sh, s_sh, named_sharding(mesh)))
            plan = None
            if compressed:
                # Analytic wire accounting for the traced schedule —
                # priced once per treedef, recorded per step call
                # (kind="gspmd", docs/metrics.md).
                if len(axes) == 2:
                    lsz, csz = (int(mesh.shape[axes[0]]),
                                int(mesh.shape[axes[1]]))
                else:
                    lsz, csz = world, 1
                plan = XC.plan_allreduce_step(
                    [int(l.size) for l in
                     jax.tree_util.tree_leaves(params)],
                    local_size=lsz, cross_size=csz, spec=spec,
                    wire_dtype=wire_dtype)
            entry = (fn, plan)
            compiled[key] = entry
        fn, plan = entry
        out = fn(params, opt_state, batch)
        if plan is not None:
            XC.record_wire_bytes(plan.raw, plan.sent)
        return out

    return ZeroStepFns(init=init, step=step, stage=stage)


def per_device_bytes(tree) -> dict:
    """{device: resident bytes} across every leaf's addressable shards
    — the measured per-rank residency the ZeRO memory claims are graded
    on (``bench.py --bench zero``; replicated leaves count their full
    size on every device)."""
    jax = _jax()
    out: dict = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            for shard in leaf.addressable_shards:
                # .nbytes on the shard's jax.Array — never np.asarray,
                # which would device-to-host copy the whole state just
                # to read a byte count.
                out[shard.device] = out.get(shard.device, 0) + \
                    int(shard.data.nbytes)
        elif hasattr(leaf, "nbytes"):
            dev = "host"
            out[dev] = out.get(dev, 0) + int(leaf.nbytes)
    return out


def residency_report(tree, mesh, axis: str = DATA_AXIS) -> dict:
    """Residency accounting for a pytree: total logical bytes, max
    per-device resident bytes, the 1/world ideal, and which leaves
    could not shard (dim 0 not divisible) — the disclosure surface for
    the stage-3 <= 1.3x-of-ideal acceptance bar."""
    jax = _jax()
    world = int(mesh.shape[axis])
    total = 0
    unsharded = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        n = int(getattr(leaf, "nbytes", 0))
        total += n
        if not _shardable(leaf, world):
            unsharded.append(jax.tree_util.keystr(path))
    per_dev = per_device_bytes(tree)
    max_dev = max(per_dev.values()) if per_dev else 0
    return {
        "total_bytes": total,
        "max_device_bytes": max_dev,
        "ideal_bytes": total // world,
        "ratio_to_ideal": (max_dev * world / total) if total else 0.0,
        "unsharded_leaves": unsharded,
        "world": world,
    }
